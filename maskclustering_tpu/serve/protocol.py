"""mct-serve wire protocol: line-delimited JSON over a local socket.

One request or response per ``\\n``-terminated line; every line is a JSON
object carrying ``v`` (protocol version). The daemon answers a scene
request with an immediate ``ack`` (the daemon-assigned request id), then
streams ``status`` events (queued -> running, retry/degrade decisions)
and exactly one terminal ``result`` — or a typed ``reject`` instead of
the ack when admission refuses the work. Stdlib-only: clients need
nothing from the rest of the tree.

Request ops::

    {"op": "scene", "scene": "scene0001_00",
     "deadline_s": 30.0,          # optional per-request budget (0 = none)
     "resume": false,             # optional: artifact/journal resume
     "tag": "client-key",         # optional: echoed on every event
     "tenant": "team-a"}          # optional: accounting identity — the
                                  # telemetry plane attributes requests,
                                  # latency, queue waits, crashes,
                                  # device-seconds and d2h bytes per
                                  # tenant (obs/telemetry.py windows)
    {"op": "scene", "scene": "synth-a",
     "synthetic": {"num_boxes": 3, "num_frames": 10,
                   "image_hw": [60, 80], "spacing": 0.06, "seed": 40}}
    {"op": "stream_chunk", "scene": "synth-a", "chunk": 8,
     "synthetic": {...}}          # accumulate the scene's NEXT frame
                                  # chunk (live-scan streaming); result
                                  # carries partial_instances + done.
                                  # The scene name IS the stream identity
                                  # (one producer per scene, like the
                                  # artifact paths) — two clients
                                  # streaming one scene interleave on a
                                  # single cursor
    {"op": "stream_end", "scene": "synth-a"}  # finalize + export the
                                  # stream's artifacts, drop its session
                                  # (only on success — a failed export
                                  # keeps it, resend the op)
    {"op": "status"}              # daemon stats snapshot
    {"op": "status", "detail": "telemetry"}  # + windowed telemetry ring
    {"op": "shutdown"}            # drain in-flight requests, then exit
    {"op": "recarve", "carve": "2x4", "workers": 2}  # worker-pool admin
                                  # op (serve/pool.py): drain every
                                  # slice, respawn under the new carve
                                  # (admission keeps queueing meanwhile;
                                  # the shared AOT cache keeps the new
                                  # slices warm). Answers an ack-shaped
                                  # {"kind": "recarve", "ok": ...}

Responses (all carry ``id`` when bound to a request)::

    {"kind": "ack", "id": "r-000001", "scene": ..., "queue_depth": 2}
    {"kind": "reject", "reason": "queue_full" | "deadline" |
                                 "bad_request" | "draining", ...}
    {"kind": "status", "id": ..., "state": "running" | "retrying" |
                                           "degraded" | "worker_crash", ...}
    {"kind": "result", "id": ..., "status": "ok" | "failed" | "skipped" |
                                            "deadline", ...}

``worker_crash`` (process-isolated serving only, serve/supervisor.py):
the device-owning worker subprocess died under this request; the request
was requeued (``requeued: true``) for the respawned worker (in a pool,
rerouted to a bucket-warm neighbor), or — after repeated crashes — the
next event is a ``failed`` result with ``error_class: "device"``.

``stream_lost`` (status + terminal, streaming under crash containment):
the worker holding this scene's device-resident ``_StreamSession`` died
— the accumulator state died with it, so the stream CANNOT silently
continue (the wire ``chunk`` field is frames-per-chunk, not a cursor; a
respawned worker would reopen the stream at chunk 0 and corrupt it).
In-flight and queued stream ops for the lost scene answer a ``status``
with ``state: "stream_lost"`` then a ``failed`` result with
``error_class: "stream_lost"``; the session is dropped so the client can
restart the stream from its own source. When the daemon runs with a
shared ``stream_state/`` directory (serve/wal.py durability plane), a
per-chunk accumulator snapshot usually exists and the stream instead
RESUMES on the respawned worker (or a surviving pool slice): the client
sees a ``worker_crash`` status with ``requeued: true`` and the chunk
answers ``ok`` as if nothing died — ``stream_lost`` remains the typed
terminal fallback when no snapshot exists or the resumed replay exhausts
``MAX_REQUEST_CRASHES``.

``idem`` (optional, scene-naming ops): a client-chosen idempotency key.
The daemon journals it in the admission WAL; a reconnect-and-resubmit
with the same key dedupes instead of re-running — an already-answered
key replays the cached terminal event (stamped ``deduped: true``), an
in-flight key re-attaches the new connection to the live request's
status stream.

The same shapes ride the supervisor<->worker pipe (see
``forward_request``), plus three pipe-only kinds: ``hb`` (heartbeat),
``ready`` (worker warm, carries the retrace/aot digest) and ``bye``
(drain complete).

``quota`` rejects and the ``recarve`` op are worker-pool surface
(serve/pool.py): quota = the tenant's configured queued-request bound
(config.serve_tenants) was hit; recarve = drain + respawn the pool
under a new ``serve_carve`` while admission keeps queueing.
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
from typing import Dict, Optional

PROTOCOL_VERSION = 1

# accounting identities are dict keys in telemetry windows and column
# labels in obs.top — bound their length so a hostile client cannot bloat
# every window row
TENANT_MAX_LEN = 64

# idempotency keys are dict keys in the daemon's dedupe map and ride WAL
# rows verbatim — same bounded-identity rule as tenants
IDEM_MAX_LEN = 128

OPS = ("scene", "stream_chunk", "stream_end", "status", "shutdown",
       "recarve")
# the ops that name a scene and ride the admission queue as work items
SCENE_OPS = ("scene", "stream_chunk", "stream_end")
# status op detail levels: "" (the classic point-in-time snapshot),
# "telemetry" (adds the windowed aggregator's ring + cumulative digest)
# or "slo" (telemetry plus the armed spec's burn-rate verdict, obs/slo.py)
# or "sentinel" (the canary sentinel's drift-plane snapshot, obs/canary.py)
STATUS_DETAILS = ("", "telemetry", "slo", "sentinel")
REJECT_REASONS = ("queue_full", "deadline", "bad_request", "draining",
                  "quota")
RESULT_STATUSES = ("ok", "failed", "skipped", "deadline", "interrupted")

# make_scene parameters an inline synthetic request may set; anything else
# is a bad_request (the daemon must not forward arbitrary kwargs into the
# generator)
SYNTHETIC_PARAMS = frozenset({
    "num_boxes", "num_frames", "image_hw", "spacing", "seed", "room_half",
    "camera_radius", "camera_height", "floor_spacing",
})


class ProtocolError(ValueError):
    """A request line the daemon cannot admit (reason: bad_request)."""


@dataclasses.dataclass
class SceneRequest:
    """One admitted unit of work (daemon-internal; not the wire shape)."""

    id: str
    scene: str
    op: str = "scene"  # "scene" | "stream_chunk" | "stream_end"
    chunk: int = 0  # stream_chunk only: frames per chunk (0 = config)
    synthetic: Optional[Dict] = None
    deadline_s: float = 0.0
    resume: bool = False
    tag: str = ""
    tenant: str = ""  # optional accounting identity ("" = untenanted)
    idem: str = ""  # optional idempotency key ("" = no dedupe contract)
    admitted_at: float = 0.0       # time.monotonic() at admission
    deadline_at: float = math.inf  # monotonic deadline (inf = none)
    # how many device workers this request has crashed (the isolated
    # worker supervisor stamps it on requeue; the respawned worker's
    # SceneSupervisor starts that many degradation rungs down)
    crashes: int = 0
    send = None  # bound by the daemon: callable(event dict) -> None

    def expired(self) -> bool:
        return time.monotonic() >= self.deadline_at

    def remaining_s(self) -> float:
        return max(self.deadline_at - time.monotonic(), 0.0)


def parse_line(line: str) -> Dict:
    """One wire line -> validated request dict (raises ProtocolError)."""
    line = line.strip()
    if not line:
        raise ProtocolError("empty request line")
    try:
        doc = json.loads(line)
    except ValueError as e:
        raise ProtocolError(f"not JSON: {e}") from e
    if not isinstance(doc, dict):
        raise ProtocolError("request must be a JSON object")
    op = doc.get("op")
    if op not in OPS:
        raise ProtocolError(f"unknown op {op!r} (one of {OPS})")
    if op == "status":
        detail = doc.get("detail", "")
        if detail not in STATUS_DETAILS:
            raise ProtocolError(f"unknown status detail {detail!r} "
                                f"(one of {STATUS_DETAILS})")
    if op == "recarve":
        workers = doc.get("workers", 0)
        if not isinstance(workers, int) or isinstance(workers, bool) \
                or workers < 0:
            raise ProtocolError("'workers' must be an integer >= 0")
        carve = doc.get("carve", "")
        if not isinstance(carve, str):
            raise ProtocolError("'carve' must be a 'KxC' string")
    if op in SCENE_OPS:
        scene = doc.get("scene")
        if not isinstance(scene, str) or not scene:
            raise ProtocolError(f"{op} op needs a non-empty 'scene' name")
        if os_sep_like(scene):
            raise ProtocolError(f"scene name {scene!r} must not contain "
                                "path separators")
        chunk = doc.get("chunk", 0)
        if not isinstance(chunk, int) or isinstance(chunk, bool) or chunk < 0:
            raise ProtocolError("'chunk' must be an integer >= 0")
        if chunk and op != "stream_chunk":
            raise ProtocolError("'chunk' only applies to the stream_chunk "
                                "op")
        syn = doc.get("synthetic")
        if syn is not None:
            if not isinstance(syn, dict):
                raise ProtocolError("'synthetic' must be an object of "
                                    "make_scene params")
            unknown = set(syn) - SYNTHETIC_PARAMS
            if unknown:
                raise ProtocolError(
                    f"unknown synthetic param(s) {sorted(unknown)} "
                    f"(allowed: {sorted(SYNTHETIC_PARAMS)})")
        deadline = doc.get("deadline_s", 0.0)
        if not isinstance(deadline, (int, float)) or deadline < 0:
            raise ProtocolError("'deadline_s' must be a number >= 0")
        if not isinstance(doc.get("resume", False), bool):
            raise ProtocolError("'resume' must be a boolean")
        if "tenant" in doc:
            tenant = doc["tenant"]
            if not isinstance(tenant, str) or not tenant:
                raise ProtocolError("'tenant' must be a non-empty string")
            if len(tenant) > TENANT_MAX_LEN:
                raise ProtocolError(f"'tenant' longer than {TENANT_MAX_LEN} "
                                    "chars")
            if os_sep_like(tenant):
                raise ProtocolError(f"tenant {tenant!r} must not contain "
                                    "path separators")
        if "idem" in doc:
            idem = doc["idem"]
            if not isinstance(idem, str) or not idem:
                raise ProtocolError("'idem' must be a non-empty string")
            if len(idem) > IDEM_MAX_LEN:
                raise ProtocolError(f"'idem' longer than {IDEM_MAX_LEN} "
                                    "chars")
            if os_sep_like(idem):
                raise ProtocolError(f"idem key {idem!r} must not contain "
                                    "path separators")
        if "crashes" in doc:
            # supervisor-internal (the pipe carries it via forward_request,
            # which bypasses parse_line): a client must not pre-degrade its
            # own request's ladder — or crash the handler with a non-int
            raise ProtocolError("'crashes' is supervisor-internal and not "
                                "accepted on the client wire")
    return doc


def os_sep_like(name: str) -> bool:
    return "/" in name or "\\" in name or name in (".", "..")


def build_request(doc: Dict, request_id: str) -> SceneRequest:
    """A validated scene-naming op -> the daemon's work item."""
    deadline = float(doc.get("deadline_s", 0.0) or 0.0)
    now = time.monotonic()
    return SceneRequest(
        id=request_id,
        scene=doc["scene"],
        op=str(doc.get("op", "scene")),
        chunk=int(doc.get("chunk", 0) or 0),
        synthetic=doc.get("synthetic"),
        deadline_s=deadline,
        resume=bool(doc.get("resume", False)),
        tag=str(doc.get("tag", "")),
        tenant=str(doc.get("tenant", "")),
        idem=str(doc.get("idem", "")),
        admitted_at=now,
        deadline_at=(now + deadline) if deadline > 0 else math.inf,
        crashes=int(doc.get("crashes", 0) or 0),
    )


def forward_request(req: SceneRequest) -> Dict:
    """A ``SceneRequest`` -> the wire doc the supervisor pipes to its
    worker subprocess (serve/supervisor.py -> serve/worker_main.py).

    Carries the daemon-assigned ``id`` (the child assigns none), the
    REMAINING deadline budget (monotonic clocks do not cross process
    boundaries), and the crash count (the child's SceneSupervisor starts
    pre-degraded by it).
    """
    doc: Dict = {"op": req.op or "scene", "id": req.id, "scene": req.scene}
    if req.chunk:
        doc["chunk"] = req.chunk
    if req.synthetic is not None:
        doc["synthetic"] = req.synthetic
    if not math.isinf(req.deadline_at):
        doc["deadline_s"] = max(round(req.remaining_s(), 3), 0.001)
    if req.resume:
        doc["resume"] = True
    if req.tag:
        doc["tag"] = req.tag
    if req.tenant:
        doc["tenant"] = req.tenant
    if req.crashes:
        doc["crashes"] = req.crashes
    return doc


def forward_batch(reqs) -> Dict:
    """A same-bucket request batch -> ONE pipe envelope (pipe-only op).

    The supervisor's packing pump forwards a whole batch in one write so
    the child's scheduler sees the members together (its own
    ``next_batch`` re-packs them into one fused dispatch instead of
    meeting them one stdin line at a time). The envelope is
    supervisor-internal — ``parse_line`` never accepts it from a client.
    """
    return {"op": "batch", "requests": [forward_request(r) for r in reqs]}


# ---------------------------------------------------------------------------
# response builders (the only shapes the daemon ever sends)
# ---------------------------------------------------------------------------


def _event(kind: str, req: Optional[SceneRequest] = None, **fields) -> Dict:
    ev = {"v": PROTOCOL_VERSION, "kind": kind}
    if req is not None:
        ev["id"] = req.id
        if req.tag:
            ev["tag"] = req.tag
    ev.update(fields)
    return ev


def ack(req: SceneRequest, *, queue_depth: int) -> Dict:
    return _event("ack", req, scene=req.scene, queue_depth=queue_depth)


def reject(reason: str, *, req: Optional[SceneRequest] = None,
           detail: str = "", tag: str = "") -> Dict:
    assert reason in REJECT_REASONS, reason
    ev = _event("reject", req, reason=reason)
    if detail:
        ev["detail"] = detail
    if tag and "tag" not in ev:
        ev["tag"] = tag
    return ev


def status(req: SceneRequest, state: str, **fields) -> Dict:
    return _event("status", req, state=state, **fields)


def result(req: SceneRequest, status_: str, **fields) -> Dict:
    assert status_ in RESULT_STATUSES, status_
    return _event("result", req, status=status_, **fields)


def encode(event: Dict) -> bytes:
    return (json.dumps(event, sort_keys=True) + "\n").encode("utf-8")
