"""CLI: ``python -m maskclustering_tpu.serve`` — start the daemon.

Mirrors run.py's operational posture: backend init under a watchdog,
SIGTERM -> cooperative drain (exit 143), obs events armed when a path is
given, the retrace sanitizer as the serve-many contract's runtime gate
(frozen after warm-up), and ONE machine-readable JSON digest line on
stdout at shutdown — the load generator and the CI smoke gate read that
line, everything else goes to stderr via logging.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from maskclustering_tpu.utils import faults

log = logging.getLogger("maskclustering_tpu")


def _parse_overrides(pairs) -> dict:
    """``--set key=value`` pairs -> typed config overrides.

    Coercion follows the PipelineConfig field's current type (bools accept
    1/0/true/false); unknown keys fail loudly, same as load_config.
    """
    import dataclasses

    from maskclustering_tpu.config import PipelineConfig

    fields = {f.name: f for f in dataclasses.fields(PipelineConfig)}
    out = {}
    for pair in pairs:
        key, sep, value = pair.partition("=")
        if not sep or key not in fields:
            raise SystemExit(f"--set {pair!r}: expected KEY=VALUE with a "
                             f"PipelineConfig field as KEY")
        default = getattr(PipelineConfig(), key)
        if isinstance(default, bool):
            out[key] = value.strip().lower() in ("1", "true", "on", "yes")
        elif isinstance(default, int):
            out[key] = int(value)
        elif isinstance(default, float):
            out[key] = float(value)
        else:
            out[key] = value
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="maskclustering_tpu.serve",
        description="long-lived scene-serving daemon (JSONL over a local "
                    "socket)")
    parser.add_argument("--config", required=True,
                        help="config name under configs/")
    parser.add_argument("--socket", default=None,
                        help="AF_UNIX socket path to serve on")
    parser.add_argument("--host", default=None,
                        help="TCP host to serve on instead of --socket "
                             "(with --port; loopback serving only — there "
                             "is no auth layer)")
    parser.add_argument("--port", type=int, default=0,
                        help="TCP port for --host (0 = ephemeral, printed "
                             "on startup)")
    parser.add_argument("--capacity", type=int, default=8,
                        help="admission queue bound (typed queue_full "
                             "reject beyond it)")
    parser.add_argument("--deadline", type=float, default=0.0,
                        help="default per-request deadline seconds "
                             "(0 = none; requests may set their own)")
    parser.add_argument("--journal-dir", default=None,
                        help="per-request RunJournal directory "
                             "(<dir>/<request id>.jsonl; default: "
                             "<data_root>/serve_journals)")
    parser.add_argument("--no-journal", action="store_true",
                        help="disable per-request journals (also disables "
                             "the admission WAL, which lives beside them)")
    parser.add_argument("--no-wal", action="store_true",
                        help="disable the admission WAL (serve/wal.py): no "
                             "crash-replay of admitted requests, no "
                             "idempotency-key dedupe")
    parser.add_argument("--stream-state", default=None, metavar="DIR",
                        help="shared per-chunk stream snapshot directory "
                             "(models/streaming save_state): a crashed "
                             "stream's session re-opens from the latest "
                             "snapshot instead of answering stream_lost "
                             "(default: <data_root>/stream_state)")
    parser.add_argument("--no-stream-state", action="store_true",
                        help="disable stream snapshots/failover (crashed "
                             "streams answer the typed stream_lost)")
    parser.add_argument("--warm", default=None,
                        help="+-joined scene names to run end-to-end "
                             "(exports included) before accepting requests")
    parser.add_argument("--warm-baseline", default=None, nargs="?",
                        const="compile_surface_baseline.json",
                        help="pre-warm the serving vocabulary from this "
                             "surface baseline's workload (flag alone: "
                             "compile_surface_baseline.json)")
    parser.add_argument("--no-freeze", action="store_true",
                        help="do not freeze the retrace sanitizer after "
                             "warm-up (armed runs only)")
    parser.add_argument("--obs_events", default=None,
                        help="obs span/metrics JSONL path (the Serving "
                             "report section renders from it; telemetry "
                             "window rows append here too)")
    parser.add_argument("--flight-dir", default=None,
                        help="arm the flight recorder's dump directory "
                             "(obs/flight.py black box; also via "
                             "$MCT_FLIGHT_DIR — the worker subprocess "
                             "inherits it)")
    parser.add_argument("--slo-spec", default=None,
                        help="SLO spec JSON for the status op's detail=slo "
                             "answer (obs/slo.py; default: the canned "
                             "serve-default spec)")
    parser.add_argument("--canary-interval", type=float, default=0.0,
                        help="arm the mct-sentinel canary scheduler: every "
                             "N seconds an idle daemon replays its warm "
                             "scenes and byte-compares the invariant "
                             "digests against canary_goldens.json "
                             "(obs/canary.py; 0 = off)")
    parser.add_argument("--canary-goldens", default=None,
                        help="committed goldens path for --canary-interval "
                             "(default: canary_goldens.json; regenerate "
                             "via scripts/load_gen.py --write-goldens)")
    parser.add_argument("--telemetry-window", type=float, default=5.0,
                        help="telemetry aggregation window seconds "
                             "(obs/telemetry.py ring; the status op's "
                             "detail=telemetry and obs.top read it)")
    parser.add_argument("--retrace-sanitizer", action="store_true",
                        help="arm the compile-event sanitizer (default: "
                             "$MCT_RETRACE_SANITIZER); the daemon freezes "
                             "it after warm-up so every post-warm compile "
                             "is a violation")
    parser.add_argument("--fault-plan", default=None,
                        help="deterministic fault injection spec "
                             "(testing/drill knob — never in production; "
                             "with --isolate-worker the plan is handed to "
                             "the FIRST worker subprocess, so crash/wedge "
                             "drills land on the supervised path)")
    parser.add_argument("--isolate-worker", action="store_true",
                        help="run the device-owning worker as a supervised "
                             "SUBPROCESS (serve/supervisor.py): heartbeat "
                             "watchdog, SIGKILL-on-wedge, bounded respawn "
                             "with requeue — a hard XLA/TPU crash costs a "
                             "respawn, not the daemon")
    parser.add_argument("--workers", type=int, default=None,
                        help="carve the device mesh into this many worker "
                             "slices, one supervised subprocess each "
                             "(serve/pool.py; needs --isolate-worker; "
                             "shorthand for --set serve_workers=K)")
    parser.add_argument("--carve", default=None, metavar="KxC",
                        help="explicit pool carve, K slices x C chips each "
                             "(shorthand for --set serve_carve=KxC; K must "
                             "equal --workers when both are given)")
    parser.add_argument("--tenants", default=None,
                        metavar="NAME:WEIGHT[:QUOTA],...",
                        help="weighted-fair tenant QoS spec (shorthand for "
                             "--set serve_tenants=...; unknown tenants get "
                             "weight 1, no quota)")
    parser.add_argument("--aot-cache", default=None, nargs="?", const="auto",
                        metavar="DIR",
                        help="arm the persistent AOT executable cache "
                             "(utils/aot_cache.py) so a (re)started "
                             "worker reaches first dispatch with zero "
                             "compiles (flag alone: aot_cache/ next to "
                             "the perf ledger; also via $MCT_AOT_CACHE)")
    parser.add_argument("--point-shards", type=int, default=None,
                        help="shard the scene-point axis over this many "
                             "chips (third mesh axis of the fused path; "
                             "needs the config's mesh_shape). Million-"
                             "point requests fit without widening any "
                             "per-chip HBM bucket; shorthand for "
                             "--set point_shards=N")
    parser.add_argument("--set", action="append", default=[],
                        metavar="KEY=VALUE", dest="overrides",
                        help="override a config field (repeatable; value "
                             "coerced to the field's type, e.g. "
                             "--set step=1 --set mask_pad_multiple=32)")
    parser.add_argument("--data_root", default=None,
                        help="override the config's data root")
    parser.add_argument("--prediction-root", default=None,
                        help="artifact root (default: <data_root>/prediction)")
    parser.add_argument("--init_timeout", type=float, default=120.0)
    parser.add_argument("--debug", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(
        stream=sys.stderr,  # stdout carries exactly one digest line
        level=logging.DEBUG if args.debug else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    if args.socket is None and args.host is None:
        parser.error("need --socket PATH or --host HOST [--port N]")

    from maskclustering_tpu.config import load_config

    overrides = {"data_root": args.data_root} if args.data_root else {}
    overrides.update(_parse_overrides(args.overrides))
    if args.point_shards is not None:
        overrides["point_shards"] = args.point_shards
    if args.aot_cache is not None:
        overrides["aot_cache_dir"] = args.aot_cache
    if args.workers is not None:
        overrides["serve_workers"] = args.workers
    if args.carve is not None:
        overrides["serve_carve"] = args.carve
    if args.tenants is not None:
        overrides["serve_tenants"] = args.tenants
    cfg = load_config(args.config, **overrides)

    from maskclustering_tpu.analysis import retrace_sanitizer

    if args.retrace_sanitizer:
        retrace_sanitizer.arm(True)
    if retrace_sanitizer.enabled():
        retrace_sanitizer.install()
    if args.fault_plan:
        faults.set_plan(faults.FaultPlan.from_spec(args.fault_plan))
    faults.install_sigterm_handler()

    if args.obs_events:
        from maskclustering_tpu import obs

        obs.configure(args.obs_events, truncate=True,
                      meta={"tool": "serve", "config": cfg.config_name})

    from maskclustering_tpu.run import init_backend_or_die

    init_backend_or_die(args.init_timeout,
                        platform="cpu" if cfg.backend == "cpu" else None)

    journal_dir = None
    if not args.no_journal:
        journal_dir = args.journal_dir or os.path.join(cfg.data_root,
                                                       "serve_journals")
    stream_state_dir = None
    if not args.no_stream_state:
        stream_state_dir = args.stream_state or os.path.join(
            cfg.data_root, "stream_state")

    from maskclustering_tpu.serve.daemon import ServeDaemon

    daemon = ServeDaemon(
        cfg,
        socket_path=args.socket,
        host=args.host, port=args.port,
        capacity=args.capacity,
        journal_dir=journal_dir,
        stream_state_dir=stream_state_dir,
        wal=not args.no_wal,
        prediction_root=args.prediction_root,
        warm_scenes=tuple(s for s in (args.warm or "").split("+") if s),
        warm_baseline=args.warm_baseline,
        freeze_after_warm=not args.no_freeze,
        default_deadline_s=args.deadline,
        isolate_worker=args.isolate_worker,
        fault_plan_spec=args.fault_plan,
        telemetry_window_s=args.telemetry_window,
        slo_spec=args.slo_spec,
        flight_dir=args.flight_dir,
        canary_interval_s=args.canary_interval,
        canary_goldens=args.canary_goldens,
    )
    daemon.start()
    if args.host is not None:
        # the ephemeral port is only knowable now; clients parse this line
        print(json.dumps({"kind": "listening",
                          "address": list(daemon.address)}), flush=True)
    try:
        daemon.serve_forever()
    finally:
        daemon.shutdown()
        from maskclustering_tpu import obs

        if args.obs_events and obs.enabled():
            daemon.emit_serve_counters()
            if retrace_sanitizer.enabled():
                retrace_sanitizer.emit_counters()
            obs.flush_metrics()
            obs.disable()
        # the one stdout line: the daemon's final digest (load_gen / CI
        # smoke parse it; bench.py keeps the same one-line contract)
        print(json.dumps({"kind": "digest", **daemon.stats()},
                         sort_keys=True), flush=True)
    return 143 if faults.stop_requested() else 0


if __name__ == "__main__":
    raise SystemExit(main())
