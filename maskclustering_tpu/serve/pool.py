"""mct-pool: multi-worker serving — one daemon, every chip.

The PR-12 supervisor runs exactly ONE device-owning subprocess, so on a
v5e-8 seven chips idle while one worker serializes the admission queue.
``WorkerPool`` carves the device product into K slices (``cfg.
serve_workers`` + the ``serve_carve`` "KxC" spec, reusing the
``make_run_mesh`` scene x frame x point vocabulary: a v5e-8 runs as
"4x2" for small buckets or "1x8" for 1M-point scenes) and runs one FULL
WorkerSupervisor per slice — each with its own heartbeat-silence
SIGKILL, bounded respawn and crash-containment ladder. The single-
consumer dequeue becomes a scheduler thread with three planes:

- **bucket affinity** — requests route to a slice already warm for
  their (k_max, f_pad, n_pad) bucket. Every slice warms the same
  baseline vocabulary at spawn and the shared on-disk AOT cache
  (utils/aot_cache.py) restores anything any slice ever compiled, so a
  post-warm request NEVER compiles anywhere in the pool; a cold bucket
  routes least-loaded (and marks that slice warm for its successors).
- **weighted-fair tenant QoS** — per-tenant sub-queues drained by
  virtual-time stride scheduling (``vt += 1/weight``): a 3:1 weight
  ratio yields ~3:1 completions under saturation, and every weight > 0
  tenant is starvation-bounded by construction. Optional per-tenant
  quotas bound QUEUED (admitted, pre-dispatch) requests — exceeding one
  answers a typed ``quota`` reject at admission. Spec grammar:
  ``config.parse_tenant_spec`` ("name:weight[:quota],...").
- **per-slice continuous batching** — each slice's supervisor drains
  its own feed queue with PR 18's ``next_batch`` packing, so same-
  bucket company fuses per mesh slice exactly as in the single-worker
  topology.

Crash containment composes rather than changes: a slice crash requeues
its victims through ``_FeedQueue.requeue`` back into the POOL, which
reroutes them to a bucket-warm NEIGHBOR (warm respawn still happens,
but the victim does not wait for it). Stream sessions are slice-local,
so stream ops pin to their owner slice (``_stream_owner``) — but when a
shared ``stream_state_dir`` holds a per-chunk snapshot (the worker ships
them on the ``stream_journal_every`` cadence), a stream whose owner died
re-opens on a SURVIVING warm slice from the snapshot instead of
answering the typed ``stream_lost`` (which remains the fallback when no
snapshot exists). ``recarve`` drains every slice and respawns under a
new carve while admission keeps queueing — the shared AOT cache makes
the new slices warm, and snapshotted streams migrate the same way.

The pool exposes the ServeWorker/WorkerSupervisor surface (start/stop/
wait_idle/stats/latency_quantiles/run_canary/child_retrace/busy) so
``ServeDaemon`` swaps topologies with one constructor choice.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Deque, Dict, List, Optional, Set, Tuple

from maskclustering_tpu import obs
from maskclustering_tpu.analysis.lock_sanitizer import mct_lock
from maskclustering_tpu.config import parse_carve_spec, parse_tenant_spec
from maskclustering_tpu.serve import protocol
from maskclustering_tpu.serve.admission import AdmissionQueue, QueueFullReject
from maskclustering_tpu.serve.router import Router
from maskclustering_tpu.serve.supervisor import WorkerSupervisor
from maskclustering_tpu.serve.worker import _send

log = logging.getLogger("maskclustering_tpu")

STREAM_OPS = ("stream_chunk", "stream_end")


class QuotaReject(Exception):
    """Typed admission reject: the tenant's queued-request quota is full."""

    def __init__(self, tenant: str, limit: int, queued: int):
        self.tenant = tenant
        self.limit = limit
        self.queued = queued
        super().__init__(
            f"tenant {tenant!r} quota full ({queued}/{limit} queued)")


def check_carve(workers: int, chips: int,
                device_product: Optional[int]) -> None:
    """Reject a carve that does not divide the device product (typed).

    ``chips == 0`` means "no carve — every slice sees the whole backend"
    and ``device_product is None`` means the backend is not inspectable
    from this process (CPU slices synthesize their own host devices via
    per-child XLA flags); both skip the check.
    """
    if chips <= 0 or device_product is None:
        return
    total = workers * chips
    if total > device_product or device_product % total != 0:
        raise ValueError(
            f"serve_carve {workers}x{chips} needs {total} chips but the "
            f"backend has {device_product}; the carve product must divide "
            f"the device product")


class _FeedQueue(AdmissionQueue):
    """One slice's dispatch buffer: unmetered (the POOL's queue is the
    admission layer), sized to hold a full batch, and its ``requeue`` —
    the supervisor's crash path — hands the victim back to the pool so
    it reroutes to a warm NEIGHBOR instead of waiting out the respawn."""

    def __init__(self, pool: "WorkerPool", worker_id: int, capacity: int):
        super().__init__(capacity=capacity, metered=False)
        self._pool = pool
        self._worker_id = worker_id

    def requeue(self, req: protocol.SceneRequest) -> bool:
        return self._pool._requeue_from_worker(self._worker_id, req)

    def put_direct(self, req: protocol.SceneRequest) -> bool:
        """The base put-back (pool-internal: crash reroute INTO a feed)."""
        return AdmissionQueue.requeue(self, req)


class WorkerPool:
    """K supervised device slices behind one affinity/QoS scheduler."""

    def __init__(self, cfg, queue: AdmissionQueue, router: Router, *,
                 journal_dir: Optional[str] = None,
                 prediction_root: Optional[str] = None,
                 stream_state_dir: Optional[str] = None,
                 warm_scenes: Tuple[str, ...] = (),
                 warm_baseline: Optional[str] = None,
                 freeze_after_warm: bool = True,
                 fault_plan_spec: Optional[str] = None,
                 child_argv: Optional[list] = None,
                 start_timeout_s: float = 600.0,
                 poll_s: float = 0.25,
                 on_fatal=None):
        self.cfg = cfg
        self.queue = queue
        self.router = router
        self.journal_dir = journal_dir
        self.prediction_root = prediction_root
        self.stream_state_dir = stream_state_dir
        self.warm_scenes = tuple(warm_scenes)
        self.warm_baseline = warm_baseline
        self.freeze_after_warm = freeze_after_warm
        self.fault_plan_spec = fault_plan_spec
        self.child_argv = child_argv
        self.start_timeout_s = float(start_timeout_s)
        self.poll_s = poll_s
        self.on_fatal = on_fatal
        self.workers = max(int(cfg.serve_workers), 1)
        carve = str(cfg.serve_carve or "")
        self.chips = parse_carve_spec(carve)[1] if carve else 0
        self._qos = parse_tenant_spec(str(cfg.serve_tenants or ""))
        self._lock = mct_lock("serve.WorkerPool._lock")
        self._stop = threading.Event()
        self._pause = threading.Event()  # recarve: dispatch suspended
        self._sched: Optional[threading.Thread] = None
        self._sups: List[WorkerSupervisor] = []
        self._feeds: List[_FeedQueue] = []
        self._dead: Set[int] = set()
        # per-slice warm-bucket shadow (the affinity plane): seeded from
        # the shared vocabulary every child warms at spawn, grown
        # optimistically at dispatch (the slice is warm for the bucket by
        # the time its successor routes)
        self._warm: List[Set[tuple]] = []
        # weighted-fair state: per-tenant FIFO sub-queues + virtual time
        self._subq: Dict[str, Deque[protocol.SceneRequest]] = {}
        self._vt: Dict[str, float] = {}
        self._gvt = 0.0
        # quota accounting: queued (admitted, pre-dispatch) per tenant;
        # _counted holds the request ids the admit() path incremented so
        # crash requeues (exempt) never double-decrement
        self._tenant_queued: Dict[str, int] = {}
        self._counted: Set[str] = set()
        # stream ops pin to the slice holding their device-resident
        # session; a retired (fatal) owner answers a typed stream_lost
        self._stream_owner: Dict[str, int] = {}
        # scheduler accounting (stats + the Serving report's share lines)
        self._dispatched = 0
        self._by_tenant: Dict[str, int] = {}
        self._by_worker: Dict[int, int] = {}
        self._affinity_hits = 0
        self._affinity_misses = 0
        self._crash_reroutes = 0
        self._recarves = 0
        # recarve retires whole slices: their request/crash history folds
        # into these baselines so the daemon's counts survive the carve
        self._retired_counts: Dict[str, int] = {}
        self._retired_worker = {"spawns": 0, "respawns": 0, "crashes": 0,
                                "streams_resumed": 0}
        self._retired_latencies: List[float] = []

    # -- carve plumbing ------------------------------------------------------

    def _device_product(self) -> Optional[int]:
        """The backend's chip count, when this process can see it. CPU
        slices synthesize their own host devices per child (XLA flags),
        so the parent's count is not the pool's resource there."""
        if self.cfg.backend == "cpu":
            return None
        try:
            import jax

            return len(jax.devices())
        except Exception:  # noqa: BLE001 — parent may not own a backend
            return None

    def _child_env(self, worker_id: int) -> Optional[Dict[str, str]]:
        """The slice's device carve, as a child-process env overlay."""
        if self.chips <= 0:
            return None
        if self.cfg.backend == "cpu":
            # each CPU child synthesizes exactly its slice's chip count
            flags = [p for p in os.environ.get("XLA_FLAGS", "").split()
                     if not p.startswith(
                         "--xla_force_host_platform_device_count")]
            flags.append(
                f"--xla_force_host_platform_device_count={self.chips}")
            return {"XLA_FLAGS": " ".join(flags)}
        # TPU: best-effort chip pinning by visible-device ids (no
        # authoritative slicing guide ships with the toolchain; hosts
        # that ignore the variable fall back to whole-backend slices,
        # which is correct but unpartitioned)
        lo = worker_id * self.chips
        return {"TPU_VISIBLE_DEVICES":
                ",".join(str(c) for c in range(lo, lo + self.chips))}

    def _feed_capacity(self) -> int:
        # a slice's buffer holds one full pack plus margin, mirroring the
        # child's own local queue (worker_main.py)
        return max(2, int(getattr(self.cfg, "serve_batch_max", 1)) + 1)

    def _build_slices(self) -> None:
        seed = self.router.warm_buckets() | self.router.vocabulary_buckets()
        self._feeds = [_FeedQueue(self, i, self._feed_capacity())
                       for i in range(self.workers)]
        self._sups = [
            WorkerSupervisor(
                self.cfg, self._feeds[i], self.router,
                journal_dir=self.journal_dir,
                prediction_root=self.prediction_root,
                stream_state_dir=self.stream_state_dir,
                warm_scenes=self.warm_scenes,
                warm_baseline=self.warm_baseline,
                freeze_after_warm=self.freeze_after_warm,
                # drills target slice 0 only: the drill is one fault, not
                # a fleet-wide crash storm
                fault_plan_spec=self.fault_plan_spec if i == 0 else None,
                child_argv=self.child_argv,
                start_timeout_s=self.start_timeout_s,
                poll_s=self.poll_s,
                on_fatal=(lambda wid=i: self._slice_fatal(wid)),
                worker_id=i, pooled=True,
                child_env=self._child_env(i))
            for i in range(self.workers)]
        self._dead = set()
        self._warm = [set(seed) for _ in range(self.workers)]

    def _start_slices(self) -> None:
        """Spawn every slice concurrently (K children warm in parallel —
        the AOT cache makes each warm-up cheap, but K serial warm walls
        would still stack)."""
        errors: List[str] = []

        def _one(sup: WorkerSupervisor) -> None:
            try:
                sup.start()
            except Exception as e:  # noqa: BLE001 — collected, re-raised
                errors.append(f"worker {sup.worker_id}: {e}")

        threads = []
        for s in self._sups:
            t = threading.Thread(target=_one, args=(s,), daemon=True,
                                 name=f"pool-start-{s.worker_id}")
            threads.append(t)
            t.start()
        for t in threads:
            t.join(self.start_timeout_s + 30.0)
        if errors or any(t.is_alive() for t in threads):
            for s in self._sups:
                try:
                    s.stop(timeout_s=5.0)
                except Exception:  # noqa: BLE001
                    pass
            raise RuntimeError(
                "worker pool failed to start: " + "; ".join(errors or
                                                            ["spawn hung"]))

    # -- lifecycle (ServeWorker surface) ------------------------------------

    def start(self) -> None:
        if self._sched is not None:
            return
        check_carve(self.workers, self.chips, self._device_product())
        self._build_slices()
        self._start_slices()
        self._sched = threading.Thread(  # mct-thread: abandon(daemon-lifetime scheduler, bounded-joined in stop(); the spawn/join pair spans methods)
            target=self._schedule, daemon=True, name="pool-scheduler")
        self._sched.start()

    def stop(self, timeout_s: float = 60.0) -> bool:
        # drain what was admitted (scheduler still routing), THEN stop
        idle = self.wait_idle(timeout_s)
        self._stop.set()
        t = self._sched
        if t is not None:
            t.join(10.0)
        oks: List[bool] = []

        def _one(sup: WorkerSupervisor) -> None:
            oks.append(sup.stop(timeout_s=timeout_s))

        threads = []
        for s in self._sups:
            th = threading.Thread(target=_one, args=(s,), daemon=True,
                                  name=f"pool-stop-{s.worker_id}")
            threads.append(th)
            th.start()
        for th in threads:
            th.join(timeout_s + 15.0)
        # anything still undispatched answers the drain's typed reject
        leftovers: List[protocol.SceneRequest] = []
        with self._lock:
            for dq in self._subq.values():
                leftovers.extend(dq)
                dq.clear()
        for feed in self._feeds:
            leftovers.extend(feed.drain())
        for req in leftovers:
            obs.count("serve.admission.rejects.draining")
            _send(req, protocol.reject(
                "draining", req=req,
                detail="daemon shutting down before dispatch"))
        return idle and len(oks) == len(self._sups) and all(oks)

    def wait_idle(self, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                pending = sum(len(dq) for dq in self._subq.values())
            if self.queue.depth() == 0 and pending == 0 \
                    and all(f.depth() == 0 for f in self._feeds) \
                    and not any(s.busy() for s in self._sups):
                return True
            time.sleep(0.01)
        return False

    def busy(self) -> bool:
        return any(s.busy() for s in self._sups)

    # -- admission (the daemon's quota gate) --------------------------------

    def admit(self, req: protocol.SceneRequest) -> int:
        """Quota-gated admission: the daemon submits through the pool so
        a tenant at its queued-request bound answers a typed ``quota``
        reject BEFORE consuming a queue slot. Raises QuotaReject or the
        queue's own QueueFullReject; returns the post-admission depth."""
        tenant = req.tenant
        limit = self._qos.get(tenant, (1.0, None))[1]
        depth = 0
        with self._lock:
            queued = self._tenant_queued.get(tenant, 0)
            over = limit is not None and queued >= limit
            if not over:
                depth = self.queue.submit(req)  # may raise QueueFullReject
                self._tenant_queued[tenant] = queued + 1
                self._counted.add(req.id)
        if over:
            obs.count("serve.admission.rejects.quota")
            raise QuotaReject(tenant, limit, queued)
        return depth

    # -- the scheduler -------------------------------------------------------

    def _schedule(self) -> None:
        while not self._stop.is_set():
            if self._pause.is_set():
                time.sleep(0.02)
                continue
            self._drain_admission()
            tenant = self._pick_tenant()
            if tenant is None:
                continue
            with self._lock:
                dq = self._subq.get(tenant)
                req = dq[0] if dq else None
            if req is None:
                continue
            outcome = self._try_dispatch(req)
            if outcome == "no_room":
                # every routable feed is full: hold the head, let slices
                # drain (bounded spin; admission keeps queueing behind)
                time.sleep(0.005)
                continue
            with self._lock:
                dq = self._subq.get(tenant)
                if dq and dq[0] is req:
                    dq.popleft()
                w = self._qos.get(tenant, (1.0, None))[0]
                self._vt[tenant] = self._vt.get(tenant, self._gvt) + 1.0 / w
                self._gvt = self._vt[tenant]

    def _drain_admission(self) -> None:
        """Move admitted requests into their tenant sub-queues. Blocks
        one poll interval only when nothing is pending (the scheduler's
        stop-flag poll), else drains what is there and returns."""
        with self._lock:
            pending = any(self._subq.values())
        req = self.queue.next(timeout_s=0.0 if pending else self.poll_s)
        while req is not None:
            with self._lock:
                dq = self._subq.setdefault(req.tenant, collections.deque())
                if not dq:
                    # a tenant (re)entering the rotation starts at the
                    # pool's virtual time — an idle spell is not credit
                    self._vt[req.tenant] = max(
                        self._vt.get(req.tenant, 0.0), self._gvt)
                dq.append(req)
            req = self.queue.next(timeout_s=0.0)

    def _pick_tenant(self) -> Optional[str]:
        with self._lock:
            candidates = [(self._vt.get(t, self._gvt), t)
                          for t, dq in self._subq.items() if dq]
        if not candidates:
            return None
        return min(candidates)[1]

    def _alive(self, exclude: Optional[int] = None) -> List[int]:
        with self._lock:
            dead = set(self._dead)
        return [i for i in range(len(self._sups))
                if i not in dead and i != exclude]

    def _load(self, i: int) -> int:
        return self._feeds[i].depth() + (1 if self._sups[i].busy() else 0)

    def _route(self, req: protocol.SceneRequest,
               exclude: Optional[int] = None) -> Tuple[str, Optional[int]]:
        """One routing decision: ("dispatch"|"no_room"|"lost", slice).

        Streams pin to their owner slice (sessions are slice-local);
        scene ops route bucket-warm first, least-loaded on a cold bucket.
        """
        alive = self._alive(exclude)
        if not alive and exclude is not None:
            alive = self._alive()  # a 1-slice pool reroutes to itself
        if not alive:
            return ("no_room", None)
        if req.op in STREAM_OPS:
            owner = self._stream_owner.get(req.scene)
            if owner is not None:
                with self._lock:
                    owner_dead = owner in self._dead
                if (owner_dead or owner == exclude) \
                        and self._stream_resumable(req.scene):
                    # snapshot failover: the owner slice died (retired,
                    # or is the crashed slice this reroute excludes) but
                    # a per-chunk snapshot exists — re-open the session
                    # on a surviving warm slice; its child resumes the
                    # accumulator from disk (_book_dispatch re-pins)
                    room = [i for i in alive if self._has_room(i)]
                    if not room:
                        return ("no_room", None)
                    return ("dispatch", min(room, key=self._load))
                if owner_dead:
                    return ("lost", owner)
                if self._has_room(owner):
                    return ("dispatch", owner)
                return ("no_room", None)
            # a NEW stream: open it on the least-loaded slice
            room = [i for i in alive if self._has_room(i)]
            if not room:
                return ("no_room", None)
            return ("dispatch", min(room, key=self._load))
        room = [i for i in alive if self._has_room(i)]
        if not room:
            return ("no_room", None)
        bucket = self.router.bucket_for(req.scene)
        if bucket is not None:
            warm = [i for i in room if bucket in self._warm[i]]
            if warm:
                return ("dispatch", min(warm, key=self._load))
        return ("dispatch", min(room, key=self._load))

    def _has_room(self, i: int) -> bool:
        return self._feeds[i].depth() < self._feeds[i].capacity

    def _stream_resumable(self, scene: str) -> bool:
        """A per-chunk snapshot exists for this scene's stream: the
        session can re-open on another slice from disk."""
        if not self.stream_state_dir:
            return False
        from maskclustering_tpu.models.streaming import stream_state_path
        try:
            return os.path.exists(
                stream_state_path(self.stream_state_dir, scene))
        except OSError:
            return False

    def _try_dispatch(self, req: protocol.SceneRequest) -> str:
        verdict, wid = self._route(req)
        if verdict == "no_room":
            return "no_room"
        if verdict == "lost":
            self._answer_retired_stream(req, wid)
            return "answered"
        try:
            self._feeds[wid].submit(req)
        except QueueFullReject:
            return "no_room"  # racing dispatch filled the slot; re-route
        self._book_dispatch(req, wid)
        return "dispatched"

    def _book_dispatch(self, req: protocol.SceneRequest, wid: int) -> None:
        bucket = self.router.bucket_for(req.scene)
        hit: Optional[bool] = None
        with self._lock:
            if req.id in self._counted:
                self._counted.discard(req.id)
                t = req.tenant
                self._tenant_queued[t] = max(
                    0, self._tenant_queued.get(t, 0) - 1)
            self._dispatched += 1
            self._by_tenant[req.tenant] = \
                self._by_tenant.get(req.tenant, 0) + 1
            self._by_worker[wid] = self._by_worker.get(wid, 0) + 1
            if req.op in STREAM_OPS:
                self._stream_owner[req.scene] = wid
            if bucket is not None:
                hit = bucket in self._warm[wid]
                if hit:
                    self._affinity_hits += 1
                else:
                    self._affinity_misses += 1
                    # optimistic warmth: the slice compiles (or AOT-
                    # restores) this bucket now; its successors are warm
                    self._warm[wid].add(bucket)
        if hit is not None:
            obs.count("serve.pool.affinity_hits" if hit
                      else "serve.pool.affinity_misses")
        obs.count("serve.pool.dispatched")

    def _answer_retired_stream(self, req: protocol.SceneRequest,
                               owner: int) -> None:
        """The slice holding this stream's session exhausted its respawn
        budget and retired — the session is unrecoverable. Typed loss,
        owner cleared so a restarted stream opens fresh elsewhere."""
        with self._lock:
            self._stream_owner.pop(req.scene, None)
        obs.count("serve.requests")
        obs.count("serve.streams_lost")
        obs.count("serve.requests_failed")
        _send(req, protocol.status(
            req, "stream_lost",
            detail=f"owner worker {owner} retired (respawn budget "
                   f"exhausted)"))
        _send(req, protocol.result(
            req, "failed",
            error=f"stream session for {req.scene!r} lost: owner worker "
                  f"{owner} retired",
            error_class="stream_lost"))

    # -- crash rerouting -----------------------------------------------------

    def _requeue_from_worker(self, worker_id: int,
                             req: protocol.SceneRequest) -> bool:
        """A slice's supervisor hands back a crash victim (or its stop
        path hands back undispatched work): reroute to a warm NEIGHBOR
        immediately — the victim must not wait out the respawn wall."""
        if self._stop.is_set():
            return False  # the supervisor answers its own draining reject
        verdict, wid = self._route(req, exclude=worker_id)
        if verdict == "dispatch" and wid is not None \
                and self._feeds[wid].put_direct(req):
            with self._lock:
                self._crash_reroutes += 1
                self._by_worker[wid] = self._by_worker.get(wid, 0) + 1
                if req.op in STREAM_OPS:
                    self._stream_owner[req.scene] = wid
            obs.count("serve.pool.crash_reroutes")
            log.info("worker pool: rerouted %s from worker %d to %d",
                     req.id, worker_id, wid)
            return True
        # no warm neighbor with room right now: back to the main queue,
        # the scheduler re-routes it on its next pass
        return self.queue.requeue(req)

    def _slice_fatal(self, worker_id: int) -> None:
        """One slice exhausted its respawn budget: retire it, reroute its
        queued work, and only when EVERY slice is dead declare the pool
        (and daemon) unserveable."""
        with self._lock:
            self._dead.add(worker_id)
            dead = len(self._dead)
        obs.count("serve.pool.workers_retired")
        log.error("worker pool: worker %d retired (respawn budget "
                  "exhausted); %d/%d slices remain", worker_id,
                  len(self._sups) - dead, len(self._sups))
        for req in self._feeds[worker_id].drain():
            if req.op in STREAM_OPS and not self._stream_resumable(req.scene):
                self._answer_retired_stream(req, worker_id)
            elif not self.queue.requeue(req):
                obs.count("serve.requests_failed")
                _send(req, protocol.result(
                    req, "failed",
                    error=f"worker {worker_id} retired and the queue is "
                          f"full", error_class="device"))
        if dead >= len(self._sups) and self.on_fatal is not None:
            try:
                self.on_fatal()
            except Exception:  # noqa: BLE001
                log.exception("worker pool: on_fatal callback failed")

    # -- recarve -------------------------------------------------------------

    def recarve(self, workers: int = 0, carve: str = "",
                timeout_s: float = 300.0) -> Dict:
        """Drain every slice and respawn under a new carve. Admission
        keeps queueing the whole time (dispatch pauses); the shared AOT
        cache brings the new slices to first dispatch with zero compiles.
        """
        if not workers and not carve:
            raise ValueError("recarve needs 'workers' and/or 'carve'")
        chips = self.chips
        if carve:
            workers_spec, chips = parse_carve_spec(carve)
            if workers and workers != workers_spec:
                raise ValueError(
                    f"recarve workers={workers} contradicts carve "
                    f"{carve!r} (K={workers_spec})")
            workers = workers_spec
        check_carve(workers, chips, self._device_product())
        t0 = time.monotonic()
        self._pause.set()
        try:
            drained = self._wait_slices_idle(timeout_s)
            if not drained:
                raise RuntimeError(
                    "recarve: slices did not drain within "
                    f"{timeout_s:.0f}s; carve unchanged")
            for sup in self._sups:
                # stop FIRST: a drained slice may still be booking its
                # last result's counts — a stopped one is quiesced
                sup.stop(timeout_s=timeout_s)
                retired = sup.stats()
                for k, v in retired["counts"].items():
                    self._retired_counts[k] = \
                        self._retired_counts.get(k, 0) + v
                for k in self._retired_worker:
                    self._retired_worker[k] += retired["worker"][k]
                self._retired_latencies.extend(sup._latencies)
                del self._retired_latencies[:-512]  # bounded history
            self.workers = workers
            self.chips = chips
            new_carve = f"{workers}x{chips}" if chips else ""
            self.cfg = self.cfg.replace(serve_workers=workers,
                                        serve_carve=new_carve)
            self._build_slices()
            self._start_slices()
            with self._lock:
                self._recarves += 1
                # sessions died with the old slices; an owner-less stream
                # op routes as a new stream and the fresh child resumes
                # from its per-chunk snapshot when one exists
                self._stream_owner.clear()
        finally:
            self._pause.clear()
        obs.count("serve.pool.recarves")
        wall = time.monotonic() - t0
        log.info("worker pool: recarved to %dx%s in %.1fs", workers,
                 chips or "all", wall)
        return {"ok": True, "workers": workers,
                "carve": f"{workers}x{chips}" if chips else "",
                "seconds": round(wall, 2)}

    def _wait_slices_idle(self, timeout_s: float) -> bool:
        """In-flight + fed work finishes; the MAIN queue may keep filling
        (that is the point: recarve does not reject admissions)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(f.depth() == 0 for f in self._feeds) \
                    and not any(s.busy() for s in self._sups):
                return True
            time.sleep(0.02)
        return False

    # -- introspection (ServeWorker surface) --------------------------------

    def latency_quantiles(self) -> Dict[str, Optional[float]]:
        from maskclustering_tpu.obs.report import percentile

        vals: List[float] = list(self._retired_latencies)
        for sup in self._sups:
            vals.extend(sup._latencies)  # package-internal raw deque
        vals.sort()
        if not vals:
            return {"p50_s": None, "p95_s": None, "count": 0}
        return {"p50_s": round(percentile(vals, 50), 4),
                "p95_s": round(percentile(vals, 95), 4),
                "count": len(vals)}

    def run_canary(self, timeout_s: float = 120.0) -> Optional[list]:
        for i in self._alive():
            probes = self._sups[i].run_canary(timeout_s=timeout_s)
            if probes is not None:
                return probes
        return None

    def child_retrace(self) -> Dict:
        """Merged retrace digest: numeric fields sum across slices (zero
        post-warm compiles must hold on EVERY worker — a sum of zeros is
        zero), plus the per-worker digests for the drill's per-slice
        assertion."""
        merged: Dict = {}
        per: Dict[str, Dict] = {}
        for sup in self._sups:
            digest = sup.child_retrace()
            if digest:
                per[str(sup.worker_id)] = digest
            for k, v in digest.items():
                if isinstance(v, bool):
                    merged[k] = merged.get(k, False) or v
                elif isinstance(v, (int, float)):
                    merged[k] = merged.get(k, 0) + v
                else:
                    merged.setdefault(k, v)
        if per:
            merged["workers"] = per
        return merged

    def stats(self) -> Dict:
        per = [sup.stats() for sup in self._sups]
        counts: Dict[str, int] = dict(self._retired_counts)
        for p in per:
            for k, v in p["counts"].items():
                counts[k] = counts.get(k, 0) + v
        with self._lock:
            dead = set(self._dead)
            by_tenant = dict(self._by_tenant)
            tenant_queued = dict(self._tenant_queued)
            dispatched = self._dispatched
            hits, misses = self._affinity_hits, self._affinity_misses
            reroutes, recarves = self._crash_reroutes, self._recarves
            by_worker = dict(self._by_worker)
            warm_sizes = [len(w) for w in self._warm]
        alive = sum(1 for p in per if p["worker"]["alive"])
        workers = []
        for i, p in enumerate(per):
            w = dict(p["worker"])
            w.update({
                "worker_id": i,
                "retired": i in dead,
                "feed_depth": self._feeds[i].depth(),
                "dispatched": by_worker.get(i, 0),
                "warm_buckets": warm_sizes[i] if i < len(warm_sizes) else 0,
            })
            workers.append(w)
        tenants = {}
        for t in set(by_tenant) | set(self._qos) | set(tenant_queued):
            weight, quota = self._qos.get(t, (1.0, None))
            row = {"dispatched": by_tenant.get(t, 0), "weight": weight,
                   "queued": tenant_queued.get(t, 0)}
            if quota is not None:
                row["quota"] = quota
            tenants[t] = row
        return {
            "counts": counts,
            "latency": self.latency_quantiles(),
            "warm_buckets": sorted(self.router.warm_buckets()),
            # aggregate worker digest (the single-worker panel's shape;
            # per-slice detail lives under "pool")
            "worker": {"isolated": True, "pool": len(self._sups),
                       "alive": alive,
                       "spawns": self._retired_worker["spawns"]
                       + sum(p["worker"]["spawns"] for p in per),
                       "respawns": self._retired_worker["respawns"]
                       + sum(p["worker"]["respawns"] for p in per),
                       "crashes": self._retired_worker["crashes"]
                       + sum(p["worker"]["crashes"] for p in per),
                       "streams_resumed":
                       self._retired_worker["streams_resumed"]
                       + sum(p["worker"]["streams_resumed"] for p in per),
                       "inflight_width": sum(p["worker"]["inflight_width"]
                                             for p in per)},
            "pool": {
                "carve": (f"{self.workers}x{self.chips}" if self.chips
                          else str(self.workers)),
                "workers": workers,
                "scheduler": {"dispatched": dispatched,
                              "affinity_hits": hits,
                              "affinity_misses": misses,
                              "crash_reroutes": reroutes,
                              "recarves": recarves},
                "tenants": tenants,
            },
        }
