"""mct-serve daemon: the long-lived scene-serving process.

Lifecycle of one daemon::

    start()            bind the socket, pre-warm the serving vocabulary
                       (explicit warm scenes and/or the surface baseline's
                       workload), optionally freeze the retrace sanitizer
                       (a warm daemon books ZERO compiles per request),
                       then start the worker + acceptor threads
    serve_forever()    poll the stop flags (own + faults.stop_requested(),
                       which the SIGTERM handler sets) at scene-safe
                       granularity
    shutdown()         stop admitting (new lines answer ``draining``),
                       finish the request in flight, typed-reject the
                       still-queued ones, join every thread bounded,
                       close the socket

Thread topology (all spawns bounded-joined at shutdown; the scope-local
CONC.JOIN check cannot see the cross-method join, hence the abandon
markers with that exact rationale):

- **acceptor** — ``accept()`` with a poll timeout; spawns one handler per
  connection;
- **handler** (per connection) — reads JSONL lines, validates, admits
  into the bounded queue, answers ``ack``/``reject`` inline; the
  request's ``send`` stays bound to this connection (one lock per
  connection serializes event lines);
- **worker** (``serve/worker.py``) — the single device-owning executor.

The daemon deliberately reuses the one-shot stack end to end — the same
``setup_compilation_cache``, the same executors, the same artifact
exports — so a served scene's npz is byte-identical to ``run.py``'s and a
restarted daemon starts against the same persistent compile cache.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from maskclustering_tpu import obs
from maskclustering_tpu.analysis.lock_sanitizer import mct_lock
from maskclustering_tpu.obs import flight as _flight
from maskclustering_tpu.obs import slo as _slo
from maskclustering_tpu.obs import telemetry
from maskclustering_tpu.serve import protocol
from maskclustering_tpu.serve import wal as _wal
from maskclustering_tpu.serve.admission import AdmissionQueue, QueueFullReject
from maskclustering_tpu.serve.pool import QuotaReject
from maskclustering_tpu.serve.router import Router
from maskclustering_tpu.serve.worker import ServeWorker
from maskclustering_tpu.utils import faults

log = logging.getLogger("maskclustering_tpu")

DEFAULT_CAPACITY = 8


def _make_sender(conn: socket.socket):
    """A thread-safe one-line-per-event sender bound to one connection.

    ``send.lock``/``send.raw`` exist for the admission handshake: the
    handler holds the lock across queue-submit + ack (written via
    ``raw``), so the worker — which can pick the request up the instant
    it lands in the queue — cannot interleave a ``running`` status (or
    even the result) BEFORE the ack the protocol promises first.
    """
    lock = mct_lock("serve.Connection._send_lock")

    def raw(event: Dict) -> None:
        conn.sendall(protocol.encode(event))

    def send(event: Dict) -> None:
        with lock:
            raw(event)

    send.lock = lock
    send.raw = raw
    return send


class _WalSend:
    """A WAL-tracked request's ``send``: records the dispatch row (first
    status event) and the terminal row in the admission WAL, then forwards
    to the currently attached client connection. ``client`` is the one
    mutable cell — a reconnect-and-resubmit with the same idempotency key
    swaps it live (re-attach), and a request replayed from the WAL starts
    detached (``client`` None: the work runs and journals, the terminal
    waits in the dedupe cache for the client's resubmit)."""

    def __init__(self, daemon: "ServeDaemon", rid: str, idem: str,
                 client=None):
        self._daemon = daemon
        self.rid = rid
        self.idem = idem
        self.client = client
        self._dispatched = False

    def __call__(self, event: Dict) -> None:
        kind = event.get("kind")
        if kind == "status" and not self._dispatched:
            # benign race on the flag: at worst a duplicate advisory
            # dispatch row, never a lost one
            self._dispatched = True
            self._daemon._wal_dispatch(self.rid)
        if kind in ("result", "reject"):
            self._daemon._wal_terminal(self.rid, self.idem, event)
        client = self.client
        if client is not None:
            client(event)


class ServeDaemon:
    """One serving process: admission + router + worker + socket front."""

    def __init__(self, cfg, *,
                 socket_path: Optional[str] = None,
                 host: Optional[str] = None, port: int = 0,
                 capacity: int = DEFAULT_CAPACITY,
                 journal_dir: Optional[str] = None,
                 prediction_root: Optional[str] = None,
                 stream_state_dir: Optional[str] = None,
                 wal: bool = True,
                 warm_scenes: Tuple[str, ...] = (),
                 warm_baseline: Optional[str] = None,
                 freeze_after_warm: bool = True,
                 default_deadline_s: float = 0.0,
                 isolate_worker: bool = False,
                 fault_plan_spec: Optional[str] = None,
                 telemetry_window_s: float = 5.0,
                 slo_spec: Optional[str] = None,
                 flight_dir: Optional[str] = None,
                 canary_interval_s: float = 0.0,
                 canary_goldens: Optional[str] = None):
        if socket_path is None and host is None:
            raise ValueError("need a socket_path (AF_UNIX) or host/port (TCP)")
        self.cfg = cfg
        self.socket_path = socket_path
        self.host = host
        self.port = port
        self.default_deadline_s = float(default_deadline_s)
        self.freeze_after_warm = freeze_after_warm
        self.warm_scenes = tuple(warm_scenes)
        self.isolate_worker = bool(isolate_worker)
        self.journal_dir = journal_dir
        # shared per-chunk stream snapshots (models/streaming save_state):
        # the worker ships them here and a crashed stream's session
        # re-opens from the latest one instead of answering stream_lost
        self.stream_state_dir = stream_state_dir
        if stream_state_dir:
            os.makedirs(stream_state_dir, exist_ok=True)
        self.queue = AdmissionQueue(capacity)
        self.router = Router(cfg, baseline_path=warm_baseline)
        pool_size = max(int(cfg.serve_workers), 1)
        if pool_size > 1 and not isolate_worker:
            raise ValueError(
                f"serve_workers={pool_size} needs --isolate-worker: pool "
                "slices are supervised subprocesses (one device owner per "
                "slice), never threads")
        if pool_size > 1:
            # the worker pool (serve/pool.py): K supervised slices behind
            # one affinity-aware weighted-fair scheduler; exposes the
            # WorkerSupervisor surface so everything below is unchanged
            from maskclustering_tpu.serve.pool import WorkerPool

            self.worker = WorkerPool(
                cfg, self.queue, self.router,
                journal_dir=journal_dir,
                prediction_root=prediction_root,
                stream_state_dir=stream_state_dir,
                warm_scenes=self.warm_scenes,
                warm_baseline=warm_baseline,
                freeze_after_warm=freeze_after_warm,
                fault_plan_spec=fault_plan_spec,
                on_fatal=self.request_stop)
        elif isolate_worker:
            # crash containment (serve/supervisor.py): the device owner is
            # a supervised SUBPROCESS — a SIGKILL'd/wedged worker costs a
            # respawn, not the daemon; warm-up (incl. the AOT-cache warm
            # start) happens in the child, so the parent stays device-free
            from maskclustering_tpu.serve.supervisor import WorkerSupervisor

            self.worker = WorkerSupervisor(
                cfg, self.queue, self.router,
                journal_dir=journal_dir,
                prediction_root=prediction_root,
                stream_state_dir=stream_state_dir,
                warm_scenes=self.warm_scenes,
                warm_baseline=warm_baseline,
                freeze_after_warm=freeze_after_warm,
                fault_plan_spec=fault_plan_spec,
                on_fatal=self.request_stop)
        else:
            self.worker = ServeWorker(cfg, self.queue, self.router,
                                      journal_dir=journal_dir,
                                      prediction_root=prediction_root,
                                      stream_state_dir=stream_state_dir)
        self._lock = mct_lock("serve.ServeDaemon._lock")
        self._ids = 0
        # admission WAL (serve/wal.py): armed whenever journaling is on —
        # journal_dir holds the per-request journals AND the daemon's one
        # crash-safe admission ledger. The sink opens in start() (after
        # recovery compacts the predecessor's file)
        self._wal: Optional[_wal.AdmissionWal] = None
        self._wal_path = ""
        if wal and journal_dir:
            os.makedirs(journal_dir, exist_ok=True)
            self._wal_path = os.path.join(journal_dir, _wal.WAL_FILENAME)
        # idem dedupe planes: key -> cached terminal event (answered) and
        # key -> the live in-flight request (running; re-attach target)
        self._wal_answered: Dict[str, Dict] = {}
        self._wal_running: Dict[str, protocol.SceneRequest] = {}
        self._wal_replayed = 0
        self._wal_deduped = 0
        self._wal_reattached = 0
        self._journals_pruned = 0
        self._pruner: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._draining = threading.Event()
        # connections outlive the stop flag: in-flight results and the
        # queued requests' draining rejects must still reach their
        # clients, so handler threads only exit once the drain is done
        self._conns_stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._acceptor: Optional[threading.Thread] = None
        self._handlers: List[threading.Thread] = []
        self._started_at = 0.0
        self._warmup_s = 0.0
        # the live telemetry plane (obs/telemetry.py): windowed rolling
        # aggregation over the parent registry — which, under
        # --isolate-worker, the supervisor keeps fed via the telem relay
        self.aggregator = telemetry.WindowAggregator(
            window_s=telemetry_window_s)
        self._ticker = telemetry.TelemetryTicker(self.aggregator)
        # the SLO plane (obs/slo.py): a bad spec must fail daemon startup
        # loudly, not surface as a broken `status` answer hours later
        self.slo_spec = _slo.load_spec(slo_spec)
        # the correctness plane (obs/canary.py): goldens load at start()
        # so a warm worker exists before the first probe
        self.canary_interval_s = float(canary_interval_s)
        self.canary_goldens = canary_goldens
        self.sentinel = None
        if flight_dir:
            # arm this process AND (via env) any worker subprocess it
            # spawns — the child's flight ring needs somewhere to dump too
            _flight.arm(flight_dir)
            os.environ[_flight.ENV_DIR] = flight_dir
        self._capacity_dumped = threading.Event()

    # -- lifecycle ----------------------------------------------------------

    @property
    def address(self):
        """The bound address: the socket path, or (host, port) for TCP."""
        if self.socket_path is not None:
            return self.socket_path
        assert self._listener is not None, "start() first"
        return self._listener.getsockname()

    def start(self) -> None:
        from maskclustering_tpu.utils.compile_cache import \
            setup_compilation_cache

        setup_compilation_cache(self.cfg.compilation_cache_dir)
        self._started_at = time.monotonic()
        self._bind()
        if self.isolate_worker:
            # the child owns warm-up end to end (AOT restore + warm
            # scenes + sanitizer freeze); start() blocks until its ready
            # line, so the daemon accepts requests only against a warm
            # worker — same contract as the in-thread _prewarm
            t0 = time.monotonic()
            self.worker.start()
            self._warmup_s = time.monotonic() - t0
        else:
            from maskclustering_tpu.utils import aot_cache

            aot_cache.warm_start(self.cfg)
            self._prewarm()
            self.worker.start()
        # durability plane: recover the predecessor's admission WAL (seed
        # the id counter, warm the dedupe cache, replay journaled-but-
        # unanswered requests into the queue), then the retention pass —
        # both BEFORE the acceptor so recovery races no live admission
        self._recover_wal()
        self._prune_retention()
        self._start_pruner()
        # install + tick AFTER warm-up, with the delta baseline re-anchored
        # to NOW: windows meter serving, and without the rebase window 0
        # would charge the whole warm-up wall + its counter deltas (AOT
        # restores, prewarm dispatches) to itself
        self.aggregator.rebase()
        telemetry.install(self.aggregator)
        self._ticker.start()
        self._start_sentinel()
        self._acceptor = threading.Thread(  # mct-thread: abandon(daemon-lifetime thread, bounded-joined in shutdown(); the spawn/join pair spans methods, which the scope-local check cannot see)
            target=self._accept_loop, daemon=True, name="serve-acceptor")
        self._acceptor.start()
        log.info("mct-serve: accepting on %s (capacity %d, %d warm "
                 "bucket(s), warm-up %.1fs)", self.address,
                 self.queue.capacity, len(self.router.warm_buckets()),
                 self._warmup_s)

    def _bind(self) -> None:
        if self.socket_path is not None:
            if os.path.exists(self.socket_path):
                os.unlink(self.socket_path)
            os.makedirs(os.path.dirname(self.socket_path) or ".",
                        exist_ok=True)
            self._listener = socket.socket(socket.AF_UNIX,
                                           socket.SOCK_STREAM)
            self._listener.bind(self.socket_path)
        else:
            self._listener = socket.socket(socket.AF_INET,
                                           socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEADDR, 1)
            self._listener.bind((self.host, self.port))
        self._listener.listen(16)
        self._listener.settimeout(0.25)  # the acceptor's stop-poll cadence

    def _start_sentinel(self) -> None:
        """Arm the canary sentinel (correctness plane) when requested.

        Missing/stale goldens disable the sentinel with a loud warning
        rather than failing startup: a daemon that serves real traffic
        but cannot self-verify beats no daemon, and the drill/CI gate is
        where an unverifiable daemon must fail.
        """
        if self.canary_interval_s <= 0:
            return
        from maskclustering_tpu.obs import canary as _canary

        path = self.canary_goldens or _canary.DEFAULT_GOLDENS_PATH
        goldens = _canary.load_goldens(path)
        if goldens is None:
            log.warning("mct-serve: canary sentinel requested but no usable "
                        "goldens at %s — sentinel disabled; regenerate via "
                        "load_gen --write-goldens", path)
            return
        self.sentinel = _canary.CanarySentinel(
            run_round=self.worker.run_canary,
            goldens=goldens, interval_s=self.canary_interval_s,
            # idle = nothing queued; the worker may still be mid-request,
            # which run_canary's handshake waits out at the next idle poll
            is_idle=lambda: self.queue.depth() == 0)
        self.sentinel.start()
        log.info("mct-serve: canary sentinel armed (%d golden coordinate(s),"
                 " every %.1fs)", len(goldens.get("goldens") or {}),
                 self.canary_interval_s)

    def _prewarm(self) -> None:
        """Pay the serving vocabulary's compiles before the first request.

        An active FaultPlan (a serving-path drill) is suspended for the
        duration: warm-up scenes often ARE the drill's target scenes, and
        a plan consumed during warm-up would leave the serving path —
        the thing the drill exists to exercise — fault-free.
        """
        t0 = time.monotonic()
        drill = faults.active_plan()
        faults.set_plan(None)
        try:
            for name, tensors in self.router.warmup_workload():
                self.worker.warm_tensors(name, tensors)
                # continuous batching: the width-S fused executable is a
                # DISTINCT program from the width-1 warm above — pay its
                # compile here too, or the first packed batch compiles
                # after the sanitizer freeze (a post-warm violation)
                self.worker.warm_batch_executable(name, tensors)
            if self.warm_scenes:
                from maskclustering_tpu.run import cluster_scenes

                statuses = cluster_scenes(self.cfg, list(self.warm_scenes),
                                          resume=False)
                for st in statuses:
                    log.info("mct-serve: warm scene %s -> %s", st.seq_name,
                             st.status)
                self._warm_batch_from_disk()
        finally:
            faults.set_plan(drill)
        self._warmup_s = time.monotonic() - t0
        from maskclustering_tpu.analysis import retrace_sanitizer

        if self.freeze_after_warm and retrace_sanitizer.enabled():
            # the serve-many contract's runtime half: from here on, every
            # compile is a post-warm violation (enumerated ladder-rung
            # surface excepted) — "compiles post-warm-up" in the Serving
            # report reads straight off this freeze
            retrace_sanitizer.freeze()
            log.info("mct-serve: retrace sanitizer frozen after warm-up")

    def _warm_batch_from_disk(self) -> None:
        """Classify --warm disk scenes in the router and pay their width-S
        fused compiles (no-op with batching off).

        cluster_scenes warms the single-chip ladder but never touches the
        router, so without this the first live request for a warm scene
        dispatches solo-unclassified AND the first packed batch compiles
        after the sanitizer freeze."""
        if int(getattr(self.cfg, "serve_batch_max", 1) or 1) <= 1:
            return
        from maskclustering_tpu.datasets import get_dataset

        for name in self.warm_scenes:
            try:
                ds = get_dataset(self.cfg.dataset, name,
                                 data_root=self.cfg.data_root)
                tensors = ds.load_scene_tensors(self.cfg.step)
            except Exception:
                log.exception("mct-serve: batch warm skipped for %s", name)
                continue
            self.router.remember(name, self.router.classify_tensors(tensors))
            self.worker.warm_batch_executable(name, tensors)

    def request_stop(self) -> None:
        self._stop.set()

    def stopping(self) -> bool:
        return self._stop.is_set() or faults.stop_requested()

    def serve_forever(self, poll_s: float = 0.2) -> None:
        """Block until a stop is requested (own flag or SIGTERM), then
        drain and shut down."""
        while not self.stopping():
            time.sleep(poll_s)
        self.shutdown()

    def shutdown(self, timeout_s: float = 60.0) -> None:
        """SIGTERM-shaped drain: finish the in-flight request, typed-reject
        the queued ones, join every thread bounded, close the socket."""
        if self._draining.is_set():
            return
        self._draining.set()
        self._stop.set()
        log.info("mct-serve: draining (in-flight request finishes, queued "
                 "requests get typed rejects)")
        if self.sentinel is not None:
            self.sentinel.stop()
        drained_clean = self.worker.stop(timeout_s=timeout_s)
        if not drained_clean:
            log.error("mct-serve: in-flight request outlived the %.0fs "
                      "drain budget; its journal has the in-flight attempt",
                      timeout_s)
        for req in self.queue.drain():
            obs.count("serve.admission.rejects.draining")
            try:
                if req.send is not None:
                    req.send(protocol.reject(
                        "draining", req=req,
                        detail="daemon shutting down before dispatch"))
            except Exception:  # noqa: BLE001 — client gone mid-shutdown
                pass
        # stop sampling AFTER the drain: its final roll puts the drain's
        # rejects on disk as the last telemetry window
        self._ticker.stop()
        if telemetry.installed() is self.aggregator:
            telemetry.install(None)
        self._conns_stop.set()
        if self._acceptor is not None:
            self._acceptor.join(5.0)
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        if self.socket_path and os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        with self._lock:
            handlers = list(self._handlers)
        for t in handlers:
            t.join(2.0)
        if self._pruner is not None:
            self._pruner.join(2.0)  # _stop is set: the wait returns now
        if self._wal is not None:
            # after the drain: every terminal (incl. the draining rejects
            # above, which route through the _WalSend wrappers) is on disk
            self._wal.close()
        # cooperative-drain black box (the SIGTERM handler itself is
        # flag-only — CONC.SIGNAL): armed runs keep the daemon's final
        # admission/span history next to any worker-crash dumps
        _flight.record(_flight.KIND_SIGNAL, what="daemon_drained",
                       clean=drained_clean)
        _flight.dump("sigterm" if faults.stop_requested() else "shutdown")
        log.info("mct-serve: shutdown complete (%s)", self.stats()["counts"])

    # -- durability (serve/wal.py) ------------------------------------------

    def _recover_wal(self) -> None:
        """Fold the predecessor daemon's WAL into this one: id-counter
        seed, idem dedupe cache, and the replay of every journaled-but-
        unanswered request back into the admission queue (detached — the
        client re-attaches by resubmitting its idempotency key)."""
        if not self._wal_path:
            return
        state = _wal.read_wal(self._wal_path)
        with self._lock:
            self._ids = max(self._ids, state.max_id)
            self._wal_answered.update(state.answered)
        if state.stats.torn or state.stats.unknown_version:
            log.warning("mct-serve: WAL recovery skipped %d torn / %d "
                        "unknown-version row(s)", state.stats.torn,
                        state.stats.unknown_version)
        if state.rows:
            _wal.compact(self._wal_path, state)
        self._wal = _wal.AdmissionWal(self._wal_path)
        submit = getattr(self.worker, "admit", self.queue.submit)
        replayed = 0
        for rid, doc, idem in state.pending:
            try:
                req = protocol.build_request(dict(doc), rid)
            except (protocol.ProtocolError, KeyError, TypeError,
                    ValueError) as e:
                # a poisoned row must settle, not resurrect every restart
                self._wal.terminal(rid, protocol.reject(
                    "bad_request", detail=f"unreplayable WAL row: {e}"),
                    idem=idem)
                continue
            req.send = _WalSend(self, rid, idem, client=None)
            if idem:
                with self._lock:
                    self._wal_running[idem] = req
            try:
                submit(req)
            except (QuotaReject, QueueFullReject) as e:
                reason = ("queue_full" if isinstance(e, QueueFullReject)
                          else "quota")
                self._wal_terminal(rid, idem, protocol.reject(
                    reason, detail=f"WAL replay re-admission failed: {e}"))
                continue
            replayed += 1
            obs.count("serve.wal.replayed")
        self._wal_replayed = replayed
        if replayed:
            log.warning("mct-serve: replayed %d journaled-but-unanswered "
                        "request(s) from the admission WAL", replayed)

    def _wal_dispatch(self, rid: str) -> None:
        wal = self._wal
        if wal is not None:
            wal.dispatch(rid)

    def _wal_terminal(self, rid: str, idem: str, event: Dict) -> None:
        wal = self._wal
        if wal is not None:
            wal.terminal(rid, event, idem=idem)
        if idem:
            with self._lock:
                self._wal_answered[idem] = dict(event)
                self._wal_running.pop(idem, None)

    def _wal_resubmit(self, req: protocol.SceneRequest, send) -> bool:
        """The idempotency contract: a resubmitted key that already
        answered replays the cached terminal (stamped ``deduped``); one
        still running re-attaches THIS connection to its event stream.
        False = a fresh key, admit normally."""
        with self._lock:
            cached = self._wal_answered.get(req.idem)
            running = self._wal_running.get(req.idem)
        if cached is not None:
            self._wal_deduped += 1
            obs.count("serve.wal.deduped")
            ev = dict(cached)
            ev["deduped"] = True
            if req.tag:
                ev["tag"] = req.tag
            with send.lock:
                send.raw(protocol.ack(req, queue_depth=self.queue.depth()))
                send.raw(ev)
            return True
        if running is not None:
            wrapper = running.send
            if isinstance(wrapper, _WalSend):
                wrapper.client = send
            self._wal_reattached += 1
            obs.count("serve.wal.reattached")
            # the running request may have answered between the lookup
            # and the re-attach: replay the terminal to this connection
            # (a racing duplicate line is harmless — the client stops at
            # its first terminal)
            with self._lock:
                cached = self._wal_answered.get(req.idem)
            with send.lock:
                send.raw(protocol.ack(running,
                                      queue_depth=self.queue.depth()))
                if cached is not None:
                    ev = dict(cached)
                    ev["deduped"] = True
                    send.raw(ev)
            return True
        return False

    def _wal_abort(self, req: Optional[protocol.SceneRequest],
                   event: Dict) -> None:
        """An admission that WAL-journaled but failed to enqueue (quota /
        queue_full raised at submit) settles with the reject as its
        terminal row — replay must not resurrect it."""
        if req is not None and isinstance(req.send, _WalSend):
            self._wal_terminal(req.id, req.idem, event)

    def _prune_retention(self) -> None:
        """Retention pass: settled per-request journals and finished
        streams' snapshots age out under the serve_journal_keep /
        serve_journal_max_age_s knobs (the WAL itself is skipped by
        name; prune_dir's freshness floor protects live state)."""
        keep = int(self.cfg.serve_journal_keep or 0)
        age = float(self.cfg.serve_journal_max_age_s or 0.0)
        removed = 0
        if self.journal_dir:
            removed += _wal.prune_dir(self.journal_dir, keep=keep,
                                      max_age_s=age, suffixes=(".jsonl",))
        if self.stream_state_dir:
            removed += _wal.prune_dir(self.stream_state_dir, keep=keep,
                                      max_age_s=age,
                                      suffixes=(".stream.npz",))
        if removed:
            with self._lock:
                self._journals_pruned += removed
            obs.count("serve.journals_pruned", removed)
            log.info("mct-serve: retention pruned %d journal/snapshot "
                     "file(s)", removed)

    def _start_pruner(self) -> None:
        interval = float(self.cfg.serve_prune_interval_s or 0.0)
        if interval <= 0 or not (self.journal_dir or self.stream_state_dir):
            return

        def _loop() -> None:
            while not self._stop.wait(interval):
                self._prune_retention()

        self._pruner = threading.Thread(  # mct-thread: abandon(daemon-lifetime retention timer, bounded-joined in shutdown(); the spawn/join pair spans methods, which the scope-local check cannot see)
            target=_loop, daemon=True, name="serve-pruner")
        self._pruner.start()

    # -- socket front -------------------------------------------------------

    def _accept_loop(self) -> None:
        # polls the DAEMON's stop flag, not the process-global SIGTERM
        # flag: only serve_forever()/shutdown() translate a SIGTERM into
        # a daemon stop, so an embedding process (tests, a future
        # multi-daemon host) can field signals without killing acceptors
        while not self._stop.is_set():
            listener = self._listener
            if listener is None:
                break
            try:
                conn, _addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed under us: shutdown in progress
            t = threading.Thread(  # mct-thread: abandon(per-connection reader, bounded-joined in shutdown(); clients may hold connections open for the daemon's lifetime)
                target=self._handle_conn, args=(conn,), daemon=True,
                name="serve-conn")
            with self._lock:
                self._handlers = [h for h in self._handlers
                                  if h.is_alive()] + [t]
            t.start()

    def _handle_conn(self, conn: socket.socket) -> None:
        send = _make_sender(conn)
        buf = b""
        conn.settimeout(0.5)
        try:
            while not self._conns_stop.is_set():
                try:
                    chunk = conn.recv(65536)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not chunk:
                    break
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    try:
                        self._handle_line(send,
                                          line.decode("utf-8", "replace"))
                    except OSError:
                        # the client hung up before its answer (an aborted
                        # probe, a dead load-gen thread): admitted work
                        # still runs and journals; only this connection dies
                        return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _next_id(self) -> str:
        with self._lock:
            self._ids += 1
            return f"r-{self._ids:06d}"

    def _handle_line(self, send, line: str) -> None:
        if not line.strip():
            return
        tag = ""
        req: Optional[protocol.SceneRequest] = None
        try:
            doc = protocol.parse_line(line)
            tag = str(doc.get("tag", ""))
            op = doc["op"]
            if op == "status":
                doc_stats = self.stats()
                detail = doc.get("detail")
                if detail in ("telemetry", "slo"):
                    doc_stats["telemetry"] = self.aggregator.snapshot()
                if detail == "slo":
                    doc_stats["slo"] = _slo.evaluate(
                        self.slo_spec, doc_stats["telemetry"])
                if detail == "sentinel":
                    doc_stats["sentinel"] = (
                        self.sentinel.stats() if self.sentinel is not None
                        else {"armed": False})
                send({"v": protocol.PROTOCOL_VERSION, "kind": "stats",
                      **doc_stats})
                return
            if op == "shutdown":
                send({"v": protocol.PROTOCOL_VERSION, "kind": "ack",
                      "op": "shutdown"})
                self.request_stop()
                return
            if self._draining.is_set() or self._stop.is_set():
                obs.count("serve.admission.rejects.draining")
                send(protocol.reject("draining", tag=tag,
                                     detail="daemon is shutting down"))
                return
            if op == "recarve":
                # pool admin op: drain + respawn under a new carve while
                # admission keeps queueing. Blocks THIS connection's
                # handler (the carve wall is the answer's payload); other
                # connections keep admitting throughout
                if not hasattr(self.worker, "recarve"):
                    raise protocol.ProtocolError(
                        "recarve needs a worker pool (serve_workers > 1)")
                try:
                    out = self.worker.recarve(
                        workers=int(doc.get("workers", 0) or 0),
                        carve=str(doc.get("carve", "") or ""))
                except (ValueError, RuntimeError) as e:
                    send({"v": protocol.PROTOCOL_VERSION, "kind": "recarve",
                          "ok": False, "error": str(e), **({"tag": tag}
                                                           if tag else {})})
                    return
                send({"v": protocol.PROTOCOL_VERSION, "kind": "recarve",
                      **out, **({"tag": tag} if tag else {})})
                return
            if doc.get("synthetic") is not None \
                    and self.cfg.dataset != "scannet":
                raise protocol.ProtocolError(
                    "inline synthetic scenes need a scannet-layout config "
                    f"(daemon dataset is {self.cfg.dataset!r})")
            if doc.get("deadline_s", 0) == 0 and self.default_deadline_s > 0:
                doc["deadline_s"] = self.default_deadline_s
            req = protocol.build_request(doc, self._next_id())
            req.send = send
            if self._wal is not None:
                if req.idem and self._wal_resubmit(req, send):
                    return  # answered from cache, or re-attached live
                # crash-safe admission: the admit row hits disk BEFORE
                # the queue, so a daemon killed between them resurrects
                # (never loses) the request at the next start()
                req.send = _WalSend(self, req.id, req.idem, client=send)
                if req.idem:
                    with self._lock:
                        self._wal_running[req.idem] = req
                self._wal.admit(req.id, doc, idem=req.idem)
            # the chaos drill's daemon-death seam: a `die` FaultPlan entry
            # SIGKILLs THIS process here — after the WAL admit, before
            # the queue — the worst torn state recovery must survive
            faults.inject("admission", req.scene)
            # submit + ack under the connection's send lock: the worker's
            # first event for this request serializes AFTER the ack. A
            # pool worker gates admission through its tenant quotas
            # (pool.admit raises the typed QuotaReject below)
            submit = getattr(self.worker, "admit", self.queue.submit)
            with send.lock:
                depth = submit(req)
                send.raw(protocol.ack(req, queue_depth=depth))
        except protocol.ProtocolError as e:
            obs.count("serve.admission.rejects.bad_request")
            send(protocol.reject("bad_request", detail=str(e), tag=tag))
            return
        except QuotaReject as e:
            telemetry.record_reject(str(doc.get("tenant", "")))
            ev = protocol.reject(
                "quota", tag=tag,
                detail=f"tenant {e.tenant!r} at its queued-request quota "
                       f"({e.queued}/{e.limit}); retry after completions")
            self._wal_abort(req, ev)
            send(ev)
        except QueueFullReject as e:
            telemetry.record_reject(str(doc.get("tenant", "")))
            if not self._capacity_dumped.is_set():
                # first capacity error per process: what the admission
                # plane looked like when backpressure began (later
                # queue_full rejects are ordinary backpressure, not news)
                self._capacity_dumped.set()
                _flight.dump("capacity")
            ev = protocol.reject(
                "queue_full", tag=tag,
                detail=f"{e.depth}/{e.capacity} queued; retry with backoff")
            self._wal_abort(req, ev)
            send(ev)

    # -- introspection ------------------------------------------------------

    def stats(self) -> Dict:
        w = self.worker.stats()
        from maskclustering_tpu.analysis import retrace_sanitizer

        retrace: Dict = {}
        if self.isolate_worker:
            # compiles happen in the worker subprocess: its ready/bye
            # digest is the serve-many contract's evidence, not the
            # parent's (empty) sanitizer state
            retrace = self.worker.child_retrace()
        elif retrace_sanitizer.enabled():
            retrace = retrace_sanitizer.summary()
        return {
            "config": self.cfg.config_name,
            # perf-attribution coordinate (ledger serve rows + --regress
            # knob-flip advisory): a resharded daemon's latency profile is
            # the knob's, not code drift's
            "point_shards": int(self.cfg.point_shards),
            "streaming_chunk": int(self.cfg.streaming_chunk),
            "uptime_s": round(time.monotonic() - self._started_at, 2)
            if self._started_at else 0.0,
            "warmup_s": round(self._warmup_s, 2),
            "queue": {"depth": self.queue.depth(),
                      "capacity": self.queue.capacity,
                      "high_water": self.queue.high_water,
                      "admitted": self.queue.admitted},
            "counts": w["counts"],
            "latency": w["latency"],
            "warm_buckets": [list(b) for b in w["warm_buckets"]],
            "retrace": retrace,
            # drift-plane summary for load_gen verdicts + serve ledger
            # stamping (full matrix behind the "sentinel" status detail)
            "canary": ({"rounds": self.sentinel.stats()["rounds"],
                        "drift_total": self.sentinel.stats()["drift_total"]}
                       if self.sentinel is not None else None),
            "draining": self._draining.is_set(),
            # the durability plane (serve/wal.py): WAL replay/dedupe and
            # retention evidence — the chaos drill's verdict reads these
            "durable": {"wal": self._wal is not None
                        or bool(self._wal_path),
                        "wal_replayed": self._wal_replayed,
                        "wal_deduped": self._wal_deduped,
                        "wal_reattached": self._wal_reattached,
                        "journals_pruned": self._journals_pruned},
            # the packing scheduler's occupancy digest (in-thread worker
            # only; under --isolate-worker the CHILD packs and its
            # serve.batch.* counters relay up via telemetry instead)
            **({"batch": w["batch"]} if "batch" in w else {}),
            **({"worker": w["worker"]} if "worker" in w else {}),
            # the pool plane (serve/pool.py): per-slice liveness/warmth,
            # scheduler affinity/share accounting, tenant QoS table
            **({"pool": w["pool"]} if "pool" in w else {}),
        }

    def emit_serve_counters(self) -> None:
        """Book the serving digest on the obs registry (the report's
        Serving section renders from these; call before flush/shutdown)."""
        lat = self.worker.latency_quantiles()
        if lat["p50_s"] is not None:
            obs.gauge("serve.request_p50_s", lat["p50_s"])
            obs.gauge("serve.request_p95_s", lat["p95_s"])
        obs.gauge("serve.queue_depth_high_water",
                  float(self.queue.high_water))
        obs.gauge("serve.warm_buckets", float(len(self.router.warm_buckets())))
        batch = getattr(self.worker, "batch_stats", lambda: None)()
        if batch and batch.get("dispatches"):
            obs.gauge("serve.batch_occupancy", float(batch["occupancy"]))
