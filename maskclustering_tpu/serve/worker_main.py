"""The process-isolated device worker: one subprocess, one device owner.

``python -m maskclustering_tpu.serve.worker_main --cfg-json PATH`` is the
child half of the crash-containment story (serve/supervisor.py is the
parent): the device-owning execution moved out of the daemon's process so
a hard XLA/TPU crash (segfault, OOM-kill, wedged runtime — the failure
mode that kept BENCH_r04/r05 null) costs one SIGKILL + respawn instead of
the whole serving process, its admission queue and every connected client.

Wire contract (line-delimited JSON over the stdio pipes; stderr carries
logging only):

- stdin  <- ``{"op": "scene", "id": ..., ...}`` (protocol.forward_request
  shape: remaining deadline, crash count), ``{"op": "batch",
  "requests": [...]}`` (protocol.forward_batch: a same-bucket pack whose
  members land in the local queue together so the worker's own scheduler
  re-fuses them), ``{"op": "canary"}`` (one mct-sentinel probe round;
  answers ``{"kind": "canary", "probes": ...}``) and
  ``{"op": "shutdown"}``; EOF == shutdown.
- stdout -> ``{"kind": "ready", ...}`` once warm (carries the warm-up
  wall, the AOT-cache restore stats and the retrace digest — the
  supervisor's proof the respawn reached first dispatch with zero
  compiles), ``{"kind": "hb"}`` heartbeats at a fixed cadence, the
  standard per-request ``status``/``result`` events, and
  ``{"kind": "bye", ...}`` after a drain.

The heartbeat is emitted by a dedicated thread so a busy device phase
never silences it — only a process-level wedge does (a GIL-held native
hang stops every Python thread, which is exactly what the parent's
``faults.Heartbeat`` budget detects; the ``wedge`` fault kind simulates
it deterministically by silencing the emitter via ``faults.set_wedge_hook``
before hanging).

Execution semantics are ServeWorker's, verbatim — the same per-request
SceneSupervisor, deadline folding, per-request journal and bucket
accounting the in-thread daemon worker runs — fed by a local two-slot
admission queue this process's stdin reader fills. One copy of the
serving semantics, two process topologies.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import threading
import time

log = logging.getLogger("maskclustering_tpu")


def _retrace_digest() -> dict:
    from maskclustering_tpu.analysis import retrace_sanitizer

    if not retrace_sanitizer.enabled():
        return {}
    return retrace_sanitizer.summary()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="maskclustering_tpu.serve.worker_main",
        description="device-owning serving worker subprocess (JSONL over "
                    "stdio; spawned by serve/supervisor.py)")
    parser.add_argument("--cfg-json", required=True,
                        help="path to the daemon's serialized PipelineConfig "
                             "(config.to_json) — field-for-field fidelity, "
                             "no re-derivation drift")
    parser.add_argument("--journal-dir", default=None)
    parser.add_argument("--prediction-root", default=None)
    parser.add_argument("--stream-state", default=None,
                        help="SHARED per-chunk stream snapshot directory "
                             "(stream-session failover): every accumulated "
                             "chunk lands an atomic accumulator snapshot "
                             "here, and a respawned/neighbor worker resumes "
                             "the stream from it instead of stream_lost")
    parser.add_argument("--warm", default=None,
                        help="+-joined scene names to run end-to-end before "
                             "answering ready")
    parser.add_argument("--warm-baseline", default=None,
                        help="surface-baseline path for vocabulary warm-up")
    parser.add_argument("--no-freeze", action="store_true",
                        help="do not freeze the retrace sanitizer post-warm")
    parser.add_argument("--retrace-sanitizer", action="store_true")
    parser.add_argument("--fault-plan", default=None,
                        help="drill spec (the supervisor passes it to the "
                             "FIRST spawn only — a respawn is the recovery "
                             "under test, not the drill target)")
    parser.add_argument("--worker-id", type=int, default=0,
                        help="pool slice id (serve/pool.py); stamps logs "
                             "and the ready/bye digests so per-worker "
                             "evidence is attributable")
    parser.add_argument("--hb-interval", type=float, default=1.0)
    parser.add_argument("--telem-interval", type=float, default=2.0,
                        help="seconds between periodic telemetry relay "
                             "flushes (request boundaries flush too; "
                             "0 disables the relay)")
    parser.add_argument("--init_timeout", type=float, default=120.0)
    parser.add_argument("--debug", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(
        stream=sys.stderr,  # stdout is the pipe protocol, exclusively
        level=logging.DEBUG if args.debug else logging.INFO,
        format=f"%(asctime)s worker{args.worker_id}[%(process)d] "
               "%(levelname)s %(message)s")

    from maskclustering_tpu.config import config_from_json

    with open(args.cfg_json, "r", encoding="utf-8") as f:
        cfg = config_from_json(f.read())

    from maskclustering_tpu.analysis import retrace_sanitizer
    from maskclustering_tpu.utils import faults

    if args.retrace_sanitizer:
        retrace_sanitizer.arm(True)
    if retrace_sanitizer.enabled():
        retrace_sanitizer.install()
    if args.fault_plan:
        faults.set_plan(faults.FaultPlan.from_spec(args.fault_plan))
    faults.install_sigterm_handler()

    out_lock = threading.Lock()

    def emit_raw(doc: dict) -> None:
        with out_lock:
            sys.stdout.write(json.dumps(doc, sort_keys=True) + "\n")
            sys.stdout.flush()

    # the telemetry relay (obs/telemetry.py): spans + registry deltas ship
    # up the pipe so the parent's Serving report / windows / status op are
    # topology-invariant — nothing stays stranded in this process
    from maskclustering_tpu import obs
    from maskclustering_tpu.obs import flight
    from maskclustering_tpu.obs import telemetry

    relay = telemetry.ChildRelay() if args.telem_interval > 0 else None
    if relay is not None:
        obs.configure_sink(relay.sink)
    # one lock across collect+write: the hb thread and the device thread
    # both flush, and a collect drained by one thread must hit the pipe
    # before the other thread's (later) result line — otherwise a telem
    # line can land AFTER the result it accounts for and the parent's
    # fold-before-result ordering contract breaks
    telem_lock = threading.Lock()

    def flush_telem() -> None:
        if relay is None:
            return
        with telem_lock:
            try:
                doc = relay.collect()
            except Exception:  # noqa: BLE001 — telemetry never faults serving
                log.exception("worker: telemetry collect failed")
                return
            if doc is not None:
                emit_raw(doc)

    def emit(doc: dict) -> None:
        if doc.get("kind") in ("result", "reject"):
            # request boundary: ship this request's counters/spans BEFORE
            # its terminal line — the parent reader folds in pipe order,
            # so by the time any client sees the result, the parent's
            # registry/windows already account for it (no stale-status
            # race for a telemetry poll fired on the result)
            flush_telem()
        emit_raw(doc)

    # the heartbeat emitter: alive while the PROCESS is alive (a busy
    # device phase keeps beating; only a process-wide wedge — or the
    # wedge drill's hook below — silences it). The telemetry relay rides
    # the same thread at its own (coarser) cadence — a wedge silences
    # both, which is exactly the signal the parent watches for.
    hb_stop = threading.Event()

    # the flight-ring delta relay: if this process wedges and eats a
    # SIGKILL, the parent's retained copy of these rows is the only black
    # box left — the victim request's final spans included. The hb thread
    # ships on its cadence; the stdin loop also ships right after marking
    # a request received, so the victim's identity reaches the parent
    # BEFORE any crash that request can cause (a sub-interval crash must
    # not outrun the relay). The lock covers only the snapshot cursor
    # (never the pipe write — no blocking under a held lock); two racing
    # shippers may emit out of ring order, which the supervisor undoes by
    # sorting retained rows on their ``seq`` at dump time.
    flight_lock = threading.Lock()
    flight_seq = [0]

    def ship_flight() -> None:
        with flight_lock:
            rows, flight_seq[0] = flight.recorder().snapshot(flight_seq[0])
        if rows:
            emit_raw({"kind": flight.KIND_DELTA, "pid": os.getpid(),
                      "rows": rows})

    def hb_loop() -> None:
        last_telem = time.monotonic()
        while not hb_stop.wait(max(args.hb_interval, 0.05)):
            emit_raw({"kind": "hb"})
            ship_flight()
            if relay is not None and \
                    time.monotonic() - last_telem >= args.telem_interval:
                last_telem = time.monotonic()
                flush_telem()

    faults.set_wedge_hook(hb_stop.set)

    from maskclustering_tpu.run import init_backend_or_die

    init_backend_or_die(args.init_timeout,
                        platform="cpu" if cfg.backend == "cpu" else None)

    from maskclustering_tpu.utils import aot_cache
    from maskclustering_tpu.utils.compile_cache import setup_compilation_cache

    setup_compilation_cache(cfg.compilation_cache_dir)
    t0 = time.monotonic()
    aot_stats = aot_cache.warm_start(cfg)

    from maskclustering_tpu.serve import protocol
    from maskclustering_tpu.serve.admission import AdmissionQueue
    from maskclustering_tpu.serve.router import Router
    from maskclustering_tpu.serve.worker import ServeWorker

    router = Router(cfg, baseline_path=args.warm_baseline)
    # the supervisor serializes dispatch units; 2 = margin, and a batch
    # envelope lands all its members at once so the packing worker can
    # re-fuse them (capacity must hold a full batch plus margin).
    # metered=False: this queue is pipe plumbing — the PARENT's queue is
    # the admission layer, and this one's counters must not relay up as
    # doubled admission accounting
    queue = AdmissionQueue(
        capacity=max(2, int(getattr(cfg, "serve_batch_max", 1)) + 1),
        metered=False)
    worker = ServeWorker(cfg, queue, router,
                         journal_dir=args.journal_dir,
                         prediction_root=args.prediction_root,
                         stream_state_dir=args.stream_state)

    # warm-up mirrors the daemon's _prewarm: drills are suspended so they
    # land on the serving path, then (armed runs) the sanitizer freezes —
    # every post-warm compile in THIS process is a violation
    drill = faults.active_plan()
    faults.set_plan(None)
    try:
        for name, tensors in router.warmup_workload():
            worker.warm_tensors(name, tensors)
            # the width-S fused executable is a distinct program from the
            # width-1 warm — compile it pre-freeze or the first packed
            # batch books a post-warm violation
            worker.warm_batch_executable(name, tensors)
        warm = [s for s in (args.warm or "").split("+") if s]
        if warm:
            from maskclustering_tpu.run import cluster_scenes

            for st in cluster_scenes(cfg, warm, resume=False):
                log.info("worker: warm scene %s -> %s", st.seq_name,
                         st.status)
            if int(getattr(cfg, "serve_batch_max", 1) or 1) > 1:
                # classify warm scenes + pay their width-S fused compile,
                # mirroring daemon._warm_batch_from_disk
                from maskclustering_tpu.datasets import get_dataset

                for name in warm:
                    try:
                        ds = get_dataset(cfg.dataset, name,
                                         data_root=cfg.data_root)
                        tensors = ds.load_scene_tensors(cfg.step)
                    except Exception:
                        log.exception("worker: batch warm skipped for %s",
                                      name)
                        continue
                    router.remember(name, router.classify_tensors(tensors))
                    worker.warm_batch_executable(name, tensors)
    finally:
        faults.set_plan(drill)
    if not args.no_freeze and retrace_sanitizer.enabled():
        retrace_sanitizer.freeze()
    warmup_s = time.monotonic() - t0

    worker.start()
    hb_thread = threading.Thread(target=hb_loop, daemon=True,
                                 name="worker-hb")  # mct-thread: abandon(bounded-joined at drain below; the spawn/join pair brackets the stdin loop)
    hb_thread.start()
    emit_raw({"kind": "ready", "pid": os.getpid(),
              "worker_id": args.worker_id,
              "warmup_s": round(warmup_s, 2), "aot": aot_stats,
              "retrace": _retrace_digest()})
    flush_telem()  # warm-up's counters (aot_cache.*, d2h.*) relay at once
    log.info("worker: ready (warm-up %.1fs, aot %s)", warmup_s, aot_stats)

    # the stdin loop: one request at a time from the supervisor; EOF or a
    # shutdown op drains (finish in flight, then bye)
    rc = 0
    for line in sys.stdin:
        if not line.strip():
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            log.error("worker: unreadable pipe line %r", line[:200])
            continue
        op = doc.get("op")
        if op == "shutdown":
            break
        if op == "canary":
            # mct-sentinel probe round (supervisor.run_canary): executes
            # on the worker thread at its next idle poll; blocking the
            # stdin loop here is safe — the supervisor serializes canary
            # rounds against forwarded requests, and queued lines just
            # buffer in the pipe until the round answers
            probes = worker.run_canary(
                timeout_s=max(cfg.watchdog_device_s, 60.0))
            emit_raw({"kind": "canary", "id": doc.get("id"),
                      "probes": probes})
            continue
        if op == "batch":
            # the supervisor's packing envelope (protocol.forward_batch):
            # all members land in the local queue in one stdin line, so
            # the worker's own next_batch sees them together and re-packs
            # the fused dispatch instead of draining one line at a time
            member_docs = [d for d in (doc.get("requests") or ())
                           if isinstance(d, dict)]
        else:
            if op not in protocol.SCENE_OPS:
                continue
            member_docs = [doc]
        for member in member_docs:
            req = protocol.build_request(member,
                                         str(member.get("id") or "r-local"))
            req.send = emit
            flight.record(flight.KIND_REQUEST, event="received",
                          request=req.id, scene=req.scene, op=req.op,
                          **({"tenant": req.tenant} if req.tenant else {}))
            ship_flight()  # victim identity must reach the parent pre-crash
            try:
                queue.submit(req)
            except Exception as e:  # noqa: BLE001 — answer, never die silently
                emit(protocol.result(req, "failed",
                                     error=f"worker admission: {e}",
                                     error_class="terminal"))
    drained = worker.stop(timeout_s=max(cfg.watchdog_device_s, 60.0) * 2)
    hb_stop.set()
    hb_thread.join(2.0)
    if not drained:
        log.error("worker: in-flight request outlived the drain budget")
        rc = 1
    if retrace_sanitizer.enabled():
        # book the sanitizer digest as retrace.* counters so the FINAL
        # telem flush relays them — the parent's Serving report reads
        # "compiles post-warm-up" off the same counters in both topologies
        retrace_sanitizer.emit_counters()
    flush_telem()
    ship_flight()  # final ring delta: the parent's copy ends complete
    emit_raw({"kind": "bye", "worker_id": args.worker_id,
              "retrace": _retrace_digest(),
              "counts": worker.stats()["counts"]})
    if faults.stop_requested():
        # cooperative drain path, NOT the signal handler (CONC.SIGNAL):
        # the black box of a SIGTERM'd worker survives its own exit
        flight.dump("sigterm")
    elif rc:
        flight.dump("drain_timeout")
    return 143 if faults.stop_requested() else rc


if __name__ == "__main__":
    raise SystemExit(main())
