"""mct-serve: the long-lived scene-serving daemon (L6 serving layer).

The batch orchestrator (``run.py``) walks a scene list and exits,
throwing away a warm compile cache that costs ~106 s to rebuild
(BENCH_r03). This package keeps the process — and therefore every jit
cache and the persistent XLA cache's deserialized executables — alive
across requests:

- ``protocol``  — line-delimited JSON request/response schema;
- ``admission`` — bounded queue, typed rejects, per-request deadlines;
- ``router``    — shape-bucket classification (one vocabulary with
  ``utils/compile_cache.scene_bucket`` and the retrace census) and
  serving-vocabulary warm-up from ``compile_surface_baseline.json``;
- ``worker``    — the single device-owning thread driving
  ``run.SceneSupervisor`` per request (per-request retry/degradation,
  journal, obs spans, ``serve.*`` metrics);
- ``daemon``    — socket front + lifecycle (SIGTERM drains in flight);
- ``supervisor``/``worker_main`` — the crash-contained topology
  (``--isolate-worker``): the device owner as a heartbeat-watchdogged
  SUBPROCESS with SIGKILL-on-wedge, bounded respawn and requeue, made
  instantly warm by the persistent AOT cache (``utils/aot_cache.py``);
- ``client``    — the one blocking client implementation every caller
  (load_gen, CI smoke, tests) shares.

Start one with ``python -m maskclustering_tpu.serve --config scannet
--socket /tmp/mct.sock``; drive it with ``scripts/load_gen.py``.
"""

from maskclustering_tpu.serve.admission import AdmissionQueue, QueueFullReject
from maskclustering_tpu.serve.client import ServeClient
from maskclustering_tpu.serve.daemon import ServeDaemon
from maskclustering_tpu.serve.protocol import (ProtocolError, SceneRequest,
                                               parse_line)
from maskclustering_tpu.serve.router import Router
from maskclustering_tpu.serve.worker import ServeWorker

__all__ = [
    "AdmissionQueue", "QueueFullReject", "ServeClient", "ServeDaemon",
    "ProtocolError", "SceneRequest", "parse_line", "Router", "ServeWorker",
]
