"""mct-serve worker core: one device-owning thread serving the queue.

The device is a single resource, so ONE worker thread drains the
admission queue and drives the batch pipeline's own execution stack per
request — ``run.SceneSupervisor`` (retry + degradation ladder, PR 5) over
the PR-3 executors — with serving-specific wiring around it:

- a **fresh supervisor per request**: the degradation ladder is
  per-request state, so a sick request degrades ITSELF to the rung that
  heals it while its neighbors keep the full configuration (and the
  retrace-sanitizer ladder context is restored to baseline between
  requests for the same reason);
- **deadline enforcement**: a request whose deadline expired while queued
  is answered with a typed ``deadline`` reject before any device work;
  a live deadline becomes the phase watchdog budget (min'd with the
  config's own ``watchdog_*_s``), so a stalled device phase raises
  ``DeviceStallError`` within the remaining budget — the ladder degrades
  and, while budget remains, the request retries; once the budget is
  gone ``should_continue`` stops the retry loop and the request answers
  ``deadline`` with its best-so-far attribution;
- a **per-request RunJournal** (``journal_dir/<request id>.jsonl``,
  rows stamped with the request id) so a daemon crash leaves per-request
  attribution on disk, exactly like a one-shot run's journal;
- **serve.* metrics + spans**: every request runs under a
  ``serve.request`` span (the Serving report's p50/p95 source) and books
  ``serve.requests_*`` counters; scene shape buckets newly compiled by a
  request are reported on its result (``buckets_new`` — a warm daemon
  answers 0) and fed to the router's warmth set.

Synthetic requests materialize on disk (ScanNet layout under the
daemon's data root) on first use and are ordinary disk scenes from then
on — journals, artifact resume and byte-for-byte parity with one-shot
``run.py`` all hold by construction.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from maskclustering_tpu import obs
from maskclustering_tpu.analysis.lock_sanitizer import mct_lock
from maskclustering_tpu.obs import telemetry
from maskclustering_tpu.serve import protocol
from maskclustering_tpu.serve.admission import AdmissionQueue
from maskclustering_tpu.serve.router import Router
from maskclustering_tpu.utils import faults

log = logging.getLogger("maskclustering_tpu")


def _send(req: protocol.SceneRequest, event: Dict) -> None:
    """Deliver one event to the request's client; never the failure source
    (a disconnected client must not take the worker down)."""
    if req.send is None:
        return
    try:
        req.send(event)
    except Exception:  # noqa: BLE001 — client gone; the journal still has it
        log.warning("serve: could not deliver %s for request %s "
                    "(client gone?)", event.get("kind"), req.id)


def ensure_synthetic_scene(cfg, name: str, params: Dict) -> None:
    """Materialize an inline-synthetic scene on disk (idempotent)."""
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    write_scannet_layout)

    processed = os.path.join(cfg.data_root, "scannet", "processed", name)
    if os.path.isdir(os.path.join(processed, "color")):
        return
    kw = dict(params)
    if "image_hw" in kw:
        kw["image_hw"] = tuple(kw["image_hw"])
    with obs.span("serve.materialize", scene=name):
        write_scannet_layout(make_scene(**kw), cfg.data_root, name)


def _scene_buckets() -> set:
    """The compile-cache's scene-kind shape buckets seen so far."""
    from maskclustering_tpu.utils.compile_cache import seen_scene_buckets

    return seen_scene_buckets()


class _StreamSession:
    """One live scan's worker-side state (worker-thread-only access).

    Holds the scene's host tensors (loaded once), the streaming
    accumulator and the frame cursor. Sessions are keyed by scene name in
    ``ServeWorker._streams`` — the scene name IS the stream identity
    (same contract as the scene-artifact paths: one producer per scene;
    two clients streaming the same scene interleave on one cursor) — and
    the single worker thread is the only reader/writer, so no lock is
    needed (mct-threads: the dict never escapes the worker thread).
    """

    def __init__(self, tensors, acc):
        self.tensors = tensors
        self.acc = acc
        self.last_used = time.monotonic()

    @property
    def done(self) -> bool:
        return self.acc.frames_done >= self.acc.total_frames


class ServeWorker:
    """The daemon's single execution thread (start/stop bounded)."""

    def __init__(self, cfg, queue: AdmissionQueue, router: Router, *,
                 journal_dir: Optional[str] = None,
                 prediction_root: Optional[str] = None,
                 stream_state_dir: Optional[str] = None,
                 poll_s: float = 0.25):
        self.cfg = cfg
        self.queue = queue
        self.router = router
        self.journal_dir = journal_dir
        # shared per-chunk accumulator snapshot directory (stream-session
        # failover): every accumulated chunk lands an atomic snapshot here
        # on the stream_journal_every cadence, and _open_stream resumes
        # from it — so a stream survives its worker's death (a surviving
        # pool slice or the respawned worker re-opens mid-scan) instead
        # of answering the typed stream_lost. None = sessions are
        # process-lifetime only (the pre-durability contract)
        self.stream_state_dir = stream_state_dir
        self.prediction_root = (prediction_root
                                or os.path.join(cfg.data_root, "prediction"))
        self.poll_s = poll_s
        self._stop = threading.Event()
        self._idle = threading.Event()  # set whenever no request is running
        self._idle.set()
        self._lock = mct_lock("serve.ServeWorker._lock")
        self._thread: Optional[threading.Thread] = None
        # bounded window (worker-thread appends only): a daemon that
        # serves for days must not grow per-request state without bound,
        # and stats() re-sorts the window per call — O(window), not
        # O(requests ever)
        self._latencies: Deque[float] = deque(maxlen=4096)
        self._counts = {"requests": 0, "ok": 0, "failed": 0, "deadline": 0,
                        "skipped": 0, "interrupted": 0}
        # live-scan streams (stream_chunk/stream_end ops), keyed by scene
        # name; worker-thread-only (see _StreamSession). Bounded: a
        # session pins the scene's host tensors AND the O(M^2) device
        # accumulator, so abandoned streams (client gone, no stream_end)
        # must not accumulate for the daemon's lifetime — past the cap
        # the least-recently-used session evicts (typed counter + log;
        # the evicted client's next op reopens from chunk 0)
        self._streams: Dict[str, _StreamSession] = {}
        self.max_stream_sessions = 4
        # mct-sentinel canary state: warm-up fitted tensors are retained
        # so canary probes replay the EXACT warm executables (no compile,
        # no host-side scene regeneration); the pending/done pair hands a
        # round to the device-owning worker thread at an idle poll
        self._warm_cache: List[Tuple[str, object]] = []
        self._canary_pending = threading.Event()
        self._canary_done = threading.Event()
        self._canary_probes: Optional[List[Dict]] = None
        # continuous scene batching (cfg.serve_batch_max > 1): the fused
        # dispatch mesh (lazy — single-chip daemons build a (1, 1) mesh)
        # and the occupancy histogram {batch width -> dispatches}, both
        # worker-thread-only
        self._mesh = None
        self._batch_hist: Dict[int, int] = {}

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(  # mct-thread: abandon(daemon-lifetime thread, bounded-joined in stop(); the spawn/join pair spans methods, which the scope-local check cannot see)
            target=self._run, daemon=True, name="serve-worker")
        self._thread.start()

    def stop(self, timeout_s: float = 60.0) -> bool:
        """Request stop and wait (bounded) for the in-flight request.

        The worker finishes the request it is currently executing — the
        SIGTERM drain contract — and exits; requests still queued are the
        daemon's to answer with ``draining`` rejects. Returns False when
        the in-flight request outlived the timeout (the daemon then exits
        anyway; the thread is a daemon thread and the per-request journal
        has the in-flight attempt on disk).
        """
        self._stop.set()
        t = self._thread
        if t is None:
            return True
        t.join(timeout_s)
        return not t.is_alive()

    def wait_idle(self, timeout_s: float) -> bool:
        """Block (bounded) until no request is executing AND the queue is
        empty — the warm-up/test synchronization point."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.queue.depth() == 0 and self._idle.is_set():
                return True
            time.sleep(0.01)
        return False

    # -- the thread main ----------------------------------------------------

    def _run(self) -> None:
        batch_max = max(int(self.cfg.serve_batch_max), 1)
        while not self._stop.is_set():
            if batch_max > 1:
                batch = self.queue.next_batch(
                    self._batch_key, max_n=batch_max,
                    linger_s=self.cfg.serve_batch_linger_s,
                    timeout_s=self.poll_s)
            else:
                head = self.queue.next(timeout_s=self.poll_s)
                batch = None if head is None else [head]
            if batch is None:
                if self._canary_pending.is_set():
                    # idle poll: run the requested canary round HERE, on
                    # the device-owning thread — canaries never race a
                    # request for the device
                    self._canary_pending.clear()
                    self._idle.clear()
                    try:
                        self._canary_probes = self._canary_round()
                    except Exception:  # noqa: BLE001 — a canary must not kill serving
                        log.exception("serve: canary round failed")
                        self._canary_probes = None
                    finally:
                        self._idle.set()
                        self._canary_done.set()
                continue
            if self._stop.is_set():
                # stop landed while we were blocked in the pop: these
                # requests were promised a draining reject, not execution —
                # hand them back for the daemon's drain (or answer the
                # reject ourselves if a racing submit refilled the slot)
                for req in batch:
                    if not self.queue.requeue(req):
                        obs.count("serve.admission.rejects.draining")
                        _send(req, protocol.reject(
                            "draining", req=req,
                            detail="daemon shutting down before dispatch"))
                break
            self._idle.clear()
            try:
                if len(batch) == 1:
                    if batch_max > 1 and batch[0].op == "scene":
                        # solo scene dispatch under the packing scheduler:
                        # a width-1 histogram entry, so `occupancy` means
                        # requests-per-dispatch over ALL scene dispatches
                        # (not just the fused ones, which are >= 2 by
                        # construction)
                        obs.count("serve.batch.dispatches")
                        obs.count("serve.batch.packed_requests")
                        self._batch_hist[1] = self._batch_hist.get(1, 0) + 1
                    self._serve_one(batch[0])
                else:
                    self._serve_batch(batch)
            except Exception:  # noqa: BLE001 — one batch, not the daemon
                log.exception("serve: request(s) %s crashed the worker "
                              "loop", [r.id for r in batch])
                for req in batch:
                    _send(req, protocol.result(req, "failed",
                                               error="internal worker error",
                                               error_class="terminal"))
            finally:
                self._idle.set()

    # -- per-request execution ---------------------------------------------

    def _deadline_cfg(self, req: protocol.SceneRequest):
        """The request's config: deadline folded into the phase watchdogs."""
        if math.isinf(req.deadline_at):
            return self.cfg
        remaining = req.remaining_s()
        overrides = {}
        for field in ("watchdog_load_s", "watchdog_device_s",
                      "watchdog_host_s"):
            cur = getattr(self.cfg, field)
            overrides[field] = min(cur, remaining) if cur > 0 else remaining
        return self.cfg.replace(**overrides)

    def _journal(self, req: protocol.SceneRequest):
        if not self.journal_dir:
            return None
        os.makedirs(self.journal_dir, exist_ok=True)
        path = os.path.join(self.journal_dir, f"{req.id}.jsonl")
        return faults.RunJournal(path, self.cfg.config_name,
                                 request_id=req.id)

    def _finish_request(self, req: protocol.SceneRequest, status_: str,
                        latency: float, *, telemetry_bucket=None,
                        **fields) -> None:
        """The one request tail — latency window, ``serve.requests_*``
        counter, locked counts, telemetry row, terminal result emit —
        shared by the classic scene op and the stream ops so request
        accounting cannot drift between the two paths."""
        self._latencies.append(latency)
        obs.count(f"serve.requests_{status_}")
        with self._lock:
            self._counts[status_] = self._counts.get(status_, 0) + 1
        telemetry.record_request(
            telemetry_bucket if telemetry_bucket is not None
            else self.router.bucket_for(req.scene), latency,
            tenant=req.tenant, status=status_)
        _send(req, protocol.result(req, status_,
                                   seconds=round(latency, 4), **fields))

    def _book_arrival(self, req: protocol.SceneRequest) -> bool:
        """Per-request arrival bookkeeping (request count, queue wait,
        deadline cutoff) — shared by the solo and the packed paths so
        admission accounting cannot drift between them. False when the
        request was answered with a typed ``deadline`` reject."""
        obs.count("serve.requests")
        with self._lock:
            self._counts["requests"] += 1
        # ack->dequeue wait: the telemetry window's queue_wait histogram
        # and the trace CLI's queue-wait segment (no-op without a daemon
        # aggregator — e.g. inside the isolated worker subprocess, where
        # the PARENT supervisor measured the real wait already)
        telemetry.record_queue_wait(
            req, max(time.monotonic() - req.admitted_at, 0.0))
        if req.expired():
            # admitted in time, dequeued too late: a typed answer beats
            # burning device time on a result nobody is waiting for
            obs.count("serve.rejects.deadline")
            telemetry.record_reject(req.tenant)
            with self._lock:
                self._counts["deadline"] += 1
            _send(req, protocol.reject(
                "deadline", req=req,
                detail=f"deadline_s={req.deadline_s:g} expired after "
                       f"{time.monotonic() - req.admitted_at:.2f}s in queue"))
            return False
        return True

    def _serve_one(self, req: protocol.SceneRequest) -> None:
        if not self._book_arrival(req):
            return
        if req.op in ("stream_chunk", "stream_end"):
            self._serve_stream(req)
            return
        self._serve_scene(req)

    def _serve_scene(self, req: protocol.SceneRequest) -> None:
        from maskclustering_tpu.run import SceneSupervisor

        t0 = time.monotonic()
        bucket = None
        if req.synthetic is not None:
            try:
                ensure_synthetic_scene(self.cfg, req.scene, req.synthetic)
                bucket = self.router.bucket_for(req.scene)
                if bucket is None:
                    # first sight of this scene: generate once to
                    # classify, then the router remembers — repeats must
                    # not pay a host-side scene regeneration per request
                    from maskclustering_tpu.utils.synthetic import (
                        make_scene, to_scene_tensors)

                    kw = dict(req.synthetic)
                    if "image_hw" in kw:
                        kw["image_hw"] = tuple(kw["image_hw"])
                    bucket = self.router.classify_tensors(
                        to_scene_tensors(make_scene(**kw)))
                    self.router.remember(req.scene, bucket)
            except Exception as e:  # noqa: BLE001 — answer, don't crash
                log.exception("serve: synthetic materialization failed "
                              "for %s", req.id)
                obs.count("serve.requests_failed")
                with self._lock:
                    self._counts["failed"] += 1
                _send(req, protocol.result(
                    req, "failed", error=f"synthetic materialization: {e}",
                    error_class=faults.classify_error(e)))
                return
        _send(req, protocol.status(
            req, "running", scene=req.scene,
            **({"bucket": list(bucket),
                "warm": self.router.is_warm(bucket)}
               if bucket is not None else {})))

        def on_event(kind: str, **info) -> None:
            state = {"retry": "retrying", "degrade": "degraded"}.get(kind)
            if state:
                _send(req, protocol.status(req, state, **info))

        journal = self._journal(req)
        buckets_before = _scene_buckets()
        try:
            supervisor = SceneSupervisor(
                self._deadline_cfg(req), resume=req.resume, journal=journal,
                on_event=on_event,
                should_continue=lambda: not req.expired(),
                # a request that crashed its previous worker(s) re-runs
                # pre-degraded: the full configuration already proved
                # fatal once (serve/supervisor.py stamps req.crashes)
                initial_rungs=req.crashes)
            if journal is not None:
                journal.begin_run()
            with obs.span("serve.request", request=req.id, scene=req.scene):
                statuses = supervisor.run([req.scene])
        finally:
            if journal is not None:
                journal.end_run(interrupted=faults.stop_requested())
                journal.close()
            from maskclustering_tpu.analysis import retrace_sanitizer

            if retrace_sanitizer.enabled():
                # the ladder context is per-request: restore baseline so a
                # degraded request cannot mislabel its neighbors' compiles
                retrace_sanitizer.set_context("baseline")
        new_buckets = _scene_buckets() - buckets_before
        for b in new_buckets:
            self.router.note_served(b)
        if bucket is not None:
            self.router.note_served(bucket)
        latency = time.monotonic() - t0

        st = statuses[0] if statuses else None
        if st is None:
            status_ = "failed"
            fields: Dict = {"error": "supervisor returned no status",
                            "error_class": "terminal"}
        else:
            status_ = st.status
            if st.status == "failed" and req.expired():
                status_ = "deadline"
            fields = {"scene_seconds": round(st.seconds, 4),
                      "attempts": st.attempts, "rung": st.degradation_rung,
                      "num_objects": st.num_objects}
            if getattr(st, "digest", None):
                # per-request invariant digest: the pack-vs-sequential
                # identity gate compares this against the fused path's
                # per-lane digest (artifact fingerprint is universal)
                fields["digest"] = st.digest
                fields["digest_coord"] = getattr(st, "digest_coord", "")
            if st.error:
                fields["error"] = str(st.error).strip().splitlines()[-1][:200]
                fields["error_class"] = st.error_class
        if new_buckets:
            obs.count("serve.buckets_cold", len(new_buckets))
        self._finish_request(
            req, status_, latency, telemetry_bucket=bucket,
            buckets_new=len(new_buckets),
            **({"bucket": list(bucket)} if bucket is not None else {}),
            **fields)

    # -- continuous scene batching (cfg.serve_batch_max > 1) ----------------

    def _run_mesh(self):
        """The fused dispatch mesh: cfg.mesh_shape when set, else a
        single-device (1, 1) mesh (scene lanes still batch — they stack
        on the scene dim and shard 1-wide)."""
        if self._mesh is None:
            import jax

            from maskclustering_tpu.parallel.batch import make_run_mesh
            from maskclustering_tpu.parallel.mesh import make_mesh

            # the fallback pins ONE device explicitly: make_mesh must
            # cover every device it is handed, and multi-device hosts
            # (8-core TPU, forced-multi-CPU tests) would reject (1, 1)
            self._mesh = (make_run_mesh(self.cfg) if self.cfg.mesh_shape
                          else make_mesh((1, 1),
                                         devices=jax.devices()[:1]))
        return self._mesh

    def _batch_key(self, req: protocol.SceneRequest) -> Optional[tuple]:
        """The packing scheduler's grouping key: the request's shape
        bucket, or None for requests that must dispatch solo.

        Solo (None): stream ops (one bucket per stream stays the rule),
        resume requests (artifact-exists short-circuit is a sequential-
        path contract), crash-requeued requests (they re-run pre-degraded
        on their own ladder), scenes the router has not classified yet
        (first sight classifies on the sequential path, repeats batch),
        and scenes with a pending FaultPlan entry — the sequential
        ladder owns fault handling, so a scripted fault fails or retries
        ONLY its own request while batchmates pack normally.
        """
        if req.op != "scene" or req.resume or req.crashes:
            return None
        bucket = self.router.bucket_for(req.scene)
        if bucket is None:
            return None
        plan = faults.active_plan()
        if plan is not None and any(
                e.scene == req.scene
                and (e.remaining is None or e.remaining > 0)
                for e in plan.entries):
            return None
        return bucket

    def _serve_batch(self, batch: List[protocol.SceneRequest]) -> None:
        """One fused scene-axis dispatch for up to S same-bucket requests.

        Members are padded to exactly ``cfg.serve_batch_max`` lanes with
        the router's warm pad tensors, so every occupancy >= 2 replays the
        one full-width warm executable (solo requests take the sequential
        path — the batch-width vocabulary is {1, S}). Results demux
        per-lane: each member gets its own export, artifact digest,
        journal rows and telemetry booking, byte-identical to sequential
        execution; pad lanes book nothing anywhere. Any dispatch-level
        failure falls the whole batch back to the sequential path, where
        each member's own retry/degradation ladder takes over.
        """
        from maskclustering_tpu.datasets import get_dataset
        from maskclustering_tpu.models.postprocess import export_artifacts
        from maskclustering_tpu.obs import digest as sentinel
        from maskclustering_tpu.parallel.batch import cluster_scene_batch
        from maskclustering_tpu.parallel.mesh import mesh_label

        members = [r for r in batch if self._book_arrival(r)]
        if not members:
            return
        if len(members) == 1:
            self._serve_scene(members[0])
            return
        # pure classification, NOT _batch_key: the solo-routing policy
        # (fault plans, resume, crashes) belongs to the scheduler that
        # built this batch — by the time a batch reaches the dispatcher
        # its members pack, and scripted faults land per-lane below
        bucket = self.router.bucket_for(members[0].scene)
        if bucket is None:
            for req in members:
                self._serve_scene(req)
            return
        k_max, f_b, n_b = bucket
        t0 = time.monotonic()
        loaded: List[tuple] = []  # (req, dataset, tensors)
        for req in members:
            try:
                if req.synthetic is not None:
                    ensure_synthetic_scene(self.cfg, req.scene, req.synthetic)
                ds = get_dataset(self.cfg.dataset, req.scene,
                                 data_root=self.cfg.data_root)
                tensors = faults.call_with_deadline(
                    lambda ds=ds: ds.load_scene_tensors(self.cfg.step),
                    self.cfg.watchdog_load_s, seam="load", scene=req.scene)
                if self.router.classify_tensors(tensors) != bucket:
                    # the remembered bucket went stale (scene bytes
                    # changed on disk): serve it solo rather than force
                    # it into the wrong executable
                    self.router.remember(
                        req.scene, self.router.classify_tensors(tensors))
                    self._serve_scene(req)
                    continue
                loaded.append((req, ds, tensors))
            except Exception as e:  # noqa: BLE001 — one member, not the batch
                log.exception("serve: batch member %s failed to load",
                              req.id)
                self._finish_request(
                    req, "failed", time.monotonic() - t0,
                    telemetry_bucket=bucket,
                    error=f"scene load: {e}"[:200],
                    error_class=faults.classify_error(e))
        if not loaded:
            return
        if len(loaded) == 1:
            self._serve_scene(loaded[0][0])
            return

        width = max(int(self.cfg.serve_batch_max), len(loaded))
        pad_tensors = self.router.pad_tensors_for(bucket)
        if pad_tensors is None:
            # no warm pad retained yet (first batch of a bucket warmed
            # by real traffic): the first member's tensors pad — same
            # executable shape, pad lanes still discarded
            pad_tensors = loaded[0][2]
            self.router.remember_pad_tensors(bucket, pad_tensors)
        for req, _, _ in loaded:
            _send(req, protocol.status(
                req, "running", scene=req.scene, bucket=list(bucket),
                warm=self.router.is_warm(bucket), batch=len(loaded)))

        budget = self.cfg.watchdog_device_s
        rems = [r.remaining_s() for r, _, _ in loaded
                if not math.isinf(r.deadline_at)]
        if rems:
            tightest = max(min(rems), 0.01)
            budget = min(budget, tightest) if budget > 0 else tightest
        buckets_before = _scene_buckets()
        try:
            objects_list = faults.call_with_deadline(
                lambda: cluster_scene_batch(
                    self.cfg, self._run_mesh(),
                    [t for _, _, t in loaded], k_max=k_max,
                    seq_names=[r.scene for r, _, _ in loaded],
                    pads=(f_b, n_b), width=width, pad_tensors=pad_tensors),
                budget, seam="device",
                scene=",".join(r.scene for r, _, _ in loaded))
        except Exception:  # noqa: BLE001 — fall back, don't fail the batch
            log.exception(
                "serve: fused batch %s failed; falling back to the "
                "sequential path", [r.id for r, _, _ in loaded])
            obs.count("serve.batch.fallbacks")
            for req, _, _ in loaded:
                self._serve_scene(req)
            return
        wall = time.monotonic() - t0
        new_buckets = _scene_buckets() - buckets_before
        for b in new_buckets:
            self.router.note_served(b)
        self.router.note_served(bucket)
        k = len(loaded)
        per_scene = wall / k
        obs.count("serve.batch.dispatches")
        obs.count("serve.batch.packed_requests", k)
        if width > k:
            obs.count("serve.batch.pad_lanes", width - k)
        self._batch_hist[k] = self._batch_hist.get(k, 0) + 1

        mesh_lab = (mesh_label(self.cfg.mesh_shape) if self.cfg.mesh_shape
                    else "single")
        for (req, ds, _), objects in zip(loaded, objects_list):
            journal = self._journal(req)
            if journal is not None:
                journal.begin_run()
                journal.attempt(req.scene, 1, 0)
            try:
                faults.inject("export", req.scene)
                export_artifacts(
                    objects, req.scene, self.cfg.config_name,
                    ds.object_dict_dir, prediction_root=self.prediction_root,
                    top_k_repre=self.cfg.num_representative_masks)
                # per-LANE invariant digest (never per-dispatch): the
                # fused path materializes no DeviceHandoff, so the
                # universal artifact fingerprint carries the identity
                dg = sentinel.artifact_only_digest(
                    objects, bucket="fused",
                    count_dtype=self.cfg.count_dtype)
                coord = sentinel.digest_coord(dg, mesh=mesh_lab)
                if journal is not None:
                    journal.outcome(
                        req.scene, "ok", attempt=1, rung=0,
                        seconds=per_scene,
                        num_objects=len(objects.point_ids_list))
                obs.record_span("serve.request", wall, request=req.id,
                                scene=req.scene, batch=k)
                self._finish_request(
                    req, "ok", wall, telemetry_bucket=bucket,
                    bucket=list(bucket), batch=k,
                    scene_seconds=round(per_scene, 4), attempts=1, rung=0,
                    num_objects=len(objects.point_ids_list),
                    buckets_new=len(new_buckets),
                    digest=dg, digest_coord=coord)
            except Exception as e:  # noqa: BLE001 — one lane, not the batch
                log.exception("serve: batch member %s export failed",
                              req.id)
                if journal is not None:
                    journal.outcome(req.scene, "failed", attempt=1, rung=0,
                                    error_class=faults.classify_error(e),
                                    error=str(e)[:200], seconds=per_scene)
                self._finish_request(
                    req, "failed", wall, telemetry_bucket=bucket,
                    batch=k, error=str(e).strip().splitlines()[-1][:200],
                    error_class=faults.classify_error(e))
            finally:
                if journal is not None:
                    journal.end_run()
                    journal.close()

    # -- live-scan streaming (stream_chunk / stream_end ops) ----------------

    def _open_stream(self, req: protocol.SceneRequest) -> _StreamSession:
        """Create the scene's stream session: tensors loaded ONCE, the
        accumulator sized for the whole scan."""
        from maskclustering_tpu.datasets import get_dataset
        from maskclustering_tpu.models.pipeline import bucket_k_max
        from maskclustering_tpu.models.streaming import StreamAccumulator
        from maskclustering_tpu.utils.compile_cache import max_seg_id

        if req.synthetic is not None:
            ensure_synthetic_scene(self.cfg, req.scene, req.synthetic)
        ds = get_dataset(self.cfg.dataset, req.scene,
                         data_root=self.cfg.data_root)
        tensors = ds.load_scene_tensors(self.cfg.step)
        chunk = int(req.chunk) or self.cfg.streaming_chunk or 8
        cfg = (self.cfg if self.cfg.streaming_chunk == chunk
               else self.cfg.replace(streaming_chunk=chunk))
        acc = StreamAccumulator(
            cfg, total_frames=tensors.num_frames,
            num_points=tensors.num_points,
            k_max=bucket_k_max(max_seg_id(tensors.segmentations)),
            seq_name=req.scene)
        state_path = self._stream_state_path(req.scene)
        if state_path and acc.load_state(state_path):
            # a previous worker's snapshot exists and its coordinates
            # match: resume mid-scan instead of restarting at chunk 0 —
            # the failover contract (the cursor self-derives from the
            # restored chunks_done)
            obs.count("serve.streams_resumed")
            log.warning("serve: stream %r resumed from snapshot at chunk "
                        "%d (%d/%d frames)", req.scene, acc.chunks_done,
                        acc.frames_done, acc.total_frames)
        while len(self._streams) >= self.max_stream_sessions:
            victim = min(self._streams, key=lambda s:
                         self._streams[s].last_used)
            log.warning("serve: evicting idle stream session %r "
                        "(cap %d; its client must restart the scan)",
                        victim, self.max_stream_sessions)
            obs.count("serve.streams_evicted")
            del self._streams[victim]
        return _StreamSession(tensors, acc)

    def _stream_state_path(self, scene: str) -> Optional[str]:
        """The scene's shared snapshot path (None = failover disarmed)."""
        if not self.stream_state_dir:
            return None
        from maskclustering_tpu.models.streaming import stream_state_path

        os.makedirs(self.stream_state_dir, exist_ok=True)
        return stream_state_path(self.stream_state_dir, scene)

    def _serve_stream(self, req: protocol.SceneRequest) -> None:
        """One stream op: accumulate the scene's next chunk, or finalize.

        Each op is one admitted request (ack -> status -> result), so
        streams interleave fairly with classic scene requests on the one
        device-owning thread. The result's ``partial_instances`` /
        ``done`` fields are the live-scan anytime contract; a failed
        chunk answers a typed ``failed`` result with the accumulator
        intact, so the client can simply resend the op.
        """
        from maskclustering_tpu.models.streaming import slice_scene_frames

        t0 = time.monotonic()
        status_ = "ok"
        fields: Dict = {}
        try:
            if req.op == "stream_end":
                sess = self._streams.get(req.scene)
                if sess is None or sess.acc.chunks_done == 0:
                    raise ValueError(
                        f"no live stream for scene {req.scene!r} "
                        f"(send stream_chunk first)")
                sess.last_used = time.monotonic()
                _send(req, protocol.status(
                    req, "running", scene=req.scene, stream="end"))
                from maskclustering_tpu.datasets import get_dataset

                ds = get_dataset(self.cfg.dataset, req.scene,
                                 data_root=self.cfg.data_root)
                with obs.span("serve.request", request=req.id,
                              scene=req.scene, stream="end"):
                    result = sess.acc.finalize(
                        export=True, object_dict_dir=ds.object_dict_dir,
                        prediction_root=self.prediction_root)
                # only a SUCCESSFUL finalize consumes the session: a
                # failed export/finalize keeps the accumulated stream so
                # the client can simply resend stream_end
                self._streams.pop(req.scene, None)
                state_path = self._stream_state_path(req.scene)
                if state_path and os.path.exists(state_path):
                    # the stream is settled — its snapshot must not
                    # resurrect a finished scan on the next open
                    try:
                        os.remove(state_path)
                    except OSError:
                        pass
                fields = {"num_objects": len(result.objects.point_ids_list),
                          "frames": sess.acc.frames_done,
                          "chunks": sess.acc.chunks_done}
                obs.count("serve.stream_ends")
            else:
                sess = self._streams.get(req.scene)
                if sess is None:
                    sess = self._open_stream(req)
                    self._streams[req.scene] = sess
                    obs.count("serve.streams_opened")
                sess.last_used = time.monotonic()
                acc = sess.acc
                if sess.done:
                    if req.crashes:
                        # crash-requeued chunk whose push was already
                        # absorbed before the worker died (the snapshot
                        # includes it): answer the anytime fields instead
                        # of double-pushing or failing the replay
                        obs.count("serve.stream_chunks_rerun")
                        fields = {"chunk": max(acc.chunks_done - 1, 0),
                                  "frames_done": acc.frames_done,
                                  "total_frames": acc.total_frames,
                                  "partial_instances": acc.partial_instances,
                                  "done": True}
                        self._finish_request(
                            req, "ok", time.monotonic() - t0,
                            op=req.op, **fields)
                        return
                    raise ValueError(
                        f"stream {req.scene!r} already consumed all "
                        f"{acc.total_frames} frames (send stream_end)")
                if req.crashes:
                    # the chunk in flight when the previous worker died,
                    # replayed against the resumed accumulator
                    obs.count("serve.stream_chunks_rerun")
                _send(req, protocol.status(
                    req, "running", scene=req.scene,
                    stream="chunk", chunk_index=acc.chunks_done))
                start = acc.chunks_done * acc.chunk_frames
                stop = min(start + acc.chunk_frames,
                           sess.tensors.num_frames)
                with obs.span("serve.request", request=req.id,
                              scene=req.scene, stream="chunk"):
                    # the request deadline folds into the chunk watchdog
                    # exactly like the classic scene op (min of the
                    # config budget and the remaining deadline)
                    digest = faults.call_with_deadline(
                        lambda: acc.push_chunk(
                            slice_scene_frames(sess.tensors, start, stop)),
                        self._deadline_cfg(req).watchdog_device_s,
                        seam="device", scene=req.scene)
                state_path = self._stream_state_path(req.scene)
                if state_path and self.cfg.stream_journal_every > 0 and (
                        digest["done"] or acc.chunks_done
                        % self.cfg.stream_journal_every == 0):
                    # ship the accumulator snapshot to the SHARED state
                    # dir (atomic tmp+rename in save_state): the failover
                    # plane a surviving slice resumes from. The final
                    # chunk always snapshots — stream_end is a separate
                    # request and the worker may die in between
                    acc.save_state(state_path)
                # the per-chunk anytime signal: partial-instance count on
                # a status event BEFORE the terminal result (live
                # dashboards and the client's streaming helper read it)
                _send(req, protocol.status(
                    req, "chunk_done", scene=req.scene,
                    chunk_index=digest["chunk"],
                    frames_done=digest["frames_done"],
                    total_frames=digest["total_frames"],
                    partial_instances=digest["partial_instances"]))
                fields = {k: digest[k]
                          for k in ("chunk", "frames_done", "total_frames",
                                    "partial_instances", "done")}
                obs.count("serve.stream_chunks")
        except Exception as e:  # noqa: BLE001 — one op, not the daemon
            log.exception("serve: stream op %s failed for %s",
                          req.op, req.id)
            status_ = "failed"
            msg = str(e).strip()
            fields = {"error": (msg.splitlines()[-1] if msg
                                else type(e).__name__)[:200],
                      "error_class": faults.classify_error(e)}
        if status_ == "failed" and req.expired():
            # same reclassification as the classic scene op: a failure
            # past the request's deadline answers as the deadline's
            status_ = "deadline"
        self._finish_request(req, status_, time.monotonic() - t0,
                             op=req.op, **fields)

    # -- warm-up ------------------------------------------------------------

    def warm_tensors(self, name: str, tensors) -> bool:
        """Run one warm-up scene through the serving path (no export).

        Best-effort: a failed warm-up logs and returns False — the daemon
        still serves, it just pays that bucket's compiles on the first
        real request.
        """
        from maskclustering_tpu.models.pipeline import (run_scene_device,
                                                        run_scene_host)

        bucket = self.router.classify_tensors(tensors)
        try:
            with obs.span("serve.warmup", scene=name):
                handoff = run_scene_device(tensors, self.cfg, seq_name=name)
                run_scene_host(handoff, self.cfg, export=False)
        except Exception:  # noqa: BLE001 — warm-up must not kill startup
            log.exception("serve: warm-up scene %s (bucket %s) failed",
                          name, bucket)
            return False
        self.router.note_served(bucket)
        obs.count("serve.warmup_scenes")
        # the packing scheduler's pad-lane source: partial batches pad to
        # full width with THIS bucket's warm synthetic tensors
        self.router.remember_pad_tensors(bucket, tensors)
        # sentinel: retain the fitted tensors — canary probes replay them
        # byte-for-byte through the warm executables (never compiling,
        # never regenerating scenes host-side)
        if all(n != name for n, _ in self._warm_cache):
            self._warm_cache.append((name, tensors))
        return True

    def warm_batch_executable(self, name: str, tensors) -> bool:
        """Warm the FULL-WIDTH fused executable for the scene's bucket.

        One width-S dispatch per warm bucket (real lane = the warm scene,
        pad lanes = the same tensors) so every packed batch — including
        partial ones, which pad to exactly S — replays a warm executable:
        zero post-warm compiles at any occupancy. No-op when batching is
        off; best-effort like ``warm_tensors``.
        """
        if int(self.cfg.serve_batch_max) <= 1:
            return False
        from maskclustering_tpu.parallel.batch import cluster_scene_batch

        bucket = self.router.classify_tensors(tensors)
        width = int(self.cfg.serve_batch_max)
        try:
            with obs.span("serve.warmup_batch", scene=name, width=width):
                cluster_scene_batch(
                    self.cfg, self._run_mesh(), [tensors],
                    k_max=bucket[0], seq_names=[name],
                    pads=(bucket[1], bucket[2]), width=width,
                    pad_tensors=tensors)
        except Exception:  # noqa: BLE001 — warm-up must not kill startup
            log.exception("serve: fused-batch warm-up %s (bucket %s, "
                          "width %d) failed", name, bucket, width)
            return False
        self.router.remember_pad_tensors(bucket, tensors)
        obs.count("serve.warmup_batches")
        return True

    # -- mct-sentinel canary probes -----------------------------------------

    def run_canary(self, timeout_s: float = 120.0) -> Optional[List[Dict]]:
        """Execute one canary round; returns per-scene probe digests.

        On a running worker the round is handed to the device-owning
        thread (it picks it up at an idle queue poll, so a canary never
        races a request for the device); without a running thread (goldens
        generation, tests) it executes inline. Returns None on timeout.

        Canary traffic is fenced BY CONSTRUCTION: it never enters the
        admission queue, the latency window, ``serve.requests_*`` counts,
        tenant accounting or the request journal — it books only
        ``canary.*`` counters and spans.
        """
        if self._thread is None or not self._thread.is_alive():
            return self._canary_round()
        self._canary_done.clear()
        self._canary_probes = None
        self._canary_pending.set()
        if not self._canary_done.wait(timeout_s):
            self._canary_pending.clear()
            log.warning("serve: canary round timed out after %.1fs "
                        "(worker busy)", timeout_s)
            return None
        return self._canary_probes

    def _canary_round(self) -> List[Dict]:
        from maskclustering_tpu.models.pipeline import (run_scene_device,
                                                        run_scene_host)
        from maskclustering_tpu.obs import digest as sentinel

        probes: List[Dict] = []
        for name, tensors in list(self._warm_cache):
            t0 = time.monotonic()
            with obs.span("serve.canary", scene=name):
                handoff = run_scene_device(tensors, self.cfg, seq_name=name)
                result = run_scene_host(handoff, self.cfg, export=False)
            obs.count("canary.probes")
            probes.append({
                "scene": name,
                "coord": sentinel.digest_coord(result.digest),
                "digest": result.digest,
                "seconds": round(time.monotonic() - t0, 4),
            })
        return probes

    # -- introspection ------------------------------------------------------

    def latency_quantiles(self) -> Dict[str, Optional[float]]:
        from maskclustering_tpu.obs.report import percentile

        vals = sorted(self._latencies)
        if not vals:
            return {"p50_s": None, "p95_s": None, "count": 0}
        return {"p50_s": round(percentile(vals, 50), 4),
                "p95_s": round(percentile(vals, 95), 4),
                "count": len(vals)}

    def batch_stats(self) -> Optional[Dict]:
        """Occupancy view of the packing scheduler (None when off):
        dispatches, packed requests, mean occupancy, width histogram."""
        if int(self.cfg.serve_batch_max) <= 1:
            return None
        hist = dict(self._batch_hist)
        dispatches = sum(hist.values())
        packed = sum(k * n for k, n in hist.items())
        return {"max": int(self.cfg.serve_batch_max),
                "linger_s": float(self.cfg.serve_batch_linger_s),
                "dispatches": dispatches,
                "packed_requests": packed,
                "occupancy": (round(packed / dispatches, 3)
                              if dispatches else None),
                "hist": {str(k): hist[k] for k in sorted(hist)}}

    def stats(self) -> Dict:
        with self._lock:
            counts = dict(self._counts)
        out = {"counts": counts,
               "latency": self.latency_quantiles(),
               "warm_buckets": sorted(self.router.warm_buckets())}
        batch = self.batch_stats()
        if batch is not None:
            out["batch"] = batch
        return out
