"""Typed pipeline configuration.

The reference drives its pipeline from per-dataset JSON blobs merged into an
argparse namespace with no validation (reference utils/config.py:9-26) and a
hardcoded ``/workspace/MaskClustering/configs`` path (utils/config.py:10).
Here the config is a frozen dataclass with typed fields, repo-relative config
discovery, and explicit validation, plus TPU-specific knobs the reference has
no analog for (backend, mesh shape, padding buckets).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Optional, Tuple

_CONFIG_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "configs")


def parse_carve_spec(spec: str) -> Tuple[int, int]:
    """``"KxC"`` -> (workers, chips_per_worker), with typed errors.

    Pure grammar: the device-product division check lives at pool start
    (serve/pool.py), where the backend is visible.
    """
    parts = spec.lower().split("x")
    if len(parts) != 2:
        raise ValueError(
            f"serve_carve must be 'KxC' (workers x chips), got {spec!r}")
    try:
        workers, chips = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"serve_carve must be 'KxC' with integer K and C, "
            f"got {spec!r}") from None
    if workers < 1 or chips < 1:
        raise ValueError(
            f"serve_carve needs K >= 1 and C >= 1, got {spec!r}")
    return workers, chips


def parse_tenant_spec(spec: str) -> Dict[str, Tuple[float, Optional[int]]]:
    """``"name:weight[:quota],..."`` -> {name: (weight, quota_or_None)}.

    The pool scheduler's QoS table (weight = weighted-fair dequeue
    share; quota = max queued requests before a typed ``quota`` reject).
    Typed errors per the PR-5 config validation pattern.
    """
    table: Dict[str, Tuple[float, Optional[int]]] = {}
    for entry in (e.strip() for e in spec.split(",")):
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"serve_tenants entry must be 'name:weight' or "
                f"'name:weight:quota', got {entry!r}")
        name = parts[0]
        if not name or "/" in name or "\\" in name:
            raise ValueError(
                f"serve_tenants name must be non-empty without path "
                f"separators, got {name!r}")
        if name in table:
            raise ValueError(f"serve_tenants repeats tenant {name!r}")
        try:
            weight = float(parts[1])
        except ValueError:
            raise ValueError(
                f"serve_tenants weight must be a number, got "
                f"{parts[1]!r} for tenant {name!r}") from None
        if weight <= 0:
            raise ValueError(
                f"serve_tenants weight must be > 0, got {weight} for "
                f"tenant {name!r}")
        quota: Optional[int] = None
        if len(parts) == 3:
            try:
                quota = int(parts[2])
            except ValueError:
                raise ValueError(
                    f"serve_tenants quota must be an integer, got "
                    f"{parts[2]!r} for tenant {name!r}") from None
            if quota < 1:
                raise ValueError(
                    f"serve_tenants quota must be >= 1, got {quota} "
                    f"for tenant {name!r}")
        table[name] = (weight, quota)
    return table


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """All knobs for one pipeline run.

    Threshold semantics follow reference configs/scannet.json:1-9 and the
    module-level constants in reference utils/mask_backprojection.py:8-14.
    """

    # --- identity ---
    config_name: str = "demo"
    dataset: str = "demo"
    seq_name: Optional[str] = None

    # --- clustering thresholds (reference configs/*.json) ---
    mask_visible_threshold: float = 0.3
    undersegment_filter_threshold: float = 0.3
    view_consensus_threshold: float = 0.9
    contained_threshold: float = 0.8
    point_filter_threshold: float = 0.5
    step: int = 10  # frame stride

    # --- backprojection constants (reference utils/mask_backprojection.py:8-14) ---
    coverage_threshold: float = 0.3
    distance_threshold: float = 0.01  # metres; ball radius / depth-agreement tol
    few_points_threshold: int = 25
    depth_trunc: float = 20.0
    # (the reference's BBOX_EXPAND constant is defined but never used,
    # mask_backprojection.py:14 — intentionally not carried over)

    # --- post-processing (reference utils/post_process.py) ---
    dbscan_split_eps: float = 0.1
    dbscan_split_min_points: int = 4
    denoise_eps: float = 0.04
    denoise_min_points: int = 4
    overlap_merge_ratio: float = 0.8
    min_masks_per_object: int = 2
    num_representative_masks: int = 5
    big_mask_point_count: int = 500  # absolute-visibility override (construction.py:119)

    # --- TPU-specific (no reference analog) ---
    backend: str = "tpu"  # "tpu" | "cpu" (tests) — which jax platform to target
    association_window: int = 1  # half-width of the pixel window in projective association
    # frames vectorized per association-scan step (lax.map batch_size):
    # 1 = strictly sequential (one frame's intermediates live at a time);
    # B > 1 trades a B-fold intermediate footprint (~40 MB/frame at
    # 480x640/192k pts) for B-wide utilization per step. Default stays 1
    # until a live-chip measurement shows a win (CPU backend measures a
    # slight loss; byte-identity at any B is pinned by
    # tests/test_backprojection.py).
    # DECISION PENDING (VERDICT Weak #4): scripts/chip_session.sh runs a
    # dedicated bench_fb8 on/off A/B every session — the first healthy
    # window's capture decides whether this default flips to 8 or the
    # knob is deleted. Until that record exists this is dead config
    # surface kept only for the A/B itself.
    association_frame_batch: int = 1
    # operand encoding of the boolean/one-hot counting contractions
    # (ops/counting.py): "bf16" = bf16 operands + f32 accumulation (exact
    # to 2^24), "int8" = s8 operands + s32 accumulation (exact to 2^31; on
    # v5e the MXU runs s8 at 2x bf16 throughput with half the operand HBM
    # traffic). Both produce byte-identical artifacts (tests/
    # test_counting.py); default stays bf16 until the on-chip A/B in
    # scripts/chip_session.sh (bench_int8) captures the wall-clock win.
    count_dtype: str = "bf16"
    point_chunk: int = 8192  # point-chunk size for the affinity matmul
    mask_pad_multiple: int = 256  # pad N_masks to a multiple of this (bucketed recompiles)
    frame_pad_multiple: int = 32  # pad N_frames likewise (mesh batch path)
    max_cluster_iterations: int = 20  # schedule length (95..0 step -5 = 20 entries)
    # parity mode: run the reference's ball-query association
    # (models/exact_backprojection.py) instead of projective association
    use_exact_ball_query: bool = False
    # post-process entirely on device (routing prep, claim statistics, grid
    # DBSCAN split, group structures, mask assignment, overlap-merge
    # intersection counts) with an emit-only drain — the (F, N) claim
    # planes are consumed in HBM, never pulled, and the only transfer is
    # the final compact instance planes. False = the host numpy path
    # (reference-shaped; also the degradation ladder's fallback rung).
    # Both paths produce byte-identical artifacts
    # (tests/test_postprocess_device.py)
    device_postprocess: bool = True
    # capacity ceiling of the device post-process's global DBSCAN-group
    # axis (groups = per-instance spatial components + one noise slot
    # each). The compiled group width is the pow2 bucket of the TRUE
    # total (pulled with the per-rep root counts), so this knob never
    # costs matmul lanes; a scene splitting into more groups raises
    # PostprocessCapacityError (device-class) and the ladder's
    # host-postprocess rung re-runs it on the host path. 512 leaves ~10x
    # headroom over the honest bench scene
    post_group_cap: int = 512
    # static per-pair neighbor window of the device grid-DBSCAN split
    # (same-instance in-eps neighbors per point, prefix-sum packed).
    # Overflow drops hits, so the kernel flags it and the drain raises
    # PostprocessCapacityError -> host-postprocess rung, like the group
    # cap; 256 covers eps-ball occupancies ~5x the honest bench scene's
    post_neighbor_cap: int = 256
    # (scene, frame) device-mesh factorization for the fused multi-chip path
    # (parallel/batch.py); empty = single-device host pipeline
    mesh_shape: Tuple[int, ...] = ()
    # third mesh axis: shard the scene-point dimension N over this many
    # chips (parallel/mesh.py "point"). The (F, N) claim planes,
    # mask_of_point and the cloud — the largest long-lived HBM residents
    # — divide by it, turning the 192k-point honest ceiling into a knob
    # (a 1M+ point ScanNet++/Matterport mesh fits at point_shards >= 4);
    # the graph co-occurrence contractions psum partial counts over the
    # axis, byte-identical under both count_dtype encodings
    # (tests/test_point_sharding.py). Requires mesh_shape (the fused mesh
    # path owns the axis); the device product becomes
    # scene * frame * point_shards. Capacity note: an HBM-capacity
    # failure at high N degrades best by RAISING this knob (more shards,
    # same artifacts), not by dropping to the host-postprocess rung —
    # the ladder's single-chip rung resets it to 1 like the mesh.
    point_shards: int = 1

    # --- streaming incremental clustering (models/streaming.py) ---
    # frames per accumulation chunk (0 = off, the classic offline-batch
    # pipeline). > 0 routes the scene through the chunked accumulator:
    # only one chunk's (F', N) claim planes plus the O(M^2) accumulator
    # state are ever resident (stream.max_plane_bytes pins it), partial
    # instances are exported per chunk, and the final answer converges to
    # the batch result — byte-identical when one chunk covers the whole
    # scene, AP-equivalent at smaller chunks (tests/test_streaming.py).
    # Single-chip mode: incompatible with mesh_shape (the fused mesh path
    # owns whole scenes) and with use_exact_ball_query (host parity path)
    streaming_chunk: int = 0
    # re-cluster cadence in chunks (1 = after every chunk). Between
    # re-clusters new masks stay their own partial instances; the warm
    # start from the previous assignment makes a re-cluster O(iterations
    # to absorb the new chunk), not a from-singletons solve
    stream_recluster_every: int = 1
    # mask-capacity headroom of the streaming accumulator: the global
    # M_pad bucket is projected from the first chunk's mask density x
    # the chunk count x this factor, so later chunks land in the SAME
    # bucket (zero post-warm compiles). A projection overflow grows the
    # bucket (a counted recompile), never drops masks
    stream_mask_headroom: float = 1.5
    # extra attempts per failed chunk (mid-stream faults retry the CHUNK
    # with the accumulator intact, not the scene; 0 = fail fast to the
    # scene supervisor)
    stream_chunk_retries: int = 2
    # accumulator snapshot cadence in chunks (crash resume): every
    # snapshot drains the O(M^2) state to host and writes an npz, which
    # is real per-chunk latency at production M — 1 journals every chunk
    # (lose nothing on a kill), N journals every Nth chunk (lose at most
    # N-1 chunks of re-runnable work); 0 disables the journal entirely
    stream_journal_every: int = 1

    # --- scene executor (run.py, single-chip scene queue) ---
    # overlap scene N's host tail (DBSCAN split, merge, export) on a worker
    # thread with scene N+1's device phase; artifacts are byte-identical to
    # the sequential order (tests/test_executor.py)
    scene_overlap: bool = True
    # disk-load lookahead depth of the scene prefetcher (0 = load inline,
    # 1 = the classic one-scene lookahead); each prefetched scene holds its
    # decoded tensors resident, so depth bounds host memory
    prefetch_depth: int = 1
    # donate dead device buffers back to the allocator: the uploaded
    # depth/seg frames into the association jit, and the (F, N) claim
    # tensors into the post-process group-counts kernel — scene N's padded
    # buffers free in time for scene N+1's dispatch at the same shape bucket
    donate_buffers: bool = True
    # rows per chunked bit-plane device->host pull in the post-process
    # emit drain (the surviving objects' packed point planes; 0 = one
    # blocking pull); chunks stream via copy_to_host_async so unpack
    # overlaps the next chunk's DMA
    claims_pull_chunk: int = 64

    # --- fault tolerance (run.py scene supervisor + utils/faults.py) ---
    # extra attempts per failed scene beyond the first (0 = fail fast);
    # only retryable/device error classes retry — terminal errors
    # (programming/config) never burn the budget
    scene_retries: int = 2
    # base of the exponential per-round retry backoff (doubles per round,
    # capped at 8x base by the supervisor's RetryPolicy)
    retry_backoff_s: float = 0.25
    # watchdog budgets (seconds; 0 = disabled, the default — no threads,
    # no overhead). Armed, a phase that exceeds its budget raises a typed
    # DeviceStallError in the scene loop (retried + degraded per the
    # ladder) instead of wedging the run; size them ~5-10x the healthy
    # phase wall (README "Surviving a wedged chip")
    watchdog_load_s: float = 0.0
    watchdog_device_s: float = 0.0
    watchdog_host_s: float = 0.0
    # process-isolated serving worker (serve/supervisor.py): parent-side
    # liveness budget — a worker subprocess that emits no heartbeat for
    # this long is declared wedged and SIGKILLed (a GIL-held native hang
    # defeats every in-process watchdog; only the parent can clear it) —
    # and how many consecutive crash/wedge respawns the supervisor pays
    # before declaring the device unserveable and stopping the daemon
    worker_heartbeat_s: float = 20.0
    worker_respawns: int = 2
    # continuous scene batching (serve/worker.py + parallel/batch.py):
    # the worker drains up to this many SAME-BUCKET requests from the
    # admission queue into ONE fused scene-axis dispatch (1 = off, the
    # sequential path). Partial batches (2 <= k < S) are padded to
    # exactly S with the router's warm synthetic tensors so the width
    # vocabulary stays {1, S} — one AOT executable per bucket per width,
    # zero post-warm compiles at any occupancy. Solo requests keep the
    # per-scene path (already warm, full degradation ladder).
    serve_batch_max: int = 1
    # bounded linger: how long the scheduler may hold the batch head open
    # waiting for same-bucket company. Always clipped to half the head's
    # remaining deadline budget, so a lone request never waits past it.
    serve_batch_linger_s: float = 0.05

    # --- worker pool (serve/pool.py) ---
    # how many supervised device-owning worker subprocesses the daemon
    # runs (1 = the classic single-worker topology). Each worker is a
    # full PR-12 crash-containment ladder (heartbeat, SIGKILL, bounded
    # respawn) over its own device slice; the pool scheduler routes by
    # bucket affinity and weighted-fair tenant share
    serve_workers: int = 1
    # device carve spec "KxC": K workers x C chips each, reusing the
    # make_run_mesh scene x frame x point product vocabulary (a v5e-8 is
    # "4x2" for small buckets or "1x8" for 1M-point scenes). "" = every
    # worker sees the whole backend (CPU tests / single-chip hosts). K
    # must equal serve_workers; K*C must divide the device product —
    # grammar is validated here, the device check happens at pool start
    # (the config cannot see the backend)
    serve_carve: str = ""
    # tenant QoS spec "name:weight[:quota],...": weight > 0 sets the
    # weighted-fair dequeue share (a 3:1 weight ratio yields ~3:1
    # completions under saturation), optional integer quota >= 1 bounds
    # the tenant's QUEUED (admitted, pre-dispatch) requests — exceeding
    # it answers a typed "quota" reject. Unlisted tenants serve at
    # weight 1 with no quota; "" = no QoS (FIFO)
    serve_tenants: str = ""

    # --- serving durability (serve/wal.py) ---
    # per-request journal / stream-snapshot retention: keep at most this
    # many settled files in journal_dir/ and stream_state/ (0 = keep
    # all), and delete anything older than serve_journal_max_age_s
    # seconds (0 = no age bound). Pruning runs at daemon start and every
    # serve_prune_interval_s on a timer, counted as
    # serve.journals_pruned; the admission WAL itself and files younger
    # than the live-state floor are never pruned
    serve_journal_keep: int = 512
    serve_journal_max_age_s: float = 0.0
    serve_prune_interval_s: float = 300.0

    # --- persistent AOT executable cache (utils/aot_cache.py) ---
    # "" = off (unless $MCT_AOT_CACHE arms it), "auto" = aot_cache/ next
    # to the perf ledger, any other value = explicit directory. Armed, the
    # serving programs' jax.export round-trips persist keyed by the
    # retrace census coordinates and a version stamp, and warm_start()
    # restores them at run/daemon/worker start — a respawned process
    # reaches first dispatch with zero compiles
    aot_cache_dir: str = ""

    # --- paths ---
    data_root: str = "./data"
    cropformer_path: str = ""
    debug: bool = False
    # persistent XLA compilation cache: None -> ~/.cache/maskclustering_tpu/xla
    # (or $MCT_COMPILE_CACHE); "" disables. A ScanNet-val run hits a handful
    # of (k_max, F_pad, N_pad) buckets; caching makes repeat runs compile 0.
    compilation_cache_dir: Optional[str] = None

    def __post_init__(self):
        if not (0.0 <= self.mask_visible_threshold <= 1.0):
            raise ValueError(f"mask_visible_threshold must be in [0,1], got {self.mask_visible_threshold}")
        if not (0.0 <= self.view_consensus_threshold <= 1.0):
            raise ValueError(f"view_consensus_threshold must be in [0,1], got {self.view_consensus_threshold}")
        if self.step < 1:
            raise ValueError(f"step must be >= 1, got {self.step}")
        if self.distance_threshold <= 0:
            raise ValueError("distance_threshold must be positive")
        if self.association_frame_batch < 1:
            raise ValueError(f"association_frame_batch must be >= 1, "
                             f"got {self.association_frame_batch}")
        if self.backend not in ("tpu", "cpu", "gpu"):
            raise ValueError(f"unknown backend {self.backend!r}")
        from maskclustering_tpu.ops.counting import COUNT_DTYPES

        if self.count_dtype not in COUNT_DTYPES:
            raise ValueError(f"count_dtype must be one of {COUNT_DTYPES}, "
                             f"got {self.count_dtype!r}")
        if self.mesh_shape and len(self.mesh_shape) != 2:
            raise ValueError(
                f"mesh_shape must be (scene, frame), got {self.mesh_shape}")
        if self.point_shards < 1:
            raise ValueError(
                f"point_shards must be >= 1, got {self.point_shards}")
        if self.point_shards > 1 and not self.mesh_shape:
            raise ValueError(
                "point_shards > 1 requires the fused mesh path — set "
                "mesh_shape (scene, frame); the point axis is the mesh's "
                "third axis, not a single-chip mode")
        if self.streaming_chunk < 0:
            raise ValueError(
                f"streaming_chunk must be >= 0, got {self.streaming_chunk}")
        if self.streaming_chunk > 0 and self.mesh_shape:
            raise ValueError(
                "streaming_chunk is a single-chip mode — the fused mesh "
                "path (mesh_shape) consumes whole scenes; unset one")
        if self.streaming_chunk > 0 and self.use_exact_ball_query:
            raise ValueError(
                "streaming_chunk cannot run the exact ball-query parity "
                "path (host-only, no chunk planes); unset one")
        if self.stream_recluster_every < 1:
            raise ValueError(
                f"stream_recluster_every must be >= 1, "
                f"got {self.stream_recluster_every}")
        if self.stream_mask_headroom < 1.0:
            raise ValueError(
                f"stream_mask_headroom must be >= 1.0, "
                f"got {self.stream_mask_headroom}")
        if self.stream_chunk_retries < 0:
            raise ValueError(
                f"stream_chunk_retries must be >= 0, "
                f"got {self.stream_chunk_retries}")
        if self.stream_journal_every < 0:
            raise ValueError(
                f"stream_journal_every must be >= 0, "
                f"got {self.stream_journal_every}")
        if self.prefetch_depth < 0:
            raise ValueError(
                f"prefetch_depth must be >= 0, got {self.prefetch_depth}")
        if self.claims_pull_chunk < 0:
            raise ValueError(
                f"claims_pull_chunk must be >= 0, got {self.claims_pull_chunk}")
        if self.post_group_cap < 1:
            raise ValueError(
                f"post_group_cap must be >= 1, got {self.post_group_cap}")
        if self.post_neighbor_cap < 1:
            raise ValueError(
                f"post_neighbor_cap must be >= 1, "
                f"got {self.post_neighbor_cap}")
        if self.scene_retries < 0:
            raise ValueError(
                f"scene_retries must be >= 0, got {self.scene_retries}")
        for knob in ("retry_backoff_s", "watchdog_load_s",
                     "watchdog_device_s", "watchdog_host_s",
                     "worker_heartbeat_s", "serve_journal_keep",
                     "serve_journal_max_age_s", "serve_prune_interval_s"):
            if getattr(self, knob) < 0:
                raise ValueError(
                    f"{knob} must be >= 0, got {getattr(self, knob)}")
        if self.worker_respawns < 0:
            raise ValueError(
                f"worker_respawns must be >= 0, got {self.worker_respawns}")
        if self.serve_batch_max < 1:
            raise ValueError(
                f"serve_batch_max must be >= 1, got {self.serve_batch_max}")
        if self.serve_batch_linger_s < 0:
            raise ValueError(
                f"serve_batch_linger_s must be >= 0, "
                f"got {self.serve_batch_linger_s}")
        if self.serve_batch_max > 1 and self.streaming_chunk > 0:
            raise ValueError(
                "serve_batch_max > 1 packs whole scenes onto the scene "
                "mesh axis — streaming_chunk is a single-chip whole-stream "
                "mode; unset one")
        if self.serve_workers < 1:
            raise ValueError(
                f"serve_workers must be >= 1, got {self.serve_workers}")
        if self.serve_carve:
            workers, _chips = parse_carve_spec(self.serve_carve)
            if workers != self.serve_workers:
                raise ValueError(
                    f"serve_carve {self.serve_carve!r} names {workers} "
                    f"workers but serve_workers={self.serve_workers}; "
                    f"the carve's K must equal serve_workers")
        if self.serve_tenants:
            parse_tenant_spec(self.serve_tenants)  # grammar check (typed)

    def replace(self, **kw) -> "PipelineConfig":
        return dataclasses.replace(self, **kw)

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["mesh_shape"] = list(d["mesh_shape"])
        return json.dumps(d, indent=2)


def config_from_json(text: str) -> PipelineConfig:
    """Inverse of ``PipelineConfig.to_json``.

    The isolated serving worker's config transport: the daemon serializes
    its EXACT config (every override applied) and the worker subprocess
    rebuilds it field-for-field — re-deriving from a config name + CLI
    overrides would silently drift the two processes apart.
    """
    raw = json.loads(text)
    fields = {f.name for f in dataclasses.fields(PipelineConfig)}
    unknown = set(raw) - fields
    if unknown:
        raise ValueError(f"unknown config keys: {sorted(unknown)}")
    if isinstance(raw.get("mesh_shape"), list):
        raw["mesh_shape"] = tuple(raw["mesh_shape"])
    return PipelineConfig(**raw)


def load_config(name: str, config_dir: Optional[str] = None, **overrides) -> PipelineConfig:
    """Load ``configs/<name>.json`` relative to the repo (not a hardcoded abs path).

    Unknown keys in the JSON are rejected so typos fail loudly (the reference
    silently setattr's anything, utils/config.py:13-15).
    """
    config_dir = config_dir or _CONFIG_DIR
    path = os.path.join(config_dir, f"{name}.json")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no config named {name!r}: {path} does not exist")
    fields = {f.name for f in dataclasses.fields(PipelineConfig)}
    with open(path) as f:
        raw = json.load(f)
    unknown = set(raw) - fields
    if unknown:
        raise ValueError(f"unknown config keys in {path}: {sorted(unknown)}")
    raw["config_name"] = name
    raw.update(overrides)
    if isinstance(raw.get("mesh_shape"), list):
        raw["mesh_shape"] = tuple(raw["mesh_shape"])
    return PipelineConfig(**raw)
