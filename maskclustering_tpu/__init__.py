"""maskclustering_tpu — a TPU-native open-vocabulary 3D instance segmentation framework.

A from-scratch JAX/XLA/Pallas re-design of the MaskClustering (CVPR 2024)
pipeline (reference: /root/reference). The reference is a CUDA/torch/Open3D
script collection; this framework maps the same capability onto TPU hardware:

- per-frame mask backprojection   -> vmapped projective association (models/backprojection.py)
- mask-graph statistics           -> one MXU boolean matmul (models/graph.py)
- iterative view-consensus merge  -> jitted lax.scan + min-label propagation (models/clustering.py)
- post-processing + export        -> segment math + host C++ DBSCAN (models/postprocess.py)
- ScanNet AP protocol             -> evaluation/ap.py
- open-vocab semantics            -> semantics/ (CLIP pooling in jnp)
- multi-chip scale-out            -> parallel/ (Mesh + shard_map + collectives)
"""

__version__ = "0.1.0"

from maskclustering_tpu.config import PipelineConfig, load_config

__all__ = ["PipelineConfig", "load_config", "__version__"]
