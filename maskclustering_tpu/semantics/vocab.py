"""Benchmark label vocabularies (ScanNet / Matterport3D / ScanNet++).

These are fixed benchmark label lists (data, not logic), stored as JSON under
``vocab_data/`` rather than inlined in code. Sources: the ScanNet 200/..
benchmark vocabulary, Matterport3D categories, and the ScanNet++ class list
(reference evaluation/constants.py holds the same data as Python literals).
"""

from __future__ import annotations

import functools
import json
import os
from typing import List, Tuple

_VOCAB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "vocab_data")

_ALIASES = {"matterport": "matterport3d", "demo": "scannet"}


def vocab_name(dataset: str) -> str:
    """Canonical vocabulary name for a dataset (demo shares scannet's)."""
    return _ALIASES.get(dataset, dataset)


@functools.lru_cache(maxsize=None)
def get_vocab(dataset: str) -> Tuple[List[str], List[int]]:
    """Return (labels, ids) for a dataset's benchmark vocabulary."""
    dataset = vocab_name(dataset)
    path = os.path.join(_VOCAB_DIR, f"{dataset}.json")
    if not os.path.exists(path):
        raise KeyError(f"no vocabulary for dataset {dataset!r}")
    with open(path) as f:
        d = json.load(f)
    return d["labels"], d["ids"]
