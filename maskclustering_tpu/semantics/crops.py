"""Multi-scale mask crops for open-vocabulary feature extraction.

OpenMask3D-style crop policy (reference semantics/get_open-voc_features.py:44-99):
for each representative mask, crop the RGB frame at CROP_SCALES levels — level 0
is the tight mask bbox, level k expands each side by ``int(extent * 0.1) * k``
clamped to the image — then pad each crop to a white square. The encoder
normalizes/resizes; this module only produces the square uint8 crops.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

CROP_SCALES = 3  # follow OpenMask3D
EXPANSION_RATIO = 0.1


def mask_to_box(mask: np.ndarray, level: int,
                expansion_ratio: float = EXPANSION_RATIO) -> Tuple[int, int, int, int]:
    """(left, top, right, bottom) of the mask bbox expanded for ``level``.

    Level 0 is the tight box; higher levels expand by
    ``int(extent * ratio) * level`` per axis, clamped to the image bounds
    (reference get_open-voc_features.py:49-61).
    """
    rows, cols = np.where(mask)
    if rows.size == 0:
        raise ValueError("mask_to_box called with an empty mask")
    top, bottom = int(rows.min()), int(rows.max())
    left, right = int(cols.min()), int(cols.max())
    if level == 0:
        return left, top, right, bottom
    h, w = mask.shape
    x_exp = int(abs(right - left) * expansion_ratio) * level
    y_exp = int(abs(bottom - top) * expansion_ratio) * level
    return (max(0, left - x_exp), max(0, top - y_exp),
            min(w, right + x_exp), min(h, bottom + y_exp))


def pad_to_square(image: np.ndarray, fill: int = 255) -> np.ndarray:
    """Center an image on a white square canvas (reference lines 75-82)."""
    h, w = image.shape[:2]
    size = max(h, w)
    canvas = np.full((size, size, 3), fill, dtype=np.uint8)
    top = (size - h) // 2
    left = (size - w) // 2
    canvas[top:top + h, left:left + w] = image
    return canvas


def multiscale_crops(rgb: np.ndarray, mask: np.ndarray,
                     num_scales: int = CROP_SCALES) -> List[np.ndarray]:
    """``num_scales`` square crops of ``rgb`` around ``mask``.

    ``mask`` is nearest-resized to the RGB resolution first if the
    segmentation was stored at depth resolution (reference line 71).
    """
    if mask.shape != rgb.shape[:2]:
        from maskclustering_tpu.io.image import resize_nearest

        mask = resize_nearest(mask.astype(np.uint8),
                              (rgb.shape[1], rgb.shape[0])).astype(bool)
    out = []
    for level in range(num_scales):
        left, top, right, bottom = mask_to_box(mask, level)
        crop = rgb[top:bottom, left:right]
        if crop.size == 0:  # single-row/col tight box
            crop = rgb[top:bottom + 1, left:right + 1]
        out.append(pad_to_square(np.ascontiguousarray(crop)))
    return out
