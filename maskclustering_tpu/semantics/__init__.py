"""Open-vocabulary semantics (reference semantics/ layer, L4)."""

from maskclustering_tpu.semantics.vocab import get_vocab
from maskclustering_tpu.semantics.crops import (
    CROP_SCALES,
    mask_to_box,
    multiscale_crops,
    pad_to_square,
)
from maskclustering_tpu.semantics.encoder import (
    HashEncoder,
    HFCLIPEncoder,
    ImageEncoder,
    PrecomputedFeatures,
    TextEncoder,
    l2_normalize,
)
from maskclustering_tpu.semantics.features import (
    extract_label_features,
    extract_mask_features,
    pool_scale_features,
    representative_mask_index,
    save_mask_features,
)
from maskclustering_tpu.semantics.query import (
    assign_labels,
    classify_objects,
    object_features,
    run_query,
)

__all__ = [
    "get_vocab",
    "CROP_SCALES",
    "mask_to_box",
    "multiscale_crops",
    "pad_to_square",
    "HashEncoder",
    "HFCLIPEncoder",
    "ImageEncoder",
    "PrecomputedFeatures",
    "TextEncoder",
    "l2_normalize",
    "extract_label_features",
    "extract_mask_features",
    "pool_scale_features",
    "representative_mask_index",
    "save_mask_features",
    "assign_labels",
    "classify_objects",
    "object_features",
    "run_query",
]
