from maskclustering_tpu.semantics.vocab import get_vocab

__all__ = ["get_vocab"]
