"""Pluggable CLIP encoders for open-vocabulary semantics.

The reference hardwires open_clip ViT-H-14 laion2b_s32b_b79k on CUDA
(get_open-voc_features.py:101-107, extract_label_featrues.py:7-13). Here the
encoder is an interface so the pooling/query math (pure jnp) is testable and
the model backend is swappable:

- ``HFCLIPEncoder``: HuggingFace ``transformers`` CLIP (Flax on TPU when
  available, else torch CPU) from a *local* checkpoint path or cache.
- ``PrecomputedFeatures``: reads feature npy artifacts produced elsewhere —
  the common deployment shape, since 2D mask prediction and CLIP encoding are
  frozen upstream stages (SURVEY.md §2.2).
- ``HashEncoder``: deterministic fake for tests.

All encoders return L2-normalized float32 features.
"""

from __future__ import annotations

import os
from typing import Optional, Protocol, Sequence

import numpy as np


class ImageEncoder(Protocol):
    feature_dim: int

    def encode_images(self, images: Sequence[np.ndarray]) -> np.ndarray:
        """(B, D) L2-normalized features from a list of HxWx3 uint8 images."""
        ...


class TextEncoder(Protocol):
    feature_dim: int

    def encode_texts(self, texts: Sequence[str]) -> np.ndarray:
        """(B, D) L2-normalized features from text prompts."""
        ...


def l2_normalize(x: np.ndarray, axis: int = -1, eps: float = 1e-12) -> np.ndarray:
    return x / np.maximum(np.linalg.norm(x, axis=axis, keepdims=True), eps)


def find_local_clip_checkpoint(extra_dirs: Sequence[str] = ()) -> Optional[str]:
    """First CLIP checkpoint directory found on local disk, or None.

    The reference downloads ViT-H-14 laion2b_s32b_b79k at run time
    (get_open-voc_features.py:101-107); this environment has no egress, so a
    checkpoint can only be used if it already exists. Searched: the
    HuggingFace hub cache (model dirs whose name mentions clip), any
    ``MCT_CLIP_PATH`` env override, and ``extra_dirs``. A hit is any
    directory holding a config plus a weights file — both the HF-transformers
    layout (config.json + flax/pytorch/safetensors weights, loadable by
    HFCLIPEncoder directly) and the open_clip cache layout the reference's
    exact checkpoint lands in (open_clip_config.json +
    open_clip_pytorch_model.bin; needs a transformers conversion before
    HFCLIPEncoder can use it, but its presence IS the fact). The
    orchestrator records the outcome in run_report.json either way, turning
    "no real CLIP weights available" into a machine-checked environment fact.
    """
    import glob

    candidates = []
    env = os.environ.get("MCT_CLIP_PATH")
    if env:
        candidates.append(env)
    candidates.extend(extra_dirs)
    hub = os.environ.get(
        "HF_HUB_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "huggingface", "hub"))
    for model_dir in sorted(glob.glob(os.path.join(hub, "models--*"))):
        if "clip" in os.path.basename(model_dir).lower():
            candidates.extend(sorted(glob.glob(
                os.path.join(model_dir, "snapshots", "*"))))
    config_names = ("config.json", "open_clip_config.json")
    weight_names = ("flax_model.msgpack", "pytorch_model.bin",
                    "model.safetensors", "open_clip_pytorch_model.bin",
                    "open_clip_model.safetensors")
    for cand in candidates:
        if not any(os.path.isfile(os.path.join(cand, c)) for c in config_names):
            continue
        if any(os.path.isfile(os.path.join(cand, w)) for w in weight_names):
            return cand
    return None


class HashEncoder:
    """Deterministic stand-in encoder: feature = seeded hash of the input.

    Images/texts that are bytewise identical map to identical unit vectors,
    so pooling and query logic can be exercised without model weights.
    """

    def __init__(self, feature_dim: int = 64):
        self.feature_dim = feature_dim

    def _embed(self, payload: bytes) -> np.ndarray:
        import zlib

        rng = np.random.default_rng(zlib.crc32(payload))
        return rng.standard_normal(self.feature_dim).astype(np.float32)

    def encode_images(self, images: Sequence[np.ndarray]) -> np.ndarray:
        feats = [self._embed(np.ascontiguousarray(im).tobytes()) for im in images]
        return l2_normalize(np.stack(feats))

    def encode_texts(self, texts: Sequence[str]) -> np.ndarray:
        feats = [self._embed(t.encode()) for t in texts]
        return l2_normalize(np.stack(feats))


class HFCLIPEncoder:
    """CLIP via HuggingFace transformers from a local checkpoint.

    Prefers the Flax model (runs on the TPU through jax); falls back to torch
    CPU. Raises a clear error when the checkpoint is unavailable — this
    environment has no network egress, so weights must already be on disk.
    """

    def __init__(self, model_name_or_path: str, image_size: int = 224):
        import logging

        self.image_size = image_size
        self._flax = None
        self._torch = None
        try:
            from transformers import CLIPProcessor, FlaxCLIPModel

            self._model = FlaxCLIPModel.from_pretrained(
                model_name_or_path, local_files_only=True)
            self._processor = CLIPProcessor.from_pretrained(
                model_name_or_path, local_files_only=True)
            self._flax = True
        except (ImportError, OSError, EnvironmentError) as e:
            logging.getLogger("maskclustering_tpu").warning(
                "Flax CLIP load failed (%s); falling back to torch CPU", e)
            from transformers import CLIPModel, CLIPProcessor

            self._model = CLIPModel.from_pretrained(
                model_name_or_path, local_files_only=True)
            self._processor = CLIPProcessor.from_pretrained(
                model_name_or_path, local_files_only=True)
            self._torch = True
        self.feature_dim = int(self._model.config.projection_dim)

    def encode_images(self, images: Sequence[np.ndarray]) -> np.ndarray:
        inputs = self._processor(images=list(images), return_tensors="np"
                                 if self._flax else "pt")
        if self._flax:
            feats = np.asarray(self._model.get_image_features(**inputs))
        else:
            import torch

            with torch.no_grad():
                feats = self._model.get_image_features(**inputs).numpy()
        return l2_normalize(feats.astype(np.float32))

    def encode_texts(self, texts: Sequence[str]) -> np.ndarray:
        inputs = self._processor(text=list(texts), return_tensors="np"
                                 if self._flax else "pt", padding=True)
        if self._flax:
            feats = np.asarray(self._model.get_text_features(**inputs))
        else:
            import torch

            with torch.no_grad():
                feats = self._model.get_text_features(**inputs).numpy()
        return l2_normalize(feats.astype(np.float32))


class PrecomputedFeatures:
    """Feature store backed by the reference's npy artifacts.

    ``open-vocabulary_features.npy`` maps ``"{frame_id}_{mask_id}"`` to a
    feature vector (reference get_open-voc_features.py:143-149);
    ``data/text_features/<dataset>.npy`` maps label text to a feature
    (extract_label_featrues.py:22-26).
    """

    def __init__(self, path: str):
        self._dict = np.load(path, allow_pickle=True).item()
        if not self._dict:
            raise ValueError(f"feature store {path} is empty")
        first = next(iter(self._dict.values()))
        self.feature_dim = int(np.asarray(first).shape[-1])

    def __contains__(self, key: str) -> bool:
        return key in self._dict

    def get(self, key: str) -> Optional[np.ndarray]:
        v = self._dict.get(key)
        return None if v is None else np.asarray(v, dtype=np.float32)

    def keys(self):
        return self._dict.keys()
