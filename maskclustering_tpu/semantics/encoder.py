"""Pluggable CLIP encoders for open-vocabulary semantics.

The reference hardwires open_clip ViT-H-14 laion2b_s32b_b79k on CUDA
(get_open-voc_features.py:101-107, extract_label_featrues.py:7-13). Here the
encoder is an interface so the pooling/query math (pure jnp) is testable and
the model backend is swappable:

- ``HFCLIPEncoder``: HuggingFace ``transformers`` CLIP (Flax on TPU when
  available, else torch CPU) from a *local* checkpoint path or cache.
- ``PrecomputedFeatures``: reads feature npy artifacts produced elsewhere —
  the common deployment shape, since 2D mask prediction and CLIP encoding are
  frozen upstream stages (SURVEY.md §2.2).
- ``HashEncoder``: deterministic fake for tests.

All encoders return L2-normalized float32 features.
"""

from __future__ import annotations

import os
from typing import Optional, Protocol, Sequence

import numpy as np


class ImageEncoder(Protocol):
    feature_dim: int

    def encode_images(self, images: Sequence[np.ndarray]) -> np.ndarray:
        """(B, D) L2-normalized features from a list of HxWx3 uint8 images."""
        ...


class TextEncoder(Protocol):
    feature_dim: int

    def encode_texts(self, texts: Sequence[str]) -> np.ndarray:
        """(B, D) L2-normalized features from text prompts."""
        ...


def l2_normalize(x: np.ndarray, axis: int = -1, eps: float = 1e-12) -> np.ndarray:
    return x / np.maximum(np.linalg.norm(x, axis=axis, keepdims=True), eps)


def find_local_clip_checkpoint(extra_dirs: Sequence[str] = ()) -> Optional[str]:
    """First CLIP checkpoint directory found on local disk, or None.

    The reference downloads ViT-H-14 laion2b_s32b_b79k at run time
    (get_open-voc_features.py:101-107); this environment has no egress, so a
    checkpoint can only be used if it already exists. Searched: the
    HuggingFace hub cache (model dirs whose name mentions clip), any
    ``MCT_CLIP_PATH`` env override, and ``extra_dirs``. A hit is any
    directory holding a config plus a weights file — both the HF-transformers
    layout (config.json + flax/pytorch/safetensors weights, loadable by
    HFCLIPEncoder directly) and the open_clip cache layout the reference's
    exact checkpoint lands in (open_clip_config.json +
    open_clip_pytorch_model.bin; needs a transformers conversion before
    HFCLIPEncoder can use it, but its presence IS the fact). The
    orchestrator records the outcome in run_report.json either way, turning
    "no real CLIP weights available" into a machine-checked environment fact.
    """
    import glob

    candidates = []
    env = os.environ.get("MCT_CLIP_PATH")
    if env:
        candidates.append(env)
    candidates.extend(extra_dirs)
    hub = os.environ.get(
        "HF_HUB_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "huggingface", "hub"))
    for model_dir in sorted(glob.glob(os.path.join(hub, "models--*"))):
        if "clip" in os.path.basename(model_dir).lower():
            candidates.extend(sorted(glob.glob(
                os.path.join(model_dir, "snapshots", "*"))))
    config_names = ("config.json", "open_clip_config.json")
    weight_names = ("flax_model.msgpack", "pytorch_model.bin",
                    "model.safetensors", "open_clip_pytorch_model.bin",
                    "open_clip_model.safetensors")
    for cand in candidates:
        if not any(os.path.isfile(os.path.join(cand, c)) for c in config_names):
            continue
        if any(os.path.isfile(os.path.join(cand, w)) for w in weight_names):
            return cand
    return None


class HashEncoder:
    """Deterministic stand-in encoder: feature = seeded hash of the input.

    Images/texts that are bytewise identical map to identical unit vectors,
    so pooling and query logic can be exercised without model weights.
    """

    def __init__(self, feature_dim: int = 64):
        self.feature_dim = feature_dim

    def _embed(self, payload: bytes) -> np.ndarray:
        import zlib

        rng = np.random.default_rng(zlib.crc32(payload))
        return rng.standard_normal(self.feature_dim).astype(np.float32)

    def encode_images(self, images: Sequence[np.ndarray]) -> np.ndarray:
        feats = [self._embed(np.ascontiguousarray(im).tobytes()) for im in images]
        return l2_normalize(np.stack(feats))

    def encode_texts(self, texts: Sequence[str]) -> np.ndarray:
        feats = [self._embed(t.encode()) for t in texts]
        return l2_normalize(np.stack(feats))


# ---------------------------------------------------------------------------
# open_clip -> HF transformers CLIP state-dict conversion
# ---------------------------------------------------------------------------
#
# The reference's exact checkpoint (ViT-H-14 laion2b_s32b_b79k) downloads
# into the open_clip cache layout: ``open_clip_config.json`` +
# ``open_clip_pytorch_model.bin``. ``find_local_clip_checkpoint`` has always
# DETECTED that layout; this converter makes it LOADABLE by HFCLIPEncoder —
# if the reference's weights ever land on disk, the pipeline uses them with
# zero new code (VERDICT r5 Next #5).

# per-resblock submodule map, shared by the vision and text towers
_OC_BLOCK_MAP = (
    ("ln_1.weight", "layer_norm1.weight"),
    ("ln_1.bias", "layer_norm1.bias"),
    ("attn.out_proj.weight", "self_attn.out_proj.weight"),
    ("attn.out_proj.bias", "self_attn.out_proj.bias"),
    ("ln_2.weight", "layer_norm2.weight"),
    ("ln_2.bias", "layer_norm2.bias"),
    ("mlp.c_fc.weight", "mlp.fc1.weight"),
    ("mlp.c_fc.bias", "mlp.fc1.bias"),
    ("mlp.c_proj.weight", "mlp.fc2.weight"),
    ("mlp.c_proj.bias", "mlp.fc2.bias"),
)


def _oc_to_np(v) -> np.ndarray:
    """torch tensor / numpy array -> float32-preserving numpy array."""
    if hasattr(v, "detach"):  # torch without importing torch
        v = v.detach().cpu().numpy()
    return np.asarray(v)


def _oc_convert_block(out: dict, src: dict, oc_prefix: str, hf_prefix: str) -> None:
    """One transformer resblock: torch MultiheadAttention's fused in_proj
    splits row-wise into the HF q/k/v projections; everything else renames."""
    for oc_name, hf_name in _OC_BLOCK_MAP:
        out[hf_prefix + hf_name] = _oc_to_np(src.pop(oc_prefix + oc_name))
    w = _oc_to_np(src.pop(oc_prefix + "attn.in_proj_weight"))
    b = _oc_to_np(src.pop(oc_prefix + "attn.in_proj_bias"))
    d = w.shape[0] // 3
    for i, proj in enumerate(("q_proj", "k_proj", "v_proj")):
        out[f"{hf_prefix}self_attn.{proj}.weight"] = w[i * d:(i + 1) * d]
        out[f"{hf_prefix}self_attn.{proj}.bias"] = b[i * d:(i + 1) * d]


def _strip_text_prefix(state_dict: dict) -> dict:
    """Normalize the CustomTextCLIP layout (text tower nested under
    ``text.``) to the classic flat key names. Returns a shallow copy."""
    out = {}
    for k, v in state_dict.items():
        out[k[len("text."):] if k.startswith("text.") else k] = v
    return out


def convert_open_clip_state_dict(state_dict: dict) -> dict:
    """open_clip CLIP-ViT state dict -> HF ``transformers`` CLIPModel layout.

    Pure array renaming/reshaping (numpy in, numpy out; torch tensors are
    accepted and detached): the fused attention ``in_proj`` splits into
    q/k/v rows, the ``visual.proj``/``text_projection`` matrices transpose
    into ``Linear`` weight convention, and the class/position embeddings
    map 1:1. Unknown keys raise — a silently dropped weight would load a
    subtly wrong encoder. Covers the classic open_clip layout the
    reference checkpoint (ViT-H-14) uses, including the ``text.``-prefixed
    CustomTextCLIP variant.
    """
    src = _strip_text_prefix(state_dict)
    out: dict = {}

    # --- vision tower ---
    out["vision_model.embeddings.class_embedding"] = \
        _oc_to_np(src.pop("visual.class_embedding")).reshape(-1)
    out["vision_model.embeddings.position_embedding.weight"] = \
        _oc_to_np(src.pop("visual.positional_embedding"))
    out["vision_model.embeddings.patch_embedding.weight"] = \
        _oc_to_np(src.pop("visual.conv1.weight"))
    out["vision_model.pre_layrnorm.weight"] = _oc_to_np(src.pop("visual.ln_pre.weight"))
    out["vision_model.pre_layrnorm.bias"] = _oc_to_np(src.pop("visual.ln_pre.bias"))
    out["vision_model.post_layernorm.weight"] = _oc_to_np(src.pop("visual.ln_post.weight"))
    out["vision_model.post_layernorm.bias"] = _oc_to_np(src.pop("visual.ln_post.bias"))
    out["visual_projection.weight"] = _oc_to_np(src.pop("visual.proj")).T

    # --- text tower ---
    out["text_model.embeddings.token_embedding.weight"] = \
        _oc_to_np(src.pop("token_embedding.weight"))
    out["text_model.embeddings.position_embedding.weight"] = \
        _oc_to_np(src.pop("positional_embedding"))
    out["text_model.final_layer_norm.weight"] = _oc_to_np(src.pop("ln_final.weight"))
    out["text_model.final_layer_norm.bias"] = _oc_to_np(src.pop("ln_final.bias"))
    out["text_projection.weight"] = _oc_to_np(src.pop("text_projection")).T
    out["logit_scale"] = _oc_to_np(src.pop("logit_scale")).reshape(())

    # --- transformer blocks of both towers ---
    blocks = {}
    for key in list(src):
        for oc_root, hf_root in (("visual.transformer.resblocks.",
                                  "vision_model.encoder.layers."),
                                 ("transformer.resblocks.",
                                  "text_model.encoder.layers.")):
            if key.startswith(oc_root):
                idx = key[len(oc_root):].split(".", 1)[0]
                blocks[(oc_root, hf_root, int(idx))] = True
    for oc_root, hf_root, idx in sorted(blocks):
        _oc_convert_block(out, src, f"{oc_root}{idx}.", f"{hf_root}{idx}.")

    # attn_mask buffers et al. are derived, not weights; anything else is a
    # layout this converter does not understand
    leftovers = [k for k in src if not k.endswith("attn_mask")]
    if leftovers:
        raise ValueError(
            f"unrecognized open_clip keys (not a classic CLIP-ViT layout?): "
            f"{sorted(leftovers)[:8]}")
    return out


def hf_clip_config_from_open_clip(oc_config: dict, state_dict: dict):
    """transformers CLIPConfig equivalent to an ``open_clip_config.json``.

    Shape facts (widths, depths, vocab) come from the weights themselves
    where possible — the config only contributes what weights cannot carry
    (head counts, context length). open_clip ViT heads default to width/64
    when the config does not name them (open_clip's ``head_width`` knob).
    """
    from transformers import CLIPConfig, CLIPTextConfig, CLIPVisionConfig

    model_cfg = oc_config.get("model_cfg", oc_config)
    vis, txt = model_cfg.get("vision_cfg", {}), model_cfg.get("text_cfg", {})
    conv = state_dict["visual.conv1.weight"]
    v_width, _, patch, _ = (int(x) for x in _oc_to_np(conv).shape)
    t_width = int(_oc_to_np(state_dict["token_embedding.weight"]).shape[1])
    embed_dim = int(model_cfg.get(
        "embed_dim", _oc_to_np(state_dict["text_projection"]).shape[1]))
    v_layers = len({k.split(".")[3] for k in state_dict
                    if k.startswith("visual.transformer.resblocks.")})
    t_layers = len({k.split(".")[2] for k in state_dict
                    if k.startswith("transformer.resblocks.")})

    def inter(prefix: str, width: int) -> int:
        key = f"{prefix}.resblocks.0.mlp.c_fc.weight"
        return (int(_oc_to_np(state_dict[key]).shape[0])
                if key in state_dict else 4 * width)

    # open_clip models use EXACT GeLU unless the config opts into the
    # OpenAI quick_gelu approximation; HF's CLIPConfig defaults to
    # quick_gelu (the OpenAI checkpoints' act), so laion checkpoints like
    # the reference's ViT-H-14 must override it or every MLP is subtly off
    act = "quick_gelu" if model_cfg.get("quick_gelu") else "gelu"
    image_size = int(vis.get("image_size", 224))
    return CLIPConfig.from_text_vision_configs(
        CLIPTextConfig(
            vocab_size=int(_oc_to_np(state_dict["token_embedding.weight"]).shape[0]),
            hidden_size=t_width,
            intermediate_size=inter("transformer", t_width),
            num_hidden_layers=t_layers,
            num_attention_heads=int(txt.get("heads", t_width // 64)),
            max_position_embeddings=int(txt.get("context_length", 77)),
            hidden_act=act,
            projection_dim=embed_dim),
        CLIPVisionConfig(
            hidden_size=v_width,
            intermediate_size=inter("visual.transformer", v_width),
            num_hidden_layers=v_layers,
            num_attention_heads=v_width // int(vis.get("head_width", 64)),
            image_size=image_size,
            patch_size=patch,
            hidden_act=act,
            projection_dim=embed_dim),
        projection_dim=embed_dim)


def load_open_clip_checkpoint(path: str):
    """torch ``transformers.CLIPModel`` from an open_clip cache directory.

    ``path`` must hold ``open_clip_config.json`` plus
    ``open_clip_pytorch_model.bin`` (the layout the reference's ViT-H-14
    checkpoint downloads into). Returns the model with converted weights
    loaded strictly — a missing or unexpected key raises.
    """
    import json

    import torch
    from transformers import CLIPModel

    with open(os.path.join(path, "open_clip_config.json")) as f:
        oc_config = json.load(f)
    sd = torch.load(os.path.join(path, "open_clip_pytorch_model.bin"),
                    map_location="cpu", weights_only=True)
    # normalize the CustomTextCLIP nesting BEFORE config derivation too —
    # hf_clip_config_from_open_clip reads text-tower shapes by flat name
    sd = _strip_text_prefix(sd)
    converted = convert_open_clip_state_dict(sd)
    model = CLIPModel(hf_clip_config_from_open_clip(oc_config, sd))
    missing, unexpected = model.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v))
         for k, v in converted.items()}, strict=False)
    # position_ids buffers are derived (absent from checkpoints by design);
    # anything else missing means the conversion is incomplete — fail loudly
    real_missing = [k for k in missing if not k.endswith("position_ids")]
    if real_missing or unexpected:
        raise ValueError(f"open_clip conversion mismatch: missing={real_missing} "
                         f"unexpected={list(unexpected)}")
    return model


def is_open_clip_layout(path: str) -> bool:
    """Does ``path`` hold an open_clip cache checkpoint (vs HF layout)?"""
    return (os.path.isfile(os.path.join(path, "open_clip_config.json"))
            and os.path.isfile(os.path.join(path, "open_clip_pytorch_model.bin"))
            and not os.path.isfile(os.path.join(path, "config.json")))


class HFCLIPEncoder:
    """CLIP via HuggingFace transformers from a local checkpoint.

    Prefers the Flax model (runs on the TPU through jax); falls back to torch
    CPU. An open_clip cache layout (the reference checkpoint's on-disk
    shape) is converted in memory via ``convert_open_clip_state_dict`` and
    served through the torch path. Raises a clear error when the checkpoint
    is unavailable — this environment has no network egress, so weights
    must already be on disk.
    """

    def __init__(self, model_name_or_path: str, image_size: int = 224):
        import logging

        self.image_size = image_size
        self._flax = None
        self._torch = None
        if is_open_clip_layout(model_name_or_path):
            from transformers import CLIPProcessor

            self._model = load_open_clip_checkpoint(model_name_or_path)
            # the open_clip cache carries no HF tokenizer/processor files;
            # they are weight-independent, so accept them from the same dir
            # when present (our fixture layout) and fail with a actionable
            # message otherwise
            try:
                self._processor = CLIPProcessor.from_pretrained(
                    model_name_or_path, local_files_only=True)
            except (OSError, EnvironmentError, ValueError) as e:
                raise ValueError(
                    f"open_clip checkpoint {model_name_or_path} converted, "
                    "but no tokenizer/preprocessor files found beside it; "
                    "copy a CLIP tokenizer (vocab.json/merges.txt) and "
                    "preprocessor_config.json into the directory") from e
            self._torch = True
            self.feature_dim = int(self._model.config.projection_dim)
            return
        try:
            from transformers import CLIPProcessor, FlaxCLIPModel

            self._model = FlaxCLIPModel.from_pretrained(
                model_name_or_path, local_files_only=True)
            self._processor = CLIPProcessor.from_pretrained(
                model_name_or_path, local_files_only=True)
            self._flax = True
        except (ImportError, OSError, EnvironmentError) as e:
            logging.getLogger("maskclustering_tpu").warning(
                "Flax CLIP load failed (%s); falling back to torch CPU", e)
            from transformers import CLIPModel, CLIPProcessor

            self._model = CLIPModel.from_pretrained(
                model_name_or_path, local_files_only=True)
            self._processor = CLIPProcessor.from_pretrained(
                model_name_or_path, local_files_only=True)
            self._torch = True
        self.feature_dim = int(self._model.config.projection_dim)

    def encode_images(self, images: Sequence[np.ndarray]) -> np.ndarray:
        inputs = self._processor(images=list(images), return_tensors="np"
                                 if self._flax else "pt")
        if self._flax:
            feats = np.asarray(self._model.get_image_features(**inputs))
        else:
            import torch

            with torch.no_grad():
                feats = self._model.get_image_features(**inputs).numpy()
        return l2_normalize(feats.astype(np.float32))

    def encode_texts(self, texts: Sequence[str]) -> np.ndarray:
        inputs = self._processor(text=list(texts), return_tensors="np"
                                 if self._flax else "pt", padding=True)
        if self._flax:
            feats = np.asarray(self._model.get_text_features(**inputs))
        else:
            import torch

            with torch.no_grad():
                feats = self._model.get_text_features(**inputs).numpy()
        return l2_normalize(feats.astype(np.float32))


class PrecomputedFeatures:
    """Feature store backed by the reference's npy artifacts.

    ``open-vocabulary_features.npy`` maps ``"{frame_id}_{mask_id}"`` to a
    feature vector (reference get_open-voc_features.py:143-149);
    ``data/text_features/<dataset>.npy`` maps label text to a feature
    (extract_label_featrues.py:22-26).
    """

    def __init__(self, path: str):
        self._dict = np.load(path, allow_pickle=True).item()
        if not self._dict:
            raise ValueError(f"feature store {path} is empty")
        first = next(iter(self._dict.values()))
        self.feature_dim = int(np.asarray(first).shape[-1])

    def __contains__(self, key: str) -> bool:
        return key in self._dict

    def get(self, key: str) -> Optional[np.ndarray]:
        v = self._dict.get(key)
        return None if v is None else np.asarray(v, dtype=np.float32)

    def keys(self):
        return self._dict.keys()
