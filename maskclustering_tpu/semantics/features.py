"""Per-mask open-vocabulary feature extraction and pooling.

Pipeline parity with reference semantics/get_open-voc_features.py:109-149:
gather the representative masks of every object from ``object_dict.npy``, crop
each at 3 scales, encode with CLIP, L2-normalize, and average the scales into
one feature per (frame, mask). Artifact contract is identical:
``<object_dict_dir>/<config>/open-vocabulary_features.npy`` maps
``"{frame_id}_{mask_id}"`` to a (D,) float vector.

TPU-first difference: scale pooling is one reshaped jnp mean over the whole
batch rather than a per-item Python loop, and image decoding is a thread pool
(the reference uses a torch DataLoader with 16 workers purely for I/O).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from maskclustering_tpu.semantics.crops import CROP_SCALES, multiscale_crops
from maskclustering_tpu.semantics.encoder import ImageEncoder


def pool_scale_features(features: np.ndarray, num_scales: int = CROP_SCALES) -> np.ndarray:
    """(B*S, D) per-crop features -> (B, D) per-mask features.

    Features arrive L2-normalized; the mask feature is their plain mean over
    scales (reference get_open-voc_features.py:140-143 — NOT re-normalized).
    """
    b = features.shape[0] // num_scales
    f = jnp.asarray(features).reshape(b, num_scales, -1)
    return np.asarray(jnp.mean(f, axis=1))


def representative_mask_index(object_dict: Dict) -> List[Tuple[str, int]]:
    """Unique (frame_id, mask_id) pairs over all objects' representative masks."""
    seen = []
    seen_set = set()
    for value in object_dict.values():
        for mask_info in value.get("repre_mask_list", []):
            key = (mask_info[0], int(mask_info[1]))
            if key not in seen_set:
                seen_set.add(key)
                seen.append(key)
    return seen


def extract_mask_features(
    dataset,
    object_dict: Dict,
    encoder: ImageEncoder,
    *,
    batch_size: int = 64,
    io_workers: int = 16,
) -> Dict[str, np.ndarray]:
    """Feature dict ``"{frame}_{mask}" -> (D,)`` for all representative masks.

    ``dataset`` provides ``get_frame_path(frame_id) -> (rgb_path, seg_path)``
    (duck type, reference dataset/scannet.py:76-80).
    """
    pairs = representative_mask_index(object_dict)
    if not pairs:
        return {}

    def load_crops(pair):  # mct-thread: root (pool.map dispatches this on io_workers threads)
        frame_id, mask_id = pair
        rgb_path, seg_path = dataset.get_frame_path(frame_id)
        rgb = _imread_rgb(rgb_path)
        seg = _imread_raw(seg_path)
        return multiscale_crops(rgb, seg == mask_id)

    out: Dict[str, np.ndarray] = {}
    with ThreadPoolExecutor(max_workers=io_workers) as pool:
        for start in range(0, len(pairs), batch_size):
            chunk = pairs[start:start + batch_size]
            crops_per_mask = list(pool.map(load_crops, chunk))
            flat = [c for crops in crops_per_mask for c in crops]
            feats = encoder.encode_images(flat)
            pooled = pool_scale_features(feats)
            for (frame_id, mask_id), feat in zip(chunk, pooled):
                out[f"{frame_id}_{mask_id}"] = feat
    return out


def save_mask_features(features: Dict[str, np.ndarray], object_dict_dir: str,
                       config_name: str) -> str:
    path = os.path.join(object_dict_dir, config_name, "open-vocabulary_features.npy")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    np.save(path, features, allow_pickle=True)
    return path


def extract_label_features(labels: Sequence[str], encoder, save_path: str) -> str:
    """Text features for a benchmark vocabulary (extract_label_featrues.py:15-26).

    Writes a dict label -> (D,) normalized feature; cached by the orchestrator
    if the file already exists (reference run.py:52-55).
    """
    feats = encoder.encode_texts(list(labels))
    os.makedirs(os.path.dirname(save_path) or ".", exist_ok=True)
    np.save(save_path, {label: feats[i] for i, label in enumerate(labels)},
            allow_pickle=True)
    return save_path


def _imread_rgb(path: str) -> np.ndarray:
    from maskclustering_tpu.io.image import read_rgb

    return read_rgb(path)


def _imread_raw(path: str) -> np.ndarray:
    from maskclustering_tpu.io.image import read_mask_png

    return read_mask_png(path)
