"""Open-vocabulary label assignment for clustered objects.

Parity with reference semantics/open-voc_query.py:8-55: each object's feature
is the mean of its representative masks' CLIP features; class probability is
``softmax(feature . text_features^T * 100)``; the argmax label id is written
into the final class-aware prediction npz.

TPU-first difference: the reference loops objects one by one with numpy dot
products; here every object's similarity against the full vocabulary is one
(O, D) x (D, L) jnp matmul with a batched softmax.
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

LOGIT_SCALE = 100.0  # reference open-voc_query.py:43


def object_features(object_dict: Dict, mask_features: Dict[str, np.ndarray],
                    feature_dim: int) -> Tuple[np.ndarray, np.ndarray]:
    """(O, D) object features = mean over representative-mask features.

    Objects with no representative masks (or all features missing) get a zero
    feature and valid=False; the reference leaves their class at 0
    (open-voc_query.py:33-35).
    """
    num = len(object_dict)
    feats = np.zeros((num, feature_dim), dtype=np.float32)
    valid = np.zeros(num, dtype=bool)
    for idx, value in enumerate(object_dict.values()):
        rows = [mask_features[f"{mi[0]}_{mi[1]}"]
                for mi in value.get("repre_mask_list", [])
                if f"{mi[0]}_{mi[1]}" in mask_features]
        if rows:
            feats[idx] = np.mean(np.stack(rows), axis=0)
            valid[idx] = True
    return feats, valid


def classify_objects(obj_feats: np.ndarray, text_feats: np.ndarray,
                     logit_scale: float = LOGIT_SCALE) -> np.ndarray:
    """(O,) vocabulary indices via softmax(sim * scale) argmax, one matmul.

    precision="highest": the TPU default (bf16 operands) carries ~1e-2
    relative error on unit-norm dots — enough to flip the argmax between
    close labels; the (O, D) x (D, L) matmul is tiny, full f32 is free.
    """
    sim = jnp.matmul(jnp.asarray(obj_feats), jnp.asarray(text_feats).T,
                     precision="highest")
    prob = jax.nn.softmax(sim * logit_scale, axis=-1)
    return np.asarray(jnp.argmax(prob, axis=-1))


def assign_labels(
    object_dict: Dict,
    mask_features: Dict[str, np.ndarray],
    label_features: Dict[str, np.ndarray],
    label_to_id: Dict[str, int],
    num_points: int,
) -> Dict[str, np.ndarray]:
    """Build the class-aware prediction dict (open-voc_query.py:23-53)."""
    descriptions = list(label_features.keys())
    text_feats = np.stack([np.asarray(label_features[d]) for d in descriptions])
    feature_dim = text_feats.shape[1]

    obj_feats, valid = object_features(object_dict, mask_features, feature_dim)
    classes = np.zeros(len(object_dict), dtype=np.int32)
    if valid.any():
        vocab_idx = classify_objects(obj_feats[valid], text_feats)
        ids = np.asarray([label_to_id[descriptions[i]] for i in vocab_idx],
                         dtype=np.int32)
        classes[valid] = ids

    pred_masks = np.zeros((num_points, len(object_dict)), dtype=bool)
    for idx, value in enumerate(object_dict.values()):
        if not valid[idx]:
            # objects with no representative-mask features keep an all-False
            # column (reference open-voc_query.py:33-35 `continue`s before
            # writing the mask); the evaluator then drops it as sub-minimum
            continue
        pred_masks[np.asarray(list(value["point_ids"]), dtype=np.int64), idx] = True
    return {
        "pred_masks": pred_masks,
        "pred_score": np.ones(len(object_dict)),
        "pred_classes": classes,
    }


def run_query(dataset, config_name: str, seq_name: str,
              prediction_root: str = "data/prediction") -> str:
    """File-level stage: object_dict + features npy -> class-aware npz."""
    num_points = dataset.get_scene_points().shape[0]
    object_dict = np.load(
        os.path.join(dataset.object_dict_dir, config_name, "object_dict.npy"),
        allow_pickle=True).item()
    mask_features = np.load(
        os.path.join(dataset.object_dict_dir, config_name,
                     "open-vocabulary_features.npy"),
        allow_pickle=True).item()
    label_features = dataset.get_label_features()
    label_to_id = dataset.get_label_id()[0]

    pred = assign_labels(object_dict, mask_features, label_features,
                         label_to_id, num_points)
    out_dir = os.path.join(prediction_root, config_name)
    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, f"{seq_name}.npz")
    np.savez(out_path, **pred)
    return out_path
