"""TASMap (OmniGibson sim) sequence loader.

ScanNet-like processed layout with 1024x1024 frames and string frame ids
taken from the color filenames (reference dataset/tasmap.py:7-34; the
reference hardcodes a /workspace root — here the root is data_root-relative
like every other dataset).
"""

from __future__ import annotations

import os
from typing import List

from maskclustering_tpu.datasets.scannet import ScanNetDataset


class TASMapDataset(ScanNetDataset):
    image_size = (1024, 1024)
    dataset_name = "tasmap"

    def __init__(self, seq_name: str, data_root: str = "./data") -> None:
        super().__init__(seq_name, data_root)
        self.root = os.path.join(data_root, "tasmap", "processed", seq_name)
        self.rgb_dir = os.path.join(self.root, "color")
        self.depth_dir = os.path.join(self.root, "depth")
        self.extrinsics_dir = os.path.join(self.root, "pose")
        self.intrinsic_path = os.path.join(self.root, "intrinsic", "intrinsic_depth.txt")
        self.point_cloud_path = os.path.join(self.root, f"{seq_name}_vh_clean_2.ply")

    def get_frame_list(self, stride: int) -> List[str]:
        names = sorted(os.listdir(self.rgb_dir), key=lambda x: int(x.split(".")[0]))
        return [n.split(".")[0] for n in names][::stride]
