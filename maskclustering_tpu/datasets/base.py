"""Dataset abstraction.

The reference implements the same loader surface five times with no base
class (reference dataset/{scannet,demo,tasmap,matterport,scannetpp}.py; the
duck type is enumerated in SURVEY.md §1). Here it is a real ABC, plus a
`load_scene_tensors` helper that materializes the dense, padded per-scene
tensor bundle the TPU pipeline consumes (static shapes for jit).
"""

from __future__ import annotations

import abc
import dataclasses
import os
from typing import Dict, List, Sequence, Tuple

import numpy as np


# Sentinel coordinate for point padding: far outside any indoor scan, so a
# padded point is never inside a frustum within depth_trunc and never
# claimed. estimate_spacing (models/backprojection.py) relies on sentinel
# distances exceeding PAD_DISTANCE_CUTOFF to exclude padding from its median.
PAD_COORD = 1.0e4
PAD_DISTANCE_CUTOFF = min(10.0, PAD_COORD / 100.0)


@dataclasses.dataclass
class SceneTensors:
    """Dense per-scene arrays handed to the jitted pipeline.

    All frames share one (H, W) image size; depth is metres; extrinsics are
    camera-to-world; frames with invalid (inf/nan) poses are masked out via
    `frame_valid` instead of being dropped (keeps shapes static).
    """

    scene_points: np.ndarray  # (N, 3) float32
    depths: np.ndarray  # (F, H, W) float32, metres
    segmentations: np.ndarray  # (F, H, W) int32 mask id-maps aligned with depth
    intrinsics: np.ndarray  # (F, 3, 3) float32
    cam_to_world: np.ndarray  # (F, 4, 4) float32
    frame_valid: np.ndarray  # (F,) bool
    frame_ids: List  # original per-dataset frame identifiers

    @property
    def num_points(self) -> int:
        return int(self.scene_points.shape[0])

    @property
    def num_frames(self) -> int:
        return int(self.depths.shape[0])


class BaseDataset(abc.ABC):
    """One posed RGB-D sequence plus its reconstructed point cloud."""

    seq_name: str
    root: str
    depth_scale: float
    image_size: Tuple[int, int]  # (width, height)

    # ---- per-frame accessors (reference duck-type surface) ----

    @abc.abstractmethod
    def get_frame_list(self, stride: int) -> List:
        ...

    @abc.abstractmethod
    def get_intrinsics(self, frame_id) -> np.ndarray:
        """(3,3) float intrinsic matrix at depth/image resolution."""

    @abc.abstractmethod
    def get_extrinsic(self, frame_id) -> np.ndarray:
        """(4,4) camera-to-world pose."""

    @abc.abstractmethod
    def get_depth(self, frame_id) -> np.ndarray:
        """(H,W) float32 depth in metres."""

    @abc.abstractmethod
    def get_rgb(self, frame_id) -> np.ndarray:
        ...

    @abc.abstractmethod
    def get_segmentation(self, frame_id, align_with_depth: bool = True) -> np.ndarray:
        """(H,W) integer mask id-map; 0 = background."""

    @abc.abstractmethod
    def get_scene_points(self) -> np.ndarray:
        """(N,3) reconstructed scene point cloud."""

    # ---- optional surface ----

    def get_frame_path(self, frame_id) -> Tuple[str, str]:
        raise NotImplementedError

    def get_label_features(self) -> Dict:
        """Open-vocab text features, {label: feature} (semantics stage)."""
        raise NotImplementedError

    def get_label_id(self) -> Tuple[Dict, Dict]:
        raise NotImplementedError

    # ---- dirs (artifact contract with the reference layout) ----

    @property
    def segmentation_dir(self) -> str:
        return os.path.join(self.root, "output", "mask")

    @property
    def object_dict_dir(self) -> str:
        return os.path.join(self.root, "output", "object")

    # ---- dense bundle for the TPU pipeline ----

    def load_scene_tensors(self, stride: int) -> SceneTensors:
        frame_ids = self.get_frame_list(stride)
        depths, segs, intrs, poses, valid = [], [], [], [], []
        for fid in frame_ids:
            pose = np.asarray(self.get_extrinsic(fid), dtype=np.float64)
            ok = np.isfinite(pose).all()
            valid.append(bool(ok))
            poses.append(pose if ok else np.eye(4))
            depths.append(self.get_depth(fid))
            segs.append(np.asarray(self.get_segmentation(fid, align_with_depth=True), dtype=np.int32))
            intrs.append(np.asarray(self.get_intrinsics(fid), dtype=np.float32))
        return SceneTensors(
            scene_points=np.asarray(self.get_scene_points(), dtype=np.float32),
            depths=np.stack(depths).astype(np.float32),
            segmentations=np.stack(segs),
            intrinsics=np.stack(intrs).astype(np.float32),
            cam_to_world=np.stack(poses).astype(np.float32),
            frame_valid=np.asarray(valid, dtype=bool),
            frame_ids=list(frame_ids),
        )


def make_label_maps(labels: Sequence[str], ids: Sequence[int]) -> Tuple[Dict, Dict]:
    label2id = dict(zip(labels, ids))
    id2label = {v: k for k, v in label2id.items()}
    return label2id, id2label
