"""ScanNet++ (iPhone) sequence loader.

File contract follows reference dataset/scannetpp.py:113-217: COLMAP text
models (iphone/colmap/cameras.txt + images.txt) supply one shared pinhole
intrinsic and per-frame world-to-camera poses (quaternion + translation,
inverted to camera-to-world); frames are named frame_%06d; the scene cloud
is the x0.25-downsampled ``pcld_0.25/<seq>.pth`` tensor's sampled_coords.
"""

from __future__ import annotations

import os
from typing import Dict, List

import numpy as np

from maskclustering_tpu.datasets.base import BaseDataset, make_label_maps
from maskclustering_tpu.io import read_depth_png, read_mask_png, read_rgb, resize_nearest
from maskclustering_tpu.semantics.vocab import get_vocab


def quaternion_to_rotation(q: np.ndarray) -> np.ndarray:
    """COLMAP-convention (w, x, y, z) unit quaternion to rotation matrix."""
    w, x, y, z = q
    return np.array([
        [1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y)],
        [2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x)],
        [2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y)],
    ])


def read_colmap_cameras(path: str) -> Dict[int, dict]:
    """COLMAP cameras.txt -> {camera_id: {model, width, height, params}}."""
    cams = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            t = line.split()
            cams[int(t[0])] = {
                "model": t[1],
                "width": int(t[2]),
                "height": int(t[3]),
                "params": np.array([float(x) for x in t[4:]]),
            }
    return cams


def read_colmap_images(path: str) -> Dict[int, dict]:
    """COLMAP images.txt -> {image_id: {qvec, tvec, camera_id, name}}.

    Every image record is two lines; the second (2D point observations) is
    skipped.
    """
    images = {}
    with open(path) as f:
        lines = iter(f)
        for line in lines:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            t = line.split()
            images[int(t[0])] = {
                "qvec": np.array([float(x) for x in t[1:5]]),
                "tvec": np.array([float(x) for x in t[5:8]]),
                "camera_id": int(t[8]),
                "name": t[9],
            }
            next(lines, None)  # skip the observations line
    return images


def colmap_intrinsics(cam: dict) -> np.ndarray:
    model, p = cam["model"], cam["params"]
    k = np.eye(3)
    if model in ("SIMPLE_PINHOLE", "SIMPLE_RADIAL", "RADIAL",
                 "SIMPLE_RADIAL_FISHEYE", "RADIAL_FISHEYE"):
        k[0, 0] = k[1, 1] = p[0]
        k[0, 2], k[1, 2] = p[1], p[2]
    elif model in ("PINHOLE", "OPENCV", "OPENCV_FISHEYE", "FULL_OPENCV",
                   "FOV", "THIN_PRISM_FISHEYE"):
        k[0, 0], k[1, 1] = p[0], p[1]
        k[0, 2], k[1, 2] = p[2], p[3]
    else:
        raise NotImplementedError(f"COLMAP camera model {model}")
    return k


class ScanNetPPDataset(BaseDataset):
    depth_scale = 1000.0
    image_size = (1920, 1440)
    dataset_name = "scannetpp"

    def __init__(self, seq_name: str, data_root: str = "./data") -> None:
        self.seq_name = seq_name
        self.root = os.path.join(data_root, "scannetpp", "data", seq_name)
        self.rgb_dir = os.path.join(self.root, "iphone", "rgb")
        self.depth_dir = os.path.join(self.root, "iphone", "render_depth")
        self.point_cloud_path = os.path.join(data_root, "scannetpp", "pcld_0.25", f"{seq_name}.pth")
        self.data_root = data_root

        colmap_dir = os.path.join(self.root, "iphone", "colmap")
        cameras = read_colmap_cameras(os.path.join(colmap_dir, "cameras.txt"))
        images = read_colmap_images(os.path.join(colmap_dir, "images.txt"))
        k = colmap_intrinsics(next(iter(cameras.values())))

        self.frame_id_list: List[int] = []
        self._extrinsics: Dict[int, np.ndarray] = {}
        self._intrinsics: Dict[int, np.ndarray] = {}
        for image in images.values():
            # names are frame_%06d.jpg -> integer frame id
            frame_id = int(os.path.splitext(image["name"])[0].split("_")[1])
            w2c = np.eye(4)
            w2c[:3, :3] = quaternion_to_rotation(image["qvec"])
            w2c[:3, 3] = image["tvec"]
            self.frame_id_list.append(frame_id)
            self._extrinsics[frame_id] = np.linalg.inv(w2c)
            self._intrinsics[frame_id] = k

    def get_frame_list(self, stride: int) -> List[int]:
        return self.frame_id_list[::stride]

    def get_intrinsics(self, frame_id) -> np.ndarray:
        return self._intrinsics[frame_id]

    def get_extrinsic(self, frame_id) -> np.ndarray:
        return self._extrinsics[frame_id]

    def get_depth(self, frame_id) -> np.ndarray:
        return read_depth_png(os.path.join(self.depth_dir, f"frame_{frame_id:06d}.png"),
                              self.depth_scale)

    def get_rgb(self, frame_id) -> np.ndarray:
        return read_rgb(os.path.join(self.rgb_dir, f"frame_{frame_id:06d}.jpg"))

    def get_segmentation(self, frame_id, align_with_depth: bool = True) -> np.ndarray:
        seg = read_mask_png(os.path.join(self.segmentation_dir, f"frame_{frame_id:06d}.png"))
        if align_with_depth:
            seg = resize_nearest(seg, self.image_size)
        return seg

    def get_frame_path(self, frame_id):
        return (
            os.path.join(self.rgb_dir, f"frame_{frame_id:06d}.jpg"),
            os.path.join(self.segmentation_dir, f"frame_{frame_id:06d}.png"),
        )

    def get_scene_points(self) -> np.ndarray:
        import torch  # CPU torch: only used to read the .pth artifact

        data = torch.load(self.point_cloud_path, map_location="cpu", weights_only=False)
        return np.asarray(data["sampled_coords"])

    def get_label_features(self):
        path = os.path.join(self.data_root, "text_features", "scannetpp.npy")
        return np.load(path, allow_pickle=True).item()

    def get_label_id(self):
        labels, ids = get_vocab("scannetpp")
        return make_label_maps(labels, ids)
