"""Matterport3D sequence loader.

File contract follows reference dataset/matterport.py:7-137: per-scene
``undistorted_camera_parameters/<seq>.conf`` carries per-camera intrinsics
(each shared by 6 scan directions) and per-frame GL-convention extrinsics
(columns 1,2 negated to OpenCV), depth PNGs at 0.25 mm/unit, and the
``house_segmentations/<seq>.ply`` cloud. Frame ids are indices into the
name arrays parsed from the .conf.
"""

from __future__ import annotations

import os
from typing import List

import numpy as np

from maskclustering_tpu.datasets.base import BaseDataset, make_label_maps
from maskclustering_tpu.io import read_depth_png, read_mask_png, read_ply_points, read_rgb, resize_nearest
from maskclustering_tpu.semantics.vocab import get_vocab


def parse_matterport_conf(path: str):
    """Parse a Matterport .conf: returns (rgb_names, depth_names,
    intrinsics (F,3,3), extrinsics (F,4,4) camera-to-world, OpenCV axes)."""
    intrinsics: List[np.ndarray] = []
    extrinsics: List[np.ndarray] = []
    rgb_names: List[str] = []
    depth_names: List[str] = []
    current_k = None
    with open(path) as f:
        for line in f:
            tokens = line.split()
            if not tokens:
                continue
            if tokens[0] == "intrinsics_matrix":
                vals = [float(t) for t in tokens[1:] if t]
                if len(vals) != 9:
                    raise ValueError(f"bad intrinsics_matrix line in {path}: {line!r}")
                current_k = np.asarray(vals).reshape(3, 3)
            elif tokens[0] == "scan":
                if current_k is None:
                    raise ValueError(f"scan line before intrinsics_matrix in {path}")
                depth_names.append(tokens[1])
                rgb_names.append(tokens[2])
                vals = [float(t) for t in tokens[3:] if t]
                if len(vals) != 16:
                    raise ValueError(f"bad scan line in {path}: {line!r}")
                ext = np.asarray(vals).reshape(4, 4)
                ext[:3, 1] *= -1.0  # GL -> CV: flip y and z columns
                ext[:3, 2] *= -1.0
                intrinsics.append(current_k)
                extrinsics.append(ext)
    return (
        rgb_names,
        depth_names,
        np.stack(intrinsics) if intrinsics else np.zeros((0, 3, 3)),
        np.stack(extrinsics) if extrinsics else np.zeros((0, 4, 4)),
    )


class MatterportDataset(BaseDataset):
    depth_scale = 4000.0  # 0.25 mm per unit
    image_size = (1280, 1024)
    dataset_name = "matterport3d"

    def __init__(self, seq_name: str, data_root: str = "./data") -> None:
        self.seq_name = seq_name
        self.root = os.path.join(data_root, "matterport3d", "scans", seq_name, seq_name)
        self.rgb_dir = os.path.join(self.root, "undistorted_color_images")
        self.depth_dir = os.path.join(self.root, "undistorted_depth_images")
        self.point_cloud_path = os.path.join(self.root, "house_segmentations", f"{seq_name}.ply")
        self.data_root = data_root
        conf = os.path.join(self.root, "undistorted_camera_parameters", f"{seq_name}.conf")
        self.rgb_names, self.depth_names, self._intrinsics, self._extrinsics = \
            parse_matterport_conf(conf)

    def get_frame_list(self, stride: int) -> List[int]:
        return [int(i) for i in np.arange(0, len(self.rgb_names), stride)]

    def get_intrinsics(self, frame_id) -> np.ndarray:
        return self._intrinsics[frame_id]

    def get_extrinsic(self, frame_id) -> np.ndarray:
        return self._extrinsics[frame_id]

    def get_depth(self, frame_id) -> np.ndarray:
        return read_depth_png(os.path.join(self.depth_dir, self.depth_names[frame_id]),
                              self.depth_scale)

    def get_rgb(self, frame_id) -> np.ndarray:
        return read_rgb(os.path.join(self.rgb_dir, self.rgb_names[frame_id]))

    def get_segmentation(self, frame_id, align_with_depth: bool = True) -> np.ndarray:
        stem = os.path.splitext(self.rgb_names[frame_id])[0]
        seg = read_mask_png(os.path.join(self.segmentation_dir, f"{stem}.png"))
        if align_with_depth:
            seg = resize_nearest(seg, self.image_size)
        return seg

    def get_frame_path(self, frame_id):
        stem = os.path.splitext(self.rgb_names[frame_id])[0]
        return (
            os.path.join(self.rgb_dir, self.rgb_names[frame_id]),
            os.path.join(self.segmentation_dir, f"{stem}.png"),
        )

    def get_scene_points(self) -> np.ndarray:
        return read_ply_points(self.point_cloud_path)

    def get_label_features(self):
        path = os.path.join(self.data_root, "text_features", "matterport3d.npy")
        return np.load(path, allow_pickle=True).item()

    def get_label_id(self):
        labels, ids = get_vocab("matterport3d")
        return make_label_maps(labels, ids)
