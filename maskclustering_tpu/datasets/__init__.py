"""Dataset registry.

Mirrors the reference's factory (utils/config.py:28-42) but with registered
classes instead of an if/elif chain.
"""

from __future__ import annotations

from typing import Callable, Dict

from maskclustering_tpu.datasets.base import BaseDataset, SceneTensors

_REGISTRY: Dict[str, Callable[..., BaseDataset]] = {}


def register_dataset(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        return cls

    return deco


def get_dataset(dataset: str, seq_name: str, data_root: str = "./data") -> BaseDataset:
    # lazy imports keep optional deps (cv2 etc.) out of library import time
    if not _REGISTRY:
        _populate()
    if dataset not in _REGISTRY:
        raise KeyError(f"unknown dataset {dataset!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[dataset](seq_name, data_root=data_root)


def _populate():
    from maskclustering_tpu.datasets.matterport import MatterportDataset
    from maskclustering_tpu.datasets.scannet import DemoDataset, ScanNetDataset
    from maskclustering_tpu.datasets.scannetpp import ScanNetPPDataset
    from maskclustering_tpu.datasets.tasmap import TASMapDataset

    _REGISTRY.setdefault("scannet", ScanNetDataset)
    _REGISTRY.setdefault("demo", DemoDataset)
    _REGISTRY.setdefault("scannetpp", ScanNetPPDataset)
    _REGISTRY.setdefault("matterport3d", MatterportDataset)
    _REGISTRY.setdefault("tasmap", TASMapDataset)


__all__ = ["BaseDataset", "SceneTensors", "get_dataset", "register_dataset"]
