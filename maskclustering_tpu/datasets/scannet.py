"""ScanNet (and demo) sequence loaders.

File-format contract follows reference dataset/scannet.py:7-103 and
dataset/demo.py — processed dirs with color/, depth/, pose/, intrinsic/,
output/mask id-map PNGs, and a `<seq>_vh_clean_2.ply` scene cloud.
"""

from __future__ import annotations

import os
from typing import List

import numpy as np

from maskclustering_tpu.datasets.base import BaseDataset, make_label_maps
from maskclustering_tpu.io import read_depth_png, read_mask_png, read_ply_points, read_rgb, resize_nearest
from maskclustering_tpu.semantics.vocab import get_vocab


class ScanNetDataset(BaseDataset):
    depth_scale = 1000.0
    dataset_name = "scannet"

    def __init__(self, seq_name: str, data_root: str = "./data") -> None:
        self.seq_name = seq_name
        self.root = os.path.join(data_root, "scannet", "processed", seq_name)
        self.rgb_dir = os.path.join(self.root, "color")
        self.depth_dir = os.path.join(self.root, "depth")
        self.extrinsics_dir = os.path.join(self.root, "pose")
        self.intrinsic_path = os.path.join(self.root, "intrinsic", "intrinsic_depth.txt")
        self.point_cloud_path = os.path.join(self.root, f"{seq_name}_vh_clean_2.ply")
        self.data_root = data_root
        self._intrinsics_cache = None
        self._image_size = None

    @property
    def image_size(self):
        """(width, height) of the depth stream — the alignment target for
        segmentations (reference hardcodes 640x480, dataset/scannet.py:15;
        deriving it from the data keeps non-standard resolutions working)."""
        if self._image_size is None:
            from PIL import Image

            names = sorted(f for f in os.listdir(self.depth_dir)
                           if f.split(".")[0].isdigit()) \
                if os.path.isdir(self.depth_dir) else []
            if not names:
                return (640, 480)
            with Image.open(os.path.join(self.depth_dir, names[0])) as im:
                self._image_size = im.size  # PIL size is (width, height)
        return self._image_size

    # frame ids are integers 0..last, subsampled by stride; the id space is
    # defined by the numerically-largest color image (reference scannet.py:25-31)
    def get_frame_list(self, stride: int) -> List[int]:
        names = [f for f in os.listdir(self.rgb_dir) if f.split(".")[0].isdigit()]
        if not names:
            return []
        end = max(int(f.split(".")[0]) for f in names) + 1
        return [int(i) for i in np.arange(0, end, stride)]

    def get_intrinsics(self, frame_id) -> np.ndarray:
        if self._intrinsics_cache is None:
            m = np.loadtxt(self.intrinsic_path)
            self._intrinsics_cache = np.asarray(m[:3, :3], dtype=np.float64)
        return self._intrinsics_cache

    def get_extrinsic(self, frame_id) -> np.ndarray:
        return np.loadtxt(os.path.join(self.extrinsics_dir, f"{frame_id}.txt"))

    def get_depth(self, frame_id) -> np.ndarray:
        return read_depth_png(os.path.join(self.depth_dir, f"{frame_id}.png"), self.depth_scale)

    def get_rgb(self, frame_id) -> np.ndarray:
        return read_rgb(os.path.join(self.rgb_dir, f"{frame_id}.jpg"))

    def get_segmentation(self, frame_id, align_with_depth: bool = True) -> np.ndarray:
        seg = read_mask_png(os.path.join(self.segmentation_dir, f"{frame_id}.png"))
        if align_with_depth:
            seg = resize_nearest(seg, self.image_size)
        return seg

    def get_frame_path(self, frame_id):
        return (
            os.path.join(self.rgb_dir, f"{frame_id}.jpg"),
            os.path.join(self.segmentation_dir, f"{frame_id}.png"),
        )

    def get_scene_points(self) -> np.ndarray:
        return read_ply_points(self.point_cloud_path)

    def get_label_features(self):
        path = os.path.join(self.data_root, "text_features", "scannet.npy")
        return np.load(path, allow_pickle=True).item()

    def get_label_id(self):
        labels, ids = get_vocab("scannet")
        return make_label_maps(labels, ids)


class DemoDataset(ScanNetDataset):
    """Demo scene layout: 640px color dir + its own intrinsics file
    (reference dataset/demo.py:12,34)."""

    dataset_name = "demo"

    def __init__(self, seq_name: str, data_root: str = "./data") -> None:
        super().__init__(seq_name, data_root)
        self.root = os.path.join(data_root, "demo", seq_name)
        self.rgb_dir = os.path.join(self.root, "color_640")
        self.depth_dir = os.path.join(self.root, "depth")
        self.extrinsics_dir = os.path.join(self.root, "pose")
        # demo layout keeps intrinsics at the scene root (reference dataset/demo.py:34)
        self.intrinsic_path = os.path.join(self.root, "intrinsic_640.txt")
        self.point_cloud_path = os.path.join(self.root, f"{seq_name}_vh_clean_2.ply")
