"""Span-triggered jax.profiler trace capture (xprof).

Whole-run profiler traces at bench scale are huge and usually wasted: the
question is almost always "what does ONE clustering step / ONE post.claims
dispatch look like on the device timeline". This module arms trace capture
from the span tracer instead: when a span whose name matches the armed set
opens, ``jax.profiler.start_trace`` begins; when that same span closes, the
trace stops and flushes to ``<dir>/<span-name>-<k>``. Rules:

- **bounded**: at most ``limit`` captures per span name (default 1) — a
  311-scene run must not write 311 traces;
- **non-reentrant**: a capture owns the profiler until its span closes;
  nested/overlapping armed spans do not start a second trace (jax has one
  global profiler session);
- **best-effort**: start/stop failures log once and disarm — profiling
  must never sink the run it profiles (same posture as the event sink).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Optional, Sequence

log = logging.getLogger("maskclustering_tpu")


class XprofArm:
    """Armed capture state; consulted by Span.__enter__/__exit__."""

    def __init__(self, trace_dir: str, spans: Sequence[str], *,
                 limit: int = 1):
        self.trace_dir = trace_dir
        # "*" arms every span — useful for one-shot smoke captures
        self.spans = frozenset(spans)
        self.limit = max(int(limit), 1)
        self.captured: Dict[str, int] = {}
        self.active_span: Optional[str] = None
        self.dead = False

    def _matches(self, name: str) -> bool:
        return "*" in self.spans or name in self.spans

    def maybe_start(self, name: str) -> bool:
        """Start a trace for this span; True iff this span now owns it."""
        if self.dead or self.active_span is not None or not self._matches(name):
            return False
        if self.captured.get(name, 0) >= self.limit:
            return False
        k = self.captured.get(name, 0)
        out = os.path.join(self.trace_dir, f"{name.replace('/', '_')}-{k}")
        try:
            import jax.profiler

            os.makedirs(out, exist_ok=True)
            jax.profiler.start_trace(out)
        except Exception:  # noqa: BLE001 — never sink the run being profiled
            log.exception("xprof: start_trace failed; disarming (%s)", out)
            self.dead = True
            return False
        self.active_span = name
        self.captured[name] = k + 1
        log.info("xprof: capturing span %r -> %s", name, out)
        return True

    def stop(self, name: str) -> None:
        """Stop the trace this span owns (no-op for non-owners)."""
        if self.active_span != name:
            return
        self.active_span = None
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — a flush failure must not mask
            # the span body's real exception
            log.exception("xprof: stop_trace failed; disarming")
            self.dead = True

    def close(self) -> None:
        """Disarm; stops a trace left open by a crashed span body."""
        if self.active_span is not None:
            self.stop(self.active_span)
        self.dead = True


def parse_spans(spec: str) -> Sequence[str]:
    """CLI form: comma-joined span names, e.g. ``cluster,post.claims.kernel``
    (``*`` = every span)."""
    return tuple(s for s in spec.split(",") if s)
