"""Always-on in-process flight recorder: the serving plane's black box.

Every process that executes scenes keeps a small bounded ring of the
last ~N observability events — finished spans, compile/retrace events,
fault-seam firings, admission decisions, heartbeat ages, queue
transitions, crash bookkeeping — in memory, always, whether or not an
events file is armed. The ring costs one deque append under a named
lock per event; nothing is written anywhere until something goes wrong.

When something DOES go wrong the ring is dumped crash-safely (atomic
tmp+rename, schema-versioned JSONL readable by the shared torn-line
reader) so the postmortem survives the process that caused it:

- **watchdog fire**: ``utils/faults.py`` dumps at the
  ``DeviceStallError`` raise sites (``call_with_deadline`` /
  ``Heartbeat.check``) — the wedge evidence is on disk before anyone
  decides what to do about the wedge;
- **capacity error**: the daemon dumps on the first ``QueueFullReject``
  per process — what the admission plane looked like when backpressure
  began;
- **SIGTERM**: dumped on the cooperative drain path (the handler itself
  is flag-only async-signal-safe and must not do IO — CONC.SIGNAL);
- **heartbeat-silence SIGKILL** — the hard case: the child that wedged
  cannot dump anything, so the PR-12 supervisor dumps its OWN ring plus
  the child's last relayed flight delta (shipped on the heartbeat
  cadence, not the result-driven telemetry relay) — the victim
  request's child-side spans the live relay never shipped survive.

Dumps land in ``$MCT_FLIGHT_DIR`` (or an explicitly armed directory);
with neither set, ``dump()`` is a counted no-op — the recorder is never
the failure source. ``python -m maskclustering_tpu.obs.flight DUMP``
renders the postmortem; ``obs.trace REQUEST_ID --blackbox DUMP`` merges
ring events into the causal timeline.

Span ring records use the event sink's span shape (``kind`` "span",
``name``/``dur_s``/``sync_s``/``attrs``) so the trace merger treats
them exactly like live events; everything else uses ``flight.*`` kinds
that can never collide with the sink vocabulary.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from maskclustering_tpu.analysis.lock_sanitizer import mct_lock

log = logging.getLogger("maskclustering_tpu")

FLIGHT_SCHEMA_VERSION = 1
DEFAULT_CAPACITY = 256
ENV_DIR = "MCT_FLIGHT_DIR"

# ring/dump event kinds (plus "span", shared with the event sink)
KIND_META = "flight_meta"          # dump header line
KIND_ADMIT = "flight.admission"    # admit / reject / dequeue / requeue / drain
KIND_FAULT = "flight.fault"        # fault-seam firing / watchdog expiry
KIND_CRASH = "flight.crash"        # worker death bookkeeping (parent side)
KIND_HB = "flight.heartbeat"       # heartbeat age observations
KIND_COMPILE = "flight.compile"    # compile/retrace events
KIND_REQUEST = "flight.request"    # request lifecycle marks (child side)
KIND_SIGNAL = "flight.signal"      # stop/drain transitions
KIND_CHILD_TELEM = "flight.child_telem"  # last relayed child metrics delta
# supervisor<->worker pipe line carrying a child ring delta (NOT a ring
# event kind): {"kind": KIND_DELTA, "rows": [...], "pid": ...} shipped by
# worker_main's heartbeat thread, retained parent-side for the SIGKILL dump
KIND_DELTA = "flight_delta"


class FlightRecorder:
    """Bounded ring + crash-safe dumper; one instance per process.

    ``record()`` is the hot path: build the event dict, append under the
    named lock, nothing else — no IO, no allocation beyond the dict, no
    calls into other locked subsystems while holding the lock. ``dump()``
    snapshots the ring under the lock and writes OUTSIDE it.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = mct_lock("obs.FlightRecorder._lock")
        self._ring: deque = deque(maxlen=max(int(capacity), 8))
        self._seq = 0          # total events ever recorded (ring evicts)
        self._dumps = 0
        self._dir: Optional[str] = None
        self._dump_failed = False  # log the first write failure only

    # -- arming ------------------------------------------------------------

    def arm(self, dir_path: Optional[str]) -> None:
        with self._lock:
            self._dir = dir_path

    def armed_dir(self) -> Optional[str]:
        """The dump directory: explicit arm wins, else $MCT_FLIGHT_DIR."""
        with self._lock:
            if self._dir:
                return self._dir
        return os.environ.get(ENV_DIR) or None

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        ev: Dict = {"kind": kind, "ts": time.time()}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)

    def record_span(self, name: str, dur_s: float, sync_s: float,
                    attrs: Optional[Dict]) -> None:
        """A finished span, in the event sink's span shape (obs/events.py)
        so dump rows merge into ``obs.trace`` untranslated."""
        ev: Dict = {"kind": "span", "ts": time.time(),
                    "name": name, "dur_s": round(float(dur_s), 6),
                    "sync_s": round(float(sync_s), 6)}
        if attrs:
            ev["attrs"] = dict(attrs)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)

    # -- reading -----------------------------------------------------------

    def snapshot(self, since_seq: int = 0) -> Tuple[List[Dict], int]:
        """(events newer than ``since_seq``, newest seq) — the delta shape
        the child heartbeat ships to the supervisor."""
        with self._lock:
            evs = [dict(e) for e in self._ring if e.get("seq", 0) > since_seq]
            return evs, self._seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- dumping -----------------------------------------------------------

    def dump(self, reason: str, *, path: Optional[str] = None,
             extra_rows: Optional[List[Dict]] = None) -> Optional[str]:
        """Write the ring (plus ``extra_rows``) crash-safely; returns the
        dump path, or None when unarmed or on write failure — the
        recorder must never become the failure source of the failure it
        is recording."""
        events, _seq = self.snapshot()
        target = path
        if target is None:
            d = self.armed_dir()
            if not d:
                return None
            with self._lock:
                self._dumps += 1
                n = self._dumps
            target = os.path.join(
                d, f"flight-{os.getpid()}-{n:02d}-{reason}.jsonl")
        pid = os.getpid()
        header = {"v": FLIGHT_SCHEMA_VERSION, "kind": KIND_META,
                  "ts": time.time(), "pid": pid, "reason": reason,
                  "events": len(events) + len(extra_rows or ())}
        tmp = target + ".tmp"
        try:
            d = os.path.dirname(target)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(json.dumps(header) + "\n")
                for ev in events:
                    row = {"v": FLIGHT_SCHEMA_VERSION, "pid": pid}
                    row.update(ev)
                    f.write(json.dumps(row) + "\n")
                for ev in extra_rows or ():
                    row = {"v": FLIGHT_SCHEMA_VERSION}
                    row.update(ev)
                    f.write(json.dumps(row) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, target)  # atomic: readers see all or nothing
        except Exception:  # noqa: BLE001 — postmortems must not cascade
            if not self._dump_failed:
                self._dump_failed = True
                log.exception("flight dump failed; postmortem dropped (%s)",
                              target)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        log.warning("flight recorder dumped %d event(s) [%s] -> %s",
                    header["events"], reason, target)
        return target


_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def record(kind: str, **fields) -> None:
    _RECORDER.record(kind, **fields)


def record_span(name: str, dur_s: float, sync_s: float,
                attrs: Optional[Dict]) -> None:
    _RECORDER.record_span(name, dur_s, sync_s, attrs)


def arm(dir_path: Optional[str]) -> None:
    _RECORDER.arm(dir_path)


def armed_dir() -> Optional[str]:
    return _RECORDER.armed_dir()


def dump(reason: str, *, path: Optional[str] = None,
         extra_rows: Optional[List[Dict]] = None) -> Optional[str]:
    return _RECORDER.dump(reason, path=path, extra_rows=extra_rows)


# ---------------------------------------------------------------------------
# reading + rendering (the postmortem CLI)
# ---------------------------------------------------------------------------


def resolve_dump(path: str) -> Optional[str]:
    """A dump file, or — given a directory — its newest flight-*.jsonl."""
    if os.path.isdir(path):
        cands = sorted(
            (os.path.join(path, n) for n in os.listdir(path)
             if n.startswith("flight-") and n.endswith(".jsonl")),
            key=lambda p: os.path.getmtime(p))
        return cands[-1] if cands else None
    return path if os.path.exists(path) else None


def read_dump(path: str) -> Tuple[Dict, List[Dict]]:
    """(header meta, event rows) — shared torn-line read policy."""
    from maskclustering_tpu.obs.events import iter_jsonl_rows

    meta: Dict = {}
    rows: List[Dict] = []
    for row in iter_jsonl_rows(path, version=FLIGHT_SCHEMA_VERSION):
        if row.get("kind") == KIND_META and not meta:
            meta = row
        else:
            rows.append(row)
    return meta, rows


def _age(ts, ref) -> str:
    try:
        return f"{max(ref - float(ts), 0.0):8.3f}s"
    except (TypeError, ValueError):
        return "       ?"


def render_dump(meta: Dict, rows: List[Dict],
                request: Optional[str] = None) -> str:
    """The human postmortem: header, then the ring oldest-first with ages
    relative to the dump instant; ``request`` filters to one request's
    rows (span attrs / lifecycle marks / crash bookkeeping)."""
    ref = float(meta.get("ts") or (rows[-1].get("ts") if rows else 0.0) or 0.0)
    when = time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime(ref)) if ref else "?"
    out = [f"== flight postmortem: reason={meta.get('reason', '?')} "
           f"pid={meta.get('pid', '?')} at {when} UTC "
           f"({len(rows)} event(s)) =="]
    shown = 0
    for ev in rows:
        kind = ev.get("kind", "?")
        rid = None
        if kind == "span":
            attrs = ev.get("attrs") or {}
            rid = attrs.get("request")
            body = (f"span {ev.get('name')} dur {ev.get('dur_s')}s"
                    + (f" sync {ev['sync_s']}s" if ev.get("sync_s") else "")
                    + (f" [{' '.join(f'{k}={v}' for k, v in attrs.items())}]"
                       if attrs else ""))
        elif kind == KIND_CHILD_TELEM:
            body = (f"child telem delta (pid {ev.get('pid', '?')}): "
                    f"{len((ev.get('doc') or {}).get('counters') or {})} "
                    f"counter(s), "
                    f"{len((ev.get('doc') or {}).get('spans') or [])} span(s)")
        else:
            rid = ev.get("request")
            body = kind.replace("flight.", "") + " " + " ".join(
                f"{k}={v}" for k, v in ev.items()
                if k not in ("kind", "ts", "seq", "v", "pid"))
        if request is not None and rid != request:
            continue
        shown += 1
        src = f"pid {ev.get('pid', '?')}"
        out.append(f"-{_age(ev.get('ts'), ref)}  [{src}] {body.rstrip()}")
    if request is not None:
        out.append(f"({shown} event(s) for request {request})")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m maskclustering_tpu.obs.flight",
        description="render a flight-recorder postmortem dump")
    p.add_argument("dump", help="dump file, or a directory holding "
                                "flight-*.jsonl (newest wins)")
    p.add_argument("--request", default=None,
                   help="filter to one request id's events")
    p.add_argument("--json", action="store_true",
                   help="emit {meta, events} instead of the rendering")
    args = p.parse_args(argv)
    path = resolve_dump(args.dump)
    if path is None:
        print(f"flight: no dump at {args.dump}", file=sys.stderr)
        return 1
    meta, rows = read_dump(path)
    if args.json:
        print(json.dumps({"meta": meta, "events": rows}, indent=2))
    else:
        print(render_dump(meta, rows, request=args.request))
    return 0 if rows else 1


if __name__ == "__main__":
    sys.exit(main())
