"""Run-report CLI over obs JSONL event files + cost/ledger sections.

    python -m maskclustering_tpu.obs.report events.jsonl
    python -m maskclustering_tpu.obs.report new.jsonl --diff old.jsonl
    python -m maskclustering_tpu.obs.report --cost            # live CPU AOT
    python -m maskclustering_tpu.obs.report events.jsonl --cost  # from events
    python -m maskclustering_tpu.obs.report --history         # PERF_LEDGER
    python -m maskclustering_tpu.obs.report --regress BASELINE  # CI gate

Renders per-stage span tables — count, p50/p95 wall, device (fenced sync)
vs host split, per-stage host<->device bytes, HBM high-water — and diffs
two runs stage by stage. ``--cost`` renders the compile-time cost
observatory (obs/cost.py): per-(stage, mesh) collective census, ICI bytes
vs v5e bandwidth, FLOPs/HBM rooflines and the XLA memory plan, computed
entirely on CPU virtual devices. ``--history``/``--regress`` read the perf
regression ledger (obs/ledger.py): the bench trajectory as data, with a
non-zero exit when the newest headline p50 regresses past the threshold.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import Dict, List, Optional

from maskclustering_tpu.obs.events import (KIND_ANALYSIS, KIND_COST,
                                           KIND_DRIFT, KIND_METRICS,
                                           KIND_SPAN, KIND_TELEMETRY,
                                           ReadStats, read_events)

log = logging.getLogger("maskclustering_tpu")


# the disjoint per-stage spans whose total duration is the overlap-ratio
# numerator: the IO loads (daemon threads), the device-phase stages (main
# thread) and the host-tail post-process (worker thread). Parent container
# spans (exec.device / exec.host_tail) and nested post.* children are
# deliberately excluded — they would double-count their contents.
OVERLAP_STAGE_SPANS = ("exec.load", "associate", "graph", "cluster",
                       "postprocess")


class RunData:
    """Parsed event file: ordered span series + final metrics snapshot."""

    def __init__(self, path: str):
        self.path = path
        self.meta: Dict = {}
        self.spans: Dict[str, List[Dict]] = {}  # name -> span events, in order
        self.order: List[str] = []
        self.cost_rows: List[Dict] = []  # cost-observatory events, in order
        self.analysis_rows: List[Dict] = []  # mct-check findings/summaries
        self.telemetry_rows: List[Dict] = []  # windowed serving snapshots
        self.drift_rows: List[Dict] = []  # mct-sentinel canary drift events
        self.hbm_high_water: Optional[float] = None
        self.read_stats = ReadStats()  # torn/unknown lines: counted, warned
        metrics_by_pid: Dict = {}  # counters are monotonic PER PROCESS:
        # keep each pid's last flush, then sum counters across pids (one
        # file can hold several worker attempts plus the supervisor)
        for ev in read_events(path, stats=self.read_stats):
            kind = ev.get("kind")
            if kind == "meta" and not self.meta:
                self.meta = {k: v for k, v in ev.items()
                             if k not in ("v", "kind", "ts", "pid")}
            elif kind == KIND_SPAN:
                name = ev.get("name")
                if not isinstance(name, str):
                    continue
                if name not in self.spans:
                    self.spans[name] = []
                    self.order.append(name)
                self.spans[name].append(ev)
                mem = ev.get("mem") or {}
                in_use = mem.get("bytes_in_use")
                if in_use is not None and (self.hbm_high_water is None
                                           or in_use > self.hbm_high_water):
                    self.hbm_high_water = float(in_use)
            elif kind == KIND_COST:
                self.cost_rows.append(ev)
            elif kind == KIND_ANALYSIS:
                self.analysis_rows.append(ev)
            elif kind == KIND_TELEMETRY:
                self.telemetry_rows.append(ev)
            elif kind == KIND_DRIFT:
                self.drift_rows.append(ev)
            elif kind == KIND_METRICS:
                metrics_by_pid[ev.get("pid")] = ev.get("metrics") or {}
        if self.read_stats.skipped:
            log.warning("obs report: skipped %s in %s",
                        self.read_stats.describe(), path)
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, Dict] = {}
        for m in metrics_by_pid.values():
            for k, v in (m.get("counters") or {}).items():
                counters[k] = counters.get(k, 0.0) + v
            for k, v in (m.get("gauges") or {}).items():
                # max across processes: correct for the high-water/bucket
                # gauges this subsystem emits (all are "largest seen" style)
                if k not in gauges or v > gauges[k]:
                    gauges[k] = v
            for k, h in (m.get("histograms") or {}).items():
                # bounded summaries only (count/total/p50/p95/max): counts
                # and totals sum exactly across processes; percentiles
                # cannot merge, so the largest-count process's stand for
                # the merged view (one process dominates in practice)
                if not isinstance(h, dict):
                    continue
                cur = hists.get(k)
                if cur is None:
                    hists[k] = dict(h)
                    continue
                bigger = h if (h.get("count") or 0) > (cur.get("count") or 0) \
                    else cur
                merged = dict(bigger)
                merged["count"] = (cur.get("count") or 0) + (h.get("count") or 0)
                merged["total"] = (cur.get("total") or 0.0) \
                    + (h.get("total") or 0.0)
                maxes = [x.get("max") for x in (cur, h)
                         if isinstance(x.get("max"), (int, float))]
                merged["max"] = max(maxes) if maxes else bigger.get("max")
                hists[k] = merged
        hw = gauges.get("hbm.high_water_bytes")
        if hw is not None and (self.hbm_high_water is None
                               or hw > self.hbm_high_water):
            self.hbm_high_water = float(hw)
        self._counters = counters
        self._gauges = gauges
        self._histograms = hists

    def stage_rows(self) -> List[Dict]:
        """One aggregate row per span name, in first-appearance order."""
        rows = []
        for name in self.order:
            evs = self.spans[name]
            durs = sorted(float(e.get("dur_s", 0.0)) for e in evs)
            syncs = sorted(float(e.get("sync_s", 0.0)) for e in evs)
            rows.append({
                "stage": name,
                "count": len(evs),
                "total_s": sum(durs),
                "p50_s": _pct(durs, 50),
                "p95_s": _pct(durs, 95),
                "device_p50_s": _pct(syncs, 50),
                "host_p50_s": max(_pct(durs, 50) - _pct(syncs, 50), 0.0),
                "h2d_bytes": self._counters.get(f"h2d.bytes.{name}"),
                "d2h_bytes": self._counters.get(f"d2h.bytes.{name}"),
            })
        return rows

    def overlap(self) -> Optional[Dict]:
        """Scene-loop overlap accounting, or None without an executor span.

        ``ratio`` = sum of per-stage span time / scene-loop wall time. A
        fully serialized loop sits at <= 1.0 (stages plus orchestration
        overhead fill the wall exactly once); every point above 1.0 is
        stage work that ran CONCURRENTLY — loads under device dispatch,
        host tails under the next scene's device phase. The denominator is
        the ``exec.scene_loop`` span the executor wraps around the whole
        queue (summed, for multi-step runs)."""
        loops = self.spans.get("exec.scene_loop")
        if not loops:
            return None
        wall = sum(float(e.get("dur_s", 0.0)) for e in loops)
        stages: Dict[str, float] = {}
        busy = 0.0
        for name in OVERLAP_STAGE_SPANS:
            tot = sum(float(e.get("dur_s", 0.0))
                      for e in self.spans.get(name, ()))
            if tot:
                stages[name] = round(tot, 4)
            busy += tot
        return {
            "mode": (loops[-1].get("attrs") or {}).get("mode"),
            "scene_loop_s": round(wall, 4),
            "busy_s": round(busy, 4),
            "ratio": round(busy / wall, 4) if wall > 0 else None,
            "stages": stages,
        }

    def summary(self) -> Dict:
        """JSON-able digest for embedding (run_report.json / bench line)."""
        out = {
            "events": self.path,
            "stages": {r["stage"]: {"count": r["count"],
                                    "p50_s": round(r["p50_s"], 4),
                                    "p95_s": round(r["p95_s"], 4),
                                    "device_p50_s": round(r["device_p50_s"], 4)}
                       for r in self.stage_rows()},
            "hbm_high_water_bytes": self.hbm_high_water,
            "h2d_bytes": self._counters.get("h2d.bytes"),
            "d2h_bytes": self._counters.get("d2h.bytes"),
            "counters": {k: v for k, v in sorted(self._counters.items())
                         if k.startswith(("run.", "bench.", "compile_cache.",
                                          "pipeline.", "faults.",
                                          "retrace.", "serve.", "stream.",
                                          "aot_cache.", "worker."))},
            # the registry's bounded histogram summaries (metrics.py
            # snapshot contract): span.* series are already covered by the
            # stage table above, so only the non-span histograms (queue
            # waits, future explicit observe() series) ride the digest
            "histograms": {
                k: {f: (round(x, 6) if isinstance(x, float) else x)
                    for f, x in v.items()}
                for k, v in sorted(self._histograms.items())
                if not k.startswith("span.")},
        }
        ov = self.overlap()
        if ov is not None:
            out["overlap"] = ov
        return out


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an ascending list — THE one quantile
    rule every surface shares (stage tables here, the serve worker's
    latency digest, load_gen's verdict), so p50/p95 cannot silently
    disagree between the report, the daemon and the ledger row."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q / 100.0 * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


_pct = percentile  # internal alias (stage tables predate the public name)


def _fmt_s(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:.3f}"


def _fmt_bytes(v: Optional[float]) -> str:
    if v is None:
        return "-"
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(v) < 1024 or unit == "TB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024.0
    return "-"  # unreachable


def _render(headers: List[str], rows: List[List[str]]) -> str:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    fmt_row = lambda cells: "  ".join(c.ljust(w) if i == 0 else c.rjust(w)  # noqa: E731
                                      for i, (c, w) in enumerate(zip(cells, widths)))
    lines = [fmt_row(headers), fmt_row(["-" * w for w in widths])]
    lines += [fmt_row(r) for r in rows]
    return "\n".join(lines)


def render_faults(counters: Dict[str, float]) -> Optional[str]:
    """The Faults section: retry/stall/degradation/injection accounting.

    Rendered only when the run recorded any fault activity — a clean run's
    report stays exactly as it was. Sources are the supervisor counters
    (run.scene_retries / run.device_stalls / run.journal_skips), the
    degradation ladder (run.degradations.<rung>) and the deterministic
    fault-injection harness (faults.injected.<seam>).
    """
    retries = int(counters.get("run.scene_retries", 0))
    stalls = int(counters.get("run.device_stalls", 0))
    skips = int(counters.get("run.journal_skips", 0))
    failed = int(counters.get("run.scenes_failed", 0))
    abandoned = int(counters.get("run.abandoned_results", 0))
    degr = {k[len("run.degradations."):]: int(v)
            for k, v in sorted(counters.items())
            if k.startswith("run.degradations.")}
    inj = {k[len("faults.injected."):]: int(v)
           for k, v in sorted(counters.items())
           if k.startswith("faults.injected.")}
    # the lock sanitizer's digest (lock_sanitizer.emit_counters, armed
    # runs only): acquisition volume, distinct nesting edges, long holds
    lock_acq = int(counters.get("locks.acquisitions", 0))
    if not (retries or stalls or skips or failed or abandoned or degr
            or inj or lock_acq):
        # `failed` matters alone: a terminal-class error is never retried,
        # so it can be the ONLY fault signal of the run
        return None
    lines = ["== faults ==",
             f"scene retries {retries} | device stalls {stalls} | "
             f"journal skips {skips} | scenes failed {failed}"
             + (f" | abandoned results {abandoned}" if abandoned else "")]
    if degr:
        lines.append("degradations: " + ", ".join(
            f"{name} x{n}" for name, n in degr.items()))
    if inj:
        lines.append("injected (fault plan): " + ", ".join(
            f"{seam} x{n}" for seam, n in inj.items()))
    if lock_acq:
        lines.append(
            f"lock sanitizer: {lock_acq} acquisition(s) | "
            f"{int(counters.get('locks.order_edges', 0))} order edge(s) | "
            f"{int(counters.get('locks.long_holds', 0))} long hold(s)")
    return "\n".join(lines)


def latest_analysis_run(rows: List[Dict]) -> tuple:
    """(finding rows, summary row|None) of the newest mct-check run.

    The analysis CLI appends one event per finding then one summary row
    per invocation; a shared events file holds several runs, and only the
    newest one describes the current tree. Findings are keyed to their
    summary by PID: a run killed before its summary (the 90 s CI
    timeout) leaves orphan rows that must not be attributed to the NEXT
    invocation — a clean summary rendered above a dead run's findings
    would contradict itself. Trailing rows after the last summary are a
    newer in-flight/crashed run and render summary-less.
    """
    runs: List[tuple] = []
    pending: Dict = {}  # pid -> finding rows not yet closed by a summary
    tail: List[Dict] = []  # rows appended after the newest summary
    for ev in rows:
        if ev.get("summary"):
            runs.append((pending.pop(ev.get("pid"), []), ev))
            tail = []
        else:
            pending.setdefault(ev.get("pid"), []).append(ev)
            tail.append(ev)
    if tail or not runs:
        return tail, None
    return runs[-1]


def render_analysis(rows: List[Dict]) -> Optional[str]:
    """The Analysis section: the newest mct-check run's findings.

    Rendered only when the events file carries ``analysis`` events (the
    mct-check CLI with ``--events``); a plain run report is unchanged.
    """
    findings, summary = latest_analysis_run(rows)
    if not findings and summary is None:
        return None
    out = ["== analysis (mct-check) =="]
    if summary is not None:
        state = "clean" if summary.get("clean") else "FINDINGS"
        out.append(
            f"{state}: {summary.get('findings', 0)} unsuppressed | "
            f"{summary.get('suppressed', 0)} suppressed | "
            f"{summary.get('stale', 0)} stale suppression(s) "
            f"({summary.get('elapsed_s', '?')}s, "
            f"families {'+'.join(summary.get('families') or [])})")
    table = []
    for ev in findings:
        if ev.get("suppressed"):
            continue  # the gate cares about unsuppressed ones
        loc = ev.get("file") or "<ir>"
        if ev.get("line"):
            loc = f"{loc}:{ev['line']}"
        table.append([str(ev.get("check", "?")), loc,
                      str(ev.get("message", ""))[:72]])
    if table:
        out.append(_render(["check", "location", "finding"], table))
    elif summary is not None and summary.get("suppressed"):
        out.append("(all findings baseline-suppressed)")
    return "\n".join(out)


def render_serving(run: "RunData") -> Optional[str]:
    """The Serving section: the daemon's admission/latency/warmth digest.

    Rendered only when the events file carries ``serve.*`` metrics (a
    daemon run with ``--obs_events``); batch run reports are unchanged.
    Sources: the admission counters (``serve.admission.*``,
    ``serve.requests*``), the queue/latency gauges the daemon books at
    shutdown (``emit_serve_counters``), the ``serve.request`` span series
    (per-request p50/p95 — preferred over the gauges when present), and
    the retrace sanitizer's post-freeze count as "compiles post-warm-up"
    (the serve-many contract's headline number: a warm daemon reads 0).
    """
    c, g = run._counters, run._gauges
    if not any(k.startswith("serve.") for k in list(c) + list(g)):
        return None
    requests = int(c.get("serve.requests", 0))
    by_status = {s: int(c.get(f"serve.requests_{s}", 0))
                 for s in ("ok", "failed", "deadline", "skipped",
                           "interrupted")}
    rejects = {k[len("serve.admission.rejects."):]: int(v)
               for k, v in sorted(c.items())
               if k.startswith("serve.admission.rejects.")}
    if c.get("serve.rejects.deadline"):
        rejects["deadline"] = (rejects.get("deadline", 0)
                               + int(c["serve.rejects.deadline"]))
    lines = ["== serving (mct-serve) =="]
    lines.append(
        f"requests {requests} | "
        + " | ".join(f"{s} {n}" for s, n in by_status.items() if n)
        + (f" | warm-up scenes {int(c['serve.warmup_scenes'])}"
           if c.get("serve.warmup_scenes") else ""))
    depth_hw = g.get("serve.queue_depth_high_water")
    admitted = c.get("serve.admission.admitted")
    lines.append(
        f"admission: {int(admitted or 0)} admitted | queue high-water "
        f"{int(depth_hw or 0)}"
        + (" | rejects: " + ", ".join(f"{r} x{n}"
                                      for r, n in rejects.items())
           if rejects else " | rejects: none"))
    # per-request latency: the span series is exact; the shutdown gauges
    # are the fallback when a digest-only file has no spans
    p50 = p95 = None
    for r in run.stage_rows():
        if r["stage"] == "serve.request":
            p50, p95 = r["p50_s"], r["p95_s"]
            break
    if p50 is None:
        p50, p95 = g.get("serve.request_p50_s"), g.get("serve.request_p95_s")
    if p50 is not None:
        lines.append(f"request latency: p50 {_fmt_s(p50)} | p95 {_fmt_s(p95)}")
    # crash containment (serve/supervisor.py): worker subprocess deaths,
    # respawns and requeues — zero lines on an in-thread (or untroubled)
    # daemon, loud attribution on a supervised one
    crashes = int(c.get("serve.worker_crashes", 0))
    respawns = int(c.get("serve.worker_respawns", 0))
    requeued = int(c.get("serve.requests_requeued", 0))
    if crashes or respawns:
        line = (f"worker crashes {crashes} | respawns {respawns} | "
                f"requests requeued {requeued}")
        if c.get("serve.worker_fatal"):
            line += " | FATAL: respawn budget exhausted"
        lines.append(line)
    # persistent AOT cache (utils/aot_cache.py): warm-start economics —
    # how much of this process's warmth was paid from disk
    aot = {k: int(c.get(f"aot_cache.{k}", 0))
           for k in ("restored", "hits", "misses", "stores", "invalidated")}
    if any(aot.values()):
        line = (f"aot cache: {aot['restored']} restored | "
                f"{aot['hits']} hit(s) | {aot['misses']} miss(es) | "
                f"{aot['stores']} captured")
        if aot["invalidated"]:
            line += (f" | {aot['invalidated']} invalidated "
                     f"[version-stamp mismatch — prune or recapture]")
        lines.append(line)
    post_warm = c.get("retrace.post_freeze_compiles")
    cold = int(c.get("serve.buckets_cold", 0))
    warm_n = g.get("serve.warm_buckets")
    tail = []
    if warm_n is not None:
        tail.append(f"warm buckets {int(warm_n)}")
    if cold:
        tail.append(f"cold bucket dispatches {cold}")
    if c.get("retrace.cache_hits"):
        tail.append(f"compile-cache hits {int(c['retrace.cache_hits'])}")
    tail.append(f"compiles post-warm-up: "
                f"{int(post_warm) if post_warm is not None else 0}"
                + (" [VIOLATION — the serve-many contract broke]"
                   if post_warm else ""))
    lines.append(" | ".join(tail))
    tele = render_telemetry_windows(run.telemetry_rows)
    if tele:
        lines.append(tele)
    tenants = render_tenants(run.telemetry_rows)
    if tenants:
        lines.extend(tenants)
    pool = render_pool(run)
    if pool:
        lines.extend(pool)
    return "\n".join(lines)


def render_pool(run: "RunData") -> List[str]:
    """Worker-pool digest: scheduler counters (``serve.pool.*``) plus
    per-worker completion shares summed from the telemetry windows'
    ``workers`` maps and per-tenant dequeue shares from their tenant
    sub-rows. Empty list when the run never carved a pool (no pool
    counters AND no window carried a workers map) — single-worker
    reports are unchanged."""
    c = run._counters
    by_worker: Dict[str, int] = {}
    for r in run.telemetry_rows or ():
        for wid, n in (r.get("workers") or {}).items():
            by_worker[wid] = by_worker.get(wid, 0) + int(n or 0)
    pool_counters = any(k.startswith("serve.pool.") for k in c)
    if not pool_counters and not by_worker:
        return []
    out: List[str] = []
    hits = int(c.get("serve.pool.affinity_hits", 0))
    misses = int(c.get("serve.pool.affinity_misses", 0))
    routed = hits + misses
    line = (f"pool: dispatched {int(c.get('serve.pool.dispatched', 0))} | "
            f"affinity {hits}/{routed} warm")
    if routed:
        line += f" ({hits / routed:.0%})"
    if c.get("serve.pool.crash_reroutes"):
        line += f" | crash reroutes {int(c['serve.pool.crash_reroutes'])}"
    if c.get("serve.pool.workers_retired"):
        line += f" | workers retired {int(c['serve.pool.workers_retired'])}"
    if c.get("serve.pool.recarves"):
        line += f" | recarves {int(c['serve.pool.recarves'])}"
    out.append(line)
    total = sum(by_worker.values())
    for wid in sorted(by_worker, key=lambda w: (len(w), w)):
        n = by_worker[wid]
        share = f" ({n / total:.0%})" if total else ""
        out.append(f"  worker {wid}: completions {n}{share}")
    # dequeue share by tenant: what the weighted-fair scheduler actually
    # granted, from the same windows (requests completed per tenant)
    by_tenant: Dict[str, int] = {}
    for r in run.telemetry_rows or ():
        for name, t in (r.get("tenants") or {}).items():
            by_tenant[name] = (by_tenant.get(name, 0)
                               + int(t.get("requests", 0) or 0))
    t_total = sum(by_tenant.values())
    if by_tenant and t_total:
        out.append("  dequeue share: " + " | ".join(
            f"{name} {n} ({n / t_total:.0%})"
            for name, n in sorted(by_tenant.items())))
    return out


def render_tenants(rows: List[Dict]) -> List[str]:
    """Per-tenant accounting digest summed over the telemetry windows
    (empty list when no window carried the tenant dimension)."""
    agg: Dict[str, Dict] = {}
    for r in rows or ():
        for name, t in (r.get("tenants") or {}).items():
            a = agg.setdefault(name, {"requests": 0, "rejects": 0,
                                      "crashes": 0, "device_s": 0.0,
                                      "d2h_bytes": 0})
            a["requests"] += int(t.get("requests", 0) or 0)
            a["rejects"] += int(t.get("rejects", 0) or 0)
            a["crashes"] += int(t.get("crashes", 0) or 0)
            a["device_s"] += float(t.get("device_s", 0.0) or 0.0)
            a["d2h_bytes"] += int(t.get("d2h_bytes", 0) or 0)
    if not agg:
        return []
    out = ["tenants:"]
    for name in sorted(agg):
        a = agg[name]
        out.append(f"  {name:<16} requests {a['requests']} | "
                   f"rejects {a['rejects']} | crashes {a['crashes']} | "
                   f"device {a['device_s']:.3f}s | d2h {a['d2h_bytes']}B")
    return out


def render_slo(run: "RunData", spec_path: Optional[str] = None) \
        -> Optional[str]:
    """The SLO section: the spec's burn-rate verdict over the telemetry
    window rows the events file carries (None without any serve/telemetry
    evidence — batch reports are unchanged)."""
    if not run.telemetry_rows:
        return None
    from maskclustering_tpu.obs import slo as slo_mod

    spec = slo_mod.load_spec(spec_path)
    result = slo_mod.evaluate(spec, {"windows": run.telemetry_rows})
    return "\n".join(["== SLO =="] + slo_mod.render_result(result))


def render_correctness(run: "RunData") -> Optional[str]:
    """The Correctness section (mct-sentinel): canary probe volume, the
    drift matrix per coordinate, and last-verified recency per bucket.

    Rendered only when the events carry canary evidence (``canary.*``
    counters or ``canary.drift`` rows) — batch reports are unchanged. A
    clean section is one line; a drifted one names every coordinate whose
    outputs stopped matching the committed goldens, which fields moved,
    and when the coordinate was last verified clean.
    """
    c = run._counters
    probes = int(c.get("canary.probes", 0))
    drift = int(c.get("canary.drift", 0))
    if not probes and not drift and not run.drift_rows:
        return None
    lines = ["== correctness (mct-sentinel) =="]
    head = f"canary probes {probes} | drift {drift}"
    skipped = int(c.get("canary.skipped_busy", 0))
    if skipped:
        head += f" | ticks skipped busy {skipped}"
    head += (" [DRIFT — outputs diverged from committed goldens]"
             if drift or run.drift_rows
             else " | every probe byte-identical to goldens")
    lines.append(head)
    # the drift matrix: coordinate -> occurrence count + moved fields +
    # when this run last saw the coordinate clean (ok windows carry no
    # event, so recency comes from the telemetry ring's clean windows)
    by_coord: Dict[str, Dict] = {}
    for ev in run.drift_rows:
        coord = str(ev.get("coord") or "?")
        row = by_coord.setdefault(coord, {"n": 0, "fields": set(),
                                          "scene": ev.get("scene"),
                                          "first_ts": ev.get("ts")})
        row["n"] += 1
        for f in ev.get("fields") or ():
            row["fields"].add(str(f))
    last_clean_ts = None
    for r in run.telemetry_rows:
        if int(r.get("canary_probes", 0) or 0) \
                and not int(r.get("drift", 0) or 0):
            ts = r.get("ts")
            if ts is not None and (last_clean_ts is None
                                   or ts > last_clean_ts):
                last_clean_ts = ts
    for coord in sorted(by_coord):
        row = by_coord[coord]
        line = (f"  DRIFT {coord} (scene {row['scene']}): x{row['n']} | "
                f"fields {','.join(sorted(row['fields'])) or '?'}")
        if last_clean_ts is not None and row["first_ts"] is not None:
            line += (f" | last verified clean "
                     f"{max(row['first_ts'] - last_clean_ts, 0.0):.1f}s "
                     f"before first drift")
        lines.append(line)
    return "\n".join(lines)


def render_telemetry_windows(rows: List[Dict]) -> Optional[str]:
    """One-line digest of the windowed telemetry ring (obs/telemetry.py
    rows the daemon's ticker appended): window count, request volume,
    peak queue depth across windows, and the busiest window's worst
    per-bucket p95 — the live-view numbers, durable on disk."""
    if not rows:
        return None
    requests = sum(int(r.get("requests", 0) or 0) for r in rows)
    peak_depth = max((int(r.get("queue_depth", 0) or 0) for r in rows),
                     default=0)
    crashes = sum(int(r.get("crashes", 0) or 0) for r in rows)
    post_warm = sum(int(r.get("post_warm_compiles", 0) or 0) for r in rows)
    p95 = None
    for r in rows:
        for h in (r.get("latency") or {}).values():
            v = (h or {}).get("p95_s")
            if v is not None and (p95 is None or v > p95):
                p95 = v
    line = (f"telemetry: {len(rows)} window(s) | {requests} request(s) | "
            f"peak queue depth {peak_depth}")
    if p95 is not None:
        line += f" | worst window p95 {_fmt_s(p95)}"
    if crashes:
        line += f" | crashes {crashes}"
    if post_warm:
        line += f" | post-warm compiles {post_warm} [VIOLATION]"
    return line


def render_retrace(counters: Dict[str, float]) -> Optional[str]:
    """The retrace-sanitizer digest line (armed runs only): compile events
    vs new shape buckets, with violations called out. Lives in the
    Analysis section — the sanitizer is the retrace family's dynamic
    half, so its verdict renders next to mct-check's."""
    compiles = counters.get("retrace.compiles")
    if compiles is None:
        return None
    line = (f"retrace sanitizer: {int(compiles)} compile(s) | "
            f"{int(counters.get('retrace.distinct_programs', 0))} "
            f"program(s) | "
            f"{int(counters.get('retrace.buckets_new', 0))} new bucket(s)")
    hits = int(counters.get("retrace.cache_hits", 0))
    restores = int(counters.get("retrace.aot_restores", 0))
    if hits or restores:
        # warm-start economics: events the persistent caches served are
        # not compiles (compiles above already excludes them)
        line += f" | {hits} cache hit(s), {restores} aot restore(s)"
    repeats = int(counters.get("retrace.repeat_compiles", 0))
    frozen = int(counters.get("retrace.post_freeze_compiles", 0))
    if repeats or frozen:
        line += (f" | VIOLATIONS: {repeats} repeat, {frozen} post-warm — "
                 f"the serve-many contract broke")
    return line


def render_streaming(run: "RunData") -> Optional[str]:
    """The Streaming section: latency-per-chunk + residency digest.

    Rendered only when the events carry ``stream.*`` metrics (a
    ``--streaming-chunk`` run or a daemon serving ``stream_chunk`` ops);
    batch reports are unchanged. Chunk p50/p95 come from the
    ``stream.chunk`` span series; frames/s sustained is total streamed
    frames over total chunk wall — the live-scan SLO number — and the
    two high-water gauges pin the headline residency claim: only one
    chunk's claim planes (``stream.max_plane_bytes``) plus the O(M^2)
    accumulator (``stream.state_bytes``) are ever resident.
    """
    c, g = run._counters, run._gauges
    chunks = int(c.get("stream.chunks", 0))
    if not chunks:
        return None
    lines = ["== streaming (chunked accumulation) =="]
    frames = int(c.get("stream.frames", 0))
    p50 = p95 = total = None
    for r in run.stage_rows():
        if r["stage"] == "stream.chunk":
            p50, p95, total = r["p50_s"], r["p95_s"], r["total_s"]
            break
    line = (f"chunks {chunks} | frames {frames} | "
            f"re-clusters {int(c.get('stream.reclusters', 0))}")
    if c.get("stream.chunk_retries"):
        line += f" | chunk retries {int(c['stream.chunk_retries'])}"
    if c.get("stream.state_resumes"):
        line += f" | journal resumes {int(c['stream.state_resumes'])}"
    if c.get("stream.mask_capacity_growths"):
        line += (f" | mask-capacity growths "
                 f"{int(c['stream.mask_capacity_growths'])}")
    lines.append(line)
    if p50 is not None:
        sustained = frames / total if total else None
        lines.append(
            f"chunk latency: p50 {_fmt_s(p50)} | p95 {_fmt_s(p95)}"
            + (f" | {sustained:.1f} frames/s sustained"
               if sustained else ""))
    plane = g.get("stream.max_plane_bytes")
    state = g.get("stream.state_bytes")
    if plane is not None or state is not None:
        lines.append(
            f"residency high-water: chunk planes {_fmt_bytes(plane)} | "
            f"accumulator state {_fmt_bytes(state)}")
    partials = g.get("stream.partial_instances")
    if partials is not None:
        lines.append(f"partial instances (last chunk): {int(partials)}")
    return "\n".join(lines)


def render_report(run: RunData, slo_spec: Optional[str] = None) -> str:
    rows = [[r["stage"], str(r["count"]), _fmt_s(r["p50_s"]), _fmt_s(r["p95_s"]),
             _fmt_s(r["device_p50_s"]), _fmt_s(r["host_p50_s"]),
             _fmt_s(r["total_s"]), _fmt_bytes(r["h2d_bytes"]),
             _fmt_bytes(r["d2h_bytes"])]
            for r in run.stage_rows()]
    out = [f"== obs report: {run.path} =="]
    if run.read_stats.skipped:
        out.append(f"WARNING: skipped {run.read_stats.describe()}")
    if run.meta:
        out.append("meta: " + json.dumps(run.meta, sort_keys=True))
    out.append(_render(
        ["stage", "n", "p50[s]", "p95[s]", "dev.p50[s]", "host.p50[s]",
         "total[s]", "h2d", "d2h"], rows))
    ov = run.overlap()
    if ov is not None and ov.get("ratio") is not None:
        parts = " | ".join(f"{k} {v:.2f}s" for k, v in ov["stages"].items())
        out.append(f"scene overlap [{ov.get('mode') or '?'}]: "
                   f"ratio {ov['ratio']:.2f}x = stage time {ov['busy_s']:.2f}s"
                   f" / loop wall {ov['scene_loop_s']:.2f}s  ({parts})")
    tail = []
    if run.hbm_high_water is not None:
        tail.append(f"HBM high-water: {_fmt_bytes(run.hbm_high_water)}")
    for d in ("h2d", "d2h"):
        total = run._counters.get(f"{d}.bytes")
        if total is not None:
            tail.append(f"{d} total: {_fmt_bytes(total)}")
    hits = {k: v for k, v in run._counters.items()
            if k.startswith("compile_cache.")}
    if hits:
        tail.append("compile cache: " + ", ".join(
            f"{k.split('.', 1)[1]}={int(v)}" for k, v in sorted(hits.items())))
    if tail:
        out.append(" | ".join(tail))
    faults_sec = render_faults(run._counters)
    if faults_sec:
        out.append(faults_sec)
    serving_sec = render_serving(run)
    if serving_sec:
        out.append(serving_sec)
    slo_sec = render_slo(run, slo_spec)
    if slo_sec:
        out.append(slo_sec)
    correctness_sec = render_correctness(run)
    if correctness_sec:
        out.append(correctness_sec)
    streaming_sec = render_streaming(run)
    if streaming_sec:
        out.append(streaming_sec)
    analysis_sec = render_analysis(run.analysis_rows)
    retrace_line = render_retrace(run._counters)
    if analysis_sec:
        out.append(analysis_sec + ("\n" + retrace_line if retrace_line
                                   else ""))
    elif retrace_line:
        out.append("== analysis (retrace sanitizer) ==\n" + retrace_line)
    return "\n".join(out)


def render_diff(run_a: RunData, run_b: RunData) -> str:
    """Stage-by-stage p50 diff: A (the file argument) vs B (--diff)."""
    rows_a = {r["stage"]: r for r in run_a.stage_rows()}
    rows_b = {r["stage"]: r for r in run_b.stage_rows()}
    names = list(run_a.order) + [n for n in run_b.order if n not in rows_a]
    rows = []
    for name in names:
        a, b = rows_a.get(name), rows_b.get(name)
        pa = a["p50_s"] if a else None
        pb = b["p50_s"] if b else None
        if pa is not None and pb is not None and pb > 0:
            delta = f"{100.0 * (pa - pb) / pb:+.1f}%"
        else:
            delta = "-"
        rows.append([name, _fmt_s(pa), _fmt_s(pb), delta])
    head = [f"== obs diff: A={run_a.path}  B={run_b.path} =="]
    return "\n".join(head + [_render(["stage", "A p50[s]", "B p50[s]", "A vs B"],
                                     rows)])


# ---------------------------------------------------------------------------
# cost observatory section (--cost)
# ---------------------------------------------------------------------------

# compact per-collective column labels for the census table
_COLL_SHORT = (("all-gather", "ag"), ("all-reduce", "ar"),
               ("reduce-scatter", "rs"), ("collective-permute", "cp"),
               ("all-to-all", "a2a"), ("collective-broadcast", "cb"))


def render_cost(rows: List[Dict]) -> str:
    """Per-mesh tables of the cost-observatory rows (obs/cost.py).

    One table per mesh config: stage rooflines (FLOPs, HBM bytes, XLA
    memory plan peak), the collective census with payload bytes, fusion /
    copy / transpose counts, and v5e-context lines — estimated ICI
    microseconds at v5e link rate so "how much cross-chip talk" has units
    a bench reader can compare with the 3.21 s/scene headline.
    """
    from maskclustering_tpu.obs.cost import V5E_HBM_GBPS, V5E_ICI_GBPS

    if not rows:
        return "== cost observatory: no cost events =="
    by_mesh: Dict[tuple, List[Dict]] = {}
    for r in rows:
        by_mesh.setdefault(tuple(r.get("mesh") or ()), []).append(r)
    out: List[str] = []
    for mesh, mesh_rows in by_mesh.items():
        fp = mesh_rows[0].get("fingerprint") or {}
        label = (f"scene={mesh[0]} x frame={mesh[1]}" if len(mesh) == 2
                 else str(mesh))
        out.append(f"== cost observatory: mesh {label} "
                   f"(F={fp.get('frames')} N={fp.get('points')} "
                   f"k_max={fp.get('k_max')}, {fp.get('backend', '?')} AOT) ==")
        headers = ["stage", "flops", "hbm", "peak/dev", "ici",
                   "ag", "ar", "rs", "cp", "a2a", "fus", "copy", "trans",
                   "out", "comp[s]"]
        table = []
        total_ici = 0.0
        for r in mesh_rows:
            if "error" in r:
                # a failed stage stays one renderable row (padded to the
                # header width) — it must not crash the successful rows
                table.append(([r["stage"], "ERROR"]
                              + ["-"] * (len(headers) - 2)))
                continue
            colls = r.get("collectives") or {}
            coll_cells = [str(int(colls[name]["count"])) if name in colls
                          else "0" for name, _ in _COLL_SHORT[:5]]
            ici = float(r.get("ici_bytes") or 0.0)
            total_ici += ici
            ops = r.get("ops") or {}
            table.append([
                r["stage"],
                _fmt_count(r.get("flops")),
                _fmt_bytes(r.get("hbm_bytes")),
                _fmt_bytes(r.get("peak_bytes")),
                _fmt_bytes(ici), *coll_cells,
                str(ops.get("fusion", "-")), str(ops.get("copy", "-")),
                str(ops.get("transpose", "-")),
                _fmt_bytes(r.get("out_bytes")),
                f"{r.get('compile_s', 0):.1f}",
            ])
        out.append(_render(headers, table))
        # v5e context: payload bytes over the per-chip ICI rate is a lower
        # bound on the collective wall time a real slice would pay
        ici_us = total_ici / (V5E_ICI_GBPS * 1e9) * 1e6
        hbm_rows = [float(r.get("hbm_bytes") or 0.0) for r in mesh_rows
                    if "error" not in r]
        hbm_us = sum(hbm_rows) / (V5E_HBM_GBPS * 1e9) * 1e6
        out.append(f"ICI total: {_fmt_bytes(total_ici)} "
                   f"(>= {ici_us:.1f} us at v5e {V5E_ICI_GBPS:.0f} GB/s/chip)"
                   f" | HBM traffic: >= {hbm_us:.0f} us at v5e "
                   f"{V5E_HBM_GBPS:.0f} GB/s")
        out.append("")
    return "\n".join(out).rstrip()


def render_dtype_compare(diffs: List[Dict],
                         planes: Optional[Dict] = None) -> str:
    """The dtype census: count_dtype bf16-vs-int8 diff per (stage, mesh).

    One row per (stage, mesh): the narrowed dot classes (the counting
    contractions ops/counting.py dispatches) with operand bytes under each
    encoding and the reduction ratio, the classes that stayed wide (the
    audited f32 sites), and XLA's memory-plan peak per variant. ``planes``
    (obs.cost.claim_plane_bytes) adds the unconditional int16 claim-plane
    line — that halving is not count_dtype-gated, so it cannot appear as
    an A/B delta.
    """
    out = ["== dtype census: count_dtype bf16 vs int8 (CPU AOT, StableHLO "
           "dot classes) =="]
    if not diffs:
        out.append("no comparable (stage, mesh) rows — every lowering "
                   "failed or meshes were skipped")
        return "\n".join(out)

    def _classes(d: Dict) -> str:
        return (" ".join(f"{k}:{int(v['count'])}" for k, v in sorted(d.items()))
                or "-")

    rows = []
    for d in diffs:
        mesh = d.get("mesh") or []
        label = f"{mesh[0]}x{mesh[1]}" if len(mesh) == 2 else "-"
        ratio = d.get("operand_byte_ratio")
        rows.append([
            d["stage"], label,
            _classes(d.get("narrowed_bf16") or {}),
            _fmt_bytes(d.get("narrowed_bytes_bf16")),
            _classes(d.get("narrowed_int8") or {}),
            _fmt_bytes(d.get("narrowed_bytes_int8")),
            "-" if ratio is None else f"{ratio:.2f}x",
            _classes(d.get("stable_dots") or {}),
            _fmt_bytes(d.get("peak_bytes_bf16")),
            _fmt_bytes(d.get("peak_bytes_int8")),
        ])
    out.append(_render(
        ["stage", "mesh", "bf16 dot classes", "op.bytes",
         "int8 dot classes", "op.bytes", "ratio", "stays wide",
         "peak bf16", "peak int8"], rows))
    if planes:
        out.append(
            f"(F, N) first/last claim planes (unconditional int16): "
            f"{_fmt_bytes(planes.get('int16'))} resident vs "
            f"{_fmt_bytes(planes.get('int32_historical'))} at the "
            f"historical int32 layout (halved)")
    return "\n".join(out)


def _fmt_count(v: Optional[float]) -> str:
    if v is None:
        return "-"
    for unit, div in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.1f}{unit}"
    return f"{v:.0f}"


# ---------------------------------------------------------------------------
# perf regression ledger sections (--history / --regress)
# ---------------------------------------------------------------------------


def render_history(rows: List[Dict], stats: Optional[ReadStats] = None,
                   path: str = "") -> str:
    """The bench trajectory, oldest first, nulls included (a null verdict
    IS trajectory — it records the chip window that never delivered)."""
    out = [f"== perf ledger: {path} ({len(rows)} rows) =="]
    if stats is not None and stats.skipped:
        out.append(f"WARNING: skipped {stats.describe()}")
    table = []
    import time as _time

    for r in rows:
        ts = r.get("ts")
        when = (_time.strftime("%Y-%m-%d %H:%M", _time.gmtime(ts))
                if isinstance(ts, (int, float)) else "-")
        val = r.get("value")
        stages = r.get("stages") or {}
        top = sorted(((v, k) for k, v in stages.items()
                      if isinstance(v, (int, float))), reverse=True)[:3]
        table.append([
            when, str(r.get("tool", "-")), str(r.get("git", "-")),
            "-" if val is None else f"{val:.3f}",
            str(r.get("unit", "-")),
            "-" if r.get("vs_baseline") is None else f"{r['vs_baseline']:.1f}x",
            (str(r.get("error", ""))[:40] or
             " ".join(f"{k}={v:.2f}" for v, k in top)),
        ])
    out.append(_render(["when (UTC)", "tool", "git", "value", "unit",
                        "vs_ref", "stages / error"], table))
    return "\n".join(out)


def _regress_eval(ledger_path: str, baseline_path: str,
                  threshold: float) -> tuple:
    """(exit_code, message lines, JSON-able gate record) for --regress."""
    from maskclustering_tpu.obs import ledger as led

    lines: List[str] = []
    stats = ReadStats()
    try:
        rows = led.read_ledger(ledger_path, stats=stats)
    except OSError as e:
        msg = f"--regress: cannot read ledger {ledger_path}: {e}"
        return 2, [msg], {"ok": False, "error": msg}
    if stats.skipped:
        lines.append(f"WARNING: ledger skipped {stats.describe()}")
    baseline = led.load_baseline(baseline_path)
    # tenant-dimension fence, both ways (same shape as the tool fence
    # below): a serve row carrying per-tenant sub-rows measured a
    # multi-tenant mix — its latency is the mix's, so it only gates
    # against a baseline that carried the dimension too, and an
    # untenanted baseline never gates a tenant-dimension row
    tenancy = led.tenant_dimension(baseline or {})
    rows = [r for r in rows if led.tenant_dimension(r) == tenancy]
    # sentinel fence, both ways (mct-sentinel): a row that recorded canary
    # digest drift measured a run whose OUTPUTS were wrong — its latency
    # is a drill's (or an incident's), never a perf baseline, and a clean
    # row must not gate against a drifted baseline either
    drifted = led.sentinel_dimension(baseline or {})
    rows = [r for r in rows if led.sentinel_dimension(r) == drifted]
    # batch-dimension fence, both ways (continuous batching): a row
    # measured under the packing scheduler carries its mean occupancy —
    # its per-request latency amortizes dispatch overhead across
    # batchmates, so it only gates against a baseline measured under
    # packing too (occupancy SHIFTS between two packed rows become
    # advisory attribution lines inside check_regression)
    packed = led.batch_dimension(baseline or {})
    rows = [r for r in rows if led.batch_dimension(r) == packed]
    # durability fence, both ways (mct-durable): a row measured under
    # failover/replay (streams resumed from snapshots, WAL replay after a
    # daemon kill) pays re-run chunks and restart walls that are the
    # chaos drill's, not code drift's — it only gates against a baseline
    # measured under failover too, and never fences a clean row
    failover = led.durability_dimension(baseline or {})
    rows = [r for r in rows if led.durability_dimension(r) == failover]
    # gate comparable rows: a run-row median must not be compared against a
    # bench baseline just because it is the newest numeric row
    current = None
    base_metric = baseline.get("metric") if baseline else None
    # fenced trajectories measure different experiments (serve: s/request
    # under concurrency; tier1: suite wall seconds) — a baseline from one
    # of them only gates its own rows, and a bench/run baseline never
    # gates them just because their row is the newest
    base_fence = None
    for tool in led.FENCED_TOOLS:
        if (baseline or {}).get("tool") == tool or (
                isinstance(base_metric, str)
                and base_metric.startswith(tool + " ")):
            base_fence = tool
    if base_metric:
        current = led.latest_value_row(rows, metric=base_metric)
    if current is None:
        pool = ([r for r in rows if r.get("tool") == base_fence]
                if base_fence else rows)
        current = led.latest_value_row(
            pool, exclude_tools=() if base_fence else led.FENCED_TOOLS)
        if current is not None and base_metric \
                and current.get("metric") != base_metric:
            lines.append(f"WARNING: no ledger row matches baseline metric "
                         f"{base_metric!r}; gating the newest numeric row "
                         f"({current.get('metric')!r}) — interpret with care")
    ok, verdict_lines = led.check_regression(current, baseline,
                                             threshold=threshold)
    lines.append(f"== perf regress gate: {ledger_path} vs {baseline_path} ==")
    lines.extend(verdict_lines)
    record = {"ok": ok, "threshold": threshold,
              "current": current, "baseline": baseline,
              "detail": verdict_lines}
    return (0 if ok else 2), lines, record


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m maskclustering_tpu.obs.report",
        description="render / diff obs JSONL event captures; cost "
                    "observatory and perf-ledger sections")
    p.add_argument("events", nargs="?", default=None,
                   help="events.jsonl written by an obs-armed run (optional "
                        "with --cost/--history/--regress)")
    p.add_argument("--diff", default=None,
                   help="second events.jsonl to diff against (B side)")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable summary instead of tables")
    p.add_argument("--cost", action="store_true",
                   help="render the compile-time cost observatory: from the "
                        "events file's cost rows when given, else computed "
                        "live on CPU virtual devices (tiny shapes)")
    p.add_argument("--cost-mesh", default="1x8,8x1",
                   help="mesh configs for a live --cost run, e.g. 1x8,2x4")
    p.add_argument("--ledger", default=None,
                   help="perf ledger path (default: PERF_LEDGER.jsonl or "
                        "$MCT_PERF_LEDGER)")
    p.add_argument("--history", action="store_true",
                   help="render the perf ledger trajectory")
    p.add_argument("--regress", default=None, metavar="BASELINE",
                   help="gate the ledger's newest value against BASELINE (a "
                        "ledger JSONL or a JSON doc with a 'value'); exits 2 "
                        "on a regression past the threshold")
    p.add_argument("--regress-threshold", type=float, default=None,
                   help="relative p50 slowdown that fails the gate "
                        "(default 0.15)")
    p.add_argument("--slo-spec", default=None, metavar="SPEC",
                   help="SLO spec JSON for the report's SLO section "
                        "(default: the canned serve-default; the section "
                        "renders only when the events file carries "
                        "telemetry windows)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(message)s")
    rc = 0
    did_something = False
    # --json must keep stdout one machine-readable document: every
    # requested section lands in this dict, printed exactly once at the end
    json_doc: Dict = {}
    sections: List[str] = []

    if args.events:
        did_something = True
        run = RunData(args.events)
        if args.json:
            json_doc["summary"] = run.summary()
        else:
            sections.append(render_report(run, slo_spec=args.slo_spec))
            if args.diff:
                sections.append(render_diff(run, RunData(args.diff)))
        if args.cost:
            if run.cost_rows:
                if args.json:
                    json_doc["cost"] = run.cost_rows
                else:
                    sections.append(render_cost(run.cost_rows))
            elif not args.json:
                sections.append(
                    "== cost observatory: no cost events in "
                    f"{args.events} (generate with python -m "
                    "maskclustering_tpu.obs.cost --events <path>) ==")
    elif args.cost:
        # live mode: AOT-lower on CPU virtual devices right here — no chip,
        # no events file, just the compiled HLO's own accounting
        did_something = True
        from maskclustering_tpu.obs import cost as cost_mod

        cost_mod.ensure_cpu_devices()
        try:
            meshes = cost_mod.parse_mesh_specs([args.cost_mesh])
        except ValueError as e:
            p.error(str(e))
        rows = cost_mod.observe_costs(meshes)
        if args.json:
            json_doc["cost"] = rows
        else:
            sections.append(render_cost(rows))
        if not any("error" not in r for r in rows):
            rc = 1

    if args.history or args.regress:
        from maskclustering_tpu.obs import ledger as led

        ledger_path = args.ledger or led.default_ledger_path()
        if args.history:
            did_something = True
            stats = ReadStats()
            try:
                rows = led.read_ledger(ledger_path, stats=stats)
            except OSError as e:
                print(f"--history: cannot read ledger {ledger_path}: {e}",
                      file=sys.stderr)
                return 2
            if args.json:
                json_doc["history"] = rows
            else:
                sections.append(render_history(rows, stats, ledger_path))
        if args.regress:
            did_something = True
            threshold = (args.regress_threshold
                         if args.regress_threshold is not None
                         else led.DEFAULT_REGRESS_THRESHOLD)
            gate_rc, lines, record = _regress_eval(ledger_path, args.regress,
                                                   threshold)
            rc = max(rc, gate_rc)
            if args.json:
                json_doc["regress"] = record
            else:
                sections.append("\n".join(lines))

    if not did_something:
        p.error("nothing to do: give an events file or one of "
                "--cost/--history/--regress")
    if args.json:
        # one-section --json keeps the historical flat shape (the summary
        # document test_run and run.py's digest embed); multi-section gets
        # the keyed document
        if list(json_doc) == ["summary"]:
            print(json.dumps(json_doc["summary"], indent=2))
        else:
            print(json.dumps(json_doc, indent=2))
    else:
        print("\n\n".join(sections))
    return rc


if __name__ == "__main__":
    sys.exit(main())
