"""Run-report CLI over obs JSONL event files.

    python -m maskclustering_tpu.obs.report events.jsonl
    python -m maskclustering_tpu.obs.report new.jsonl --diff old.jsonl

Renders per-stage span tables — count, p50/p95 wall, device (fenced sync)
vs host split, per-stage host<->device bytes, HBM high-water — and diffs
two runs stage by stage. This makes ``BENCH_*.json`` and ``run_report``
captures self-explaining: the post.claims kernel-vs-transfer split is a
by-product of any run with obs armed, not a bespoke diagnostic script.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from maskclustering_tpu.obs.events import KIND_METRICS, KIND_SPAN, read_events


class RunData:
    """Parsed event file: ordered span series + final metrics snapshot."""

    def __init__(self, path: str):
        self.path = path
        self.meta: Dict = {}
        self.spans: Dict[str, List[Dict]] = {}  # name -> span events, in order
        self.order: List[str] = []
        self.hbm_high_water: Optional[float] = None
        metrics_by_pid: Dict = {}  # counters are monotonic PER PROCESS:
        # keep each pid's last flush, then sum counters across pids (one
        # file can hold several worker attempts plus the supervisor)
        for ev in read_events(path):
            kind = ev.get("kind")
            if kind == "meta" and not self.meta:
                self.meta = {k: v for k, v in ev.items()
                             if k not in ("v", "kind", "ts", "pid")}
            elif kind == KIND_SPAN:
                name = ev.get("name")
                if not isinstance(name, str):
                    continue
                if name not in self.spans:
                    self.spans[name] = []
                    self.order.append(name)
                self.spans[name].append(ev)
                mem = ev.get("mem") or {}
                in_use = mem.get("bytes_in_use")
                if in_use is not None and (self.hbm_high_water is None
                                           or in_use > self.hbm_high_water):
                    self.hbm_high_water = float(in_use)
            elif kind == KIND_METRICS:
                metrics_by_pid[ev.get("pid")] = ev.get("metrics") or {}
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        for m in metrics_by_pid.values():
            for k, v in (m.get("counters") or {}).items():
                counters[k] = counters.get(k, 0.0) + v
            for k, v in (m.get("gauges") or {}).items():
                # max across processes: correct for the high-water/bucket
                # gauges this subsystem emits (all are "largest seen" style)
                if k not in gauges or v > gauges[k]:
                    gauges[k] = v
        hw = gauges.get("hbm.high_water_bytes")
        if hw is not None and (self.hbm_high_water is None
                               or hw > self.hbm_high_water):
            self.hbm_high_water = float(hw)
        self._counters = counters
        self._gauges = gauges

    def stage_rows(self) -> List[Dict]:
        """One aggregate row per span name, in first-appearance order."""
        rows = []
        for name in self.order:
            evs = self.spans[name]
            durs = sorted(float(e.get("dur_s", 0.0)) for e in evs)
            syncs = sorted(float(e.get("sync_s", 0.0)) for e in evs)
            rows.append({
                "stage": name,
                "count": len(evs),
                "total_s": sum(durs),
                "p50_s": _pct(durs, 50),
                "p95_s": _pct(durs, 95),
                "device_p50_s": _pct(syncs, 50),
                "host_p50_s": max(_pct(durs, 50) - _pct(syncs, 50), 0.0),
                "h2d_bytes": self._counters.get(f"h2d.bytes.{name}"),
                "d2h_bytes": self._counters.get(f"d2h.bytes.{name}"),
            })
        return rows

    def summary(self) -> Dict:
        """JSON-able digest for embedding (run_report.json / bench line)."""
        return {
            "events": self.path,
            "stages": {r["stage"]: {"count": r["count"],
                                    "p50_s": round(r["p50_s"], 4),
                                    "p95_s": round(r["p95_s"], 4),
                                    "device_p50_s": round(r["device_p50_s"], 4)}
                       for r in self.stage_rows()},
            "hbm_high_water_bytes": self.hbm_high_water,
            "h2d_bytes": self._counters.get("h2d.bytes"),
            "d2h_bytes": self._counters.get("d2h.bytes"),
            "counters": {k: v for k, v in sorted(self._counters.items())
                         if k.startswith(("run.", "bench.", "compile_cache."))},
        }


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q / 100.0 * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def _fmt_s(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:.3f}"


def _fmt_bytes(v: Optional[float]) -> str:
    if v is None:
        return "-"
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(v) < 1024 or unit == "TB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024.0
    return "-"  # unreachable


def _render(headers: List[str], rows: List[List[str]]) -> str:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    fmt_row = lambda cells: "  ".join(c.ljust(w) if i == 0 else c.rjust(w)  # noqa: E731
                                      for i, (c, w) in enumerate(zip(cells, widths)))
    lines = [fmt_row(headers), fmt_row(["-" * w for w in widths])]
    lines += [fmt_row(r) for r in rows]
    return "\n".join(lines)


def render_report(run: RunData) -> str:
    rows = [[r["stage"], str(r["count"]), _fmt_s(r["p50_s"]), _fmt_s(r["p95_s"]),
             _fmt_s(r["device_p50_s"]), _fmt_s(r["host_p50_s"]),
             _fmt_s(r["total_s"]), _fmt_bytes(r["h2d_bytes"]),
             _fmt_bytes(r["d2h_bytes"])]
            for r in run.stage_rows()]
    out = [f"== obs report: {run.path} =="]
    if run.meta:
        out.append("meta: " + json.dumps(run.meta, sort_keys=True))
    out.append(_render(
        ["stage", "n", "p50[s]", "p95[s]", "dev.p50[s]", "host.p50[s]",
         "total[s]", "h2d", "d2h"], rows))
    tail = []
    if run.hbm_high_water is not None:
        tail.append(f"HBM high-water: {_fmt_bytes(run.hbm_high_water)}")
    for d in ("h2d", "d2h"):
        total = run._counters.get(f"{d}.bytes")
        if total is not None:
            tail.append(f"{d} total: {_fmt_bytes(total)}")
    hits = {k: v for k, v in run._counters.items()
            if k.startswith("compile_cache.")}
    if hits:
        tail.append("compile cache: " + ", ".join(
            f"{k.split('.', 1)[1]}={int(v)}" for k, v in sorted(hits.items())))
    if tail:
        out.append(" | ".join(tail))
    return "\n".join(out)


def render_diff(run_a: RunData, run_b: RunData) -> str:
    """Stage-by-stage p50 diff: A (the file argument) vs B (--diff)."""
    rows_a = {r["stage"]: r for r in run_a.stage_rows()}
    rows_b = {r["stage"]: r for r in run_b.stage_rows()}
    names = list(run_a.order) + [n for n in run_b.order if n not in rows_a]
    rows = []
    for name in names:
        a, b = rows_a.get(name), rows_b.get(name)
        pa = a["p50_s"] if a else None
        pb = b["p50_s"] if b else None
        if pa is not None and pb is not None and pb > 0:
            delta = f"{100.0 * (pa - pb) / pb:+.1f}%"
        else:
            delta = "-"
        rows.append([name, _fmt_s(pa), _fmt_s(pb), delta])
    head = [f"== obs diff: A={run_a.path}  B={run_b.path} =="]
    return "\n".join(head + [_render(["stage", "A p50[s]", "B p50[s]", "A vs B"],
                                     rows)])


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m maskclustering_tpu.obs.report",
        description="render / diff obs JSONL event captures")
    p.add_argument("events", help="events.jsonl written by an obs-armed run")
    p.add_argument("--diff", default=None,
                   help="second events.jsonl to diff against (B side)")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable summary instead of tables")
    args = p.parse_args(argv)

    run = RunData(args.events)
    if args.json:
        print(json.dumps(run.summary(), indent=2))
        return 0
    print(render_report(run))
    if args.diff:
        print()
        print(render_diff(run, RunData(args.diff)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
