"""JSONL event sink: one line per span / metrics flush, append-only.

The sink is the durable half of the obs subsystem: every span close and
every metrics flush becomes one self-describing JSON line, so a run that
dies mid-scene (the chip-outage mode that ate two rounds of captures)
still leaves every completed span on disk. Rules:

- **schema-versioned**: every line carries ``"v": SCHEMA_VERSION``; the
  reader skips lines from versions it does not know instead of crashing
  a report on a mixed-version file.
- **append-only + crash-safe**: the file is opened in append mode and
  flushed per line; a SIGKILL can truncate at most the line in flight,
  and ``read_events`` tolerates a torn final line.
- **never the failure source**: a sink write error disables the sink and
  logs once — observability must not sink the run it observes.
"""

from __future__ import annotations

import io
import json
import logging
import os
import time
from typing import Dict, Iterator, List, Optional

# stdlib-only; a raw threading.Lock unless MCT_LOCK_SANITIZER is armed.
# The literal name keys this lock in both the static lock-order graph
# (analysis/concurrency.py) and the runtime sanitizer's observed one
from maskclustering_tpu.analysis.lock_sanitizer import mct_lock

log = logging.getLogger("maskclustering_tpu")

SCHEMA_VERSION = 1

# event kinds the schema defines (readers skip unknown kinds, same policy
# as unknown versions, so the schema can grow without breaking old reports)
KIND_META = "meta"
KIND_SPAN = "span"
KIND_METRICS = "metrics"
KIND_COST = "cost"  # compile-time cost observatory rows (obs/cost.py)
KIND_ANALYSIS = "analysis"  # mct-check findings/summary (analysis/__main__.py)
KIND_TELEMETRY = "telemetry"  # windowed serving snapshots (obs/telemetry.py)
KIND_DRIFT = "canary.drift"  # mct-sentinel golden-probe drift (obs/canary.py)


class ReadStats:
    """Skip accounting for tolerant JSONL readers.

    A crash can tear the final line and a newer writer can emit versions
    this reader does not know; both are skipped — but silently losing lines
    makes a report lie by omission, so readers count what they drop and the
    CLIs surface the counts as a warning.
    """

    __slots__ = ("torn", "unknown_version", "total")

    def __init__(self):
        self.torn = 0
        self.unknown_version = 0
        self.total = 0

    @property
    def skipped(self) -> int:
        return self.torn + self.unknown_version

    def describe(self) -> str:
        return (f"{self.torn} torn/corrupt line(s), "
                f"{self.unknown_version} unknown-schema line(s)")


class EventSink:
    """Append-only JSONL writer, one flush per line, thread-safe.

    ``truncate=True`` starts the file fresh (single-owner paths that are
    re-derived per run); the sink itself never truncates mid-run.
    """

    def __init__(self, path: str, *, truncate: bool = False):
        self.path = path
        self._lock = mct_lock("obs.events.EventSink._lock")
        self._dead = False
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f: Optional[io.TextIOBase] = open(
            path, "w" if truncate else "a", encoding="utf-8")

    def emit(self, kind: str, payload: Dict) -> None:
        """Write one event line; payload keys merge into the envelope."""
        if self._dead or self._f is None:
            return
        # pid in the envelope: one file can hold several processes' events
        # (bench worker attempts + supervisor; spawn-pool workers), and the
        # reader must aggregate monotonic counters per process, not across
        line = {"v": SCHEMA_VERSION, "kind": kind, "ts": time.time(),
                "pid": os.getpid()}
        line.update(payload)
        try:
            with self._lock:
                self._f.write(json.dumps(line, default=_json_default) + "\n")
                self._f.flush()
        except Exception:  # noqa: BLE001 — the sink must never sink the run
            self._dead = True
            log.exception("obs event sink failed; disabling (%s)", self.path)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except Exception:  # noqa: BLE001
                    pass
                self._f = None


def _json_default(obj):
    """Last-resort JSON coercion for numpy scalars and odd attr values."""
    for attr in ("item",):  # numpy scalars / 0-d arrays
        if hasattr(obj, attr):
            try:
                return obj.item()
            except Exception:  # noqa: BLE001
                break
    return repr(obj)


def iter_jsonl_rows(path: str, *, version: int,
                    stats: Optional[ReadStats] = None) -> Iterator[Dict]:
    """Tolerant schema-versioned JSONL reader (events AND the perf ledger).

    One copy of the crash-tolerance policy: torn/corrupt lines (a crash can
    truncate the final line) and unknown ``v`` values are skipped — counted
    into ``stats`` when given, so CLIs can warn instead of losing lines
    silently.
    """
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            if stats is not None:
                stats.total += 1
            try:
                row = json.loads(raw)
            except ValueError:
                if stats is not None:
                    stats.torn += 1
                continue  # torn line (crash mid-write)
            if not isinstance(row, dict) or row.get("v") != version:
                if stats is not None:
                    stats.unknown_version += 1
                continue
            yield row


def read_events(path: str, *, kinds: Optional[List[str]] = None,
                stats: Optional[ReadStats] = None) -> Iterator[Dict]:
    """Yield parsed events from a JSONL file.

    Skips: torn/corrupt lines, unknown schema versions (see
    ``iter_jsonl_rows``), and — when ``kinds`` is given — other kinds.
    """
    for ev in iter_jsonl_rows(path, version=SCHEMA_VERSION, stats=stats):
        if kinds is not None and ev.get("kind") not in kinds:
            continue
        yield ev
