"""Per-request trace assembly: one serving request as a causal timeline.

    python -m maskclustering_tpu.obs.trace r-000003 --events X.jsonl
    python -m maskclustering_tpu.obs.trace r-000003 --events X.jsonl \
        --journal /path/serve_journals

Stitches everything the serving stack recorded about REQUEST_ID into one
ordered timeline with per-segment durations:

- ``serve.queue_wait`` spans (booked at dequeue; duration = ack->dequeue,
  so the segment STARTS at admission) — one per dispatch, so a requeued
  request shows its second wait too;
- ``serve.request`` execution windows (in-process: booked directly;
  isolated: relayed from the worker subprocess and replayed into the
  events file with a ``worker_pid`` tag), with the pipeline stage spans
  that ran inside each window nested under it by time containment;
- ``serve.worker_crash`` markers (the supervisor books one per in-flight
  crash) — a crash->requeue->respawn request reads as
  wait -> attempt -> CRASH -> wait -> attempt -> result;
- per-request RunJournal rows (``--journal DIR`` -> ``DIR/<id>.jsonl``):
  attempt starts, ``interrupted`` crash stamps, and the final outcome;
- ``canary.drift`` event marks (mct-sentinel, obs/canary.py): drift
  detected around this request's window renders as a zero-width
  ``CANARY DRIFT`` mark — correctness context next to the latency story;
- ``--blackbox DUMP`` merges a flight-recorder postmortem
  (obs/flight.py): span rows dedup against the live events, everything
  else becomes zero-width black-box marks — the child-side spans a
  SIGKILL'd worker never relayed appear in their true place.

Relayed spans anchor on the worker's own close timestamp (the ``end_ts``
attr the relay preserves), not the parent's re-emit time, so child and
parent segments order correctly on one wall clock.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from maskclustering_tpu.obs.events import (KIND_DRIFT, KIND_SPAN, ReadStats,
                                           read_events)

# spans that ARE the request skeleton (matched by attrs.request == id)
_SKELETON = ("serve.queue_wait", "serve.request", "serve.worker_crash")
# container spans excluded from nesting (they would double-count stages)
_CONTAINERS = ("exec.device", "exec.host_tail", "exec.scene_loop")


def _span_window(ev: Dict) -> tuple:
    """(start_epoch, end_epoch) of one span event: the relay-preserved
    close time when present, else the envelope emit time."""
    attrs = ev.get("attrs") or {}
    end = attrs.get("end_ts")
    if not isinstance(end, (int, float)):
        end = ev.get("ts", 0.0)
    dur = float(ev.get("dur_s", 0.0))
    return float(end) - dur, float(end)


def assemble_trace(request_id: str, events_path: str,
                   journal_dir: Optional[str] = None,
                   blackbox: Optional[str] = None) -> Dict:
    """All known segments of one request, time-ordered.

    Returns ``{"request": id, "segments": [...], "warnings": [...]}``;
    each segment: ``{"t0", "t1", "dur_s", "kind", "label", "detail",
    "children": [...]}`` (children only on execution windows).

    ``blackbox`` (a flight-recorder dump, or a directory of them —
    obs/flight.py) merges the postmortem ring into the same timeline:
    the victim's final child-side spans the live relay never shipped,
    plus admission/crash marks, so a crash->requeue->respawn request
    reads end to end even when the worker died mid-flight.
    """
    stats = ReadStats()
    skeleton: List[Dict] = []
    others: List[Dict] = []
    drift_rows: List[Dict] = []
    for ev in read_events(events_path, kinds=[KIND_SPAN, KIND_DRIFT],
                          stats=stats):
        if ev.get("kind") == KIND_DRIFT:
            drift_rows.append(ev)
            continue
        name = ev.get("name")
        attrs = ev.get("attrs") or {}
        if name in _SKELETON and attrs.get("request") == request_id:
            skeleton.append(ev)
        elif isinstance(name, str) and name not in _SKELETON:
            others.append(ev)

    warnings: List[str] = []
    if stats.skipped:
        warnings.append(f"events reader skipped {stats.describe()}")

    marks: List[Dict] = []
    if blackbox:
        _merge_blackbox(blackbox, request_id, skeleton, others, marks,
                        warnings)

    segments: List[Dict] = []
    for ev in skeleton:
        t0, t1 = _span_window(ev)
        attrs = ev.get("attrs") or {}
        name = ev["name"]
        if name == "serve.queue_wait":
            seg = {"kind": "queue_wait", "label": "queue wait",
                   "detail": f"scene {attrs.get('scene', '?')}"}
        elif name == "serve.worker_crash":
            seg = {"kind": "crash", "label": "WORKER CRASH",
                   "detail": str(attrs.get("detail", ""))[:120]}
        else:
            where = (f"worker pid {attrs['worker_pid']}"
                     if attrs.get("worker_pid") else "in-process")
            seg = {"kind": "attempt", "label": "execution",
                   "detail": f"scene {attrs.get('scene', '?')} ({where})",
                   "children": _children(others, t0, t1)}
        seg.update(t0=t0, t1=t1, dur_s=round(t1 - t0, 4))
        segments.append(seg)

    for row in _journal_rows(request_id, journal_dir, warnings):
        segments.append(row)
    segments.extend(marks)

    # mct-sentinel drift marks: canary drift is daemon-wide (probes carry
    # no request id), so mark any drift detected around this request's
    # window — an answer computed next to detected corruption deserves
    # the flag in its own timeline
    if drift_rows and segments:
        lo = min(s["t0"] for s in segments)
        hi = max(s["t1"] for s in segments)
        for ev in drift_rows:
            ts = float(ev.get("ts", 0.0))
            if lo - 1.0 <= ts <= hi + 1.0:
                fields = ",".join(ev.get("fields") or []) or "?"
                segments.append({
                    "t0": ts, "t1": ts, "dur_s": 0.0, "kind": "drift",
                    "label": "CANARY DRIFT",
                    "detail": (f"coord {ev.get('coord', '?')} fields "
                               f"{fields} (daemon-wide)")[:140]})

    segments.sort(key=lambda s: (s["t0"], s["t1"]))
    if not segments:
        warnings.append(f"no spans or journal rows mention request "
                        f"{request_id!r} — wrong events file, or the run "
                        f"was not obs-armed")
    return {"request": request_id, "segments": segments,
            "warnings": warnings}


def _merge_blackbox(blackbox: str, request_id: str, skeleton: List[Dict],
                    others: List[Dict], marks: List[Dict],
                    warnings: List[str]) -> None:
    """Fold a flight dump's rows into the live pools (span rows, deduped
    against anything the events file already holds) plus zero-width
    black-box marks (admission decisions, request lifecycle, crash and
    fault bookkeeping that mention this request)."""
    from maskclustering_tpu.obs import flight as _flight

    path = _flight.resolve_dump(blackbox)
    if path is None:
        warnings.append(f"no flight dump at {blackbox}")
        return
    _meta, rows = _flight.read_dump(path)
    seen = set()
    for ev in skeleton + others:
        s0, s1 = _span_window(ev)
        seen.add((ev.get("name"), round(s1, 3),
                  round(float(ev.get("dur_s", 0.0)), 5)))
    merged = 0
    for ev in rows:
        kind = ev.get("kind")
        if kind == "span":
            name = ev.get("name")
            if not isinstance(name, str):
                continue
            s0, s1 = _span_window(ev)
            key = (name, round(s1, 3),
                   round(float(ev.get("dur_s", 0.0)), 5))
            if key in seen:
                continue
            seen.add(key)
            attrs = ev.get("attrs") or {}
            if name in _SKELETON:
                if attrs.get("request") == request_id:
                    skeleton.append(ev)
                    merged += 1
            else:
                others.append(ev)
                merged += 1
            continue
        if ev.get("request") != request_id:
            continue
        ts = float(ev.get("ts", 0.0))
        detail = " ".join(
            f"{k}={v}" for k, v in ev.items()
            if k not in ("kind", "ts", "seq", "v", "pid", "request"))
        label = {
            _flight.KIND_ADMIT: f"blackbox {ev.get('event', 'admission')}",
            _flight.KIND_REQUEST: f"blackbox {ev.get('event', 'request')}"
                                  f" (pid {ev.get('pid', '?')})",
            _flight.KIND_CRASH: "blackbox WORKER CRASH",
            _flight.KIND_FAULT: "blackbox fault",
        }.get(kind)
        if label is None:
            continue
        marks.append({"t0": ts, "t1": ts, "dur_s": 0.0, "kind": "blackbox",
                      "label": label, "detail": detail[:140]})
        merged += 1
    if not merged:
        warnings.append(f"flight dump {path} held no new events for "
                        f"{request_id!r}")


def _children(others: List[Dict], t0: float, t1: float,
              eps: float = 0.01) -> List[Dict]:
    """Stage spans whose window sits inside [t0, t1] (time containment:
    request ids do not propagate into the pipeline's own spans).

    eps is tight and the span must START inside the window: on a warm
    daemon back-to-back requests sit milliseconds apart, and a loose
    tolerance would attribute a neighbor request's boundary spans here.
    """
    out = []
    for ev in others:
        name = ev.get("name")
        if name in _CONTAINERS or name == "serve.materialize":
            continue
        s0, s1 = _span_window(ev)
        if s0 >= t0 - eps and s1 <= t1 + eps and s0 < t1:
            out.append({"t0": s0, "t1": s1,
                        "dur_s": round(s1 - s0, 4),
                        "kind": "stage", "label": name,
                        "sync_s": float(ev.get("sync_s", 0.0))})
    out.sort(key=lambda s: (s["t0"], s["t1"]))
    return out


def _journal_rows(request_id: str, journal_dir: Optional[str],
                  warnings: List[str]) -> List[Dict]:
    if not journal_dir:
        return []
    path = os.path.join(journal_dir, f"{request_id}.jsonl")
    if not os.path.exists(path):
        warnings.append(f"no journal at {path}")
        return []
    from maskclustering_tpu.utils import faults

    out = []
    for row in faults.read_journal(path, request=request_id):
        ts = float(row.get("ts", 0.0))
        event = row.get("event")
        if event == "attempt":
            out.append({"t0": ts, "t1": ts, "dur_s": 0.0,
                        "kind": "journal",
                        "label": f"attempt {row.get('attempt')}",
                        "detail": f"rung {row.get('rung', 0)} (journal)"})
        elif event == "outcome":
            status = row.get("status", "?")
            detail = f"attempt {row.get('attempt')} (journal)"
            if row.get("error"):
                detail += f" — {row['error'][:100]}"
            label = ("INTERRUPTED (worker died)" if status == "interrupted"
                     else f"outcome {status}")
            out.append({"t0": ts, "t1": ts, "dur_s": 0.0,
                        "kind": "journal", "label": label, "detail": detail})
    return out


def render_trace(trace: Dict) -> str:
    segments = trace["segments"]
    out = [f"== request trace: {trace['request']} =="]
    for w in trace.get("warnings", ()):
        out.append(f"WARNING: {w}")
    if not segments:
        return "\n".join(out)
    origin = segments[0]["t0"]
    total = max(s["t1"] for s in segments) - origin
    out.append(f"origin t0={origin:.3f} | end-to-end "
               f"{total:.3f}s | {len(segments)} segment(s)")
    for seg in segments:
        rel = seg["t0"] - origin
        line = (f"  +{rel:8.3f}s  {seg['dur_s']:8.3f}s  "
                f"{seg['label']:<26} {seg.get('detail', '')}")
        out.append(line.rstrip())
        for ch in seg.get("children", ()):
            rel_c = ch["t0"] - origin
            sync = f" (device {ch['sync_s']:.3f}s)" if ch.get("sync_s") else ""
            out.append(f"      +{rel_c:8.3f}s  {ch['dur_s']:8.3f}s  "
                       f"· {ch['label']}{sync}")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m maskclustering_tpu.obs.trace",
        description="assemble one serving request's causal timeline from "
                    "obs events + per-request journals")
    p.add_argument("request_id", help="daemon-assigned id (r-000001)")
    p.add_argument("--events", required=True,
                   help="obs events JSONL the daemon wrote (--obs_events)")
    p.add_argument("--journal", default=None, metavar="DIR",
                   help="per-request journal directory (the daemon's "
                        "--journal-dir)")
    p.add_argument("--blackbox", default=None, metavar="DUMP",
                   help="flight-recorder dump (file or directory; "
                        "obs/flight.py) to merge into the timeline — "
                        "crash postmortems included")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable trace document")
    args = p.parse_args(argv)
    try:
        trace = assemble_trace(args.request_id, args.events,
                               journal_dir=args.journal,
                               blackbox=args.blackbox)
    except OSError as e:
        print(f"obs.trace: cannot read {args.events}: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(trace, sort_keys=True))
    else:
        print(render_trace(trace))
    return 0 if trace["segments"] else 1


if __name__ == "__main__":
    sys.exit(main())
