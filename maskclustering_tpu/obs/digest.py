"""mct-sentinel: device-side invariant digests for correctness observability.

The pipeline's whole contract is that every coordinate — count_dtype
encodings, mesh shards, degradation rungs, streaming chunks, crash
respawns — produces BYTE-IDENTICAL instances (PAPER.md §1: exact integer
view-consensus). This module turns that contract into a runtime signal: a
jitted exact-integer reduction over the device-resident claim planes and
graph/cluster state collapses a scene's intermediate state into a tiny
uint32 vector, and a host composition folds in the mask table, the pulled
assignment, NaN/Inf counts over the f32 geometry, and a canonical hash of
the exported instances.

Everything is modular uint32 arithmetic (associative + commutative, mod
2**32 exact) so the digest is reduction-order invariant and therefore
byte-stable across executors, shard layouts, and XLA scheduling — any two
coordinates that claim identity MUST produce the same digest, and any
silent corruption flips it.

The device program's output rides the existing emit-only post-process
drain in ``run_scene_host`` (one extra O(1) DMA after every kernel has
retired); ``pipeline.host_sync`` stays exactly 1. Internally everything is
cast to fixed int32/uint32, so the program has no count_dtype or donation
key axes and compiles once per scene bucket — it joins SERVING_PROGRAMS
and the compile-surface census like every other serving program.

Digest schema (``version`` bumps invalidate committed goldens)::

    {"v": 1, "bucket": "k63:f32:n16384", "count_dtype": "u32",
     "plane": "<crc32 hex8>", "artifact": "<crc32 hex8>", "nan_inf": 0}

``plane`` fingerprints the device-side invariants (claim planes, graph
stats, assignment, mask table) — present on every DeviceHandoff path.
``artifact`` fingerprints the final SceneObjects — universal, including
the fused mesh path and the multi-chunk streaming finalize which never
materialize a handoff.
"""
from __future__ import annotations

import functools
import zlib
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

DIGEST_VERSION = 1

# Knuth multiplicative hash constants — position weights for the wrapped
# uint32 checksums (weight(i) = i * MULT + OFFS mod 2**32)
_W_MULT = 2654435761
_W_OFFS = 0x9E3779B9


def _wsum(x: jnp.ndarray) -> jnp.ndarray:
    """Position-weighted uint32 checksum (exact, order-invariant)."""
    v = x.reshape(-1).astype(jnp.uint32)
    w = (jnp.arange(v.shape[0], dtype=jnp.uint32) * jnp.uint32(_W_MULT)
         + jnp.uint32(_W_OFFS))
    return jnp.sum(v * w, dtype=jnp.uint32)


@functools.partial(jax.jit)
def _digest_scene_impl(
    first_id: jnp.ndarray,      # (F, N) int16 claim plane
    last_id: jnp.ndarray,       # (F, N) int16 claim plane
    assignment: jnp.ndarray,    # (M_pad,) int32 mask -> cluster rep
    active: jnp.ndarray,        # (M_pad,) bool
    node_visible: jnp.ndarray,  # (M_pad, F) bool graph stat
) -> jnp.ndarray:
    """Scene invariant digest: (8,) uint32, exact-integer reductions only.

    Components: claim-plane popcounts + position checksums (first/last),
    assignment histogram checksum, active popcount, node-visible row-sum
    checksum, active-masked assignment checksum. No f32 enters the
    reduction, so the vector is bit-exact on any backend.
    """
    m = assignment.shape[0]
    hist = jnp.zeros((m + 1,), jnp.uint32).at[
        jnp.clip(assignment, 0, m)].add(jnp.uint32(1))
    row_sums = jnp.sum(node_visible.astype(jnp.uint32), axis=1,
                       dtype=jnp.uint32)
    return jnp.stack([
        jnp.count_nonzero(first_id).astype(jnp.uint32),
        _wsum(first_id),
        jnp.count_nonzero(last_id).astype(jnp.uint32),
        _wsum(last_id),
        _wsum(hist),
        jnp.count_nonzero(active).astype(jnp.uint32),
        _wsum(row_sums),
        _wsum(jnp.where(active, assignment + 1, 0)),
    ])


@functools.partial(jax.jit)
def _digest_stream_impl(
    assignment: jnp.ndarray,  # (M_pad,) int32 global accumulator state
    active: jnp.ndarray,      # (M_pad,) bool
    rep_plane: jnp.ndarray,   # (N_pad,) int32 point -> rep slot + 1
) -> jnp.ndarray:
    """Streaming-accumulator digest: (4,) uint32 over the post-bind state
    of one chunk (assignment, active set, point->rep plane)."""
    return jnp.stack([
        jnp.count_nonzero(active).astype(jnp.uint32),
        _wsum(jnp.where(active, assignment + 1, 0)),
        jnp.count_nonzero(rep_plane).astype(jnp.uint32),
        _wsum(rep_plane),
    ])


# ---------------------------------------------------------------------------
# dispatch helpers (device side — no sync)
# ---------------------------------------------------------------------------


def digest_scene_device(handoff) -> jnp.ndarray:
    """Dispatch the scene digest program over a DeviceHandoff's arrays.

    Returns the DEVICE vector (no pull) — dispatch this before the
    post-process kernels so a donating kernel can't invalidate an input,
    and pull it at the drain tail where every kernel has retired.
    """
    return _digest_scene_impl(handoff.first_id, handoff.last_id,
                              handoff.assignment, handoff.active,
                              handoff.node_visible)


def digest_stream_device(assignment, active, rep_plane) -> jnp.ndarray:
    """Dispatch the streaming-accumulator digest (device vector, no pull)."""
    return _digest_stream_impl(assignment, active, rep_plane)


# ---------------------------------------------------------------------------
# host composition
# ---------------------------------------------------------------------------


def _crc(data: bytes, seed: int = 0) -> int:
    return zlib.crc32(data, seed) & 0xFFFFFFFF


def table_hash(table) -> int:
    """Exact uint32 hash of a MaskTable's identifying rows."""
    seed = _crc(np.asarray(table.frame, np.int32).tobytes())
    seed = _crc(np.asarray(table.mask_id, np.int32).tobytes(), seed)
    seed = _crc(np.asarray(table.valid, np.uint8).tobytes(), seed)
    return _crc(np.asarray([table.num_masks, table.k_max],
                           np.int32).tobytes(), seed)


def nan_inf_count(scene_points: np.ndarray) -> int:
    """Non-finite count over the f32 geometry (host numpy, no device op)."""
    return int(np.count_nonzero(~np.isfinite(scene_points)))


def artifact_digest(objects) -> str:
    """Canonical hex8 fingerprint of a SceneObjects (the exported answer).

    Serializes every instance's point ids and (frame, mask, coverage)
    support rows in their deterministic export order — byte-identity of
    this hash IS the repo's cross-coordinate identity claim.
    """
    seed = _crc(np.asarray([len(objects.point_ids_list),
                            int(objects.num_points)], np.int64).tobytes())
    for pids, masks in zip(objects.point_ids_list, objects.mask_list):
        seed = _crc(np.asarray(pids, np.int64).tobytes(), seed)
        for row in masks:
            frame_id, mask_id, coverage = row[0], row[1], row[2]
            seed = _crc(str(frame_id).encode(), seed)
            seed = _crc(np.asarray([int(mask_id)], np.int64).tobytes(), seed)
            seed = _crc(np.asarray([coverage], np.float64).tobytes(), seed)
    return f"{seed:08x}"


def plane_digest(vec_host: np.ndarray, table, assignment_host: np.ndarray,
                 nan_inf: int) -> str:
    """Hex8 of the device invariant vector + mask table + pulled assignment."""
    seed = _crc(np.asarray(vec_host, np.uint32).tobytes())
    seed = _crc(np.asarray([table_hash(table)], np.uint32).tobytes(), seed)
    seed = _crc(np.asarray(assignment_host, np.int32).tobytes(), seed)
    seed = _crc(np.asarray([nan_inf], np.int64).tobytes(), seed)
    return f"{seed:08x}"


def bucket_label(k_max: int, f_pad: int, n_pad: int) -> str:
    """The census bucket coordinate string (same grammar as the retrace
    compile-surface rows): ``k63:f32:n16384``."""
    return f"k{k_max}:f{f_pad}:n{n_pad}"


def compose_scene_digest(vec_host: np.ndarray, handoff, assignment_host:
                         np.ndarray, objects, *, count_dtype: str) -> Dict:
    """Fold device vector + host components into the scene digest dict."""
    f_pad, n_pad = handoff.first_id.shape
    nan_inf = nan_inf_count(handoff.scene_points)
    return {
        "v": DIGEST_VERSION,
        "bucket": bucket_label(handoff.k_max, f_pad, n_pad),
        "count_dtype": count_dtype,
        "plane": plane_digest(vec_host, handoff.table, assignment_host,
                              nan_inf),
        "artifact": artifact_digest(objects),
        "nan_inf": nan_inf,
    }


def artifact_only_digest(objects, *, bucket: str, count_dtype: str) -> Dict:
    """Digest for paths that never materialize a DeviceHandoff (the fused
    mesh batch, the multi-chunk streaming finalize): artifact hash only."""
    return {
        "v": DIGEST_VERSION,
        "bucket": bucket,
        "count_dtype": count_dtype,
        "plane": "",
        "artifact": artifact_digest(objects),
        "nan_inf": 0,
    }


def chunk_digest_hex(vec_host: np.ndarray) -> str:
    """Hex8 of one streaming chunk's accumulator digest vector."""
    return f"{_crc(np.asarray(vec_host, np.uint32).tobytes()):08x}"


# ---------------------------------------------------------------------------
# coordinates
# ---------------------------------------------------------------------------


def digest_coord(digest: Optional[Dict], *, mesh: str = "single",
                 rung: int = 0, chunk: int = 0) -> str:
    """The full census coordinate a digest was observed at.

    ``<bucket>|<count_dtype>|<mesh>|r<rung>|c<chunk>`` — the key goldens
    are stored under and drift is attributed to. ``chunk`` is the
    streaming chunk count (0 = batch).
    """
    if not digest:
        return ""
    return (f"{digest.get('bucket', '?')}|{digest.get('count_dtype', '?')}"
            f"|{mesh or 'single'}|r{int(rung)}|c{int(chunk)}")


def digests_match(a: Optional[Dict], b: Optional[Dict]) -> bool:
    """Byte-for-byte digest equality (version-aware: a version skew is a
    mismatch, not an error — regenerate goldens)."""
    if not a or not b:
        return False
    keys = ("v", "plane", "artifact", "nan_inf")
    return all(a.get(k) == b.get(k) for k in keys)


def diff_digests(a: Optional[Dict], b: Optional[Dict]) -> list:
    """Field names that differ between two digests (drift attribution)."""
    if not a or not b:
        return ["missing"]
    return [k for k in ("v", "plane", "artifact", "nan_inf")
            if a.get(k) != b.get(k)]
