"""Compile-time cost observatory: HLO censuses + rooflines, no chip needed.

Runtime observability (obs/tracer.py) needs a healthy accelerator — a
resource this project's round history shows up rarely (BENCH_r0{2,4,5} are
null on backend-init wedges). This module answers the cost questions
*statically*: it AOT-lowers each staged pipeline stage (and the whole fused
step) over CPU virtual devices — the HLO is backend-shaped by the mesh and
shardings, not chip-timed — and reads, per (stage, mesh config):

- **collective census**: counts and payload bytes of every all-gather /
  all-reduce / reduce-scatter / collective-permute / all-to-all in the
  optimized module. This turns "no cross-chip comm on the critical path"
  (VERDICT Weak #5) from an argument into a table: pure scene-DP compiles
  to zero data collectives (only O(1)-byte while-loop predicates), while
  frame-sharded meshes show exactly which stages pay ICI and how much.
- **fusion & op census**: fusions, copies, transposes, and output-transfer
  bytes — the static half of the post.claims kernel-vs-tunnel question.
- **rooflines**: XLA's own FLOP and bytes-accessed estimates
  (``Compiled.cost_analysis``) plus the buffer-assignment memory plan
  (``Compiled.memory_analysis``), with v5e peak-rate context so the table
  reads as "this stage is HBM-bound, that one is ICI-visible".

Every row is emitted as a schema-versioned ``cost`` event into the obs
JSONL sink; render with ``python -m maskclustering_tpu.obs.report --cost``
or run this module directly::

    JAX_PLATFORMS=cpu python -m maskclustering_tpu.obs.cost \
        --mesh 1x8 --mesh 8x1 --events /tmp/cost_events.jsonl
"""

from __future__ import annotations

import logging
import os
import re
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from maskclustering_tpu.obs.events import KIND_COST, EventSink

log = logging.getLogger("maskclustering_tpu")

# v5e peak rates, used only to contextualize static byte/FLOP counts as
# lower-bound microseconds (HBM: 819 GB/s; ICI: 1600 Gbit/s = 200 GB/s per
# chip across links; MXU: 197 TFLOP/s bf16). Sources: TPU v5e system
# architecture docs; same constants family as scripts/hbm_analysis.py.
V5E_HBM_GBPS = 819.0
V5E_ICI_GBPS = 200.0
V5E_BF16_TFLOPS = 197.0
V5E_HBM_GB = 16.0

COLLECTIVE_OPS = ("all-gather", "all-reduce", "reduce-scatter",
                  "collective-permute", "all-to-all", "collective-broadcast")
_OP_CENSUS_OPS = ("fusion", "copy", "transpose")

# HLO primitive type -> element size in bytes (pred is byte-backed)
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")

# MLIR/StableHLO element type -> byte size (i1 is byte-backed like pred)
_MLIR_DTYPE_BYTES = {
    "i1": 1, "i4": 1, "ui4": 1, "i8": 1, "ui8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1,
    "i16": 2, "ui16": 2, "f16": 2, "bf16": 2,
    "i32": 4, "ui32": 4, "f32": 4,
    "i64": 8, "ui64": 8, "f64": 8,
}

# one stablehlo.dot_general instruction with its typed operand/result list:
#   ... = stablehlo.dot_general %a, %b, ... : (tensor<8x16xi8>,
#   tensor<16x8xi8>) -> tensor<8x8xi32>
_DOT_RE = re.compile(
    r"stablehlo\.dot_general\b[^\n]*?:\s*"
    r"\(tensor<([^>]+)>,\s*tensor<([^>]+)>\)\s*->\s*tensor<([^>]+)>")


def _tensor_info(spec: str) -> Tuple[str, int, int]:
    """('i8', element count, byte size) of a tensor<...> body like '8x16xi8'."""
    parts = spec.split("x")
    dtype = parts[-1]
    count = 1
    for d in parts[:-1]:
        count *= int(d)
    return dtype, count, count * _MLIR_DTYPE_BYTES.get(dtype, 0)


def dot_census(stablehlo_text: str) -> Dict[str, Dict[str, float]]:
    """Per-dtype-class census of every dot_general in a LOWERED module.

    Keyed by ``LHSxRHS->OUT`` (e.g. ``i8xi8->i32``, ``bf16xbf16->f32``) with
    instruction count and total operand bytes. This reads the *StableHLO*
    the backend compiler receives, not the CPU-optimized HLO: the CPU
    backend promotes s8 operands to s32 before its dots (no s8 ALU path),
    which would misreport the MXU op class a TPU actually executes. The
    byte totals are per-instruction static sizes — relative comparisons
    across ``count_dtype`` variants of the SAME program are exact, which is
    all the dtype census needs.
    """
    out: Dict[str, Dict[str, float]] = {}
    for lhs, rhs, res in _DOT_RE.findall(stablehlo_text):
        lt, _, lb = _tensor_info(lhs)
        rt, _, rb = _tensor_info(rhs)
        ot, _, _ = _tensor_info(res)
        key = f"{lt}x{rt}->{ot}"
        row = out.setdefault(key, {"count": 0, "operand_bytes": 0.0})
        row["count"] += 1
        row["operand_bytes"] += float(lb + rb)
    return out


def dot_operand_bytes(census: Dict[str, Dict[str, float]]) -> float:
    return float(sum(c["operand_bytes"] for c in census.values()))


def _element_bytes(type_str: str) -> List[int]:
    """Per-array byte sizes of every shape inside an HLO type string.

    Handles plain (``f32[64,8]{0,1}``), scalar (``pred[]``) and tuple
    (``(f32[8,2], u8[4])``) types; unknown primitive types contribute 0
    (a census must not crash on an exotic dtype).
    """
    out: List[int] = []
    for prim, dims in _SHAPE_RE.findall(type_str):
        size = _DTYPE_BYTES.get(prim)
        if size is None:
            out.append(0)
            continue
        count = 1
        for d in dims.split(","):
            if d:
                count *= int(d)
        out.append(count * size)
    return out


def shape_bytes(type_str: str) -> int:
    """Total byte size of an HLO result type string (tuples sum)."""
    return sum(_element_bytes(type_str))


def _op_pattern(op: str, *, start: bool = False) -> re.Pattern:
    # one HLO instruction: `%name = TYPE op(...)`
    suffix = "-start" if start else ""
    return re.compile(
        r"=\s+(?P<type>\([^=]*?\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)"
        r"\s+" + re.escape(op) + suffix + r"\(")


def collective_census(hlo_text: str) -> Dict[str, Dict[str, float]]:
    """Count + byte-total every collective in an optimized HLO module.

    Bytes are the payload (result-shape) bytes per collective instruction —
    a lower bound on link traffic (ring algorithms move up to 2x) that is
    comparable across mesh configs. Async collectives lower to
    ``op-start``/``op-done`` pairs: the start is counted once and — since
    its tuple type aliases BOTH the operand and result buffers (plus
    context scalars on some backends) — its payload is the largest tuple
    element, not the tuple sum, which would double-count the transfer.
    The done is never counted. Returns only ops that appear.
    """
    out: Dict[str, Dict[str, float]] = {}
    for op in COLLECTIVE_OPS:
        count = 0
        total = 0.0
        # sync form: a tuple result is a variadic collective — sum it
        sync_matches = _op_pattern(op).findall(hlo_text)
        count += len(sync_matches)
        total += sum(shape_bytes(t) for t in sync_matches)
        # async form: tuple holds (operand, result, context...) — max
        start_matches = _op_pattern(op, start=True).findall(hlo_text)
        count += len(start_matches)
        total += sum(max(_element_bytes(t) or [0]) for t in start_matches)
        if count:
            out[op] = {"count": count, "bytes": float(total)}
    return out


def op_census(hlo_text: str) -> Dict[str, int]:
    """Fusion / copy / transpose instruction counts over the module text.

    A textual census (includes fusion-computation bodies): fusions
    approximate kernel-launch count, top-level copies and transposes are
    the layout-churn signal behind the post.claims kernel-vs-tunnel
    question. Async copy-start/copy-done pairs count once (the start).
    """
    return {op: (len(_op_pattern(op).findall(hlo_text))
                 + len(_op_pattern(op, start=True).findall(hlo_text)))
            for op in _OP_CENSUS_OPS}


def ici_bytes(census: Dict[str, Dict[str, float]]) -> float:
    return float(sum(c["bytes"] for c in census.values()))


def analyze_compiled(compiled, *, lower_s: float = 0.0,
                     compile_s: float = 0.0) -> Dict:
    """Extract the full static cost row from a ``jax.stages.Compiled``.

    Never raises on a backend that lacks an analysis — missing pieces are
    None/empty so a row stays renderable.
    """
    row: Dict = {"lower_s": round(lower_s, 3), "compile_s": round(compile_s, 3)}
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    except Exception:  # noqa: BLE001 — analysis is optional per backend
        ca = {}
    row["flops"] = float(ca["flops"]) if "flops" in ca else None
    row["hbm_bytes"] = (float(ca["bytes accessed"])
                        if "bytes accessed" in ca else None)
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        ma = None
    if ma is not None:
        row["arg_bytes"] = float(ma.argument_size_in_bytes)
        row["out_bytes"] = float(ma.output_size_in_bytes)
        row["temp_bytes"] = float(ma.temp_size_in_bytes)
        row["alias_bytes"] = float(ma.alias_size_in_bytes)
        # aliased bytes are counted in both args and outputs
        row["peak_bytes"] = (row["arg_bytes"] + row["out_bytes"]
                             + row["temp_bytes"] - row["alias_bytes"])
    try:
        text = compiled.as_text()
    except Exception:  # noqa: BLE001
        text = ""
    census = collective_census(text)
    row["collectives"] = census
    row["ici_bytes"] = ici_bytes(census)
    row["ops"] = op_census(text)
    return row


# ---------------------------------------------------------------------------
# the observatory driver
# ---------------------------------------------------------------------------

DEFAULT_MESHES: Tuple[Tuple[int, int], ...] = ((1, 8), (8, 1))
ALL_STAGES = ("backprojection", "graph", "clustering", "postprocess", "fused")


def parse_mesh_specs(specs: Sequence[str]) -> List[Tuple[int, ...]]:
    """CLI mesh parsing shared by ``obs.cost`` and ``report --cost``.

    Accepts ``SCENExFRAME`` or ``SCENExFRAMExPOINT`` items, each
    optionally comma-joined (``["1x8", "1x2x4"]`` or ``["1x8,1x2x4"]``).
    Raises ValueError with a message the CLIs can surface instead of a
    traceback.
    """
    meshes: List[Tuple[int, ...]] = []
    for item in specs:
        for m in item.split(","):
            if not m:
                continue
            parts = m.split("x")
            try:
                if len(parts) not in (2, 3):
                    raise ValueError
                meshes.append(tuple(int(p) for p in parts))
            except ValueError:
                raise ValueError(
                    f"bad mesh spec {m!r}: expected SCENExFRAME[xPOINT], "
                    f"e.g. 1x8 or 1x2x4") from None
    return meshes


def ensure_cpu_devices(count: int = 8) -> int:
    """Best-effort: a CPU backend with ``count`` virtual devices.

    Must run before jax initializes a backend (XLA_FLAGS is read at
    backend init, not import). If a backend already exists — e.g. inside
    a pytest session — whatever device count it has is what the caller
    gets; meshes that do not fit are skipped with a warning.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={count}").strip()
    import jax

    try:
        # config, not env: the environment may preload a TPU platform and
        # JAX_PLATFORMS would be read too late (same move as tests/conftest)
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — already initialized elsewhere
        pass
    return jax.device_count()


def default_pipeline_cfg(point_chunk: int):
    """The observatory's lowering config — also the mct-check seam.

    ``analysis/ir_checks.py`` lowers through this exact config so the IR
    invariant gates inspect the same program the cost rows describe.
    """
    from maskclustering_tpu.config import PipelineConfig

    return PipelineConfig(config_name="cost_observatory", dataset="demo",
                          distance_threshold=0.01, few_points_threshold=25,
                          point_chunk=point_chunk)


def observe_costs(
    mesh_shapes: Sequence[Tuple[int, int]] = DEFAULT_MESHES,
    *,
    stages: Sequence[str] = ALL_STAGES,
    frames: int = 8,
    points: int = 1024,
    image_hw: Tuple[int, int] = (24, 32),
    k_max: int = 7,
    cfg=None,
    sink: Optional[EventSink] = None,
    keep_texts: bool = False,
) -> List[Dict]:
    """AOT-lower every (stage, mesh) pair and return/emit the cost rows.

    Scene count per mesh equals the ``scene`` axis size (one scene shard
    per scene group — the honest serving shape); ``frames`` must divide by
    every frame axis requested. Rows are plain dicts (JSON-able); when
    ``sink`` is given each row is also emitted as a ``cost`` event.

    ``keep_texts`` attaches each lowering's StableHLO + optimized-HLO text
    to its row (``"stablehlo"`` / ``"compiled_text"``) so a caller can fan
    further text analyses over ONE sweep — the seam the tier-1 conftest
    fixture shares between the cost tests and ``analysis.ir_checks``.
    The texts never reach the sink (megabytes per event line).
    """
    import jax

    if cfg is None:
        cfg = default_pipeline_cfg(point_chunk=max(256, points // 4))
    from maskclustering_tpu.parallel.mesh import make_mesh
    from maskclustering_tpu.parallel.sharded import (
        build_fused_step,
        build_stage_step,
        stage_arg_shapes,
    )

    n_dev = jax.device_count()
    rows: List[Dict] = []
    fingerprint = {"frames": frames, "points": points,
                   "image_hw": list(image_hw), "k_max": k_max,
                   "backend": jax.default_backend()}
    for mesh_shape in mesh_shapes:
        # 2-tuple = (scene, frame); 3-tuple adds the point axis
        s_ax, f_ax = mesh_shape[0], mesh_shape[1]
        p_ax = mesh_shape[2] if len(mesh_shape) == 3 else 1
        if s_ax * f_ax * p_ax != n_dev:
            log.warning("cost observatory: mesh %s needs %d devices, have %d "
                        "— skipped", mesh_shape, s_ax * f_ax * p_ax, n_dev)
            continue
        if frames % f_ax:
            log.warning("cost observatory: frames=%d not divisible by frame "
                        "axis %d — mesh %s skipped", frames, f_ax, mesh_shape)
            continue
        if points % p_ax:
            log.warning("cost observatory: points=%d not divisible by point "
                        "axis %d — mesh %s skipped", points, p_ax, mesh_shape)
            continue
        mesh = make_mesh(mesh_shape)
        scenes = s_ax
        for stage in stages:
            t0 = time.perf_counter()
            try:
                if stage == "fused":
                    # lower the program production runs: the batch path
                    # compiles the fused step with donation (batch.py
                    # _cached_step), which changes the memory plan's peak
                    step = build_fused_step(mesh, cfg, k_max=k_max,
                                            donate=bool(cfg.donate_buffers))
                    shapes = stage_arg_shapes(
                        "backprojection", scenes=scenes, frames=frames,
                        points=points, image_hw=image_hw, k_max=k_max)
                else:
                    step = build_stage_step(stage, mesh, cfg, k_max=k_max)
                    shapes = stage_arg_shapes(
                        stage, scenes=scenes, frames=frames, points=points,
                        image_hw=image_hw, k_max=k_max,
                        max_iters=cfg.max_cluster_iterations)
                lowered = step.lower(*shapes)
                t1 = time.perf_counter()
                compiled = lowered.compile()
                t2 = time.perf_counter()
            except Exception as e:  # noqa: BLE001 — one stage must not sink the sweep
                log.exception("cost observatory: %s @ mesh %s failed",
                              stage, mesh_shape)
                rows.append({"stage": stage, "mesh": list(mesh_shape),
                             "error": f"{type(e).__name__}: {e}",
                             "fingerprint": fingerprint})
                continue
            row = analyze_compiled(compiled, lower_s=t1 - t0,
                                   compile_s=t2 - t1)
            try:
                # the dot dtype census reads the pre-optimization StableHLO
                # (the program a TPU backend receives; the CPU pipeline
                # rewrites s8 dots to s32 and would misreport the MXU class)
                stablehlo = lowered.as_text()
                row["dots"] = dot_census(stablehlo)
            except Exception:  # noqa: BLE001 — census is best-effort
                stablehlo = None
                row["dots"] = {}
            row.update({"stage": stage, "mesh": list(mesh_shape),
                        "devices": n_dev, "count_dtype": cfg.count_dtype,
                        "fingerprint": fingerprint})
            if sink is not None:
                sink.emit(KIND_COST, row)  # before the texts ride along
            if keep_texts and stablehlo is not None:
                row["stablehlo"] = stablehlo
                row["compiled_text"] = compiled.as_text()
            rows.append(row)
            log.info("cost observatory: %s @ mesh %s: %d collective(s), "
                     "%.0f ICI bytes", stage, mesh_shape,
                     sum(c["count"] for c in row["collectives"].values()),
                     row["ici_bytes"])
    return rows


def compare_dtypes(
    mesh_shapes: Sequence[Tuple[int, int]] = DEFAULT_MESHES,
    *,
    stages: Sequence[str] = ALL_STAGES,
    frames: int = 8,
    points: int = 1024,
    image_hw: Tuple[int, int] = (24, 32),
    k_max: int = 7,
    cfg=None,
    sink: Optional[EventSink] = None,
) -> Tuple[Dict[str, List[Dict]], List[Dict]]:
    """A/B the whole observatory across ``count_dtype`` encodings.

    Lowers every (stage, mesh) pair twice — ``count_dtype="bf16"`` and
    ``"int8"`` — and returns ``(rows_by_dtype, diff_rows)``. Each diff row
    compares one (stage, mesh):

    - ``narrowed_*``: the dot classes that CHANGED between the variants
      (the counting contractions this repo dispatches through
      ops/counting.py) with their operand bytes per variant and the
      reduction ratio — the "is the MXU really fed narrower operands"
      evidence;
    - ``stable_dots``: classes identical in both variants (the audited
      stays-wide set: f32 geometry/projection matmuls);
    - memory-plan deltas (``peak/arg/out`` bytes) from XLA's buffer
      assignment.

    Rows are also emitted as ``cost`` events (tagged ``count_dtype``) when
    ``sink`` is given, so ``report --cost`` renders both variants later.
    """
    if cfg is None:
        cfg = default_pipeline_cfg(point_chunk=max(256, points // 4))
    rows_by: Dict[str, List[Dict]] = {}
    for cd in ("bf16", "int8"):
        rows_by[cd] = observe_costs(
            mesh_shapes, stages=stages, frames=frames, points=points,
            image_hw=image_hw, k_max=k_max,
            cfg=cfg.replace(count_dtype=cd), sink=sink)

    def _key(r):
        return (r.get("stage"), tuple(r.get("mesh") or ()))

    bf_rows = {_key(r): r for r in rows_by["bf16"]}
    diffs: List[Dict] = []
    for r8 in rows_by["int8"]:
        rb = bf_rows.get(_key(r8))
        if rb is None or "error" in r8 or "error" in rb:
            continue
        dots_b = rb.get("dots") or {}
        dots_8 = r8.get("dots") or {}
        stable = {k: dots_b[k] for k in dots_b
                  if k in dots_8 and dots_8[k] == dots_b[k]}
        narrowed_b = {k: v for k, v in dots_b.items() if k not in stable}
        narrowed_8 = {k: v for k, v in dots_8.items() if k not in stable}
        nb = dot_operand_bytes(narrowed_b)
        n8 = dot_operand_bytes(narrowed_8)
        diffs.append({
            "stage": r8["stage"], "mesh": r8.get("mesh"),
            "narrowed_bf16": narrowed_b, "narrowed_int8": narrowed_8,
            "narrowed_bytes_bf16": nb, "narrowed_bytes_int8": n8,
            "operand_byte_ratio": (nb / n8) if n8 else None,
            "stable_dots": stable,
            "peak_bytes_bf16": rb.get("peak_bytes"),
            "peak_bytes_int8": r8.get("peak_bytes"),
            "arg_bytes": r8.get("arg_bytes"),
            "out_bytes_bf16": rb.get("out_bytes"),
            "out_bytes_int8": r8.get("out_bytes"),
            "fingerprint": r8.get("fingerprint"),
        })
    return rows_by, diffs


def claim_plane_bytes(frames: int, points: int) -> Dict[str, float]:
    """Static size of the two (F, N) first/last claim planes per scene.

    The int16 narrowing is unconditional (not count_dtype-gated), so the
    A/B cannot show it as a delta; this puts the halving on the record
    next to the census: 2 planes x F x N x 2 bytes, vs the historical
    int32 layout's x4.
    """
    return {"int16": 2.0 * frames * points * 2,
            "int32_historical": 2.0 * frames * points * 4}


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m maskclustering_tpu.obs.cost",
        description="AOT cost observatory: collective census + rooflines "
                    "per (stage, mesh), computed on CPU virtual devices")
    p.add_argument("--mesh", action="append", default=None,
                   metavar="SxF[xP]",
                   help="mesh config, e.g. 1x8 or 1x2x4 — a third factor "
                        "shards the point axis (repeatable; default: 1x8 "
                        "and 8x1)")
    p.add_argument("--stages", default=",".join(ALL_STAGES),
                   help=f"comma-separated subset of {ALL_STAGES}")
    p.add_argument("--frames", type=int, default=8)
    p.add_argument("--points", type=int, default=1024)
    p.add_argument("--image-h", type=int, default=24)
    p.add_argument("--image-w", type=int, default=32)
    p.add_argument("--k-max", type=int, default=7)
    p.add_argument("--events", default=None,
                   help="append cost events to this JSONL (render later with "
                        "obs.report --cost)")
    p.add_argument("--devices", type=int, default=8,
                   help="CPU virtual device count to request")
    p.add_argument("--compare-dtypes", action="store_true",
                   help="A/B every (stage, mesh) across count_dtype bf16 vs "
                        "int8: dot-class census diff, operand bytes, memory-"
                        "plan delta (see README 'Reading the dtype census')")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(message)s")
    ensure_cpu_devices(args.devices)
    try:
        meshes = parse_mesh_specs(args.mesh or ["1x8", "8x1"])
    except ValueError as e:
        p.error(str(e))

    sink = EventSink(args.events) if args.events else None
    stages = tuple(s for s in args.stages.split(",") if s)
    if args.compare_dtypes:
        from maskclustering_tpu.obs.report import render_dtype_compare

        rows_by, diffs = compare_dtypes(
            meshes, stages=stages, frames=args.frames, points=args.points,
            image_hw=(args.image_h, args.image_w), k_max=args.k_max,
            sink=sink)
        if sink is not None:
            sink.close()
        print(render_dtype_compare(
            diffs, planes=claim_plane_bytes(args.frames, args.points)))
        ok = [r for rows in rows_by.values() for r in rows if "error" not in r]
        return 0 if diffs and ok else 1
    rows = observe_costs(
        meshes, stages=stages,
        frames=args.frames, points=args.points,
        image_hw=(args.image_h, args.image_w), k_max=args.k_max, sink=sink)
    if sink is not None:
        sink.close()
    from maskclustering_tpu.obs.report import render_cost

    print(render_cost(rows))
    ok = [r for r in rows if "error" not in r]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
