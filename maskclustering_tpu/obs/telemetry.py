"""Live serving telemetry plane: cross-process relay + windowed snapshots.

The PR-1/2 obs stack is process-local and post-hoc: spans and counters
live in one process's registry and become readable only when that process
flushes an events file at exit. That breaks exactly where it matters most
— the serving daemon. Under ``--isolate-worker`` every pipeline counter
(``d2h.bytes.*``, ``pipeline.host_sync``, the AOT-cache and retrace
digests) is booked in the worker SUBPROCESS and stranded there, and even
the in-process daemon answers ``status`` with a point-in-time queue depth
only. This module makes the daemon watchable live and topology-invariant:

- **cross-process relay** — the worker subprocess periodically (and at
  request boundaries) ships a ``telem`` line over the existing stdio
  JSONL pipe: counter/gauge DELTAS of its metrics registry
  (``metrics.snapshot_delta``) plus the spans completed since the last
  flush. The supervisor folds counters into the parent registry under the
  SAME flat names and REPLAYS the spans through ``obs.record_span`` —
  so the Serving report, the span tables and the windowed aggregator read
  identically in-process and isolated, modulo the ``worker.*`` relay
  bookkeeping counters and a ``worker_pid`` span attr (the process tag).
- **windowed aggregation** — a rolling bounded ring of per-window rows
  (request latency by shape bucket, queue depth/wait, rejects by reason,
  worker crashes/respawns, AOT hits, post-warm compile violations),
  closed by a ticker thread at a fixed cadence and appended as
  schema-versioned ``telemetry`` rows to the events JSONL when obs is
  armed. The daemon's ``status`` op serves the ring over the wire
  (``detail: "telemetry"``) — ``obs.top`` renders it live, and a crash
  leaves every closed window on disk.

Thread shape (mct-threads clean): the module-global aggregator handle is
guarded by its own ``mct_lock``; the aggregator never calls into another
locked subsystem while holding its lock (registry snapshots are taken
BEFORE the window lock, event emission happens AFTER release), and the
ticker thread is bounded-joined at stop.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from maskclustering_tpu.analysis.lock_sanitizer import mct_lock
from maskclustering_tpu.obs import metrics as _metrics
from maskclustering_tpu.obs.events import KIND_SPAN, KIND_TELEMETRY
from maskclustering_tpu.obs.metrics import Histogram

TELEM_SCHEMA = 1          # the pipe message's own version stamp
KIND_TELEM = "telem"      # the stdio-pipe message kind (worker -> parent)

# bounded relay buffers: a burst must cost dropped SPANS (counted), never
# unbounded child memory or a pipe line the parent cannot parse
RELAY_SPAN_CAP = 1024
# counter families worth shipping verbatim in a window's cumulative view
CUMULATIVE_PREFIXES = ("serve.", "retrace.", "aot_cache.", "worker.",
                      "pipeline.", "run.", "compile_cache.", "canary.")


def _bucket_key(bucket) -> str:
    """One stable string key per shape bucket ('all' when unknown)."""
    if not bucket:
        return "all"
    try:
        return "x".join(str(int(b)) for b in bucket)
    except (TypeError, ValueError):
        return str(bucket)


# ---------------------------------------------------------------------------
# child half: relay sink + delta collector (serve/worker_main.py)
# ---------------------------------------------------------------------------


class RelaySink:
    """An in-memory span buffer with the EventSink emit surface.

    The worker subprocess arms its tracer with this instead of a file:
    completed spans queue here (bounded; overflow counted, never blocking)
    until the next ``telem`` flush ships them up the pipe. Metrics-flush
    events are ignored — the relay ships registry DELTAS itself.
    """

    path = "<telemetry-relay>"

    def __init__(self, cap: int = RELAY_SPAN_CAP):
        self._lock = mct_lock("obs.telemetry.RelaySink._lock")
        self._spans: Deque[Dict] = deque(maxlen=cap)
        self._dropped = 0

    def emit(self, kind: str, payload: Dict) -> None:
        if kind != KIND_SPAN:
            return
        row = {"name": payload.get("name"),
               "dur_s": payload.get("dur_s", 0.0),
               "sync_s": payload.get("sync_s", 0.0),
               "depth": payload.get("depth", 0),
               "ts": time.time()}  # close time on the CHILD's epoch clock
        if payload.get("parent"):
            row["parent"] = payload["parent"]
        if payload.get("attrs"):
            row["attrs"] = payload["attrs"]
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._spans.append(row)

    def close(self) -> None:
        return None

    def drain(self) -> tuple:
        """(spans, dropped-since-last-drain) — one flush's payload."""
        with self._lock:
            spans = list(self._spans)
            self._spans.clear()
            dropped, self._dropped = self._dropped, 0
        return spans, dropped


class ChildRelay:
    """The worker subprocess's telemetry source: one ``collect()`` per
    flush returns the ``telem`` pipe document (or None when nothing
    changed — idle heartbeat windows cost zero pipe traffic).

    ``collect()`` is serialized by its own lock: worker_main flushes from
    TWO threads (the heartbeat ticker and the device-worker thread at
    request boundaries), and an unserialized read-modify-write of the
    delta baseline would diff two snapshots against the SAME ``_prev``
    and double-ship the increments — breaking exactly the counter parity
    the relay exists to provide.
    """

    def __init__(self, sink: Optional[RelaySink] = None):
        self.sink = sink or RelaySink()
        self._lock = mct_lock("obs.telemetry.ChildRelay._lock")
        self._seq = 0
        self._prev: Dict = {}

    def collect(self) -> Optional[Dict]:
        # live retrace gauges ride the delta so the PARENT's windows can
        # show a post-warm violation the moment it happens, not at bye
        try:
            from maskclustering_tpu.analysis import retrace_sanitizer

            if retrace_sanitizer.enabled():
                s = retrace_sanitizer.summary()
                _metrics.gauge("retrace.live.compiles", float(s["compiles"]))
                _metrics.gauge("retrace.live.post_freeze",
                               float(s["post_freeze"]))
                _metrics.gauge("retrace.live.repeats", float(s["repeats"]))
        except Exception:  # noqa: BLE001 — telemetry never faults the worker
            pass
        with self._lock:
            cur = _metrics.registry().snapshot(include_histograms=False)
            delta = _metrics.snapshot_delta(self._prev, cur)
            self._prev = cur
            spans, dropped = self.sink.drain()
            if not (delta["counters"] or delta["gauges"] or spans or dropped):
                return None
            self._seq += 1
            seq = self._seq
        doc: Dict = {"kind": KIND_TELEM, "v": TELEM_SCHEMA, "seq": seq,
                     "metrics": delta}
        if spans:
            doc["spans"] = spans
        if dropped:
            doc["spans_dropped"] = dropped
        return doc


# ---------------------------------------------------------------------------
# parent half: folding relayed telemetry into this process (supervisor)
# ---------------------------------------------------------------------------


def fold_telem(doc: Dict, *, child_pid: Optional[int] = None,
               worker_id: Optional[int] = None) -> None:
    """Fold one relayed ``telem`` line into THIS process's obs state.

    Counters land under their own flat names (topology invariance: the
    Serving report cannot tell a relayed ``d2h.bytes.post.drain`` from a
    locally-booked one); spans replay through ``obs.record_span`` so the
    events file and the span histograms carry real samples. The relay's
    own bookkeeping is the ``worker.*`` process tag. ``worker_id`` (the
    pool slice that relayed this doc) stamps every replayed span so the
    obs plane — and the pool drill's concurrency-overlap check — can
    attribute device phases per worker.
    """
    from maskclustering_tpu import obs

    if doc.get("v") != TELEM_SCHEMA:
        obs.count("worker.telem_unknown_version")
        return
    _metrics.merge_snapshot_delta(doc.get("metrics") or {})
    obs.count("worker.telem_messages")
    if doc.get("spans_dropped"):
        obs.count("worker.telem_spans_dropped", float(doc["spans_dropped"]))
    spans = doc.get("spans") or ()
    if spans:
        obs.count("worker.telem_spans", float(len(spans)))
    for row in spans:
        name = row.get("name")
        dur = row.get("dur_s")
        if not isinstance(name, str) or not isinstance(dur, (int, float)):
            continue
        attrs = dict(row.get("attrs") or {})
        if child_pid is not None:
            attrs["worker_pid"] = child_pid
        if worker_id is not None:
            attrs["worker_id"] = worker_id
        if row.get("ts") is not None:
            # the CHILD's close time: obs/trace.py anchors relayed spans on
            # this, not on the (later) parent re-emit timestamp
            attrs["end_ts"] = row["ts"]
        obs.record_span(name, float(dur), parent=row.get("parent"),
                        sync_s=float(row.get("sync_s") or 0.0), **attrs)


# ---------------------------------------------------------------------------
# windowed aggregation (the daemon's rolling view)
# ---------------------------------------------------------------------------

# counter names a window reads as deltas between consecutive ticks
_WINDOW_STATUSES = ("ok", "failed", "deadline", "skipped", "interrupted")
_SAMPLE_CAP = 512  # per-window raw latency/wait samples before drop-count

# tenant accounting bounds: identities are client-supplied (validated,
# length-capped by the protocol), so the aggregator additionally caps how
# many DISTINCT tenants it tracks per process — overflow lumps into one
# bucket instead of growing every window row without bound
_TENANT_CAP = 32
_TENANT_OVERFLOW = "(other)"
_TENANT_SAMPLE_CAP = 256  # per-tenant per-window raw samples


def _attrib_counters() -> Dict[str, float]:
    """The two attribution totals (fenced device seconds, d2h bytes) —
    read OUTSIDE the window lock (registry has its own lock). Both are
    plain counters, so the cross-process relay's delta fold keeps them
    topology-invariant: a child-booked d2h byte reads like a local one."""
    c = _metrics.registry().snapshot(include_histograms=False).get(
        "counters") or {}
    return {"device_s": float(c.get("device.seconds", 0.0)),
            "d2h_bytes": float(c.get("d2h.bytes", 0.0))}


def _new_tenant_slot() -> Dict:
    return {"requests": 0, "by_status": {}, "rejects": 0, "crashes": 0,
            "device_s": 0.0, "d2h_bytes": 0.0, "latency": {},
            "queue_wait": []}


def _tenant_rows(store: Dict[str, Dict]) -> Dict[str, Dict]:
    """JSON-able per-tenant sub-rows (samples summarized, zeros elided)."""
    out: Dict[str, Dict] = {}
    for t, s in sorted(store.items()):
        row: Dict[str, Any] = {"requests": int(s["requests"])}
        if s["by_status"]:
            row["by_status"] = dict(s["by_status"])
        if s["rejects"]:
            row["rejects"] = int(s["rejects"])
        if s["crashes"]:
            row["crashes"] = int(s["crashes"])
        if s["device_s"]:
            row["device_s"] = round(s["device_s"], 4)
        if s["d2h_bytes"]:
            row["d2h_bytes"] = int(s["d2h_bytes"])
        lat = {k: _hist_summary(v) for k, v in sorted(s["latency"].items())}
        lat = {k: v for k, v in lat.items() if v}
        if lat:
            row["latency"] = lat
        qw = _hist_summary(s["queue_wait"])
        if qw:
            row["queue_wait"] = qw
        out[t] = row
    return out


def _hist_summary(vals: List[float]) -> Optional[Dict]:
    if not vals:
        return None
    from maskclustering_tpu.obs.report import percentile

    s = sorted(vals)
    return {"count": len(s), "p50_s": round(percentile(s, 50), 4),
            "p95_s": round(percentile(s, 95), 4), "max_s": round(s[-1], 4)}


class WindowAggregator:
    """Rolling ring of per-window serving digests.

    ``record_request``/``record_queue_wait`` feed the current window from
    the worker/supervisor threads (bounded per-window sample lists; the
    overflow is counted, never grown); ``roll()`` — the ticker's tick —
    closes the window against a registry snapshot taken OUTSIDE the
    window lock and appends it to the bounded ring. Cumulative per-bucket
    latency rides ``metrics.Histogram`` (stride-decimated, capped), so a
    daemon serving for days keeps O(ring + cap) memory.
    """

    def __init__(self, window_s: float = 5.0, ring: int = 120):
        self.window_s = max(float(window_s), 0.05)
        self._lock = mct_lock("obs.telemetry.WindowAggregator._lock")
        self._windows: Deque[Dict] = deque(maxlen=max(int(ring), 2))
        self._t0 = time.time()
        self._latency: Dict[str, List[float]] = {}
        self._waits: List[float] = []
        self._dropped = 0
        self._prev_counters: Dict[str, float] = {}
        self._prev_post_freeze = 0.0
        self._cum_hist: Dict[str, Histogram] = {}
        # tenant accounting: current-window slots, monotone cumulative
        # slots, and one capped histogram per tenant (all bounded by
        # _TENANT_CAP; overflow lumps into _TENANT_OVERFLOW)
        self._tenants: Dict[str, Dict] = {}
        self._cum_tenants: Dict[str, Dict] = {}
        self._cum_tenant_hist: Dict[str, Histogram] = {}
        # per-pool-slice completion counts for the current window (keyed
        # by str(worker_id); single-worker daemons never populate it)
        self._workers: Dict[str, int] = {}
        # the device-seconds / d2h attribution baseline: the counter
        # totals at the PREVIOUS request completion — one worker
        # serializes requests, so the delta between consecutive
        # completions is the finishing request's consumption (under the
        # isolated worker the relay's flush-before-result ordering folds
        # the child's counters before the result books here)
        self._prev_attrib = _attrib_counters()
        self.started_at = time.time()

    def rebase(self) -> None:
        """Re-anchor the delta baseline and window clock to NOW.

        Called when the daemon starts ticking (AFTER warm-up): without
        it, window 0 would charge the whole warm-up wall and its counter
        deltas (AOT restores, prewarm dispatches) to itself — serving
        rates diluted by startup that served nothing.
        """
        snap = _metrics.registry().snapshot(include_histograms=False)
        post_freeze = self._post_freeze_cum(snap.get("gauges") or {})
        attrib = _attrib_counters()
        with self._lock:  # like roll(): no other lock acquired inside
            self._prev_counters = dict(snap.get("counters") or {})
            self._prev_post_freeze = post_freeze
            self._prev_attrib = attrib  # warm-up device time charges no one
            self._t0 = time.time()
            self._latency = {}
            self._waits = []
            self._tenants = {}
            self._workers = {}

    # -- recorders (worker / supervisor threads) ----------------------------

    def _tenant_slot(self, store: Dict[str, Dict], tenant: str) -> Dict:
        """The tenant's accumulation slot (capped; overflow shared). Caller
        holds the window lock."""
        key = tenant if (tenant in store or len(store) < _TENANT_CAP) \
            else _TENANT_OVERFLOW
        slot = store.get(key)
        if slot is None:
            slot = store[key] = _new_tenant_slot()
        return slot

    def record_request(self, bucket, latency_s: float, *,
                       tenant: str = "", status: str = "ok",
                       worker: Optional[int] = None) -> None:
        """Book one finished request's latency under its shape bucket.

        The cumulative stride-decimated histogram observes EVERY sample
        (it exists precisely to absorb unbounded load); only the current
        window's raw list is capped, and independently of the queue-wait
        list — a wait burst must not starve the latency view.

        ``tenant`` attributes the request (count, status, latency sample,
        and the device-seconds / d2h-bytes consumed since the previous
        completion) to its accounting identity; "" books globally only.
        ``worker`` attributes the completion to a pool slice (the window
        row's ``workers`` map; None under a single-worker daemon).
        """
        key = _bucket_key(bucket)
        attrib = _attrib_counters()  # registry lock BEFORE the window lock
        with self._lock:
            if worker is not None:
                wk = str(int(worker))
                self._workers[wk] = self._workers.get(wk, 0) + 1
            dev_delta = max(attrib["device_s"]
                            - self._prev_attrib["device_s"], 0.0)
            d2h_delta = max(attrib["d2h_bytes"]
                            - self._prev_attrib["d2h_bytes"], 0.0)
            # every completion advances the baseline — an untenanted
            # request's consumption is attributed to no one, not to the
            # NEXT tenanted request
            self._prev_attrib = attrib
            if tenant:
                for store in (self._tenants, self._cum_tenants):
                    slot = self._tenant_slot(store, tenant)
                    slot["requests"] += 1
                    slot["by_status"][status] = \
                        slot["by_status"].get(status, 0) + 1
                    slot["device_s"] += dev_delta
                    slot["d2h_bytes"] += d2h_delta
                wslot = self._tenant_slot(self._tenants, tenant)
                samples = wslot["latency"].setdefault(key, [])
                if len(samples) < _TENANT_SAMPLE_CAP:
                    samples.append(float(latency_s))
                th = self._cum_tenant_hist.get(tenant)
                if th is None and len(self._cum_tenant_hist) < _TENANT_CAP:
                    th = self._cum_tenant_hist.setdefault(tenant, Histogram())
                if th is not None:
                    th.observe(float(latency_s))
            h = self._cum_hist.get(key)
            if h is None:
                h = self._cum_hist.setdefault(key, Histogram())
            h.observe(float(latency_s))
            if sum(len(v) for v in self._latency.values()) >= _SAMPLE_CAP:
                self._dropped += 1
                return
            self._latency.setdefault(key, []).append(float(latency_s))

    def record_queue_wait(self, wait_s: float, *, tenant: str = "") -> None:
        with self._lock:
            if tenant:
                samples = self._tenant_slot(self._tenants,
                                            tenant)["queue_wait"]
                if len(samples) < _TENANT_SAMPLE_CAP:
                    samples.append(float(wait_s))
            if len(self._waits) >= _SAMPLE_CAP:
                self._dropped += 1
                return
            self._waits.append(float(wait_s))

    def record_reject(self, tenant: str) -> None:
        """Attribute one admission/deadline reject to its tenant (global
        reject counts stay counter-delta driven at roll time)."""
        if not tenant:
            return
        with self._lock:
            for store in (self._tenants, self._cum_tenants):
                self._tenant_slot(store, tenant)["rejects"] += 1

    def record_crash(self, tenant: str) -> None:
        """Attribute one worker crash to the tenant whose request it was
        executing (supervisor._on_crash)."""
        if not tenant:
            return
        with self._lock:
            for store in (self._tenants, self._cum_tenants):
                self._tenant_slot(store, tenant)["crashes"] += 1

    # -- the tick -----------------------------------------------------------

    def _counter_deltas(self, counters: Dict[str, float]) -> Dict[str, float]:
        out = _metrics.snapshot_delta({"counters": self._prev_counters},
                                      {"counters": counters})["counters"]
        self._prev_counters = dict(counters)
        return out

    def _post_freeze_cum(self, gauges: Dict[str, float]) -> float:
        """Cumulative post-warm violations, live: the relayed gauge when a
        worker subprocess ships one, else this process's own sanitizer."""
        v = gauges.get("retrace.live.post_freeze")
        if v is not None:
            return float(v)
        try:
            from maskclustering_tpu.analysis import retrace_sanitizer

            if retrace_sanitizer.enabled():
                return float(retrace_sanitizer.summary()["post_freeze"])
        except Exception:  # noqa: BLE001
            pass
        return 0.0

    def roll(self) -> Dict:
        """Close the current window; returns the (JSON-able) window row."""
        # registry lock NOT nested; histogram summaries skipped — the
        # window derives nothing from them and each costs a reservoir sort
        snap = _metrics.registry().snapshot(include_histograms=False)
        counters = snap.get("counters") or {}
        gauges = snap.get("gauges") or {}
        now = time.time()
        post_freeze_cum = self._post_freeze_cum(gauges)
        with self._lock:
            deltas = self._counter_deltas(counters)
            latency = {k: _hist_summary(v)
                       for k, v in sorted(self._latency.items())}
            waits = _hist_summary(self._waits)
            dropped = self._dropped
            self._latency = {}
            self._waits = []
            self._dropped = 0
            pf_delta = post_freeze_cum - self._prev_post_freeze
            self._prev_post_freeze = post_freeze_cum
            row: Dict[str, Any] = {
                "t0": round(self._t0, 3),
                "dur_s": round(now - self._t0, 3),
                "requests": int(deltas.get("serve.requests", 0)),
                "by_status": {s: int(deltas[f"serve.requests_{s}"])
                              for s in _WINDOW_STATUSES
                              if deltas.get(f"serve.requests_{s}")},
                "rejects": self._reject_deltas(deltas),
                "crashes": int(deltas.get("serve.worker_crashes", 0)),
                "respawns": int(deltas.get("serve.worker_respawns", 0)),
                "requeued": int(deltas.get("serve.requests_requeued", 0)),
                "aot_hits": int(deltas.get("aot_cache.hits", 0)),
                "post_warm_compiles": int(max(pf_delta, 0)),
                # mct-sentinel: canary drift occurrences this window (the
                # SLO ``correctness`` objective reads this field; probes
                # ride along for the panel's coverage view)
                "drift": int(deltas.get("canary.drift", 0)),
                "canary_probes": int(deltas.get("canary.probes", 0)),
                "queue_depth": int(gauges.get("serve.queue_depth", 0)),
                "latency": {k: v for k, v in latency.items() if v},
            }
            if waits:
                row["queue_wait"] = waits
            if dropped:
                row["samples_dropped"] = dropped
            # continuous batching (serve.batch.* counters; relayed fold
            # included, so the isolated child's packing shows up here
            # too): this window's dispatch count + mean occupancy
            dispatches = int(deltas.get("serve.batch.dispatches", 0))
            if dispatches:
                packed = int(deltas.get("serve.batch.packed_requests", 0))
                row["batch"] = {
                    "dispatches": dispatches,
                    "packed_requests": packed,
                    "occupancy": round(packed / dispatches, 3),
                    "pad_lanes": int(deltas.get("serve.batch.pad_lanes", 0)),
                }
            if self._tenants:
                row["tenants"] = _tenant_rows(self._tenants)
                self._tenants = {}
            if self._workers:
                row["workers"] = dict(sorted(self._workers.items()))
                self._workers = {}
            self._windows.append(row)
            self._t0 = now
        return row

    @staticmethod
    def _reject_deltas(deltas: Dict[str, float]) -> Dict[str, int]:
        out: Dict[str, int] = {}
        prefix = "serve.admission.rejects."
        for k, v in deltas.items():
            if k.startswith(prefix):
                out[k[len(prefix):]] = out.get(k[len(prefix):], 0) + int(v)
        if deltas.get("serve.rejects.deadline"):
            out["deadline"] = (out.get("deadline", 0)
                               + int(deltas["serve.rejects.deadline"]))
        return out

    # -- reads --------------------------------------------------------------

    def snapshot(self) -> Dict:
        """Wire/CLI shape: ring + in-progress window + cumulative digest.

        The registry snapshot happens before the window lock (no nested
        lock acquisition); the returned structure is plain JSON-able data.
        """
        snap = _metrics.registry().snapshot(include_histograms=False)
        counters = {k: v for k, v in (snap.get("counters") or {}).items()
                    if k.startswith(CUMULATIVE_PREFIXES)}
        gauges = {k: v for k, v in (snap.get("gauges") or {}).items()
                  if k.startswith(("serve.", "retrace.", "hbm.", "worker."))}
        now = time.time()
        with self._lock:
            windows = list(self._windows)
            current = {
                "t0": round(self._t0, 3),
                "dur_s": round(now - self._t0, 3),
                "latency": {k: _hist_summary(v)
                            for k, v in sorted(self._latency.items()) if v},
                "queue_wait": _hist_summary(self._waits),
            }
            if self._tenants:
                current["tenants"] = _tenant_rows(self._tenants)
            cum_latency = {k: h.summary()
                           for k, h in sorted(self._cum_hist.items())}
            cum_tenants = _tenant_rows(self._cum_tenants)
            for t, h in sorted(self._cum_tenant_hist.items()):
                if t in cum_tenants:
                    cum_tenants[t]["latency"] = {"all": h.summary()}
        cumulative: Dict[str, Any] = {"counters": counters, "gauges": gauges,
                                      "latency": cum_latency}
        if cum_tenants:
            cumulative["tenants"] = cum_tenants
        return {"v": TELEM_SCHEMA, "window_s": self.window_s,
                "started_at": self.started_at,
                "windows": windows, "current": current,
                "cumulative": cumulative}


class TelemetryTicker:
    """The daemon's sampling thread: one ``roll()`` per window, each
    closed row appended to the obs events file (when armed) as a
    crash-safe ``telemetry`` line. Bounded-joined at stop."""

    def __init__(self, aggregator: WindowAggregator):
        self.aggregator = aggregator
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(  # mct-thread: abandon(daemon-lifetime ticker, bounded-joined in stop(); the spawn/join pair spans methods, which the scope-local check cannot see)
            target=self._run, daemon=True, name="telemetry-ticker")
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout_s)
            self._thread = None
        # one final roll so the shutdown tail (last requests, the drain's
        # rejects) is a window on disk, not lost in-progress state
        self._emit(self.aggregator.roll())

    def _emit(self, row: Dict) -> None:
        from maskclustering_tpu import obs

        try:
            obs.emit_event(KIND_TELEMETRY, row)
        except Exception:  # noqa: BLE001 — telemetry never faults serving
            pass

    def _run(self) -> None:
        while not self._stop.wait(self.aggregator.window_s):
            self._emit(self.aggregator.roll())


# ---------------------------------------------------------------------------
# module-global plumbing: the serving code records against whatever
# aggregator the daemon installed; a process without one (the one-shot
# CLI, the worker subprocess) records into a no-op
# ---------------------------------------------------------------------------

_AGG_LOCK = mct_lock("obs.telemetry._agg_lock")
_AGGREGATOR: Optional[WindowAggregator] = None


def install(aggregator: Optional[WindowAggregator]) -> None:
    global _AGGREGATOR
    with _AGG_LOCK:
        _AGGREGATOR = aggregator


def installed() -> Optional[WindowAggregator]:
    with _AGG_LOCK:
        return _AGGREGATOR


def record_request(bucket, latency_s: float, *, tenant: str = "",
                   status: str = "ok",
                   worker: Optional[int] = None) -> None:
    """Book one finished request into the current window (no-op without an
    installed aggregator — i.e. outside a daemon parent process). Window
    status attribution comes from the serve.requests_* counter deltas at
    roll time, not from this call; the per-TENANT sub-windows, which
    cannot be split out of relayed counters, come from ``tenant``/
    ``status`` here — both call sites (worker._finish_request in-process,
    supervisor._book_result/_serve_one isolated) are parent-side, which
    is what keeps tenant windows topology-invariant."""
    agg = installed()
    if agg is not None:
        agg.record_request(bucket, latency_s, tenant=tenant, status=status,
                           worker=worker)


def record_queue_wait(req, wait_s: float) -> None:
    """Book one request's ack->dequeue wait: the window's queue_wait
    histogram plus a zero-width ``serve.queue_wait`` span (obs/trace.py's
    queue-wait segment). No-op outside a daemon parent process."""
    agg = installed()
    if agg is None:
        return
    agg.record_queue_wait(wait_s, tenant=getattr(req, "tenant", ""))
    from maskclustering_tpu import obs

    obs.observe("serve.queue_wait_s", float(wait_s))
    obs.record_span("serve.queue_wait", float(wait_s), request=req.id,
                    scene=req.scene, end_ts=time.time())


def record_reject(tenant: str) -> None:
    """Attribute one reject to its tenant (no-op untenanted / undaemoned)."""
    agg = installed()
    if agg is not None:
        agg.record_reject(tenant)


def record_crash(tenant: str) -> None:
    """Attribute one worker crash to its tenant (supervisor._on_crash)."""
    agg = installed()
    if agg is not None:
        agg.record_crash(tenant)
