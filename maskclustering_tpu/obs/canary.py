"""mct-sentinel canary plane: golden probes against committed digests.

The other half of obs/digest.py: a serving daemon periodically replays
its warm-up scenes (the router's ``--warm-baseline`` fitted tensors, so
canaries never compile and never regenerate scenes host-side) and
compares the resulting invariant digests BYTE-FOR-BYTE against a
committed ``canary_goldens.json``. A clean probe proves the daemon still
produces the committed answers; a mismatch is **drift** — silent data
corruption, a numerics regression behind a knob flip, a rung that stopped
being byte-identical — and trips the whole correctness plane:

- a typed ``canary.drift`` event on the armed obs sink,
- a FlightRecorder postmortem dump naming the offending coordinate,
- the ``canary.drift`` counter, which the telemetry window folds into a
  ``drift`` field and the SLO plane's zero-tolerance ``correctness``
  objective pages on.

Goldens are versioned like ``compile_surface_baseline.json``: regenerated
ONLY via the audited ``--write-goldens`` flow (scripts/load_gen.py), and
their coordinate set is ratcheted by mct-check (growth and shrinkage both
fail loudly — analysis/retrace.check_goldens).

Canary traffic is fenced from tenant accounting, admission metering, the
latency window and serve ledger gating by construction: probes execute
through ``ServeWorker.run_canary`` (never the admission queue), book only
``canary.*`` counters, and the ledger stamps ``canary_drift`` so
--regress fences drifted rows both ways (obs/ledger.sentinel_dimension).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from maskclustering_tpu import obs
from maskclustering_tpu.analysis.lock_sanitizer import mct_lock
from maskclustering_tpu.obs import digest as digest_mod
from maskclustering_tpu.obs import flight

log = logging.getLogger("maskclustering_tpu")

GOLDENS_VERSION = 1
DEFAULT_GOLDENS_PATH = "canary_goldens.json"


# ---------------------------------------------------------------------------
# goldens file (committed, versioned, ratcheted)
# ---------------------------------------------------------------------------


def load_goldens(path: str = DEFAULT_GOLDENS_PATH) -> Optional[Dict]:
    """The committed goldens doc, or None when absent/unreadable/stale.

    A version skew (file format OR digest schema) invalidates the whole
    file — serving with goldens that mean something else would turn every
    probe into a false drift, so a stale file reads as "no goldens" and
    the caller logs the regeneration instruction.
    """
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    if doc.get("version") != GOLDENS_VERSION \
            or doc.get("digest_version") != digest_mod.DIGEST_VERSION:
        log.warning("canary goldens %s carry version %s/digest %s (want "
                    "%s/%s) — regenerate via --write-goldens", path,
                    doc.get("version"), doc.get("digest_version"),
                    GOLDENS_VERSION, digest_mod.DIGEST_VERSION)
        return None
    if not isinstance(doc.get("goldens"), dict):
        return None
    return doc


def write_goldens(path: str, goldens: Dict[str, Dict], *,
                  config: Optional[Dict] = None) -> Dict:
    """Write the versioned goldens doc (atomic tmp+rename, sorted keys —
    the diff a regeneration produces is the audit artifact)."""
    doc = {
        "version": GOLDENS_VERSION,
        "digest_version": digest_mod.DIGEST_VERSION,
        "config": config or {},
        "goldens": {k: goldens[k] for k in sorted(goldens)},
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return doc


def probes_to_goldens(probes: List[Dict]) -> Dict[str, Dict]:
    """Goldens mapping (coord -> golden row) from one canary round."""
    out: Dict[str, Dict] = {}
    for p in probes or []:
        if not p.get("coord") or not p.get("digest"):
            continue
        row = dict(p["digest"])
        row["scene"] = p.get("scene")
        out[p["coord"]] = row
    return out


def goldens_config():
    """The ONE PipelineConfig goldens are generated (and probed) under.

    Identical to ``analysis/retrace.compile_surface``'s census cfg, so the
    committed goldens' coordinate set is derivable from the canonical
    workload by the mct-check ratchet (``retrace.check_goldens``) without
    reading the file — the classifier and the knobs are shared, not
    re-declared.
    """
    from maskclustering_tpu.obs.cost import default_pipeline_cfg

    return default_pipeline_cfg(point_chunk=8192).replace(
        frame_pad_multiple=32, mask_pad_multiple=256)


def generate_goldens(cfg=None, *,
                     baseline_path: str = "compile_surface_baseline.json",
                     ) -> Dict[str, Dict]:
    """One in-process canary round over the warm vocabulary -> goldens.

    Shared by ``load_gen --write-goldens`` and the tier-1 round-trip test:
    a Router seeded from the committed surface baseline's workload, a
    thread-less ServeWorker warmed per distinct bucket, then an inline
    ``run_canary`` — exactly the scenes and executables a sentinel-armed
    daemon probes, without spawning one.
    """
    from maskclustering_tpu.serve.admission import AdmissionQueue
    from maskclustering_tpu.serve.router import Router
    from maskclustering_tpu.serve.worker import ServeWorker

    if cfg is None:
        cfg = goldens_config()
    router = Router(cfg, baseline_path=baseline_path)
    if not router.vocabulary:
        raise ValueError(f"no serving vocabulary in {baseline_path} — "
                         f"goldens need the surface baseline's workload")
    worker = ServeWorker(cfg, AdmissionQueue(capacity=1, metered=False),
                         router)
    for name, tensors in router.warmup_workload():
        if not worker.warm_tensors(name, tensors):
            raise RuntimeError(f"goldens warm-up failed for scene {name!r}")
    probes = worker.run_canary()
    goldens = probes_to_goldens(probes)
    if not goldens:
        raise RuntimeError("canary round produced no probes — goldens "
                           "would be empty")
    return goldens


# ---------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------


def compare_probe(probe: Dict, goldens_doc: Dict) -> Dict:
    """One probe vs the goldens: a verdict row for the drift plane.

    ``status``: "ok" (byte-equal), "drift" (mismatch — the page-worthy
    outcome) or "uncovered" (no golden at this coordinate — a vocabulary
    change that should have regenerated goldens; the ratchet catches the
    committed file, this catches the live daemon).
    """
    coord = probe.get("coord") or ""
    golden = (goldens_doc.get("goldens") or {}).get(coord)
    if golden is None:
        return {"coord": coord, "scene": probe.get("scene"),
                "status": "uncovered", "fields": ["missing"]}
    fields = digest_mod.diff_digests(probe.get("digest"), golden)
    return {"coord": coord, "scene": probe.get("scene"),
            "status": "drift" if fields else "ok", "fields": fields,
            "observed": probe.get("digest"), "golden": golden}


# ---------------------------------------------------------------------------
# the idle-aware scheduler
# ---------------------------------------------------------------------------


class CanarySentinel:
    """Periodic golden probes on a serving daemon, idle-aware.

    ``run_round`` executes one canary round and returns probe rows
    (``ServeWorker.run_canary`` or the supervisor's pipe equivalent);
    ``is_idle`` gates firing — a busy daemon skips the tick (typed
    ``canary.skipped_busy`` counter) so canaries never add latency to
    real traffic. On drift: typed event + flight dump + ``canary.drift``
    counter (-> telemetry ``drift`` window field -> SLO ``correctness``).
    """

    def __init__(self, *, run_round: Callable[[], Optional[List[Dict]]],
                 goldens: Dict, interval_s: float = 60.0,
                 is_idle: Optional[Callable[[], bool]] = None):
        self.run_round = run_round
        self.goldens = goldens
        self.interval_s = max(float(interval_s), 0.05)
        self.is_idle = is_idle or (lambda: True)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = mct_lock("obs.CanarySentinel._lock")
        # drift bookkeeping for the sentinel status panel / report section
        self._rounds = 0
        self._drift_total = 0
        self._skipped_busy = 0
        self._last_results: List[Dict] = []
        self._last_verified: Dict[str, float] = {}  # coord -> monotonic ts
        self._drift_coords: Dict[str, int] = {}

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(  # mct-thread: abandon(daemon-lifetime thread, bounded-joined in stop(); spawn/join spans methods)
            target=self._loop, daemon=True, name="canary-sentinel")
        self._thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout_s)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the sentinel must not kill serving
                log.exception("canary sentinel tick failed")

    # -- one tick -----------------------------------------------------------

    def tick(self) -> Optional[List[Dict]]:
        """One scheduler tick: skip when busy, else probe + compare.

        Returns the verdict rows (None when skipped) — the unit tests and
        the drill drive this directly for determinism.
        """
        if not self.is_idle():
            obs.count("canary.skipped_busy")
            with self._lock:
                self._skipped_busy += 1
            return None
        probes = self.run_round()
        if probes is None:
            obs.count("canary.skipped_busy")
            with self._lock:
                self._skipped_busy += 1
            return None
        results = [compare_probe(p, self.goldens) for p in probes]
        now = time.monotonic()
        drifted = [r for r in results if r["status"] != "ok"]
        with self._lock:
            self._rounds += 1
            self._last_results = results
            for r in results:
                if r["status"] == "ok":
                    self._last_verified[r["coord"]] = now
                else:
                    self._drift_total += 1
                    self._drift_coords[r["coord"]] = (
                        self._drift_coords.get(r["coord"], 0) + 1)
        for r in drifted:
            self._on_drift(r)
        return results

    def _on_drift(self, result: Dict) -> None:
        obs.count("canary.drift")
        # the typed event: the machine-readable drift record on the armed
        # sink (events.jsonl / the child relay), next to the flight rows
        obs.emit_event("canary.drift", {
            "coord": result["coord"], "scene": result.get("scene"),
            "status": result["status"], "fields": result.get("fields"),
            "observed": result.get("observed"), "golden": result.get("golden"),
        })
        flight.record("flight.canary", what="drift", coord=result["coord"],
                      scene=str(result.get("scene")),
                      fields=",".join(result.get("fields") or []))
        # the postmortem: ring contents + the offending coordinate, dumped
        # the moment drift is detected (the state that produced it is
        # still warm in the ring)
        flight.dump("canary_drift", extra_rows=[{
            "kind": "canary.drift", "coord": result["coord"],
            "scene": result.get("scene"), "fields": result.get("fields"),
            "observed": result.get("observed"),
            "golden": result.get("golden"),
        }])
        log.error("canary DRIFT at %s (scene %s): fields %s — outputs no "
                  "longer match committed goldens", result["coord"],
                  result.get("scene"), result.get("fields"))

    # -- introspection ------------------------------------------------------

    def stats(self) -> Dict:
        """The sentinel panel's snapshot (protocol status detail
        "sentinel", obs.top, the drill's assertions)."""
        now = time.monotonic()
        with self._lock:
            return {
                "rounds": self._rounds,
                "drift_total": self._drift_total,
                "skipped_busy": self._skipped_busy,
                "interval_s": self.interval_s,
                "coords": sorted(self._last_verified),
                "last_verified_age_s": {
                    c: round(now - t, 1)
                    for c, t in sorted(self._last_verified.items())},
                "drift_coords": dict(self._drift_coords),
                "last_results": [
                    {k: r.get(k) for k in ("coord", "scene", "status",
                                           "fields")}
                    for r in self._last_results],
            }
