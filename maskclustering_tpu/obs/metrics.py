"""Process-local metrics registry: counters, gauges, histograms.

Covers the counters the tree previously had no home for: compile-cache
bucket hits/misses (utils/compile_cache.py), host<->device bytes per stage
(io/feed.py, models/*), scene/worker retry and failure counts (run.py,
bench.py), perf-ledger append/drop counts (obs/ledger.py), and live-HBM
gauges sampled at span ends (obs/tracer.py).

Design constraints, in order:

1. **near-zero cost** — a counter bump is one uncontended lock + dict add.
   The lock became load-bearing with the overlapped scene executor
   (run.py): the host-tail worker, the prefetch daemons, and the main
   dispatch thread all bump SHARED aggregate keys (``d2h.bytes``, span
   histograms) concurrently, and an unlocked read-modify-write would
   silently drop increments from exactly the numbers the perf ledger
   regresses against. An uncontended CPython lock costs ~100 ns — noise
   against the device work these counters meter.
2. **flat names** — ``h2d.bytes.feed`` not nested objects, so a snapshot
   is one JSON-able dict and a diff is set arithmetic.
3. **bounded memory** — histograms keep a capped reservoir (deterministic
   stride-decimation, not random sampling: reproducible percentiles), and
   ``snapshot()`` carries them as bounded summaries (count/total/p50/p95/
   max) next to the counters and gauges — the report digest and run
   digests consume all three sections, not just the scalars.

Cross-process relay helpers (``snapshot_delta``/``merge_snapshot_delta``):
the serving worker subprocess ships counter increments + changed gauges
over its supervisor pipe and the parent folds them under the same flat
names (obs/telemetry.py) — flat names are what make that fold one
``count()`` per key.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# stdlib-only import; off (the default) this returns a RAW threading.Lock,
# so constraint 1 below still holds on the counter hot path. The literal
# name is the lock's identity in BOTH the static lock-order graph
# (analysis/concurrency.py) and the runtime-observed one
from maskclustering_tpu.analysis.lock_sanitizer import mct_lock

_HIST_CAP = 4096  # per-histogram value cap before stride decimation


class Histogram:
    """Value series with bounded memory and exact-until-capped percentiles."""

    __slots__ = ("values", "count", "total", "_stride", "_skip")

    def __init__(self):
        self.values: List[float] = []
        self.count = 0
        self.total = 0.0
        self._stride = 1
        self._skip = 0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self._skip += 1
        if self._skip >= self._stride:
            self._skip = 0
            self.values.append(value)
            if len(self.values) >= _HIST_CAP:
                # decimate deterministically: keep every other sample and
                # double the stride — percentiles stay representative while
                # memory stays O(cap) over arbitrarily long runs
                self.values = self.values[::2]
                self._stride *= 2

    def percentile(self, q: float) -> Optional[float]:
        if not self.values:
            return None
        vals = sorted(self.values)
        idx = min(int(q / 100.0 * len(vals)), len(vals) - 1)
        return vals[idx]

    def summary(self) -> Dict:
        # one sort serves all three order statistics; the quantile rule is
        # THE shared nearest-rank helper (obs.report.percentile) so these
        # summaries cannot silently disagree with any other surface
        from maskclustering_tpu.obs.report import percentile

        vals = sorted(self.values)
        return {
            "count": self.count,
            "total": self.total,
            "p50": percentile(vals, 50) if vals else None,
            "p95": percentile(vals, 95) if vals else None,
            "max": vals[-1] if vals else None,
        }


class Registry:
    """Flat-namespace counters/gauges/histograms with one snapshot call."""

    def __init__(self):
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}
        self._lock = mct_lock("obs.metrics.Registry._lock")

    # -- write paths (hot) --------------------------------------------------
    def count(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + delta

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def gauge_max(self, name: str, value: float) -> None:
        """High-water gauge: keeps the max ever seen (HBM high-water)."""
        with self._lock:
            cur = self._gauges.get(name)
            if cur is None or value > cur:
                self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists.setdefault(name, Histogram())
            h.observe(float(value))

    # -- read paths ---------------------------------------------------------
    def histogram(self, name: str) -> Optional[Histogram]:
        return self._hists.get(name)

    def snapshot(self, *, include_histograms: bool = True) -> Dict:
        """One JSON-able dict of everything; cheap enough to flush per scene.

        ``include_histograms=False`` skips the per-histogram summaries —
        each one sorts its (up to 4096-sample) reservoir under the
        registry lock, which the telemetry hot paths (relay deltas,
        window rolls, status polls) neither ship nor need.
        """
        with self._lock:  # a concurrent insert would break dict iteration
            out = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
            }
            if include_histograms:
                out["histograms"] = {k: h.summary()
                                     for k, h in self._hists.items()}
            return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


def snapshot_delta(prev: Dict, cur: Dict) -> Dict:
    """Counter/gauge delta between two ``Registry.snapshot()`` dicts.

    The telemetry relay's wire shape (obs/telemetry.py): counters ship as
    INCREMENTS (cur - prev, changed keys only — a fold is one ``count()``
    per key, idempotent against re-ordering of other keys), gauges ship as
    their current values (changed keys only — gauges are last-value
    semantics, so a fold is one ``gauge()``). Histograms do NOT ride the
    delta: the relay ships the completed spans themselves and the receiver
    replays them, so the merged histograms hold real samples instead of
    unmergable percentile summaries.
    """
    prev_c = prev.get("counters") or {}
    cur_c = cur.get("counters") or {}
    counters = {}
    for k, v in cur_c.items():
        d = v - prev_c.get(k, 0.0)
        if d:
            counters[k] = d
    prev_g = prev.get("gauges") or {}
    gauges = {k: v for k, v in (cur.get("gauges") or {}).items()
              if prev_g.get(k) != v}
    return {"counters": counters, "gauges": gauges}


def merge_snapshot_delta(delta: Dict, reg: Optional["Registry"] = None) -> None:
    """Fold one ``snapshot_delta`` payload into a registry (the relay's
    receiving half): counter increments via ``count``, gauges via ``gauge``
    — except ``*high_water*`` names, which keep max-ever semantics so a
    late-arriving stale relay line cannot LOWER a high-water mark."""
    reg = reg or _REGISTRY
    for k, v in (delta.get("counters") or {}).items():
        if isinstance(v, (int, float)):
            reg.count(str(k), float(v))
    for k, v in (delta.get("gauges") or {}).items():
        if not isinstance(v, (int, float)):
            continue
        if "high_water" in str(k):
            reg.gauge_max(str(k), float(v))
        else:
            reg.gauge(str(k), float(v))


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


# module-level conveniences: the instrumentation call sites read better as
# obs.count("...") than obs.registry().count("...")
count = _REGISTRY.count
gauge = _REGISTRY.gauge
gauge_max = _REGISTRY.gauge_max
observe = _REGISTRY.observe


def count_transfer(direction: str, nbytes: int, stage: str) -> None:
    """Account one host<->device transfer: per-stage + total counters.

    direction: "h2d" or "d2h". Call sites pass nbytes from the host-side
    buffer (``arr.nbytes``); this measures payload, not link framing.
    """
    _REGISTRY.count(f"{direction}.bytes.{stage}", float(nbytes))
    _REGISTRY.count(f"{direction}.bytes", float(nbytes))


def sample_hbm() -> Optional[Dict[str, float]]:
    """Live device-memory stats of device 0, or None when unavailable.

    ``memory_stats()`` is a host-side query (no device sync, safe at span
    ends); CPU backends return None or {} — both map to None here.
    """
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — no backend / no stats support
        return None
    if not stats:
        return None
    out = {k: float(v) for k, v in stats.items()
           if isinstance(v, (int, float))}
    in_use = out.get("bytes_in_use")
    if in_use is not None:
        _REGISTRY.gauge("hbm.bytes_in_use", in_use)
        _REGISTRY.gauge_max("hbm.high_water_bytes", in_use)
    return out or None
