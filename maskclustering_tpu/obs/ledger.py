"""Perf regression ledger: the bench trajectory as an append-only JSONL.

Three rounds of BENCH_r0*.json are null (chip wedges), so the project has
no machine-checkable performance trajectory — every "did we regress?"
question is answered by a human reading markdown. This module gives every
bench verdict and run-report digest a durable, schema-versioned row in
``PERF_LEDGER.jsonl``:

- **append-only + crash-safe** like the event sink (one flush per row; a
  torn final line is skipped-with-a-count by the reader, never fatal);
- **never the failure source**: an append error logs once and returns
  False — the bench's one-JSON-line stdout contract and the run's exit
  code must not depend on ledger disk health;
- **machine-checkable**: ``python -m maskclustering_tpu.obs.report
  --history`` renders the trajectory, ``--regress BASELINE`` exits
  non-zero when the newest headline p50 regresses >15% — a CI gate and
  the driver's bench-trajectory answer in one.

Rows carry ``v`` (ledger schema version), ``ts``, ``tool`` (bench | run |
seed), the headline ``value``/``unit``, per-stage medians when known, and
the git revision when resolvable.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import time
from typing import Dict, List, Optional, Tuple

from maskclustering_tpu.obs.events import ReadStats

log = logging.getLogger("maskclustering_tpu")

LEDGER_SCHEMA_VERSION = 1
DEFAULT_REGRESS_THRESHOLD = 0.15  # >15% p50 slowdown fails --regress

# trajectories measuring a different experiment than bench/run s/scene:
# --regress only compares them against their own kind (obs/report.py's
# gate fences them out of the metric-less fallback pick BOTH ways)
FENCED_TOOLS = ("serve", "tier1")


def default_ledger_path() -> str:
    """``PERF_LEDGER.jsonl`` in the cwd; overridable via MCT_PERF_LEDGER
    (tests point it at a tmp dir so default-on appends stay hermetic)."""
    return os.environ.get("MCT_PERF_LEDGER", "PERF_LEDGER.jsonl")


def _git_rev() -> Optional[str]:
    try:
        out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             capture_output=True, timeout=10)
        rev = out.stdout.decode("utf-8", "replace").strip()
        return rev if out.returncode == 0 and rev else None
    except Exception:  # noqa: BLE001 — no git is a fine place to run a bench
        return None


def append_row(path: str, row: Dict) -> bool:
    """Append one schema-versioned row; one flush, never raises."""
    line = {"v": LEDGER_SCHEMA_VERSION, "ts": time.time(), "pid": os.getpid()}
    line.update(row)
    if "git" not in line:
        rev = _git_rev()
        if rev:
            line["git"] = rev
    from maskclustering_tpu.obs import metrics as _metrics

    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(line) + "\n")
        _metrics.count("ledger.rows_appended")
        return True
    except Exception:  # noqa: BLE001 — the ledger must never sink the run
        log.exception("perf ledger append failed; row dropped (%s)", path)
        _metrics.count("ledger.rows_dropped")
        return False


def bench_row(verdict: Dict, **extra) -> Dict:
    """Ledger row from a bench JSON verdict line (bench.py's stdout line)."""
    row = {"tool": "bench",
           "metric": verdict.get("metric"),
           "value": verdict.get("value"),
           "unit": verdict.get("unit", "s/scene")}
    for k in ("vs_baseline", "spread_pct", "stages", "attempts",
              "frame_batch", "count_dtype", "plane_dtype",
              "postprocess_path", "point_shards", "retrace_compiles",
              "retrace_repeats", "retrace_post_freeze", "error"):
        if verdict.get(k) is not None:
            row[k] = verdict[k]
    row.update(extra)
    return row


def run_row(report: Dict, **extra) -> Dict:
    """Ledger row from a run-report dict (run.py's run_report.json shape).

    Headline value: median ok-scene seconds (the serving-facing number);
    stages come from the embedded obs digest when the run was armed.
    """
    scenes = report.get("scenes") or []
    ok = sorted(s.get("seconds", 0.0) for s in scenes
                if s.get("status") == "ok")
    value = ok[len(ok) // 2] if ok else None
    row = {"tool": "run",
           "metric": "run s/scene (median of ok scenes)",
           "value": round(value, 3) if value is not None else None,
           "unit": "s/scene",
           "scenes_ok": len(ok),
           "scenes_failed": sum(1 for s in scenes
                                if s.get("status") == "failed"),
           "config": report.get("config_name")}
    digest = report.get("obs") or {}
    stages = digest.get("stages")
    if stages:
        row["stages"] = {k: v.get("p50_s") for k, v in stages.items()}
    # compile-surface attribution (retrace-sanitizer-armed runs only): the
    # summary's counters carry the compile events; stamping them on the
    # row lets --regress attribute a warm-up/compile delta before anyone
    # blames code drift (same move as the dtype knobs)
    counters = digest.get("counters") or {}
    for src, dst in (("retrace.compiles", "retrace_compiles"),
                     ("retrace.repeat_compiles", "retrace_repeats"),
                     ("retrace.post_freeze_compiles", "retrace_post_freeze"),
                     ("compile_cache.bucket_new", "buckets_new")):
        if src in counters:
            # presence, not truthiness: a fully-warm armed run books
            # retrace.compiles=0, and THAT zero is the baseline row the
            # 0 -> N regression attribution anchors on
            row[dst] = int(counters[src])
    for src, dst in (("retrace.cache_hits", "retrace_cache_hits"),
                     ("aot_cache.restored", "aot_restored"),
                     ("aot_cache.invalidated", "aot_invalidated")):
        # warm-start attribution (nonzero only — clean rows stay compact):
        # a fast cold start next to restored/hit counts is the AOT cache's
        # story, not code drift
        if counters.get(src):
            row[dst] = int(counters[src])
    faults = report.get("faults") or {}
    # fault attribution: a degraded/retried run's headline is the fault's
    # story, not code drift — stamp it so --regress can say so (keys only
    # appear when nonzero, keeping clean rows compact)
    for src, dst in (("scene_retries", "retries"),
                     ("device_stalls", "device_stalls"),
                     ("final_rung", "final_rung")):
        if faults.get(src):
            row[dst] = faults[src]
    if faults.get("degradations"):
        row["degradations"] = sum(faults["degradations"].values())
    if faults.get("interrupted"):
        row["interrupted"] = True
    # mct-sentinel stamp: the census coordinates the run's digests were
    # observed at plus one combined artifact fingerprint over all ok
    # scenes — --regress attributes a digest change to a coordinate/knob
    # flip before anyone reads it as code drift (and vice versa)
    coords = sorted({s.get("digest_coord") for s in scenes
                     if s.get("status") == "ok" and s.get("digest_coord")})
    if coords:
        row["digest_coord"] = ",".join(coords)
        import zlib

        seed = 0
        for s in sorted(scenes, key=lambda s: s.get("seq_name") or ""):
            art = ((s.get("digest") or {}).get("artifact") or "")
            seed = zlib.crc32(
                f"{s.get('seq_name')}:{art}".encode(), seed) & 0xFFFFFFFF
        row["digest"] = f"{seed:08x}"
    row.update(extra)
    return row


def serve_row(verdict: Dict, **extra) -> Dict:
    """Ledger row from a load_gen serve verdict (scripts/load_gen.py).

    The metric is serve-specific ("serve s/request ..."), so --regress
    never gates a serve row against a bench/run baseline (or vice versa):
    ``latest_value_row``'s metric filter plus the tool fence below keep
    the trajectories separate while sharing one ledger file.
    """
    row = {"tool": "serve",
           "metric": verdict.get("metric", "serve s/request (p50)"),
           "value": verdict.get("value"),
           "unit": verdict.get("unit", "s/request")}
    for k in ("p95_s", "throughput_rps", "requests", "concurrency",
              "scenes", "buckets", "rejects", "failed", "warmup_s",
              "count_dtype", "plane_dtype", "point_shards",
              "streaming_chunk",
              "retrace_compiles", "retrace_repeats", "retrace_post_freeze",
              "retrace_cache_hits", "aot_restored", "worker_crashes",
              "worker_respawns", "telemetry_windows", "window_p95",
              "tenants", "error",
              # mct-sentinel: canary probe accounting (fenced from the
              # latency headline — canaries never enter the latency
              # window) and the coordinates the probes verified
              "canary_probes", "canary_drift", "digest_coord",
              # continuous batching: the packing scheduler's occupancy
              # coordinate — a packed row's latency/throughput belongs to
              # its occupancy, so --regress fences/attributes on these
              # (batch_dimension below, occupancy advisory in
              # check_regression)
              "batch_occupancy", "batch_dispatches", "batch_max",
              "batch_hist",
              # mct-durable: failover/replay evidence from the chaos
              # drill — a row measured under injected worker/daemon death
              # is its own dimension (durability_dimension below)
              "streams_resumed", "wal_replayed", "wal_deduped",
              "journals_pruned"):
        if verdict.get(k) is not None:
            row[k] = verdict[k]
    row.update(extra)
    return row


def tenant_dimension(row: Optional[Dict]) -> bool:
    """True when a ledger row (or baseline) carries per-tenant sub-rows.

    A serve row with a ``tenants`` dict measured a multi-tenant mix, so
    its latency belongs to that mix: --regress fences the dimension BOTH
    ways (obs/report.py), exactly like the tool fence above — a tenant
    row never gates against an untenanted baseline, and vice versa.
    """
    return bool((row or {}).get("tenants"))


def sentinel_dimension(row: Optional[Dict]) -> bool:
    """True when a ledger row recorded canary digest drift (mct-sentinel).

    A row measured while the correctness plane was tripping (a corruption
    drill, a real SDC event) is not a perf datapoint: --regress fences the
    dimension BOTH ways, like ``tenant_dimension`` — a drifted row never
    gates against a clean baseline, and a clean row never gates against a
    drifted one.
    """
    return bool((row or {}).get("canary_drift"))


def batch_dimension(row: Optional[Dict]) -> bool:
    """True when a ledger row was measured under the packing scheduler
    (continuous scene batching, ``serve_batch_max > 1``).

    A packed row's per-request latency and throughput belong to its batch
    occupancy — dispatch overhead amortizes across batchmates — so
    --regress fences the dimension BOTH ways (obs/report.py), like
    ``tenant_dimension``: a packed row never gates against a sequential
    baseline, and vice versa. Occupancy SHIFTS between two packed rows are
    attributed as advisory lines in ``check_regression`` instead.
    """
    return (row or {}).get("batch_occupancy") is not None


def durability_dimension(row: Optional[Dict]) -> bool:
    """True when a ledger row was measured under failover/replay — a
    stream resumed from a snapshot or a WAL replay answered requests
    (the chaos drill's rows).

    Re-run chunks and daemon restarts inflate per-request latency for
    reasons that are the DRILL's, not code drift's, so --regress fences
    the dimension BOTH ways (obs/report.py), like ``batch_dimension``: a
    failover row never gates against a clean baseline, and vice versa.
    """
    row = row or {}
    return bool(row.get("streams_resumed")) or bool(row.get("wal_replayed"))


def tier1_row(wall_s: float, passed: int, **extra) -> Dict:
    """Ledger row for one tier-1 suite run (scripts/ci.sh appends it).

    Tracks the 870 s budget trajectory with the same --regress machinery
    as perf: the metric is tier1-specific ("tier1 ..."), so the tool fence
    (FENCED_TOOLS) keeps it out of bench/run gating, and a tier1 baseline
    gates only tier1 rows. ``passed`` rides along so a wall drop that
    coincides with a pass-count drop reads as a trim, not a speedup.
    """
    row = {"tool": "tier1",
           "metric": "tier1 wall s (not-slow suite)",
           "value": round(float(wall_s), 1),
           "unit": "s",
           "passed": int(passed)}
    row.update(extra)
    return row


def read_ledger(path: str, *, stats: Optional[ReadStats] = None) -> List[Dict]:
    """All known-version rows, oldest first; torn/unknown lines are counted
    into ``stats`` and skipped (one shared policy: events.iter_jsonl_rows)."""
    from maskclustering_tpu.obs.events import iter_jsonl_rows

    return list(iter_jsonl_rows(path, version=LEDGER_SCHEMA_VERSION,
                                stats=stats))


def latest_value_row(rows: List[Dict], *,
                     metric: Optional[str] = None,
                     exclude_tools: Tuple[str, ...] = ()) -> Optional[Dict]:
    """Newest row with a numeric headline value (null verdicts are history,
    not baselines). ``metric`` restricts the pick to comparable rows — the
    --regress gate must not compare a run-row median against a bench
    baseline just because it is newer. ``exclude_tools`` fences whole
    trajectories out of the METRIC-LESS fallback pick: a ``serve`` p50
    (s/request under concurrency) must never gate against a bench
    baseline (s/scene) just because a load_gen row is the newest."""
    for row in reversed(rows):
        if not isinstance(row.get("value"), (int, float)):
            continue
        if metric is not None and row.get("metric") != metric:
            continue
        if metric is None and row.get("tool") in exclude_tools:
            continue
        return row
    return None


def load_baseline(path: str) -> Optional[Dict]:
    """A baseline for --regress: a ledger JSONL (newest valid row) or a
    single JSON document with a ``value`` field (a bench verdict / BENCH_*
    record)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            head = f.read()
    except OSError:
        return None
    try:
        doc = json.loads(head)
        if isinstance(doc, dict) and isinstance(doc.get("value"), (int, float)):
            return doc
    except ValueError:
        pass
    try:
        return latest_value_row(read_ledger(path))
    except Exception:  # noqa: BLE001
        return None


def check_regression(current: Optional[Dict], baseline: Optional[Dict], *,
                     threshold: float = DEFAULT_REGRESS_THRESHOLD
                     ) -> Tuple[bool, List[str]]:
    """Headline p50 gate: ok unless current is >threshold slower.

    Lower is better (s/scene). Stage-level drifts are reported as advisory
    lines but only the headline value gates — stage noise on shared CPUs
    would otherwise make the gate cry wolf.
    """
    lines: List[str] = []
    if current is None:
        return False, ["no current row with a numeric value — cannot gate "
                       "(an empty/null trajectory is itself a failure)"]
    if baseline is None:
        return False, ["no usable baseline value"]
    cur, base = float(current["value"]), float(baseline["value"])
    if base <= 0:
        return False, [f"baseline value {base} is not positive"]
    rel = (cur - base) / base
    verdict = "REGRESSION" if rel > threshold else "ok"
    lines.append(f"headline: {cur:.3f} vs baseline {base:.3f} "
                 f"({rel:+.1%}, threshold +{threshold:.0%}) -> {verdict}")
    # knob attribution: a headline delta that coincides with a dtype or
    # postprocess-path flip is a knob effect, not code drift — say so next
    # to the verdict (rows predating a knob have no key and read as the
    # historical defaults; postprocess_path predates as "device": rows
    # before the knob ran the default device path)
    knob_flips = []
    # point_shards defaults to 1: rows predating the knob ran unsharded,
    # so a sharded row against an old baseline reads as a knob flip (the
    # resharded program has its own compile surface and ICI profile).
    # streaming_chunk defaults to 0 (offline batch): a chunked row's
    # latency profile belongs to the chunk size, not code drift
    for knob, default in (("count_dtype", "bf16"), ("plane_dtype", "int32"),
                          ("postprocess_path", "device"),
                          ("point_shards", 1), ("streaming_chunk", 0)):
        c, b = current.get(knob, default), baseline.get(knob, default)
        if c != b:
            knob_flips.append(knob)
            lines.append(f"  {knob}: {b} -> {c} [knob flip — attribute "
                         f"the delta before blaming code]")
    # compile-surface attribution (retrace sanitizer, PR-9): a compile
    # count or warm-up wall that regressed next to the headline is either
    # a knob flip's new variant or genuine surface growth — the advisory
    # names which BEFORE anyone reads the delta as code drift
    cur_rc = current.get("retrace_compiles")
    base_rc = baseline.get("retrace_compiles")
    if cur_rc is not None and base_rc is not None \
            and int(cur_rc) > int(base_rc):
        cause = ("the flipped knob's variant compiling its own programs"
                 if knob_flips else
                 "compile-surface growth or a cold process — check the "
                 "retrace digest and compile_surface_baseline.json")
        lines.append(f"  retrace: sanitizer recorded {base_rc} -> {cur_rc} "
                     f"compile(s) [{cause}]")
    for key, label in (("retrace_repeats", "repeat compile(s)"),
                       ("retrace_post_freeze", "post-warm compile(s)")):
        if current.get(key):
            lines.append(f"  retrace VIOLATION: current run booked "
                         f"{current[key]} {label} — the warm path "
                         f"retraced; fix that before reading the headline "
                         f"as code drift")
    # fault attribution: run rows stamp retries/degradations (run.py) — a
    # degraded run is slower BY DESIGN, so the gate says so before anyone
    # blames code drift for the fault's wall-clock cost
    for label, r in (("current", current), ("baseline", baseline)):
        retries = int(r.get("retries") or 0)
        degr = int(r.get("degradations") or 0)
        if retries or degr:
            lines.append(
                f"  {label} run recorded {retries} scene retr"
                f"{'y' if retries == 1 else 'ies'} and {degr} "
                f"degradation(s) [fault attribution — the delta may be "
                f"the fault's, not code drift]")
    # sentinel attribution: a digest change at an UNCHANGED coordinate is
    # code drift in the outputs themselves — say so louder than any perf
    # delta; a coordinate change explains a digest change before anyone
    # blames code (the knob-flip move, applied to correctness)
    cur_dc, base_dc = current.get("digest_coord"), baseline.get("digest_coord")
    cur_dg, base_dg = current.get("digest"), baseline.get("digest")
    if cur_dc and base_dc and cur_dc != base_dc:
        lines.append(f"  digest_coord: {base_dc} -> {cur_dc} [coordinate "
                     f"change — digests are per-coordinate; not comparable]")
    elif cur_dg and base_dg and cur_dg != base_dg:
        cause = ("the flipped knob changed the observed coordinate set"
                 if knob_flips else
                 "OUTPUTS CHANGED at an unchanged coordinate — code drift "
                 "in the answers; audit before regenerating canary goldens")
        lines.append(f"  sentinel: run digest {base_dg} -> {cur_dg} "
                     f"[{cause}]")
    for label, r in (("current", current), ("baseline", baseline)):
        if r.get("canary_drift"):
            lines.append(
                f"  {label} row recorded {int(r['canary_drift'])} canary "
                f"drift event(s) [sentinel fence — correctness was "
                f"violated while measuring; not a perf datapoint]")
    # occupancy attribution (continuous batching): two packed rows with
    # different mean occupancy measured different amortization — the
    # throughput/latency delta is the packing's before it is code drift
    # (the digest-coord move, applied to the batching dimension; rows on
    # OPPOSITE sides of the dimension never reach this gate — obs/report
    # fences batch_dimension both ways)
    cur_occ = current.get("batch_occupancy")
    base_occ = baseline.get("batch_occupancy")
    if cur_occ is not None and base_occ is not None:
        try:
            co, bo = float(cur_occ), float(base_occ)
        except (TypeError, ValueError):
            co = bo = 0.0
        if abs(co - bo) >= 0.25:
            lines.append(
                f"  batch_occupancy: {bo:g} -> {co:g} [occupancy shift — "
                f"packed dispatches amortize over their members; attribute "
                f"the per-request delta to the packing mix before blaming "
                f"code]")
    cur_stages = current.get("stages") or {}
    base_stages = baseline.get("stages") or {}
    for k in sorted(set(cur_stages) & set(base_stages)):
        try:
            c, b = float(cur_stages[k]), float(base_stages[k])
        except (TypeError, ValueError):
            continue
        if b > 0 and (c - b) / b > threshold:
            lines.append(f"  stage {k}: {c:.3f} vs {b:.3f} "
                         f"({(c - b) / b:+.1%}) [advisory]")
    return rel <= threshold, lines
