"""Span tracer with device-sync-aware fencing.

All timing in the tree used to be host-side ``perf_counter`` around async
jit dispatch — which attributes a device stage's cost to whichever LATER
stage first forces a sync (``np.asarray``), not to the stage that ran it.
The round-5 verdict's open question ("is post.claims kernel time or
transfer time?") is exactly this ambiguity. Spans fix it with explicit
fencing:

- ``span.sync(value)`` calls ``jax.block_until_ready`` on the value and
  accumulates the blocked wall time into the span's ``sync_s`` — so a
  span that closes after syncing its own outputs owns its device time,
  and ``duration - sync_s`` is its true host-side cost.
- fencing only happens on a **real, fence-enabled tracer**. The no-op
  singleton's ``sync`` returns its argument untouched: instrumented code
  paths add ZERO extra device syncs when observability is off, so
  honest-shape bench numbers are unaffected.

Nesting is thread-local (prefetch daemon threads get their own stacks);
each span carries key=value attrs (scene id, shape bucket, frame/point
counts) and can pass through ``jax.profiler.TraceAnnotation`` so spans
line up with XLA profile traces.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Dict, Optional

from maskclustering_tpu.obs import flight as _flight
from maskclustering_tpu.obs import metrics as _metrics
from maskclustering_tpu.obs.events import KIND_SPAN, EventSink


class Span:
    """One timed region. Created by ``Tracer.span``; close via the ctx mgr."""

    __slots__ = ("name", "attrs", "t0", "duration", "sync_s", "parent",
                 "depth", "_tracer", "_annotation", "_owns_xprof")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any],
                 parent: Optional[str], depth: int):
        self.name = name
        self.attrs = attrs
        self.parent = parent
        self.depth = depth
        self.t0 = 0.0
        self.duration = 0.0
        self.sync_s = 0.0
        self._tracer = tracer
        self._annotation = None
        self._owns_xprof = False

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def sync(self, value=None):
        """Fence: block until ``value`` (a pytree of arrays) is ready.

        Charges the blocked wall time to THIS span so device work is
        attributed to the stage that dispatched it. Returns ``value`` for
        chaining (``out = sp.sync(kernel(x))``). No-ops (and costs no
        device sync) when the tracer has fencing off.
        """
        if value is not None and self._tracer.fence:
            import jax

            t0 = time.perf_counter()
            jax.block_until_ready(value)
            self.sync_s += time.perf_counter() - t0
        return value

    def __enter__(self) -> "Span":
        tr = self._tracer
        stack = tr._stack()
        self.parent = stack[-1].name if stack else self.parent
        self.depth = len(stack)
        stack.append(self)
        if tr.xprof is not None:
            # span-triggered profiler capture (obs/xprof.py): the span that
            # starts the trace owns it and stops it at close
            self._owns_xprof = tr.xprof.maybe_start(self.name)
        if tr.annotations:
            try:
                import jax.profiler

                self._annotation = jax.profiler.TraceAnnotation(self.name)
                self._annotation.__enter__()
            except Exception:  # noqa: BLE001 — annotations are best-effort
                self._annotation = None
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration = time.perf_counter() - self.t0
        if self._annotation is not None:
            self._annotation.__exit__(exc_type, exc, tb)
        tr = self._tracer
        if self._owns_xprof and tr.xprof is not None:
            tr.xprof.stop(self.name)
        stack = tr._stack()
        if stack and stack[-1] is self:
            stack.pop()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        tr._finish(self)
        return False


class _NullSpan:
    """Shared do-nothing span: the disabled-mode fast path."""

    __slots__ = ()
    name = "null"
    parent = None
    depth = 0
    duration = 0.0
    sync_s = 0.0
    attrs: Dict[str, Any] = {}

    def set(self, **attrs):
        return self

    def sync(self, value=None):
        return value  # NO block_until_ready: disabled mode adds no syncs

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """No-op tracer singleton: zero allocation, zero syncs, zero events."""

    fence = False
    annotations = False
    enabled = False
    xprof = None

    def span(self, name: str, **attrs):
        return _NULL_SPAN

    def record_span(self, name: str, seconds: float, *, parent=None, **attrs):
        return None

    def traced(self, name: str, **attrs):
        return lambda fn: fn

    def flush_metrics(self) -> None:
        return None


NULL_TRACER = NullTracer()


class Tracer:
    """Real tracer: times spans, optionally fences, emits, samples HBM.

    ``sink=None`` gives a timing-only tracer (what run_scene falls back to
    when obs is off, so its timings dict always exists) — it never emits,
    never fences, never samples memory.
    """

    enabled = True

    def __init__(self, sink: Optional[EventSink] = None, *, fence: bool = True,
                 annotations: bool = False, sample_memory: bool = True,
                 aggregate: bool = True, xprof=None):
        self.sink = sink
        self.fence = fence and sink is not None
        self.annotations = annotations
        self.sample_memory = sample_memory and sink is not None
        self.aggregate = aggregate and sink is not None
        self.xprof = xprof  # Optional[obs.xprof.XprofArm]
        self._local = threading.local()

    def _stack(self):
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs, parent=None, depth=0)

    def record_span(self, name: str, seconds: float, *, parent: Optional[str] = None,
                    sync_s: float = 0.0, **attrs) -> None:
        """Register an externally-measured phase as a finished span.

        The retrofit path for code that already owns its timing (the
        post-process ``_PhaseTimer`` phases): same event schema, no
        double-timing.
        """
        sp = Span(self, name, attrs, parent=parent, depth=1 if parent else 0)
        sp.duration = float(seconds)
        sp.sync_s = float(sync_s)
        sp.t0 = time.perf_counter() - sp.duration
        self._finish(sp)

    def traced(self, name: str, **attrs):
        """Decorator form: the whole call body becomes one span."""

        def deco(fn):
            @functools.wraps(fn)
            def wrapper(*a, **kw):
                with self.span(name, **attrs):
                    return fn(*a, **kw)

            return wrapper

        return deco

    def _finish(self, span: Span) -> None:
        # every finished span — real, timing-only or relay-armed — lands
        # in the in-process flight ring (obs/flight.py): the black box is
        # always on, costing one deque append, no IO
        _flight.record_span(span.name, span.duration, span.sync_s,
                            span.attrs)
        if self.aggregate:
            _metrics.observe(f"span.{span.name}.s", span.duration)
            if span.sync_s:
                _metrics.observe(f"span.{span.name}.sync_s", span.sync_s)
                # fenced device time as a COUNTER so the cross-process
                # relay's delta fold carries it: the per-tenant
                # device-seconds attribution reads this, topology-invariant
                _metrics.count("device.seconds", span.sync_s)
        if self.sink is None:
            return
        mem = _metrics.sample_hbm() if self.sample_memory else None
        payload: Dict[str, Any] = {
            "name": span.name,
            "t0": span.t0,
            "dur_s": round(span.duration, 6),
            "sync_s": round(span.sync_s, 6),
            "depth": span.depth,
        }
        if span.parent:
            payload["parent"] = span.parent
        if span.attrs:
            payload["attrs"] = span.attrs
        if mem:
            payload["mem"] = {k: mem[k] for k in ("bytes_in_use",) if k in mem}
        self.sink.emit(KIND_SPAN, payload)

    def flush_metrics(self) -> None:
        """Emit one metrics-snapshot event (counters/gauges/histograms)."""
        if self.sink is not None:
            self.sink.emit("metrics", {"metrics": _metrics.registry().snapshot()})
