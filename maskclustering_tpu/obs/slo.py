"""Declarative serving SLOs + multi-window burn-rate evaluation.

A spec is a small JSON document naming objectives over the windowed
telemetry ring (obs/telemetry.py snapshots — live over the wire, or the
``telemetry`` rows an armed daemon appended to its events file):

    {"v": 1, "name": "serve-default",
     "windows": {"short": 1, "long": 5},
     "objectives": [
       {"name": "latency-p95", "kind": "latency_p95", "threshold": 60.0},
       {"name": "errors", "kind": "error_rate", "threshold": 0.05},
       {"name": "queue-wait-p95", "kind": "queue_wait_p95",
        "threshold": 60.0},
       {"name": "no-post-warm-compiles", "kind": "post_warm_compiles",
        "threshold": 0}]}

Objective kinds: ``latency_p95`` / ``latency_p50`` (worst bucket in the
window, or one bucket via ``"bucket"``), ``error_rate`` (non-ok
terminal statuses + crashes over requests), ``queue_wait_p95``,
``post_warm_compiles``, ``crash_count`` and ``drift_count`` (absolute
counts; threshold is the allowed total — ``drift_count`` reads the
canary digest-mismatch field the telemetry window folds in, the
mct-sentinel correctness signal). An objective may scope to one tenant with
``"tenant"`` — it then reads the per-tenant sub-windows the aggregator
maintains.

Evaluation is the classic two-window burn rate: each objective is
measured over the SHORT window (the newest ``windows.short`` ring rows)
and the LONG window (the newest ``windows.long`` rows); ``burn`` =
observed / threshold, and the objective is **violated only when both
windows burn past 1.0** — a single bad window does not page, a
sustained one does. Zero-threshold counts burn at the observed count
itself, so a lone occurrence (burn exactly 1.0) stays on the right
side of the strict ``>`` rule — EXCEPT ``drift_count``, which is
zero-tolerance: any occurrence in the long window violates, because a
canary digest mismatch is silent corruption, not a budgetable
degradation. Windows with no traffic produce no
verdict (``no_data``) rather than a fake pass/fail number — the
empty-window render path must never divide by zero or take a
percentile of nothing.

Percentiles cannot be merged across windows, so a multi-window latency
observation is the WORST window p95 in range — the same worst-window
rule the report's telemetry digest uses.

``--check`` mode exits non-zero naming the violated objective(s) — the
CI gate shape. The daemon serves the evaluation as the ``slo`` wire
detail; ``obs.top`` renders it live and ``obs.report`` as an "SLO"
section.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import Dict, List, Optional

log = logging.getLogger("maskclustering_tpu")

SLO_SCHEMA_VERSION = 1

KINDS = ("latency_p95", "latency_p50", "error_rate", "queue_wait_p95",
         "post_warm_compiles", "crash_count", "drift_count")

# statuses that count against the error budget (the non-ok terminal
# classes the aggregator tracks; "skipped" is an artifact no-op, not an
# error)
ERROR_STATUSES = ("failed", "deadline", "interrupted")

DEFAULT_SPEC: Dict = {
    "v": SLO_SCHEMA_VERSION,
    "name": "serve-default",
    "windows": {"short": 1, "long": 5},
    "objectives": [
        {"name": "latency-p95", "kind": "latency_p95", "threshold": 120.0},
        {"name": "errors", "kind": "error_rate", "threshold": 0.05},
        {"name": "queue-wait-p95", "kind": "queue_wait_p95",
         "threshold": 120.0},
        {"name": "no-post-warm-compiles", "kind": "post_warm_compiles",
         "threshold": 0},
        # zero tolerance: any canary digest drift is silent corruption,
        # not a budgetable degradation (mct-sentinel correctness plane)
        {"name": "correctness", "kind": "drift_count", "threshold": 0},
    ],
}


def validate_spec(spec: Dict) -> Dict:
    """Normalize + validate; raises ValueError naming the bad field."""
    if not isinstance(spec, dict):
        raise ValueError("SLO spec must be a JSON object")
    if spec.get("v", SLO_SCHEMA_VERSION) != SLO_SCHEMA_VERSION:
        raise ValueError(f"unknown SLO spec version {spec.get('v')!r}")
    wins = spec.get("windows") or {}
    short = int(wins.get("short", 1))
    long_ = int(wins.get("long", 5))
    if short < 1 or long_ < short:
        raise ValueError(f"windows must satisfy 1 <= short <= long "
                         f"(got short={short} long={long_})")
    objs = spec.get("objectives")
    if not isinstance(objs, list) or not objs:
        raise ValueError("SLO spec needs a non-empty 'objectives' list")
    seen = set()
    out_objs = []
    for i, o in enumerate(objs):
        if not isinstance(o, dict):
            raise ValueError(f"objective #{i} is not an object")
        name = o.get("name")
        if not isinstance(name, str) or not name:
            raise ValueError(f"objective #{i} needs a name")
        if name in seen:
            raise ValueError(f"duplicate objective name {name!r}")
        seen.add(name)
        kind = o.get("kind")
        if kind not in KINDS:
            raise ValueError(f"objective {name!r}: unknown kind {kind!r} "
                             f"(one of {KINDS})")
        thr = o.get("threshold")
        if not isinstance(thr, (int, float)) or thr < 0:
            raise ValueError(f"objective {name!r}: threshold must be a "
                             f"non-negative number")
        norm = {"name": name, "kind": kind, "threshold": float(thr)}
        for opt in ("bucket", "tenant"):
            v = o.get(opt)
            if v is not None:
                if not isinstance(v, str) or not v:
                    raise ValueError(f"objective {name!r}: {opt} must be a "
                                     f"non-empty string")
                norm[opt] = v
        out_objs.append(norm)
    return {"v": SLO_SCHEMA_VERSION,
            "name": str(spec.get("name") or "unnamed"),
            "windows": {"short": short, "long": long_},
            "objectives": out_objs}


def load_spec(path: Optional[str]) -> Dict:
    """The spec file, validated; None loads the canned default."""
    if not path:
        return validate_spec(json.loads(json.dumps(DEFAULT_SPEC)))
    with open(path, "r", encoding="utf-8") as f:
        return validate_spec(json.load(f))


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------


def _scope(row: Dict, tenant: Optional[str]) -> Optional[Dict]:
    """The window row, or its per-tenant sub-row (None when the tenant
    never appeared in that window)."""
    if tenant is None:
        return row
    return (row.get("tenants") or {}).get(tenant)


def _observe(obj: Dict, rows: List[Dict]) -> Optional[float]:
    """The objective's observed value over ``rows``, or None with no
    data. Rates divide by request volume; percentiles take the worst
    window (percentiles cannot merge); counts sum."""
    kind = obj["kind"]
    scoped = [s for s in (_scope(r, obj.get("tenant")) for r in rows)
              if s is not None]
    if not scoped:
        return None
    if kind in ("latency_p95", "latency_p50"):
        key = "p95_s" if kind == "latency_p95" else "p50_s"
        worst = None
        for s in scoped:
            lat = s.get("latency") or {}
            hists = ([lat.get(obj["bucket"])] if obj.get("bucket")
                     else list(lat.values()))
            for h in hists:
                v = (h or {}).get(key)
                if v is not None and (worst is None or v > worst):
                    worst = float(v)
        return worst
    if kind == "queue_wait_p95":
        worst = None
        for s in scoped:
            v = (s.get("queue_wait") or {}).get("p95_s")
            if v is not None and (worst is None or v > worst):
                worst = float(v)
        return worst
    if kind == "error_rate":
        requests = sum(int(s.get("requests", 0) or 0) for s in scoped)
        if requests <= 0:
            return None
        errors = 0
        for s in scoped:
            by = s.get("by_status") or {}
            errors += sum(int(by.get(k, 0) or 0) for k in ERROR_STATUSES)
            errors += int(s.get("crashes", 0) or 0)
        return errors / requests
    if kind == "post_warm_compiles":
        return float(sum(int(s.get("post_warm_compiles", 0) or 0)
                         for s in scoped))
    if kind == "crash_count":
        return float(sum(int(s.get("crashes", 0) or 0) for s in scoped))
    if kind == "drift_count":
        # canary digest mismatches folded into the window by the
        # aggregator (obs/telemetry.py "drift") — correctness, not speed
        return float(sum(int(s.get("drift", 0) or 0) for s in scoped))
    return None


def _burn(observed: Optional[float], threshold: float) -> Optional[float]:
    """observed/threshold; a zero threshold burns at the observed count
    itself (any occurrence is over budget)."""
    if observed is None:
        return None
    if threshold <= 0:
        return float(observed)
    return observed / threshold


def evaluate(spec: Dict, snapshot: Dict) -> Dict:
    """The verdict document over one telemetry snapshot.

    ``snapshot`` is the aggregator shape ({"windows": [...], ...});
    closed window rows only — the in-flight ``current`` window is
    deliberately ignored (its duration is still running, so its rates
    are not comparable).
    """
    rows = [r for r in (snapshot or {}).get("windows") or []
            if isinstance(r, dict)]
    short_n = spec["windows"]["short"]
    long_n = spec["windows"]["long"]
    short_rows = rows[-short_n:]
    long_rows = rows[-long_n:]
    objectives = []
    ok = True
    for obj in spec["objectives"]:
        obs_short = _observe(obj, short_rows)
        obs_long = _observe(obj, long_rows)
        b_short = _burn(obs_short, obj["threshold"])
        b_long = _burn(obs_long, obj["threshold"])
        # drift_count at threshold 0 is zero-tolerance: one canary
        # digest mismatch anywhere in the long window pages — silent
        # corruption has no burn budget to amortize against
        zero_tol = obj["kind"] == "drift_count" and obj["threshold"] <= 0
        if b_short is None and b_long is None:
            state = "no_data"
        elif zero_tol and obs_long is not None and obs_long > 0:
            state = "violated"
            ok = False
        elif (not zero_tol
              and b_short is not None and b_short > 1.0
              and b_long is not None and b_long > 1.0):
            # the two-window rule: both the fast signal and the
            # sustained one must burn past budget before this pages
            state = "violated"
            ok = False
        else:
            state = "ok"
        row = {"name": obj["name"], "kind": obj["kind"],
               "threshold": obj["threshold"], "state": state,
               "observed_short": obs_short, "observed_long": obs_long,
               "burn_short": (round(b_short, 4)
                              if b_short is not None else None),
               "burn_long": (round(b_long, 4)
                             if b_long is not None else None)}
        for opt in ("bucket", "tenant"):
            if obj.get(opt):
                row[opt] = obj[opt]
        objectives.append(row)
    return {"v": SLO_SCHEMA_VERSION, "spec": spec["name"], "ok": ok,
            "windows_seen": len(rows),
            "windows": {"short": len(short_rows), "long": len(long_rows)},
            "objectives": objectives}


def violated(result: Dict) -> List[str]:
    return [o["name"] for o in (result or {}).get("objectives") or []
            if o.get("state") == "violated"]


# ---------------------------------------------------------------------------
# rendering (shared by obs.top's panel and obs.report's SLO section)
# ---------------------------------------------------------------------------


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v:.3f}" if isinstance(v, float) else str(v)


def render_result(result: Optional[Dict]) -> List[str]:
    """Human lines, one per objective — safe on empty/no-data input."""
    if not result:
        return ["slo: no evaluation (no spec armed)"]
    head = (f"slo [{result.get('spec', '?')}]: "
            + ("OK" if result.get("ok") else "VIOLATED")
            + f" over {result.get('windows_seen', 0)} window(s)")
    lines = [head]
    for o in result.get("objectives") or []:
        scope = "".join(f" {k}={o[k]}" for k in ("bucket", "tenant")
                        if o.get(k))
        mark = {"ok": " ok ", "violated": "FAIL", "no_data": " -- "}.get(
            o.get("state"), " ?  ")
        lines.append(
            f"  [{mark}] {o.get('name')}{scope}: "
            f"short {_fmt(o.get('observed_short'))} / "
            f"long {_fmt(o.get('observed_long'))} vs "
            f"{_fmt(o.get('threshold'))} "
            f"(burn {_fmt(o.get('burn_short'))}/{_fmt(o.get('burn_long'))})")
    return lines


# ---------------------------------------------------------------------------
# CLI: evaluate a live daemon or an events file;  --check gates
# ---------------------------------------------------------------------------


def snapshot_from_events(path: str) -> Dict:
    """A pseudo-snapshot from the ``telemetry`` rows an armed daemon
    appended to its events file (the durable half of the live ring)."""
    from maskclustering_tpu.obs.events import KIND_TELEMETRY, read_events

    rows = [ev for ev in read_events(path)
            if ev.get("kind") == KIND_TELEMETRY]
    return {"windows": rows}


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m maskclustering_tpu.obs.slo",
        description="evaluate serving SLO burn rates over the telemetry "
                    "window ring")
    p.add_argument("--spec", default=None,
                   help="SLO spec JSON (default: the canned serve-default)")
    p.add_argument("--socket", default=None, help="live daemon AF_UNIX path")
    p.add_argument("--host", default=None, help="live daemon TCP host")
    p.add_argument("--port", type=int, default=0, help="live daemon TCP port")
    p.add_argument("--events", default=None,
                   help="events.jsonl with telemetry rows (offline mode)")
    p.add_argument("--check", action="store_true",
                   help="exit 2 naming each violated objective (CI gate)")
    p.add_argument("--json", action="store_true",
                   help="emit the verdict document")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(message)s")
    try:
        spec = load_spec(args.spec)
    except (OSError, ValueError) as e:
        print(f"slo: bad spec: {e}", file=sys.stderr)
        return 2
    if args.events:
        snap = snapshot_from_events(args.events)
    elif args.socket or args.host:
        from maskclustering_tpu.serve.client import ServeClient

        address = args.socket if args.socket else (args.host, args.port)
        with ServeClient(address, timeout_s=30.0) as client:
            snap = (client.telemetry().get("telemetry") or {})
    else:
        p.error("need --socket, --host/--port or --events")
        return 2  # unreachable — argparse exits

    result = evaluate(spec, snap)
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print("\n".join(render_result(result)))
    if args.check and not result["ok"]:
        for name in violated(result):
            print(f"slo: VIOLATED objective: {name}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
