"""Live terminal dashboard over a serving daemon's telemetry op.

    python -m maskclustering_tpu.obs.top --socket /tmp/mct.sock
    python -m maskclustering_tpu.obs.top --host 127.0.0.1 --port 7777
    python -m maskclustering_tpu.obs.top --socket ... --once   # one frame

Polls ``{"op": "status", "detail": "telemetry"}`` at a fixed interval and
renders a refreshing view: request latency p50/p95 by shape bucket
(window + cumulative), a queue-depth sparkline over the window ring,
reject/crash/respawn rates, worker liveness (heartbeat age, consecutive
respawns, in-flight crash count — the wedge-is-coming signals), AOT-cache
hits and post-warm compile violations (the serve-many contract, live),
per-tenant accounting rows and the armed SLO spec's burn-rate panel
(the poll asks for ``detail=slo``, which is telemetry + the verdict).

Rendering is a pure function over the stats document (``render_top``) so
the dashboard is testable without a TTY; the CLI loop only clears the
screen and reconnects per poll (a daemon restart costs one missed frame,
not a dead dashboard).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 32) -> str:
    """Unicode sparkline of the last ``width`` values (empty-safe)."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    hi = max(vals)
    if hi <= 0:
        return _SPARK[0] * len(vals)
    return "".join(_SPARK[min(int(v / hi * (len(_SPARK) - 1) + 0.5),
                              len(_SPARK) - 1)] for v in vals)


def _fmt(v: Optional[float], suffix: str = "s") -> str:
    return "-" if v is None else f"{v:.3f}{suffix}"


def _rate(windows: List[Dict], key: str) -> float:
    """Per-second rate of a window counter over the ring."""
    total = sum(w.get(key, 0) or 0 for w in windows)
    dur = sum(w.get("dur_s", 0.0) or 0.0 for w in windows)
    return total / dur if dur > 0 else 0.0


def render_top(stats: Dict, *, now: Optional[float] = None) -> str:
    """One dashboard frame from a ``status detail=telemetry`` answer."""
    now = time.time() if now is None else now
    tel = stats.get("telemetry") or {}
    windows: List[Dict] = tel.get("windows") or []
    cum = tel.get("cumulative") or {}
    counters = cum.get("counters") or {}
    gauges = cum.get("gauges") or {}
    current = tel.get("current") or {}
    queue = stats.get("queue") or {}
    worker = stats.get("worker") or {}
    lines: List[str] = []

    lines.append(
        f"mct-serve top — config {stats.get('config', '?')} | "
        f"uptime {stats.get('uptime_s', 0):.0f}s | "
        f"window {tel.get('window_s', '?')}s x {len(windows)} | "
        f"{'DRAINING' if stats.get('draining') else 'serving'}")

    counts = stats.get("counts") or {}
    lines.append(
        "requests: " + " | ".join(
            f"{k} {counts.get(k, 0)}"
            for k in ("requests", "ok", "failed", "deadline", "interrupted")
            if counts.get(k)) if any(counts.values())
        else "requests: none yet")

    # queue: live depth + the ring's depth history as a sparkline
    depths = [w.get("queue_depth", 0) for w in windows]
    lines.append(
        f"queue: depth {queue.get('depth', 0)}/{queue.get('capacity', '?')} "
        f"| high-water {queue.get('high_water', 0)} "
        f"| admitted {queue.get('admitted', 0)}"
        + (f"  [{sparkline(depths)}]" if depths else ""))

    # latency by bucket: each bucket's newest window WITH data (an idle
    # last window must not blank the view) next to cumulative
    cum_lat = cum.get("latency") or {}
    buckets = sorted(set(list(cum_lat))
                     | {b for w in windows for b in (w.get("latency") or {})})
    for b in buckets:
        w = next((wd["latency"][b] for wd in reversed(windows)
                  if (wd.get("latency") or {}).get(b)), {})
        c = cum_lat.get(b) or {}
        lines.append(
            f"  bucket {b:<18} window p50 {_fmt(w.get('p50_s'))} "
            f"p95 {_fmt(w.get('p95_s'))} (n={w.get('count', 0)}) | "
            f"cum p50 {_fmt(c.get('p50'))} p95 {_fmt(c.get('p95'))} "
            f"(n={c.get('count', 0)})")
    wait = next((wd["queue_wait"] for wd in reversed(windows)
                 if wd.get("queue_wait")),
                current.get("queue_wait") or {})
    if wait:
        lines.append(f"  queue wait: p50 {_fmt(wait.get('p50_s'))} "
                     f"p95 {_fmt(wait.get('p95_s'))} "
                     f"max {_fmt(wait.get('max_s'))}")

    # fault surface: rejects / crashes / respawns as ring rates
    rejects: Dict[str, int] = {}
    for w in windows:
        for r, n in (w.get("rejects") or {}).items():
            rejects[r] = rejects.get(r, 0) + int(n)
    crash_rate = _rate(windows, "crashes")
    lines.append(
        "faults: "
        + (("rejects " + ", ".join(f"{r} x{n}"
                                   for r, n in sorted(rejects.items())) + " | ")
           if rejects else "rejects none | ")
        + f"crashes {int(sum(w.get('crashes', 0) for w in windows))} "
        f"({crash_rate:.3f}/s) | "
        f"respawns {int(sum(w.get('respawns', 0) for w in windows))} | "
        f"requeued {int(sum(w.get('requeued', 0) for w in windows))}")

    # worker liveness: pool panel (one row per slice) when the daemon
    # carves a pool, else the single isolated-worker wedge-is-coming line
    pool = stats.get("pool") or {}
    if pool:
        sched = pool.get("scheduler") or {}
        hits = int(sched.get("affinity_hits", 0))
        misses = int(sched.get("affinity_misses", 0))
        routed = hits + misses
        lines.append(
            f"pool: carve {pool.get('carve', '?')} | "
            f"alive {worker.get('alive', '?')}/{worker.get('pool', '?')} | "
            f"dispatched {int(sched.get('dispatched', 0))} | "
            f"affinity {hits}/{routed} warm"
            + (f" ({hits / routed:.0%})" if routed else "")
            + f" | crash reroutes {int(sched.get('crash_reroutes', 0))} | "
            f"recarves {int(sched.get('recarves', 0))}")
        for w in pool.get("workers") or []:
            hb = w.get("hb_age_s")
            state = "RETIRED" if w.get("retired") else "up"
            lines.append(
                f"  worker {w.get('worker_id', '?')}: {state:<7} "
                f"pid {w.get('pid', '?')} | "
                f"hb age {_fmt(hb) if hb is not None else '-'} | "
                f"feed {int(w.get('feed_depth', 0))} | "
                f"dispatched {int(w.get('dispatched', 0))} | "
                f"warm {int(w.get('warm_buckets', 0))} | "
                f"respawns {w.get('consecutive_respawns', 0)} | "
                f"streams open {int(w.get('open_streams', 0))}"
                + (f" lost {int(w.get('lost_streams', 0))}"
                   if w.get("lost_streams") else ""))
        tenants = pool.get("tenants") or {}
        if tenants:
            lines.append("  dequeue share: " + " | ".join(
                f"{t} {int(v.get('dispatched', 0))} (w={v.get('weight', 1)}"
                + (f", quota {v.get('quota')}" if v.get("quota") else "")
                + ")" for t, v in sorted(tenants.items())))
    elif worker:
        hb = worker.get("hb_age_s")
        lines.append(
            f"worker: pid {worker.get('pid', '?')} | "
            f"hb age {_fmt(hb) if hb is not None else '-'} | "
            f"spawns {worker.get('spawns', 0)} | "
            f"consecutive respawns {worker.get('consecutive_respawns', 0)} | "
            f"in-flight crashes {worker.get('inflight_crashes', 0)}")

    # the serve-many contract, live
    post_warm = int(sum(w.get("post_warm_compiles", 0) for w in windows))
    pf_gauge = gauges.get("retrace.live.post_freeze")
    if pf_gauge is not None:
        post_warm = max(post_warm, int(pf_gauge))
    aot_hits = int(counters.get("aot_cache.hits", 0))
    lines.append(
        f"compiles: post-warm {post_warm}"
        + (" [VIOLATION]" if post_warm else "")
        + f" | aot-cache hits {aot_hits} | warm buckets "
        f"{len(stats.get('warm_buckets') or [])}")
    relayed = int(counters.get("worker.telem_messages", 0))
    if relayed:
        lines.append(
            f"relay: {relayed} telem line(s) | "
            f"{int(counters.get('worker.telem_spans', 0))} span(s)"
            + (f" | {int(counters.get('worker.telem_spans_dropped', 0))} "
               f"dropped" if counters.get("worker.telem_spans_dropped")
               else ""))

    # per-tenant accounting (cumulative since rebase; windows carry the
    # same sub-rows): who is spending the device
    cum_tenants = cum.get("tenants") or {}
    if cum_tenants:
        lines.append("tenants:")
        for name in sorted(cum_tenants):
            t = cum_tenants[name] or {}
            lat = (t.get("latency") or {}).get("all") or {}
            lines.append(
                f"  {name:<16} req {int(t.get('requests', 0))} "
                f"| rejects {int(t.get('rejects', 0))} "
                f"| crashes {int(t.get('crashes', 0))} "
                f"| p95 {_fmt(lat.get('p95'))} "
                f"| device {float(t.get('device_s', 0.0)):.3f}s "
                f"| d2h {int(t.get('d2h_bytes', 0))}B")

    # the mct-sentinel panel: canary probe volume + drift, live. The
    # summary rides every status answer of a sentinel-armed daemon; the
    # per-coordinate matrix only a ``detail=sentinel`` poll.
    canary = stats.get("canary")
    sentinel = stats.get("sentinel") or {}
    if canary or sentinel.get("rounds") is not None:
        rounds = int((canary or {}).get("rounds",
                                        sentinel.get("rounds", 0)) or 0)
        drift_total = int((canary or {}).get(
            "drift_total", sentinel.get("drift_total", 0)) or 0)
        ring_drift = int(sum(w.get("drift", 0) or 0 for w in windows))
        line = (f"sentinel: canary rounds {rounds} | drift {drift_total}"
                + (" [DRIFT — outputs diverged from goldens]"
                   if drift_total or ring_drift else " | goldens hold"))
        skipped = int(sentinel.get("skipped_busy", 0) or 0)
        if skipped:
            line += f" | ticks skipped busy {skipped}"
        lines.append(line)
        ages = sentinel.get("last_verified_age_s") or {}
        drift_coords = sentinel.get("drift_coords") or {}
        for coord in sorted(set(ages) | set(drift_coords)):
            mark = (f"DRIFT x{drift_coords[coord]}"
                    if coord in drift_coords else "ok")
            age = (f"verified {ages[coord]:.0f}s ago"
                   if coord in ages else "never verified")
            lines.append(f"  {coord:<44} {mark:<10} {age}")

    # the SLO burn-rate panel (status detail=slo answers only)
    slo = stats.get("slo")
    if slo is not None:
        from maskclustering_tpu.obs.slo import render_result

        lines.extend(render_result(slo))
    return "\n".join(lines)


def _poll(address, timeout_s: float) -> Dict:
    from maskclustering_tpu.serve.client import ServeClient

    with ServeClient(address, timeout_s=timeout_s) as client:
        # detail=slo is telemetry plus the armed spec's burn-rate verdict
        stats = client.slo()
        if stats.get("canary") is not None:
            # sentinel-armed daemon: add the per-coordinate drift matrix
            stats["sentinel"] = client.sentinel().get("sentinel")
        return stats


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m maskclustering_tpu.obs.top",
        description="live terminal dashboard over a serving daemon's "
                    "telemetry op")
    p.add_argument("--socket", default=None, help="daemon AF_UNIX socket")
    p.add_argument("--host", default=None, help="daemon TCP host")
    p.add_argument("--port", type=int, default=0, help="daemon TCP port")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll/refresh seconds (default 2)")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (scripts/CI)")
    p.add_argument("--json", action="store_true",
                   help="print the raw stats document instead of the view")
    args = p.parse_args(argv)
    if not args.socket and not args.host:
        p.error("need --socket PATH or --host HOST --port N")
    address = args.socket if args.socket else (args.host, args.port)

    while True:
        try:
            stats = _poll(address, timeout_s=max(args.interval * 4, 10.0))
        except Exception as e:  # noqa: BLE001 — daemon gone/restarting
            if args.once:
                print(f"obs.top: cannot reach daemon at {address}: {e}",
                      file=sys.stderr)
                return 1
            print(f"obs.top: daemon unreachable ({e}); retrying",
                  file=sys.stderr)
            time.sleep(args.interval)
            continue
        if args.json:
            print(json.dumps(stats, sort_keys=True))
        else:
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(render_top(stats))
            sys.stdout.flush()
        if args.once:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
