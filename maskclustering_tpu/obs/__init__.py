"""Unified tracing + metrics for the pipeline (spans, counters, JSONL).

One import point for all instrumentation call sites::

    from maskclustering_tpu import obs

    with obs.span("graph", scene=seq, m_pad=m_pad) as sp:
        stats = compute_graph_stats(...)
        sp.sync(stats)            # device time charged to THIS span

    obs.count_transfer("d2h", planes.nbytes, "post.claims")

Disabled (the default) everything routes to a no-op tracer singleton:
``span`` returns a shared null span whose ``sync`` does NOT touch the
device — instrumented code has zero extra syncs and no event I/O, so
honest-shape bench numbers are unaffected. ``configure(path)`` arms the
real tracer: spans fence at their boundaries, every span/metrics flush
appends one schema-versioned JSON line to ``path``, and live HBM is
sampled at span ends. Render/diff captured files with::

    python -m maskclustering_tpu.obs.report events.jsonl [--diff other.jsonl]

Modules: tracer (spans + fencing), metrics (registry), events (JSONL
sink/reader), report (CLI).
"""

from __future__ import annotations

import atexit
from typing import Optional

from maskclustering_tpu.obs.events import (SCHEMA_VERSION, EventSink,
                                           ReadStats, read_events)
from maskclustering_tpu.obs.metrics import (count, count_transfer, gauge,
                                            gauge_max, observe, registry,
                                            sample_hbm)
from maskclustering_tpu.obs.tracer import NULL_TRACER, NullTracer, Span, Tracer
from maskclustering_tpu.obs.xprof import XprofArm

__all__ = [
    "configure", "configure_sink", "disable", "enabled", "events_path",
    "emit_event", "get_tracer",
    "scene_tracer", "span", "record_span", "traced", "flush_metrics",
    "count", "count_transfer", "gauge", "gauge_max", "observe", "registry",
    "sample_hbm", "read_events", "EventSink", "Tracer", "NullTracer",
    "Span", "NULL_TRACER", "SCHEMA_VERSION", "ReadStats", "XprofArm",
]

_active = NULL_TRACER
_sink: Optional[EventSink] = None
# timing-only fallback: run_scene's per-stage timings dict must exist with
# or without obs, so scene_tracer() never returns the null tracer — but
# this one never fences, emits, or samples (sink=None disables all three)
_TIMING_TRACER = Tracer(sink=None)


def configure(path: str, *, fence: bool = True, annotations: bool = False,
              sample_memory: bool = True, meta: Optional[dict] = None,
              truncate: bool = False, xprof_dir: Optional[str] = None,
              xprof_spans: Optional[tuple] = None,
              xprof_limit: int = 1) -> Tracer:
    """Arm tracing: spans + metrics flushes append to the JSONL at ``path``.

    Idempotent per path; re-configuring to a new path closes the old sink.
    Writes one ``meta`` event up front (schema version + caller context) so
    a report can label the run without side-channel files.

    ``truncate``: start the file fresh. For callers that OWN the path and
    re-derive it per run (run.py's --report default) — mixing a rerun's
    spans into a stale capture would silently skew every percentile. Leave
    False when several processes share one file by design (bench worker
    attempts + supervisor).

    ``xprof_dir`` + ``xprof_spans``: arm span-triggered ``jax.profiler``
    capture (obs/xprof.py) — the first ``xprof_limit`` openings of each
    named span are bracketed by start/stop_trace, flushed to
    ``xprof_dir/<span>-<k>``. Off by default: profiling is the one obs
    feature with real runtime cost.
    """
    global _active, _sink
    if (_sink is not None and _sink.path == path
            and isinstance(_active, Tracer)
            and not truncate and not (xprof_dir and xprof_spans)):
        # idempotent ONLY for a plain re-arm of the same path: a truncate
        # or xprof request must reconfigure, not be silently dropped
        return _active
    disable()
    if truncate:
        # a truncating owner starts a FRESH capture: stale process-local
        # counters from an earlier run in this process would otherwise pool
        # into the new digest (same skew the span truncate defends against)
        registry().reset()
    _sink = EventSink(path, truncate=truncate)
    # NO jax probe here: ``jax.default_backend()`` initializes the backend,
    # and configure() must stay safe in chip-free processes (bench.py's
    # supervisor). Callers that know the backend pass it via ``meta``.
    payload = {"schema": SCHEMA_VERSION}
    if meta:
        payload.update(meta)
    _sink.emit("meta", payload)
    arm = None
    if xprof_dir and xprof_spans:
        arm = XprofArm(xprof_dir, xprof_spans, limit=xprof_limit)
    _active = Tracer(_sink, fence=fence, annotations=annotations,
                     sample_memory=sample_memory, xprof=arm)
    return _active


def configure_sink(sink, *, fence: bool = False, annotations: bool = False,
                   sample_memory: bool = False) -> Tracer:
    """Arm tracing against an arbitrary sink object (anything with the
    ``EventSink`` emit/close surface).

    The telemetry relay's entry point (obs/telemetry.RelaySink): the
    worker subprocess needs its spans CAPTURED but has no events file —
    they ship up the supervisor pipe instead. Defaults are the zero-cost
    posture (no fencing, no memory sampling): the relay must not add
    device syncs the in-process topology would not pay.
    """
    global _active, _sink
    disable()
    _sink = sink
    _active = Tracer(sink, fence=fence, annotations=annotations,
                     sample_memory=sample_memory)
    return _active


def emit_event(kind: str, payload: dict) -> None:
    """Append one typed event line to the armed sink (no-op when off).

    The telemetry ticker's window rows ride this — any subsystem with its
    own event kind can append without holding a tracer.
    """
    if _sink is not None:
        _sink.emit(kind, payload)


def disable() -> None:
    """Back to the zero-cost singleton; flushes and closes any open sink."""
    global _active, _sink
    if _sink is not None:
        try:
            _active.flush_metrics()
        except Exception:  # noqa: BLE001
            pass
        xprof = getattr(_active, "xprof", None)
        if xprof is not None:
            # stops a trace left open by a crashed span body before the
            # interpreter can exit with a wedged profiler session
            xprof.close()
        _sink.close()
        _sink = None
    _active = NULL_TRACER


atexit.register(disable)  # final metrics flush on clean interpreter exit


def enabled() -> bool:
    return _active is not NULL_TRACER


def events_path() -> Optional[str]:
    return _sink.path if _sink is not None else None


def get_tracer():
    """The active tracer: a real ``Tracer`` when armed, else the no-op
    singleton. Library instrumentation goes through this (or the
    module-level ``span``/``traced`` shortcuts)."""
    return _active


def scene_tracer() -> Tracer:
    """The tracer ``run_scene`` times its stages with: the armed tracer
    when obs is on, else a shared timing-only tracer (no fence, no events)
    so ``SceneResult.timings`` exists either way."""
    return _active if isinstance(_active, Tracer) else _TIMING_TRACER


def span(name: str, **attrs):
    return _active.span(name, **attrs)


def record_span(name: str, seconds: float, **kw) -> None:
    _active.record_span(name, seconds, **kw)


def traced(name: str, **attrs):
    """Decorator: trace every call of the wrapped function as one span.

    Late-binds the active tracer so functions decorated at import time
    still pick up a tracer configured afterwards.
    """
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with _active.span(name, **attrs):
                return fn(*a, **kw)

        return wrapper

    return deco


def flush_metrics() -> None:
    _active.flush_metrics()
