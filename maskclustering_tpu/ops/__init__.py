from maskclustering_tpu.ops.geometry import (
    bbox_of,
    bboxes_overlap,
    invert_se3,
    project_points,
    transform_points,
    unproject_depth,
    voxel_downsample_np,
)

__all__ = [
    "bbox_of",
    "bboxes_overlap",
    "invert_se3",
    "project_points",
    "transform_points",
    "unproject_depth",
    "voxel_downsample_np",
]
