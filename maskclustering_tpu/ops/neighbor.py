"""Fixed-radius K-neighbor search (ball query) with pytorch3d semantics.

The reference's single CUDA kernel dependency: pytorch3d.ops.ball_query with
K=20, radius=0.01 over padded ragged batches, returning -1-padded neighbor
indices in scan order (reference utils/mask_backprojection.py:27-39,123-128).
Used by the exact-parity backprojection path and validated against a brute
force oracle; the default pipeline path replaces the search direction
entirely (models/backprojection.py) and does not call this.

The jnp implementation processes query chunks against the full candidate
set with a running "first K within radius" selection — scan-order semantics
identical to pytorch3d (which keeps the FIRST K candidates by index, not
the nearest K). A Pallas TPU kernel with the same contract lives in
ops/pallas/ball_query.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k", "radius", "query_chunk"))
def ball_query(
    query: jnp.ndarray,  # (B, P, 3) padded query points
    candidates: jnp.ndarray,  # (B, S, 3) padded candidate points
    query_lengths: jnp.ndarray,  # (B,) valid query counts
    candidate_lengths: jnp.ndarray,  # (B,) valid candidate counts
    *,
    k: int = 20,
    radius: float = 0.01,
    query_chunk: int = 1024,
) -> jnp.ndarray:
    """First-K-within-radius indices per query point, -1 padded.

    Matches pytorch3d.ops.ball_query(return_nn=False): for each valid query
    point, the indices of the first K candidates (ascending index order)
    with squared distance <= radius^2; remaining slots are -1. Rows beyond
    query_lengths are all -1.
    """
    b, p, _ = query.shape
    s = candidates.shape[1]
    r2 = radius * radius

    p_chunks = max(1, -(-p // query_chunk))
    p_pad = p_chunks * query_chunk
    query = jnp.pad(query, ((0, 0), (0, p_pad - p), (0, 0)))

    cand_idx = jnp.arange(s, dtype=jnp.int32)

    def per_batch(q, c, ql, cl):
        cvalid = cand_idx < cl

        def chunk_fn(start):
            qc = jax.lax.dynamic_slice(q, (start, 0), (query_chunk, 3))
            d2 = jnp.sum((qc[:, None, :] - c[None, :, :]) ** 2, axis=-1)
            hit = (d2 <= r2) & cvalid[None, :]  # (chunk, S)
            # rank of each hit within its row (0-based among hits, scan order)
            rank = jnp.cumsum(hit.astype(jnp.int32), axis=1) - 1
            take = hit & (rank < k)
            # scatter candidate index into output slot `rank`
            out = jnp.full((query_chunk, k), -1, dtype=jnp.int32)
            rows = jnp.broadcast_to(jnp.arange(query_chunk)[:, None], (query_chunk, s))
            slot = jnp.where(take, rank, k)  # k = dropped
            out = out.at[rows.reshape(-1), slot.reshape(-1)].max(
                jnp.where(take, cand_idx[None, :], -1).reshape(-1), mode="drop")
            qvalid = (jnp.arange(query_chunk) + start) < ql
            return jnp.where(qvalid[:, None], out, -1)

        outs = jax.lax.map(chunk_fn, jnp.arange(p_chunks) * query_chunk)
        return outs.reshape(p_pad, k)[:p]

    return jax.vmap(per_batch)(query, candidates, query_lengths, candidate_lengths)


def ball_query_brute(query, candidates, query_lengths, candidate_lengths, k, radius):
    """Numpy oracle: literal first-K-within-radius."""
    import numpy as np

    query = np.asarray(query)
    candidates = np.asarray(candidates)
    b, p, _ = query.shape
    out = np.full((b, p, k), -1, dtype=np.int64)
    for bi in range(b):
        for pi in range(int(query_lengths[bi])):
            found = 0
            for si in range(int(candidate_lengths[bi])):
                d = query[bi, pi] - candidates[bi, si]
                if float(d @ d) <= radius * radius:
                    out[bi, pi, found] = si
                    found += 1
                    if found == k:
                        break
    return out
