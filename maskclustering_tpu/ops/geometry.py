"""Camera geometry as pure JAX array ops.

The reference leans on Open3D's C++ geometry (depth unprojection via
``create_from_depth_image``, voxel downsampling — reference
utils/mask_backprojection.py:17-24,105). Here the same math is expressed as
jit/vmap-able jnp so it runs on the MXU/VPU and fuses with downstream ops.

Pinhole conventions match Open3D: pixel (u,v) at depth z unprojects to
x=(u-cx)z/fx, y=(v-cy)z/fy (no half-pixel offset), camera-to-world extrinsic
applied as p_world = R p_cam + t.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def invert_se3(mat: jnp.ndarray) -> jnp.ndarray:
    """Invert a (...,4,4) rigid transform without a general solve."""
    r = mat[..., :3, :3]
    t = mat[..., :3, 3]
    rt = jnp.swapaxes(r, -1, -2)
    new_t = -jnp.einsum("...ij,...j->...i", rt, t)
    out = jnp.zeros_like(mat)
    out = out.at[..., :3, :3].set(rt)
    out = out.at[..., :3, 3].set(new_t)
    out = out.at[..., 3, 3].set(1.0)
    return out


def unproject_depth(depth: jnp.ndarray, intrinsics: jnp.ndarray, cam_to_world: jnp.ndarray,
                    depth_trunc: float = 20.0):
    """Dense depth-map unprojection to world coordinates.

    Args:
        depth: (H, W) metres.
        intrinsics: (3, 3).
        cam_to_world: (4, 4).
        depth_trunc: depths above this are invalid (reference DEPTH_TRUNC=20,
            utils/mask_backprojection.py:13,22).

    Returns:
        points: (H, W, 3) world-frame points (garbage where ~valid).
        valid: (H, W) bool — depth in (0, depth_trunc].
    """
    h, w = depth.shape
    fx, fy = intrinsics[0, 0], intrinsics[1, 1]
    cx, cy = intrinsics[0, 2], intrinsics[1, 2]
    v, u = jnp.mgrid[0:h, 0:w]
    z = depth
    x = (u - cx) * z / fx
    y = (v - cy) * z / fy
    cam = jnp.stack([x, y, z], axis=-1)
    r = cam_to_world[:3, :3]
    t = cam_to_world[:3, 3]
    # full f32 precision: on TPU, default matmul precision is bf16-ish, whose
    # ~0.4% coordinate error would swamp the 1 cm association threshold
    world = jnp.matmul(cam, r.T, precision="highest") + t
    valid = (depth > 0) & (depth <= depth_trunc)
    return world, valid


def transform_points(points: jnp.ndarray, mat: jnp.ndarray) -> jnp.ndarray:
    """Apply a (4,4) rigid transform to (..., 3) points (full f32 precision)."""
    return jnp.matmul(points, mat[:3, :3].T, precision="highest") + mat[:3, 3]


def project_points(points: jnp.ndarray, intrinsics: jnp.ndarray, world_to_cam: jnp.ndarray):
    """Project world points into a pinhole camera.

    Returns:
        uv: (..., 2) continuous pixel coordinates (u=column, v=row).
        z: (...,) camera-frame depth.
    """
    cam = transform_points(points, world_to_cam)
    z = cam[..., 2]
    safe_z = jnp.where(z != 0, z, 1.0)
    u = cam[..., 0] / safe_z * intrinsics[0, 0] + intrinsics[0, 2]
    v = cam[..., 1] / safe_z * intrinsics[1, 1] + intrinsics[1, 2]
    return jnp.stack([u, v], axis=-1), z


def voxel_keys(points: jnp.ndarray, voxel_size: float, origin: jnp.ndarray) -> jnp.ndarray:
    """Integer voxel coordinates for each point (floor grid, Open3D-style)."""
    return jnp.floor((points - origin) / voxel_size).astype(jnp.int32)


def backproject_depth_np(depth: np.ndarray, intrinsics: np.ndarray,
                         cam_to_world: np.ndarray, depth_trunc: float = np.inf):
    """Host pinhole backprojection: (world points (M, 3) f64, valid (H, W) bool).

    The single source of truth for host-side depth-to-world geometry —
    shared by the exact-parity association path and the debug viewers so a
    convention change (pixel centers, truncation) cannot drift between them.
    """
    depth = np.asarray(depth, dtype=np.float64)
    intrinsics = np.asarray(intrinsics, dtype=np.float64)
    cam_to_world = np.asarray(cam_to_world, dtype=np.float64)
    h, w = depth.shape
    fx, fy = intrinsics[0, 0], intrinsics[1, 1]
    cx, cy = intrinsics[0, 2], intrinsics[1, 2]
    v, u = np.mgrid[0:h, 0:w]
    valid = (depth > 0) & (depth <= depth_trunc)
    z = depth[valid]
    pts = np.stack([(u[valid] - cx) / fx * z, (v[valid] - cy) / fy * z, z], axis=1)
    pts = pts @ cam_to_world[:3, :3].T + cam_to_world[:3, 3]
    return pts, valid


def voxel_downsample_np(points: np.ndarray, voxel_size: float) -> np.ndarray:
    """Host-side voxel downsample: mean of points per occupied voxel.

    Open3D's voxel_down_sample averages points per voxel over the min-corner
    grid; `np.unique` picks voxel order (sorted), which differs from Open3D's
    hash order but downstream consumers are order-invariant.
    """
    points = np.asarray(points)
    if len(points) == 0:
        return points
    origin = points.min(axis=0)
    keys = np.floor((points - origin) / voxel_size).astype(np.int64)
    _, inverse, counts = np.unique(keys, axis=0, return_inverse=True, return_counts=True)
    sums = np.zeros((len(counts), 3), dtype=np.float64)
    np.add.at(sums, inverse, points)
    return sums / counts[:, None]


def bbox_of(points: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Axis-aligned (min, max) corners of a point set."""
    pts = np.asarray(points)
    return pts.min(axis=0), pts.max(axis=0)


def bboxes_overlap(amin, amax, bmin, bmax) -> bool:
    """Axis-aligned box intersection test (reference utils/geometry.py:3-7)."""
    return bool(np.all(np.asarray(amin) <= np.asarray(bmax)) and np.all(np.asarray(bmin) <= np.asarray(amax)))
