"""Scene-scale voxel-grid DBSCAN as a static-shape device kernel.

The post-process DBSCAN split historically ran on host (native C++ /
sklearn over the pulled node point lists). `ops/dbscan.dbscan_fixed_jax`
exists for per-mask denoising on the exact-parity path, but its O(P^2)
distance matrix caps it at a few thousand points — a node of a scene-scale
instance (a floor, a wall) holds tens of thousands. This module is the
grid/union-find algorithm of ``native/src/mc_native.cpp`` reformulated for
XLA with static shapes, usable at instance granularity inside the
post-process program:

- the **grid** is pure scene geometry (cell = eps-sized voxel), so it is
  built ONCE per scene on host from the host-resident cloud
  (``build_grid``) and uploaded — candidate enumeration never depends on
  device data, which is what keeps every shape static. Two points within
  ``eps`` differ by at most one cell per axis, so the 27-cell stencil is a
  complete candidate cover; the per-cell candidate window is the
  power-of-two bucket of the scene's max cell occupancy.
- the work items are **(instance, point) pairs** — every instance's node
  membership flattened and compacted to a ``C_pad`` bucket (points
  claimed by several representatives appear once per representative, like
  the host path's per-rep point lists). Pair compaction follows ascending
  (rep slot, point id) order, so min-LABEL arithmetic below is min-INDEX
  arithmetic within each rep.
- each pair's in-eps SAME-INSTANCE neighbors compact into a static
  ``neighbor_cap`` window (one pass over the 27-cell stencil, prefix-sum
  packing); core/border classification and the iterative min-label
  propagation with pointer jumping — the same fixpoint
  `models/clustering.py` runs on device — then sweep (C_pad,
  neighbor_cap) gathers instead of touching the (27 x cell_cap) stencil
  again, which is what makes the sweeps cheap at scene scale.

Label semantics are the host dispatch's exactly (ops/dbscan.dbscan_labels,
both native and sklearn): per instance, clusters numbered 0.. in ascending
order of their lowest core point index, border points attached to the
lowest-numbered neighboring core cluster, noise = -1. Min-label
propagation makes every core pair's label the component's lowest core pair
index, so ranking root pairs reproduces the scan-order numbering without
any scan — pinned against the host dispatch by
tests/test_postprocess_device.py.

Distances compare in f32 on device vs f64 on host; both see the same
f32 coordinates, so decisions only diverge for pairs within f32 rounding
of ``eps`` exactly — the same tolerance `dbscan_fixed_jax` already accepts
on the parity path.

Point-sharded scenes (``cfg.point_shards`` > 1, the fused mesh path):
the split kernel's inputs arrive with their N dimension sharded over the
``point`` mesh axis; the pair compaction (`jnp.nonzero` at the C_pad
bucket) is a global enumeration, so GSPMD gathers the (r_pad, N)
candidate plane once — bounded, bool-typed, and orders of magnitude
under the (F, N) claim planes the emit-only drain keeps in HBM. The
grid itself is host geometry either way (the cloud never left the host
on any path), so nothing here depends on the shard count; byte-identity
across shard counts rides the same label-for-label pin as the host
dispatch (tests/test_point_sharding.py).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import numpy as np

# the 27 stencil offsets, fixed order (x-major, matching mc_native's loops)
STENCIL: Tuple[Tuple[int, int, int], ...] = tuple(
    (dx, dy, dz) for dx in (-1, 0, 1) for dy in (-1, 0, 1)
    for dz in (-1, 0, 1))


def _bucket_pow2(value: int, minimum: int = 8) -> int:
    b = minimum
    while b < value:
        b *= 2
    return b


class GridStructure(NamedTuple):
    """Host-built, device-consumed candidate structure of one scene.

    ``order`` lists point indices sorted by voxel; ``start[s, i]`` /
    ``length[s, i]`` delimit, inside ``order``, the points of the cell at
    stencil offset ``s`` from point ``i``'s cell. ``cell_cap`` is the
    static candidate window (pow2 bucket of the max cell occupancy), so
    ``order[start + 0..cell_cap)`` masked by ``length`` enumerates every
    candidate with static shapes.
    """

    order: np.ndarray  # (N,) int32
    start: np.ndarray  # (27, N) int32
    length: np.ndarray  # (27, N) int32
    cell_cap: int


def build_grid(points: np.ndarray, eps: float, *,
               cap_minimum: int = 8,
               n_real: Optional[int] = None) -> GridStructure:
    """Voxel-bin a host point cloud at cell size ``eps`` (f64 quantization,
    like the native path). O(27 N log N) numpy; pure geometry — no device
    data involved, so the post-process can build it before any kernel
    lands.

    ``n_real``: number of leading REAL points when the cloud is padded to
    a shape bucket. Padded points share one sentinel coordinate, so
    binning them would put thousands of points in a single voxel and blow
    the static candidate window (``cell_cap``) up by orders of magnitude.
    They can never be node points (the sentinel-pad invariant), so they
    are excluded from the grid entirely: they never appear in ``order``
    and the per-point run tables only cover the real prefix (valid pairs
    only ever index real points)."""
    pts = np.asarray(points, dtype=np.float64)
    n = pts.shape[0]
    if n_real is not None and n_real < n:
        pts = pts[:n_real]
    if n == 0 or pts.shape[0] == 0:
        z = np.zeros((27, n), np.int32)
        return GridStructure(np.zeros(0, np.int32), z, z.copy(), cap_minimum)
    cell = np.floor(pts / float(eps)).astype(np.int64)
    cell -= cell.min(axis=0)
    cell += 1  # stencil neighbors at -1 stay non-negative
    dims = cell.max(axis=0) + 2  # covers every neighbor coordinate

    def lin(c):
        return (c[..., 0] * dims[1] + c[..., 1]) * dims[2] + c[..., 2]

    key = lin(cell)
    order = np.argsort(key, kind="stable").astype(np.int32)
    sorted_key = key[order]
    n = pts.shape[0]  # run tables cover the real (grid-binned) prefix only
    start = np.empty((27, n), np.int32)
    length = np.empty((27, n), np.int32)
    off = np.empty_like(cell)
    for s, (dx, dy, dz) in enumerate(STENCIL):
        off[:, 0] = cell[:, 0] + dx
        off[:, 1] = cell[:, 1] + dy
        off[:, 2] = cell[:, 2] + dz
        nk = lin(off)
        lo = np.searchsorted(sorted_key, nk, side="left")
        hi = np.searchsorted(sorted_key, nk, side="right")
        start[s] = lo
        length[s] = hi - lo
    # every cell is its own center cell, so the center lengths cover the
    # max occupancy (any neighbor cell is some point's center cell)
    cap = _bucket_pow2(int(length[13].max(initial=1)), cap_minimum)
    return GridStructure(order=order, start=start, length=length,
                         cell_cap=cap)


def grid_dbscan_pairs(points, order, start, length, pair_rep, pair_pt,
                      pair_valid, *, r_pad: int, cell_cap: int,
                      neighbor_cap: int, eps: float, min_points: int):
    """DBSCAN over compacted (rep, point) pairs; call INSIDE a jit.

    ``pair_rep``/``pair_pt``/``pair_valid`` (C_pad,) name the work items in
    ascending (rep, point) order (padding: valid False). Returns
    ``(dense_local, root_count, nb_overflow)``:

    - ``dense_local`` (C_pad,) int32 — the pair's DBSCAN label within ITS
      rep, numbered like the host dispatch (ascending min core point
      index; -1 = noise/invalid);
    - ``root_count`` (r_pad,) int32 — clusters per rep (the per-rep group
      count minus the noise slot);
    - ``nb_overflow`` () bool — some pair had more than ``neighbor_cap``
      same-rep in-eps neighbors, so hits were dropped and the labels are
      unusable: the caller must fail over (the post-process raises
      ``PostprocessCapacityError`` and the ladder's host rung re-runs).

    One stencil pass packs each pair's same-rep in-eps neighbors into a
    (C_pad, neighbor_cap) table by prefix-sum compaction; the propagation
    fixpoint then never touches the grid again. ``degree`` counts the pair
    itself (its own cell is in the stencil and d2=0), matching the
    sklearn/Open3D ``min_points`` contract.
    """
    import jax
    import jax.numpy as jnp

    n = points.shape[0]
    n_grid = order.shape[0]  # real (grid-binned) prefix; n - n_grid = pads
    c_pad = pair_rep.shape[0]
    sent = jnp.int32(c_pad)
    lanes = jnp.arange(cell_cap, dtype=jnp.int32)
    arange_c = jnp.arange(c_pad, dtype=jnp.int32)
    eps2 = jnp.float32(float(eps) * float(eps))

    # (rep, point) -> pair index lookup (sentinel: c_pad); one dump slot
    # keeps padded pairs' scatters off slot 0
    flat = jnp.where(pair_valid, pair_rep * n + pair_pt, r_pad * n)
    pair_of = jnp.full(r_pad * n + 1, c_pad, jnp.int32).at[flat].set(arange_c)
    own = jnp.take(points, pair_pt, axis=0)  # (C, 3)
    rep_base = jnp.clip(pair_rep, 0, r_pad - 1) * n

    def pack_step(carry, xs):
        nb, pos = carry
        st_s, ln_s = xs  # (N,) each: this stencil direction's runs
        base = jnp.take(st_s, pair_pt)  # (C,)
        run = jnp.take(ln_s, pair_pt)
        idx = jnp.clip(base[:, None] + lanes[None, :], 0, max(n_grid - 1, 0))
        cand = jnp.take(order, idx)  # (C, L) global point ids
        delta = jnp.take(points, cand, axis=0) - own[:, None, :]
        d2 = jnp.sum(delta * delta, axis=-1)
        q_nb = jnp.take(pair_of, rep_base[:, None] + cand)  # same-rep pair
        hit = ((d2 <= eps2) & (lanes[None, :] < run[:, None])
               & pair_valid[:, None] & (q_nb < sent))
        hpos = pos[:, None] + jnp.cumsum(hit, axis=1) - hit
        nb = nb.at[arange_c[:, None],
                   jnp.where(hit, hpos, neighbor_cap)].set(
            jnp.where(hit, q_nb, sent), mode="drop")
        return (nb, pos + jnp.sum(hit, axis=1, dtype=jnp.int32)), None

    (nb, degree), _ = jax.lax.scan(
        pack_step,
        (jnp.full((c_pad, neighbor_cap), sent, jnp.int32),
         jnp.zeros(c_pad, jnp.int32)),
        (start, length))
    nb_overflow = jnp.any(degree > neighbor_cap)

    core = pair_valid & (degree >= jnp.int32(min_points))
    core_ext = jnp.concatenate([core, jnp.zeros(1, bool)])

    def neighbor_min(labels):
        lab_ext = jnp.concatenate([labels, jnp.full(1, sent, jnp.int32)])
        nblab = jnp.where(jnp.take(core_ext, nb), jnp.take(lab_ext, nb), sent)
        return jnp.min(nblab, axis=1)

    init = jnp.where(core, arange_c, sent)

    def cond(state):
        return state[1]

    def body(state):
        labels, _ = state
        best = jnp.where(core, jnp.minimum(labels, neighbor_min(labels)),
                         labels)
        ext = jnp.concatenate([best, jnp.full(1, sent, jnp.int32)])
        best = jnp.where(core, jnp.minimum(best, jnp.take(ext, best)), best)
        return best, jnp.any(best != labels)

    labels, _ = jax.lax.while_loop(cond, body, (init, jnp.bool_(True)))

    # border pairs: lowest neighboring core cluster of the same rep
    blab = neighbor_min(labels)
    labels = jnp.where(core, labels,
                       jnp.where(pair_valid & (blab < sent), blab, sent))

    # densify per rep: pairs are ordered (rep, point)-ascending, so a rep's
    # roots are contiguous in the global root ranking — local rank = global
    # rank minus the rep's root offset, and the numbering matches the host
    # dispatch (ascending min core point index)
    is_root = core & (labels == arange_c)
    gcum = jnp.cumsum(is_root.astype(jnp.int32))
    root_count = jnp.zeros(r_pad, jnp.int32).at[
        jnp.where(is_root, pair_rep, r_pad)].add(1, mode="drop")
    roots_before = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(root_count)[:-1]])
    grank = jnp.take(gcum, jnp.clip(labels, 0, max(c_pad - 1, 0))) - 1
    dense_local = jnp.where(
        labels < sent,
        grank - jnp.take(roots_before, jnp.clip(pair_rep, 0, r_pad - 1)),
        -1).astype(jnp.int32)
    return dense_local, root_count, nb_overflow


@functools.lru_cache(maxsize=1)
def _pairs_jit():
    """ONE persistent jit of :func:`grid_dbscan_pairs` for the standalone
    dispatch. A per-call ``jax.jit(...)`` wrapper would rebuild its
    executable cache on every invocation — the retrace family's
    RETRACE.STATIC pattern, the measured 48 s/scene bug class
    ``_associate_scene_jit`` documents. jax stays a lazy import: the
    module's host-side half (build_grid) must import without it.
    """
    import jax

    return functools.partial(jax.jit, static_argnames=(
        "r_pad", "cell_cap", "neighbor_cap", "eps", "min_points"))(
        grid_dbscan_pairs)


def grid_dbscan_reference(points, valid_rows, grid: GridStructure, *,
                          neighbor_cap: int, eps: float, min_points: int):
    """Standalone jitted entry over (R, N) validity rows (tests and
    diagnostics); the post-process embeds :func:`grid_dbscan_pairs` in its
    own program with device-side pair compaction instead. Returns (R, N)
    dense labels (-1 noise/invalid)."""
    import jax.numpy as jnp

    valid_rows = np.asarray(valid_rows)
    r_pad, n = valid_rows.shape
    rep, pt = np.nonzero(valid_rows)
    c_pad = _bucket_pow2(max(len(rep), 1), minimum=8)
    pair_rep = np.zeros(c_pad, np.int32)
    pair_pt = np.zeros(c_pad, np.int32)
    pair_valid = np.zeros(c_pad, bool)
    pair_rep[: len(rep)] = rep
    pair_pt[: len(rep)] = pt
    pair_valid[: len(rep)] = True

    dense, _, overflow = _pairs_jit()(
        jnp.asarray(points), jnp.asarray(grid.order),
        jnp.asarray(grid.start), jnp.asarray(grid.length),
        jnp.asarray(pair_rep), jnp.asarray(pair_pt),
        jnp.asarray(pair_valid), r_pad=r_pad, cell_cap=grid.cell_cap,
        neighbor_cap=neighbor_cap, eps=float(eps),
        min_points=int(min_points))
    if bool(overflow):
        raise ValueError(f"neighbor_cap {neighbor_cap} overflowed")
    out = np.full((r_pad, n), -1, np.int32)
    out[rep, pt] = np.asarray(dense)[: len(rep)]
    return out
