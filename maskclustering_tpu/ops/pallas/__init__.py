"""Pallas TPU kernels for the hot custom ops."""
