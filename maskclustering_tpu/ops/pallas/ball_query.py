"""Pallas TPU kernel for fixed-radius first-K neighbor search (ball query).

Replaces the reference's single CUDA kernel dependency —
pytorch3d.ops.ball_query(K=20, radius=0.01, return_nn=False) over padded
ragged batches (reference utils/mask_backprojection.py:27-39,123-128) —
with the identical contract: for each valid query point, the indices of
the FIRST K candidates in ascending index order within the radius, -1
padded; invalid query rows are all -1.

Kernel shape: grid over (batch, query tiles). Each program holds its
query tile and the batch's full candidate array in VMEM and walks the
candidates in tiles, maintaining a running per-row hit count. Within a
candidate tile the output slot of each hit is ``count + cumsum - 1``;
slots are materialized with a one-hot sum (slots are distinct within a
tile, so sum == select), which keeps the inner loop pure VPU math — no
scatter, no sort.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _SMEM = pltpu.SMEM
except Exception:  # pragma: no cover
    pltpu = None
    _SMEM = None


def _kernel(ql_ref, cl_ref, q_ref, c_ref, out_ref, *, k: int, r2: float,
            cand_tile: int, query_tile: int):
    q = q_ref[0]  # (QT, 3)
    bi = pl.program_id(0)
    ql = ql_ref[bi]
    cl = cl_ref[bi]
    s_pad = c_ref.shape[1]
    n_tiles = s_pad // cand_tile

    out0 = jnp.full((query_tile, k), -1, dtype=jnp.int32)
    count0 = jnp.zeros((query_tile,), dtype=jnp.int32)
    tile_iota = jax.lax.broadcasted_iota(jnp.int32, (query_tile, cand_tile), 1)
    # inclusive-prefix-sum matrix: cumsum(hit, axis=1) == hit_f32 @ tri
    # (Mosaic has no cumsum primitive; an MXU matmul is the fast lowering.
    # f32 accumulation is exact for counts << 2^24.)
    tri = (jax.lax.broadcasted_iota(jnp.int32, (cand_tile, cand_tile), 0)
           <= jax.lax.broadcasted_iota(jnp.int32, (cand_tile, cand_tile), 1)
           ).astype(jnp.float32)

    def body(t, carry):
        out, count = carry
        c = c_ref[0, pl.ds(t * cand_tile, cand_tile), :]  # (CT, 3)
        gidx = t * cand_tile + tile_iota  # (QT, CT) global candidate index
        # slice-and-reshape per coordinate: integer indexing (q[:, None, 0])
        # would lower to an unsupported Mosaic gather
        d2 = ((q[:, 0:1] - c[:, 0:1].reshape(1, cand_tile)) ** 2
              + (q[:, 1:2] - c[:, 1:2].reshape(1, cand_tile)) ** 2
              + (q[:, 2:3] - c[:, 2:3].reshape(1, cand_tile)) ** 2)
        hit = (d2 <= r2) & (gidx < cl)
        hit_f = hit.astype(jnp.float32)
        prefix = jnp.dot(hit_f, tri, preferred_element_type=jnp.float32)
        rank = count[:, None] + prefix.astype(jnp.int32) - 1
        take = hit & (rank < k)
        vals = jnp.where(take, gidx + 1, 0)  # 0 = no hit
        # distinct slots per row within a tile -> per-slot sum selects
        # exactly one value; K is small and static, so unroll (no 3-D
        # one-hot: that shape fails the Mosaic lowering)
        cols = [jnp.sum(jnp.where(rank == kk, vals, 0), axis=1,
                        dtype=jnp.int32)[:, None] for kk in range(k)]
        contrib = jnp.concatenate(cols, axis=1)  # (QT, K)
        out = jnp.where(contrib > 0, contrib - 1, out)
        count = count + jnp.sum(hit, axis=1, dtype=jnp.int32)
        return out, count

    out, _ = jax.lax.fori_loop(0, n_tiles, body, (out0, count0))
    qrow = pl.program_id(1) * query_tile + jax.lax.broadcasted_iota(
        jnp.int32, (query_tile, 1), 0)[:, 0]
    out_ref[0] = jnp.where((qrow < ql)[:, None], out, -1)


@functools.partial(
    jax.jit,
    static_argnames=("k", "radius", "query_tile", "cand_tile", "batch_chunk",
                     "interpret"),
)
def ball_query_pallas(
    query: jnp.ndarray,  # (B, P, 3) float32
    candidates: jnp.ndarray,  # (B, S, 3) float32
    query_lengths: jnp.ndarray,  # (B,) int32
    candidate_lengths: jnp.ndarray,  # (B,) int32
    *,
    k: int = 20,
    radius: float = 0.01,
    query_tile: int = 128,
    cand_tile: int = 256,
    batch_chunk: int = 8,
    interpret: bool = False,
) -> jnp.ndarray:
    """pytorch3d-semantics ball query on TPU; returns (B, P, k) int32.

    Batches are processed batch_chunk at a time (lax.map) so the per-call
    output stays well under the 16 MB VMEM scoped-allocation budget — XLA
    stack-allocates a pallas_call's whole output when it fits.
    """
    b, p, _ = query.shape
    s = candidates.shape[1]
    p_pad = -(-p // query_tile) * query_tile
    s_pad = -(-s // cand_tile) * cand_tile
    bc = min(batch_chunk, b) or 1
    b_pad = -(-b // bc) * bc
    query = jnp.pad(query.astype(jnp.float32),
                    ((0, b_pad - b), (0, p_pad - p), (0, 0)))
    candidates = jnp.pad(candidates.astype(jnp.float32),
                         ((0, b_pad - b), (0, s_pad - s), (0, 0)))
    ql = jnp.pad(query_lengths.astype(jnp.int32), (0, b_pad - b))
    cl = jnp.pad(candidate_lengths.astype(jnp.int32), (0, b_pad - b))

    # whole (bc,) length vectors live in SMEM; the kernel indexes by batch id
    len_spec = (pl.BlockSpec(memory_space=_SMEM)
                if _SMEM is not None and not interpret
                else pl.BlockSpec((bc,), lambda bi, qi: (0,)))
    call = pl.pallas_call(
        functools.partial(_kernel, k=k, r2=float(radius) * float(radius),
                          cand_tile=cand_tile, query_tile=query_tile),
        grid=(bc, p_pad // query_tile),
        in_specs=[
            len_spec,
            len_spec,
            pl.BlockSpec((1, query_tile, 3), lambda bi, qi: (bi, qi, 0)),
            pl.BlockSpec((1, s_pad, 3), lambda bi, qi: (bi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, query_tile, k), lambda bi, qi: (bi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bc, p_pad, k), jnp.int32),
        interpret=interpret,
    )

    def group(args):
        return call(*args)

    n_groups = b_pad // bc
    out = jax.lax.map(group, (
        ql.reshape(n_groups, bc),
        cl.reshape(n_groups, bc),
        query.reshape(n_groups, bc, p_pad, 3),
        candidates.reshape(n_groups, bc, s_pad, 3),
    ))
    return out.reshape(b_pad, p_pad, k)[:b, :p]
