"""DBSCAN clustering — host dispatch (native C++ or sklearn) + jittable core.

Two call sites in the pipeline, both off the XLA hot path (reference uses
Open3D's C++ cluster_dbscan at eps 0.04/0.1, utils/geometry.py:10 and
utils/post_process.py:109). `dbscan_labels` dispatches to the native C++
extension (maskclustering_tpu/native) when built, else sklearn.

`dbscan_fixed_jax` is a bounded-iteration, static-shape DBSCAN usable inside
jit for the exact-parity backprojection path where per-mask denoising runs
on-device (SURVEY.md §7.3).
"""

from __future__ import annotations

import numpy as np

try:
    from maskclustering_tpu.native import native_available, native_dbscan

    _HAS_NATIVE = native_available()
except Exception:  # pragma: no cover
    native_dbscan = None
    _HAS_NATIVE = False


def dbscan_labels(points: np.ndarray, eps: float, min_points: int) -> np.ndarray:  # mct-thread: root (dbscan_labels_parallel's pool lambda hides this entry from the AST collector)
    """Standard DBSCAN labels; -1 = noise (Open3D cluster_dbscan contract).

    min_points counts the point itself, matching Open3D and sklearn.
    """
    points = np.ascontiguousarray(np.asarray(points, dtype=np.float64))
    if len(points) == 0:
        return np.zeros(0, dtype=np.int64)
    if _HAS_NATIVE:
        return native_dbscan(points, eps, min_points)
    from sklearn.cluster import DBSCAN

    return DBSCAN(eps=eps, min_samples=min_points).fit(points).labels_.astype(np.int64)


def dbscan_labels_parallel(point_sets, eps: float, min_points: int):
    """dbscan_labels over many point sets, threaded (native call drops the GIL).

    Order-preserving; falls back to a plain loop for 0-1 sets (or when only
    sklearn — which holds the GIL for most of its run — is available, where
    threads would just add overhead).
    """
    point_sets = list(point_sets)
    if len(point_sets) <= 1 or not _HAS_NATIVE:
        return [dbscan_labels(p, eps=eps, min_points=min_points) for p in point_sets]
    import os
    from concurrent.futures import ThreadPoolExecutor

    workers = min(len(point_sets), os.cpu_count() or 4)
    with ThreadPoolExecutor(max_workers=workers) as ex:
        return list(ex.map(
            lambda p: dbscan_labels(p, eps=eps, min_points=min_points), point_sets))


def dbscan_fixed_jax(points, valid, eps: float, min_points: int):
    """Static-shape DBSCAN inside jit: core-point expansion by label propagation.

    points: (P, 3); valid: (P,) bool (padding rows excluded).
    Returns (P,) int32 labels, -1 for noise/padding. Border points attach to
    the lowest-labeled neighboring core cluster (deterministic, unlike
    scan-order-dependent classic DBSCAN — only tie-breaking differs).
    O(P^2) distances — intended for per-mask point sets (P <= a few k).

    Label propagation runs to fixpoint with pointer jumping (one hop + one
    label-of-label per sweep), so chains longer than any fixed iteration
    budget still collapse to a single component.
    """
    import jax
    import jax.numpy as jnp

    p = points.shape[0]
    d2 = jnp.sum((points[:, None, :] - points[None, :, :]) ** 2, axis=-1)
    near = (d2 <= eps * eps) & valid[:, None] & valid[None, :]
    degree = jnp.sum(near, axis=1)  # includes self
    core = (degree >= min_points) & valid

    core_adj = near & core[:, None] & core[None, :]
    init = jnp.where(core, jnp.arange(p, dtype=jnp.int32), p)

    def cond(state):
        return state[1]

    def body(state):
        lab, _ = state
        neigh = jnp.where(core_adj, lab[None, :], p)
        best = jnp.where(core, jnp.minimum(lab, jnp.min(neigh, axis=1)), lab)
        # pointer jumping: label-of-label (padding index p stays p)
        ext = jnp.concatenate([best, jnp.array([p], dtype=jnp.int32)])
        best = jnp.where(core, jnp.minimum(best, ext[best]), best)
        return best, jnp.any(best != lab)

    labels, _ = jax.lax.while_loop(cond, body, (init, jnp.bool_(True)))
    # border points: lowest neighboring core label
    border_lab = jnp.min(jnp.where(near & core[None, :], labels[None, :], p), axis=1)
    labels = jnp.where(core, labels, jnp.where(valid & (border_lab < p), border_lab, p))
    # compact: noise/padding -> -1
    return jnp.where(labels >= p, -1, labels)
