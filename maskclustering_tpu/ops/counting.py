"""Exact boolean/one-hot counting contractions with a selectable MXU dtype.

Nearly every matmul in this pipeline is a *count*: view-consensus rates,
observer counts, per-mask visible/claim statistics, AP intersections — all
contractions of {0, 1} (occasionally {0, 1, 2}) operands whose results are
small integers. Historically those ran as bf16 operands with f32
accumulation — bit-exact for 0/1 data up to 2^24 — because bf16 is the
MXU's native fast path. On v5e the systolic array also runs s8 x s8 -> s32
at 2x the bf16 rate with HALF the operand HBM traffic, and integer
accumulation is exact to 2^31, so the same contractions can be dispatched
as int8 with no tolerance games at all.

This module is the single dispatch point: every counting site in
models/graph.py, models/clustering.py, models/backprojection.py,
models/postprocess_device.py and evaluation/ap.py routes through
``count_dot`` / ``count_dot_general`` / ``count_onehot``, selected by
``cfg.count_dtype in {"bf16", "int8"}``. Both paths produce IDENTICAL
results (pinned by tests/test_counting.py and the artifact byte-identity
tests): the operands are exact small integers in either encoding, and the
accumulator (f32 below 2^24, s32 below 2^31) never rounds.

What may NOT route through here: contractions with a real-valued operand
(CLIP feature pooling, geometry transforms) or with integer operands that
exceed the operand dtype's range — see ARCHITECTURE.md "Integer counting
dtype policy" for the per-site audit. Small multi-valued operands (the
postprocess claim-correction matrix holds {0, 1, 2}) are fine: both bf16
and int8 represent them exactly.

Sharded contraction dims (the point-axis mesh, parallel/mesh.py): when a
caller's contraction dimension is sharded — the graph co-occurrence and
node-stats counts contract over the point-sharded N — XLA partitions the
dot into per-shard partials accumulated in the SAME exact dtype this
module selects (f32 or s32), then psums over the axis. Exactness is what
makes that safe under BOTH encodings: integer summands in an associative
accumulator mean shard order cannot change a byte, so the byte-identity
contract extends to any shard count without a per-site audit
(tests/test_point_sharding.py pins it end-to-end).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# the two supported operand encodings for counting contractions; config.py
# validates against this tuple so a typo fails at construction, not in jit
COUNT_DTYPES = ("bf16", "int8")

# operand encoding -> (operand dtype, accumulator dtype the MXU natively
# pairs with it: f32 for bf16 inputs, s32 for s8 inputs)
_DTYPE_MAP = {
    "bf16": (jnp.bfloat16, jnp.float32),
    "int8": (jnp.int8, jnp.int32),
}


def operand_dtype(count_dtype: str):
    """The jnp dtype counting operands are cast to under ``count_dtype``."""
    return _dtypes(count_dtype)[0]


def accumulator_dtype(count_dtype: str):
    """The exact accumulator dtype paired with ``count_dtype`` operands."""
    return _dtypes(count_dtype)[1]


def _dtypes(count_dtype: str):
    try:
        return _DTYPE_MAP[count_dtype]
    except KeyError:
        raise ValueError(
            f"unknown count_dtype {count_dtype!r}; valid: {COUNT_DTYPES}"
        ) from None


def count_dot(a, b, *, count_dtype: str = "bf16", out_dtype=jnp.float32):
    """``a @ b`` for 0/1-valued operands, exact under either encoding.

    Operands are cast to the counting operand dtype (bf16 or int8) and
    contracted with the paired exact accumulator
    (``preferred_element_type``); the result is cast to ``out_dtype``
    (f32 by default — an exact conversion for any count below 2^24, which
    keeps every downstream ratio/threshold comparison byte-identical
    between the two encodings). Pass ``out_dtype=None`` to keep the raw
    accumulator dtype.
    """
    od, acc = _dtypes(count_dtype)
    out = jnp.dot(a.astype(od), b.astype(od), preferred_element_type=acc)
    return out if out_dtype is None else out.astype(out_dtype)


def count_dot_general(a, b, dimension_numbers, *, count_dtype: str = "bf16",
                      out_dtype=jnp.float32):
    """``lax.dot_general`` form of :func:`count_dot` (batch/multi-dim
    contractions, e.g. the postprocess node-stats frame-chunk scan)."""
    od, acc = _dtypes(count_dtype)
    out = jax.lax.dot_general(a.astype(od), b.astype(od), dimension_numbers,
                              preferred_element_type=acc)
    return out if out_dtype is None else out.astype(out_dtype)


def count_onehot(ids, num: int, *, count_dtype: str = "bf16", axis: int = -1):
    """``jax.nn.one_hot`` in the counting operand dtype.

    One-hot matrices built here feed straight into ``count_dot*`` without
    a re-cast; out-of-range ids (negative sentinels, padded slots) produce
    all-zero rows exactly as with the float encodings.
    """
    return jax.nn.one_hot(ids, num, axis=axis, dtype=operand_dtype(count_dtype))
