"""Ground-truth instance encoding and grouping.

The GT contract follows the ScanNet benchmark (reference evaluation/utils_3d.py:11-65):
a per-vertex integer file where ``instance_id = label_id * 1000 + inst + 1`` and
0 means unannotated. Instances are grouped per class label; ids whose label is
outside the benchmark vocabulary are "void" and ignored by the matcher.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import numpy as np


def load_gt_ids(path: str) -> np.ndarray:
    """Load a per-vertex GT id file (one integer per line)."""
    return np.loadtxt(path, dtype=np.int64)


@dataclasses.dataclass
class GTInstance:
    """One ground-truth instance (reference utils_3d.py:11-41)."""

    instance_id: int
    label_id: int
    vert_count: int
    med_dist: float = -1.0
    dist_conf: float = 0.0

    @classmethod
    def from_ids(cls, gt_ids: np.ndarray, instance_id: int) -> "GTInstance":
        return cls(
            instance_id=int(instance_id),
            label_id=int(instance_id // 1000),
            vert_count=int((gt_ids == instance_id).sum()),
        )


def group_instances(
    gt_ids: np.ndarray,
    valid_ids: Sequence[int],
    labels: Sequence[str],
    id_to_label: Dict[int, str],
) -> Dict[str, List[GTInstance]]:
    """Group GT instances by class label (reference utils_3d.py:54-65).

    id 0 (unannotated) is skipped; ids with out-of-vocabulary labels are
    dropped here and counted as void by the matcher.
    """
    valid = set(int(v) for v in valid_ids)
    grouped: Dict[str, List[GTInstance]] = {label: [] for label in labels}
    for iid in np.unique(gt_ids):
        if iid == 0:
            continue
        inst = GTInstance.from_ids(gt_ids, int(iid))
        if inst.label_id in valid:
            grouped[id_to_label[inst.label_id]].append(inst)
    return grouped
