"""ScanNet-benchmark average-precision evaluation.

Protocol parity with reference evaluation/evaluate.py: AP averaged over IoU
thresholds 0.5:0.05:0.95 plus AP50/AP25 (evaluate.py:44, 207-224), minimum
region size 100 vertices (evaluate.py:46), greedy confidence-ordered gt<->pred
matching with void/group/small-instance ignore rules (evaluate.py:53-205), and
the same convolution-based precision-recall integration (evaluate.py:192-198).

TPU-first difference: the reference computes one GPU matmul per prediction
mask against the same-label GT tensor (evaluate.py:313-314). Here ALL
pred x gt intersections for a scan are one counting matmul
(ops/counting.py — bf16+f32 or, under ``count_dtype="int8"``, the MXU's
double-rate s8+s32 path; both exact for the 0/1 mask operands) of
(N_pts, P)^T @ (N_pts, G), plus a matvec for void intersections; only the
small (P, G) count matrix crosses back to host for the greedy pass.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from maskclustering_tpu.evaluation.instances import GTInstance, group_instances, load_gt_ids
from maskclustering_tpu.ops import counting
from maskclustering_tpu.semantics.vocab import get_vocab

# IoU thresholds: 0.50..0.90 step 0.05, then 0.25 (reference evaluate.py:44).
DEFAULT_OVERLAPS: np.ndarray = np.append(np.arange(0.5, 0.95, 0.05), 0.25)
# Minimum instance size in vertices (reference evaluate.py:46).
MIN_REGION_SIZE: int = 100


def _intersection_counts(pred_masks: jnp.ndarray, gt_onehot: jnp.ndarray,
                         void_mask: jnp.ndarray, count_dtype: str = "bf16"):
    """(P, G) intersection counts + (P,) void intersections, one MXU pass.

    A counting contraction of 0/1 masks (ops/counting.py), kept in the
    encoding's RAW accumulator (``out_dtype=None``): the int8 path's s32
    counts convert to int32 losslessly and are exact to 2^31 vertices,
    the bf16 path's f32 counts round-trip through rint exactly below 2^24
    — identical int32 counts wherever both are exact. Deliberately NOT
    jitted: every scan has a unique (N_pts, P, G) shape, so a jit wrapper
    would recompile per scan and cost more than the two matmuls it wraps.
    """
    def to_i32(x):
        return (x.astype(jnp.int32) if jnp.issubdtype(x.dtype, jnp.integer)
                else jnp.rint(x).astype(jnp.int32))

    inter = to_i32(counting.count_dot(
        pred_masks.T, gt_onehot, count_dtype=count_dtype, out_dtype=None))
    void = to_i32(counting.count_dot(
        pred_masks.T, void_mask, count_dtype=count_dtype, out_dtype=None))
    return inter, void


class _Pred:
    """One retained prediction and its GT overlap records."""

    __slots__ = ("uid", "label_id", "vert_count", "confidence",
                 "void_intersection", "matched_gt")

    def __init__(self, uid, label_id, vert_count, confidence, void_intersection):
        self.uid = uid
        self.label_id = label_id
        self.vert_count = vert_count
        self.confidence = confidence
        self.void_intersection = void_intersection
        self.matched_gt: List[Tuple[GTInstance, int]] = []  # (gt, intersection)


class _GTRecord:
    """One GT instance and the predictions that touch it."""

    __slots__ = ("inst", "matched_pred")

    def __init__(self, inst: GTInstance):
        self.inst = inst
        self.matched_pred: List[Tuple[_Pred, int]] = []  # (pred, intersection)


def assign_instances_for_scan(
    pred_masks: np.ndarray,  # (N_pts, P) -- nonzero = member
    pred_scores: np.ndarray,  # (P,)
    pred_classes: np.ndarray,  # (P,)
    gt_ids: np.ndarray,  # (N_pts,)
    labels: Sequence[str],
    valid_ids: Sequence[int],
    *,
    no_class: bool = False,
    scan_key: str = "scan",
    min_region_size: int = MIN_REGION_SIZE,
    count_dtype: str = "bf16",
) -> Tuple[Dict[str, List[_GTRecord]], Dict[str, List[_Pred]]]:
    """Match one scan's predictions to GT (reference evaluate.py:254-329).

    Returns (gt2pred, pred2gt), both keyed by class label.
    """
    id_to_label = {int(v): l for v, l in zip(valid_ids, labels)}
    if no_class:
        # collapse every annotated vertex onto the first valid class
        # (reference evaluate.py:261-262, 282-283)
        gt_ids = gt_ids % 1000 + int(valid_ids[0]) * 1000

    gt_instances = group_instances(gt_ids, valid_ids, labels, id_to_label)
    gt2pred: Dict[str, List[_GTRecord]] = {
        label: [_GTRecord(inst) for inst in insts]
        for label, insts in gt_instances.items()
    }
    pred2gt: Dict[str, List[_Pred]] = {label: [] for label in labels}

    # flatten GT instances into one one-hot tensor; columns are grouped by
    # label, so each label owns a contiguous [start, stop) column range
    columns: List[np.ndarray] = []
    label_cols: Dict[str, Tuple[int, int]] = {}
    for label in labels:
        start = len(columns)
        for rec in gt2pred[label]:
            columns.append(gt_ids == rec.inst.instance_id)
        label_cols[label] = (start, len(columns))
    gt_onehot = (np.stack(columns, axis=1) if columns
                 else np.zeros((len(gt_ids), 0), dtype=bool))
    void = ~np.isin(gt_ids // 1000, np.asarray(valid_ids))

    masks_bool = np.not_equal(pred_masks, 0)
    if pred_masks.shape[0] != len(gt_ids):
        raise ValueError(
            f"{scan_key}: prediction has {pred_masks.shape[0]} vertices "
            f"but GT has {len(gt_ids)}")
    inter, void_inter = _intersection_counts(
        jnp.asarray(masks_bool), jnp.asarray(gt_onehot), jnp.asarray(void),
        count_dtype=count_dtype)
    inter = np.asarray(inter)
    void_inter = np.asarray(void_inter)
    vert_counts = masks_bool.sum(axis=0)

    for i in range(masks_bool.shape[1]):
        label_id = int(valid_ids[0]) if no_class else int(pred_classes[i])
        if label_id not in id_to_label:
            continue
        if vert_counts[i] < min_region_size:
            continue  # too small to evaluate (evaluate.py:300-301)
        label = id_to_label[label_id]
        pred = _Pred(
            uid=f"{scan_key}_{i}",
            label_id=label_id,
            vert_count=int(vert_counts[i]),
            confidence=float(pred_scores[i]),
            void_intersection=int(void_inter[i]),
        )
        # same-label GT overlaps only (evaluate.py:313-323); the label's
        # columns are contiguous, so only its nonzero entries are visited
        start, stop = label_cols[label]
        for j in np.nonzero(inter[i, start:stop])[0]:
            n = int(inter[i, start + j])
            pred.matched_gt.append((gt2pred[label][j].inst, n))
            gt2pred[label][j].matched_pred.append((pred, n))
        pred2gt[label].append(pred)
    return gt2pred, pred2gt


def _average_precision(y_true: np.ndarray, y_score: np.ndarray,
                       hard_false_negatives: int) -> float:
    """AP from matched samples (reference evaluate.py:156-198, vectorized).

    Precision/recall are evaluated at each unique confidence cutoff, then
    integrated with the [-0.5, 0, 0.5] convolution step rule.
    """
    order = np.argsort(y_score)
    ys, yt = y_score[order], y_true[order]
    cum = np.cumsum(yt)
    _, first_idx = np.unique(ys, return_index=True)
    num_examples = len(ys)
    num_true = cum[-1]
    # matches with score strictly below each cutoff (0 at the lowest cutoff)
    below = np.where(first_idx > 0, cum[first_idx - 1], 0.0)
    tp = num_true - below
    fp = num_examples - first_idx - tp
    fn = below + hard_false_negatives
    precision = np.append(tp / (tp + fp), 1.0)  # final point is artificial
    recall = np.append(tp / (tp + fn), 0.0)
    r = np.concatenate([recall[:1], recall, [0.0]])
    step_widths = np.convolve(r, [-0.5, 0, 0.5], "valid")
    return float(np.dot(precision, step_widths))


def evaluate_matches(
    matches: Dict[str, Dict[str, Dict[str, list]]],
    labels: Sequence[str],
    *,
    overlaps: np.ndarray = DEFAULT_OVERLAPS,
    min_region_size: int = MIN_REGION_SIZE,
) -> np.ndarray:
    """Greedy AP per (class, overlap) over all scans (evaluate.py:53-205).

    ``matches[scan] = {"gt": gt2pred, "pred": pred2gt}``. Returns
    (len(labels), len(overlaps)) float array; NaN marks classes with no GT
    and no predictions.
    """
    ap = np.zeros((len(labels), len(overlaps)), dtype=float)
    for oi, overlap_th in enumerate(overlaps):
        visited: Dict[str, bool] = {}
        for scan in matches.values():
            for preds in scan["pred"].values():
                for p in preds:
                    visited[p.uid] = False
        for li, label in enumerate(labels):
            y_true_parts: List[np.ndarray] = []
            y_score_parts: List[np.ndarray] = []
            hard_false_negatives = 0
            has_gt = False
            has_pred = False
            for scan in matches.values():
                pred_instances: List[_Pred] = scan["pred"][label]
                gt_records: List[_GTRecord] = [
                    r for r in scan["gt"][label]
                    if r.inst.instance_id >= 1000
                    and r.inst.vert_count >= min_region_size
                ]
                has_gt = has_gt or bool(gt_records)
                has_pred = has_pred or bool(pred_instances)

                cur_true = [1.0] * len(gt_records)
                cur_score = [-np.inf] * len(gt_records)
                cur_match = [False] * len(gt_records)
                for gi, rec in enumerate(gt_records):
                    found_match = False
                    for pred, inter in rec.matched_pred:
                        if visited[pred.uid]:
                            continue  # greedy: each pred matches one GT
                        union = rec.inst.vert_count + pred.vert_count - inter
                        if inter / union <= overlap_th:
                            continue
                        if cur_match[gi]:
                            # duplicate detection: lower-confidence one
                            # becomes a false positive (evaluate.py:100-109)
                            lo = min(cur_score[gi], pred.confidence)
                            cur_score[gi] = max(cur_score[gi], pred.confidence)
                            cur_true.append(0.0)
                            cur_score.append(lo)
                            cur_match.append(True)
                        else:
                            found_match = True
                            cur_match[gi] = True
                            cur_score[gi] = pred.confidence
                            visited[pred.uid] = True
                    if not found_match:
                        hard_false_negatives += 1
                matched = np.asarray(cur_match, dtype=bool)
                y_true_parts.append(np.asarray(cur_true)[matched])
                y_score_parts.append(np.asarray(cur_score)[matched])

                # unmatched predictions: false positives unless mostly
                # covering ignored regions (evaluate.py:124-146)
                for pred in pred_instances:
                    matched_any = any(
                        inter / (gt.vert_count + pred.vert_count - inter) > overlap_th
                        for gt, inter in pred.matched_gt)
                    if matched_any:
                        continue
                    num_ignore = pred.void_intersection
                    for gt, inter in pred.matched_gt:
                        if gt.instance_id < 1000:  # annotation group
                            num_ignore += inter
                        if gt.vert_count < min_region_size:
                            num_ignore += inter
                    if num_ignore / pred.vert_count <= overlap_th:
                        y_true_parts.append(np.zeros(1))
                        y_score_parts.append(np.full(1, pred.confidence))

            if has_gt and has_pred:
                y_true = np.concatenate(y_true_parts) if y_true_parts else np.empty(0)
                y_score = np.concatenate(y_score_parts) if y_score_parts else np.empty(0)
                ap[li, oi] = (0.0 if len(y_score) == 0 else
                              _average_precision(y_true, y_score, hard_false_negatives))
            elif has_gt:
                ap[li, oi] = 0.0
            else:
                ap[li, oi] = np.nan
    return ap


def compute_averages(aps: np.ndarray, labels: Sequence[str],
                     overlaps: np.ndarray = DEFAULT_OVERLAPS) -> Dict:
    """AP / AP50 / AP25 summaries (reference evaluate.py:207-224)."""
    import warnings

    o50 = np.isclose(overlaps, 0.5)
    o25 = np.isclose(overlaps, 0.25)
    not25 = ~o25
    with warnings.catch_warnings():
        # all-NaN when no class has GT or predictions; NaN result is correct
        warnings.simplefilter("ignore", category=RuntimeWarning)
        out = {
            "all_ap": float(np.nanmean(aps[:, not25])),
            "all_ap_50%": float(np.nanmean(aps[:, o50])),
            "all_ap_25%": float(np.nanmean(aps[:, o25])),
            "classes": {},
        }
    for li, label in enumerate(labels):
        out["classes"][label] = {
            "ap": float(np.average(aps[li, not25])),
            "ap50%": float(np.average(aps[li, o50])),
            "ap25%": float(np.average(aps[li, o25])),
        }
    return out


def format_results(avgs: Dict, labels: Sequence[str]) -> str:
    """Console AP table (reference evaluate.py:331-368)."""
    width = 64
    lines = ["#" * width,
             "{:<15}:{:>15}{:>15}{:>15}".format("what", "AP", "AP_50%", "AP_25%"),
             "#" * width]
    for label in labels:
        c = avgs["classes"][label]
        if np.isnan(c["ap"]):
            continue
        lines.append("{:<15}:{:>15.3f}{:>15.3f}{:>15.3f}".format(
            label, c["ap"], c["ap50%"], c["ap25%"]))
    lines.append("-" * width)
    lines.append("{:<15}:{:>15.3f}{:>15.3f}{:>15.3f}".format(
        "average", avgs["all_ap"], avgs["all_ap_50%"], avgs["all_ap_25%"]))
    return "\n".join(lines)


def write_result_file(avgs: Dict, labels: Sequence[str], valid_ids: Sequence[int],
                      path: str) -> None:
    """CSV-ish result file (reference evaluate.py:370-381)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write("class,class id,ap,ap50,ap25\n")
        for label, vid in zip(labels, valid_ids):
            c = avgs["classes"][label]
            f.write(f"{label},{vid},{c['ap']},{c['ap50%']},{c['ap25%']}\n")
        f.write(f"{avgs['all_ap']},{avgs['all_ap_50%']},{avgs['all_ap_25%']}\n")


def _load_prediction_npz(path: str):
    pred = np.load(path)
    return pred["pred_masks"], pred["pred_score"], pred["pred_classes"]


def evaluate_scans(
    pred_files: Sequence[str],
    gt_files: Sequence[str],
    dataset: str,
    *,
    no_class: bool = False,
    output_file: Optional[str] = None,
    verbose: bool = True,
    count_dtype: str = "bf16",
) -> Dict:
    """Evaluate npz predictions against GT txt files (evaluate.py:383-400)."""
    labels, valid_ids = get_vocab(dataset)
    matches = {}
    for pred_file, gt_file in zip(pred_files, gt_files):
        masks, scores, classes = _load_prediction_npz(pred_file)
        gt_ids = load_gt_ids(gt_file)
        gt2pred, pred2gt = assign_instances_for_scan(
            masks, scores, classes, gt_ids, labels, valid_ids,
            no_class=no_class, scan_key=os.path.basename(pred_file),
            count_dtype=count_dtype)
        matches[os.path.abspath(gt_file)] = {"gt": gt2pred, "pred": pred2gt}
    aps = evaluate_matches(matches, labels)
    avgs = compute_averages(aps, labels)
    if verbose:
        print(format_results(avgs, labels))
    if output_file:
        write_result_file(avgs, labels, valid_ids, output_file)
    return avgs
