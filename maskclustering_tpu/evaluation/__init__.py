"""ScanNet-benchmark AP evaluation (reference evaluation/ layer, L5)."""

from maskclustering_tpu.evaluation.instances import (
    GTInstance,
    group_instances,
    load_gt_ids,
)
from maskclustering_tpu.evaluation.ap import (
    DEFAULT_OVERLAPS,
    MIN_REGION_SIZE,
    assign_instances_for_scan,
    compute_averages,
    evaluate_matches,
    evaluate_scans,
    format_results,
    write_result_file,
)

__all__ = [
    "GTInstance",
    "group_instances",
    "load_gt_ids",
    "DEFAULT_OVERLAPS",
    "MIN_REGION_SIZE",
    "assign_instances_for_scan",
    "compute_averages",
    "evaluate_matches",
    "evaluate_scans",
    "format_results",
    "write_result_file",
]
