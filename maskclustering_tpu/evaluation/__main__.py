"""CLI: ``python -m maskclustering_tpu.evaluation`` (reference evaluate.py:7-13 CLI).

Evaluates a directory of prediction npz files against GT txt files and writes
``data/evaluation/<dataset>/<config>[_class_agnostic].txt``.
"""

from __future__ import annotations

import argparse
import os
import sys

from maskclustering_tpu.evaluation.ap import evaluate_scans
from maskclustering_tpu.ops.counting import COUNT_DTYPES


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="maskclustering_tpu.evaluation",
        description="ScanNet-protocol AP evaluation")
    parser.add_argument("--pred_path", required=True,
                        help="directory of predicted .npz files")
    parser.add_argument("--gt_path", required=True,
                        help="directory of ground-truth .txt files")
    parser.add_argument("--dataset", required=True,
                        help="dataset vocabulary: scannet | matterport3d | scannetpp")
    parser.add_argument("--output_file", default="",
                        help="result txt path (default: data/evaluation/<dataset>/<pred dirname>.txt)")
    parser.add_argument("--no_class", action="store_true",
                        help="class-agnostic evaluation")
    parser.add_argument("--count_dtype", default="bf16",
                        choices=COUNT_DTYPES,
                        help="operand encoding of the intersection matmuls "
                             "(ops/counting.py; identical counts either way)")
    args = parser.parse_args(argv)

    output_file = args.output_file
    if not output_file:
        output_file = os.path.join(
            "data", "evaluation", args.dataset,
            os.path.basename(os.path.normpath(args.pred_path)) + ".txt")
    if args.no_class and "class_agnostic" not in output_file:
        root, ext = os.path.splitext(output_file)
        output_file = f"{root}_class_agnostic{ext or '.txt'}"

    pred_names = sorted(
        f for f in os.listdir(args.pred_path)
        if f.endswith(".npz") and not f.startswith("semantic_instance_evaluation"))
    pred_files, gt_files = [], []
    for name in pred_names:
        gt_file = os.path.join(args.gt_path, name.replace(".npz", ".txt"))
        if not os.path.isfile(gt_file):
            print(f"prediction {name} has no matching GT file {gt_file}",
                  file=sys.stderr)
            return 1
        pred_files.append(os.path.join(args.pred_path, name))
        gt_files.append(gt_file)

    evaluate_scans(pred_files, gt_files, args.dataset,
                   no_class=args.no_class, output_file=output_file,
                   count_dtype=args.count_dtype)
    print(f"saved results to {output_file}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
