"""Persistent XLA compilation cache + shape-bucket accounting.

The pipeline jits a small family of programs keyed by static shape buckets
(k_max from models/pipeline.bucket_k_max, F padded to cfg.frame_pad_multiple,
N padded to cfg.point_chunk, M padded to cfg.mask_pad_multiple). Warm-up
compilation of the association scan is the single largest fixed cost
(~100 s on a v5e chip at ScanNet scale), so:

- `setup_compilation_cache` points JAX's persistent cache at a durable
  directory: the second process-level run of the same config compiles
  nothing (the reference has no analog — torch re-JITs nothing but pays
  eager kernel-launch overhead every run instead);
- `record_shape_bucket` counts distinct buckets per process so a run can
  assert bucket reuse (tests/test_compile_cache.py) and the log shows
  exactly which shapes triggered compilation.
"""

from __future__ import annotations

import logging
import math
import os
from typing import Optional, Set, Tuple

log = logging.getLogger("maskclustering_tpu")

_CACHE_APPLIED: Optional[str] = None
_CACHE_MIN_S: Optional[float] = None
_SEEN_BUCKETS: Set[Tuple] = set()


def default_cache_dir() -> str:
    return os.environ.get(
        "MCT_COMPILE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "maskclustering_tpu", "xla"))


def setup_compilation_cache(cache_dir: Optional[str] = None, *,
                            min_compile_time_s: Optional[float] = None
                            ) -> Optional[str]:
    """Enable JAX's persistent compilation cache (idempotent).

    cache_dir: explicit directory, None for the default, "" to disable.
    ``min_compile_time_s``: the persistence floor — None keeps the 1 s
    default (sub-second CPU test compiles cost more to serialize than to
    redo); the AOT cache (utils/aot_cache.py) lowers it to 0 so EVERY
    serving executable persists, which is what the zero-compile
    cross-process warm start stands on. Returns the directory in effect
    (or None when disabled).
    """
    global _CACHE_APPLIED, _CACHE_MIN_S
    if cache_dir == "":
        return None
    path = os.path.expanduser(cache_dir or default_cache_dir())
    min_s = 1.0 if min_compile_time_s is None else float(min_compile_time_s)
    if _CACHE_APPLIED == path and _CACHE_MIN_S == min_s:
        return path
    os.makedirs(path, exist_ok=True)

    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", min_s)
    _CACHE_APPLIED = path
    _CACHE_MIN_S = min_s
    log.info("persistent compilation cache at %s (floor %.3gs)", path, min_s)
    return path


def bucket_size(value: int, multiple: int) -> int:
    """Geometric shape bucket: the multiple count is rounded up to two
    significant bits (2^k or 3*2^(k-1)).

    Linear rounding gives one jit bucket per `multiple` of size variance —
    ScanNet clouds span ~80k-400k points and mask tables ~2k-16k masks,
    which would mean dozens of compiles. Two-significant-bit steps waste
    <= 33% padded work per bucketed DIMENSION (so up to ~78% on the
    (M_pad, M_pad)-shaped graph/clustering matrices, which square it) and
    bound the bucket count to ~2 per octave of size range. Lives here
    because bounding distinct jit shapes IS the compile
    cache's hit rate; every padded dimension (F, N, M) must go through it.
    """
    m = max(1, -(-value // multiple))
    bit = max(m.bit_length() - 2, 0)
    m = -(-m >> bit) << bit
    return m * multiple


def scene_pads(cfg, frames: int, points: int) -> Tuple[int, int]:
    """(f_pad, n_pad) of a scene under ``cfg``'s padding multiples.

    ``point_shards`` joins the N multiple (lcm with the point chunk) so
    the ONE bucket vocabulary — serving router, retrace census, this
    classifier — always yields pads every point shard can hold an equal
    slice of. Power-of-two shard counts divide the 8192 default chunk,
    so the historical pads are unchanged there.
    """
    n_mult = math.lcm(max(cfg.point_chunk, 1),
                      max(getattr(cfg, "point_shards", 1), 1))
    return (bucket_size(frames, max(cfg.frame_pad_multiple, 1)),
            bucket_size(points, n_mult))


def scene_bucket(cfg, frames: int, points: int, max_id: int) -> Tuple[int, int, int]:
    """The scene-level compile-cache key: (k_max, f_pad, n_pad).

    THE classifier — ``run_scene_device`` routes every scene through the
    same ``scene_pads``/``bucket_k_max`` helpers before dispatch, and the
    retrace family's compile-surface census (analysis/retrace.py)
    enumerates executables with this composition, so "bucket" means one
    thing across serving, the static gate and the runtime sanitizer.
    ``max_id`` is the scene's largest segmentation id.
    """
    from maskclustering_tpu.models.pipeline import bucket_k_max

    return (bucket_k_max(max_id), *scene_pads(cfg, frames, points))


def max_seg_id(segmentations) -> int:
    """Largest mask id in a scene's id-maps (0 for an empty stack) — the
    third ``scene_bucket`` coordinate, shared by the pipeline's k_max
    derivation and the serving router's classification."""
    import numpy as np

    return int(np.max(segmentations)) if np.size(segmentations) else 0


def scene_bucket_of(cfg, tensors) -> Tuple[int, int, int]:
    """``scene_bucket`` read off a SceneTensors (datasets/base.py)."""
    return scene_bucket(cfg, tensors.num_frames, tensors.num_points,
                        max_seg_id(tensors.segmentations))


def record_shape_bucket(kind: str, *bucket) -> bool:
    """Record a jit shape bucket; returns True (and logs) if new.

    Doubles as the compile-cache hit-rate metric: a repeat bucket is a
    guaranteed in-process jit-cache hit, a new one is (at best) a
    persistent-cache deserialize and (at worst) a fresh compile. The
    retrace sanitizer (analysis/retrace_sanitizer.py) is told about new
    buckets so its digest can read "N compiles against M new buckets" —
    a warm serve-many process reads 0/0.
    """
    from maskclustering_tpu import obs
    from maskclustering_tpu.analysis import retrace_sanitizer

    key = (kind, *bucket)
    if key in _SEEN_BUCKETS:
        obs.count("compile_cache.bucket_hit")
        retrace_sanitizer.note_bucket(False)
        return False
    _SEEN_BUCKETS.add(key)
    obs.count("compile_cache.bucket_new")
    obs.gauge("compile_cache.distinct_buckets", len(_SEEN_BUCKETS))
    retrace_sanitizer.note_bucket(True)
    log.info("new %s shape bucket: %s", kind, bucket)
    return True


def seen_shape_buckets() -> Set[Tuple]:
    return set(_SEEN_BUCKETS)


def seen_scene_buckets() -> Set[Tuple]:
    """Just the scene-kind (k_max, f_pad, n_pad) buckets — the serving
    vocabulary this process has compiled against (serve/worker.py diffs
    it per request to report cold dispatches)."""
    return {key[1:] for key in _SEEN_BUCKETS if key[0] == "scene"}


def reset_shape_buckets() -> None:
    _SEEN_BUCKETS.clear()
