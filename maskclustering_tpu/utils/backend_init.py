"""Watchdog-guarded JAX backend initialization.

A wedged TPU client hangs inside backend init with no exception (seen when
another process holds the chip), so a timer thread turns a silent
multi-minute stall into a loud exit. Shared by bench.py and
scripts/northstar.py so the timeout semantics (and the exit-code-3
convention their supervisors/drivers key on) cannot silently diverge.

The watchdog is a Python thread: it CANNOT fire if native init wedges while
holding the GIL — a supervising parent process with a hard kill (bench.py's
supervisor) is the only complete backstop for that case.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Callable, Optional

INIT_TIMEOUT_EXIT_CODE = 3  # retryable "backend never came up" convention


def init_backend(platform: Optional[str] = None, timeout_s: float = 120.0,
                 on_timeout: Optional[Callable[[], None]] = None,
                 tag: str = "backend", logger=None):
    """Import jax and touch devices under a watchdog; returns the devices.

    ``platform``: force a jax platform (must go through jax.config — this
    image preloads the TPU plugin via sitecustomize, so the JAX_PLATFORMS
    env var is read too early to matter). ``on_timeout`` runs in the
    watchdog thread right before ``os._exit(3)`` (e.g. emit a JSON line).
    ``logger``: a logging.Logger to route messages through (callers with a
    configured logging setup, e.g. run.py); default is raw stderr prints.
    Exceptions from init propagate to the caller.
    """
    def _info(msg):
        if logger is not None:
            logger.info(msg)
        else:
            print(f"[{tag}] {msg}", file=sys.stderr, flush=True)

    def _fatal(msg):
        if logger is not None:
            logger.fatal(msg)
        else:
            print(f"[{tag}] FATAL: {msg}", file=sys.stderr, flush=True)

    def _watchdog():
        _fatal(f"backend init did not finish within {timeout_s}s "
               "(backend busy or runtime wedged)")
        if on_timeout is not None:
            on_timeout()
        os._exit(INIT_TIMEOUT_EXIT_CODE)

    timer = threading.Timer(timeout_s, _watchdog)
    timer.daemon = True
    timer.start()
    try:
        import jax

        if platform:
            jax.config.update("jax_platforms", platform)
        devices = jax.devices()
    finally:
        timer.cancel()
    _info(f"backend up: {len(devices)}x {devices[0].device_kind}")
    return devices


def _main(argv=None) -> int:
    """Health probe CLI: ``python -m maskclustering_tpu.utils.backend_init``.

    Exit 0 = backend up (one line on stdout), exit 3 = init timed out
    (the watchdog's os._exit), exit 2 = init raised. chip_session.sh's
    wait-for-healthy preflight loops on this probe so a capture session
    arms itself and fires the moment a healthy window opens, instead of
    failing fast into a wedged chip.
    """
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m maskclustering_tpu.utils.backend_init",
        description="probe jax backend health under a watchdog")
    p.add_argument("--timeout", type=float, default=60.0,
                   help="seconds before a hung init exits 3 (60 cleanly "
                        "separates 'no usable chip' from a healthy init)")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu) before init")
    args = p.parse_args(argv)
    try:
        devices = init_backend(args.platform, timeout_s=args.timeout,
                               tag="probe")
    except Exception as e:  # noqa: BLE001 — one-line diagnosis, nonzero exit
        print(f"[probe] backend init failed: {type(e).__name__}: "
              f"{str(e).splitlines()[0] if str(e) else e}",
              file=sys.stderr, flush=True)
        return 2
    print(f"healthy: {len(devices)}x {devices[0].device_kind}")
    return 0


if __name__ == "__main__":
    sys.exit(_main())
