"""One-shot future on a daemon thread.

A daemon thread — unlike a ThreadPoolExecutor worker, which the
interpreter joins at exit — can never stall process shutdown on an
abandoned blocking call, e.g. a scene load mid-Ctrl-C (run.py's
prefetcher). Note: NOT for device->host pulls — ``np.asarray`` on a
device array holds the GIL for the transfer on this backend, so a
threaded pull serializes host compute instead of overlapping it; use
``jax.Array.copy_to_host_async()`` for that (see PROFILE.md, round 5).
The result or the raised error is re-raised in ``result()`` so failures
attribute to the consuming stage.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class DaemonFuture:
    """Run ``fn`` on a daemon thread; ``result()`` blocks and re-raises."""

    def __init__(self, fn: Callable, name: str = "daemon-future"):
        self._done = threading.Event()
        self._value = None
        self._exc: Optional[BaseException] = None

        def work():
            try:
                self._value = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised in result()
                self._exc = e
            finally:
                self._done.set()

        threading.Thread(target=work, daemon=True, name=name).start()

    def result(self, timeout: Optional[float] = None):
        """Block for the value (re-raising the worker's error).

        ``timeout`` (seconds) raises ``TimeoutError`` when the worker has
        not finished in time — the fault layer's host-tail watchdog turns
        that into a typed ``DeviceStallError`` and abandons this thread
        (daemon: it can never stall shutdown).
        """
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"daemon future did not finish within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._value
