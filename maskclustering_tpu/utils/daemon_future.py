"""One-shot future on a daemon thread.

A daemon thread — unlike a ThreadPoolExecutor worker, which the
interpreter joins at exit — can never stall process shutdown on an
abandoned blocking call, e.g. a scene load mid-Ctrl-C (run.py's
prefetcher). Note: NOT for device->host pulls — ``np.asarray`` on a
device array holds the GIL for the transfer on this backend, so a
threaded pull serializes host compute instead of overlapping it; use
``jax.Array.copy_to_host_async()`` for that (see PROFILE.md, round 5).
The result or the raised error is re-raised in ``result()`` so failures
attribute to the consuming stage.

Memory-visibility contract (the mct-threads audit, PR 7): ``_value`` /
``_exc`` are written strictly BEFORE ``_done.set()`` and read only after
``_done.wait()`` returns true — the Event's internal lock is the
happens-before edge, so no additional lock is needed. A consumer whose
``result(timeout)`` expired calls ``abandon()``: the worker then drops a
late-arriving value instead of pinning it (and everything it references —
a whole scene's tensors in the executor's host tail) on the future until
the wedged native call returns.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class DaemonFuture:
    """Run ``fn`` on a daemon thread; ``result()`` blocks and re-raises."""

    def __init__(self, fn: Callable, name: str = "daemon-future"):
        self._done = threading.Event()
        self._abandoned = threading.Event()
        self._value = None
        self._exc: Optional[BaseException] = None

        def work():
            try:
                value = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised in result()
                if self._abandoned.is_set():
                    self._drop_late()  # an abandoned error is a drop too
                else:
                    self._exc = e
            else:
                if self._abandoned.is_set():
                    self._drop_late()
                else:
                    self._value = value
            finally:
                self._done.set()

        threading.Thread(  # mct-thread: abandon(one-shot daemon worker: result(timeout) bounds the consumer's wait and abandon() drops a late value; a join would re-create the shutdown stall this class exists to avoid)
            target=work, daemon=True, name=name).start()

    @staticmethod
    def _drop_late() -> None:
        """Book an abandoned-result drop. ``faults._count`` owns the
        never-fault lazy-obs-import semantics (one copy to maintain);
        faults is stdlib-only at import, so this module stays chip-free
        for bench.py's supervisor."""
        from maskclustering_tpu.utils.faults import _count

        _count("run.abandoned_results")

    def abandon(self) -> None:
        """Declare this future's consumer gone (its ``result`` timed out).

        The worker cannot be cancelled — only outwaited — but a value it
        produces after this call is dropped immediately instead of living
        on the future for the daemon thread's remaining lifetime.
        """
        self._abandoned.set()

    def done(self) -> bool:
        """Non-blocking completion probe."""
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block for the value (re-raising the worker's error).

        ``timeout`` (seconds) raises ``TimeoutError`` when the worker has
        not finished in time — the fault layer's host-tail watchdog turns
        that into a typed ``DeviceStallError``, calls ``abandon()``, and
        leaves this thread behind (daemon: it can never stall shutdown).
        """
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"daemon future did not finish within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._value
