"""Fault-tolerance primitives: watchdogs, retries, degradation, journal.

The pipeline is embarrassingly scene-parallel (each scene's mask graph is
built and clustered independently, arXiv:2401.07745 §3), which makes the
SCENE the natural fault boundary: a transient device fault should cost one
scene-retry, not a run. Before this module the runtime only survived
faults at process *startup* (utils/backend_init.py); a wedged chip mid-run
— a device dispatch that never completes, a stuck device->host drain —
hung the whole run forever (VERDICT round 5: a 17+ hour outage produced a
third consecutive null bench). This module is the in-run half:

- **watchdogs** (`call_with_deadline`, `Heartbeat`): a bounded wait around
  any device-phase dispatch / host pull / prefetch resolve; on expiry a
  typed ``DeviceStallError`` is raised in the CALLER and the wedged work
  is abandoned on its daemon thread (a hung native call cannot be
  interrupted — only outwaited — so the watchdog moves the wait, not the
  work);
- **retry + degradation** (`RetryPolicy`, `DegradationLadder`): failed
  scenes retry with backoff (``cfg.scene_retries``/``cfg.retry_backoff_s``),
  and repeated device-class failures degrade the run along an explicit,
  logged ladder (overlapped -> sequential executor, fused mesh -> single
  chip, donation off, device -> host postprocess) instead of failing the
  batch. bench.py's supervisor shares ``RetryPolicy`` (linear style) so
  the backoff semantics cannot silently diverge;
- **crash-safe run journal** (`RunJournal`): an append-only,
  schema-versioned JSONL of scene attempt/outcome/degradation-rung rows
  (the obs/events.py sink + torn-line read policy), giving mid-run resume
  with exact attribution — artifact-exists resume cannot distinguish
  "done" from "never started" for non-exporting steps;
- **deterministic fault injection** (`FaultPlan`): seam-level fault
  scripts (``MCT_FAULT_PLAN="load:scene2, stall:scene4.device,
  flaky:scene5:2"``) so every watchdog, retry, degradation rung and
  journal-resume path is exercised deterministically on CPU in tier-1,
  not argued from the next outage.

This module imports nothing heavier than the stdlib at module scope (obs
metrics are imported lazily per call) so bench.py's chip-free supervisor
can use ``RetryPolicy`` without pulling jax pre-watchdog.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Set

# stdlib-only (the shim returns a raw threading.Lock unless the sanitizer
# is armed); the literal names are the shared vocabulary between the
# static lock-order graph and the runtime-observed one
from maskclustering_tpu.analysis.lock_sanitizer import mct_lock

log = logging.getLogger("maskclustering_tpu")

# seams a FaultPlan can target; these are the places run.py / models/
# pipeline.py / models/postprocess_device.py / models/streaming.py call
# inject() (see ARCHITECTURE.md §Fault tolerance); "post" fires at the
# head of the device post-process chain — the seam that drives the
# ladder's host-postprocess rung — and "chunk" fires at the top of every
# streaming accumulation chunk, the seam whose faults retry the CHUNK
# (accumulator intact), not the scene
# "admission" fires in the DAEMON process at the head of request
# admission (serve/daemon.py) — the parent-side seam the daemon-death
# drills script (the "die" kind) without shelling out a kill
SEAMS = ("load", "device", "host", "export", "pull", "post", "chunk",
         "admission")

# error_class vocabulary stamped on SceneStatus / journal rows:
#   retryable — transient by default (IO, unknown runtime errors)
#   device    — retryable AND drives the degradation ladder (stalls,
#               XLA runtime/OOM errors: the chip, not the scene, is sick)
#   terminal  — a retry cannot help (programming/config errors)
ERROR_CLASSES = ("retryable", "device", "terminal")


def _count(name: str, delta: float = 1.0) -> None:
    """obs counter bump; lazy import keeps this module stdlib-only."""
    try:
        from maskclustering_tpu.obs import metrics

        metrics.count(name, delta)
    except Exception:  # noqa: BLE001 — accounting must never fault the fault layer
        pass


def _flight_record(kind: str, **fields) -> None:
    """Flight-ring mark (obs/flight.py); lazy + never the failure source."""
    try:
        from maskclustering_tpu.obs import flight

        flight.record(kind, **fields)
    except Exception:  # noqa: BLE001 — the black box must never fault the fault layer
        pass


def _flight_dump(reason: str) -> None:
    """Crash-safe black-box dump (no-op unless $MCT_FLIGHT_DIR / an armed
    dir exists). Called on the watchdog-fire and cooperative-drain paths —
    NEVER from a signal handler (CONC.SIGNAL: handlers are flag-only)."""
    try:
        from maskclustering_tpu.obs import flight

        flight.dump(reason)
    except Exception:  # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# typed errors + classification
# ---------------------------------------------------------------------------


class DeviceStallError(RuntimeError):
    """A watchdog deadline expired: the guarded call never returned.

    Raised in the CALLING thread; the stalled work is abandoned on its
    daemon thread (it cannot be cancelled, only outwaited). Carries the
    seam/scene/budget so retry and degradation decisions — and the run
    journal — get exact attribution.
    """

    def __init__(self, seam: str, scene: Optional[str], budget_s: float):
        self.seam = seam
        self.scene = scene
        self.budget_s = budget_s
        super().__init__(
            f"{seam} phase of scene {scene!r} did not finish within "
            f"{budget_s:.3g}s (device stalled or wedged)")


class InjectedFault(RuntimeError):
    """A FaultPlan-scripted failure; ``retryable`` steers classification."""

    def __init__(self, msg: str, *, retryable: bool = True):
        self.retryable = retryable
        super().__init__(msg)


class WorkerCrashError(RuntimeError):
    """The device-owning worker subprocess died under a request.

    Raised (synthesized) by the serving worker supervisor
    (serve/supervisor.py) when a child is SIGKILLed on a missed heartbeat
    or dies outright (segfault, OOM-kill, a ``crash`` fault drill).
    Classified ``device`` — the chip/runtime, not the scene, is the story
    — so the requeue/ladder machinery composes with it like any other
    device-class failure.
    """

    def __init__(self, scene: Optional[str], detail: str):
        self.scene = scene
        self.detail = detail
        super().__init__(
            f"device worker crashed under scene {scene!r}: {detail}")


# exception type names that mean "the device/runtime is sick" without
# importing jaxlib here (the names are stable across jaxlib versions)
_DEVICE_ERROR_NAMES = frozenset({
    "XlaRuntimeError", "DeadlineExceeded", "UnavailableError",
    "InternalError", "ResourceExhaustedError",
    # a scene overflowing a device post-process capacity bucket
    # (models/postprocess_device.py) heals on the ladder's
    # host-postprocess rung, so it must route through the device class
    "PostprocessCapacityError",
})
# a retry cannot fix a programming/config error; fail fast and keep the
# retry budget for faults that can actually heal
_TERMINAL_TYPES = (ValueError, TypeError, KeyError, IndexError,
                   AttributeError, AssertionError, NotImplementedError,
                   ImportError)


def classify_error(exc: BaseException) -> str:
    """Stable error class for retry/degradation decisions (ERROR_CLASSES)."""
    if isinstance(exc, (DeviceStallError, WorkerCrashError)):
        return "device"
    if isinstance(exc, InjectedFault):
        return "retryable" if exc.retryable else "terminal"
    if isinstance(exc, MemoryError) or type(exc).__name__ in _DEVICE_ERROR_NAMES:
        return "device"
    if isinstance(exc, _TERMINAL_TYPES):
        return "terminal"
    return "retryable"  # OSError and unknown runtime errors: worth one more try


# ---------------------------------------------------------------------------
# watchdogs
# ---------------------------------------------------------------------------


def call_with_deadline(fn: Callable, budget_s: float, *, seam: str = "device",
                       scene: Optional[str] = None):
    """Run ``fn`` under a watchdog; ``DeviceStallError`` after ``budget_s``.

    ``budget_s <= 0`` (the production default) calls inline — zero threads,
    zero overhead. Armed, ``fn`` runs on a daemon thread and this thread
    waits at most ``budget_s``: a wedged device dispatch or host pull then
    costs one bounded wait instead of the rest of the run. The abandoned
    thread keeps blocking in native code but — being a daemon — can never
    stall process shutdown. ``fn``'s own exception re-raises here so
    failures attribute to the calling scene.
    """
    if not budget_s or budget_s <= 0:
        return fn()
    box: Dict[str, object] = {}
    done = threading.Event()
    abandoned = threading.Event()

    def work():
        # a call that finishes AFTER the deadline expired is abandoned
        # work: drop the value on the floor immediately (and count it)
        # instead of parking it — and the scene tensors it references —
        # in `box` for the rest of the daemon thread's life
        try:
            value = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            if abandoned.is_set():
                _count("run.abandoned_results")
            else:
                box["error"] = e
        else:
            if abandoned.is_set():
                _count("run.abandoned_results")
            else:
                box["value"] = value
        finally:
            done.set()

    worker = threading.Thread(  # mct-thread: abandon(a wedged native call can only be outwaited, never cancelled; the daemon flag keeps it off the shutdown path and the `abandoned` event drops its late result)
        target=work, daemon=True, name=f"watchdog-{seam}-{scene}")
    worker.start()
    if not done.wait(budget_s):
        abandoned.set()
        _count("run.device_stalls")
        # the wedge evidence goes to disk BEFORE the error unwinds into
        # retry/degradation machinery that may not survive it
        _flight_record("flight.fault", what="watchdog_expired", seam=seam,
                       scene=scene, budget_s=budget_s)
        _flight_dump("watchdog")
        raise DeviceStallError(seam, scene, budget_s)
    if "error" in box:
        raise box["error"]  # type: ignore[misc]
    return box["value"]


class Heartbeat:
    """A deadline that re-arms on progress (long multi-step loops).

    ``beat()`` marks liveness; ``check()`` raises ``DeviceStallError``
    when no beat landed within ``budget_s`` — a loop that is merely SLOW
    keeps beating and lives, one whose next step never arrives dies
    within the budget. Thread-safe (the beating worker and the checking
    supervisor are usually different threads).

    Status: an exported, unit-tested primitive for supervisor loops that
    can interleave ``check()`` with their own progress. It is NOT wired
    into the chunked claims drain: the drain blocks inside ``np.asarray``
    (it cannot self-check mid-chunk), and bounding each chunk with a
    watchdog thread is the GIL-serialization this backend measured as a
    regression (postprocess_device.py's drain comment) — the coarse
    ``watchdog_host_s`` phase deadline bounds the whole drain instead.
    """

    def __init__(self, budget_s: float, *, seam: str = "device",
                 scene: Optional[str] = None):
        self.budget_s = budget_s
        self.seam = seam
        self.scene = scene
        self._lock = mct_lock("faults.Heartbeat._lock")
        self._last = time.monotonic()

    def beat(self) -> None:
        with self._lock:
            self._last = time.monotonic()

    def remaining(self) -> float:
        with self._lock:
            return self.budget_s - (time.monotonic() - self._last)

    def age_s(self) -> float:
        """Seconds since the last beat — the liveness number a status
        snapshot shows BEFORE the budget expires (a climbing age is the
        wedge-is-coming signal; ``expired`` is the wedge-already-here one)."""
        with self._lock:
            return time.monotonic() - self._last

    def expired(self) -> bool:
        return self.budget_s > 0 and self.remaining() <= 0

    def check(self) -> None:
        if self.expired():
            _count("run.device_stalls")
            _flight_record("flight.fault", what="heartbeat_expired",
                           seam=self.seam, scene=self.scene,
                           budget_s=self.budget_s)
            _flight_dump("watchdog")
            raise DeviceStallError(self.seam, self.scene, self.budget_s)


# ---------------------------------------------------------------------------
# retry policy (shared with bench.py's supervisor)
# ---------------------------------------------------------------------------


class RetryPolicy:
    """Backoff schedule for retry loops; one copy of the semantics.

    ``style="exp"``: ``base * 2**(attempt-1)`` capped at ``cap_s`` — the
    scene-retry shape. ``style="linear"``: ``base * attempt`` capped —
    bench.py's historical supervisor shape (20s, 40s, ... cap 120s),
    preserved exactly so the chip-recovery cadence three rounds of BENCH
    records were tuned against does not silently change.

    ``scale_env`` names an env var multiplying every delay (tests shrink
    waits to milliseconds); a malformed value falls back to 1.0 and never
    goes negative — a bad knob must not break a retry loop mid-outage.
    """

    def __init__(self, attempts: int = 3, base_s: float = 0.25,
                 cap_s: float = 30.0, style: str = "exp",
                 scale_env: Optional[str] = None):
        if style not in ("exp", "linear"):
            raise ValueError(f"unknown backoff style {style!r}")
        self.attempts = max(int(attempts), 1)
        self.base_s = max(float(base_s), 0.0)
        self.cap_s = max(float(cap_s), 0.0)
        self.style = style
        self.scale_env = scale_env

    def scale(self) -> float:
        if not self.scale_env:
            return 1.0
        try:
            return max(float(os.environ.get(self.scale_env, "1.0")), 0.0)
        except ValueError:
            return 1.0

    def backoff(self, attempt: int) -> float:
        """Delay after the ``attempt``-th failure (1-based)."""
        attempt = max(int(attempt), 1)
        if self.style == "linear":
            delay = self.base_s * attempt
        else:
            delay = self.base_s * (2.0 ** (attempt - 1))
        return min(delay, self.cap_s) * self.scale()


# ---------------------------------------------------------------------------
# degradation ladder
# ---------------------------------------------------------------------------

# (rung name, config overrides, applicability predicate). Ordered most-
# performant first; each device-class failure round drops ONE rung and the
# overrides accumulate. Rungs the config already satisfies are skipped at
# ladder construction (degrading an already-sequential run to "sequential"
# would burn a rung for nothing).
_LADDER_RUNGS = (
    ("sequential-executor", {"scene_overlap": False},
     lambda cfg: bool(cfg.scene_overlap)),
    # the single-chip rung retires the whole mesh, point axis included
    # (point_shards > 1 without mesh_shape is invalid config). Shard-count
    # awareness: an HBM-capacity failure at high N is better answered by
    # RAISING cfg.point_shards — more shards keep the scene on device with
    # byte-identical artifacts — than by riding the ladder down to
    # single-chip/host; the ladder stays a survival path, not a capacity
    # plan (README "Scaling past the point ceiling").
    ("single-chip", {"mesh_shape": (), "point_shards": 1},
     lambda cfg: bool(cfg.mesh_shape)),
    ("donation-off", {"donate_buffers": False},
     lambda cfg: bool(cfg.donate_buffers)),
    ("host-postprocess", {"device_postprocess": False},
     lambda cfg: bool(cfg.device_postprocess)),
)


class DegradationLadder:
    """Run-level graceful degradation on repeated device-class failures.

    Each ``degrade()`` call drops one rung (logged + counted on
    ``run.degradations.<rung>``); ``apply(cfg)`` returns the config with
    every dropped rung's overrides merged. The ladder trades throughput
    for survivability in a fixed, auditable order — the run report and
    perf ledger stamp the final rung so a degraded run's numbers are
    attributed to the fault, not to code drift.
    """

    def __init__(self, cfg):
        self._rungs = [(name, overrides) for name, overrides, pred
                       in _LADDER_RUNGS if pred(cfg)]
        self._applied = 0

    @property
    def rung(self) -> int:
        """Rungs dropped so far (0 = full configuration)."""
        return self._applied

    @property
    def applied_names(self) -> List[str]:
        return [name for name, _ in self._rungs[:self._applied]]

    @property
    def exhausted(self) -> bool:
        return self._applied >= len(self._rungs)

    def degrade(self, reason: str = "") -> Optional[str]:
        """Drop one rung; returns its name, or None when exhausted."""
        if self.exhausted:
            return None
        name, _ = self._rungs[self._applied]
        self._applied += 1
        _count(f"run.degradations.{name}")
        log.warning("degrading to rung %d (%s)%s", self._applied, name,
                    f": {reason}" if reason else "")
        return name

    def apply(self, cfg):
        """The config at the current rung (overrides of every dropped rung)."""
        overrides: Dict[str, object] = {}
        for _, o in self._rungs[:self._applied]:
            overrides.update(o)
        return cfg.replace(**overrides) if overrides else cfg


# ---------------------------------------------------------------------------
# deterministic fault injection
# ---------------------------------------------------------------------------


class _FaultEntry:
    __slots__ = ("kind", "seam", "scene", "remaining", "lock")

    def __init__(self, kind: str, seam: str, scene: str,
                 count: Optional[int]):
        self.kind = kind
        self.seam = seam
        self.scene = scene
        self.remaining = count  # None = every attempt
        self.lock = mct_lock("faults._FaultEntry.lock")

    def take(self) -> bool:
        """Consume one firing; False once the count is exhausted."""
        with self.lock:
            if self.remaining is None:
                return True
            if self.remaining <= 0:
                return False
            self.remaining -= 1
            return True


# kind -> (default seam, default count; None = unlimited)
_KIND_DEFAULTS = {
    "fail": ("device", None),
    "load": ("load", None),
    "flaky": ("device", 1),
    "stall": ("device", 1),
    "terminal": ("device", None),
    "sigterm": ("load", 1),
    # crash-containment drills (serve/supervisor.py): "crash" SIGKILLs
    # the process executing the seam (in the isolated serving worker, a
    # real hard kill of the device-owning subprocess); "wedge" simulates
    # the GIL-held native hang no in-process watchdog can clear — it
    # silences the worker's heartbeat (set_wedge_hook) and blocks the
    # seam UNBOUNDED, so only the supervisor's SIGKILL ends it
    "crash": ("device", 1),
    "wedge": ("device", 1),
    # daemon-death drill (serve/daemon.py, scripts/load_gen.py
    # --chaos-drill): "die" SIGKILLs the process executing the seam,
    # exactly like "crash", but defaults to the PARENT-side admission
    # seam — arming it in the daemon scripts whole-daemon death
    # deterministically (WAL replay territory), where "crash" at a
    # worker seam kills only the contained subprocess
    "die": ("admission", 1),
    # silent-data-corruption drill (obs/digest.py, obs/canary.py):
    # "corrupt" deterministically bit-flips a pulled claim/graph stat at
    # the seam INSTEAD of raising — the retry policy and degradation
    # ladder never see it, so the corruption must surface as sentinel
    # digest drift, not vanish into a heal. Unlimited by default so every
    # canary probe of the target scene drifts (the SLO burn-rate rule
    # needs repeated occurrences to page).
    "corrupt": ("host", None),
}


class FaultPlan:
    """A deterministic, seam-scripted fault schedule.

    Spec grammar (comma-separated entries)::

        KIND:SCENE[.SEAM][:COUNT]

        load:scene2           # scene2's load raises, every attempt
        stall:scene4.device   # scene4's first device phase hangs (sleep)
        flaky:scene5:2        # scene5's device phase fails twice, then ok
        fail:scene3.export:1  # one export failure
        terminal:scene6       # a non-retryable failure (classification)
        sigterm:scene1.load   # one real SIGTERM to this process at the seam
        crash:scene7.device   # one real SIGKILL to the executing process
        wedge:scene8.device   # heartbeat-silent unbounded hang (SIGKILL cures)
        corrupt:scene9.host   # silent bit-flip of a pulled stat (digest drift)
        die:sceneA.admission  # one real SIGKILL of the DAEMON at admission

    ``stall`` sleeps ``stall_s`` at the seam — under an armed watchdog the
    caller sees ``DeviceStallError`` within its budget; without one the
    sleep IS the simulated hang. Counts decrement per firing, so retries
    see the scripted sequence deterministically (flaky-then-ok, stall-
    then-heal). Thread-safe: seams fire from prefetch daemons, the
    dispatch thread and the host-tail worker.
    """

    def __init__(self, entries: Iterable[_FaultEntry], *,
                 stall_s: float = 5.0, spec: str = ""):
        self.entries = list(entries)
        self.stall_s = float(stall_s)
        self.spec = spec

    @classmethod
    def from_spec(cls, spec: str, *, stall_s: Optional[float] = None) -> "FaultPlan":
        if stall_s is None:
            try:
                stall_s = float(os.environ.get("MCT_FAULT_STALL_S", "5.0"))
            except ValueError:
                stall_s = 5.0
        entries = []
        for raw in spec.split(","):
            raw = raw.strip()
            if not raw:
                continue
            parts = raw.split(":")
            if len(parts) not in (2, 3):
                raise ValueError(f"bad fault entry {raw!r} "
                                 "(KIND:SCENE[.SEAM][:COUNT])")
            kind, target = parts[0].strip(), parts[1].strip()
            if kind not in _KIND_DEFAULTS:
                raise ValueError(f"unknown fault kind {kind!r} in {raw!r} "
                                 f"(one of {sorted(_KIND_DEFAULTS)})")
            seam, count = _KIND_DEFAULTS[kind]
            if "." in target:
                scene, _, maybe_seam = target.rpartition(".")
                if maybe_seam not in SEAMS:
                    raise ValueError(f"unknown seam {maybe_seam!r} in {raw!r} "
                                     f"(one of {SEAMS})")
                target, seam = scene, maybe_seam
            if len(parts) == 3:
                count = int(parts[2])
                if count < 1:
                    raise ValueError(f"count must be >= 1 in {raw!r}")
            if not target:
                raise ValueError(f"empty scene name in {raw!r}")
            entries.append(_FaultEntry(kind, seam, target, count))
        return cls(entries, stall_s=stall_s, spec=spec)

    def fire(self, seam: str, scene: Optional[str]) -> None:
        """Perform every scripted action matching (seam, scene); called by
        ``inject()`` at the seam sites. Raising entries raise; a ``stall``
        sleeps; ``sigterm`` signals this very process (exercising the real
        handler deterministically)."""
        if scene is None:
            return
        for e in self.entries:
            if e.kind == "corrupt":
                # corruption never fires at an inject() seam — it is
                # consumed by take_corruption() at the data site, so no
                # exception ever reaches the retry/ladder machinery
                continue
            if e.seam != seam or e.scene != scene or not e.take():
                continue
            _count(f"faults.injected.{seam}")
            _flight_record("flight.fault", what="injected",
                           fault_kind=e.kind, seam=seam, scene=scene)
            log.warning("fault injection: %s at %s seam of scene %s",
                        e.kind, seam, scene)
            if e.kind == "stall":
                time.sleep(self.stall_s)
            elif e.kind == "sigterm":
                os.kill(os.getpid(), signal.SIGTERM)
            elif e.kind in ("crash", "die"):
                # the hard-failure drills: SIGKILL the process executing
                # this seam (no handler, no cleanup — the observed XLA
                # segfault/OOM-kill class). "crash" under the isolated
                # serving worker kills the SUBPROCESS (the supervisor
                # respawns and requeues); "die" at the admission seam
                # kills the DAEMON itself (WAL replay recovers on the
                # next start).
                os.kill(os.getpid(), signal.SIGKILL)
            elif e.kind == "wedge":
                hook = wedge_hook()
                if hook is not None:
                    hook()  # silence the worker's heartbeat emitter
                while True:  # unbounded: only an external SIGKILL ends it
                    time.sleep(60.0)
            elif e.kind == "terminal":
                raise InjectedFault(
                    f"injected terminal fault at {seam} seam of {scene}",
                    retryable=False)
            elif seam == "post":
                # the post seam's one real failure mode is a capacity
                # overflow; injecting the production error type drives the
                # production classification (device class) and therefore
                # the ladder drop down to the host-postprocess rung
                from maskclustering_tpu.models.postprocess_device import (
                    PostprocessCapacityError,
                )

                raise PostprocessCapacityError(
                    f"injected ({e.kind} fault at scene {scene})", -1, 0,
                    "post_group_cap")
            else:  # fail / load / flaky
                raise InjectedFault(
                    f"injected {e.kind} fault at {seam} seam of {scene}")

    def take_corruption(self, seam: str, scene: Optional[str]) -> bool:
        """Consume one scripted ``corrupt`` firing for (seam, scene).

        Called from the data sites themselves (the pulled-assignment tail
        of run_scene_host, the streaming chunk-digest pull) — the caller
        flips a bit when this returns True. Deliberately classification-
        free: nothing raises, nothing retries, the ladder stays blind.
        """
        if scene is None:
            return False
        for e in self.entries:
            if (e.kind != "corrupt" or e.seam != seam or e.scene != scene
                    or not e.take()):
                continue
            _count(f"faults.injected.{seam}")
            _flight_record("flight.fault", what="injected",
                           fault_kind="corrupt", seam=seam, scene=scene)
            log.warning("fault injection: corrupt at %s seam of scene %s",
                        seam, scene)
            return True
        return False


_PLAN: Optional[FaultPlan] = None
_PLAN_LOADED = False
_PLAN_LOCK = mct_lock("faults._PLAN_LOCK")
_WEDGE_HOOK: Optional[Callable] = None


def set_wedge_hook(fn: Optional[Callable]) -> None:
    """Register the action a ``wedge`` fault performs before hanging —
    the isolated serving worker (serve/worker_main.py) installs its
    heartbeat-silencer here so a wedge drill looks exactly like the
    GIL-held native hang it simulates."""
    global _WEDGE_HOOK
    with _PLAN_LOCK:
        _WEDGE_HOOK = fn


def wedge_hook() -> Optional[Callable]:
    with _PLAN_LOCK:
        return _WEDGE_HOOK


def active_plan() -> Optional[FaultPlan]:
    """The process-wide plan: explicit ``set_plan`` wins, else
    ``$MCT_FAULT_PLAN`` (parsed once)."""
    global _PLAN, _PLAN_LOADED
    with _PLAN_LOCK:
        if not _PLAN_LOADED:
            spec = os.environ.get("MCT_FAULT_PLAN", "").strip()
            _PLAN = FaultPlan.from_spec(spec) if spec else None
            _PLAN_LOADED = True
        return _PLAN


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Install (or clear with None) the process-wide plan; overrides env."""
    global _PLAN, _PLAN_LOADED
    with _PLAN_LOCK:
        _PLAN = plan
        _PLAN_LOADED = True


def inject(seam: str, scene: Optional[str]) -> None:
    """The seam hook: a no-op without an active plan (one dict lookup),
    else fires the plan's matching entries. Call sites: run.py executors
    (load/device/export), models/pipeline.py (device/host/export/pull)."""
    plan = active_plan()
    if plan is not None:
        plan.fire(seam, scene)


def take_corruption(seam: str, scene: Optional[str]) -> bool:
    """The corruption hook: True when an active plan scripts a ``corrupt``
    firing at (seam, scene) — the data site then flips one bit. Call
    sites: models/pipeline.py (host), models/streaming.py (chunk)."""
    plan = active_plan()
    return plan.take_corruption(seam, scene) if plan is not None else False


# ---------------------------------------------------------------------------
# cooperative stop (SIGTERM-safe shutdown)
# ---------------------------------------------------------------------------

_STOP = threading.Event()
_STOP_REASON = ""
_STOP_ANNOUNCED = threading.Event()


def _set_stop(reason: str) -> None:
    """Flag-only stop: Event + string assignment, nothing else.

    This is the whole async-signal-safe surface — the SIGTERM handler
    calls it mid-anything, so it must not log (the interrupted thread may
    hold the logging module's lock), allocate containers, or do IO
    (CONC.SIGNAL, analysis/concurrency.py). The announcement is deferred
    to the first ``stop_requested()`` poll on a normal thread.
    """
    global _STOP_REASON
    if not _STOP.is_set():
        _STOP_REASON = reason
    _STOP.set()


def _announce_stop() -> None:
    """One-shot stop warning, from a NORMAL thread only (never the
    handler). The check-then-set is not atomic — two first polls can in
    principle both announce — but the worst case is a duplicate log line,
    accepted for a lock-free poll path."""
    if not _STOP_ANNOUNCED.is_set():
        _STOP_ANNOUNCED.set()
        # first safe-thread poll after the (flag-only) handler: the ring
        # mark for the stop transition happens HERE, never in the handler
        _flight_record("flight.signal", what="stop_requested",
                       reason=_STOP_REASON)
        log.warning("stop requested%s: finishing in-flight scenes, "
                    "journaling the rest",
                    f" ({_STOP_REASON})" if _STOP_REASON else "")


def request_stop(reason: str = "") -> None:
    _set_stop(reason)
    _announce_stop()


def stop_requested() -> bool:
    # the deferred half of the handler's contract: the first scene-boundary
    # poll after a signal announces the stop from a safe (normal) thread
    if _STOP.is_set():
        _announce_stop()
    return _STOP.is_set()


def stop_reason() -> str:
    return _STOP_REASON


def clear_stop() -> None:
    global _STOP_REASON
    _STOP.clear()
    _STOP_ANNOUNCED.clear()
    _STOP_REASON = ""


def install_sigterm_handler() -> Callable:
    """SIGTERM -> cooperative stop; a second SIGTERM force-exits (143).

    The scene loops check ``stop_requested()`` at every scene boundary, so
    a terminated run journals in-flight scenes and still writes a valid
    partial run_report.json — the same posture bench.py's supervisor takes
    for its one-JSON-line contract. Returns the previous handler (callers
    restore it; tests install/restore around in-process runs).
    """
    def _handler(signum, frame):  # noqa: ARG001 — signal API shape
        if _STOP.is_set():
            os._exit(143)  # second signal: the polite path already ran
        _set_stop(f"signal {signum}")  # flag-only; logging is deferred

    return signal.signal(signal.SIGTERM, _handler)


# ---------------------------------------------------------------------------
# crash-safe run journal
# ---------------------------------------------------------------------------

# the journal rides the obs event envelope (v/kind/ts/pid + one flush per
# line) and the shared torn-line read policy — one copy of crash tolerance
KIND_RUN = "run"
KIND_SCENE = "scene"


class RunJournal:
    """Append-only scene attempt/outcome journal for one config's runs.

    One line per scene attempt start and per outcome, so a crash (SIGKILL,
    chip wedge, OOM) leaves exact attribution on disk: ``done`` scenes are
    skipped on resume, an ``attempt`` with no outcome was in flight and
    re-runs, scenes never journaled never started. Rows carry the config
    name — one journal file can serve several configs without cross-talk.
    ``request_id`` (the serving daemon's per-request attribution) stamps
    every row when given, so one journal path can carry many requests
    without clobbering — ``read_journal``/``replay_journal``/``resume_done``
    filter on it, and a request-free reader still round-trips the rows.
    Writes go through the obs EventSink (thread-safe, flush per line,
    never the failure source).
    """

    def __init__(self, path: str, config_name: str,
                 request_id: Optional[str] = None):
        from maskclustering_tpu.obs.events import EventSink

        self.path = path
        self.config_name = config_name
        self.request_id = request_id
        self._sink = EventSink(path)

    def _stamp(self, payload: Dict) -> Dict:
        if self.request_id is not None:
            payload["request"] = self.request_id
        return payload

    def begin_run(self) -> None:
        self._sink.emit(KIND_RUN, self._stamp({"event": "begin",
                                               "config": self.config_name}))

    def end_run(self, *, interrupted: bool = False) -> None:
        self._sink.emit(KIND_RUN, self._stamp({
            "event": "end", "config": self.config_name,
            "interrupted": bool(interrupted)}))

    def attempt(self, seq: str, attempt: int, rung: int) -> None:
        self._sink.emit(KIND_SCENE, self._stamp({
            "event": "attempt", "seq": seq, "attempt": attempt,
            "rung": rung, "config": self.config_name}))

    def outcome(self, seq: str, status: str, *, attempt: int = 0,
                rung: int = 0, error_class: str = "", error: str = "",
                seconds: float = 0.0, num_objects: int = -1) -> None:
        payload = {"event": "outcome", "seq": seq, "status": status,
                   "attempt": attempt, "rung": rung,
                   "error_class": error_class,
                   "num_objects": num_objects,
                   "seconds": round(float(seconds), 4),
                   "config": self.config_name}
        if error:
            # final line only ("ExceptionType: message" in a formatted
            # traceback): the journal is attribution, not a stack dump
            payload["error"] = str(error).strip().splitlines()[-1][:200]
        self._sink.emit(KIND_SCENE, self._stamp(payload))

    def resume_done(self) -> Set[str]:
        return resume_done(self.path, config=self.config_name,
                           request=self.request_id)

    def close(self) -> None:
        self._sink.close()


def read_journal(path: str, *, config: Optional[str] = None,
                 request: Optional[str] = None, stats=None) -> List[Dict]:
    """All journal rows (oldest first), sharing the events torn-line
    policy; ``config`` filters to one config's rows, ``request`` to one
    serving request's (rows without a request stamp only match ``None``)."""
    from maskclustering_tpu.obs.events import SCHEMA_VERSION, iter_jsonl_rows

    rows = []
    for row in iter_jsonl_rows(path, version=SCHEMA_VERSION, stats=stats):
        if row.get("kind") not in (KIND_RUN, KIND_SCENE):
            continue
        if config is not None and row.get("config") != config:
            continue
        if request is not None and row.get("request") != request:
            continue
        rows.append(row)
    return rows


def replay_journal(path: str, *, config: Optional[str] = None,
                   request: Optional[str] = None, stats=None
                   ) -> Dict[str, Dict]:
    """Final per-scene state from the journal alone.

    Returns ``{seq: {status, attempts, degradation_rung, error_class,
    num_objects}}`` — the same fields run_report.json carries per scene,
    so a report can be REPLAYED from the journal and cross-checked (or
    reconstructed after a crash that ate the report). A trailing
    ``attempt`` with no outcome replays as status ``"in-flight"``: that
    scene was running when the process died and must re-run.
    """
    out: Dict[str, Dict] = {}
    for row in read_journal(path, config=config, request=request,
                            stats=stats):
        if row.get("kind") != KIND_SCENE:
            continue
        seq = row.get("seq")
        if not isinstance(seq, str):
            continue
        cur = out.setdefault(seq, {"status": "in-flight", "attempts": 0,
                                   "degradation_rung": 0, "error_class": "",
                                   "num_objects": -1})
        if row.get("event") == "attempt":
            cur["attempts"] = max(cur["attempts"], int(row.get("attempt", 0)))
            cur["status"] = "in-flight"
        elif row.get("event") == "outcome":
            cur["status"] = row.get("status", "in-flight")
            cur["attempts"] = max(cur["attempts"], int(row.get("attempt", 0)))
            cur["degradation_rung"] = int(row.get("rung", 0))
            cur["error_class"] = row.get("error_class", "")
            cur["num_objects"] = int(row.get("num_objects", -1))
    return out


def resume_done(path: str, *, config: Optional[str] = None,
                request: Optional[str] = None) -> Set[str]:
    """Scenes whose journal says they need no re-run: final status ``ok``
    (exported) or ``skipped`` (a previous resume already vouched). Failed,
    interrupted and in-flight scenes all re-run."""
    if not os.path.exists(path):
        return set()
    return {seq for seq, st in replay_journal(path, config=config,
                                              request=request).items()
            if st["status"] in ("ok", "skipped")}
