"""Persistent AOT executable cache: serialized serving programs on disk.

ROADMAP item 3: the cold/warm gap is compile-dominated (106.6 s warm-up vs
~3 s/scene steady in BENCH_r03), and every daemon restart, crashed-worker
respawn and scarce chip-recovery window re-bought it. This module makes
warm a DURABLE property of the deployment instead of a property of one
process:

- **export blobs** — the serving programs' ``jax.export`` round-trips
  (StableHLO + calling convention), serialized one file per executable and
  keyed by the retrace census coordinates ``(fn, shape bucket/avals,
  count_dtype, donation)`` plus a jax/jaxlib/schema **version stamp**.
  The cache lives next to PERF_LEDGER (``aot_cache/`` beside the ledger
  path; ``$MCT_AOT_CACHE`` or ``cfg.aot_cache_dir`` override) with a
  human-auditable ``index.json``. ``warm_start`` deserializes every entry
  matching the current stamp + config coordinates and AOT-compiles it
  from abstract avals (nothing materializes); the dispatch seams
  (``models/backprojection.associate_scene``, ``parallel/batch``) then
  run the RESTORED executable — zero Python tracing, zero lowering, and
  the XLA compile of the restored module is itself served by the
  persistent compilation cache after the first restore.
- **backend-compile dedup** — enabling the cache also drops
  ``jax_persistent_cache_min_compile_time_secs`` to 0 so EVERY serving
  executable persists in the XLA compilation cache
  (``utils/compile_cache.setup_compilation_cache``). Programs without an
  export blob still trace in a fresh process, but their backend compile
  is a cache deserialize — and the retrace sanitizer correlates those
  compile-log events with jax's ``/jax/compilation_cache/cache_hits``
  monitoring events and books them as **cache hits, not compiles**
  (analysis/retrace_sanitizer.py). A warm second process therefore
  reaches first dispatch with a ``compiles: 0`` digest.

**Version invalidation**: an entry whose stamp does not match the running
jax/jaxlib/schema versions is never restored — it is reported (and
counted on ``aot_cache.invalidated``) and the dispatch falls back to a
normal compile, which re-captures a fresh entry. ``prune()`` deletes the
mismatched files.

Thread-safety: the runtime registry is written by ``warm_start`` (process
start, single-threaded) and read by the dispatch seams (worker + host-tail
threads); captures can fire from the worker thread. One ``mct_lock``
guards all module state.

Stdlib-only at module scope (jax imports are deferred): bench.py's
chip-free supervisor may import config (which transitively reaches
utils/) without pulling jax pre-watchdog.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from maskclustering_tpu.analysis.lock_sanitizer import mct_lock

log = logging.getLogger("maskclustering_tpu")

SCHEMA_VERSION = 1
INDEX_NAME = "index.json"
ENV_DIR = "MCT_AOT_CACHE"


def _count(name: str, delta: float = 1.0) -> None:
    try:
        from maskclustering_tpu.obs import metrics

        metrics.count(name, delta)
    except Exception:  # noqa: BLE001 — accounting never faults the cache
        pass


# ---------------------------------------------------------------------------
# key schema
# ---------------------------------------------------------------------------


def version_stamp() -> Dict[str, str]:
    """The invalidation coordinates: a serialized executable is only valid
    under the exact jax/jaxlib (serialization + compiler) versions and this
    module's schema version that produced it."""
    import jax
    import jaxlib

    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "schema": str(SCHEMA_VERSION)}


@dataclasses.dataclass(frozen=True)
class AotKey:
    """One executable's identity — the retrace census coordinates.

    ``avals`` is the tuple of (shape, dtype) pairs of the call arguments
    (the shape bucket, fully resolved: the same program at two buckets is
    two entries); ``statics`` carries the compile-stable builder params
    (k_max, window, thresholds, ...) that select the program variant;
    ``count_dtype``/``donate`` are the census's extra key axes.
    """

    fn: str
    avals: Tuple[Tuple[Tuple[int, ...], str], ...]
    statics: Tuple[Tuple[str, str], ...]
    count_dtype: str
    donate: bool

    def digest(self) -> str:
        doc = {"fn": self.fn, "avals": [list(a) for a, d in self.avals],
               "dtypes": [d for _, d in self.avals],
               "statics": dict(self.statics),
               "count_dtype": self.count_dtype, "donate": self.donate}
        return hashlib.sha1(
            json.dumps(doc, sort_keys=True).encode("utf-8")).hexdigest()[:16]

    def describe(self) -> Dict:
        return {"fn": self.fn,
                "avals": [f"{d}{list(s)}" for s, d in self.avals],
                "statics": dict(self.statics),
                "count_dtype": self.count_dtype,
                "donate": self.donate}


def key_for(fn: str, args: Sequence, *, statics: Dict, count_dtype: str,
            donate: bool) -> AotKey:
    """Build an AotKey from concrete call arguments (shapes + dtypes only
    are read — works for numpy arrays, jax arrays, and ShapeDtypeStructs)."""
    import numpy as np

    avals = []
    for a in args:
        shape = tuple(int(d) for d in getattr(a, "shape", ()))
        dtype = str(np.dtype(getattr(a, "dtype", np.float32)))
        avals.append((shape, dtype))
    return AotKey(fn=fn, avals=tuple(avals),
                  statics=tuple(sorted((k, str(v))
                                       for k, v in statics.items())),
                  count_dtype=str(count_dtype), donate=bool(donate))


# ---------------------------------------------------------------------------
# the on-disk cache (index + one blob per entry)
# ---------------------------------------------------------------------------


def default_cache_dir() -> str:
    """``aot_cache/`` next to the perf ledger (one durable artifact home),
    overridable via $MCT_AOT_CACHE."""
    env = os.environ.get(ENV_DIR, "").strip()
    if env:
        return env
    from maskclustering_tpu.obs.ledger import default_ledger_path

    return os.path.join(os.path.dirname(default_ledger_path()) or ".",
                        "aot_cache")


def resolve_cache_dir(cfg) -> Optional[str]:
    """The cache directory for ``cfg`` (None = the cache is disabled).

    ``cfg.aot_cache_dir``: "" disables unless $MCT_AOT_CACHE arms it;
    "auto" (or the env var alone) uses the default next-to-ledger home; an
    explicit path wins outright.
    """
    explicit = getattr(cfg, "aot_cache_dir", "") or ""
    if explicit and explicit != "auto":
        return explicit
    if explicit == "auto" or os.environ.get(ENV_DIR, "").strip():
        return default_cache_dir()
    return None


class AotCache:
    """One cache directory: ``index.json`` + ``<digest>.bin`` blobs."""

    def __init__(self, path: str):
        self.path = path
        self._lock = mct_lock("aot_cache.AotCache._lock")

    def _index_path(self) -> str:
        return os.path.join(self.path, INDEX_NAME)

    def _read_index(self) -> Dict[str, Dict]:
        try:
            with open(self._index_path(), "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {}
        return doc.get("entries", {}) if isinstance(doc, dict) else {}

    def _write_index(self, entries: Dict[str, Dict]) -> None:
        os.makedirs(self.path, exist_ok=True)
        tmp = self._index_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"schema": SCHEMA_VERSION, "entries": entries}, f,
                      indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, self._index_path())  # atomic: no torn index

    def entries(self) -> Dict[str, Dict]:
        with self._lock:
            return self._read_index()

    def store(self, key: AotKey, blob: bytes, *, donate_argnums=()) -> bool:
        """Persist one serialized executable (atomic tmp+rename); returns
        False (logged) on any disk error — the cache must never sink the
        run that tried to warm it."""
        digest = key.digest()
        try:
            os.makedirs(self.path, exist_ok=True)
            blob_path = os.path.join(self.path, f"{digest}.bin")
            tmp = blob_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, blob_path)
            with self._lock:
                entries = self._read_index()
                entries[digest] = {
                    **key.describe(),
                    "stamp": version_stamp(),
                    "bytes": len(blob),
                    "donate_argnums": list(donate_argnums),
                    "created": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                             time.gmtime()),
                }
                self._write_index(entries)
        except OSError:
            log.exception("aot cache: could not store %s", key.fn)
            return False
        _count("aot_cache.stores")
        log.info("aot cache: stored %s (%s, %d bytes)", key.fn, digest,
                 len(blob))
        return True

    def lookup(self, key: AotKey) -> Optional[bytes]:
        """The entry's blob, or None on miss/version-mismatch (mismatches
        are counted on ``aot_cache.invalidated`` — the caller falls back
        to a normal compile and re-captures)."""
        digest = key.digest()
        with self._lock:
            meta = self._read_index().get(digest)
        if meta is None:
            return None
        if meta.get("stamp") != version_stamp():
            _count("aot_cache.invalidated")
            log.warning("aot cache: %s entry stamped %s does not match the "
                        "running versions %s; ignoring (prune() deletes it)",
                        key.fn, meta.get("stamp"), version_stamp())
            return None
        try:
            with open(os.path.join(self.path, f"{digest}.bin"), "rb") as f:
                return f.read()
        except OSError:
            return None

    def prune(self) -> int:
        """Delete version-mismatched entries; returns how many."""
        stamp = version_stamp()
        removed = 0
        with self._lock:
            entries = self._read_index()
            keep = {}
            for digest, meta in entries.items():
                if meta.get("stamp") == stamp:
                    keep[digest] = meta
                    continue
                removed += 1
                try:
                    os.unlink(os.path.join(self.path, f"{digest}.bin"))
                except OSError:
                    pass
            if removed:
                self._write_index(keep)
        return removed


# ---------------------------------------------------------------------------
# capture + restore (the jax.export round-trip)
# ---------------------------------------------------------------------------

# runtime registry of restored executables: AotKey digest -> callable.
# Written by warm_start()/capture (worker thread), read per dispatch
# (worker + host-tail threads) — all under _STATE_LOCK
_STATE_LOCK = mct_lock("aot_cache._STATE_LOCK")
_RESTORED: Dict[str, Callable] = {}
_CAPTURED: set = set()  # key digests exported this process (avoid repeats)
_ACTIVE: Optional[AotCache] = None


def configure(cfg) -> Optional[AotCache]:
    """Arm the process-wide cache for ``cfg`` (idempotent; None = disabled).

    Also drops the persistent compilation cache's min-compile-time floor
    to 0 so every serving executable persists — with the AOT cache on,
    "everything compiled is durable" is the contract the zero-compile
    warm start stands on.
    """
    global _ACTIVE
    path = resolve_cache_dir(cfg)
    if path is None:
        return None
    with _STATE_LOCK:
        if _ACTIVE is None or _ACTIVE.path != path:
            _ACTIVE = AotCache(path)
        cache = _ACTIVE
    try:
        from maskclustering_tpu.utils.compile_cache import \
            setup_compilation_cache

        setup_compilation_cache(getattr(cfg, "compilation_cache_dir", None),
                                min_compile_time_s=0.0)
    except Exception:  # noqa: BLE001 — the export blobs alone still warm
        pass
    return cache


def active() -> Optional[AotCache]:
    with _STATE_LOCK:
        return _ACTIVE


def reset() -> None:
    """Drop process state (test isolation); the disk cache is untouched."""
    global _ACTIVE
    with _STATE_LOCK:
        _ACTIVE = None
        _RESTORED.clear()
        _CAPTURED.clear()


def restored(key: AotKey) -> Optional[Callable]:
    """The restored executable for ``key`` (the dispatch seams' query).

    Counts hits/misses: a hit is a dispatch that paid ZERO tracing and
    zero compilation; a miss falls back to the normal jit path (and is
    only counted while a cache is armed — disarmed processes book
    nothing).
    """
    with _STATE_LOCK:
        if _ACTIVE is None:
            return None
        fn = _RESTORED.get(key.digest())
    if fn is not None:
        _count("aot_cache.hits")
    else:
        _count("aot_cache.misses")
    return fn


_PYTREES_REGISTERED = False


def _register_pytrees() -> None:
    """Register the serving programs' namedtuple result types with
    jax.export (idempotent; needed on BOTH the capturing and the restoring
    side — an Exported's pytree structure round-trips by serialized name)."""
    global _PYTREES_REGISTERED
    if _PYTREES_REGISTERED:
        return
    from jax import export as jax_export

    from maskclustering_tpu.models.backprojection import SceneAssociation
    from maskclustering_tpu.parallel.sharded import FusedStepResult

    for cls in (SceneAssociation, FusedStepResult):
        try:
            jax_export.register_namedtuple_serialization(
                cls, serialized_name=f"maskclustering_tpu.{cls.__name__}")
        except ValueError:
            pass  # already registered (re-import in tests)
    _PYTREES_REGISTERED = True


def _compile_blob(blob: bytes, donate_argnums=()) -> Callable:
    """Deserialize + AOT-compile one blob into a ready executable.

    The compile happens from abstract avals (nothing materializes) inside
    the retrace sanitizer's restore window, so the wrapper's own compile
    event books as a cache restore, not a serving compile. The returned
    ``Compiled`` is called directly per dispatch — no jit cache involved.
    """
    import jax
    from jax import export as jax_export

    _register_pytrees()
    exp = jax_export.deserialize(blob)
    wrapped = jax.jit(exp.call,
                      donate_argnums=tuple(donate_argnums) or None)
    avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in exp.in_avals]
    from maskclustering_tpu.analysis import retrace_sanitizer

    with retrace_sanitizer.restore_window():
        return wrapped.lower(*avals).compile()


# warm_start's restore ceiling: each restore is a deserialize + one
# backend compile (usually a persistent-cache deserialize itself), so a
# shared cache dir that accumulated many configs' entries must not turn
# "instant warm" back into a compile wall. $MCT_AOT_MAX_RESTORES raises
# it; the skip is LOGGED, never silent — per-config cache dirs
# (--aot-cache DIR) are the real fix for a polluted shared home.
DEFAULT_MAX_RESTORES = 64


def _cfg_statics(cfg) -> Dict[str, str]:
    """The config-determined static coordinates (stringified exactly like
    ``key_for``), used to fence warm_start to entries THIS config can
    actually dispatch. Keys absent from an entry's statics (or from this
    map — e.g. ``k_max``, which legitimately varies per shape bucket)
    never disqualify it."""
    # the SAME SxF / SxFxP label fused_step_aot_key stamps (parallel/
    # mesh.mesh_label): point_shards is a compile-surface coordinate, so
    # a resharded deployment filters to its own mesh's entries
    shape = tuple(cfg.mesh_shape)
    if cfg.mesh_shape and cfg.point_shards > 1:
        shape = shape + (int(cfg.point_shards),)
    mesh_desc = ("x".join(str(int(d)) for d in shape)
                 if cfg.mesh_shape else "none")
    return {
        "window": str(cfg.association_window),
        "distance_threshold": str(float(cfg.distance_threshold)),
        "depth_trunc": str(float(cfg.depth_trunc)),
        "few_points_threshold": str(cfg.few_points_threshold),
        "coverage_threshold": str(float(cfg.coverage_threshold)),
        "frame_batch": str(int(cfg.association_frame_batch)),
        "mesh": mesh_desc,
    }


def warm_start(cfg) -> Dict[str, int]:
    """Restore every valid entry for ``cfg``'s coordinates at process start.

    Called by run.py, the serve daemon and the isolated worker before
    first dispatch. Returns ``{"restored": n, "invalidated": n,
    "failed": n}``; restored executables are installed in the runtime
    registry, so the dispatch seams find them without compiling. Entries
    for OTHER coordinates (a different count_dtype, the donation-off rung)
    are left on disk untouched — they are some other config's warm start.
    Restores are capped at ``DEFAULT_MAX_RESTORES`` newest entries
    (``$MCT_AOT_MAX_RESTORES``), and the cap is announced when it bites.
    """
    stats = {"restored": 0, "invalidated": 0, "failed": 0}
    cache = configure(cfg)
    if cache is None:
        return stats
    try:
        max_restores = int(os.environ.get("MCT_AOT_MAX_RESTORES",
                                          DEFAULT_MAX_RESTORES))
    except ValueError:
        max_restores = DEFAULT_MAX_RESTORES
    stamp = version_stamp()
    donate = bool(cfg.donate_buffers)
    wanted = _cfg_statics(cfg)
    entries = sorted(cache.entries().items(),
                     key=lambda kv: kv[1].get("created", ""), reverse=True)
    for digest, meta in entries:
        if meta.get("count_dtype") not in (None, cfg.count_dtype) \
                or bool(meta.get("donate")) != donate:
            continue
        statics = meta.get("statics") or {}
        if any(statics.get(k) not in (None, v) for k, v in wanted.items()):
            # another config's coordinates (different thresholds, mesh,
            # frame batch): restoring it would pay a compile for an
            # executable this process can never dispatch — and could
            # starve the restore cap. Shape-bucket axes (k_max, avals)
            # are deliberately NOT filtered: every bucket of THIS config
            # is wanted warmth.
            continue
        if stats["restored"] >= max_restores:
            log.warning(
                "aot cache: restore cap %d reached; remaining entries are "
                "skipped (raise $MCT_AOT_MAX_RESTORES, prune(), or use a "
                "per-config --aot-cache dir)", max_restores)
            break
        if meta.get("stamp") != stamp:
            stats["invalidated"] += 1
            _count("aot_cache.invalidated")
            continue
        try:
            with open(os.path.join(cache.path, f"{digest}.bin"), "rb") as f:
                blob = f.read()
            compiled = _compile_blob(blob, meta.get("donate_argnums") or ())
        except Exception:  # noqa: BLE001 — a bad blob must not sink startup
            log.exception("aot cache: restore of %s (%s) failed; entry "
                          "skipped", meta.get("fn"), digest)
            stats["failed"] += 1
            continue
        with _STATE_LOCK:
            _RESTORED[digest] = compiled
        stats["restored"] += 1
        _count("aot_cache.restored")
    if any(stats.values()):
        log.info("aot cache warm start (%s): %s", cache.path, stats)
    return stats


def capture(key: AotKey, jitted: Callable, args: Sequence, *,
            donate_argnums=()) -> bool:
    """Export + serialize + store ``jitted`` at ``args``' shapes (once per
    key per process). Costs one re-trace/lower, no compile; failures log
    and return False — capture is an optimization, never a correctness
    dependency."""
    with _STATE_LOCK:
        cache = _ACTIVE
        if cache is None or key.digest() in _CAPTURED:
            return False
        _CAPTURED.add(key.digest())
    try:
        from jax import export as jax_export

        from maskclustering_tpu.analysis import retrace_sanitizer

        _register_pytrees()
        # the export re-lowers the program, which fires a compile-log
        # event of its own — cache machinery, not serving surface, so it
        # runs inside the sanitizer's restore window (otherwise the first
        # real dispatch right after a capture would book a phantom repeat)
        with retrace_sanitizer.restore_window():
            exp = jax_export.export(jitted)(*args)
        blob = exp.serialize()
    except Exception:  # noqa: BLE001 — see docstring
        log.exception("aot cache: export of %s failed; not cached", key.fn)
        return False
    ok = cache.store(key, blob, donate_argnums=donate_argnums)
    if ok:
        # the capturing process can serve from its own export immediately
        # (and a restored executable is what a respawn will run, so the
        # capture run itself pins the restored path's byte-identity)
        try:
            compiled = _compile_blob(blob, donate_argnums)
        except Exception:  # noqa: BLE001 — the jit path still serves
            log.exception("aot cache: self-restore of %s failed", key.fn)
            return ok
        with _STATE_LOCK:
            _RESTORED[key.digest()] = compiled
    return ok


def serving_callable(key: AotKey, jitted: Callable, args: Sequence, *,
                     donate_argnums=()) -> Callable:
    """THE dispatch seam, shared by every serving program's call site
    (models/backprojection.associate_scene, parallel/batch): the restored
    executable when the registry has this key, else the jit path — with
    its export captured (from abstract avals) so the NEXT process starts
    warm. Callers guard with ``active()`` to keep the disarmed hot path
    free of key construction."""
    fn = restored(key)
    if fn is not None:
        return fn
    import jax

    capture(key, jitted,
            [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args],
            donate_argnums=donate_argnums)
    return jitted


def stats_snapshot() -> Dict[str, int]:
    """Process-local registry sizes (the report's cache digest source is
    the obs counters; this is for CLIs/tests)."""
    with _STATE_LOCK:
        return {"restored": len(_RESTORED), "captured": len(_CAPTURED),
                "active": int(_ACTIVE is not None)}
