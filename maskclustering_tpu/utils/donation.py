"""Shared bits for buffer-donating jit programs."""

from __future__ import annotations

import warnings


def suppress_unusable_donation_warning() -> None:
    """Silence jax's once-per-compile "donated buffers were not usable".

    The donating programs in this tree (association frame feed, the
    postprocess group-counts kernel, the fused batch step) donate inputs
    whose shapes rarely match any output, so XLA cannot alias them — the
    donation's value is the EARLY HBM RELEASE at last use, which happens
    either way, and the warning would read as a bug on every first scene.

    Deliberately process-global: the targeted alternative
    (``warnings.catch_warnings`` around each donating dispatch) mutates
    the same interpreter-global filter list and is NOT thread-safe, and
    the overlapped scene executor (run.py) dispatches donating programs
    from two threads concurrently. The filter matches only this exact
    jax message; embedding applications that want the warning back can
    re-enable it after importing this package.

    The suppression is NOT unaudited: mct-check (analysis/ir_checks.py)
    reads the aliasing markers from every donating program's lowering, so
    each unaliased donation is a named IR.DONATION baseline entry with a
    justification, and IR.DONATION.WIRING fails the gate if a
    donate_argnums tuple is dropped from source.
    """
    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable")
