"""Synthetic posed-RGB-D scene generator for tests and benchmarks.

Builds an analytically ray-traced scene of axis-aligned boxes on a floor:
exact depth maps, exact per-pixel object ids, and a surface-sampled scene
point cloud with per-point ground-truth instance labels. Per-frame mask ids
are randomly permuted per frame to emulate an instance segmenter's
arbitrary, frame-inconsistent numbering — exactly the inconsistency the
mask-graph clustering must undo.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class SyntheticScene:
    scene_points: np.ndarray  # (N, 3) float32
    gt_instance: np.ndarray  # (N,) int32, 0 = floor/none, 1..K = boxes
    depths: np.ndarray  # (F, H, W) float32
    segmentations: np.ndarray  # (F, H, W) int32 (per-frame permuted ids)
    object_of_mask: np.ndarray  # (F, K+1) int32: per-frame mask id -> gt object id
    intrinsics: np.ndarray  # (F, 3, 3)
    cam_to_world: np.ndarray  # (F, 4, 4)
    frame_valid: np.ndarray  # (F,) bool
    frame_ids: List[int]
    boxes: np.ndarray  # (K, 2, 3) min/max corners


def _look_at(eye: np.ndarray, target: np.ndarray, up=(0, 0, 1.0)) -> np.ndarray:
    fwd = target - eye
    fwd = fwd / np.linalg.norm(fwd)
    right = np.cross(fwd, up)
    right = right / np.linalg.norm(right)
    down = np.cross(fwd, right)
    c2w = np.eye(4)
    # camera convention: +x right, +y down, +z forward (OpenCV)
    c2w[:3, 0], c2w[:3, 1], c2w[:3, 2], c2w[:3, 3] = right, down, fwd, eye
    return c2w


def _ray_box(o: np.ndarray, d: np.ndarray, bmin: np.ndarray, bmax: np.ndarray):
    """Slab-method ray/AABB intersection. o: (3,), d: (...,3). Returns t or inf.

    bmin/bmax may carry leading batch dims broadcastable against d.
    """
    with np.errstate(divide="ignore", invalid="ignore"):
        t1 = (bmin - o) / d
        t2 = (bmax - o) / d
    tmin = np.minimum(t1, t2).max(axis=-1)
    tmax = np.maximum(t1, t2).min(axis=-1)
    hit = (tmax >= tmin) & (tmax > 0)
    t = np.where(tmin > 0, tmin, tmax)
    return np.where(hit & (t > 0), t, np.inf)


_BOX_HALF_MAX = 0.45  # upper bound of the per-box half extents drawn below


def _place_boxes(k_total: int, room_half: float, rng,
                 min_gap: float = 0.2) -> Tuple[list, float, float]:
    """Grid box placement with a guaranteed minimum inter-box gap.

    Returns ``(boxes [(bmin, bmax)], room_half_eff, scale)``. Centers land
    on a g x g grid; when the requested room packs centers closer than two
    max half-extents + ``min_gap`` — the historical interpenetrating-
    clutter regime at >= ~10 boxes (VERDICT r5 Weak #3), where both
    association paths fragment on fused geometry no segmenter could
    separate — the room scales up just enough that neighboring boxes can
    never touch: separated, reference-like furniture spacing at any box
    count. Callers scale their camera orbit by ``scale`` so the enlarged
    room stays inside the frustum. Geometry is bit-identical to the
    historical layout whenever the requested room already satisfies the
    gap (every default-room scene up to 9 boxes): the rng consumption
    order is unchanged.
    """
    g = max(2, int(np.ceil(np.sqrt(k_total))))
    spacing = 2 * room_half * 0.6 / (g - 1)
    need = 2 * _BOX_HALF_MAX + min_gap
    scale = max(1.0, need / spacing)
    room_half_eff = room_half * scale
    grid = np.linspace(-room_half_eff * 0.6, room_half_eff * 0.6, g)
    centers = [(gx, gy) for gx in grid for gy in grid]
    rng.shuffle(centers)
    boxes = []
    for i in range(k_total):
        cx_, cy_ = centers[i]
        half = rng.uniform(0.25, _BOX_HALF_MAX, size=2)
        height = rng.uniform(0.4, 0.9)
        boxes.append((np.array([cx_ - half[0], cy_ - half[1], 0.0]),
                      np.array([cx_ + half[0], cy_ + half[1], height])))
    return boxes, room_half_eff, scale


def _sample_box_surface(bmin, bmax, spacing, rng) -> np.ndarray:
    pts = []
    ext = bmax - bmin
    for axis in range(3):
        u, v = [a for a in range(3) if a != axis]
        nu = max(2, int(np.ceil(ext[u] / spacing)))
        nv = max(2, int(np.ceil(ext[v] / spacing)))
        gu, gv = np.meshgrid(np.linspace(0, ext[u], nu), np.linspace(0, ext[v], nv))
        sides = (bmin[axis], bmax[axis]) if axis != 2 else (bmax[axis],)
        # bottom face (z = bmin) skipped: coplanar with the floor, never visible
        for side_val in sides:
            p = np.zeros((gu.size, 3))
            p[:, u] = gu.ravel() + bmin[u]
            p[:, v] = gv.ravel() + bmin[v]
            p[:, axis] = side_val
            pts.append(p)
    out = np.concatenate(pts, axis=0)
    return out + rng.normal(scale=spacing * 0.05, size=out.shape)


def make_scene(
    num_boxes: int = 4,
    num_frames: int = 12,
    image_hw: Tuple[int, int] = (96, 128),
    spacing: float = 0.02,
    seed: int = 0,
    room_half: float = 2.0,
    camera_radius: float = 3.2,
    camera_height: float = 2.2,
    ghost_box: bool = False,
    floor_points: bool = True,
    id_permutation: bool = True,
    floor_spacing: Optional[float] = None,
) -> SyntheticScene:
    """Build a synthetic scene.

    ghost_box: adds one box visible in depth/segmentation but absent from
    the scene cloud — its masks must be rejected by the coverage filter.
    """
    rng = np.random.default_rng(seed)
    h, w = image_hw
    fx = fy = 1.1 * max(h, w)
    cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
    intr = np.array([[fx, 0, cx], [0, fy, cy], [0, 0, 1.0]])

    # --- boxes on the floor, separated by construction on a grid ---
    k_total = num_boxes + (1 if ghost_box else 0)
    boxes, room_half, scale = _place_boxes(k_total, room_half, rng)
    # the camera orbit scales with any room expansion so every box stays
    # inside the frustum (similar viewing geometry at any box count)
    camera_radius *= scale
    camera_height *= scale
    boxes_arr = np.array([[b[0], b[1]] for b in boxes])

    # --- scene cloud: sampled surfaces of real boxes (+ floor), labeled ---
    pts, labels = [], []
    for i in range(num_boxes):  # ghost box (index num_boxes) excluded
        p = _sample_box_surface(boxes[i][0], boxes[i][1], spacing, rng)
        pts.append(p)
        labels.append(np.full(len(p), i + 1))
    if floor_points:
        nf = int(2 * room_half / (floor_spacing or spacing))
        gx, gy = np.meshgrid(np.linspace(-room_half, room_half, nf),
                             np.linspace(-room_half, room_half, nf))
        p = np.stack([gx.ravel(), gy.ravel(), np.zeros(gx.size)], axis=1)
        pts.append(p + rng.normal(scale=spacing * 0.05, size=p.shape))
        labels.append(np.zeros(len(p), dtype=np.int64))
    scene_points = np.concatenate(pts).astype(np.float32)
    gt_instance = np.concatenate(labels).astype(np.int32)

    # --- cameras on a circle, looking at the room center ---
    depths = np.zeros((num_frames, h, w), dtype=np.float32)
    segs = np.zeros((num_frames, h, w), dtype=np.int32)
    poses = np.zeros((num_frames, 4, 4), dtype=np.float32)
    intrs = np.tile(intr[None], (num_frames, 1, 1)).astype(np.float32)
    object_of_mask = np.zeros((num_frames, k_total + 1), dtype=np.int32)

    v, u = np.mgrid[0:h, 0:w]
    d_cam = np.stack([(u - cx) / fx, (v - cy) / fy, np.ones_like(u, dtype=np.float64)], axis=-1)

    for f in range(num_frames):
        ang = 2 * np.pi * f / num_frames
        eye = np.array([camera_radius * np.cos(ang), camera_radius * np.sin(ang), camera_height])
        c2w = _look_at(eye, np.array([0, 0, 0.4]))
        poses[f] = c2w
        d_world = d_cam @ c2w[:3, :3].T  # unnormalized; t == camera depth z
        t_best = np.full((h, w), np.inf)
        hit_id = np.zeros((h, w), dtype=np.int32)
        # chunked over boxes: one broadcast slab test per chunk instead of a
        # python loop per box (the loop dominates generation at bench scale)
        bchunk = 8
        for s in range(0, k_total, bchunk):
            bmin = boxes_arr[s : s + bchunk, 0][:, None, None, :]
            bmax = boxes_arr[s : s + bchunk, 1][:, None, None, :]
            t = _ray_box(eye, d_world[None], bmin, bmax)  # (C, h, w)
            ci = np.argmin(t, axis=0)
            tc = np.take_along_axis(t, ci[None], axis=0)[0]
            closer = tc < t_best
            t_best = np.where(closer, tc, t_best)
            hit_id = np.where(closer, s + ci.astype(np.int32) + 1, hit_id)
        # floor plane z=0
        with np.errstate(divide="ignore", invalid="ignore"):
            t_floor = -eye[2] / d_world[..., 2]
        floor_ok = (t_floor > 0) & (t_floor < t_best)
        t_best = np.where(floor_ok, t_floor, t_best)
        hit_id = np.where(floor_ok, 0, hit_id)

        depth = np.where(np.isfinite(t_best), t_best, 0.0).astype(np.float32)
        depths[f] = depth
        # per-frame mask id permutation: emulate frame-inconsistent numbering
        if id_permutation:
            perm = rng.permutation(k_total) + 1
        else:
            perm = np.arange(1, k_total + 1)
        lut = np.zeros(k_total + 1, dtype=np.int32)
        lut[1:] = perm
        segs[f] = lut[hit_id]
        object_of_mask[f, perm] = np.arange(1, k_total + 1)

    return SyntheticScene(
        scene_points=scene_points,
        gt_instance=gt_instance,
        depths=depths,
        segmentations=segs,
        object_of_mask=object_of_mask,
        intrinsics=intrs,
        cam_to_world=poses,
        frame_valid=np.ones(num_frames, dtype=bool),
        frame_ids=list(range(num_frames)),
        boxes=boxes_arr,
    )


def render_depth_seg_device(boxes_arr: np.ndarray, poses: np.ndarray,
                            intrinsics: np.ndarray, perms: np.ndarray,
                            image_hw: Tuple[int, int], box_chunk: int = 8):
    """Analytic box+floor renderer as one jitted program — device-resident.

    Returns (depths (F,H,W) f32, segs (F,H,W) i32) as jax arrays. The bench
    generates at ScanNet scale (250 frames x 480x640) where the numpy path
    takes minutes and, under a tunneled TPU, uploading the rendered frames
    costs more than rendering them in HBM directly.

    Same geometry semantics as make_scene's host renderer: nearest box wins
    (first index on exact ties), floor plane z=0 occludes when closer,
    per-frame mask ids come from ``perms`` (F, K) — entry k is the mask id
    of box k.
    """
    import jax
    import jax.numpy as jnp

    h, w = image_hw
    k_total = boxes_arr.shape[0]
    n_chunks = -(-k_total // box_chunk)
    pad = n_chunks * box_chunk - k_total
    # padded boxes are masked out by index below (the slab test ignores
    # min/max orientation, so a "degenerate" box would still intersect)
    boxes_pad = np.concatenate(
        [boxes_arr, np.zeros((pad, 2, 3))], axis=0
    ).astype(np.float32) if pad else boxes_arr.astype(np.float32)

    @jax.jit
    def render(boxes, poses_, intr_, perms_):
        v, u = jnp.mgrid[0:h, 0:w]

        def one(args):
            c2w, intr, perm = args
            fx, fy = intr[0, 0], intr[1, 1]
            cx, cy = intr[0, 2], intr[1, 2]
            d_cam = jnp.stack([(u - cx) / fx, (v - cy) / fy,
                               jnp.ones((h, w), jnp.float32)], axis=-1)
            d_world = (d_cam.reshape(-1, 3) @ c2w[:3, :3].T)  # (HW, 3)
            eye = c2w[:3, 3]

            def chunk(carry, c):
                t_best, hit = carry
                b = jax.lax.dynamic_slice(boxes, (c * box_chunk, 0, 0),
                                          (box_chunk, 2, 3))
                safe_d = jnp.where(jnp.abs(d_world) < 1e-12, 1e-12, d_world)
                t1 = (b[:, 0][:, None, :] - eye) / safe_d[None]  # (C, HW, 3)
                t2 = (b[:, 1][:, None, :] - eye) / safe_d[None]
                tmin = jnp.minimum(t1, t2).max(axis=-1)
                tmax = jnp.maximum(t1, t2).min(axis=-1)
                real = c * box_chunk + jnp.arange(box_chunk) < k_total
                ok = (tmax >= tmin) & (tmax > 0) & real[:, None]
                t = jnp.where(tmin > 0, tmin, tmax)
                t = jnp.where(ok & (t > 0), t, jnp.inf)
                ci = jnp.argmin(t, axis=0)
                tc = jnp.min(t, axis=0)
                closer = tc < t_best
                return (jnp.where(closer, tc, t_best),
                        jnp.where(closer, c * box_chunk + ci.astype(jnp.int32) + 1,
                                  hit)), None

            init = (jnp.full((h * w,), jnp.inf, jnp.float32),
                    jnp.zeros((h * w,), jnp.int32))
            (t_best, hit), _ = jax.lax.scan(chunk, init, jnp.arange(n_chunks))
            dz = jnp.where(jnp.abs(d_world[:, 2]) < 1e-12, 1e-12, d_world[:, 2])
            t_floor = -eye[2] / dz
            floor_ok = (t_floor > 0) & (t_floor < t_best)
            t_best = jnp.where(floor_ok, t_floor, t_best)
            hit = jnp.where(floor_ok, 0, hit)
            depth = jnp.where(jnp.isfinite(t_best), t_best, 0.0)
            lut = jnp.concatenate([jnp.zeros(1, jnp.int32), perm.astype(jnp.int32)])
            return depth.reshape(h, w), lut[hit].reshape(h, w)

        return jax.lax.map(one, (poses_, intr_, perms_))

    return render(jnp.asarray(boxes_pad), jnp.asarray(poses, dtype=jnp.float32),
                  jnp.asarray(intrinsics, dtype=jnp.float32),
                  jnp.asarray(perms, dtype=jnp.int32))


def make_scene_device(
    num_boxes: int = 36,
    num_frames: int = 250,
    image_hw: Tuple[int, int] = (480, 640),
    spacing: float = 0.025,
    floor_spacing: Optional[float] = 0.05,
    seed: int = 0,
    room_half: float = 4.0,
    camera_radius: float = 5.0,
    camera_height: float = 2.5,
):
    """Bench-scale synthetic scene with device-resident depth/seg frames.

    Host builds the cheap parts (boxes, surface cloud, poses, per-frame id
    permutations); the frame renderer runs jitted on the accelerator.
    Returns (SceneTensors, gt_instance, object_of_mask).
    """
    rng = np.random.default_rng(seed)
    h, w = image_hw
    fx = fy = 1.1 * max(h, w)
    cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
    intr = np.array([[fx, 0, cx], [0, fy, cy], [0, 0, 1.0]], dtype=np.float32)

    boxes, room_half, scale = _place_boxes(num_boxes, room_half, rng)
    camera_radius *= scale
    camera_height *= scale
    boxes_arr = np.array([[b[0], b[1]] for b in boxes])

    pts, labels = [], []
    for i in range(num_boxes):
        p = _sample_box_surface(boxes[i][0], boxes[i][1], spacing, rng)
        pts.append(p)
        labels.append(np.full(len(p), i + 1))
    nf = int(2 * room_half / (floor_spacing or spacing))
    gx, gy = np.meshgrid(np.linspace(-room_half, room_half, nf),
                         np.linspace(-room_half, room_half, nf))
    p = np.stack([gx.ravel(), gy.ravel(), np.zeros(gx.size)], axis=1)
    pts.append(p + rng.normal(scale=spacing * 0.05, size=p.shape))
    labels.append(np.zeros(len(p), dtype=np.int64))
    scene_points = np.concatenate(pts).astype(np.float32)
    gt_instance = np.concatenate(labels).astype(np.int32)

    poses = np.zeros((num_frames, 4, 4), dtype=np.float32)
    perms = np.zeros((num_frames, num_boxes), dtype=np.int32)
    object_of_mask = np.zeros((num_frames, num_boxes + 1), dtype=np.int32)
    for f in range(num_frames):
        ang = 2 * np.pi * f / num_frames
        eye = np.array([camera_radius * np.cos(ang),
                        camera_radius * np.sin(ang), camera_height])
        poses[f] = _look_at(eye, np.array([0, 0, 0.4]))
        perm = rng.permutation(num_boxes) + 1
        perms[f] = perm
        object_of_mask[f, perm] = np.arange(1, num_boxes + 1)
    intrs = np.tile(intr[None], (num_frames, 1, 1))

    depths, segs = render_depth_seg_device(boxes_arr, poses, intrs, perms, image_hw)

    from maskclustering_tpu.datasets.base import SceneTensors

    tensors = SceneTensors(
        scene_points=scene_points,
        depths=depths,
        segmentations=segs,
        intrinsics=intrs,
        cam_to_world=poses,
        frame_valid=np.ones(num_frames, dtype=bool),
        frame_ids=list(range(num_frames)),
    )
    return tensors, gt_instance, object_of_mask


def visibility_count(scene: SyntheticScene, tol: float = 0.03) -> np.ndarray:
    """#frames in which each scene point passes the z-buffer test at its pixel."""
    n = len(scene.scene_points)
    count = np.zeros(n, dtype=np.int32)
    for f in range(len(scene.depths)):
        c2w = scene.cam_to_world[f].astype(np.float64)
        w2c = np.linalg.inv(c2w)
        cam = scene.scene_points @ w2c[:3, :3].T + w2c[:3, 3]
        fx, fy = scene.intrinsics[f][0, 0], scene.intrinsics[f][1, 1]
        cx, cy = scene.intrinsics[f][0, 2], scene.intrinsics[f][1, 2]
        h, w = scene.depths[f].shape
        z = cam[:, 2]
        ok = z > 1e-6
        u = np.round(np.where(ok, cam[:, 0] / np.where(ok, z, 1) * fx + cx, -1)).astype(int)
        v = np.round(np.where(ok, cam[:, 1] / np.where(ok, z, 1) * fy + cy, -1)).astype(int)
        inb = ok & (u >= 0) & (u < w) & (v >= 0) & (v < h)
        d = np.zeros(n)
        d[inb] = scene.depths[f][v[inb], u[inb]]
        count += (inb & (d > 0) & (np.abs(z - d) <= tol)).astype(np.int32)
    return count


def resize_scene_points(points: np.ndarray, n: int, seed: int = 0) -> np.ndarray:
    """Pad/trim a synthetic cloud to a static benchmark size.

    Undersized clouds tile (harmless duplicate points); oversized clouds take
    a seeded uniform subsample. Shared by every measurement script (bench,
    northstar, mesh_bench, profile_*, claims_diag) so they all resample the
    same way and benchmark the same cloud for a given seed.
    """
    if points.shape[0] < n:
        points = np.tile(points, (-(-n // points.shape[0]), 1))[:n]
    elif points.shape[0] > n:
        idx = np.random.default_rng(seed).choice(points.shape[0], n,
                                                 replace=False)
        points = points[idx]
    return np.ascontiguousarray(points, dtype=np.float32)


def to_scene_tensors(scene: SyntheticScene):
    from maskclustering_tpu.datasets.base import SceneTensors

    return SceneTensors(
        scene_points=scene.scene_points,
        depths=scene.depths,
        segmentations=scene.segmentations,
        intrinsics=scene.intrinsics,
        cam_to_world=scene.cam_to_world,
        frame_valid=scene.frame_valid,
        frame_ids=scene.frame_ids,
    )


def write_scannet_layout(scene: SyntheticScene, data_root: str, seq_name: str,
                         gt_label_id: int = 3) -> str:
    """Materialize a synthetic scene on disk in the ScanNet processed layout.

    Produces everything the ScanNetDataset loader and the orchestrator need:
    color/ depth/ pose/ intrinsic/ output/mask/ + the vh_clean_2 ply, plus a
    benchmark GT txt (label*1000 + inst + 1; unannotated floor = 1) under
    ``data/scannet/gt``. Used by end-to-end tests in place of real scans.
    """
    import os

    from PIL import Image

    from maskclustering_tpu.io.image import write_depth_png, write_mask_png
    from maskclustering_tpu.io.ply import write_ply_points

    root = os.path.join(data_root, "scannet", "processed", seq_name)
    for sub in ("color", "depth", "pose", "intrinsic", os.path.join("output", "mask")):
        os.makedirs(os.path.join(root, sub), exist_ok=True)
    intr4 = np.eye(4)
    intr4[:3, :3] = scene.intrinsics[0]
    np.savetxt(os.path.join(root, "intrinsic", "intrinsic_depth.txt"), intr4)
    for f, fid in enumerate(scene.frame_ids):
        write_depth_png(os.path.join(root, "depth", f"{fid}.png"),
                        scene.depths[f] * 1000.0)
        seg = scene.segmentations[f]
        write_mask_png(os.path.join(root, "output", "mask", f"{fid}.png"), seg)
        rgb = np.stack([(seg * 40 % 256).astype(np.uint8)] * 3, axis=-1)
        Image.fromarray(rgb).save(os.path.join(root, "color", f"{fid}.jpg"))
        np.savetxt(os.path.join(root, "pose", f"{fid}.txt"),
                   scene.cam_to_world[f].astype(np.float64))
    write_ply_points(os.path.join(root, f"{seq_name}_vh_clean_2.ply"),
                     scene.scene_points)
    gt_dir = os.path.join(data_root, "scannet", "gt")
    os.makedirs(gt_dir, exist_ok=True)
    gt = np.where(scene.gt_instance > 0,
                  gt_label_id * 1000 + scene.gt_instance + 1, 1)
    np.savetxt(os.path.join(gt_dir, f"{seq_name}.txt"), gt, fmt="%d")
    return root
