"""Host-side utilities (synthetic data, profiling, misc helpers)."""
