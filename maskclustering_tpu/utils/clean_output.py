"""Remove per-scene output dirs across a split (reference utils/clean_all_output.py:9-25).

Deletes ``<scene>/output`` (masks + object dicts) for every scene of a
dataset split so a benchmark run can start clean. Dry-run by default from
the CLI to avoid the reference's silent rm -r behavior.

Usage: python -m maskclustering_tpu.utils.clean_output --config scannet [--yes]
"""

from __future__ import annotations

import argparse
import os
import shutil
from typing import List, Optional, Sequence


def clean_scene_outputs(cfg, seq_names: Sequence[str],
                        dry_run: bool = False) -> List[str]:
    """Remove each scene's output dir; returns the paths (to be) removed."""
    from maskclustering_tpu.datasets import get_dataset

    removed = []
    for seq in seq_names:
        ds = get_dataset(cfg.dataset, seq, data_root=cfg.data_root)
        out_dir = os.path.join(ds.root, "output")
        if os.path.isdir(out_dir):
            removed.append(out_dir)
            if not dry_run:
                shutil.rmtree(out_dir)
    return removed


def main(argv: Optional[Sequence[str]] = None) -> None:
    from maskclustering_tpu.config import load_config
    from maskclustering_tpu.run import get_seq_name_list

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", required=True)
    parser.add_argument("--seq_name_list", default=None,
                        help="+-joined scene names (defaults to the split file)")
    parser.add_argument("--yes", action="store_true",
                        help="actually delete (default: dry-run listing)")
    args = parser.parse_args(argv)
    cfg = load_config(args.config)
    seqs = get_seq_name_list(cfg.dataset, seq_name_list=args.seq_name_list)
    removed = clean_scene_outputs(cfg, seqs, dry_run=not args.yes)
    verb = "removed" if args.yes else "would remove"
    for path in removed:
        print(f"{verb} {path}")
    print(f"{verb} {len(removed)} scene output dirs")


if __name__ == "__main__":
    main()
