// Host-side native runtime for maskclustering_tpu.
//
// The reference delegates these to Open3D's C++ core (cluster_dbscan,
// remove_statistical_outlier) and to networkx (connected components). Here
// they are implemented directly: a uniform-grid-accelerated DBSCAN, a
// union-find over edge lists, and a grid-accelerated statistical outlier
// filter. Exposed as a C ABI for ctypes.
//
// Build: python -m maskclustering_tpu.native.build

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <functional>
#include <limits>
#include <unordered_map>
#include <vector>

namespace {

struct CellKey {
    int64_t x, y, z;
    bool operator==(const CellKey& o) const { return x == o.x && y == o.y && z == o.z; }
};

struct CellHash {
    size_t operator()(const CellKey& k) const {
        return static_cast<size_t>(k.x * 73856093LL ^ k.y * 19349663LL ^ k.z * 83492791LL);
    }
};

class UniformGrid {
  public:
    UniformGrid(const double* pts, int64_t n, double cell) : pts_(pts), n_(n), cell_(cell) {
        cells_.reserve(static_cast<size_t>(n));
        for (int64_t i = 0; i < n; ++i) {
            cells_[key_of(i)].push_back(i);
        }
    }

    CellKey key_of(int64_t i) const {
        return CellKey{static_cast<int64_t>(std::floor(pts_[3 * i] / cell_)),
                       static_cast<int64_t>(std::floor(pts_[3 * i + 1] / cell_)),
                       static_cast<int64_t>(std::floor(pts_[3 * i + 2] / cell_))};
    }

    // visit points within a ring of cells at Chebyshev distance r
    template <typename F>
    void for_ring(const CellKey& c, int64_t r, F&& f) const {
        for (int64_t dx = -r; dx <= r; ++dx)
            for (int64_t dy = -r; dy <= r; ++dy)
                for (int64_t dz = -r; dz <= r; ++dz) {
                    if (std::max({dx < 0 ? -dx : dx, dy < 0 ? -dy : dy, dz < 0 ? -dz : dz}) != r)
                        continue;
                    auto it = cells_.find(CellKey{c.x + dx, c.y + dy, c.z + dz});
                    if (it == cells_.end()) continue;
                    for (int64_t j : it->second) f(j);
                }
    }

    const double* pts_;
    int64_t n_;
    double cell_;
    std::unordered_map<CellKey, std::vector<int64_t>, CellHash> cells_;
};

inline double dist2(const double* pts, int64_t i, int64_t j) {
    double dx = pts[3 * i] - pts[3 * j];
    double dy = pts[3 * i + 1] - pts[3 * j + 1];
    double dz = pts[3 * i + 2] - pts[3 * j + 2];
    return dx * dx + dy * dy + dz * dz;
}

}  // namespace

extern "C" {

// DBSCAN on a uniform grid of cells with side eps/sqrt(3): the cell diagonal
// is eps, so any two points sharing a cell are neighbors with NO distance
// test — a cell holding >= min_points is all-core for free, and all core
// points of one cell belong to one cluster. Clustering then reduces to a
// union-find over cells (early-exit pair scans connect neighboring cells),
// which stays near-linear in dense clouds where the per-point neighbor-list
// formulation degenerates to O(n * density * eps^3).
// labels: -1 noise, clusters numbered 0.. in order of their lowest core
// point index; border points take the lowest neighboring cluster label —
// both identical to the BFS formulation (and to sklearn/Open3D's scan
// order, which seeds clusters at ascending unvisited core indices).
// min_points includes the point itself (Open3D cluster_dbscan contract).
int mc_dbscan(const double* pts, int64_t n, double eps, int min_points, int64_t* labels) {
    if (n <= 0) return 0;
    const double eps2 = eps * eps;
    const double cell = eps / std::sqrt(3.0);

    // cells: key -> dense cell id; CSR-ish point lists per cell
    std::unordered_map<CellKey, int64_t, CellHash> cell_id;
    cell_id.reserve(static_cast<size_t>(n));
    std::vector<std::vector<int64_t>> cell_pts;
    std::vector<int64_t> cid_of(n);
    std::vector<CellKey> key_of_cell;
    for (int64_t i = 0; i < n; ++i) {
        CellKey k{static_cast<int64_t>(std::floor(pts[3 * i] / cell)),
                  static_cast<int64_t>(std::floor(pts[3 * i + 1] / cell)),
                  static_cast<int64_t>(std::floor(pts[3 * i + 2] / cell))};
        auto it = cell_id.find(k);
        int64_t c;
        if (it == cell_id.end()) {
            c = static_cast<int64_t>(cell_pts.size());
            cell_id.emplace(k, c);
            cell_pts.emplace_back();
            key_of_cell.push_back(k);
        } else {
            c = it->second;
        }
        cell_pts[c].push_back(i);
        cid_of[i] = c;
    }
    const int64_t n_cells = static_cast<int64_t>(cell_pts.size());

    // neighbor cell offsets: two points within eps sit at most 2 cells apart
    // on each axis (eps / (eps/sqrt(3)) = sqrt(3) < 2); every offset in
    // [-2,2]^3 has min inter-cell distance <= eps, so none can be pruned.
    auto cell_at = [&](const CellKey& k, int64_t dx, int64_t dy, int64_t dz) -> int64_t {
        auto it = cell_id.find(CellKey{k.x + dx, k.y + dy, k.z + dz});
        return it == cell_id.end() ? -1 : it->second;
    };

    // ---- core determination (early exit at min_points) ----
    std::vector<uint8_t> core(n, 0);
    std::vector<std::vector<int64_t>> core_in_cell(n_cells);
    for (int64_t c = 0; c < n_cells; ++c) {
        const auto& mine = cell_pts[c];
        if (static_cast<int>(mine.size()) >= min_points) {
            for (int64_t i : mine) core[i] = 1;  // in-cell pairs are all <= eps
        } else {
            const CellKey k = key_of_cell[c];
            for (int64_t i : mine) {
                int cnt = static_cast<int>(mine.size());  // incl. self, all in range
                for (int64_t dx = -2; dx <= 2 && cnt < min_points; ++dx)
                    for (int64_t dy = -2; dy <= 2 && cnt < min_points; ++dy)
                        for (int64_t dz = -2; dz <= 2 && cnt < min_points; ++dz) {
                            if (dx == 0 && dy == 0 && dz == 0) continue;
                            int64_t nb = cell_at(k, dx, dy, dz);
                            if (nb < 0) continue;
                            for (int64_t j : cell_pts[nb]) {
                                if (dist2(pts, i, j) <= eps2 && ++cnt >= min_points) break;
                            }
                        }
                core[i] = cnt >= min_points;
            }
        }
        for (int64_t i : mine)
            if (core[i]) core_in_cell[c].push_back(i);
    }

    // ---- union-find over cells holding core points ----
    std::vector<int64_t> parent(n_cells);
    for (int64_t c = 0; c < n_cells; ++c) parent[c] = c;
    std::function<int64_t(int64_t)> find = [&](int64_t x) {
        while (parent[x] != x) {
            parent[x] = parent[parent[x]];
            x = parent[x];
        }
        return x;
    };
    for (int64_t c = 0; c < n_cells; ++c) {
        if (core_in_cell[c].empty()) continue;
        const CellKey k = key_of_cell[c];
        for (int64_t dx = -2; dx <= 2; ++dx)
            for (int64_t dy = -2; dy <= 2; ++dy)
                for (int64_t dz = -2; dz <= 2; ++dz) {
                    // half-space: visit each unordered cell pair once
                    if (dx < 0 || (dx == 0 && (dy < 0 || (dy == 0 && dz <= 0)))) continue;
                    int64_t nb = cell_at(k, dx, dy, dz);
                    if (nb < 0 || core_in_cell[nb].empty()) continue;
                    int64_t ra = find(c), rb = find(nb);
                    if (ra == rb) continue;
                    for (int64_t a : core_in_cell[c]) {
                        bool linked = false;
                        for (int64_t b : core_in_cell[nb]) {
                            if (dist2(pts, a, b) <= eps2) {
                                parent[std::max(ra, rb)] = std::min(ra, rb);
                                linked = true;
                                break;
                            }
                        }
                        if (linked) break;
                    }
                }
    }

    // ---- labels: clusters numbered by ascending lowest core index ----
    std::vector<int64_t> root_label(n_cells, -1);
    int64_t next = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (!core[i]) {
            labels[i] = -1;
            continue;
        }
        int64_t r = find(cid_of[i]);
        if (root_label[r] == -1) root_label[r] = next++;
        labels[i] = root_label[r];
    }

    // ---- border points: lowest cluster label among in-range core points.
    // All core points of one cell share a label, so one in-range hit per
    // neighbor cell suffices; the own cell needs no distance test at all.
    for (int64_t i = 0; i < n; ++i) {
        if (core[i]) continue;
        int64_t best = std::numeric_limits<int64_t>::max();
        const int64_t c = cid_of[i];
        if (!core_in_cell[c].empty()) best = root_label[find(c)];
        const CellKey k = key_of_cell[c];
        for (int64_t dx = -2; dx <= 2; ++dx)
            for (int64_t dy = -2; dy <= 2; ++dy)
                for (int64_t dz = -2; dz <= 2; ++dz) {
                    if (dx == 0 && dy == 0 && dz == 0) continue;
                    int64_t nb = cell_at(k, dx, dy, dz);
                    if (nb < 0 || core_in_cell[nb].empty()) continue;
                    int64_t lab = root_label[find(nb)];
                    if (lab >= best) continue;
                    for (int64_t b : core_in_cell[nb]) {
                        if (dist2(pts, i, b) <= eps2) {
                            best = lab;
                            break;
                        }
                    }
                }
        if (best != std::numeric_limits<int64_t>::max()) labels[i] = best;
    }
    return 0;
}

// Union-find connected components over an edge list; out[i] = min index in
// component of i.
int mc_connected_components(const int64_t* ea, const int64_t* eb, int64_t n_edges,
                            int64_t n_nodes, int64_t* out) {
    std::vector<int64_t> parent(n_nodes);
    for (int64_t i = 0; i < n_nodes; ++i) parent[i] = i;
    std::function<int64_t(int64_t)> find = [&](int64_t x) {
        while (parent[x] != x) {
            parent[x] = parent[parent[x]];
            x = parent[x];
        }
        return x;
    };
    for (int64_t e = 0; e < n_edges; ++e) {
        int64_t a = ea[e], b = eb[e];
        if (a < 0 || b < 0 || a >= n_nodes || b >= n_nodes) return 1;
        int64_t ra = find(a), rb = find(b);
        if (ra != rb) parent[std::max(ra, rb)] = std::min(ra, rb);
    }
    for (int64_t i = 0; i < n_nodes; ++i) out[i] = find(i);
    return 0;
}

// Statistical outlier removal (Open3D remove_statistical_outlier):
// keep[i] = mean distance to k nearest neighbors <= mean + std_ratio * std
// over all points' mean-knn-distances.
int mc_statistical_outliers(const double* pts, int64_t n, int nb_neighbors,
                            double std_ratio, uint8_t* keep) {
    if (n <= 0) return 0;
    int k = nb_neighbors;
    if (k >= n) k = static_cast<int>(n - 1);
    if (k <= 0) {
        std::fill(keep, keep + n, 1);
        return 0;
    }
    // heuristic cell: aim for a few points per cell
    double minv[3] = {pts[0], pts[1], pts[2]}, maxv[3] = {pts[0], pts[1], pts[2]};
    for (int64_t i = 1; i < n; ++i)
        for (int d = 0; d < 3; ++d) {
            minv[d] = std::min(minv[d], pts[3 * i + d]);
            maxv[d] = std::max(maxv[d], pts[3 * i + d]);
        }
    double vol = std::max((maxv[0] - minv[0]) * (maxv[1] - minv[1]) * (maxv[2] - minv[2]), 1e-12);
    double cell = std::max(std::cbrt(vol / static_cast<double>(n)) * 1.5, 1e-9);
    UniformGrid grid(pts, n, cell);

    std::vector<double> mean_d(n);
    std::vector<double> best;
    const int64_t max_ring =
        2 + static_cast<int64_t>(std::ceil(std::cbrt(vol) / cell));  // spans the bbox
    for (int64_t i = 0; i < n; ++i) {
        best.clear();
        CellKey c = grid.key_of(i);
        // expand rings until no unvisited cell can hold a closer point: a
        // cell at Chebyshev ring r+1 is at Euclidean distance >= r*cell
        // from anywhere inside the query's own cell, so once the current
        // k-th smallest distance d_k satisfies d_k <= r*cell we are done.
        for (int64_t r = 0; r <= max_ring; ++r) {
            grid.for_ring(c, r, [&](int64_t j) {
                if (j != i) best.push_back(dist2(pts, i, j));
            });
            if (static_cast<int64_t>(best.size()) >= k) {
                std::nth_element(best.begin(), best.begin() + (k - 1), best.end());
                double dk2 = best[k - 1];
                double guard = static_cast<double>(r) * cell;
                if (dk2 <= guard * guard) break;
            }
        }
        if (static_cast<int64_t>(best.size()) < k) {
            // isolated: use what we have (or mark as outlier via huge distance)
            if (best.empty()) {
                mean_d[i] = std::numeric_limits<double>::infinity();
                continue;
            }
        }
        size_t kk = std::min<size_t>(k, best.size());
        std::partial_sort(best.begin(), best.begin() + kk, best.end());
        double s = 0;
        for (size_t t = 0; t < kk; ++t) s += std::sqrt(best[t]);
        mean_d[i] = s / static_cast<double>(kk);
    }
    double mu = 0;
    int64_t cnt = 0;
    for (int64_t i = 0; i < n; ++i)
        if (std::isfinite(mean_d[i])) {
            mu += mean_d[i];
            ++cnt;
        }
    mu /= std::max<int64_t>(cnt, 1);
    double var = 0;
    for (int64_t i = 0; i < n; ++i)
        if (std::isfinite(mean_d[i])) var += (mean_d[i] - mu) * (mean_d[i] - mu);
    double sigma = std::sqrt(var / std::max<int64_t>(cnt, 1));
    double cutoff = mu + std_ratio * sigma;
    for (int64_t i = 0; i < n; ++i) keep[i] = mean_d[i] <= cutoff ? 1 : 0;
    return 0;
}

}  // extern "C"
