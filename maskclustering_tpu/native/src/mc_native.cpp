// Host-side native runtime for maskclustering_tpu.
//
// The reference delegates these to Open3D's C++ core (cluster_dbscan,
// remove_statistical_outlier) and to networkx (connected components). Here
// they are implemented directly: a uniform-grid-accelerated DBSCAN, a
// union-find over edge lists, and a grid-accelerated statistical outlier
// filter. Exposed as a C ABI for ctypes.
//
// Build: python -m maskclustering_tpu.native.build

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <functional>
#include <limits>
#include <queue>
#include <unordered_map>
#include <vector>

namespace {

struct CellKey {
    int64_t x, y, z;
    bool operator==(const CellKey& o) const { return x == o.x && y == o.y && z == o.z; }
};

struct CellHash {
    size_t operator()(const CellKey& k) const {
        return static_cast<size_t>(k.x * 73856093LL ^ k.y * 19349663LL ^ k.z * 83492791LL);
    }
};

class UniformGrid {
  public:
    UniformGrid(const double* pts, int64_t n, double cell) : pts_(pts), n_(n), cell_(cell) {
        cells_.reserve(static_cast<size_t>(n));
        for (int64_t i = 0; i < n; ++i) {
            cells_[key_of(i)].push_back(i);
        }
    }

    CellKey key_of(int64_t i) const {
        return CellKey{static_cast<int64_t>(std::floor(pts_[3 * i] / cell_)),
                       static_cast<int64_t>(std::floor(pts_[3 * i + 1] / cell_)),
                       static_cast<int64_t>(std::floor(pts_[3 * i + 2] / cell_))};
    }

    // visit every point in the 27-cell neighborhood of point i
    template <typename F>
    void for_neighborhood(int64_t i, F&& f) const {
        CellKey c = key_of(i);
        for (int64_t dx = -1; dx <= 1; ++dx)
            for (int64_t dy = -1; dy <= 1; ++dy)
                for (int64_t dz = -1; dz <= 1; ++dz) {
                    auto it = cells_.find(CellKey{c.x + dx, c.y + dy, c.z + dz});
                    if (it == cells_.end()) continue;
                    for (int64_t j : it->second) f(j);
                }
    }

    // visit points within a ring of cells at Chebyshev distance r
    template <typename F>
    void for_ring(const CellKey& c, int64_t r, F&& f) const {
        for (int64_t dx = -r; dx <= r; ++dx)
            for (int64_t dy = -r; dy <= r; ++dy)
                for (int64_t dz = -r; dz <= r; ++dz) {
                    if (std::max({dx < 0 ? -dx : dx, dy < 0 ? -dy : dy, dz < 0 ? -dz : dz}) != r)
                        continue;
                    auto it = cells_.find(CellKey{c.x + dx, c.y + dy, c.z + dz});
                    if (it == cells_.end()) continue;
                    for (int64_t j : it->second) f(j);
                }
    }

    const double* pts_;
    int64_t n_;
    double cell_;
    std::unordered_map<CellKey, std::vector<int64_t>, CellHash> cells_;
};

inline double dist2(const double* pts, int64_t i, int64_t j) {
    double dx = pts[3 * i] - pts[3 * j];
    double dy = pts[3 * i + 1] - pts[3 * j + 1];
    double dz = pts[3 * i + 2] - pts[3 * j + 2];
    return dx * dx + dy * dy + dz * dz;
}

}  // namespace

extern "C" {

// DBSCAN with eps-radius neighborhoods on a uniform grid (cell = eps).
// labels: -1 noise, clusters numbered 0.. in order of first core discovery
// (Open3D cluster_dbscan contract; min_points includes the point itself).
int mc_dbscan(const double* pts, int64_t n, double eps, int min_points, int64_t* labels) {
    if (n <= 0) return 0;
    UniformGrid grid(pts, n, eps);
    const double eps2 = eps * eps;

    std::vector<std::vector<int64_t>> neigh(n);
    std::vector<uint8_t> core(n, 0);
    for (int64_t i = 0; i < n; ++i) {
        auto& ni = neigh[i];
        grid.for_neighborhood(i, [&](int64_t j) {
            if (dist2(pts, i, j) <= eps2) ni.push_back(j);  // includes self
        });
        core[i] = ni.size() >= static_cast<size_t>(min_points);
    }

    std::fill(labels, labels + n, -1);
    int64_t next = 0;
    std::queue<int64_t> q;
    for (int64_t i = 0; i < n; ++i) {
        if (!core[i] || labels[i] != -1) continue;
        int64_t lab = next++;
        labels[i] = lab;
        q.push(i);
        while (!q.empty()) {
            int64_t u = q.front();
            q.pop();
            for (int64_t v : neigh[u]) {
                if (labels[v] != -1) continue;
                labels[v] = lab;
                if (core[v]) q.push(v);
            }
        }
    }
    return 0;
}

// Union-find connected components over an edge list; out[i] = min index in
// component of i.
int mc_connected_components(const int64_t* ea, const int64_t* eb, int64_t n_edges,
                            int64_t n_nodes, int64_t* out) {
    std::vector<int64_t> parent(n_nodes);
    for (int64_t i = 0; i < n_nodes; ++i) parent[i] = i;
    std::function<int64_t(int64_t)> find = [&](int64_t x) {
        while (parent[x] != x) {
            parent[x] = parent[parent[x]];
            x = parent[x];
        }
        return x;
    };
    for (int64_t e = 0; e < n_edges; ++e) {
        int64_t a = ea[e], b = eb[e];
        if (a < 0 || b < 0 || a >= n_nodes || b >= n_nodes) return 1;
        int64_t ra = find(a), rb = find(b);
        if (ra != rb) parent[std::max(ra, rb)] = std::min(ra, rb);
    }
    for (int64_t i = 0; i < n_nodes; ++i) out[i] = find(i);
    return 0;
}

// Statistical outlier removal (Open3D remove_statistical_outlier):
// keep[i] = mean distance to k nearest neighbors <= mean + std_ratio * std
// over all points' mean-knn-distances.
int mc_statistical_outliers(const double* pts, int64_t n, int nb_neighbors,
                            double std_ratio, uint8_t* keep) {
    if (n <= 0) return 0;
    int k = nb_neighbors;
    if (k >= n) k = static_cast<int>(n - 1);
    if (k <= 0) {
        std::fill(keep, keep + n, 1);
        return 0;
    }
    // heuristic cell: aim for a few points per cell
    double minv[3] = {pts[0], pts[1], pts[2]}, maxv[3] = {pts[0], pts[1], pts[2]};
    for (int64_t i = 1; i < n; ++i)
        for (int d = 0; d < 3; ++d) {
            minv[d] = std::min(minv[d], pts[3 * i + d]);
            maxv[d] = std::max(maxv[d], pts[3 * i + d]);
        }
    double vol = std::max((maxv[0] - minv[0]) * (maxv[1] - minv[1]) * (maxv[2] - minv[2]), 1e-12);
    double cell = std::max(std::cbrt(vol / static_cast<double>(n)) * 1.5, 1e-9);
    UniformGrid grid(pts, n, cell);

    std::vector<double> mean_d(n);
    std::vector<double> best;
    const int64_t max_ring =
        2 + static_cast<int64_t>(std::ceil(std::cbrt(vol) / cell));  // spans the bbox
    for (int64_t i = 0; i < n; ++i) {
        best.clear();
        CellKey c = grid.key_of(i);
        // expand rings until no unvisited cell can hold a closer point: a
        // cell at Chebyshev ring r+1 is at Euclidean distance >= r*cell
        // from anywhere inside the query's own cell, so once the current
        // k-th smallest distance d_k satisfies d_k <= r*cell we are done.
        for (int64_t r = 0; r <= max_ring; ++r) {
            grid.for_ring(c, r, [&](int64_t j) {
                if (j != i) best.push_back(dist2(pts, i, j));
            });
            if (static_cast<int64_t>(best.size()) >= k) {
                std::nth_element(best.begin(), best.begin() + (k - 1), best.end());
                double dk2 = best[k - 1];
                double guard = static_cast<double>(r) * cell;
                if (dk2 <= guard * guard) break;
            }
        }
        if (static_cast<int64_t>(best.size()) < k) {
            // isolated: use what we have (or mark as outlier via huge distance)
            if (best.empty()) {
                mean_d[i] = std::numeric_limits<double>::infinity();
                continue;
            }
        }
        size_t kk = std::min<size_t>(k, best.size());
        std::partial_sort(best.begin(), best.begin() + kk, best.end());
        double s = 0;
        for (size_t t = 0; t < kk; ++t) s += std::sqrt(best[t]);
        mean_d[i] = s / static_cast<double>(kk);
    }
    double mu = 0;
    int64_t cnt = 0;
    for (int64_t i = 0; i < n; ++i)
        if (std::isfinite(mean_d[i])) {
            mu += mean_d[i];
            ++cnt;
        }
    mu /= std::max<int64_t>(cnt, 1);
    double var = 0;
    for (int64_t i = 0; i < n; ++i)
        if (std::isfinite(mean_d[i])) var += (mean_d[i] - mu) * (mean_d[i] - mu);
    double sigma = std::sqrt(var / std::max<int64_t>(cnt, 1));
    double cutoff = mu + std_ratio * sigma;
    for (int64_t i = 0; i < n; ++i) keep[i] = mean_d[i] <= cutoff ? 1 : 0;
    return 0;
}

}  // extern "C"
