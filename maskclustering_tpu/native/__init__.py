"""Native C++ host-side runtime components (ctypes bindings).

Provides grid-accelerated DBSCAN, union-find connected components, and
statistical-outlier removal as a shared library for the host-side parts of
the pipeline (the reference gets these from Open3D's C++ core). Build with
``python -m maskclustering_tpu.native.build``; all entry points degrade
gracefully to Python/sklearn fallbacks when the library isn't built.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "libmc_native.so")
    if not os.path.exists(path):
        return None
    lib = ctypes.CDLL(path)
    lib.mc_dbscan.restype = ctypes.c_int
    lib.mc_dbscan.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.c_double, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.mc_connected_components.restype = ctypes.c_int
    lib.mc_connected_components.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
    ]
    lib.mc_statistical_outliers.restype = ctypes.c_int
    lib.mc_statistical_outliers.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.c_int, ctypes.c_double, ctypes.POINTER(ctypes.c_uint8),
    ]
    _LIB = lib
    return lib


def native_available() -> bool:
    return _load() is not None


def native_dbscan(points: np.ndarray, eps: float, min_points: int) -> np.ndarray:
    """Grid-accelerated DBSCAN; labels with -1 noise, clusters ordered by
    first-seen core point (matches Open3D's contract)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built")
    points = np.ascontiguousarray(points, dtype=np.float64)
    n = len(points)
    labels = np.empty(n, dtype=np.int64)
    rc = lib.mc_dbscan(
        points.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n,
        ctypes.c_double(eps), ctypes.c_int(min_points),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc != 0:
        raise RuntimeError(f"mc_dbscan failed with code {rc}")
    return labels


def native_connected_components(edges_a: np.ndarray, edges_b: np.ndarray,
                                num_nodes: int) -> np.ndarray:
    """Union-find connected components over an edge list."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built")
    edges_a = np.ascontiguousarray(edges_a, dtype=np.int64)
    edges_b = np.ascontiguousarray(edges_b, dtype=np.int64)
    out = np.empty(num_nodes, dtype=np.int64)
    rc = lib.mc_connected_components(
        edges_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        edges_b.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(edges_a), num_nodes,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc != 0:
        raise RuntimeError(f"mc_connected_components failed with code {rc}")
    return out


def native_statistical_outliers(points: np.ndarray, nb_neighbors: int = 20,
                                std_ratio: float = 2.0) -> np.ndarray:
    """Inlier mask per Open3D remove_statistical_outlier semantics."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built")
    points = np.ascontiguousarray(points, dtype=np.float64)
    n = len(points)
    keep = np.empty(n, dtype=np.uint8)
    rc = lib.mc_statistical_outliers(
        points.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n,
        ctypes.c_int(nb_neighbors), ctypes.c_double(std_ratio),
        keep.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if rc != 0:
        raise RuntimeError(f"mc_statistical_outliers failed with code {rc}")
    return keep.astype(bool)
