"""Build the native C++ runtime library: python -m maskclustering_tpu.native.build"""

from __future__ import annotations

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "src", "mc_native.cpp")
OUT = os.path.join(_DIR, "libmc_native.so")


def build(force: bool = False) -> str:
    if not force and os.path.exists(OUT) and os.path.getmtime(OUT) >= os.path.getmtime(SRC):
        return OUT
    cmd = [
        os.environ.get("CXX", "g++"), "-O3", "-std=c++17", "-shared", "-fPIC",
        "-march=native", SRC, "-o", OUT,
    ]
    print("+", " ".join(cmd))
    subprocess.run(cmd, check=True)
    return OUT


if __name__ == "__main__":
    path = build(force="--force" in sys.argv)
    print(f"built {path}")
