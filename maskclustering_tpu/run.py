"""Pipeline orchestration (reference run.py, L6).

The reference fans out OS processes per GPU with ``os.system`` and files as
the only IPC (run.py:8-17,33-50). Here the seven steps run in-process against
the library API, with:

- **scene work queue**: scenes round-robin-sharded ``seq_names[i::workers]``
  (same shape as run.py:39) over a spawn Pool when ``workers > 1``; on a
  single TPU chip the default is in-process sequential — intra-scene mesh
  sharding is the parallelism axis there (SURVEY.md §2.3).
- **failure detection**: a failed scene is captured per-scene (status +
  traceback in the run report) instead of silently producing a missing npz
  (the reference's only failure signal, SURVEY.md §5).
- **resume**: artifact-level skip-if-done per step (the reference has this
  commented out, main.py:13-14); disable with ``resume=False``.
- **tracing**: optional ``jax.profiler`` trace over the clustering step plus
  per-step wall timings persisted to ``run_report.json``.

Steps: masks -> cluster -> eval_ca -> features -> label_features -> query -> eval.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
import traceback
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from maskclustering_tpu import obs
from maskclustering_tpu.config import PipelineConfig, load_config
from maskclustering_tpu.datasets import get_dataset
from maskclustering_tpu.semantics.vocab import vocab_name
from maskclustering_tpu.utils import faults

log = logging.getLogger("maskclustering_tpu")

# the full-benchmark pipeline (reference run.py:85-105)
DEFAULT_STEPS = ("masks", "cluster", "eval_ca", "features", "label_features",
                 "query", "eval")
# the tasmap/demo variant: no eval or CLIP, plus visualization
# (reference tasmap_inference.py:116-138)
TASMAP_STEPS = ("masks", "cluster", "vis", "top_images")
ALL_STEPS = DEFAULT_STEPS + ("vis", "top_images")

# dataset -> (gt dir, split file) under data_root (reference run.py:19-31,64-79).
# The reference reads splits/scannet_test.txt, which it ships EMPTY (a known
# quirk, SURVEY.md §7) — the real 311-scene val list lives in scannet.txt.
_DATASET_LAYOUT = {
    "scannet": ("scannet/gt", "scannet.txt"),
    "scannetpp": ("scannetpp/gt", "scannetpp.txt"),
    "matterport3d": ("matterport3d/gt", "matterport3d.txt"),
    "tasmap": ("tasmap/gt", "tasmap.txt"),
    "demo": ("demo/gt", "demo.txt"),
}


@dataclasses.dataclass
class SceneStatus:
    seq_name: str
    status: str  # "ok" | "skipped" | "failed" | "interrupted"
    seconds: float = 0.0
    error: str = ""
    num_objects: int = -1
    # per-stage wall seconds (associate/graph/cluster/postprocess + post.*),
    # same keys the bench reports — production triage without a re-run
    timings: Dict[str, float] = dataclasses.field(default_factory=dict)
    # fault attribution (utils/faults.py): how many attempts this scene
    # took, the degradation-ladder rung it last ran at, and the stable
    # error class of its last failure ("retryable" | "device" | "terminal";
    # "" when it never failed). attempts == 0 means the scene never ran
    # this process (journal-resume skip or interrupted before dispatch).
    attempts: int = 1
    degradation_rung: int = 0
    error_class: str = ""
    # mct-sentinel (obs/digest.py): the scene's invariant digest and the
    # census coordinate it was observed at — byte-identical across
    # executors/dtypes/rungs by contract, so the ledger and --regress can
    # attribute any digest change to a knob flip vs code drift
    digest: Optional[Dict] = None
    digest_coord: str = ""


@dataclasses.dataclass
class RunReport:
    config_name: str
    step_seconds: Dict[str, float] = dataclasses.field(default_factory=dict)
    scenes: List[SceneStatus] = dataclasses.field(default_factory=list)
    step_errors: Dict[str, str] = dataclasses.field(default_factory=dict)
    # machine-checked environment fact: local CLIP checkpoint dir, or None
    # (the reference downloads ViT-H-14 at run time; no egress here)
    clip_checkpoint: Optional[str] = None
    # obs digest (per-stage p50/p95, transfer bytes, HBM high-water) plus
    # the events.jsonl path — render/diff it with
    # ``python -m maskclustering_tpu.obs.report <events>``
    obs: Optional[Dict] = None
    # fault-tolerance digest of the cluster step: scene_retries,
    # device_stalls, degradations{rung}, final_rung, journal_skips,
    # interrupted — the ledger stamps it so --regress can attribute a perf
    # delta to a degraded run instead of code drift
    faults: Optional[Dict] = None

    @property
    def failed(self) -> List[SceneStatus]:
        return [s for s in self.scenes if s.status == "failed"]

    @property
    def interrupted(self) -> List[SceneStatus]:
        return [s for s in self.scenes if s.status == "interrupted"]

    @property
    def ok(self) -> bool:
        return (not self.failed and not self.step_errors
                and not self.interrupted)

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({
                "config_name": self.config_name,
                "step_seconds": self.step_seconds,
                "scenes": [dataclasses.asdict(s) for s in self.scenes],
                "step_errors": self.step_errors,
                "clip_checkpoint": self.clip_checkpoint,
                "obs": self.obs,
                "faults": self.faults,
            }, f, indent=2)


def get_seq_name_list(dataset: str, splits_dir: str = "splits",
                      seq_name_list: Optional[str] = None) -> List[str]:
    """Scene list from an explicit +-joined string or the split file."""
    if seq_name_list:
        return [s for s in seq_name_list.split("+") if s]
    _, split_file = _DATASET_LAYOUT[dataset]
    path = os.path.join(splits_dir, split_file)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no split file {path}; pass seq_name_list explicitly")
    with open(path) as f:
        return [line.strip() for line in f if line.strip()]


def make_encoder(spec: str):
    """Encoder factory: ``hash[:dim]`` | ``hf:<local path>``."""
    from maskclustering_tpu.semantics import HashEncoder, HFCLIPEncoder

    if spec.startswith("hash"):
        _, _, dim = spec.partition(":")
        return HashEncoder(int(dim) if dim else 64)
    if spec.startswith("hf:"):
        return HFCLIPEncoder(spec[3:])
    raise ValueError(f"unknown encoder spec {spec!r} (use hash[:dim] or hf:<path>)")


# ---------------------------------------------------------------------------
# steps
# ---------------------------------------------------------------------------


def check_masks(cfg: PipelineConfig, seq_names: Sequence[str],
                mask_command: Optional[str] = None,
                mask_predictor=None,
                predictor_spec: Optional[str] = None) -> List[str]:
    """Step 1: ensure 2D mask id-maps exist for every scene.

    Mask prediction is a pluggable external stage (CropFormer in the
    reference; SURVEY.md §2.2) — the contract is a PNG id-map per frame
    under ``<scene>/output/mask``. Scenes with missing masks are filled by
    ``mask_predictor`` (a mask_prediction.MaskPredictor run in-process)
    or ``mask_command`` (template with ``{seq_name}``, one subprocess per
    scene, the reference's shape); otherwise they are reported.

    ``predictor_spec`` (e.g. ``cfg.cropformer_path``) is resolved into a
    predictor lazily, and only once some scene actually misses masks: every
    reference config carries a bare ``.pth`` cropformer_path, so eagerly
    building the predictor would crash fully-precomputed runs on a spec
    that is never needed.
    """
    missing = []
    for seq in seq_names:
        ds = get_dataset(cfg.dataset, seq, data_root=cfg.data_root)
        seg_dir = ds.segmentation_dir
        if not (os.path.isdir(seg_dir) and os.listdir(seg_dir)):
            missing.append(seq)
    if missing and mask_predictor is None and predictor_spec:
        from maskclustering_tpu.mask_prediction import predictor_from_spec

        try:
            mask_predictor = predictor_from_spec(predictor_spec)
        except Exception:
            # a bad spec (e.g. a reference config's bare .pth path on a
            # machine without the adapter) must not abort the step — fall
            # through to the mask_command / report-missing paths
            log.exception("could not build mask predictor from spec %r",
                          predictor_spec)
    if missing and mask_predictor is not None:
        from maskclustering_tpu.mask_prediction import predict_scene_masks

        for seq in missing:
            try:
                ds = get_dataset(cfg.dataset, seq, data_root=cfg.data_root)
                log.info("predicting masks for %s", seq)
                predict_scene_masks(ds, mask_predictor, stride=cfg.step)
            except Exception:
                # one corrupt scene must not abort the whole masks step; the
                # scene stays in the missing list (mask_command fallback /
                # exclusion), like the mask_command path's non-zero-exit case
                log.exception("mask prediction failed for %s", seq)
        # keep mask_command as the fallback for scenes the predictor
        # could not fill (e.g. empty frame lists)
        return check_masks(cfg, missing, mask_command=mask_command)
    if missing and mask_command:
        for seq in missing:
            cmd = mask_command.format(seq_name=seq)
            log.info("running mask predictor: %s", cmd)
            if os.system(cmd) != 0:
                log.error("mask predictor failed for %s", seq)
        return check_masks(cfg, missing, mask_command=None)
    return missing


def _load_for_cluster(cfg: PipelineConfig, seq_name: str, resume: bool,
                      prediction_root: Optional[str]):
    """(dataset, tensors): the host-IO half of one scene; tensors None = skip."""
    faults.inject("load", seq_name)  # deterministic fault seam (disk IO)
    prediction_root = prediction_root or os.path.join(cfg.data_root, "prediction")
    ds = get_dataset(cfg.dataset, seq_name, data_root=cfg.data_root)
    npz_path = os.path.join(prediction_root, cfg.config_name + "_class_agnostic",
                            f"{seq_name}.npz")
    if resume and os.path.exists(npz_path):
        return ds, None
    return ds, ds.load_scene_tensors(cfg.step)


class _FaultCtx:
    """Per-round fault bookkeeping shared by the scene executors.

    Tracks attempt numbers across retry rounds, stamps every SceneStatus
    with its fault attribution (attempts / degradation rung / error
    class), and journals attempt + outcome rows as they happen — inside
    the executors, where a crash can still find them on disk. A default
    instance (no journal, rung 0) keeps direct executor calls working.
    """

    def __init__(self, journal: Optional[faults.RunJournal] = None,
                 rung: int = 0, attempts: Optional[Dict[str, int]] = None):
        self.journal = journal
        self.rung = rung
        self.attempts = attempts if attempts is not None else {}

    def begin(self, seq: str) -> None:
        self.attempts[seq] = self.attempts.get(seq, 0) + 1
        if self.journal is not None:
            self.journal.attempt(seq, self.attempts[seq], self.rung)

    def finish(self, st: SceneStatus) -> SceneStatus:
        st.attempts = self.attempts.get(st.seq_name, 0)
        st.degradation_rung = self.rung
        if self.journal is not None:
            self.journal.outcome(
                st.seq_name, st.status, attempt=st.attempts, rung=st.degradation_rung,
                error_class=st.error_class, error=st.error,
                seconds=st.seconds, num_objects=st.num_objects)
        return st


def _stamp_digest(st: SceneStatus, result, cfg: PipelineConfig,
                  mesh_label: str = "single") -> SceneStatus:
    """Stamp a SceneResult's sentinel digest + full census coordinate onto
    the (already rung-attributed) SceneStatus."""
    from maskclustering_tpu.obs import digest as sentinel

    digest = getattr(result, "digest", None)
    if digest:
        st.digest = digest
        st.digest_coord = sentinel.digest_coord(
            digest, mesh=mesh_label, rung=st.degradation_rung,
            chunk=cfg.streaming_chunk)
    return st


def cluster_scene(cfg: PipelineConfig, seq_name: str, *, resume: bool = True,
                  prediction_root: Optional[str] = None,
                  _preloaded=None, _ctx: Optional[_FaultCtx] = None) -> SceneStatus:
    """Step 2 for one scene: tensors -> device + host phases -> export.

    ``_preloaded``: zero-arg callable returning ``(dataset, tensors)`` — the
    prefetching loop passes ``_spawn_load``'s ``resolve`` closure so load
    errors of a prefetched scene re-raise here and are captured as that
    scene's failure. Each phase (load resolve, device dispatch, host tail)
    runs under its configured watchdog budget (``cfg.watchdog_*_s``; 0 =
    inline, no threads): a wedged chip raises ``DeviceStallError`` here
    within the budget instead of hanging the queue forever.
    """
    from maskclustering_tpu.models.pipeline import run_scene_device, run_scene_host

    prediction_root = prediction_root or os.path.join(cfg.data_root, "prediction")
    ctx = _ctx if _ctx is not None else _FaultCtx()
    t0 = time.perf_counter()
    ctx.begin(seq_name)
    try:
        loader = (_preloaded if _preloaded is not None
                  else lambda: _load_for_cluster(cfg, seq_name, resume,
                                                 prediction_root))
        ds, tensors = faults.call_with_deadline(
            loader, cfg.watchdog_load_s, seam="load", scene=seq_name)
        if tensors is None:
            obs.count("run.scenes_skipped")
            return ctx.finish(SceneStatus(seq_name, "skipped"))
        if faults.stop_requested():
            # SIGTERM landed during the load: journal the scene as
            # interrupted (in flight, must re-run) rather than dispatching
            # device work during shutdown
            return ctx.finish(SceneStatus(seq_name, "interrupted"))
        if cfg.streaming_chunk > 0:
            # streaming mode: frames feed the chunked accumulator
            # (models/streaming.py) — per-chunk watchdog + retry happen
            # INSIDE stream_scene (a mid-stream fault retries the chunk,
            # accumulator intact; the journaled state resumes a killed
            # process mid-stream). The scene supervisor's ladder still
            # wraps this call for errors the chunk retries cannot heal.
            from maskclustering_tpu.models.streaming import stream_scene

            result = stream_scene(
                tensors, cfg, seq_name=seq_name, export=True,
                object_dict_dir=ds.object_dict_dir,
                prediction_root=prediction_root,
                state_dir=os.path.join(
                    prediction_root,
                    cfg.config_name + "_stream_state"),
                resume=resume)
        else:
            handoff = faults.call_with_deadline(
                lambda: run_scene_device(tensors, cfg, seq_name=seq_name),
                cfg.watchdog_device_s, seam="device", scene=seq_name)
            result = faults.call_with_deadline(
                lambda: run_scene_host(handoff, cfg, export=True,
                                       object_dict_dir=ds.object_dict_dir,
                                       prediction_root=prediction_root),
                cfg.watchdog_host_s, seam="host", scene=seq_name)
        obs.count("run.scenes_ok")
        return _stamp_digest(ctx.finish(SceneStatus(
            seq_name, "ok", time.perf_counter() - t0,
            num_objects=len(result.objects.point_ids_list),
            timings={k: round(v, 4) for k, v in result.timings.items()})),
            result, cfg)
    except Exception as e:
        log.exception("scene %s failed", seq_name)
        obs.count("run.scenes_failed")
        return ctx.finish(SceneStatus(
            seq_name, "failed", time.perf_counter() - t0,
            error=traceback.format_exc(limit=20),
            error_class=faults.classify_error(e)))


def _spawn_load(cfg: PipelineConfig, seq_name: str, resume: bool,
                prediction_root: Optional[str]):
    """Start one scene load on a daemon thread; returns a resolve() callable.

    A daemon thread — unlike a ThreadPoolExecutor worker, which the
    interpreter joins at exit — can never stall process shutdown on an
    abandoned multi-second load (Ctrl-C mid-scene). resolve() re-raises
    load errors in the caller so they attribute to the right scene. The
    load itself runs under an ``exec.load`` span (thread-local span stacks
    keep it off the caller's stack), so the IO timeline is on the books
    for the overlap-ratio metric.
    """
    from maskclustering_tpu.utils.daemon_future import DaemonFuture

    def load():
        with obs.span("exec.load", scene=seq_name):
            return _load_for_cluster(cfg, seq_name, resume, prediction_root)

    fut = DaemonFuture(load, name=f"prefetch-{seq_name}")
    return fut.result


def _prefetched_loads(cfg: PipelineConfig, seq_names: Sequence[str], resume: bool,
                      prediction_root: Optional[str] = None, depth: int = 1):
    """Yield (seq_name, resolve) with a ``depth``-scene disk-prefetch lookahead.

    Loading a scene (hundreds of depth/seg PNG pairs + the PLY cloud) is
    seconds of pure host IO; lookahead threads load scenes i+1..i+depth
    while scene i runs on the device, hiding the IO entirely (the
    reference gets the same overlap for free from its per-GPU process
    pool, reference run.py:33-50). ``depth`` bounds the extra resident
    decoded tensors; ``depth == 0`` loads inline (no prefetch thread).
    Scenes always yield in list order, and a failed load re-raises at its
    OWN scene's resolve() so the failure attributes correctly.
    """
    if depth <= 0:
        for seq in seq_names:
            def load_inline(seq=seq):
                with obs.span("exec.load", scene=seq):
                    return _load_for_cluster(cfg, seq, resume, prediction_root)

            yield seq, load_inline
        return
    from collections import deque

    pending = deque(_spawn_load(cfg, seq_names[i], resume, prediction_root)
                    for i in range(min(depth, len(seq_names))))
    for i, seq in enumerate(seq_names):
        if i + depth < len(seq_names):
            pending.append(_spawn_load(cfg, seq_names[i + depth], resume,
                                       prediction_root))
        yield seq, pending.popleft()


def _cluster_scenes_sequential(cfg: PipelineConfig, seq_names: Sequence[str], *,
                               resume: bool = True,
                               ctx: Optional[_FaultCtx] = None
                               ) -> List[SceneStatus]:
    """The serialized in-process scene loop (disk prefetch is the only
    overlap). Kept as the bit-for-bit reference order the overlapped
    executor is tested against, and as the ``scene_overlap=false`` path."""
    ctx = ctx if ctx is not None else _FaultCtx()
    out: List[SceneStatus] = []
    with obs.span("exec.scene_loop", scenes=len(seq_names), mode="sequential"):
        for seq, resolve in _prefetched_loads(cfg, seq_names, resume,
                                              depth=cfg.prefetch_depth):
            if faults.stop_requested():
                # journal the un-run tail so the rerun knows these scenes
                # never started (vs the in-flight one cluster_scene marks)
                out.append(ctx.finish(SceneStatus(seq, "interrupted")))
                continue
            out.append(cluster_scene(cfg, seq, resume=resume,
                                     _preloaded=resolve, _ctx=ctx))
    return out


def _cluster_scenes_overlapped(cfg: PipelineConfig, seq_names: Sequence[str], *,
                               resume: bool = True,
                               prediction_root: Optional[str] = None,
                               ctx: Optional[_FaultCtx] = None
                               ) -> List[SceneStatus]:
    """Step 2, software-pipelined: three overlapped per-scene timelines.

    - **load** (daemon threads): disk IO for scenes i+1..i+depth;
    - **device** (this thread): H2D feed + associate/graph/cluster dispatch
      of scene i (``run_scene_device``);
    - **host tail** (one worker thread): scene i-1's bit-plane drain,
      DBSCAN split, overlap merge and artifact export (``run_scene_host``).

    The device phase of scene i runs while scene i-1's host tail drains —
    the handoff count is bounded to one in flight (double buffering), so
    at most two scenes' (F, N) claim tensors coexist in HBM. Results,
    artifacts and failure attribution are identical to the sequential
    loop; only the wall clock differs (pinned by tests/test_executor.py).
    """
    from maskclustering_tpu.models.pipeline import run_scene_device, run_scene_host
    from maskclustering_tpu.utils.daemon_future import DaemonFuture

    pred_root = prediction_root or os.path.join(cfg.data_root, "prediction")
    ctx = ctx if ctx is not None else _FaultCtx()
    statuses: Dict[str, SceneStatus] = {}
    in_flight = None  # (seq_name, t0, DaemonFuture of the host tail)

    def finish(entry) -> None:
        # (result, error, error_class, t_end) were produced INSIDE the
        # worker when the tail finished: this join may happen a whole
        # device-phase later (the backpressure point), and charging that
        # wait to the scene — ok or failed — would roughly double its
        # reported wall vs the sequential path. The join itself is a
        # watchdog seam: a host tail wedged in a claims drain raises
        # DeviceStallError within cfg.watchdog_host_s and is abandoned on
        # its daemon thread.
        seq, t0, fut = entry
        try:
            result, err, err_class, t_end = fut.result(
                cfg.watchdog_host_s if cfg.watchdog_host_s > 0 else None)
        except TimeoutError:
            # declare the consumer gone: the wedged tail's late result (and
            # the whole scene's tensors it references) is dropped at
            # completion instead of living on the future, and the drop is
            # booked as run.abandoned_results
            fut.abandon()
            stall = faults.DeviceStallError("host", seq, cfg.watchdog_host_s)
            obs.count("run.device_stalls")
            obs.count("run.scenes_failed")
            log.error("scene %s failed: %s", seq, stall)
            statuses[seq] = ctx.finish(SceneStatus(
                seq, "failed", time.perf_counter() - t0, error=str(stall),
                error_class="device"))
            return
        if err is not None:
            log.error("scene %s failed\n%s", seq, err)
            obs.count("run.scenes_failed")
            statuses[seq] = ctx.finish(SceneStatus(
                seq, "failed", t_end - t0, error=err, error_class=err_class))
            return
        obs.count("run.scenes_ok")
        statuses[seq] = _stamp_digest(ctx.finish(SceneStatus(
            seq, "ok", t_end - t0,
            num_objects=len(result.objects.point_ids_list),
            timings={k: round(v, 4) for k, v in result.timings.items()})),
            result, cfg)

    with obs.span("exec.scene_loop", scenes=len(seq_names), mode="overlapped"):
        for seq, resolve in _prefetched_loads(cfg, seq_names, resume,
                                              depth=cfg.prefetch_depth):
            if faults.stop_requested():
                statuses[seq] = ctx.finish(SceneStatus(seq, "interrupted"))
                continue
            t0 = time.perf_counter()
            ctx.begin(seq)
            try:
                ds, tensors = faults.call_with_deadline(
                    resolve, cfg.watchdog_load_s, seam="load", scene=seq)
                if tensors is None:
                    obs.count("run.scenes_skipped")
                    statuses[seq] = ctx.finish(SceneStatus(seq, "skipped"))
                    continue
                if faults.stop_requested():
                    statuses[seq] = ctx.finish(SceneStatus(seq, "interrupted"))
                    continue
                with obs.span("exec.device", scene=seq):
                    handoff = faults.call_with_deadline(
                        lambda: run_scene_device(tensors, cfg, seq_name=seq),
                        cfg.watchdog_device_s, seam="device", scene=seq)
            except Exception as e:
                log.exception("scene %s failed", seq)
                obs.count("run.scenes_failed")
                statuses[seq] = ctx.finish(SceneStatus(
                    seq, "failed", time.perf_counter() - t0,
                    error=traceback.format_exc(limit=20),
                    error_class=faults.classify_error(e)))
                continue
            # backpressure OUTSIDE the exec spans: the previous host tail
            # must retire before another handoff goes live, bounding HBM
            # to two scenes' claim tensors (current dispatch + one drain)
            if in_flight is not None:
                finish(in_flight)

            def host_tail(handoff=handoff, seq=seq, ds=ds):
                try:
                    with obs.span("exec.host_tail", scene=seq):
                        result = run_scene_host(
                            handoff, cfg, export=True,
                            object_dict_dir=ds.object_dict_dir,
                            prediction_root=pred_root)
                    return result, None, "", time.perf_counter()
                except Exception as e:
                    return (None, traceback.format_exc(limit=20),
                            faults.classify_error(e), time.perf_counter())

            in_flight = (seq, t0, DaemonFuture(host_tail,
                                               name=f"host-tail-{seq}"))
        if in_flight is not None:
            finish(in_flight)
    return [statuses[s] for s in seq_names if s in statuses]


def _cluster_worker(payload):
    cfg, seq_names, resume = payload  # PipelineConfig pickles whole
    if cfg.backend == "cpu":
        # spawn-children inherit the TPU plugin preload; the env var is too
        # late by now, so switch platforms through jax.config instead
        import jax

        jax.config.update("jax_platforms", "cpu")
    return [cluster_scene(cfg, s, resume=resume) for s in seq_names]


def cluster_scenes_mesh(cfg: PipelineConfig, seq_names: Sequence[str], *,
                        resume: bool = True,
                        prediction_root: Optional[str] = None,
                        ctx: Optional[_FaultCtx] = None) -> List[SceneStatus]:
    """Step 2 over a device mesh: fused batches -> per-scene artifacts.

    Scenes stream through the (scene, frame) mesh in batches of the scene
    axis size; each batch runs the fully-jitted fused step
    (parallel/batch.cluster_scene_batch), then post-process + export write
    the exact artifacts the single-chip path does. Per-scene failures are
    captured without sinking the batch queue; a batch dispatch that stalls
    past ``cfg.watchdog_device_s`` fails the whole batch with
    ``DeviceStallError`` (device-class), which the scene supervisor
    retries on the single-chip rung of the degradation ladder.
    """
    from maskclustering_tpu.models.postprocess import export_artifacts
    from maskclustering_tpu.parallel.batch import cluster_scene_batch, make_run_mesh

    prediction_root = prediction_root or os.path.join(cfg.data_root, "prediction")
    mesh = make_run_mesh(cfg)
    s_axis = int(mesh.shape["scene"])
    ctx = ctx if ctx is not None else _FaultCtx()
    statuses: Dict[str, SceneStatus] = {}
    pending: List[tuple] = []  # (seq, dataset, tensors)

    def flush():
        if not pending:
            return
        batch, pending[:] = list(pending), []
        t0 = time.perf_counter()
        try:
            def dispatch_batch():
                # injection INSIDE the guarded call: a scripted stall then
                # surfaces as DeviceStallError through the watchdog (the
                # same conversion the single-chip path gets via
                # run_scene_device) instead of sleeping the supervisor
                for seq, _, _ in batch:
                    faults.inject("device", seq)
                return cluster_scene_batch(cfg, mesh, [b[2] for b in batch],
                                           seq_names=[b[0] for b in batch])

            objects_list = faults.call_with_deadline(
                dispatch_batch, cfg.watchdog_device_s, seam="device",
                scene=",".join(b[0] for b in batch))
        except Exception as e:
            log.exception("mesh batch %s failed", [b[0] for b in batch])
            err = traceback.format_exc(limit=20)
            err_class = faults.classify_error(e)
            obs.count("run.scenes_failed", len(batch))
            for seq, _, _ in batch:
                statuses[seq] = ctx.finish(SceneStatus(
                    seq, "failed", time.perf_counter() - t0, error=err,
                    error_class=err_class))
            return
        per_scene = (time.perf_counter() - t0) / len(batch)
        for (seq, ds, _), objects in zip(batch, objects_list):
            try:
                faults.inject("export", seq)
                export_artifacts(objects, seq, cfg.config_name, ds.object_dict_dir,
                                 prediction_root=prediction_root,
                                 top_k_repre=cfg.num_representative_masks)
                obs.count("run.scenes_ok")
                st = ctx.finish(SceneStatus(
                    seq, "ok", per_scene,
                    num_objects=len(objects.point_ids_list)))
                # the fused path never materializes a DeviceHandoff, so
                # only the universal artifact digest fingerprints it —
                # byte-equal to the single-chip artifact by contract
                from maskclustering_tpu.obs import digest as sentinel
                from maskclustering_tpu.parallel.mesh import mesh_label

                st.digest = sentinel.artifact_only_digest(
                    objects, bucket="fused", count_dtype=cfg.count_dtype)
                st.digest_coord = sentinel.digest_coord(
                    st.digest, mesh=mesh_label(cfg.mesh_shape),
                    rung=st.degradation_rung, chunk=0)
                statuses[seq] = st
            except Exception as e:
                log.exception("scene %s export failed", seq)
                obs.count("run.scenes_failed")
                statuses[seq] = ctx.finish(SceneStatus(
                    seq, "failed", per_scene,
                    error=traceback.format_exc(limit=20),
                    error_class=faults.classify_error(e)))

    # lookahead prefetch: the next scenes' disk loads overlap the current
    # batch's device compute in flush() (_prefetched_loads)
    for seq, resolve in _prefetched_loads(cfg, seq_names, resume, prediction_root,
                                          depth=cfg.prefetch_depth):
        if faults.stop_requested():
            statuses[seq] = ctx.finish(SceneStatus(seq, "interrupted"))
            continue
        ctx.begin(seq)
        try:
            ds, tensors = faults.call_with_deadline(
                resolve, cfg.watchdog_load_s, seam="load", scene=seq)
        except Exception as e:
            log.exception("scene %s failed to load", seq)
            statuses[seq] = ctx.finish(SceneStatus(
                seq, "failed", error=traceback.format_exc(limit=20),
                error_class=faults.classify_error(e)))
            continue
        if tensors is None:
            statuses[seq] = ctx.finish(SceneStatus(seq, "skipped"))
            continue
        pending.append((seq, ds, tensors))
        if len(pending) == s_axis:
            flush()
    flush()
    return [statuses[s] for s in seq_names if s in statuses]


def _dispatch_scenes(cfg: PipelineConfig, seq_names: Sequence[str], *,
                     workers: int, resume: bool,
                     ctx: _FaultCtx) -> List[SceneStatus]:
    """One executor pass over ``seq_names`` at the CURRENT ladder rung.

    ``cfg.mesh_shape`` set routes through the fused multi-chip path
    (cluster_scenes_mesh). Otherwise ``workers == 1`` runs in-process (the
    single-chip TPU path: intra-scene device parallelism) — overlapped
    across scenes by default (``cfg.scene_overlap``; byte-identical
    artifacts to the sequential order) — and ``workers > 1`` spawns
    processes with round-robin scene shards — the CPU / multi-host shape,
    mirroring run.py:33-45 without os.system.
    """
    if cfg.mesh_shape:
        return cluster_scenes_mesh(cfg, seq_names, resume=resume, ctx=ctx)
    if workers <= 1:
        if cfg.streaming_chunk > 0:
            # streaming scenes pipeline INSIDE the scene (chunked
            # accumulation); the overlapped executor's device/host split
            # does not apply — cluster_scene routes through stream_scene
            return _cluster_scenes_sequential(cfg, seq_names, resume=resume,
                                              ctx=ctx)
        if cfg.scene_overlap and len(seq_names) > 1:
            return _cluster_scenes_overlapped(cfg, seq_names, resume=resume,
                                              ctx=ctx)
        return _cluster_scenes_sequential(cfg, seq_names, resume=resume,
                                          ctx=ctx)
    import multiprocessing as mp

    shards = [list(seq_names[i::workers]) for i in range(workers)]
    payloads = [(cfg, shard, resume) for shard in shards if shard]
    mp_ctx = mp.get_context("spawn")  # fork is unsafe once jax owns the TPU
    with mp_ctx.Pool(len(payloads)) as pool:
        out = pool.map(_cluster_worker, payloads)
    statuses = [s for chunk in out for s in chunk]
    order = {name: i for i, name in enumerate(seq_names)}
    statuses = sorted(statuses, key=lambda s: order[s.seq_name])
    for st in statuses:
        # child processes carry no journal/attempt state; the parent
        # stamps + journals their outcomes after the fact (coarser than
        # the in-process executors, but the resume semantics hold)
        ctx.begin(st.seq_name)
        ctx.finish(st)
    return statuses


class SceneSupervisor:
    """The fault-supervised scene work queue, as a reusable seam.

    The scene is the fault boundary (the pipeline is embarrassingly
    scene-parallel): each executor pass captures per-scene failures, and
    this supervisor then

    - **retries** failed scenes whose error class is not terminal, up to
      ``cfg.scene_retries`` extra rounds with exponential backoff
      (``cfg.retry_backoff_s`` base, shared faults.RetryPolicy);
      device-class failures additionally keep retrying while the
      degradation ladder still has rungs to drop (bounded by the ladder
      depth), so a deterministic device fault always reaches the rung
      that heals it — e.g. a post-process capacity overflow reaches the
      host-postprocess rung even at the default retry budget;
    - **degrades** one ladder rung per round that saw a device-class
      failure (overlapped -> sequential, fused mesh -> single chip,
      donation off, device -> host postprocess) — a sick chip costs
      throughput, not the batch;
    - **journal-skips** scenes a ``journal`` (utils/faults.RunJournal)
      records as already done — exact resume attribution where
      artifact-exists resume cannot distinguish "done" from "never
      started";
    - stops cleanly at scene boundaries when a SIGTERM requested stop
      (remaining scenes journal as ``interrupted`` and re-run next time).

    Two callers share one copy of these semantics: the batch cluster step
    (``cluster_scenes``, one supervisor per run) and the serving daemon's
    worker (``serve/worker.py``, one supervisor PER REQUEST so a sick
    request's ladder drop cannot poison its neighbors).

    ``on_event`` observes supervisor decisions without changing them:
    ``on_event("retry", scenes=[...], round=n, delay_s=d, rung=r)`` before
    each retry round and ``on_event("degrade", rung=name, rung_index=i)``
    on each ladder drop — the daemon streams these to the requesting
    client as status events. ``should_continue`` is polled alongside
    ``stop_requested()`` when deciding whether a failed scene may retry;
    the daemon wires the per-request deadline here so an out-of-budget
    request answers with its best-so-far failure instead of burning
    retry rounds past its deadline.
    """

    def __init__(self, cfg: PipelineConfig, *, workers: int = 1,
                 resume: bool = True,
                 journal: Optional[faults.RunJournal] = None,
                 on_event: Optional[Callable] = None,
                 should_continue: Optional[Callable[[], bool]] = None,
                 initial_rungs: int = 0):
        self.cfg = cfg
        self.workers = workers
        self.resume = resume
        self.journal = journal
        self.on_event = on_event
        self.should_continue = should_continue
        self.ladder = faults.DegradationLadder(cfg)
        # crash-class interaction (serve/supervisor.py): a request that
        # took its device worker down with it re-runs PRE-DEGRADED by the
        # crash count — the full configuration already proved fatal once,
        # so the respawned worker's retry starts one rung down instead of
        # re-buying the same crash at full configuration
        for _ in range(max(int(initial_rungs), 0)):
            if self.ladder.degrade(reason="worker crash carry-over") is None:
                break

    def _notify(self, kind: str, **info) -> None:
        if self.on_event is None:
            return
        try:
            self.on_event(kind, **info)
        except Exception:  # noqa: BLE001 — an observer must not sink the queue
            log.exception("scene supervisor on_event(%r) observer failed", kind)

    def _may_retry(self) -> bool:
        if faults.stop_requested():
            return False
        if self.should_continue is not None and not self.should_continue():
            return False
        return True

    def run(self, seq_names: Sequence[str]) -> List[SceneStatus]:
        cfg, ladder, journal = self.cfg, self.ladder, self.journal
        policy = faults.RetryPolicy(attempts=cfg.scene_retries + 1,
                                    base_s=cfg.retry_backoff_s,
                                    cap_s=max(cfg.retry_backoff_s * 8.0, 0.0))
        statuses: Dict[str, SceneStatus] = {}
        attempts: Dict[str, int] = {}
        pending = list(seq_names)
        if journal is not None and self.resume:
            done = journal.resume_done()
            for seq in pending:
                if seq in done:
                    obs.count("run.journal_skips")
                    st = SceneStatus(seq, "skipped", attempts=0)
                    journal.outcome(seq, "skipped", attempt=0, rung=0)
                    statuses[seq] = st
            if done:
                log.info("journal resume: skipping %d already-done scene(s)",
                         len([s for s in pending if s in done]))
            pending = [s for s in pending if s not in done]
        round_no = 1
        while pending:
            ctx = _FaultCtx(journal=journal, rung=ladder.rung,
                            attempts=attempts)
            batch = _dispatch_scenes(ladder.apply(cfg), pending,
                                     workers=self.workers,
                                     resume=self.resume, ctx=ctx)
            retry: List[str] = []
            saw_device = False
            for st in batch:
                statuses[st.seq_name] = st
                if st.status != "failed":
                    continue
                saw_device = saw_device or st.error_class == "device"
                # device-class failures keep retrying while the ladder
                # still has rungs to drop: a deterministic device fault
                # (e.g. a post-process capacity overflow) needs to reach
                # the rung that heals it, and with a small scene_retries
                # the budget would otherwise exhaust one rung short of
                # host-postprocess. The extension is bounded by the
                # ladder depth (<= 4 extra rounds)
                in_budget = round_no <= cfg.scene_retries
                ladder_can_help = (st.error_class == "device"
                                   and not ladder.exhausted)
                if (st.error_class != "terminal"
                        and (in_budget or ladder_can_help)
                        and self._may_retry()):
                    retry.append(st.seq_name)
            if not retry:
                break
            if saw_device:
                # the chip, not the scenes, looks sick: drop one rung
                # before the retry round so the SAME fault class cannot
                # burn the whole retry budget at full configuration
                rung_name = ladder.degrade(
                    reason=f"device-class failure(s) in round {round_no}")
                if rung_name:
                    self._notify("degrade", rung=rung_name,
                                 rung_index=ladder.rung)
                from maskclustering_tpu.analysis import retrace_sanitizer

                if retrace_sanitizer.enabled():
                    # tag compile events with the rung: donation-off (and
                    # any future surface-adding rung) legitimately rebuilds
                    # its programs — under a new context those are
                    # enumerated surface (compile_surface_baseline.json
                    # "rungs"), not repeat-compile violations. The switch
                    # happens between executor rounds, when the scene
                    # queue is drained
                    retrace_sanitizer.set_context(
                        "+".join(ladder.applied_names) or "baseline")
            delay = policy.backoff(round_no)
            obs.count("run.scene_retries", len(retry))
            self._notify("retry", scenes=list(retry), round=round_no + 1,
                         delay_s=delay, rung=ladder.rung)
            log.warning("retrying %d scene(s) in %.2fs (round %d/%d, rung %d%s)",
                        len(retry), delay, round_no + 1, cfg.scene_retries + 1,
                        ladder.rung,
                        f": {'+'.join(ladder.applied_names)}"
                        if ladder.applied_names else "")
            if delay > 0:
                time.sleep(delay)
            pending = retry
            round_no += 1
        return [statuses[s] for s in seq_names if s in statuses]


def cluster_scenes(cfg: PipelineConfig, seq_names: Sequence[str], *,
                   workers: int = 1, resume: bool = True,
                   journal: Optional[faults.RunJournal] = None
                   ) -> List[SceneStatus]:
    """Step 2: one SceneSupervisor pass over the run's scene list."""
    return SceneSupervisor(cfg, workers=workers, resume=resume,
                           journal=journal).run(seq_names)


_FAULT_COUNTERS = ("run.scene_retries", "run.device_stalls",
                   "run.journal_skips")


def _fault_counter_snapshot() -> Dict[str, float]:
    """Relevant obs counters before the cluster step (the registry is
    process-global and cumulative; the report wants THIS run's deltas)."""
    counters = obs.registry().snapshot()["counters"]
    return {k: v for k, v in counters.items()
            if k in _FAULT_COUNTERS or k.startswith("run.degradations.")}


def _fault_summary(before: Dict[str, float],
                   scenes: Sequence[SceneStatus]) -> Dict:
    """The run report's fault digest (counter deltas + scene rows)."""
    counters = obs.registry().snapshot()["counters"]

    def delta(name: str) -> int:
        return int(counters.get(name, 0.0) - before.get(name, 0.0))

    degradations = {}
    for k in counters:
        if k.startswith("run.degradations."):
            d = delta(k)
            if d:
                degradations[k[len("run.degradations."):]] = d
    return {
        "scene_retries": delta("run.scene_retries"),
        "device_stalls": delta("run.device_stalls"),
        "journal_skips": delta("run.journal_skips"),
        "degradations": degradations,
        "final_rung": sum(degradations.values()),
        "interrupted": (faults.stop_requested()
                        or any(s.status == "interrupted" for s in scenes)),
    }


def evaluate_step(cfg: PipelineConfig, *, no_class: bool,
                  seq_names: Optional[Sequence[str]] = None,
                  prediction_root: Optional[str] = None) -> Optional[dict]:
    """Steps 3/7: AP evaluation over the run's scenes.

    Restricted to seq_names when given so stale predictions from earlier
    runs (or scenes dropped from the split) can't block or skew the AP.
    """
    from maskclustering_tpu.evaluation.ap import evaluate_scans

    prediction_root = prediction_root or os.path.join(cfg.data_root, "prediction")
    suffix = "_class_agnostic" if no_class else ""
    pred_dir = os.path.join(prediction_root, cfg.config_name + suffix)
    gt_rel, _ = _DATASET_LAYOUT[cfg.dataset]
    gt_dir = os.path.join(cfg.data_root, gt_rel)
    if not os.path.isdir(pred_dir):
        log.warning("no predictions at %s; skipping evaluation", pred_dir)
        return None
    names = sorted(f for f in os.listdir(pred_dir) if f.endswith(".npz"))
    if seq_names is not None:
        wanted = set(seq_names)
        names = [n for n in names if n[:-len(".npz")] in wanted]
    if not names:
        log.warning("no predictions for this run's scenes in %s", pred_dir)
        return None
    pred_files = [os.path.join(pred_dir, n) for n in names]
    gt_files = [os.path.join(gt_dir, n.replace(".npz", ".txt")) for n in names]
    missing_gt = [g for g in gt_files if not os.path.isfile(g)]
    if missing_gt:
        # a mispointed gt_dir must fail the run, not silently yield no AP
        # (the reference raises here too, evaluate.py:407-411); run_pipeline
        # records the failure in RunReport.step_errors
        raise FileNotFoundError(
            f"missing GT for {len(missing_gt)}/{len(gt_files)} scenes under "
            f"{gt_dir}, e.g. {missing_gt[:3]}")
    out = os.path.join(cfg.data_root, "evaluation", cfg.dataset,
                       f"{cfg.config_name}{suffix}.txt")
    return evaluate_scans(pred_files, gt_files, vocab_name(cfg.dataset),
                          no_class=no_class, output_file=out)


def features_step(cfg: PipelineConfig, seq_names: Sequence[str], encoder, *,
                  resume: bool = True) -> None:
    """Step 4: per-mask CLIP features for every scene's representative masks."""
    from maskclustering_tpu.semantics import extract_mask_features, save_mask_features

    for seq in seq_names:
        ds = get_dataset(cfg.dataset, seq, data_root=cfg.data_root)
        out_path = os.path.join(ds.object_dict_dir, cfg.config_name,
                                "open-vocabulary_features.npy")
        if resume and os.path.exists(out_path):
            continue
        od_path = os.path.join(ds.object_dict_dir, cfg.config_name, "object_dict.npy")
        if not os.path.exists(od_path):
            log.warning("no object_dict for %s; run the cluster step first", seq)
            continue
        object_dict = np.load(od_path, allow_pickle=True).item()
        feats = extract_mask_features(ds, object_dict, encoder)
        save_mask_features(feats, ds.object_dict_dir, cfg.config_name)


def label_features_step(cfg: PipelineConfig, encoder, *, resume: bool = True) -> str:
    """Step 5: vocabulary text features, cached on disk (run.py:52-57)."""
    from maskclustering_tpu.semantics import extract_label_features, get_vocab

    path = os.path.join(cfg.data_root, "text_features",
                        f"{vocab_name(cfg.dataset)}.npy")
    if resume and os.path.exists(path):
        return path
    labels, _ = get_vocab(cfg.dataset)
    return extract_label_features(labels, encoder, path)


def query_step(cfg: PipelineConfig, seq_names: Sequence[str], *,
               resume: bool = True, prediction_root: Optional[str] = None) -> None:
    """Step 6: open-vocab label assignment -> class-aware npz per scene."""
    from maskclustering_tpu.semantics import run_query

    prediction_root = prediction_root or os.path.join(cfg.data_root, "prediction")
    for seq in seq_names:
        out_path = os.path.join(prediction_root, cfg.config_name, f"{seq}.npz")
        if resume and os.path.exists(out_path):
            continue
        ds = get_dataset(cfg.dataset, seq, data_root=cfg.data_root)
        needed = [os.path.join(ds.object_dict_dir, cfg.config_name, n)
                  for n in ("object_dict.npy", "open-vocabulary_features.npy")]
        missing = [p for p in needed if not os.path.exists(p)]
        if missing:
            # a failed upstream scene must not abort the whole queue
            log.warning("skipping query for %s: missing %s", seq, missing)
            continue
        run_query(ds, cfg.config_name, seq, prediction_root=prediction_root)


# ---------------------------------------------------------------------------
# pipeline driver
# ---------------------------------------------------------------------------


def _scene_points_cached(cfg: PipelineConfig, seq: str,
                         cache: Optional[Dict[str, np.ndarray]]):
    """Load a scene's cloud once per run when vis steps share a cache."""
    if cache is not None and seq in cache:
        return cache[seq]
    pts = get_dataset(cfg.dataset, seq, data_root=cfg.data_root).get_scene_points()
    if cache is not None:
        cache[seq] = pts
    return pts


def vis_step(cfg: PipelineConfig, seq_names: Sequence[str],
             prediction_root: Optional[str] = None, *, resume: bool = True,
             scene_points_cache: Optional[Dict[str, np.ndarray]] = None) -> List[str]:
    """Tasmap-variant step: instance-colored scene artifacts per scene
    (reference tasmap_inference.py vis steps -> visualize/vis_scene*)."""
    from maskclustering_tpu.visualize import vis_scene

    prediction_root = prediction_root or os.path.join(cfg.data_root, "prediction")
    written = []
    for seq in seq_names:
        npz_path = os.path.join(prediction_root, cfg.config_name + "_class_agnostic",
                                f"{seq}.npz")
        if not os.path.exists(npz_path):
            log.warning("no prediction for %s; run the cluster step first", seq)
            continue
        out_dir = os.path.join(cfg.data_root, "vis", seq)
        inst_path = os.path.join(out_dir, "instances.ply")
        if resume and os.path.exists(inst_path):
            continue
        pred = np.load(npz_path)
        out = vis_scene(_scene_points_cached(cfg, seq, scene_points_cache),
                        pred["pred_masks"], out_dir)
        written.append(out["instances"])
    return written


def top_images_step(cfg: PipelineConfig, seq_names: Sequence[str],
                    max_objects: Optional[int] = None, *, resume: bool = True,
                    scene_points_cache: Optional[Dict[str, np.ndarray]] = None
                    ) -> List[str]:
    """Tasmap-variant step: per-object bbox grids over representative
    frames (reference get_top_images.save_debug_image)."""
    from maskclustering_tpu.visualize import save_debug_grids

    written = []
    for seq in seq_names:
        ds = get_dataset(cfg.dataset, seq, data_root=cfg.data_root)
        od_path = os.path.join(ds.object_dict_dir, cfg.config_name, "object_dict.npy")
        if not os.path.exists(od_path):
            log.warning("no object_dict for %s; run the cluster step first", seq)
            continue
        out_dir = os.path.join(cfg.data_root, "vis", seq, "top_images")
        if resume and os.path.isdir(os.path.join(out_dir, "grid")) \
                and os.listdir(os.path.join(out_dir, "grid")):
            continue
        object_dict = np.load(od_path, allow_pickle=True).item()
        written.extend(save_debug_grids(
            ds, object_dict, _scene_points_cached(cfg, seq, scene_points_cache),
            out_dir, max_objects=max_objects))
    return written


def run_pipeline(
    cfg: PipelineConfig,
    seq_names: Sequence[str],
    *,
    steps: Sequence[str] = DEFAULT_STEPS,
    workers: int = 1,
    resume: bool = True,
    encoder_spec: str = "hash",
    mask_command: Optional[str] = None,
    mask_predictor=None,
    profile_dir: Optional[str] = None,
    report_path: Optional[str] = None,
    obs_events: Optional[str] = None,
    xprof_spans: Optional[Sequence[str]] = None,
    xprof_dir: Optional[str] = None,
    ledger_path: Optional[str] = None,
    ledger: bool = True,
    journal_path: Optional[str] = None,
    journal: bool = True,
) -> RunReport:
    unknown = set(steps) - set(ALL_STEPS)
    if unknown:
        raise ValueError(f"unknown steps {sorted(unknown)}; valid: {ALL_STEPS}")
    if obs_events:
        # arm span/metrics capture for the whole run: every run_scene stage
        # span and transfer counter lands in the JSONL, and the report below
        # embeds the digest — production runs self-report their timing.
        # truncate: this call owns the path (typically derived from
        # --report, which is itself overwritten); appending to a previous
        # run's capture would silently pool stale spans into the digest
        if xprof_spans and profile_dir:
            # jax has ONE profiler session; the whole-run trace owns it
            log.warning("--xprof ignored: --profile_dir already owns the "
                        "profiler session")
            xprof_spans = None
        if xprof_spans and not xprof_dir:
            root, _ = os.path.splitext(obs_events)
            xprof_dir = root + "_xprof"
        obs.configure(obs_events, annotations=bool(profile_dir), truncate=True,
                      meta={"tool": "run", "config": cfg.config_name},
                      xprof_dir=xprof_dir,
                      xprof_spans=tuple(xprof_spans) if xprof_spans else None)
        try:
            return _run_pipeline_body(
                cfg, seq_names, steps=steps, workers=workers, resume=resume,
                encoder_spec=encoder_spec, mask_command=mask_command,
                mask_predictor=mask_predictor, profile_dir=profile_dir,
                report_path=report_path, obs_events=obs_events,
                ledger_path=ledger_path, ledger=ledger,
                journal_path=journal_path, journal=journal)
        finally:
            # a step/encoder exception must not leave the global tracer
            # armed (fences on, sink open) for the rest of the process —
            # this call armed it, this call disarms it on every path
            obs.disable()
    return _run_pipeline_body(
        cfg, seq_names, steps=steps, workers=workers, resume=resume,
        encoder_spec=encoder_spec, mask_command=mask_command,
        mask_predictor=mask_predictor, profile_dir=profile_dir,
        report_path=report_path, obs_events=None,
        ledger_path=ledger_path, ledger=ledger,
        journal_path=journal_path, journal=journal)


def _run_pipeline_body(
    cfg: PipelineConfig,
    seq_names: Sequence[str],
    *,
    steps: Sequence[str],
    workers: int,
    resume: bool,
    encoder_spec: str,
    mask_command: Optional[str],
    mask_predictor,
    profile_dir: Optional[str],
    report_path: Optional[str],
    obs_events: Optional[str],
    ledger_path: Optional[str] = None,
    ledger: bool = True,
    journal_path: Optional[str] = None,
    journal: bool = True,
) -> RunReport:
    from maskclustering_tpu.utils.compile_cache import setup_compilation_cache

    setup_compilation_cache(cfg.compilation_cache_dir)
    from maskclustering_tpu.utils import aot_cache

    # persistent AOT executable cache (armed via cfg.aot_cache_dir /
    # --aot-cache / $MCT_AOT_CACHE): restore every valid serialized
    # serving executable BEFORE the first scene, so a warm-cached process
    # reaches first dispatch with zero compiles (version-mismatched
    # entries are skipped + counted; the run then compiles and re-captures)
    aot_stats = aot_cache.warm_start(cfg)
    if any(aot_stats.values()):
        log.info("aot cache: %s", aot_stats)
    from maskclustering_tpu.semantics.encoder import find_local_clip_checkpoint

    report = RunReport(config_name=cfg.config_name,
                       clip_checkpoint=find_local_clip_checkpoint())
    if report.clip_checkpoint:
        log.info("local CLIP checkpoint found: %s", report.clip_checkpoint)
    else:
        log.info("no local CLIP checkpoint on disk (hash/precomputed "
                 "encoders only; see README semantics deployment)")
    encoder = None
    trace_ctx = None
    if profile_dir:
        import jax.profiler

        trace_ctx = jax.profiler.trace(profile_dir)

    if cfg.debug:
        log.setLevel(logging.DEBUG)

    def timed(name, fn):
        t0 = time.perf_counter()
        try:
            out = fn()
        except Exception:
            # a failed step is recorded (and fails the run via RunReport.ok /
            # main's exit code) without sinking the steps that can still run
            log.exception("step %s failed", name)
            report.step_errors[name] = traceback.format_exc(limit=20)
            out = None
        report.step_seconds[name] = time.perf_counter() - t0
        log.info("step %s: %.1fs", name, report.step_seconds[name])
        return out

    if "masks" in steps:
        # the predictor is built lazily inside check_masks (and therefore
        # inside timed(), so spec/import failures land in step_errors rather
        # than crashing runs whose masks are all precomputed)
        missing = timed("masks", lambda: check_masks(
            cfg, seq_names, mask_command, mask_predictor=mask_predictor,
            predictor_spec=cfg.cropformer_path))
        if missing:
            log.warning("scenes with no 2D masks (excluded): %s", missing)
            seq_names = [s for s in seq_names if s not in set(missing)]

    if "cluster" in steps:
        jr = None
        if journal:
            jp = journal_path
            if jp is None and report_path:
                # the crash-safe scene journal lives next to the report it
                # backs; a crash that eats report.json still leaves exact
                # per-scene attribution here (faults.replay_journal)
                jp = os.path.join(os.path.dirname(report_path) or ".",
                                  "run_journal.jsonl")
            if jp:
                jr = faults.RunJournal(jp, cfg.config_name)
                jr.begin_run()
        fault_snap = _fault_counter_snapshot()
        if trace_ctx is not None:
            trace_ctx.__enter__()
        try:
            report.scenes = timed("cluster", lambda: cluster_scenes(
                cfg, seq_names, workers=workers, resume=resume,
                journal=jr)) or []
        finally:
            if trace_ctx is not None:
                trace_ctx.__exit__(None, None, None)
            report.faults = _fault_summary(fault_snap, report.scenes)
            if jr is not None:
                jr.end_run(interrupted=report.faults["interrupted"])
                jr.close()
        ok = sum(1 for s in report.scenes if s.status != "failed")
        log.info("clustered %d/%d scenes", ok, len(report.scenes))
        if report.faults["scene_retries"] or report.faults["degradations"]:
            log.warning("fault summary: %s", report.faults)

    if "eval_ca" in steps:
        timed("eval_ca", lambda: evaluate_step(cfg, no_class=True,
                                               seq_names=seq_names))

    if {"features", "label_features"} & set(steps):
        encoder = make_encoder(encoder_spec)
    if "features" in steps:
        timed("features", lambda: features_step(cfg, seq_names, encoder,
                                                resume=resume))
    if "label_features" in steps:
        timed("label_features", lambda: label_features_step(cfg, encoder,
                                                            resume=resume))
    if "query" in steps:
        timed("query", lambda: query_step(cfg, seq_names, resume=resume))
    if "eval" in steps:
        timed("eval", lambda: evaluate_step(cfg, no_class=False,
                                            seq_names=seq_names))
    if {"vis", "top_images"} & set(steps):
        pts_cache: Dict[str, np.ndarray] = {}
        if "vis" in steps:
            timed("vis", lambda: vis_step(cfg, seq_names, resume=resume,
                                          scene_points_cache=pts_cache))
        if "top_images" in steps:
            timed("top_images", lambda: top_images_step(
                cfg, seq_names, resume=resume, scene_points_cache=pts_cache))

    if obs_events and obs.enabled():
        from maskclustering_tpu.analysis import lock_sanitizer, retrace_sanitizer

        if lock_sanitizer.enabled():
            # book the sanitizer digest (locks.* counters) before the
            # flush so the report's Faults section renders it
            lock_sanitizer.emit_counters()
        if retrace_sanitizer.enabled():
            # same move for the retrace digest (retrace.* counters): the
            # report's Analysis section renders the compile-event line
            retrace_sanitizer.emit_counters()
        obs.flush_metrics()
        try:
            from maskclustering_tpu.obs.report import RunData

            report.obs = RunData(obs_events).summary()
        except Exception:  # noqa: BLE001 — a digest failure must not fail the run
            log.exception("obs digest failed for %s", obs_events)
            report.obs = {"events": obs_events}
        # run_pipeline's finally disarms; nothing more to do here
    if report_path:
        report.save(report_path)
    if ledger and report_path and report.scenes:
        # one trajectory row per reported run (schema-versioned, crash-safe
        # append): `obs.report --history` renders it, `--regress` gates it
        try:
            from maskclustering_tpu.obs import ledger as led

            led.append_row(
                ledger_path or led.default_ledger_path(),
                led.run_row({"config_name": report.config_name,
                             "scenes": [dataclasses.asdict(s)
                                        for s in report.scenes],
                             "obs": report.obs,
                             "faults": report.faults},
                            # knob attribution, same keys as bench rows:
                            # --regress flags flips instead of blaming code
                            count_dtype=cfg.count_dtype,
                            plane_dtype="int16",
                            point_shards=int(cfg.point_shards),
                            streaming_chunk=int(cfg.streaming_chunk),
                            postprocess_path=("device"
                                              if cfg.device_postprocess
                                              else "host")))
        except Exception:  # noqa: BLE001 — the ledger must never fail the run
            log.exception("perf ledger append failed")
    return report


def init_backend_or_die(timeout_s: float = 120.0, platform: Optional[str] = None):
    """Initialize the jax backend under a watchdog (shared helper).

    A wedged accelerator client hangs inside backend init with no exception
    (another process holding the chip, a dead tunnel); the watchdog turns a
    silent multi-minute stall into a one-line diagnosis and a nonzero exit
    — the failure-detection posture the reference lacks entirely (SURVEY §5).
    """
    from maskclustering_tpu.utils.backend_init import init_backend

    return init_backend(platform, timeout_s=timeout_s, tag="run", logger=log)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="maskclustering_tpu.run",
        description="TPU-native mask-clustering pipeline orchestrator")
    parser.add_argument("--config", required=True, help="config name under configs/")
    parser.add_argument("--seq_name_list", default=None,
                        help="+-joined scene names (default: split file)")
    parser.add_argument("--splits_dir", default="splits")
    parser.add_argument("--steps", default=",".join(DEFAULT_STEPS),
                        help=f"comma-separated subset of {ALL_STEPS}")
    parser.add_argument("--workers", type=int, default=1,
                        help="scene-queue worker processes (1 = in-process)")
    parser.add_argument("--prefetch-depth", type=int, default=None,
                        help="disk-load lookahead depth of the scene "
                             "prefetcher (0 = load inline; default: config "
                             "prefetch_depth, normally 1)")
    parser.add_argument("--no-overlap", action="store_true",
                        help="serialize the scene loop (disable the "
                             "overlapped executor; artifacts are identical "
                             "either way)")
    parser.add_argument("--point-shards", type=int, default=None,
                        help="shard the scene-point axis N over this many "
                             "chips (third mesh axis; needs the config's "
                             "mesh_shape — device product becomes "
                             "scene*frame*point). The (F, N) claim planes "
                             "and the cloud divide by it, so 1M+ point "
                             "scenes fit; artifacts are byte-identical at "
                             "any shard count "
                             "(tests/test_point_sharding.py). The ledger "
                             "row stamps point_shards so --regress "
                             "attributes the flip, not code drift")
    parser.add_argument("--streaming-chunk", type=int, default=None,
                        metavar="F",
                        help="streaming incremental clustering: accumulate "
                             "frames in chunks of F through the device-"
                             "resident streaming accumulator (models/"
                             "streaming.py) — only one chunk's (F, N) "
                             "claim planes plus O(M^2) graph state are "
                             "ever resident (stream.max_plane_bytes pins "
                             "it), partial instances are available per "
                             "chunk, and the final answer converges to "
                             "the batch result (byte-identical when one "
                             "chunk covers the scene). 0 = the classic "
                             "offline-batch pipeline (default: config "
                             "streaming_chunk). The ledger row stamps "
                             "streaming_chunk so --regress attributes the "
                             "flip, not code drift")
    parser.add_argument("--no-resume", action="store_true",
                        help="recompute even when artifacts exist")
    parser.add_argument("--encoder", default="hash",
                        help="CLIP encoder spec: hash[:dim] | hf:<local path>")
    parser.add_argument("--mask_command", default=None,
                        help="external mask-predictor template with {seq_name}")
    parser.add_argument("--profile_dir", default=None,
                        help="write a jax.profiler trace of the cluster step here")
    parser.add_argument("--report", default=None, help="run report JSON path")
    parser.add_argument("--obs_events", default=None,
                        help="obs span/metrics JSONL path (default: derived "
                             "from --report; render with "
                             "python -m maskclustering_tpu.obs.report)")
    parser.add_argument("--no-obs", action="store_true",
                        help="disable obs capture even when --report is set")
    parser.add_argument("--xprof", default=None, metavar="STAGE",
                        help="comma-joined span names to bracket with a "
                             "jax.profiler trace (first occurrence each; "
                             "e.g. cluster or post.claims.kernel; needs obs "
                             "capture, i.e. --report or --obs_events)")
    parser.add_argument("--xprof_dir", default=None,
                        help="trace output dir for --xprof (default: "
                             "derived from the events path)")
    parser.add_argument("--ledger", default=None,
                        help="perf ledger JSONL the run digest appends to "
                             "(default: PERF_LEDGER.jsonl / $MCT_PERF_LEDGER)")
    parser.add_argument("--no-ledger", action="store_true",
                        help="do not append this run to the perf ledger")
    parser.add_argument("--journal", default=None,
                        help="crash-safe scene journal JSONL (default: "
                             "run_journal.jsonl next to --report); reruns "
                             "skip journaled-done scenes and re-run "
                             "in-flight ones")
    parser.add_argument("--no-journal", action="store_true",
                        help="disable the scene journal (artifact-exists "
                             "resume only)")
    parser.add_argument("--scene-retries", type=int, default=None,
                        help="extra attempts per failed scene (default: "
                             "config scene_retries, normally 2; 0 = fail "
                             "fast)")
    parser.add_argument("--watchdog-device", type=float, default=None,
                        help="device-phase watchdog budget in seconds (0 "
                             "= off, the default): a dispatch or host "
                             "pull exceeding it raises DeviceStallError "
                             "and the scene retries/degrades instead of "
                             "wedging the run")
    parser.add_argument("--transfer-guard", action="store_true",
                        help="arm jax.transfer_guard('disallow') around "
                             "every scene's device phase (Family-3 "
                             "sanitizer; default: $MCT_TRANSFER_GUARD). "
                             "Any implicit transfer outside the two "
                             "sanctioned host pulls becomes a hard error "
                             "— CI/drill knob, results identical")
    parser.add_argument("--lock-sanitizer", action="store_true",
                        help="arm the instrumented lock shim for this run "
                             "(concurrency-family sanitizer; default: "
                             "$MCT_LOCK_SANITIZER). Records actual lock "
                             "acquisition orders + hold times against the "
                             "static lock-order graph — CI/drill knob, "
                             "results identical, metrics hot path gains "
                             "a few dict ops per bump")
    parser.add_argument("--retrace-sanitizer", action="store_true",
                        help="arm the compile-event sanitizer for this run "
                             "(retrace-family sanitizer; default: "
                             "$MCT_RETRACE_SANITIZER). Hooks jax's compile "
                             "log per (fn, signature, ladder rung), counts "
                             "retrace.* metrics, and flags repeat compiles "
                             "— the serve-many contract's runtime half. "
                             "CI/drill knob, results identical")
    parser.add_argument("--fault-plan", default=None,
                        help="deterministic fault injection spec (e.g. "
                             "'load:scene2, stall:scene4.device, "
                             "flaky:scene5:2'; default: $MCT_FAULT_PLAN). "
                             "Testing/drill knob — never set in production")
    parser.add_argument("--aot-cache", default=None, nargs="?", const="auto",
                        metavar="DIR",
                        help="arm the persistent AOT executable cache "
                             "(utils/aot_cache.py): restore serialized "
                             "serving executables at start and capture "
                             "newly compiled ones. Flag alone: aot_cache/ "
                             "next to the perf ledger; also armed by "
                             "$MCT_AOT_CACHE or cfg.aot_cache_dir")
    parser.add_argument("--data_root", default=None,
                        help="override the config's data root")
    parser.add_argument("--init_timeout", type=float, default=120.0,
                        help="seconds before a hung backend init aborts the run")
    parser.add_argument("--debug", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.DEBUG if args.debug else logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    overrides = {"data_root": args.data_root} if args.data_root else {}
    if args.prefetch_depth is not None:
        overrides["prefetch_depth"] = args.prefetch_depth
    if args.no_overlap:
        overrides["scene_overlap"] = False
    if args.point_shards is not None:
        overrides["point_shards"] = args.point_shards
    if args.streaming_chunk is not None:
        overrides["streaming_chunk"] = args.streaming_chunk
    if args.scene_retries is not None:
        overrides["scene_retries"] = args.scene_retries
    if args.watchdog_device is not None:
        overrides["watchdog_device_s"] = args.watchdog_device
    if args.aot_cache is not None:
        overrides["aot_cache_dir"] = args.aot_cache
    cfg = load_config(args.config, **overrides)
    if args.transfer_guard:
        from maskclustering_tpu.analysis import transfer_guard

        transfer_guard.arm(True)
    if args.lock_sanitizer:
        from maskclustering_tpu.analysis import lock_sanitizer

        lock_sanitizer.arm(True)
        # the plan/registry locks already exist (import time) — re-wrap
        # them in place; per-instance locks arm at creation from here on
        lock_sanitizer.instrument_known_locks()
    from maskclustering_tpu.analysis import retrace_sanitizer

    if args.retrace_sanitizer:
        retrace_sanitizer.arm(True)
    if retrace_sanitizer.enabled():
        # hook the compile log before backend init so warm-up compiles
        # are on the books too (the env flag alone also lands here)
        retrace_sanitizer.install()
    if args.fault_plan:
        faults.set_plan(faults.FaultPlan.from_spec(args.fault_plan))
    # SIGTERM-safe shutdown: the scene loops stop at the next scene
    # boundary, in-flight scenes journal as interrupted, and a valid
    # partial run_report.json still lands — the same contract bench.py's
    # supervisor keeps for its one-JSON-line stdout
    faults.install_sigterm_handler()
    init_backend_or_die(args.init_timeout,
                        platform="cpu" if cfg.backend == "cpu" else None)
    seq_names = get_seq_name_list(cfg.dataset, args.splits_dir, args.seq_name_list)
    log.info("there are %d scenes", len(seq_names))

    obs_events = args.obs_events
    if obs_events is None and args.report:
        # a reported run captures events by default: the report JSON then
        # carries the digest and the path to the full span stream
        root, _ = os.path.splitext(args.report)
        obs_events = root + "_events.jsonl"
    if args.no_obs:
        obs_events = None

    xprof_spans = None
    if args.xprof:
        if obs_events is None:
            log.warning("--xprof needs obs capture (--report or "
                        "--obs_events); ignored")
        else:
            from maskclustering_tpu.obs.xprof import parse_spans

            xprof_spans = parse_spans(args.xprof)

    t0 = time.time()
    report = run_pipeline(
        cfg, seq_names,
        steps=tuple(s for s in args.steps.split(",") if s),
        workers=args.workers,
        resume=not args.no_resume,
        encoder_spec=args.encoder,
        mask_command=args.mask_command,
        profile_dir=args.profile_dir,
        report_path=args.report,
        obs_events=obs_events,
        xprof_spans=xprof_spans,
        xprof_dir=args.xprof_dir,
        ledger_path=args.ledger,
        ledger=not args.no_ledger,
        journal_path=args.journal,
        journal=not args.no_journal,
    )
    total = time.time() - t0
    log.info("total time %.1f min (%.1f s/scene)", total / 60,
             total / max(len(seq_names), 1))
    if report.interrupted or faults.stop_requested():
        # SIGTERM convention (128 + 15): the run stopped cleanly with a
        # valid partial report + journal; rerun with the same --report to
        # resume from the journal. Armed runs ($MCT_FLIGHT_DIR) also drop
        # the flight ring here — the cooperative-drain dump site, never
        # the signal handler (CONC.SIGNAL)
        from maskclustering_tpu.obs import flight
        flight.dump("sigterm" if faults.stop_requested() else "interrupted")
        return 143
    if not report.ok:
        from maskclustering_tpu.obs import flight
        flight.dump("run_failed")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
