"""Mesh-batched scene clustering to artifacts — the multi-chip e2e path.

The reference scales out by completing each scene's pipeline inside one GPU
process, scenes round-robined over GPUs with the filesystem as IPC
(reference run.py:33-50). The TPU analog implemented here:

- scenes batch over the ``scene`` mesh axis, frames shard over ``frame``;
- the whole device pipeline is ONE jitted program per shape bucket
  (parallel/sharded.py `build_fused_step`: association -> graph ->
  schedule -> clustering, zero host syncs);
- ragged scenes are padded to shared static shapes: frames to a multiple of
  lcm(frame axis, cfg.frame_pad_multiple) with ``frame_valid=False``, points
  to a bucket with a far-away sentinel that no frustum ever claims;
- post-process + npz/object_dict export then run per scene on host —
  identical artifacts to the single-chip path (models/pipeline.run_scene),
  which the e2e tests assert byte-for-byte.
"""

from __future__ import annotations

import functools
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.datasets.base import SceneTensors
from maskclustering_tpu.models.pipeline import bucket_k_max
from maskclustering_tpu.models.postprocess import SceneObjects
from maskclustering_tpu.parallel.mesh import make_mesh, point_axis_size
from maskclustering_tpu.parallel.sharded import build_fused_step

from maskclustering_tpu.datasets.base import PAD_COORD as _PAD_COORD


def _round_up(value: int, multiple: int) -> int:
    return max(multiple, -(-value // multiple) * multiple)


def batch_shapes(tensors_list: Sequence[SceneTensors], cfg: PipelineConfig,
                 mesh) -> Tuple[int, int]:
    """(F_pad, N_pad) shared static shapes for a scene batch on ``mesh``.

    On a point mesh N additionally pads to a multiple of the point axis
    so every shard holds an equal column slice of the (F, N) planes (the
    lcm keeps the historical point_chunk rounding when the axis is 1 or
    divides the chunk, which every pow2 shard count does).
    """
    f_axis = int(mesh.shape["frame"])
    f_mult = math.lcm(f_axis, max(cfg.frame_pad_multiple, 1))
    f_pad = _round_up(max(t.num_frames for t in tensors_list), f_mult)
    n_mult = math.lcm(point_axis_size(mesh), max(cfg.point_chunk, 1))
    n_pad = _round_up(max(t.num_points for t in tensors_list), n_mult)
    return f_pad, n_pad


def pad_scene_batch(tensors_list: Sequence[SceneTensors], f_pad: int, n_pad: int,
                    num_scenes: int,
                    pad_tensors: Optional[SceneTensors] = None):
    """Stack scenes into the fused step's batched arrays.

    Short batches fill the lanes past ``len(tensors_list)`` with
    ``pad_tensors`` when given (the serving scheduler's warm synthetic
    scene — keeps partial batches on the full-width executable), else
    repeat the last scene; either way the pad lanes' outputs are discarded
    by the caller (``cluster_scene_batch`` post-processes real lanes only,
    so pad lanes never reach export or accounting). Scene lanes are
    data-parallel over the ``scene`` mesh axis — pad-lane contents cannot
    perturb a real lane's bytes. Padded frames are invalid, padded points
    sit at the sentinel. Returns the 6-tuple of (S, ...) arrays.
    """
    h, w = tensors_list[0].depths.shape[1:3]
    s = num_scenes
    pts = np.full((s, n_pad, 3), _PAD_COORD, dtype=np.float32)
    depths = np.zeros((s, f_pad, h, w), dtype=np.float32)
    segs = np.zeros((s, f_pad, h, w), dtype=np.int32)
    intr = np.tile(np.eye(3, dtype=np.float32), (s, f_pad, 1, 1))
    c2w = np.tile(np.eye(4, dtype=np.float32), (s, f_pad, 1, 1))
    fv = np.zeros((s, f_pad), dtype=bool)
    for i in range(s):
        if i >= len(tensors_list) and pad_tensors is not None:
            t = pad_tensors
        else:
            t = tensors_list[min(i, len(tensors_list) - 1)]
        f, n = t.num_frames, t.num_points
        pts[i, :n] = t.scene_points
        depths[i, :f] = t.depths
        segs[i, :f] = t.segmentations
        intr[i, :f] = t.intrinsics
        c2w[i, :f] = t.cam_to_world
        fv[i, :f] = t.frame_valid

    # compact feed (io/feed.py): ship uint16 over the host->device link when
    # bit-exact; the fused step infers the scale from the dtype alone, so
    # only FUSED_FEED_DEPTH_SCALE is attempted (other quantizations stay f32)
    from maskclustering_tpu.io.feed import (
        FUSED_FEED_DEPTH_SCALE, encode_depth, encode_seg)

    enc, scale = encode_depth(depths, scales=(FUSED_FEED_DEPTH_SCALE,))
    if scale:
        depths = enc
    return pts, depths, encode_seg(segs), intr, c2w, fv


def fused_scene_objects(
    out, index: int, tensors: SceneTensors, cfg: PipelineConfig, k_max: int,
    timings: Optional[Dict[str, float]] = None,
    seq_name: Optional[str] = None,
) -> SceneObjects:
    """Host post-process of one scene of a FusedStepResult batch.

    Uses the fused path's dense (frame, id) slot table; object ordering and
    artifact bytes match the single-chip path because both enumerate masks
    ascending by (frame, id) and representatives are min-index labels.
    """
    f_pad, n_pad = out.first_id.shape[1], out.first_id.shape[2]
    mask_frame = np.repeat(np.arange(f_pad, dtype=np.int32), k_max)
    mask_id = np.tile(np.arange(1, k_max + 1, dtype=np.int32), f_pad)
    frame_ids = list(tensors.frame_ids)
    frame_ids += [None] * (f_pad - len(frame_ids))

    from maskclustering_tpu.models.postprocess_device import run_postprocess

    return run_postprocess(
        cfg, out_scene_points(tensors, n_pad), out.first_id[index],
        out.last_id[index], mask_frame, mask_id, out.mask_active[index],
        out.assignment[index], out.node_visible[index], frame_ids,
        k_max=k_max, timings=timings, n_real=tensors.num_points,
        # the post fault seam needs the scene identity to fire on the
        # fused-mesh path too (capacity drills must cover both paths)
        seq_name=seq_name)


def out_scene_points(tensors: SceneTensors, n_pad: int) -> np.ndarray:
    """Scene cloud re-padded to the batch bucket (sentinel coords)."""
    pts = np.asarray(tensors.scene_points, dtype=np.float32)
    if pts.shape[0] == n_pad:
        return pts
    out = np.full((n_pad, 3), _PAD_COORD, dtype=np.float32)
    out[: pts.shape[0]] = pts
    return out


@functools.lru_cache(maxsize=None)
def _cached_step(mesh, cfg: PipelineConfig, k_max: int):
    """One jitted fused step per (mesh, cfg, k_max) — reuse across batches.

    ``cfg`` is a frozen dataclass, so every knob that shapes the program —
    including ``count_dtype`` — is part of the cache key: the bf16 and
    int8 counting variants compile (and persist in the compilation cache)
    as distinct fused steps with bit-identical outputs
    (tests/test_counting.py).

    The depth/seg batch operands are built fresh per flush by
    ``pad_scene_batch`` (host-side stacking + feed encode) and are dead
    after the step, so they are donated when ``cfg.donate_buffers`` is on:
    one batch's frame buffers — the dominant HBM tenants — recycle into
    the next same-bucket dispatch (contract pinned by
    tests/test_parallel.py::test_fused_step_donate_path_identity).
    """
    return build_fused_step(mesh, cfg, k_max=k_max,
                            donate=bool(cfg.donate_buffers))


def cluster_scene_batch(
    cfg: PipelineConfig,
    mesh,
    tensors_list: Sequence[SceneTensors],
    *,
    k_max: Optional[int] = None,
    seq_names: Optional[Sequence[str]] = None,
    pads: Optional[Tuple[int, int]] = None,
    width: Optional[int] = None,
    pad_tensors: Optional[SceneTensors] = None,
) -> List[SceneObjects]:
    """Run a batch of scenes through the fused mesh step to SceneObjects.

    The batch is padded up to a multiple of the ``scene`` axis; every scene
    in it shares one (F_pad, N_pad, k_max) shape bucket, so distinct buckets
    compile once each (lru-cached jit).

    The serving scheduler's packing kwargs pin the dispatch shape
    independently of the members so every partial batch reuses one warm
    executable: ``pads`` is a (f_pad, n_pad) floor (re-rounded to the mesh
    lcm multiples — the members' natural shapes never exceed it when they
    classified into the bucket), ``width`` is a scene-lane floor (the batch
    is padded up to it, then to the scene-axis multiple), and
    ``pad_tensors`` fills those extra lanes with a warm synthetic scene.
    Only the ``len(tensors_list)`` real lanes are post-processed — the
    demux drops pad lanes before export, digesting, or accounting.
    """
    if not tensors_list:
        return []
    s_axis = int(mesh.shape["scene"])
    num_scenes = _round_up(max(len(tensors_list), int(width or 0)), s_axis)
    f_pad, n_pad = batch_shapes(tensors_list, cfg, mesh)
    if pads is not None:
        f_mult = math.lcm(int(mesh.shape["frame"]),
                          max(cfg.frame_pad_multiple, 1))
        n_mult = math.lcm(point_axis_size(mesh), max(cfg.point_chunk, 1))
        f_pad = _round_up(max(f_pad, int(pads[0])), f_mult)
        n_pad = _round_up(max(n_pad, int(pads[1])), n_mult)
    if k_max is None:
        max_id = max(int(np.max(t.segmentations)) if np.size(t.segmentations) else 0
                     for t in tensors_list)
        k_max = bucket_k_max(max_id)

    step = _cached_step(mesh, cfg, k_max)
    args = pad_scene_batch(tensors_list, f_pad, n_pad, num_scenes,
                           pad_tensors=pad_tensors)
    # persistent AOT cache: a warm-started process dispatches the restored
    # fused step (zero tracing); a cold bucket captures its export for the
    # next process. Keyed through the sharded.py export seam so the census
    # coordinates stay one vocabulary.
    from maskclustering_tpu.parallel.sharded import fused_step_aot_key
    from maskclustering_tpu.utils import aot_cache

    if aot_cache.active() is not None:
        step = aot_cache.serving_callable(
            fused_step_aot_key(mesh, cfg, k_max, args), step, args,
            donate_argnums=(1, 2) if cfg.donate_buffers else ())
    out = jax.block_until_ready(step(*args))
    names = (list(seq_names) if seq_names is not None
             else [None] * len(tensors_list))
    return [fused_scene_objects(out, i, tensors_list[i], cfg, k_max,
                                seq_name=names[i])
            for i in range(len(tensors_list))]


def make_run_mesh(cfg: PipelineConfig):
    """Mesh from cfg.mesh_shape (+ cfg.point_shards) over the devices.

    ``point_shards > 1`` appends the third mesh axis: the device product
    becomes scene * frame * point, validated by make_mesh against the
    backend's device count (config.py already rejects point_shards > 1
    without a mesh). ``point_shards == 1`` builds the historical 2-axis
    mesh — same axis names, same programs, same compile-cache keys.
    """
    shape = tuple(cfg.mesh_shape)
    if cfg.point_shards > 1:
        shape = shape + (int(cfg.point_shards),)
    return make_mesh(shape)
