"""Mesh sharding and multi-chip execution (ICI/DCN collectives via XLA)."""

from maskclustering_tpu.parallel.batch import cluster_scene_batch, fused_scene_objects
from maskclustering_tpu.parallel.mesh import (
    constrain,
    make_mesh,
    mesh_label,
    point_axis_size,
    point_spec,
    sharding,
)
from maskclustering_tpu.parallel.sharded import (
    FusedStepResult,
    build_fused_step,
    fused_step_example_args,
)

__all__ = [
    "cluster_scene_batch",
    "constrain",
    "fused_scene_objects",
    "make_mesh",
    "mesh_label",
    "point_axis_size",
    "point_spec",
    "sharding",
    "FusedStepResult",
    "build_fused_step",
    "fused_step_example_args",
]
