"""Mesh-sharded, fully-jitted pipeline step (the multi-chip path).

The reference scales by launching one OS process per GPU over a scene list
(reference run.py:33-50); inside a scene everything is single-device. Here
the *entire* per-scene pipeline — projective association, mask-graph
statistics, observer schedule, iterative clustering — is one jitted program
over a `jax.sharding.Mesh`, with a leading scene batch axis:

- scenes  -> ``scene`` mesh axis (data parallelism; vmap with
  ``spmd_axis_name`` so batch collectives partition over the axis);
- frames  -> ``frame`` mesh axis (sequence parallelism: per-frame
  association is independent; XLA turns the cross-frame reductions —
  boundary OR, first/last min/max — into psums over ICI);
- masks   -> masks are ordered by frame, so the (M_pad, F) visibility and
  (M_pad, M_pad) containment/affinity matrices row-shard over the same
  ``frame`` axis; the V@V^T / C@C^T consensus matmuls become
  all-gather + local matmul, inserted by XLA from the constraints;
- points  -> with a ``point`` mesh axis (cfg.point_shards > 1) the scene
  cloud, ``mask_of_point`` and the (F, N) first/last claim planes — the
  largest long-lived HBM residents — column-shard over it. Association
  is elementwise in N (each shard backprojects its own points against
  the replicated frames), and the graph co-occurrence/observer
  contractions reduce over N, which XLA partitions as per-shard partial
  counts + a psum over ``point`` — exact under both counting encodings
  (integer summands in f32/s32 accumulators; order cannot move a byte),
  so artifacts stay byte-identical to the unsharded program
  (tests/test_point_sharding.py).

This fused path uses a *dense* mask slot table (slot = frame * K_max + id),
trading padding FLOPs for zero host syncs — the right trade on a pod where
a host roundtrip costs more than padded MXU work. The single-chip path
(models/pipeline.py) instead compacts masks on host between stages.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from maskclustering_tpu.io.feed import (
    FUSED_FEED_DEPTH_SCALE,
    decode_depth,
    decode_seg,
)
from maskclustering_tpu.models.backprojection import associate_frame, estimate_spacing
from maskclustering_tpu.models.clustering import iterative_clustering
from maskclustering_tpu.models.graph import compute_graph_stats, observer_schedule_device
from maskclustering_tpu.parallel.mesh import (
    constrain,
    mesh_label,
    point_spec,
    sharding,
)


def _maybe_constrain(x, mesh, *spec):
    return x if mesh is None else constrain(x, mesh, *spec)


class FusedStepResult(NamedTuple):
    """Per-scene-batch outputs of the fused step. Leading axis = scenes."""

    assignment: jnp.ndarray  # (S, M_pad) int32 representative slot per mask slot
    node_visible: jnp.ndarray  # (S, M_pad, F) bool aggregated visible_frame per rep
    mask_active: jnp.ndarray  # (S, M_pad) bool valid & not undersegmented
    mask_of_point: jnp.ndarray  # (S, F, N) int32 point-in-mask matrix
    first_id: jnp.ndarray  # (S, F, N) int16
    last_id: jnp.ndarray  # (S, F, N) int16
    num_objects: jnp.ndarray  # (S,) int32 live representative count


def _dense_mask_table(num_frames: int, k_max: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Static (frame, id) table covering every (frame, mask-id) slot."""
    mask_frame = jnp.repeat(jnp.arange(num_frames, dtype=jnp.int32), k_max)
    mask_id = jnp.tile(jnp.arange(1, k_max + 1, dtype=jnp.int32), num_frames)
    return mask_frame, mask_id


def _assoc_stage(cfg, k_max, mesh, scene_points, depths, segs, intrinsics,
                 cam_to_world, frame_valid):
    """Backprojection stage of the per-scene program (unbatched).

    Compact-feed decode (io/feed.py): uint16 depth carries
    FUSED_FEED_DEPTH_SCALE quanta by convention (pad_scene_batch only
    engages that one scale); f32 passes through untouched. dtype is static,
    so jit specializes one program per feed encoding.
    """
    if depths.dtype == jnp.uint16:
        depths = decode_depth(depths, FUSED_FEED_DEPTH_SCALE)
    segs = decode_seg(segs)

    # ---- association: vmap over frames (sequence-parallel) ----
    # the point-axis constraints are strictly additive: on a 2-axis mesh
    # pt is None and no new constraint is emitted, so the historical
    # frame-sharded program lowers unchanged
    pt = point_spec(mesh)
    spacing_cloud = scene_points
    if pt is not None:
        # the spacing estimate is a scalar statistic of a ~2k-point
        # sample; feeding it the point-sharded cloud makes GSPMD reshard
        # the (sample, chunk) all-pairs intermediate mid-reduction
        # (observed: a ~100 MB all-to-all at the 1k-point canonical
        # shape). A replicated copy costs one N x 3 all-gather and the
        # estimate runs shard-locally, byte-identically.
        spacing_cloud = _maybe_constrain(scene_points, mesh, None, None)
        scene_points = _maybe_constrain(scene_points, mesh, pt, None)
    vox_size = jnp.maximum(jnp.float32(cfg.distance_threshold),
                           estimate_spacing(spacing_cloud))

    def one_frame(depth, seg, intr, c2w, fv):
        fa = associate_frame(
            scene_points, depth, seg, intr, c2w, fv, vox_size,
            k_max=k_max, window=cfg.association_window,
            distance_threshold=cfg.distance_threshold,
            depth_trunc=cfg.depth_trunc,
            few_points_threshold=cfg.few_points_threshold,
            coverage_threshold=cfg.coverage_threshold,
            count_dtype=cfg.count_dtype,
        )
        return fa.mask_of_point, fa.first_id, fa.last_id, fa.mask_valid

    mop, first, last, mask_valid = jax.vmap(one_frame)(
        depths, segs, intrinsics, cam_to_world, frame_valid)
    # the (F, N) residents shard over frame AND — on a point mesh — the
    # point axis (their N columns divide across chips; that residency cut
    # is the whole reason the axis exists)
    mop = _maybe_constrain(mop, mesh, "frame", pt)
    first = _maybe_constrain(first, mesh, "frame", pt)
    last = _maybe_constrain(last, mesh, "frame", pt)

    # cross-frame reductions: XLA lowers these to psums over `frame`
    boundary = jnp.any(first != last, axis=0)
    if pt is not None:
        boundary = _maybe_constrain(boundary, mesh, pt)
    return mop, first, last, mask_valid, boundary


def _graph_stage(cfg, k_max, mesh, mop, boundary, active0):
    """Mask-graph statistics over the dense slot table (unbatched)."""
    f = mop.shape[0]
    mask_frame, mask_id = _dense_mask_table(f, k_max)
    stats = compute_graph_stats(
        mop, boundary, mask_frame, mask_id, active0,
        k_max=k_max, point_chunk=cfg.point_chunk,
        mask_visible_threshold=cfg.mask_visible_threshold,
        contained_threshold=cfg.contained_threshold,
        undersegment_filter_threshold=cfg.undersegment_filter_threshold,
        big_mask_point_count=cfg.big_mask_point_count,
        count_dtype=cfg.count_dtype,
    )
    visible = _maybe_constrain(stats.visible, mesh, "frame", None)
    contained = _maybe_constrain(stats.contained, mesh, "frame", None)
    return stats._replace(visible=visible, contained=contained)


def _cluster_stage(cfg, mesh, visible, contained, active, schedule):
    """Iterative view-consensus clustering (unbatched)."""
    result = iterative_clustering(
        visible, contained, active, schedule,
        view_consensus_threshold=cfg.view_consensus_threshold,
        count_dtype=cfg.count_dtype)
    assignment = _maybe_constrain(result.assignment, mesh, "frame")
    return result._replace(assignment=assignment)


def build_fused_step(mesh, cfg, *, k_max: int = 15, donate: bool = False):
    """Compile-ready fused pipeline step over `mesh`.

    Returns a jitted function of the batched scene arrays
    ``(scene_points (S,N,3), depths (S,F,H,W), segs (S,F,H,W),
    intrinsics (S,F,3,3), cam_to_world (S,F,4,4), frame_valid (S,F))``
    producing a `FusedStepResult`. All shapes static; S must equal the
    ``scene`` axis size times any per-device scene batch. ``mesh=None``
    gives the same program with no sharding (single-chip compile checks).

    ``donate=True`` donates the depth/seg frame stacks — the batch's
    dominant HBM tenants, dead after the step — so their buffers recycle
    into the next same-bucket dispatch. The caller must not touch the
    passed arrays afterwards, and device-array operands must already be
    placed with this step's in_shardings (else the resharding copy, not
    the caller's buffer, is what donation consumes). Results are
    byte-identical to the non-donating step; backends without sharded
    donation leave the operands intact (both pinned by
    tests/test_parallel.py::test_fused_step_donate_path_identity).

    Retrace contract: this builder returns a FRESH jit wrapper per call —
    callers must cache per (mesh, cfg, k_max, donate)
    (parallel/batch._cached_step is the production lru_cache; the cost
    observatory lowers offline). That caching story is what keeps it in
    mct-check's ``CACHED_BY_CALLER`` allowlist (analysis/retrace.py); the
    ``per_scene`` program it traces is registered there too, and the
    census pins one executable per lattice mesh via the lowered main
    signature.
    """

    def per_scene(scene_points, depths, segs, intrinsics, cam_to_world, frame_valid):
        mop, first, last, mask_valid, boundary = _assoc_stage(
            cfg, k_max, mesh, scene_points, depths, segs, intrinsics,
            cam_to_world, frame_valid)
        f = depths.shape[0]

        # ---- dense mask table + graph statistics ----
        mask_frame, mask_id = _dense_mask_table(f, k_max)
        active0 = mask_valid[mask_frame, mask_id]  # (M_pad,) slot validity
        stats = _graph_stage(cfg, k_max, mesh, mop, boundary, active0)

        # ---- schedule + clustering, all on device ----
        schedule = observer_schedule_device(
            stats.observer_hist, max_len=cfg.max_cluster_iterations)
        active = active0 & ~stats.undersegment
        result = _cluster_stage(cfg, mesh, stats.visible, stats.contained,
                                active, schedule)
        num_objects = jnp.sum(result.node_active & active).astype(jnp.int32)
        return FusedStepResult(
            assignment=result.assignment,
            node_visible=result.node_visible,
            mask_active=active,
            mask_of_point=mop,
            first_id=first,
            last_id=last,
            num_objects=num_objects,
        )

    if mesh is None:
        return jax.jit(jax.vmap(per_scene))
    batched = jax.vmap(per_scene, spmd_axis_name="scene")

    # point-axis policy: the scene cloud and the (F, N) planes shard their
    # N dimension over `point`; per-frame camera/image tensors omit the
    # axis from their spec, i.e. stay replicated across it (every point
    # shard backprojects against the full frame set). pt is None on a
    # 2-axis mesh, where these specs are exactly the historical ones.
    pt = point_spec(mesh)
    in_shardings = (
        sharding(mesh, "scene", pt),             # scene_points (S, N, 3)
        sharding(mesh, "scene", "frame"),        # depths (S, F, H, W)
        sharding(mesh, "scene", "frame"),        # segs
        sharding(mesh, "scene", "frame"),        # intrinsics
        sharding(mesh, "scene", "frame"),        # cam_to_world
        sharding(mesh, "scene", "frame"),        # frame_valid
    )
    out_shardings = FusedStepResult(
        assignment=sharding(mesh, "scene", "frame"),
        node_visible=sharding(mesh, "scene", "frame", None),
        mask_active=sharding(mesh, "scene", "frame"),
        mask_of_point=sharding(mesh, "scene", "frame", pt),
        first_id=sharding(mesh, "scene", "frame", pt),
        last_id=sharding(mesh, "scene", "frame", pt),
        num_objects=sharding(mesh, "scene"),
    )
    return jax.jit(
        batched,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        # (1, 2) = depths, segs — pinned by mct-check IR.DONATION.WIRING:
        # changing the tuple (or dropping it) fails the analysis gate
        donate_argnums=(1, 2) if donate else (),
    )


# ---------------------------------------------------------------------------
# AOT export seam (the persistent executable cache, utils/aot_cache.py)
# ---------------------------------------------------------------------------


def fused_step_aot_key(mesh, cfg, k_max: int, args):
    """The fused step's persistent-AOT-cache key (census coordinates).

    One entry per (mesh shape, scene batch bucket, k_max, count_dtype,
    donation) — the same axes the retrace census's "fused" section pins
    per mesh. ``args`` supplies the batched arg avals (shapes + dtypes,
    nothing is read); parallel/batch.py consults/captures through this
    seam so a respawned process re-dispatches the serialized step instead
    of re-tracing ~400 frames of scan body. The mesh descriptor is the
    compile-surface mesh label — ``SxF`` historically, ``SxFxP`` on a
    point mesh — so the point-shard count is a first-class cache-key
    coordinate (a resharded deployment never dispatches a stale layout).
    """
    from maskclustering_tpu.utils import aot_cache

    mesh_desc = (mesh_label(tuple(int(mesh.shape[a])
                                  for a in mesh.axis_names))
                 if mesh is not None else "none")
    return aot_cache.key_for(
        "per_scene", args,
        statics={"mesh": mesh_desc, "k_max": int(k_max)},
        count_dtype=str(cfg.count_dtype), donate=bool(cfg.donate_buffers))


# ---------------------------------------------------------------------------
# per-stage AOT hooks (the compile-time cost observatory, obs/cost.py)
# ---------------------------------------------------------------------------

# the staged stage functions the observatory lowers, in pipeline order;
# "fused" (the whole step) is handled by build_fused_step directly
STAGE_NAMES = ("backprojection", "graph", "clustering", "postprocess")


def build_stage_step(stage: str, mesh, cfg, *, k_max: int = 15,
                     r_pad: int = 64):
    """One pipeline stage as a compile-ready jitted program over ``mesh``.

    The cost observatory (obs/cost.py) AOT-lowers these with abstract
    shapes (`stage_arg_shapes`) to read per-stage FLOPs, HBM traffic,
    XLA's memory plan, and the collective census out of the compiled HLO
    — nothing is ever materialized, so this runs on CPU virtual devices.

    Stages reuse the exact per-scene sections the fused step runs
    (`_assoc_stage` / `_graph_stage` / `_cluster_stage`), batched with
    ``spmd_axis_name="scene"`` and the fused step's input shardings, so
    the census reflects the production program, not a lookalike.

    ``postprocess`` is the `post.claims` node-stats kernel
    (models/postprocess_device._node_stats_kernel): it runs per-scene on
    one chip in production, so it compiles unsharded regardless of
    ``mesh`` (its census answers the kernel-vs-tunnel question — fusion
    and copy counts — not an ICI question).
    """
    if stage not in STAGE_NAMES:
        raise ValueError(f"unknown stage {stage!r}; valid: {STAGE_NAMES}")

    if stage == "postprocess":
        from maskclustering_tpu.models.postprocess_device import _node_stats_kernel

        def post(first, last, rep_tab, node_visible, live_slots, live_valid):
            return _node_stats_kernel(
                first, last, rep_tab, node_visible, live_slots, live_valid,
                r_pad=r_pad,
                point_filter_threshold=float(cfg.point_filter_threshold),
                count_dtype=cfg.count_dtype)

        return jax.jit(post)

    pt = point_spec(mesh)
    if stage == "backprojection":
        fn = lambda *args: _assoc_stage(cfg, k_max, mesh, *args)  # noqa: E731
        specs = (("scene", pt), ("scene", "frame"), ("scene", "frame"),
                 ("scene", "frame"), ("scene", "frame"), ("scene", "frame"))
    elif stage == "graph":
        fn = lambda *args: _graph_stage(cfg, k_max, mesh, *args)  # noqa: E731
        specs = (("scene", "frame", pt), ("scene", pt), ("scene", "frame"))
    else:  # clustering
        fn = lambda *args: _cluster_stage(cfg, mesh, *args)  # noqa: E731
        specs = (("scene", "frame", None), ("scene", "frame", None),
                 ("scene", "frame"), ("scene",))

    if mesh is None:
        return jax.jit(jax.vmap(fn))
    return jax.jit(jax.vmap(fn, spmd_axis_name="scene"),
                   in_shardings=tuple(sharding(mesh, *s) for s in specs))


def stage_arg_shapes(stage: str, *, scenes: int = 1, frames: int = 8,
                     points: int = 4096, image_hw: Tuple[int, int] = (32, 48),
                     k_max: int = 15, max_iters: int = 20, r_pad: int = 64):
    """Abstract argument shapes for ``build_stage_step(stage, ...).lower``.

    Shapes follow the fused path's dense slot layout: ``M_pad = F * k_max``;
    the clustering schedule is the fixed-length observer-threshold vector
    (cfg.max_cluster_iterations). ``postprocess`` uses the claims kernel's
    own operands with ``k2 = k_max + 2`` local-id rows and ``r_pad`` live
    representative slots (floor 64, matching _live_rep_prep).
    """
    s, f, n = scenes, frames, points
    h, w = image_hw
    m_pad = f * k_max
    sds = jax.ShapeDtypeStruct
    if stage == "backprojection":
        return (sds((s, n, 3), jnp.float32), sds((s, f, h, w), jnp.uint16),
                sds((s, f, h, w), jnp.uint16), sds((s, f, 3, 3), jnp.float32),
                sds((s, f, 4, 4), jnp.float32), sds((s, f), jnp.bool_))
    if stage == "graph":
        return (sds((s, f, n), jnp.int32), sds((s, n), jnp.bool_),
                sds((s, m_pad), jnp.bool_))
    if stage == "clustering":
        return (sds((s, m_pad, f), jnp.bool_), sds((s, m_pad, m_pad), jnp.bool_),
                sds((s, m_pad), jnp.bool_), sds((s, max_iters), jnp.float32))
    if stage == "postprocess":
        k2 = k_max + 2
        # first/last are the int16 claim planes the association stage emits
        return (sds((f, n), jnp.int16), sds((f, n), jnp.int16),
                sds((f, k2), jnp.int32), sds((m_pad, f), jnp.bool_),
                sds((r_pad,), jnp.int32), sds((r_pad,), jnp.bool_))
    raise ValueError(f"unknown stage {stage!r}; valid: {STAGE_NAMES}")


def fused_step_example_args(num_scenes: int = 2, num_frames: int = 8,
                            num_points: int = 4096, image_hw=(32, 48), seed: int = 0,
                            spacing: float = 0.08):
    """Tiny synthetic scene batch for compile checks and dryruns.

    ``spacing``/``num_points`` are chosen so no scene exceeds the point
    budget — points are padded by tiling (harmless duplicates), never
    truncated (truncation would starve later boxes of coverage).
    """
    from maskclustering_tpu.utils.synthetic import make_scene

    scenes = [
        make_scene(num_boxes=3, num_frames=num_frames, image_hw=image_hw,
                   spacing=spacing, seed=seed + i)
        for i in range(num_scenes)
    ]
    n = num_points

    def pad_points(p):
        if p.shape[0] > n:
            raise ValueError(f"scene has {p.shape[0]} points > budget {n}; "
                             f"raise num_points or spacing")
        reps = -(-n // p.shape[0])
        return np.tile(p, (reps, 1))[:n]

    return (
        np.stack([pad_points(s.scene_points) for s in scenes]).astype(np.float32),
        np.stack([s.depths for s in scenes]),
        np.stack([s.segmentations for s in scenes]),
        np.stack([s.intrinsics for s in scenes]),
        np.stack([s.cam_to_world for s in scenes]),
        np.stack([s.frame_valid for s in scenes]),
    )
