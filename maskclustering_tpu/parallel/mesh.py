"""Device-mesh construction and sharding helpers.

The reference's only distribution mechanism is OS processes pinned to GPUs
via ``CUDA_VISIBLE_DEVICES`` with the filesystem as IPC (reference
run.py:8-17,33-50). The TPU analog is a `jax.sharding.Mesh` over the slice:
collectives ride ICI, sharding is declared with `NamedSharding` /
`PartitionSpec`, and XLA inserts the communication.

Axis convention for this workload:

- ``scene``  — data parallelism over scenes (the reference's per-GPU scene
  sharding, run.py:33-38, but inside one jit instead of one OS process).
- ``frame``  — sequence parallelism: RGB-D frames are the "sequence" axis;
  per-frame association is embarrassingly parallel and the mask axis
  (masks are ordered by frame) inherits the same sharding for the
  O(M^2) affinity matmuls.
- ``point``  — optional third axis (``cfg.point_shards > 1``): the scene
  cloud and every (.., N)-shaped resident — ``mask_of_point`` and the
  (F, N) first/last claim planes, the scene's largest HBM tenants —
  shard over it, so million-point scenes divide across chips instead of
  hitting one chip's HBM wall. Points are embarrassingly parallel
  through backprojection/association; the graph co-occurrence
  contractions reduce over the point axis, so XLA turns them into
  per-shard partial counts + a psum over ``point`` (exact in either
  counting encoding: the accumulators are f32/s32 and the summands are
  integers, so partial-sum order cannot change a byte). Per-frame
  camera/image tensors stay replicated across ``point``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# canonical axis-name ladder: a 2-tuple shape is (scene, frame), a 3-tuple
# adds the trailing point axis (ONE vocabulary across parallel/, the cost
# observatory, mct-check's IR lattice and the AOT-cache mesh coordinate)
MESH_AXIS_NAMES: Tuple[str, ...] = ("scene", "frame", "point")


def make_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Optional[Tuple[str, ...]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh over the available devices.

    With ``shape=None`` all devices land on the last axis (pure
    sequence/tensor parallelism); a leading ``scene`` axis of size 1 keeps
    the in_shardings uniform whether or not scene DP is used.
    ``axis_names=None`` resolves from the canonical ladder by rank: a
    2-tuple shape is ``(scene, frame)``, a 3-tuple ``(scene, frame,
    point)``.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_names is None:
        rank = len(shape) if shape is not None else 2
        if not (1 <= rank <= len(MESH_AXIS_NAMES)):
            raise ValueError(f"mesh shape {shape} has rank {rank}; the "
                             f"axis ladder is {MESH_AXIS_NAMES}")
        axis_names = MESH_AXIS_NAMES[:rank]
    if shape is None:
        shape = (1,) * (len(axis_names) - 1) + (n,)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    return Mesh(np.array(devices).reshape(shape), axis_names)


def mesh_label(shape: Tuple[int, ...]) -> str:
    """``SxF`` / ``SxFxP`` label of a mesh shape — ONE string vocabulary
    across the cost observatory rows, mct-check's fused-surface census,
    the AOT-cache mesh coordinate and the CLI ``--mesh`` grammar."""
    return "x".join(str(int(d)) for d in shape)


def point_spec(mesh: Optional[Mesh]) -> Optional[str]:
    """``"point"`` when the mesh carries a point axis, else None.

    A None entry in a PartitionSpec means replicated, so constraint sites
    can thread this straight into their specs: 2-axis meshes compile the
    byte-identical historical program (the point entry degenerates to
    replication) and 3-axis meshes shard the N-sized dimensions.
    """
    if mesh is not None and "point" in mesh.axis_names:
        return "point"
    return None


def point_axis_size(mesh: Optional[Mesh]) -> int:
    """Size of the mesh's point axis (1 when absent — unsharded points)."""
    if mesh is not None and "point" in mesh.axis_names:
        return int(mesh.shape["point"])
    return 1


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    """`NamedSharding(mesh, PartitionSpec(*spec))` shorthand."""
    return NamedSharding(mesh, PartitionSpec(*spec))


def constrain(x, mesh: Mesh, *spec):
    """with_sharding_constraint shorthand (no-op outside jit tracing)."""
    return jax.lax.with_sharding_constraint(x, sharding(mesh, *spec))
