"""Device-mesh construction and sharding helpers.

The reference's only distribution mechanism is OS processes pinned to GPUs
via ``CUDA_VISIBLE_DEVICES`` with the filesystem as IPC (reference
run.py:8-17,33-50). The TPU analog is a `jax.sharding.Mesh` over the slice:
collectives ride ICI, sharding is declared with `NamedSharding` /
`PartitionSpec`, and XLA inserts the communication.

Axis convention for this workload:

- ``scene``  — data parallelism over scenes (the reference's per-GPU scene
  sharding, run.py:33-38, but inside one jit instead of one OS process).
- ``frame``  — sequence parallelism: RGB-D frames are the "sequence" axis;
  per-frame association is embarrassingly parallel and the mask axis
  (masks are ordered by frame) inherits the same sharding for the
  O(M^2) affinity matmuls.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Tuple[str, ...] = ("scene", "frame"),
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh over the available devices.

    With ``shape=None`` all devices land on the last axis (pure
    sequence/tensor parallelism); a leading ``scene`` axis of size 1 keeps
    the in_shardings uniform whether or not scene DP is used.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (1,) * (len(axis_names) - 1) + (n,)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    return Mesh(np.array(devices).reshape(shape), axis_names)


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    """`NamedSharding(mesh, PartitionSpec(*spec))` shorthand."""
    return NamedSharding(mesh, PartitionSpec(*spec))


def constrain(x, mesh: Mesh, *spec):
    """with_sharding_constraint shorthand (no-op outside jit tracing)."""
    return jax.lax.with_sharding_constraint(x, sharding(mesh, *spec))
