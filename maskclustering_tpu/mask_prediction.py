"""L2 2D mask prediction: pluggable predictors + the id-map PNG contract.

The reference's mask_predict.py is a detectron2/CropFormer demo script that
writes one id-map PNG per frame: masks with confidence >= 0.5 and >= 400
pixels, numbered 1..K in ascending confidence order so higher-confidence
masks overwrite lower ones (reference mask_predict.py:94-114). That PNG is
the entire L2 -> L3 interface (SURVEY.md §1), which makes the predictor
itself pluggable: anything that returns (masks, scores) per image can feed
the pipeline.

This module keeps that contract TPU-first:

- `rasterize_id_map` turns (K,H,W) masks + scores into the id-map with one
  vectorised max-reduction (ids ascend with confidence, so "later
  overwrites earlier" == per-pixel max of id*mask) instead of the
  reference's per-mask Python loop.
- `predict_scene_masks` runs any predictor over a scene's frames and
  writes `<scene>/output/mask/<frame>.png`.
- `GridSegmenter` is a dependency-free fallback predictor (color
  quantisation + connected components) for demos and tests.
- `TorchCropFormerPredictor` adapts a detectron2/CropFormer checkpoint
  when those (GPU-stack) packages are installed; it is import-gated and
  never required.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, List, Optional, Protocol, Sequence, Tuple

import numpy as np

from maskclustering_tpu.io.image import write_mask_png

CONFIDENCE_THRESHOLD = 0.5  # reference mask_predict.py confidence flag default
MIN_MASK_PIXELS = 400  # reference mask_predict.py:109


class MaskPredictor(Protocol):
    """Any per-image instance segmenter: rgb (H,W,3) -> (masks, scores)."""

    def __call__(self, rgb: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Returns ((K,H,W) bool masks, (K,) float scores)."""
        ...


def rasterize_id_map(
    masks: np.ndarray,
    scores: np.ndarray,
    confidence_threshold: float = CONFIDENCE_THRESHOLD,
    min_pixels: int = MIN_MASK_PIXELS,
) -> np.ndarray:
    """(K,H,W) masks + (K,) scores -> id-map PNG array (0 = background).

    Reference semantics (mask_predict.py:96-114): drop masks below the
    confidence threshold, iterate the rest in ascending score order
    assigning ids 1..K (sub-400-pixel masks are skipped and consume no
    id), each mask overwriting previously written pixels. Ids ascend with
    confidence, so the overwrite loop is equivalent to a per-pixel max of
    `id_k * mask_k` — one vectorised reduction.
    """
    masks = np.asarray(masks)
    scores = np.asarray(scores)
    if masks.ndim != 3:
        raise ValueError(f"masks must be (K,H,W), got {masks.shape}")
    h, w = masks.shape[1:]
    keep = scores >= confidence_threshold
    masks, scores = masks[keep], scores[keep]
    if len(masks):
        big = masks.reshape(len(masks), -1).sum(axis=1) >= min_pixels
        masks, scores = masks[big], scores[big]
    if len(masks) == 0:
        return np.zeros((h, w), dtype=np.uint8)
    order = np.argsort(scores, kind="stable")
    ids = np.empty(len(masks), dtype=np.int64)
    ids[order] = np.arange(1, len(masks) + 1)
    id_map = (masks.astype(np.int64) * ids[:, None, None]).max(axis=0)
    dtype = np.uint16 if len(masks) > 255 else np.uint8
    return id_map.astype(dtype)


def predict_scene_masks(
    dataset,
    predictor: MaskPredictor,
    stride: int = 1,
    output_dir: Optional[str] = None,
    resume: bool = True,
    confidence_threshold: float = CONFIDENCE_THRESHOLD,
    min_pixels: int = MIN_MASK_PIXELS,
    progress: Optional[Callable[[str], None]] = None,
) -> List[str]:
    """Run a predictor over a scene's frames; write id-map PNGs.

    Writes each frame's PNG at the exact path the dataset will read it
    back from (``get_frame_path``'s segmentation slot — the name scheme is
    per-dataset, e.g. ScanNet++ uses ``frame_NNNNNN.png``); output_dir
    overrides the directory with plain ``<frame_id>.png`` names. Returns
    the list of written paths; resume skips existing PNGs.
    """
    use_frame_path = output_dir is None and hasattr(dataset, "get_frame_path")
    out_dir = output_dir or dataset.segmentation_dir
    os.makedirs(out_dir, exist_ok=True)
    written = []
    for frame_id in dataset.get_frame_list(stride):
        if use_frame_path:
            path = dataset.get_frame_path(frame_id)[1]
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        else:
            path = os.path.join(out_dir, f"{frame_id}.png")
        if resume and os.path.exists(path):
            continue
        rgb = dataset.get_rgb(frame_id)
        masks, scores = predictor(rgb)
        id_map = rasterize_id_map(np.asarray(masks), np.asarray(scores),
                                  confidence_threshold, min_pixels)
        if id_map.size == 0:
            id_map = np.zeros(rgb.shape[:2], dtype=np.uint8)
        write_mask_png(path, id_map)
        written.append(path)
        if progress is not None:
            progress(path)
    return written


# ---------------------------------------------------------------------------
# Fallback predictor: color-quantised connected components (no deps)


@dataclass
class GridSegmenter:
    """Zero-dependency segmenter: color quantisation + 4-connected CCs.

    Not a learned model — a deterministic stand-in that produces
    plausible region masks from RGB alone, used by the demo path and
    tests when no CropFormer checkpoint (or torch GPU stack) exists.
    Confidence is a deterministic function of region size so the id-map
    ordering is stable.
    """

    quant: int = 48  # color quantisation step (uint8 units)
    min_region: int = 64  # pre-filter; rasterize applies MIN_MASK_PIXELS

    def __call__(self, rgb: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        rgb = np.asarray(rgb)
        h, w = rgb.shape[:2]
        q = (rgb.astype(np.int32) // self.quant)
        # base-256 packing is collision-free for any quant >= 1
        key = q[..., 0] * 65536 + q[..., 1] * 256 + q[..., 2]
        labels = _connected_components(key)
        ids, counts = np.unique(labels, return_counts=True)
        keep = ids[counts >= self.min_region]
        masks = np.stack([labels == i for i in keep]) if len(keep) else \
            np.zeros((0, h, w), dtype=bool)
        # larger regions -> higher confidence, capped below 1.0
        sizes = counts[np.searchsorted(ids, keep)] if len(keep) else np.zeros(0)
        scores = 0.5 + 0.5 * sizes / (h * w + 1.0)
        return masks, scores.astype(np.float32)


def _connected_components(key: np.ndarray) -> np.ndarray:
    """4-connected components of equal-valued pixels.

    Vectorised min-label propagation with pointer jumping (converges in
    ~log(diameter) sweeps), so megapixel frames stay fast — the same
    fixpoint scheme the on-TPU clustering uses for graph components
    (models/clustering.py), run host-side on the pixel grid.
    """
    h, w = key.shape
    labels = np.arange(h * w, dtype=np.int64).reshape(h, w)
    same_r = key[:, :-1] == key[:, 1:]
    same_d = key[:-1, :] == key[1:, :]
    while True:
        prev = labels
        lab = labels.copy()
        # min over 4-neighbors with equal keys
        np.minimum(lab[:, 1:], np.where(same_r, labels[:, :-1], lab[:, 1:]),
                   out=lab[:, 1:])
        np.minimum(lab[:, :-1], np.where(same_r, labels[:, 1:], lab[:, :-1]),
                   out=lab[:, :-1])
        np.minimum(lab[1:, :], np.where(same_d, labels[:-1, :], lab[1:, :]),
                   out=lab[1:, :])
        np.minimum(lab[:-1, :], np.where(same_d, labels[1:, :], lab[:-1, :]),
                   out=lab[:-1, :])
        # pointer jumping: chase each label to its current representative
        flat = lab.ravel()
        flat = np.minimum(flat, flat[flat])
        flat = np.minimum(flat, flat[flat])
        labels = flat.reshape(h, w)
        if np.array_equal(labels, prev):
            break
    _, out = np.unique(labels, return_inverse=True)
    return out.reshape(h, w)


def predictor_from_spec(spec: str) -> "MaskPredictor":
    """Mask-predictor factory for config-driven construction.

    ``"grid"`` -> GridSegmenter (dependency-free fallback);
    ``"<detectron2 yaml>::<checkpoint.pth>"`` -> TorchCropFormerPredictor
    (the reference's cropformer_path carries the checkpoint,
    configs/scannet.json:8; the yaml names the architecture).
    """
    if spec == "grid":
        return GridSegmenter()
    if "::" in spec:
        config_file, _, checkpoint = spec.partition("::")
        return TorchCropFormerPredictor(config_file, checkpoint)
    raise ValueError(
        f"unknown mask-predictor spec {spec!r}: use 'grid' or "
        f"'<config.yaml>::<checkpoint.pth>'")


# ---------------------------------------------------------------------------
# Optional torch/detectron2 CropFormer adapter (import-gated)


class TorchCropFormerPredictor:
    """Adapter around a detectron2/CropFormer demo pipeline.

    The reference runs CropFormer through detectron2's VisualizationDemo
    (mask_predict.py:16-21,78,91). Those packages ship CUDA kernels and
    are not part of this framework; when they are installed alongside it,
    this adapter exposes the checkpoint through the MaskPredictor
    interface. Instantiating without them raises a clear ImportError.
    """

    def __init__(self, config_file: str, checkpoint_path: str,
                 opts: Sequence[str] = ()):
        try:
            from detectron2.config import get_cfg  # type: ignore
            from detectron2.projects.deeplab import add_deeplab_config  # type: ignore
            from demo_cropformer.predictor import VisualizationDemo  # type: ignore
        except ImportError as e:  # pragma: no cover - gated dependency
            raise ImportError(
                "TorchCropFormerPredictor needs detectron2 + CropFormer "
                "(see the reference dockerfile); install them or use "
                "precomputed mask PNGs / GridSegmenter instead") from e
        cfg = get_cfg()
        add_deeplab_config(cfg)
        cfg.merge_from_file(config_file)
        cfg.merge_from_list(list(opts) + ["MODEL.WEIGHTS", checkpoint_path])
        cfg.freeze()
        self._demo = VisualizationDemo(cfg)

    def __call__(self, rgb: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        bgr = np.asarray(rgb)[..., ::-1]
        predictions = self._demo.run_on_image(bgr)
        inst = predictions["instances"]
        return (inst.pred_masks.cpu().numpy().astype(bool),
                inst.scores.cpu().numpy())
