"""ScanNet++ preprocessing: config emission for the official toolkit.

The reference preprocesses ScanNet++ entirely through the external
`scannetpp` toolkit, shipping only yml configs for its four stages
(reference preprocess/scannetpp/*.yml, README.md:125-137): download,
iPhone RGB extraction, depth rendering, and training-data / semantic-GT
preparation (mesh sampled x0.25, instance GT in the ScanNet
`sem*1000 + inst` encoding). This module emits those configs
programmatically with the paths/knobs parameterised instead of hardcoded,
so a user points them at their data root and runs the toolkit unchanged.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence


def _dump_yaml(obj, indent: int = 0) -> str:
    """Minimal YAML emitter for the flat/nested dict+list configs we write."""
    lines = []
    pad = "  " * indent
    if isinstance(obj, dict):
        for k, v in obj.items():
            if isinstance(v, dict):
                lines.append(f"{pad}{k}:")
                lines.append(_dump_yaml(v, indent + 1))
            elif isinstance(v, list) and v and isinstance(v[0], str) and len(v) <= 12:
                lines.append(f"{pad}{k}: [{', '.join(v)}]")
            elif isinstance(v, list):
                lines.append(f"{pad}{k}:")
                for item in v:
                    lines.append(f"{pad}  - {item}")
            elif isinstance(v, bool):
                lines.append(f"{pad}{k}: {str(v).lower()}")
            else:
                lines.append(f"{pad}{k}: {v}")
    return "\n".join(lines)


def write_toolkit_configs(
    out_dir: str,
    data_root: str = "data",
    split: str = "nvs_sem_val",
    sample_factor: float = 0.25,
    near: float = 0.05,
    far: float = 20.0,
    token: Optional[str] = None,
    splits_list: Optional[Sequence[str]] = None,
) -> dict:
    """Write the four toolkit configs into out_dir; returns {name: path}.

    sample_factor is the mesh point-sampling density for the processed
    cloud (reference prepare_training_data.yml:20 `sample_factor: 0.25`);
    near/far bound the iPhone depth render (reference render.yml).
    """
    os.makedirs(out_dir, exist_ok=True)
    splits_list = list(splits_list) if splits_list is not None else [split]
    configs = {
        "download_scannetpp.yml": {
            "token": token or "YOUR_TOKEN_HERE",
            "data_root": data_root,
            "root_url": "https://kaldir.vc.in.tum.de/scannetpp/download?token=TOKEN&file=FILEPATH",
            "metadata_only": False,
            "verbose": False,
            "download_splits": splits_list,
            "default_assets": [
                "scan_mesh_path", "scan_mesh_mask_path",
                "scan_mesh_segs_path", "scan_anno_json_path", "scan_sem_mesh_path",
                "iphone_video_path", "iphone_video_mask_path", "iphone_depth_path",
                "iphone_pose_intrinsic_imu_path", "iphone_colmap_dir", "iphone_exif_path",
            ],
        },
        "prepare_iphone_data.yml": {
            "extract_rgb": True,
            "extract_masks": False,
            "extract_depth": False,
            "data_root": data_root,
            "splits": splits_list,
        },
        "render.yml": {
            "data_root": data_root,
            "render_iphone": True,
            "render_dslr": False,
            "splits": splits_list,
            "near": near,
            "far": far,
            "output_dir": os.path.join(data_root, "data"),
        },
        "prepare_training_data.yml": {
            "data": {
                "data_root": os.path.join(data_root, "data"),
                "labels_path": os.path.join(data_root, "metadata/semantic_classes.txt"),
                "use_instances": True,
                "instance_labels_path": os.path.join(data_root, "metadata/instance_classes.txt"),
                "mapping_file": os.path.join(data_root, "metadata/semantic_benchmark/map_benchmark.csv"),
                "list_path": os.path.join(data_root, f"splits/{split}.txt"),
                "ignore_label": -100,
                "sample_factor": sample_factor,
                "transforms": [
                    "add_mesh_vertices", "map_label_to_index",
                    "get_labels_on_vertices", "sample_points_on_mesh",
                ],
            },
            "out_dir": os.path.join(data_root, f"pcld_{sample_factor}"),
        },
        "prepare_semantic_gt.yml": {
            "pth_dir": os.path.join(data_root, f"pcld_{sample_factor}"),
            "scene_list": os.path.join(data_root, f"splits/{split}.txt"),
            "save_npy": False,
            "save_txt": True,
            "save_semantic": False,
            "save_instance": True,
            "inst_gt_format": True,  # sem*1000 + inst, ScanNet encoding
            "inst_gtformat_out_dir": os.path.join(data_root, "gt"),
            "inst_preds_format": False,
        },
    }
    paths = {}
    for name, cfg in configs.items():
        path = os.path.join(out_dir, name)
        with open(path, "w") as f:
            f.write(_dump_yaml(cfg) + "\n")
        paths[name] = path
    return paths
