"""L0 preprocessing: raw dataset downloads -> processed scene dirs + GT txt.

Host-side I/O layer (SURVEY.md SS2.2: "host-side Python; unchanged role").
Mirrors the reference's preprocess/{scannet,scannetpp,matterport3d} and
tasmap/tasmap2mct_format.py contracts: per-scene dirs with color/ depth/
pose/ intrinsic/ subdirs, `<scene>_vh_clean_2.ply` clouds, and GT txt files
encoding `label_id*1000 + instance + 1` per vertex.
"""

from maskclustering_tpu.preprocess.scannet import (  # noqa: F401
    SensHeader,
    iter_sens_frames,
    export_sens_scene,
    prepare_scannet_gt,
    scannet_scene_gt,
    write_sens,
)
from maskclustering_tpu.preprocess.matterport import convert_matterport_gt  # noqa: F401
from maskclustering_tpu.preprocess.scannetpp import write_toolkit_configs  # noqa: F401
from maskclustering_tpu.preprocess.tasmap import (  # noqa: F401
    omni_intrinsics,
    pose_to_extrinsic,
    convert_tasmap_scene,
)
