"""ScanNet raw-data preprocessing: .sens export + GT preparation.

The .sens container is ScanNet's public binary capture format (version 4):
a header (sensor name, color/depth intrinsics+extrinsics as 4x4 float32,
compression enums, image sizes, depth shift, frame count) followed by
per-frame records (camera-to-world 4x4 float32, two uint64 timestamps,
length-prefixed color/depth payloads; depth is zlib'd uint16, color JPEG).
The reference parses it eagerly into RAM (preprocess/scannet/SensorData.py
load) — here `iter_sens_frames` streams records lazily so a multi-GB scan
never has to fit in host memory, and `export_sens_scene` fans scenes out
over a process pool.

GT preparation follows reference preprocess/scannet/prepare_gt.py:22-95:
per-vertex `label_id*1000 + instance_id + 1` from the `.segs.json` segment
map and `.aggregation.json` groups, with raw category names mapped through
the scannetv2-labels tsv and restricted to the ScanNet benchmark ids.
"""

from __future__ import annotations

import csv
import io as _io
import json
import os
import struct
import zlib
from concurrent.futures import ProcessPoolExecutor
from dataclasses import dataclass
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from maskclustering_tpu.io.image import resize_nearest, write_depth_png

_COLOR_COMPRESSION = {-1: "unknown", 0: "raw", 1: "png", 2: "jpeg"}
_DEPTH_COMPRESSION = {-1: "unknown", 0: "raw_ushort", 1: "zlib_ushort", 2: "occi_ushort"}

CLOUD_FILE_SUFFIX = "_vh_clean_2"
SEGMENTS_FILE_SUFFIX = ".0.010000.segs.json"
AGGREGATIONS_FILE_SUFFIX = ".aggregation.json"


@dataclass
class SensHeader:
    sensor_name: str
    intrinsic_color: np.ndarray
    extrinsic_color: np.ndarray
    intrinsic_depth: np.ndarray
    extrinsic_depth: np.ndarray
    color_compression: str
    depth_compression: str
    color_width: int
    color_height: int
    depth_width: int
    depth_height: int
    depth_shift: float
    num_frames: int


@dataclass
class SensFrame:
    index: int
    camera_to_world: np.ndarray  # (4,4) float32
    timestamp_color: int
    timestamp_depth: int
    color_bytes: bytes  # compressed payload (jpeg/png/raw)
    depth_bytes: bytes  # compressed payload

    def depth(self, header: SensHeader) -> np.ndarray:
        """Decode the depth payload to (H,W) uint16 (raw sensor units)."""
        if header.depth_compression == "zlib_ushort":
            raw = zlib.decompress(self.depth_bytes)
        elif header.depth_compression == "raw_ushort":
            raw = self.depth_bytes
        else:
            raise NotImplementedError(
                f"depth compression {header.depth_compression!r}")
        return np.frombuffer(raw, dtype=np.uint16).reshape(
            header.depth_height, header.depth_width)

    def color(self, header: SensHeader) -> np.ndarray:
        """Decode the color payload to (H,W,3) uint8 RGB."""
        if header.color_compression in ("jpeg", "png"):
            from PIL import Image

            return np.asarray(Image.open(_io.BytesIO(self.color_bytes)).convert("RGB"))
        if header.color_compression == "raw":
            return np.frombuffer(self.color_bytes, dtype=np.uint8).reshape(
                header.color_height, header.color_width, 3)
        raise NotImplementedError(f"color compression {header.color_compression!r}")


def _read_mat4(f) -> np.ndarray:
    return np.frombuffer(f.read(64), dtype="<f4").reshape(4, 4).copy()


def read_sens_header(f) -> SensHeader:
    (version,) = struct.unpack("<I", f.read(4))
    if version != 4:
        raise ValueError(f"unsupported .sens version {version} (expected 4)")
    (strlen,) = struct.unpack("<Q", f.read(8))
    sensor_name = f.read(strlen).decode("ascii", errors="replace")
    intrinsic_color = _read_mat4(f)
    extrinsic_color = _read_mat4(f)
    intrinsic_depth = _read_mat4(f)
    extrinsic_depth = _read_mat4(f)
    color_comp, depth_comp = struct.unpack("<ii", f.read(8))
    cw, ch, dw, dh = struct.unpack("<IIII", f.read(16))
    (depth_shift,) = struct.unpack("<f", f.read(4))
    (num_frames,) = struct.unpack("<Q", f.read(8))
    return SensHeader(
        sensor_name=sensor_name,
        intrinsic_color=intrinsic_color, extrinsic_color=extrinsic_color,
        intrinsic_depth=intrinsic_depth, extrinsic_depth=extrinsic_depth,
        color_compression=_COLOR_COMPRESSION[color_comp],
        depth_compression=_DEPTH_COMPRESSION[depth_comp],
        color_width=cw, color_height=ch, depth_width=dw, depth_height=dh,
        depth_shift=depth_shift, num_frames=num_frames)


def iter_sens_frames(path: str) -> Iterator[Tuple[SensHeader, SensFrame]]:
    """Stream (header, frame) records from a .sens file without loading it."""
    with open(path, "rb") as f:
        header = read_sens_header(f)
        for i in range(header.num_frames):
            cam_to_world = _read_mat4(f)
            ts_color, ts_depth = struct.unpack("<QQ", f.read(16))
            color_n, depth_n = struct.unpack("<QQ", f.read(16))
            color_bytes = f.read(color_n)
            depth_bytes = f.read(depth_n)
            yield header, SensFrame(
                index=i, camera_to_world=cam_to_world,
                timestamp_color=ts_color, timestamp_depth=ts_depth,
                color_bytes=color_bytes, depth_bytes=depth_bytes)


def write_sens(path: str, header: SensHeader, frames: Sequence[SensFrame]) -> None:
    """Write a version-4 .sens file (synthetic fixtures + round-trip tests)."""
    with open(path, "wb") as f:
        f.write(struct.pack("<I", 4))
        name = header.sensor_name.encode("ascii")
        f.write(struct.pack("<Q", len(name)) + name)
        for mat in (header.intrinsic_color, header.extrinsic_color,
                    header.intrinsic_depth, header.extrinsic_depth):
            f.write(np.asarray(mat, dtype="<f4").tobytes())
        rev_c = {v: k for k, v in _COLOR_COMPRESSION.items()}
        rev_d = {v: k for k, v in _DEPTH_COMPRESSION.items()}
        f.write(struct.pack("<ii", rev_c[header.color_compression],
                            rev_d[header.depth_compression]))
        f.write(struct.pack("<IIII", header.color_width, header.color_height,
                            header.depth_width, header.depth_height))
        f.write(struct.pack("<f", header.depth_shift))
        f.write(struct.pack("<Q", len(frames)))
        for fr in frames:
            f.write(np.asarray(fr.camera_to_world, dtype="<f4").tobytes())
            f.write(struct.pack("<QQ", fr.timestamp_color, fr.timestamp_depth))
            f.write(struct.pack("<QQ", len(fr.color_bytes), len(fr.depth_bytes)))
            f.write(fr.color_bytes)
            f.write(fr.depth_bytes)


def export_sens_scene(
    sens_path: str,
    output_path: str,
    frame_skip: int = 10,
    image_size: Optional[Tuple[int, int]] = None,
    export_depth: bool = True,
    export_color: bool = True,
    export_pose: bool = True,
    export_intrinsics: bool = True,
) -> int:
    """Export a .sens capture to the processed scene layout.

    Writes `depth/<i>.png` (16-bit), `color/<i>.jpg`, `pose/<i>.txt`
    (4x4 camera-to-world), and `intrinsic/intrinsic_{color,depth}.txt` +
    `extrinsic_*` at the given frame stride — the directory contract the
    dataset loaders consume (reference preprocess/scannet/reader.py:28-35,
    dataset/scannet.py:25-54). image_size is (H, W); depth is resized
    nearest-neighbor to preserve values. Returns #frames exported.
    """
    from PIL import Image

    for sub in ("depth", "color", "pose"):
        os.makedirs(os.path.join(output_path, sub), exist_ok=True)
    os.makedirs(os.path.join(output_path, "intrinsic"), exist_ok=True)
    # header is readable even for a zero-frame capture
    with open(sens_path, "rb") as f:
        header = read_sens_header(f)
    n_exported = 0
    for header, frame in iter_sens_frames(sens_path):
        if frame.index % frame_skip != 0:
            continue
        fid = str(frame.index)
        if export_depth:
            depth = frame.depth(header)
            if image_size is not None:
                depth = resize_nearest(depth, (image_size[1], image_size[0]))
            write_depth_png(os.path.join(output_path, "depth", fid + ".png"), depth)
        if export_color:
            color = frame.color(header)
            if image_size is not None and color.shape[:2] != tuple(image_size):
                color = np.asarray(Image.fromarray(color).resize(
                    (image_size[1], image_size[0]), Image.BILINEAR))
            Image.fromarray(color).save(
                os.path.join(output_path, "color", fid + ".jpg"), quality=95)
        if export_pose:
            np.savetxt(os.path.join(output_path, "pose", fid + ".txt"),
                       frame.camera_to_world, fmt="%f")
        n_exported += 1
    if export_intrinsics:
        for name, mat in (("intrinsic_color", header.intrinsic_color),
                          ("extrinsic_color", header.extrinsic_color),
                          ("intrinsic_depth", header.intrinsic_depth),
                          ("extrinsic_depth", header.extrinsic_depth)):
            np.savetxt(os.path.join(output_path, "intrinsic", name + ".txt"),
                       mat, fmt="%f")
    return n_exported


# ---------------------------------------------------------------------------
# GT preparation


def load_label_map(tsv_path: str) -> dict:
    """raw_category name -> benchmark id from scannetv2-labels.combined.tsv."""
    mapping = {}
    with open(tsv_path, newline="") as f:
        for row in csv.DictReader(f, delimiter="\t"):
            try:
                mapping[row["raw_category"]] = int(row["id"])
            except (KeyError, ValueError, TypeError):
                continue
    return mapping


def scannet_scene_gt(scene_path: str, output_path: str, label_map: dict,
                     valid_ids: Optional[Sequence[int]] = None) -> np.ndarray:
    """Per-vertex GT ids for one scene; writes `<scene>.txt`, returns the ids.

    Matches reference prepare_gt.py:22-73: vertices outside any aggregation
    group get label 0 / instance 0; grouped vertices get the tsv-mapped
    label (0 if not a benchmark id) and instance `group_id + 1`; the final
    encoding is `label*1000 + instance + 1`.
    """
    if valid_ids is None:
        from maskclustering_tpu.semantics.vocab import get_vocab

        valid_ids = get_vocab("scannet")[1]
    valid = set(int(v) for v in valid_ids)
    scene_id = os.path.basename(os.path.normpath(scene_path))
    segs_file = os.path.join(
        scene_path, f"{scene_id}{CLOUD_FILE_SUFFIX}{SEGMENTS_FILE_SUFFIX}")
    agg_file = os.path.join(scene_path, f"{scene_id}{AGGREGATIONS_FILE_SUFFIX}")
    with open(segs_file) as f:
        seg_indices = np.asarray(json.load(f)["segIndices"])
    with open(agg_file) as f:
        groups = json.load(f)["segGroups"]

    labels = np.zeros(len(seg_indices), dtype=np.int64)
    instances = np.zeros(len(seg_indices), dtype=np.int64)
    for group in groups:
        label_id = label_map.get(group["label"], 0)
        if label_id not in valid:
            label_id = 0
        member = np.isin(seg_indices, np.asarray(group["segments"]))
        labels[member] = label_id
        instances[member] = group["id"] + 1
    gt = labels * 1000 + instances + 1
    if output_path:
        os.makedirs(os.path.dirname(output_path) or ".", exist_ok=True)
        np.savetxt(output_path, gt, fmt="%d")
    return gt


def _gt_worker(job):
    scene_path, out_file, label_map = job
    scannet_scene_gt(scene_path, out_file, label_map)
    return os.path.basename(out_file)


def prepare_scannet_gt(raw_scans_dir: str, gt_dir: str, label_map_tsv: str,
                       scenes: Sequence[str], num_workers: int = 16) -> None:
    """Fan GT prep out over a process pool (reference prepare_gt.py:92-95)."""
    label_map = load_label_map(label_map_tsv)
    os.makedirs(gt_dir, exist_ok=True)
    jobs = [(os.path.join(raw_scans_dir, s), os.path.join(gt_dir, f"{s}.txt"),
             label_map) for s in scenes]
    if num_workers <= 1 or len(jobs) <= 1:
        for job in jobs:
            _gt_worker(job)
        return
    with ProcessPoolExecutor(max_workers=num_workers) as pool:
        list(pool.map(_gt_worker, jobs))
