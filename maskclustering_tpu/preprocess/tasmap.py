"""TASMap (OmniGibson sim capture) -> MCT scene-layout converter.

Reference tasmap/tasmap2mct_format.py: per-frame `extra_info/<frame>/`
captures (original_image.png, depth.npy in metres, pose_ori.npy holding
(position, xyzw-quaternion)) become the processed scene layout the dataset
loaders consume — color/<f>.jpg, depth/<f>.png (16-bit mm), pose/<f>.txt
(4x4 camera-to-world), intrinsic/*.txt — plus a fused, voxel-downsampled
`<scene>_vh_clean_2.ply` built by unprojecting every depth frame.

TPU-first notes: the reference fuses through Open3D C++ RGBD unprojection
(tasmap2mct_format.py:211-233); here unprojection is plain vectorised
pixel-grid math (the same math the jitted pipeline uses in
ops/geometry.unproject_depth) and the voxel downsample keeps per-voxel mean
positions with the color of each voxel's first-seen point.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional, Sequence, Tuple

import numpy as np

from maskclustering_tpu.io.image import read_rgb, write_depth_png
from maskclustering_tpu.io.ply import write_ply_points

# OmniGibson camera model (reference tasmap2mct_format.py:13-17)
OMNI_SENSOR_HEIGHT = 1024
OMNI_SENSOR_WIDTH = 1024
OMNI_FOCAL_LENGTH = 17.0
OMNI_HORIZ_APERTURE = 20.954999923706055

# Realsense D435 intrinsics for real-robot captures (tasmap2mct_format.py:35-39)
REALSENSE_INTRINSICS = (605.8658447265625, 605.128173828125,
                        429.753662109375, 237.18128967285156)


def omni_intrinsics(realsense: bool = False) -> Tuple[float, float, float, float]:
    """(fx, fy, cx, cy) from the simulator's aperture camera model."""
    if realsense:
        return REALSENSE_INTRINSICS
    vert_aperture = OMNI_SENSOR_HEIGHT / OMNI_SENSOR_WIDTH * OMNI_HORIZ_APERTURE
    fx = OMNI_SENSOR_WIDTH * OMNI_FOCAL_LENGTH / OMNI_HORIZ_APERTURE
    fy = OMNI_SENSOR_HEIGHT * OMNI_FOCAL_LENGTH / vert_aperture
    cx = OMNI_SENSOR_WIDTH * 0.5
    cy = OMNI_SENSOR_HEIGHT * 0.5
    return fx, fy, cx, cy


def quat_xyzw_to_rotmat(q: np.ndarray) -> np.ndarray:
    """(x,y,z,w) quaternion -> 3x3 rotation matrix."""
    x, y, z, w = (float(v) for v in q)
    return np.array([
        [2 * (w * w + x * x) - 1, 2 * (x * y - w * z), 2 * (x * z + w * y)],
        [2 * (x * y + w * z), 2 * (w * w + y * y) - 1, 2 * (y * z - w * x)],
        [2 * (x * z - w * y), 2 * (y * z + w * x), 2 * (w * w + z * z) - 1],
    ], dtype=np.float64)


def pose_to_extrinsic(position: np.ndarray, quat_xyzw: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Sim pose -> (world_to_cam, cam_to_world) 4x4 matrices.

    The sim camera looks along -Z with +Y up; the CV camera frame flips Y
    and Z, so the camera rows are (R@[1,0,0], R@[0,-1,0], R@[0,0,-1])
    (reference tasmap2mct_format.py:80-99). The on-disk pose txt is the
    camera-to-world matrix.
    """
    rot = quat_xyzw_to_rotmat(quat_xyzw)
    rows = np.stack([rot @ np.array([1.0, 0.0, 0.0]),
                     rot @ np.array([0.0, -1.0, 0.0]),
                     rot @ np.array([0.0, 0.0, -1.0])])
    t = -rows @ np.asarray(position, dtype=np.float64).reshape(3)
    world_to_cam = np.eye(4)
    world_to_cam[:3, :3] = rows
    world_to_cam[:3, 3] = t
    cam_to_world = np.eye(4)
    cam_to_world[:3, :3] = rows.T
    cam_to_world[:3, 3] = rows.T @ (-t)
    return world_to_cam, cam_to_world


def _unproject(depth: np.ndarray, fx, fy, cx, cy, cam_to_world: np.ndarray,
               depth_trunc: float = 20.0):
    """Depth (metres) -> (world points, valid pixel mask), vectorised."""
    h, w = depth.shape
    v, u = np.mgrid[0:h, 0:w]
    valid = (depth > 0) & (depth < depth_trunc)
    z = depth[valid]
    x = (u[valid] - cx) / fx * z
    y = (v[valid] - cy) / fy * z
    pts = np.stack([x, y, z], axis=1)
    return pts @ cam_to_world[:3, :3].T + cam_to_world[:3, 3], valid


def _voxel_downsample_colored(points: np.ndarray, colors: np.ndarray,
                              voxel_size: float):
    if len(points) == 0:
        return points, colors
    origin = points.min(axis=0)
    keys = np.floor((points - origin) / voxel_size).astype(np.int64)
    _, first, inverse, counts = np.unique(
        keys, axis=0, return_index=True, return_inverse=True, return_counts=True)
    sums = np.zeros((len(counts), 3), dtype=np.float64)
    np.add.at(sums, inverse, points)
    return sums / counts[:, None], colors[first]


def convert_tasmap_scene(
    extra_info_dir: str,
    output_dir: str,
    scene_name: str,
    realsense: bool = False,
    stride: int = 1,
    voxel_size: float = 0.005,
    buffer_size: int = 30,
    frames: Optional[Sequence[str]] = None,
) -> str:
    """Convert one capture to the MCT layout; returns the fused ply path.

    Mirrors reference tasmap2mct_format.py __main__: save_2D then
    create_downsampled_point_cloud with buffered incremental voxel
    downsampling (every `buffer_size` frames, then once at the end).
    """
    fx, fy, cx, cy = omni_intrinsics(realsense)
    for sub in ("color", "depth", "depth_npy", "pose", "intrinsic"):
        os.makedirs(os.path.join(output_dir, sub), exist_ok=True)

    if frames is None:
        frames = sorted(os.listdir(extra_info_dir))
    frames = list(frames)[::stride]

    k = np.array([[fx, 0, cx], [0, fy, cy], [0, 0, 1.0]])
    for name, mat in (("intrinsic_color", k), ("extrinsic_color", np.eye(4)),
                      ("intrinsic_depth", k), ("extrinsic_depth", np.eye(4))):
        np.savetxt(os.path.join(output_dir, "intrinsic", name + ".txt"), mat, fmt="%f")

    from PIL import Image

    fused_pts, fused_cols = [], []
    buf_pts, buf_cols = [], []

    def _flush():
        nonlocal buf_pts, buf_cols
        if buf_pts:
            p, c = _voxel_downsample_colored(
                np.concatenate(buf_pts), np.concatenate(buf_cols), voxel_size)
            fused_pts.append(p)
            fused_cols.append(c)
            buf_pts, buf_cols = [], []

    for i, frame in enumerate(frames):
        fdir = os.path.join(extra_info_dir, frame)
        rgb = read_rgb(os.path.join(fdir, "original_image.png"))
        Image.fromarray(rgb).save(
            os.path.join(output_dir, "color", f"{frame}.jpg"), quality=95)

        depth_m = np.load(os.path.join(fdir, "depth.npy")).astype(np.float32)
        shutil.copy(os.path.join(fdir, "depth.npy"),
                    os.path.join(output_dir, "depth_npy", f"{frame}.npy"))
        write_depth_png(os.path.join(output_dir, "depth", f"{frame}.png"),
                        depth_m * 1000.0)

        pose_ori = np.load(os.path.join(fdir, "pose_ori.npy"), allow_pickle=True)
        _, cam_to_world = pose_to_extrinsic(pose_ori[0], pose_ori[1])
        np.savetxt(os.path.join(output_dir, "pose", f"{frame}.txt"),
                   cam_to_world, fmt="%.6f")

        if rgb.shape[:2] != depth_m.shape:
            rgb = np.asarray(Image.fromarray(rgb).resize(
                (depth_m.shape[1], depth_m.shape[0]), Image.BILINEAR))
        pts, valid = _unproject(depth_m, fx, fy, cx, cy, cam_to_world)
        buf_pts.append(pts)
        buf_cols.append(rgb[valid])
        if (i + 1) % buffer_size == 0:
            _flush()
    _flush()

    if fused_pts:
        pts, cols = _voxel_downsample_colored(
            np.concatenate(fused_pts), np.concatenate(fused_cols), voxel_size)
    else:
        pts = np.zeros((0, 3))
        cols = np.zeros((0, 3), dtype=np.uint8)
    ply_path = os.path.join(output_dir, f"{scene_name}_vh_clean_2.ply")
    write_ply_points(ply_path, pts, cols)
    return ply_path
