"""Matterport3D GT preparation: house mesh + segment jsons -> per-vertex ids.

Reference preprocess/matterport3d/process.py:41-68: faces of the
house_segmentations ply carry a raw `category_id`; fsegs.json maps faces to
segment ids; semseg.json groups segments into instances. Face attributes
are splatted onto vertices (last face writing a vertex wins), raw category
ids map to NYU ids through the category_mapping tsv, ids outside the valid
set drop to 0, and the GT encoding is `nyu_id*1000 + instance + 1`.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Optional, Sequence

import numpy as np

from maskclustering_tpu.io.ply import read_ply_mesh

# GT keeps wall(4)/floor(11)/ceiling(21) although evaluation's 157-class
# vocabulary excludes them (reference preprocess/matterport3d/constants.py
# MATTERPORT_VALID_IDS vs evaluation/constants.py MATTERPORT_IDS).
GT_ONLY_IDS = (4, 11, 21)


def load_raw_to_nyu(tsv_path: str) -> np.ndarray:
    """RAW category id -> NYU id lookup from category_mapping.tsv.

    Index 0 is the unknown category; row i of the tsv is raw id i+1
    (reference preprocess/matterport3d/constants.py:3-4).
    """
    nyu = [0]
    with open(tsv_path, newline="") as f:
        for row in csv.DictReader(f, delimiter="\t"):
            try:
                nyu.append(int(float(row["nyuId"])))
            except (ValueError, TypeError):
                nyu.append(0)
    return np.asarray(nyu, dtype=np.int64)


def _faces_to_vertices(values: np.ndarray, faces: np.ndarray, n_verts: int) -> np.ndarray:
    """Splat one per-face value onto each of its 3 vertices (last wins)."""
    out = np.zeros(n_verts, dtype=np.int64)
    out[faces.reshape(-1)] = np.repeat(values.astype(np.int64), 3)
    return out


def convert_matterport_gt(
    root_dir: str,
    seq_name: str,
    output_dir: str,
    category_mapping_tsv: str,
    valid_ids: Optional[Sequence[int]] = None,
) -> np.ndarray:
    """Write `<seq_name>.txt` GT for one house scan; returns the id array."""
    if valid_ids is None:
        from maskclustering_tpu.semantics.vocab import get_vocab

        valid_ids = list(get_vocab("matterport3d")[1]) + list(GT_ONLY_IDS)
    scene_dir = os.path.join(root_dir, seq_name, seq_name, "house_segmentations")
    verts, faces, face_props = read_ply_mesh(
        os.path.join(scene_dir, f"{seq_name}.ply"))
    if "category_id" not in face_props:
        raise ValueError(f"{seq_name}.ply faces carry no category_id")
    vert_semantic = _faces_to_vertices(
        np.asarray(face_props["category_id"], dtype=np.int64), faces, len(verts))

    with open(os.path.join(scene_dir, f"{seq_name}.fsegs.json")) as f:
        face_segment = np.asarray(json.load(f)["segIndices"], dtype=np.int64)
    vert_segment = _faces_to_vertices(face_segment, faces, len(verts))

    with open(os.path.join(scene_dir, f"{seq_name}.semseg.json")) as f:
        seg_groups = json.load(f)["segGroups"]
    segment_instance = np.full(int(vert_segment.max()) + 1, -1, dtype=np.int64)
    for instance_id, group in enumerate(seg_groups):
        members = np.asarray(group["segments"], dtype=np.int64)
        members = members[members < len(segment_instance)]
        segment_instance[members] = instance_id
    vert_instance = segment_instance[vert_segment]
    if vert_instance.min() < 0:
        raise ValueError(f"{seq_name}: vertices outside every instance group")

    raw_to_nyu = load_raw_to_nyu(category_mapping_tsv)
    # ids outside the mapping table are unknown, not the last row's label
    vert_semantic[(vert_semantic < 0) | (vert_semantic >= len(raw_to_nyu))] = 0
    vert_semantic = raw_to_nyu[vert_semantic]
    vert_semantic[~np.isin(vert_semantic, np.asarray(list(valid_ids)))] = 0

    gt = vert_semantic * 1000 + vert_instance + 1
    os.makedirs(output_dir, exist_ok=True)
    np.savetxt(os.path.join(output_dir, f"{seq_name}.txt"),
               gt.astype(np.int64), fmt="%d")
    return gt
