"""Device-resident post-process: split + merge as on-TPU tensor passes.

The host post-process (models/postprocess.py) reproduces the reference's
pipeline (reference utils/post_process.py:40-170) with vectorized numpy over
COO claim structures — but building those structures requires pulling the
(F, N) ``first_id``/``last_id`` tensors off the device (hundreds of MB per
scene) and running multi-million-row nonzero/sort passes on host. At bench
scale that is 12-16 s/scene, the dominant pipeline cost.

Since the claims-drain restructure, EVERYTHING up to the final compact
instances runs on device and the claim planes are consumed in HBM — the
drain is emit-only:

- ``_prep_kernel``: the live-representative routing tables (historically
  host prep over a pulled assignment vector) as device scatters; the
  live-rep axis is sized by the 4-byte ``_live_count_kernel`` scalar pull
  — so the cluster assignment never crosses to host mid-pipeline and
  ``pipeline.host_sync`` drops to 1.
- ``_node_stats_kernel``: one lax.scan over frames accumulates, for every
  (live representative r, point p): ``claimed`` (p is a node point of r)
  and the OVIR detection-ratio test (reference post_process.py:56-76) as
  (2R, C*k2) @ (C*k2, N) MXU matmuls (ops/counting.py dispatch).
- ``_dbscan_split_kernel`` (ops/grid_dbscan.py): the node point sets of
  every live representative split on device by the voxel-grid min-label
  kernel — the same grid/union-find algorithm as the native C++ host
  path, with the grid built host-side from the (host-resident) cloud and
  the candidate window static-shape bucketed per scene.
- ``_group_structs_kernel`` derives every group structure (sizes,
  membership planes, bounding boxes, per-mask group ranges) as segment
  scatters at the pow2 bucket of the pulled group total;
  ``_mask_group_counts_kernel`` assigns each mask to its best group via
  (k2, N) x (N, S) MXU matmuls, donating the (F, N) claim planes (their
  last consumer).
- ``overlap merge``: the pairwise |i and j| containment counts become ONE
  device mask x mask ``count_dot`` over the surviving objects' bit-planes
  (``_survivor_gather_kernel``); only the greedy threshold scan — O(objects
  squared) trivial work whose f64 ratio comparisons must match the
  reference bit-for-bit — stays host, consuming the pulled count matrix.

Net device->host traffic per scene: the final compact instance bit-planes
plus O(M_pad + S) scalars. No (F, N) plane and no (R, N) claim plane is
ever pulled on this path (span-pinned by tests/test_postprocess_device.py);
byte-identity with the host path remains the acceptance bar.

Capacity: ``cfg.post_group_cap`` caps the global group total and
``cfg.post_neighbor_cap`` the per-pair neighbor window (the compiled
group width itself is the pow2 bucket of the true total — the ceiling
never costs matmul lanes). A scene that overflows either raises
``PostprocessCapacityError`` (classified device-class), and the
degradation ladder's host-postprocess rung is the fallback — the scene
retries on the host path instead of exporting truncated groups.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from maskclustering_tpu.utils.donation import suppress_unusable_donation_warning

# this module donates the (F, N) claim tensors into the group-counts
# kernel; see the helper's docstring for why the filter is global
suppress_unusable_donation_warning()

from maskclustering_tpu import obs
from maskclustering_tpu.ops import counting
from maskclustering_tpu.ops.grid_dbscan import (
    _bucket_pow2,
    build_grid,
    grid_dbscan_pairs,
)
from maskclustering_tpu.models.postprocess import (
    SceneObjects,
    _PhaseTimer,
    merge_from_counts,
    postprocess_scene,
)


class PostprocessCapacityError(RuntimeError):
    """The scene overflowed a device post-process capacity bucket.

    Raised at drain time (the group/neighbor scatters already dropped the
    overflow, so the device results are unusable). Classified as
    device-class by ``utils/faults.classify_error``: the scene supervisor
    retries down the degradation ladder until the host-postprocess rung
    re-runs the scene on the host path — or raise the named knob for good.
    """

    def __init__(self, what: str, amount: int, cap: int, knob: str):
        self.what = what
        self.amount = amount
        self.cap = cap
        self.knob = knob
        over = f"{amount} > {cap}" if amount > 0 else f"over {cap}"
        super().__init__(
            f"device postprocess overflowed its {what} bucket ({over}); "
            f"retry degrades to the host-postprocess rung (or raise "
            f"cfg.{knob})")


def run_postprocess(cfg, scene_points, first, last, mask_frame, mask_id,
                    mask_active, assignment, node_visible, frame_ids, *,
                    k_max: int, timings: Optional[Dict[str, float]] = None,
                    n_real: Optional[int] = None,
                    seq_name: Optional[str] = None) -> SceneObjects:
    """Single dispatch point for the device/host post-process paths.

    Accepts device or host arrays for the large operands; converts to what
    the selected path needs (the device path keeps ``mask_active`` and
    ``assignment`` device-resident — pulling them was host sync 2/2 before
    the drain restructure). Both paths produce byte-identical artifacts.

    ``n_real``: the scene's true point count when the inputs are padded to a
    shape bucket; enforces the sentinel-pad invariant (no padded point may
    be claimed) and restores the real count on the returned objects.
    """
    kwargs = dict(
        k_max=k_max,
        point_filter_threshold=cfg.point_filter_threshold,
        dbscan_eps=cfg.dbscan_split_eps,
        dbscan_min_points=cfg.dbscan_split_min_points,
        overlap_merge_ratio=cfg.overlap_merge_ratio,
        min_masks_per_object=cfg.min_masks_per_object,
        timings=timings,
    )
    scene_points = np.asarray(scene_points)
    mask_frame = np.asarray(mask_frame)
    mask_id = np.asarray(mask_id)
    if cfg.device_postprocess:
        # fault seam: the device post-process chain (utils/faults.FaultPlan);
        # the host path below deliberately has no seam — it IS the ladder's
        # fallback rung, and a seam that kept firing there would make the
        # rung drop unable to heal the scene
        from maskclustering_tpu.utils import faults

        faults.inject("post", seq_name)
        objects = postprocess_scene_device(
            scene_points, jnp.asarray(first), jnp.asarray(last), mask_frame,
            mask_id, jnp.asarray(mask_active), jnp.asarray(assignment),
            jnp.asarray(node_visible), frame_ids,
            pull_chunk=cfg.claims_pull_chunk, donate=cfg.donate_buffers,
            count_dtype=cfg.count_dtype, group_cap=cfg.post_group_cap,
            neighbor_cap=cfg.post_neighbor_cap, n_real=n_real, **kwargs)
    else:
        with obs.span("post.host_pull") as sp:
            # the host path pulls the full (F, N) claim tensors — the very
            # transfer the device path exists to avoid; on the books so a
            # report makes the paths' cost difference legible
            first_h = np.asarray(first)
            last_h = np.asarray(last)
            nv_h = np.asarray(node_visible)
            obs.count_transfer(
                "d2h", first_h.nbytes + last_h.nbytes + nv_h.nbytes,
                "postprocess")
        objects = postprocess_scene(
            scene_points, first_h, last_h, first_h > 0, mask_frame,
            mask_id, np.asarray(mask_active), np.asarray(assignment), nv_h,
            frame_ids, **kwargs)
    if n_real is not None and objects.num_points != n_real:
        for pids in objects.point_ids_list:
            # not an assert: this guards exported artifacts and must survive -O
            if pids.size and int(pids.max()) >= n_real:
                raise RuntimeError(
                    "sentinel pad point claimed — padding invariant violated "
                    f"(max point id {int(pids.max())} >= num_points {n_real})")
        objects = SceneObjects(point_ids_list=objects.point_ids_list,
                               mask_list=objects.mask_list, num_points=n_real)
    return objects


def _frame_chunk(f: int) -> int:
    """Frames per claims-scan step: largest divisor of F in {8,4,2,1}.

    Keeps (most of) the matmul contraction depth when a caller pads F to a
    multiple of 4 or 2 instead of 8.
    """
    return next(c for c in (8, 4, 2, 1) if f % c == 0)


def _rep_bucket(live: int) -> int:
    """Live-representative shape bucket (pow2 of the live count).

    Floor 64: 2*r_pad = 128 fills the MXU's systolic dimension, so padding
    small scenes up is compute-free and collapses the small-scene compile
    variants. The live count comes from ``_live_count_kernel`` — a 4-byte
    scalar pull, NOT the assignment vector: the worst-case static bound
    (``m_pad // min_masks``) would be ~64x the typical live count at the
    honest shape and multiply the node-stats matmul rows with it.
    """
    return _bucket_pow2(max(int(live), 1), minimum=64)


@functools.partial(jax.jit, static_argnames=("min_masks_per_object",))
def _live_count_kernel(assignment, mask_active, *, min_masks_per_object):
    """Number of clusters with >= min_masks_per_object active members.

    The only data-dependent shape input of the post-process program: its
    4-byte pull sizes the ``r_pad`` bucket (the analog of the mask-table
    bucket pull at graph start). Everything heavier stays in HBM.
    """
    m_pad = assignment.shape[0]
    sizes = jnp.zeros(m_pad, jnp.int32).at[
        jnp.where(mask_active, assignment, m_pad)].add(1, mode="drop")
    return jnp.sum(sizes >= jnp.int32(min_masks_per_object),
                   dtype=jnp.int32)


def _live_rep_prep(mask_frame, mask_id, mask_active, assignment, f, k2,
                   min_masks_per_object):
    """HOST reference of `_prep_kernel` (kept for scripts/claims_diag.py,
    which times the node-stats kernel standalone at pipeline shapes).

    Returns None when no cluster reaches ``min_masks_per_object`` members,
    else ``(reps, r_pad, rep_lut, rep_tab, live_slots, live_valid,
    r_pull)``. The pipeline itself runs the device kernel — this helper
    must mirror its routing exactly (same r_pad bucket, same slot order).
    """
    m_pad = mask_frame.shape[0]
    sizes = np.bincount(assignment[mask_active], minlength=m_pad)
    reps = np.nonzero(sizes >= min_masks_per_object)[0]
    if len(reps) == 0:
        return None
    r_pad = _rep_bucket(len(reps))
    rep_lut = np.full(m_pad, -1, dtype=np.int32)
    rep_lut[reps] = np.arange(len(reps), dtype=np.int32)

    # local (frame, id) -> dense live-rep index of the claiming mask's cluster
    gmap = np.full((f, k2), -1, dtype=np.int64)
    act_idx = np.nonzero(mask_active)[0]
    gmap[mask_frame[act_idx], mask_id[act_idx]] = act_idx
    rep_tab = np.full((f, k2), -1, dtype=np.int32)
    mapped = gmap >= 0
    rep_tab[mapped] = rep_lut[assignment[gmap[mapped]]]

    live_slots = np.zeros(r_pad, dtype=np.int32)
    live_slots[: len(reps)] = reps
    live_valid = np.zeros(r_pad, dtype=bool)
    live_valid[: len(reps)] = True
    # quantize the row slice to multiples of 8 so an eager device slice op
    # stays within a handful of compiled shapes per r_pad
    r_pull = min(r_pad, -(-len(reps) // 8) * 8)
    return reps, r_pad, rep_lut, rep_tab, live_slots, live_valid, r_pull


@functools.partial(jax.jit, static_argnames=("r_pad", "f", "k2",
                                             "min_masks_per_object"))
def _prep_kernel(
    assignment: jnp.ndarray,  # (M_pad,) int32 final cluster representative
    mask_active: jnp.ndarray,  # (M_pad,) bool — valid & not undersegmented
    mask_frame: jnp.ndarray,  # (M_pad,) int32
    mask_id: jnp.ndarray,  # (M_pad,) int32 (-1 padding)
    *,
    r_pad: int,
    f: int,
    k2: int,
    min_masks_per_object: int,
):
    """Live-rep routing tables on device (the former host `_live_rep_prep`).

    Dense live-rep indices follow ascending representative slot order
    (cumsum compaction == np.nonzero order), so every downstream group
    offset — and therefore the emitted object order — is identical to the
    host prep's. Returns (rep_tab, live_slots, live_valid, ridx_of_mask,
    alive, mask_flat).
    """
    m_pad = assignment.shape[0]
    arange_m = jnp.arange(m_pad, dtype=jnp.int32)
    sizes = jnp.zeros(m_pad, jnp.int32).at[
        jnp.where(mask_active, assignment, m_pad)].add(1, mode="drop")
    live = sizes >= jnp.int32(min_masks_per_object)
    dense = jnp.cumsum(live.astype(jnp.int32)) - 1
    rep_lut = jnp.where(live, dense, -1)
    scatter_to = jnp.where(live, dense, r_pad)  # pad slots drop
    live_slots = jnp.zeros(r_pad, jnp.int32).at[scatter_to].set(
        arange_m, mode="drop")
    live_valid = jnp.zeros(r_pad, bool).at[scatter_to].set(True, mode="drop")
    ridx_of_mask = jnp.take(rep_lut, assignment, mode="clip")
    slot = mask_frame * k2 + jnp.clip(mask_id, 0, k2 - 1)
    rep_tab = jnp.full(f * k2, -1, jnp.int32).at[
        jnp.where(mask_active, slot, f * k2)].set(
        ridx_of_mask, mode="drop").reshape(f, k2)
    alive = mask_active & (ridx_of_mask >= 0)
    mask_flat = jnp.where(alive, slot, 0)
    return rep_tab, live_slots, live_valid, ridx_of_mask, alive, mask_flat


@functools.partial(jax.jit, static_argnames=("r_pad", "point_filter_threshold",
                                             "count_dtype"))
def _node_stats_kernel(
    first: jnp.ndarray,  # (F, N) int16 smallest valid claiming id per (frame, point)
    last: jnp.ndarray,  # (F, N) int16 largest valid claiming id
    rep_tab: jnp.ndarray,  # (F, K+2) int32: local mask id -> dense live-rep index, -1 none
    node_visible: jnp.ndarray,  # (M_pad, F) bool per-representative visibility
    live_slots: jnp.ndarray,  # (r_pad,) int32 global slot of each live rep (pad: 0)
    live_valid: jnp.ndarray,  # (r_pad,) bool
    *,
    r_pad: int,
    point_filter_threshold: float,
    count_dtype: str = "bf16",
):
    """Per-(rep, point) claim statistics.

    Returns (claimed, ratio_ok, nv_rep): (r_pad, N) bool x2 plus the
    (r_pad, F) bool node-visibility rows for the live reps — all consumed
    ON DEVICE by the DBSCAN/group kernels (nothing here is pulled).

    Frames are processed in chunks of C: each scan step contracts one
    (2R, C*k2) @ (C*k2, N) matmul — local-id one-hots of the claim
    extremes (with a -1 row correction so two masks of the same rep
    claiming one cell count ONE unique (rep, point, frame) triple, like
    the host path's sort) against per-frame weight rows W[c, r, k] =
    [rep_tab==r] (* node-visibility for the OVIR numerator). One frame per
    step made the contraction depth k2 (~65) — too shallow to feed the
    128x128 systolic array — and paid F sequential steps; C frames per
    step deepens the contraction C-fold and cuts the step count to F/C at
    the cost of a (C, k2, N) narrow operand window in HBM (~200 MB at
    C=8, bench shapes, bf16; half that under ``count_dtype="int8"``).
    One-hot operands with exact accumulation (f32 or s32, ops/counting.py)
    stay exact; the only non-0/1 entries are the {0, 1, 2} values of the
    duplicate-correction matrix m, representable in both encodings. The
    ratio denominator drops out of the scan entirely: one (R, F) @ (F, N)
    matmul of node-visibility against point-visibility.
    """
    f, n = first.shape
    k2 = rep_tab.shape[1]
    nv_rep = jnp.take(node_visible, live_slots, axis=0) & live_valid[:, None]
    od = counting.operand_dtype(count_dtype)
    acc_dtype = counting.accumulator_dtype(count_dtype)

    chunk = _frame_chunk(f)

    def step(carry, inp):
        acc = carry
        a, b, rt, nv_f = inp  # (C, N) x2, (C, k2), (C, R)
        # per-chunk weight rows, built in-step from the scanned rep rows
        # and nv columns — negligible VPU work vs holding an (F, 2R, k2)
        # tensor in HBM for the whole scan
        rep_oh = counting.count_onehot(rt, r_pad, count_dtype=count_dtype,
                                       axis=1)  # (C, R, k2)
        w = jnp.concatenate(
            [rep_oh * nv_f.astype(od)[:, :, None], rep_oh],
            axis=1)  # (C, 2R, k2)
        # id 0 = no claim and rep_tab[:, 0] is always -1 (ids are 1-based), so
        # W column 0 is zero — routing the a == b duplicate there drops it.
        # Distinct ids of one rep claiming the same cell must also count once
        # (one unique triple): detect rep_a == rep_b with a != b and subtract
        # the duplicate via a one-hot on the a id.
        b2 = jnp.where(b == a, 0, b)
        rep_a = jnp.take_along_axis(rt, a.astype(jnp.int32), axis=1)  # (C, N) dense rep or -1
        rep_b = jnp.take_along_axis(rt, b2.astype(jnp.int32), axis=1)
        dup = (rep_a >= 0) & (rep_a == rep_b) & (a != b2)
        oh_a = counting.count_onehot(a, k2, count_dtype=count_dtype,
                                     axis=1)  # (C, k2, N)
        oh_b = counting.count_onehot(b2, k2, count_dtype=count_dtype, axis=1)
        oh_dup = counting.count_onehot(jnp.where(dup, a, 0), k2,
                                       count_dtype=count_dtype, axis=1)
        m = oh_a + oh_b - oh_dup
        # sum_c w[c] @ m[c] as ONE deep contraction over (c, k2)
        acc = acc + counting.count_dot_general(
            w, m, (((0, 2), (0, 1)), ((), ())),
            count_dtype=count_dtype, out_dtype=None)
        return acc, None

    acc, _ = jax.lax.scan(
        step, jnp.zeros((2 * r_pad, n), acc_dtype),
        (first.reshape(f // chunk, chunk, n),
         last.reshape(f // chunk, chunk, n),
         rep_tab.reshape(f // chunk, chunk, k2),
         nv_rep.T.reshape(f // chunk, chunk, r_pad)))
    # exact integer counts in either accumulator; f32 conversion is exact
    # below 2^24, so the ratio threshold stays byte-identical across paths
    num = acc[:r_pad].astype(jnp.float32)
    claimed = acc[r_pad:] > 0

    den = counting.count_dot(nv_rep, first > 0, count_dtype=count_dtype)

    ratio_ok = num / (den + 1e-6) > point_filter_threshold
    return claimed, ratio_ok, nv_rep


def _pack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """(R, N) bool -> (R, ceil(N/8)) uint8, np.unpackbits-compatible (big-endian)."""
    r, n = x.shape
    n8 = -(-n // 8) * 8
    xp = jnp.pad(x, ((0, 0), (0, n8 - n))).reshape(r, n8 // 8, 8)
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.int32)
    return jnp.sum(xp.astype(jnp.int32) * weights, axis=-1).astype(jnp.uint8)


def _unpack_bits(packed: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(np.asarray(packed), axis=1)[:, :n].astype(bool)


def _row_chunks(arr, rows: int, chunk: int) -> List:
    """``arr[:rows]`` as a list of row slices of at most ``chunk`` rows.

    ``chunk <= 0`` (or a chunk covering everything) degenerates to the
    single-slice pull. Slicing is lazy on device; concatenating the
    materialized chunks in order reproduces the single pull byte-for-byte.
    """
    if chunk <= 0 or rows <= chunk:
        return [arr[:rows]]
    return [arr[i:min(i + chunk, rows)] for i in range(0, rows, chunk)]


def _start_host_copy(arr) -> None:
    """Kick off the device->host DMA without blocking (no-op off-backend)."""
    try:
        arr.copy_to_host_async()
    except AttributeError:  # backend without async host copies
        pass


@functools.partial(jax.jit, static_argnames=("c_pad", "cell_cap",
                                             "neighbor_cap", "eps",
                                             "min_points"))
def _dbscan_split_kernel(
    claimed: jnp.ndarray,  # (r_pad, N) bool node membership per live rep
    nv_rep: jnp.ndarray,  # (r_pad, F) bool node visibility rows
    live_valid: jnp.ndarray,  # (r_pad,) bool
    points: jnp.ndarray,  # (N, 3) f32 scene cloud (uploaded once)
    order: jnp.ndarray,  # grid structure (ops/grid_dbscan.build_grid)
    start: jnp.ndarray,
    length: jnp.ndarray,
    *,
    c_pad: int,
    cell_cap: int,
    neighbor_cap: int,
    eps: float,
    min_points: int,
):
    """Grid-DBSCAN split over compacted (rep, point) pairs, on device.

    Candidate reps (live, non-empty node, some node visibility — the host
    path's exact filter) flatten into compacted (rep, point) pairs
    (``c_pad`` bucketed from the tiny node-size pull) and split via
    :func:`grid_dbscan_pairs`. Returns the pair naming
    (``pair_rep``/``pair_pt``/``pair_valid``), per-pair dense local labels,
    per-rep root counts and the neighbor-window overflow flag; the
    O(r_pad) count pull sizes the group axis TIGHTLY before the
    structures/assign kernels compile (their matmul width rides it)."""
    r_pad, n = claimed.shape
    candidate = live_valid & jnp.any(claimed, axis=1) & jnp.any(nv_rep, axis=1)
    valid_rows = claimed & candidate[:, None]
    (pair_idx,) = jnp.nonzero(valid_rows.reshape(-1), size=c_pad,
                              fill_value=r_pad * n)
    pair_valid = pair_idx < r_pad * n
    pair_rep = jnp.where(pair_valid, pair_idx // n, r_pad).astype(jnp.int32)
    pair_pt = jnp.where(pair_valid, pair_idx % n, 0).astype(jnp.int32)
    dense_local, root_count, nb_overflow = grid_dbscan_pairs(
        points, order, start, length, pair_rep, pair_pt, pair_valid,
        r_pad=r_pad, cell_cap=cell_cap, neighbor_cap=neighbor_cap,
        eps=eps, min_points=min_points)
    return (pair_rep, pair_pt, pair_valid, dense_local,
            jnp.where(candidate, root_count + 1, 0), nb_overflow)


@functools.partial(jax.jit, static_argnames=("s_pad", "count_dtype"))
def _group_structs_kernel(
    pair_rep: jnp.ndarray,  # (C_pad,) int32 (pad: r_pad)
    pair_pt: jnp.ndarray,  # (C_pad,) int32 (pad: 0)
    pair_valid: jnp.ndarray,  # (C_pad,) bool
    dense_local: jnp.ndarray,  # (C_pad,) int32 per-rep DBSCAN label (-1 noise)
    goff: jnp.ndarray,  # (r_pad,) int32 global group offset per rep (host built)
    ngrp: jnp.ndarray,  # (r_pad,) int32 groups per rep incl. noise slot
    ratio_ok: jnp.ndarray,  # (r_pad, N) bool OVIR detection-ratio pass
    points: jnp.ndarray,  # (N, 3) f32
    ridx_of_mask: jnp.ndarray,  # (M_pad,) int32 dense live-rep index or -1
    alive: jnp.ndarray,  # (M_pad,) bool active & live
    *,
    s_pad: int,
    count_dtype: str = "bf16",
):
    """Every group structure as segment scatters over the split's pairs.

    Global group ids follow ``goff`` (host-accumulated in ascending
    rep-slot order from the pulled root counts — the host path's group
    numbering; noise rides slot ``goff[rep]``, clusters follow):

    - ``goh`` (N, s_pad): the group one-hot plane the mask-assign matmul
      consumes (node points, NOT ratio-filtered — like the host path);
    - ``obj_plane`` packed (s_pad, ceil(N/8)): the ratio-filtered object
      membership — the ONLY per-point payload the drain ever pulls;
    - ``group_size``/``npts_ratio``/``bb_min``/``bb_max``: O(S) stats;
    - ``glo``/``ghi``: each mask's own rep's global group range.

    ``s_pad`` is the pow2 bucket of the TRUE group total (floor 128 fills
    MXU lanes), so the (k2, N) x (N, s_pad) assign matmuls never pay for
    the capacity ceiling — ``cfg.post_group_cap`` is only the raise
    threshold, checked before this kernel is dispatched.
    """
    r_pad = goff.shape[0]
    n = points.shape[0]
    od = counting.operand_dtype(count_dtype)
    rep_clip = jnp.clip(pair_rep, 0, r_pad - 1)
    gg = jnp.where(pair_valid,
                   jnp.take(goff, rep_clip) + dense_local + 1, s_pad)
    ratio_pair = jnp.take(
        ratio_ok.reshape(-1),
        jnp.clip(rep_clip * n + pair_pt, 0, r_pad * n - 1))
    gg_ratio = jnp.where(ratio_pair & pair_valid, gg, s_pad)
    group_size = jnp.zeros(s_pad, jnp.int32).at[gg].add(1, mode="drop")
    npts_ratio = jnp.zeros(s_pad, jnp.int32).at[gg_ratio].add(1, mode="drop")
    goh = jnp.zeros((n, s_pad), od).at[pair_pt, gg].set(1, mode="drop")
    obj_plane = jnp.zeros((s_pad, n), bool).at[gg_ratio, pair_pt].set(
        True, mode="drop")
    pair_pts3 = jnp.take(points, pair_pt, axis=0)  # (C, 3)
    bb_min = jnp.full((s_pad, 3), jnp.inf, jnp.float32).at[gg].min(
        pair_pts3, mode="drop")
    bb_max = jnp.full((s_pad, 3), -jnp.inf, jnp.float32).at[gg].max(
        pair_pts3, mode="drop")

    ridx = jnp.clip(ridx_of_mask, 0, r_pad - 1)
    glo = jnp.where(alive, jnp.take(goff, ridx), 0)
    ghi = glo + jnp.where(alive, jnp.take(ngrp, ridx), 0)
    return (group_size, npts_ratio, goh, _pack_bits(obj_plane),
            bb_min, bb_max, glo, ghi)


def _mask_group_counts_impl(
    first: jnp.ndarray,  # (F, N) int16
    last: jnp.ndarray,  # (F, N) int16
    goh: jnp.ndarray,  # (N, s_pad) group one-hot plane (operand dtype)
    mask_flat: jnp.ndarray,  # (M_pad,) int32 = frame * k2 + id of each mask slot
    group_lo: jnp.ndarray,  # (M_pad,) int32 first global group of the mask's rep
    group_hi: jnp.ndarray,  # (M_pad,) int32 one past the rep's last group (0 width = dead)
    *,
    k2: int,
    s_pad: int,
    count_dtype: str = "bf16",
):
    """Best DBSCAN group (+ claim count) per mask slot.

    counts[m, g] = |claims of mask m with group label g| computed as per-frame
    one-hot matmuls against the (N, s_pad) group membership plane; the argmax
    is restricted to the mask's own rep's group range (ties -> lowest group,
    like the host path's packed reduceat). Counts reduce through the f32
    argmax/max in BOTH encodings (exact integers below 2^24) so the emitted
    coverage floats are bit-identical across count_dtype.
    """
    f, n = first.shape

    def step(_, inp):
        a, b = inp
        # a cell where last == first holds ONE claim (one mask) — drop b
        b = jnp.where(b == a, k2 - 1, b)  # k2-1 is an unused sentinel row
        oh_a = counting.count_onehot(a, k2, count_dtype=count_dtype, axis=0)  # (k2, N)
        oh_b = counting.count_onehot(b, k2, count_dtype=count_dtype, axis=0)
        cnt = counting.count_dot(oh_a, goh, count_dtype=count_dtype)
        cnt = cnt + counting.count_dot(oh_b, goh, count_dtype=count_dtype)
        return None, cnt  # (k2, s_pad) exact integer counts in f32

    _, counts = jax.lax.scan(step, None, (first, last))  # (F, k2, s_pad)
    per_mask = jnp.take(counts.reshape(f * k2, s_pad),
                        jnp.clip(mask_flat, 0, f * k2 - 1), axis=0)  # (M_pad, S)
    slots = jnp.arange(s_pad, dtype=jnp.int32)[None, :]
    in_range = (slots >= group_lo[:, None]) & (slots < group_hi[:, None])
    masked = jnp.where(in_range, per_mask, -1.0)
    best_group = jnp.argmax(masked, axis=1).astype(jnp.int32)
    best_count = jnp.max(masked, axis=1)
    return best_group, best_count


_mask_group_counts_kernel = functools.partial(
    jax.jit, static_argnames=("k2", "s_pad", "count_dtype"))(_mask_group_counts_impl)
# donating variant: this kernel is the LAST consumer of the (F, N)
# first/last claim tensors — donating them releases ~2 x F x N x 2 bytes of
# HBM mid-postprocess, in time for the NEXT scene's association dispatch at
# the same shape bucket (the overlapped executor runs the two concurrently);
# (0, 1) is pinned by mct-check IR.DONATION.WIRING — dropping the donation
# fails the analysis gate
_mask_group_counts_kernel_donating = functools.partial(
    jax.jit, static_argnames=("k2", "s_pad", "count_dtype"),
    donate_argnums=(0, 1))(_mask_group_counts_impl)


@functools.partial(jax.jit, static_argnames=("count_dtype",))
def _survivor_gather_kernel(
    obj_packed: jnp.ndarray,  # (s_pad, ceil(N/8)) uint8 object bit-planes
    surv_idx: jnp.ndarray,  # (O_pad,) int32 surviving global groups (pad: 0)
    *,
    count_dtype: str = "bf16",
):
    """Compact the surviving objects + their pairwise intersection counts.

    ``rows`` are the emit-only drain payload (bit-packed point membership
    of each surviving object); ``inter[i, j] = |points_i and points_j|``
    is the overlap-merge containment numerator, computed as ONE
    mask x mask ``count_dot`` on the MXU — the O(objects^2 x N) work the
    host merge used to spend in python set intersections. Padded rows
    beyond the true survivor count produce junk the host never reads.
    """
    rows = jnp.take(obj_packed, surv_idx, axis=0)  # (O_pad, N8/8)
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (rows[:, :, None] >> shifts[None, None, :]) & jnp.uint8(1)
    flat = bits.reshape(rows.shape[0], -1).astype(
        counting.operand_dtype(count_dtype))
    inter = counting.count_dot(flat, flat.T, count_dtype=count_dtype)
    return rows, inter


def postprocess_scene_device(
    scene_points: np.ndarray,  # (N, 3) float32, host
    first: jnp.ndarray,  # (F, N) int16, device
    last: jnp.ndarray,  # (F, N) int16, device
    mask_frame: np.ndarray,  # (M_pad,) int32, host
    mask_id: np.ndarray,  # (M_pad,) int32, host (-1 padding)
    mask_active: jnp.ndarray,  # (M_pad,) bool, device
    assignment: jnp.ndarray,  # (M_pad,) int32, device
    node_visible: jnp.ndarray,  # (M_pad, F) bool, device
    frame_ids: Sequence,  # original frame identifiers, len >= F real frames
    *,
    k_max: int = 127,
    point_filter_threshold: float = 0.5,
    dbscan_eps: float = 0.1,
    dbscan_min_points: int = 4,
    overlap_merge_ratio: float = 0.8,
    min_masks_per_object: int = 2,
    timings: Optional[Dict[str, float]] = None,
    pull_chunk: int = 0,
    donate: bool = False,
    count_dtype: str = "bf16",
    group_cap: int = 512,
    neighbor_cap: int = 256,
    n_real: Optional[int] = None,
) -> SceneObjects:
    """Same contract and outputs as postprocess_scene; emit-only drain.

    The whole split/assign/merge chain — routing prep, node statistics,
    grid DBSCAN, group structures, mask->group assignment, object
    intersection counts — dispatches as an uninterrupted device program
    sequence; the only device->host transfers are the final drain (O(M+S)
    scalars + the surviving objects' bit-packed point planes). The
    assignment and claim planes are consumed in HBM, never pulled. The
    greedy overlap-merge threshold scan and artifact assembly run on host
    over the drained compact results, so artifacts are byte-identical to
    the host path (asserted by tests/test_postprocess_device.py).

    ``pull_chunk`` > 0 drains the object bit-planes in row chunks of that
    size: every chunk's ``copy_to_host_async`` is issued up front, then
    chunks materialize and unpack in order — the unpack of chunk i rides
    under chunk i+1's DMA (byte-identical at any chunk size).

    Point-sharded inputs (the fused mesh path with ``cfg.point_shards``
    > 1 hands ``first``/``last`` in with their N columns sharded over the
    ``point`` mesh axis) run this chain unchanged: the kernels compile
    against the committed shardings, the claim planes are still consumed
    in HBM, and each drained chunk assembles per-shard (one DMA per
    addressable shard under ``copy_to_host_async``). The largest single
    host materialization stays one chunk of bit-packed survivor rows —
    ``pull_chunk x ceil(N/8)`` bytes, O(N) not O(F*N) — recorded on the
    ``post.drain.max_chunk_bytes`` gauge, which the 1M-point acceptance
    test pins far below one (F, N) plane
    (tests/test_point_sharding.py).

    ``donate=True`` donates the (F, N) first/last tensors into the final
    group-counts kernel — their HBM frees mid-postprocess instead of at
    scene teardown. The caller must not touch them afterwards.
    """
    t = _PhaseTimer(timings)
    f, n = first.shape
    m_pad = mask_frame.shape[0]
    k2 = k_max + 2
    from maskclustering_tpu.utils.compile_cache import record_shape_bucket

    # ---- r_pad sizing: a 4-byte scalar pull, not an assignment pull ----
    # The live-rep axis must be static before the prep/node-stats kernels
    # compile, and its tight bucket is device data. Pulling the one count
    # scalar keeps r_pad at the host prep's historical bucket (pow2 of the
    # live count, floor 64) without the (M_pad,) assignment ever crossing.
    with obs.span("post.prep.pull"):
        live = int(_live_count_kernel(
            assignment, mask_active,
            min_masks_per_object=int(min_masks_per_object)))
        obs.count_transfer("d2h", 4, "post.drain")
    if live == 0:
        for phase in ("claims", "dbscan", "mask_assign", "emit", "merge"):
            t.mark(phase)
        return SceneObjects(point_ids_list=[], mask_list=[], num_points=n)
    r_pad = _rep_bucket(live)
    record_shape_bucket("post.nodestats", r_pad, m_pad, f, n, k2)

    # ---- device program chain: prep -> node stats -> split -> assign ----
    # No bulk host transfer anywhere in this block: every kernel consumes
    # the previous one's device outputs, and the grid is host geometry
    # (scene_points never left the host) uploaded alongside the mask table.
    mask_frame_d = jnp.asarray(mask_frame)
    mask_id_d = jnp.asarray(mask_id)
    with obs.span("post.claims.kernel", r_pad=r_pad, m_pad=m_pad,
                  f=f, n=n) as sp:
        rep_tab, live_slots, live_valid, ridx_of_mask, alive, mask_flat = \
            _prep_kernel(assignment, mask_active, mask_frame_d, mask_id_d,
                         r_pad=r_pad, f=f, k2=k2,
                         min_masks_per_object=int(min_masks_per_object))
        claimed, ratio_ok, nv_rep = sp.sync(_node_stats_kernel(
            first, last, rep_tab, node_visible, live_slots, live_valid,
            r_pad=r_pad, point_filter_threshold=float(point_filter_threshold),
            count_dtype=count_dtype))
    t.mark("claims")

    # ---- pair-bucket sizing: the ONE O(r_pad) metadata pull mid-chain ----
    # The (rep, point) pair axis must be static before the split kernel
    # compiles, and its tight bucket is device data (per-rep node sizes).
    # This pull is a few hundred BYTES of shape metadata — the exact
    # analog of the mask-table bucket pull — not a claims drain: the
    # (r_pad, N) planes and (F, N) claim tensors stay in HBM untouched.
    # The alternative (a worst-case r_pad*N pair pad) would multiply every
    # split sweep by the dead-rep padding.
    sizes_d = jnp.sum(claimed, axis=1, dtype=jnp.int32)
    cand_d = (live_valid & (sizes_d > 0) & jnp.any(nv_rep, axis=1))
    with obs.span("post.split.pull", r_pad=r_pad) as sp:
        _start_host_copy(sizes_d)
        _start_host_copy(cand_d)
        sizes = np.asarray(sizes_d)
        cand_pre = np.asarray(cand_d)
        obs.count_transfer("d2h", sizes.nbytes + cand_pre.nbytes,
                           "post.drain")
    num_pairs = int(sizes[cand_pre].sum())
    if num_pairs == 0:
        t.mark("dbscan")
        t.mark("mask_assign")
        t.mark("emit")
        t.mark("merge")
        return SceneObjects(point_ids_list=[], mask_list=[], num_points=n)

    # ---- grid DBSCAN split, on device ----
    # n_real keeps the sentinel pad points out of the voxel grid: they
    # share ONE coordinate, so binning them would put the whole pad run
    # into a single cell and multiply the static candidate window
    # (cell_cap) by orders of magnitude
    grid = build_grid(scene_points, dbscan_eps, n_real=n_real)
    c_pad = _bucket_pow2(num_pairs, minimum=256)
    record_shape_bucket("post.dbscan", r_pad, c_pad, grid.cell_cap, n)
    points_d = jnp.asarray(scene_points, jnp.float32)
    with obs.span("post.dbscan.kernel", r_pad=r_pad,
                  c_pad=c_pad, cell_cap=grid.cell_cap) as sp:
        (pair_rep, pair_pt, pair_valid, dense_local, ngrp_d,
         nb_overflow_d) = sp.sync(
            _dbscan_split_kernel(
                claimed, nv_rep, live_valid, points_d,
                jnp.asarray(grid.order), jnp.asarray(grid.start),
                jnp.asarray(grid.length),
                c_pad=c_pad, cell_cap=grid.cell_cap,
                neighbor_cap=int(neighbor_cap), eps=float(dbscan_eps),
                min_points=int(dbscan_min_points)))
    # O(r_pad) group-count pull: sizes the group axis TIGHTLY (the assign
    # matmul width rides it — the capacity ceiling would 4x the MXU work)
    # and surfaces capacity overflows BEFORE any structure is built
    with obs.span("post.groups.pull", r_pad=r_pad):
        _start_host_copy(ngrp_d)
        _start_host_copy(nb_overflow_d)
        ngrp = np.asarray(ngrp_d)
        nb_overflow = bool(np.asarray(nb_overflow_d))
        obs.count_transfer("d2h", ngrp.nbytes + 1, "post.drain")
    if nb_overflow:
        raise PostprocessCapacityError(
            "DBSCAN neighbor-list", -1, int(neighbor_cap),
            "post_neighbor_cap")
    total = int(ngrp.sum())
    if total > max(int(group_cap), 1):
        raise PostprocessCapacityError(
            "DBSCAN group", total, int(group_cap), "post_group_cap")
    # global offsets accumulate in ascending rep-slot order — the host
    # path's group numbering; floor 128 fills the MXU's lane dimension
    goff = np.zeros(r_pad, np.int32)
    goff[1:] = np.cumsum(ngrp[:-1]).astype(np.int32)
    s_pad = _bucket_pow2(total, minimum=128)
    record_shape_bucket("post.groups", r_pad, s_pad, c_pad, n)
    with obs.span("post.groups.kernel", s_pad=s_pad) as sp:
        (group_size_d, npts_ratio_d, goh, obj_packed, bb_min_d, bb_max_d,
         glo_d, ghi_d) = sp.sync(_group_structs_kernel(
            pair_rep, pair_pt, pair_valid, dense_local,
            jnp.asarray(goff), jnp.asarray(ngrp.astype(np.int32)),
            ratio_ok, points_d, ridx_of_mask, alive,
            s_pad=s_pad, count_dtype=count_dtype))
    t.mark("dbscan")

    with obs.span("post.mask_assign.kernel", s_pad=s_pad, m_pad=m_pad) as sp:
        # last consumer of first/last: the donating variant hands their HBM
        # back to the allocator for the next scene's same-bucket dispatch
        kernel = (_mask_group_counts_kernel_donating if donate
                  else _mask_group_counts_kernel)
        best_group_d, best_count_d = sp.sync(kernel(
            first, last, goh, mask_flat, glo_d, ghi_d,
            k2=k2, s_pad=s_pad, count_dtype=count_dtype))
    t.mark("mask_assign")

    # ---- emit-only drain, stage 1: O(M_pad + S) scalars ----
    with obs.span("post.drain.pull", s_pad=s_pad, m_pad=m_pad) as sp:
        small = (group_size_d, npts_ratio_d, best_group_d,
                 best_count_d, glo_d, ghi_d, bb_min_d, bb_max_d)
        for arr in small:
            _start_host_copy(arr)
        (group_size, npts_ratio, best_group, best_count, glo, ghi,
         bb_min, bb_max) = (np.asarray(a) for a in small)
        obs.count_transfer(
            "d2h", sum(np.asarray(a).nbytes for a in
                       (group_size, npts_ratio, best_group, best_count,
                        glo, ghi, bb_min, bb_max)),
            "post.drain")

    # ---- host: mask lists per group, survivor filter (host-path order) ----
    obj_masks: Dict[int, List[Tuple]] = {}
    for m in np.nonzero(ghi > glo)[0]:
        cnt = best_count[m]
        if cnt <= 0:  # no surviving claims (all mid-id overlaps)
            continue
        gl = int(best_group[m])
        obj_masks.setdefault(gl, []).append(
            (frame_ids[mask_frame[m]], int(mask_id[m]),
             float(cnt / group_size[gl])))
    survivors = [g for g in range(int(total))
                 if group_size[g] > 0 and npts_ratio[g] > 0
                 and len(obj_masks.get(g, [])) >= min_masks_per_object]
    if not survivors:
        t.mark("emit")
        t.mark("merge")
        return SceneObjects(point_ids_list=[], mask_list=[], num_points=n)

    # ---- drain, stage 2: surviving objects' bit-planes + merge counts ----
    o = len(survivors)
    o_pad = _bucket_pow2(o, minimum=8)
    record_shape_bucket("post.drain", o_pad, s_pad, n)
    surv_idx = np.zeros(o_pad, np.int32)
    surv_idx[:o] = survivors
    with obs.span("post.drain.objpull", objects=o, o_pad=o_pad) as sp:
        rows_d, inter_d = _survivor_gather_kernel(
            obj_packed, jnp.asarray(surv_idx), count_dtype=count_dtype)
        # drain at the o_pad bucket and trim on host: an eager device
        # slice at the raw survivor count would compile one executable
        # per distinct o (the compile-variant churn the old r_pull
        # quantization existed to avoid); the padded rows are junk the
        # host never reads, a few extra KB of transfer at most
        chunks = _row_chunks(rows_d, o_pad, pull_chunk)
        for c in chunks:
            _start_host_copy(c)
        _start_host_copy(inter_d)
        pulled = 0
        max_chunk = 0
        parts = []
        for c in chunks:
            h = np.asarray(c)  # already landed (or blocks on the DMA)
            pulled += h.nbytes
            max_chunk = max(max_chunk, h.nbytes)
            parts.append(_unpack_bits(h, n))
        member = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
        inter = np.asarray(inter_d)[:o, :o]
        sp.set(chunks=len(chunks))
        # the drain's host-buffer ceiling: the largest single pull any
        # scene of this process materialized (high-water, so multi-scene
        # runs keep the worst case). The point-sharding acceptance test
        # pins it under one (F, N) claim plane — the emit-only contract
        # stated as a counter, not a comment
        obs.gauge_max("post.drain.max_chunk_bytes", float(max_chunk))
        obs.count_transfer("d2h", pulled + np.asarray(inter_d).nbytes,
                           "post.drain")
    t.mark("emit")

    point_ids = [np.nonzero(member[i])[0].astype(np.int32) for i in range(o)]
    bboxes = [(bb_min[g], bb_max[g]) for g in survivors]
    masks = [obj_masks[g] for g in survivors]
    sizes = npts_ratio[survivors]
    point_ids_list, mask_list = merge_from_counts(
        point_ids, bboxes, masks, sizes, inter, overlap_merge_ratio)
    t.mark("merge")
    return SceneObjects(point_ids_list=point_ids_list, mask_list=mask_list,
                        num_points=n)
