"""Device-side post-process: node/claim statistics as on-TPU tensor passes.

The host post-process (models/postprocess.py) reproduces the reference's
pipeline (reference utils/post_process.py:40-170) with vectorized numpy over
COO claim structures — but building those structures requires pulling the
(F, N) ``first_id``/``last_id`` tensors off the device (hundreds of MB per
scene) and running multi-million-row nonzero/sort passes on host. At bench
scale that is 12-16 s/scene, the dominant pipeline cost.

Everything except the per-object DBSCAN split is segment arithmetic over
tensors the device already holds, so this module keeps it there:

- ``_node_stats_kernel``: one lax.scan over frames accumulates, for every
  (live representative r, point p): ``claimed`` (p is a node point of r),
  ``num`` (frames where p is claimed by a node mask with node-visibility,
  the OVIR detection-ratio numerator, reference post_process.py:56-76) and
  ``den`` (node-visible frames where p is visible at all). Each frame is
  one (2R, k2) @ (k2, N) MXU matmul of local-id one-hots against per-frame
  rep-weight rows (no scatters, no gathers from large tables — both slow
  on TPU, measured in scripts/micro_tpu.py); ``den`` is a single
  (R, F) @ (F, N) matmul outside the scan.
- results return as bit-packed uint8 planes (8x smaller transfer).
- host runs DBSCAN per representative on the compact node point lists
  (reference post_process.py:104-123 uses Open3D's C++ DBSCAN on host too)
  and uploads a compact (point id, global group) list back.
- ``_mask_group_counts_kernel``: a second scan over frames counts each
  mask's claims per DBSCAN group via (K, N) x (N, S) matmuls on the MXU and
  reduces to the best group + count per mask on device, replacing the
  reference's per-(mask x group) intersect1d loop (post_process.py:~150).

Net device->host traffic: ~2 x R_pad x N/8 bytes + O(M_pad) scalars instead
of 2-3 (F, N) claim tensors (int16 since the plane narrowing — the
non-device path's pull halved along with the HBM residency).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from maskclustering_tpu.utils.donation import suppress_unusable_donation_warning

# this module donates the (F, N) claim tensors into the group-counts
# kernel; see the helper's docstring for why the filter is global
suppress_unusable_donation_warning()

from maskclustering_tpu import obs
from maskclustering_tpu.ops import counting
from maskclustering_tpu.models.postprocess import (
    SceneObjects,
    _merge_overlapping,
    _PhaseTimer,
    postprocess_scene,
)
from maskclustering_tpu.ops.dbscan import dbscan_labels_parallel


def run_postprocess(cfg, scene_points, first, last, mask_frame, mask_id,
                    mask_active, assignment, node_visible, frame_ids, *,
                    k_max: int, timings: Optional[Dict[str, float]] = None,
                    n_real: Optional[int] = None) -> SceneObjects:
    """Single dispatch point for the device/host post-process paths.

    Accepts device or host arrays for the large operands; converts to what
    the selected path needs. Both paths produce byte-identical artifacts.

    ``n_real``: the scene's true point count when the inputs are padded to a
    shape bucket; enforces the sentinel-pad invariant (no padded point may
    be claimed) and restores the real count on the returned objects.
    """
    kwargs = dict(
        k_max=k_max,
        point_filter_threshold=cfg.point_filter_threshold,
        dbscan_eps=cfg.dbscan_split_eps,
        dbscan_min_points=cfg.dbscan_split_min_points,
        overlap_merge_ratio=cfg.overlap_merge_ratio,
        min_masks_per_object=cfg.min_masks_per_object,
        timings=timings,
    )
    scene_points = np.asarray(scene_points)
    mask_frame = np.asarray(mask_frame)
    mask_id = np.asarray(mask_id)
    mask_active = np.asarray(mask_active)
    assignment = np.asarray(assignment)
    if cfg.device_postprocess:
        objects = postprocess_scene_device(
            scene_points, jnp.asarray(first), jnp.asarray(last), mask_frame,
            mask_id, mask_active, assignment, jnp.asarray(node_visible),
            frame_ids, pull_chunk=cfg.claims_pull_chunk,
            donate=cfg.donate_buffers, count_dtype=cfg.count_dtype, **kwargs)
    else:
        with obs.span("post.host_pull") as sp:
            # the host path pulls the full (F, N) claim tensors — the very
            # transfer the device path exists to avoid; on the books so a
            # report makes the paths' cost difference legible
            first_h = np.asarray(first)
            last_h = np.asarray(last)
            nv_h = np.asarray(node_visible)
            obs.count_transfer(
                "d2h", first_h.nbytes + last_h.nbytes + nv_h.nbytes,
                "postprocess")
        objects = postprocess_scene(
            scene_points, first_h, last_h, first_h > 0, mask_frame,
            mask_id, mask_active, assignment, nv_h,
            frame_ids, **kwargs)
    if n_real is not None and objects.num_points != n_real:
        for pids in objects.point_ids_list:
            # not an assert: this guards exported artifacts and must survive -O
            if pids.size and int(pids.max()) >= n_real:
                raise RuntimeError(
                    "sentinel pad point claimed — padding invariant violated "
                    f"(max point id {int(pids.max())} >= num_points {n_real})")
        objects = SceneObjects(point_ids_list=objects.point_ids_list,
                               mask_list=objects.mask_list, num_points=n_real)
    return objects


def _frame_chunk(f: int) -> int:
    """Frames per claims-scan step: largest divisor of F in {8,4,2,1}.

    Keeps (most of) the matmul contraction depth when a caller pads F to a
    multiple of 4 or 2 instead of 8.
    """
    return next(c for c in (8, 4, 2, 1) if f % c == 0)


def _bucket_pow2(value: int, minimum: int = 8) -> int:
    """Smallest power-of-two >= max(value, minimum) — jit shape buckets."""
    b = minimum
    while b < value:
        b *= 2
    return b


def _live_rep_prep(mask_frame, mask_id, mask_active, assignment, f, k2,
                   min_masks_per_object):
    """Host prep for `_node_stats_kernel`: live reps + claim routing table.

    Shared with scripts/claims_diag.py so the diagnostic always times the
    exact shapes the pipeline runs. Returns None when no cluster reaches
    ``min_masks_per_object`` members, else
    ``(reps, r_pad, rep_lut, rep_tab, live_slots, live_valid, r_pull)``.
    """
    m_pad = mask_frame.shape[0]
    sizes = np.bincount(assignment[mask_active], minlength=m_pad)
    reps = np.nonzero(sizes >= min_masks_per_object)[0]
    if len(reps) == 0:
        return None
    # floor 64: 2*r_pad = 128 exactly fills the MXU's systolic dimension, so
    # padding small scenes up is compute-free — and it collapses the
    # {8,16,32,64} r_pad compile variants (northstar's "scene 8" paid a
    # hidden ~10 s _node_stats_kernel compile for being the first 32-rep
    # scene) into one
    r_pad = _bucket_pow2(len(reps), minimum=64)
    rep_lut = np.full(m_pad, -1, dtype=np.int32)
    rep_lut[reps] = np.arange(len(reps), dtype=np.int32)

    # local (frame, id) -> dense live-rep index of the claiming mask's cluster
    gmap = np.full((f, k2), -1, dtype=np.int64)
    act_idx = np.nonzero(mask_active)[0]
    gmap[mask_frame[act_idx], mask_id[act_idx]] = act_idx
    rep_tab = np.full((f, k2), -1, dtype=np.int32)
    mapped = gmap >= 0
    rep_tab[mapped] = rep_lut[assignment[gmap[mapped]]]

    live_slots = np.zeros(r_pad, dtype=np.int32)
    live_slots[: len(reps)] = reps
    live_valid = np.zeros(r_pad, dtype=bool)
    live_valid[: len(reps)] = True
    # quantize the row slice to multiples of 8 so the eager device slice op
    # itself stays within a handful of compiled shapes per r_pad
    r_pull = min(r_pad, -(-len(reps) // 8) * 8)
    return reps, r_pad, rep_lut, rep_tab, live_slots, live_valid, r_pull


@functools.partial(jax.jit, static_argnames=("r_pad", "point_filter_threshold",
                                             "count_dtype"))
def _node_stats_kernel(
    first: jnp.ndarray,  # (F, N) int16 smallest valid claiming id per (frame, point)
    last: jnp.ndarray,  # (F, N) int16 largest valid claiming id
    rep_tab: jnp.ndarray,  # (F, K+2) int32: local mask id -> dense live-rep index, -1 none
    node_visible: jnp.ndarray,  # (M_pad, F) bool per-representative visibility
    live_slots: jnp.ndarray,  # (r_pad,) int32 global slot of each live rep (pad: 0)
    live_valid: jnp.ndarray,  # (r_pad,) bool
    *,
    r_pad: int,
    point_filter_threshold: float,
    count_dtype: str = "bf16",
):
    """Per-(rep, point) claim statistics, bit-packed.

    Returns (claimed_packed, ratio_packed, nv_rep): (r_pad, N8/8) uint8 x2
    plus the (r_pad, F) bool node-visibility rows for the live reps.

    Frames are processed in chunks of C: each scan step contracts one
    (2R, C*k2) @ (C*k2, N) matmul — local-id one-hots of the claim
    extremes (with a -1 row correction so two masks of the same rep
    claiming one cell count ONE unique (rep, point, frame) triple, like
    the host path's sort) against per-frame weight rows W[c, r, k] =
    [rep_tab==r] (* node-visibility for the OVIR numerator). One frame per
    step made the contraction depth k2 (~65) — too shallow to feed the
    128x128 systolic array — and paid F sequential steps; C frames per
    step deepens the contraction C-fold and cuts the step count to F/C at
    the cost of a (C, k2, N) narrow operand window in HBM (~200 MB at
    C=8, bench shapes, bf16; half that under ``count_dtype="int8"``).
    One-hot operands with exact accumulation (f32 or s32, ops/counting.py)
    stay exact; the only non-0/1 entries are the {0, 1, 2} values of the
    duplicate-correction matrix m, representable in both encodings. The
    ratio denominator drops out of the scan entirely: one (R, F) @ (F, N)
    matmul of node-visibility against point-visibility.
    """
    f, n = first.shape
    k2 = rep_tab.shape[1]
    nv_rep = jnp.take(node_visible, live_slots, axis=0) & live_valid[:, None]
    od = counting.operand_dtype(count_dtype)
    acc_dtype = counting.accumulator_dtype(count_dtype)

    chunk = _frame_chunk(f)

    def step(carry, inp):
        acc = carry
        a, b, rt, nv_f = inp  # (C, N) x2, (C, k2), (C, R)
        # per-chunk weight rows, built in-step from the scanned rep rows
        # and nv columns — negligible VPU work vs holding an (F, 2R, k2)
        # tensor in HBM for the whole scan
        rep_oh = counting.count_onehot(rt, r_pad, count_dtype=count_dtype,
                                       axis=1)  # (C, R, k2)
        w = jnp.concatenate(
            [rep_oh * nv_f.astype(od)[:, :, None], rep_oh],
            axis=1)  # (C, 2R, k2)
        # id 0 = no claim and rep_tab[:, 0] is always -1 (ids are 1-based), so
        # W column 0 is zero — routing the a == b duplicate there drops it.
        # Distinct ids of one rep claiming the same cell must also count once
        # (one unique triple): detect rep_a == rep_b with a != b and subtract
        # the duplicate via a one-hot on the a id.
        b2 = jnp.where(b == a, 0, b)
        rep_a = jnp.take_along_axis(rt, a.astype(jnp.int32), axis=1)  # (C, N) dense rep or -1
        rep_b = jnp.take_along_axis(rt, b2.astype(jnp.int32), axis=1)
        dup = (rep_a >= 0) & (rep_a == rep_b) & (a != b2)
        oh_a = counting.count_onehot(a, k2, count_dtype=count_dtype,
                                     axis=1)  # (C, k2, N)
        oh_b = counting.count_onehot(b2, k2, count_dtype=count_dtype, axis=1)
        oh_dup = counting.count_onehot(jnp.where(dup, a, 0), k2,
                                       count_dtype=count_dtype, axis=1)
        m = oh_a + oh_b - oh_dup
        # sum_c w[c] @ m[c] as ONE deep contraction over (c, k2)
        acc = acc + counting.count_dot_general(
            w, m, (((0, 2), (0, 1)), ((), ())),
            count_dtype=count_dtype, out_dtype=None)
        return acc, None

    acc, _ = jax.lax.scan(
        step, jnp.zeros((2 * r_pad, n), acc_dtype),
        (first.reshape(f // chunk, chunk, n),
         last.reshape(f // chunk, chunk, n),
         rep_tab.reshape(f // chunk, chunk, k2),
         nv_rep.T.reshape(f // chunk, chunk, r_pad)))
    # exact integer counts in either accumulator; f32 conversion is exact
    # below 2^24, so the ratio threshold stays byte-identical across paths
    num = acc[:r_pad].astype(jnp.float32)
    claimed = acc[r_pad:] > 0

    den = counting.count_dot(nv_rep, first > 0, count_dtype=count_dtype)

    ratio_ok = num / (den + 1e-6) > point_filter_threshold
    return _pack_bits(claimed), _pack_bits(ratio_ok), nv_rep


def _pack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """(R, N) bool -> (R, ceil(N/8)) uint8, np.unpackbits-compatible (big-endian)."""
    r, n = x.shape
    n8 = -(-n // 8) * 8
    xp = jnp.pad(x, ((0, 0), (0, n8 - n))).reshape(r, n8 // 8, 8)
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.int32)
    return jnp.sum(xp.astype(jnp.int32) * weights, axis=-1).astype(jnp.uint8)


def _unpack_bits(packed: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(np.asarray(packed), axis=1)[:, :n].astype(bool)


def _row_chunks(arr, rows: int, chunk: int) -> List:
    """``arr[:rows]`` as a list of row slices of at most ``chunk`` rows.

    ``chunk <= 0`` (or a chunk covering everything) degenerates to the
    single-slice pull. Slicing is lazy on device; concatenating the
    materialized chunks in order reproduces the single pull byte-for-byte.
    """
    if chunk <= 0 or rows <= chunk:
        return [arr[:rows]]
    return [arr[i:min(i + chunk, rows)] for i in range(0, rows, chunk)]


def _start_host_copy(arr) -> None:
    """Kick off the device->host DMA without blocking (no-op off-backend)."""
    try:
        arr.copy_to_host_async()
    except AttributeError:  # backend without async host copies
        pass


def _mask_group_counts_impl(
    first: jnp.ndarray,  # (F, N) int16
    last: jnp.ndarray,  # (F, N) int16
    pt_ids: jnp.ndarray,  # (C_pad,) int32 node point ids (pad: N — dropped)
    pt_group: jnp.ndarray,  # (C_pad,) int32 global group ids (pad: s_pad — dropped)
    mask_flat: jnp.ndarray,  # (M_pad,) int32 = frame * k2 + id of each mask slot
    group_lo: jnp.ndarray,  # (M_pad,) int32 first global group of the mask's rep
    group_hi: jnp.ndarray,  # (M_pad,) int32 one past the rep's last group (0 width = dead)
    *,
    k2: int,
    s_pad: int,
    count_dtype: str = "bf16",
):
    """Best DBSCAN group (+ claim count) per mask slot.

    counts[m, g] = |claims of mask m with group label g| computed as per-frame
    one-hot matmuls against the (N, s_pad) group membership plane; the argmax
    is restricted to the mask's own rep's group range (ties -> lowest group,
    like the host path's packed reduceat). Counts reduce through the f32
    argmax/max in BOTH encodings (exact integers below 2^24) so the emitted
    coverage floats are bit-identical across count_dtype.
    """
    f, n = first.shape
    od = counting.operand_dtype(count_dtype)
    goh = jnp.zeros((n, s_pad), od)
    goh = goh.at[pt_ids, pt_group].set(1, mode="drop")

    def step(_, inp):
        a, b = inp
        # a cell where last == first holds ONE claim (one mask) — drop b
        b = jnp.where(b == a, k2 - 1, b)  # k2-1 is an unused sentinel row
        oh_a = counting.count_onehot(a, k2, count_dtype=count_dtype, axis=0)  # (k2, N)
        oh_b = counting.count_onehot(b, k2, count_dtype=count_dtype, axis=0)
        cnt = counting.count_dot(oh_a, goh, count_dtype=count_dtype)
        cnt = cnt + counting.count_dot(oh_b, goh, count_dtype=count_dtype)
        return None, cnt  # (k2, s_pad) exact integer counts in f32

    _, counts = jax.lax.scan(step, None, (first, last))  # (F, k2, s_pad)
    per_mask = jnp.take(counts.reshape(f * k2, s_pad),
                        jnp.clip(mask_flat, 0, f * k2 - 1), axis=0)  # (M_pad, S)
    slots = jnp.arange(s_pad, dtype=jnp.int32)[None, :]
    in_range = (slots >= group_lo[:, None]) & (slots < group_hi[:, None])
    masked = jnp.where(in_range, per_mask, -1.0)
    best_group = jnp.argmax(masked, axis=1).astype(jnp.int32)
    best_count = jnp.max(masked, axis=1)
    return best_group, best_count


_mask_group_counts_kernel = functools.partial(
    jax.jit, static_argnames=("k2", "s_pad", "count_dtype"))(_mask_group_counts_impl)
# donating variant: this kernel is the LAST consumer of the (F, N)
# first/last claim tensors — donating them releases ~2 x F x N x 2 bytes of
# HBM mid-postprocess, in time for the NEXT scene's association dispatch at
# the same shape bucket (the overlapped executor runs the two concurrently);
# (0, 1) is pinned by mct-check IR.DONATION.WIRING — dropping the donation
# fails the analysis gate
_mask_group_counts_kernel_donating = functools.partial(
    jax.jit, static_argnames=("k2", "s_pad", "count_dtype"),
    donate_argnums=(0, 1))(_mask_group_counts_impl)


def postprocess_scene_device(
    scene_points: np.ndarray,  # (N, 3) float32, host
    first: jnp.ndarray,  # (F, N) int16, device
    last: jnp.ndarray,  # (F, N) int16, device
    mask_frame: np.ndarray,  # (M_pad,) int32, host
    mask_id: np.ndarray,  # (M_pad,) int32, host (-1 padding)
    mask_active: np.ndarray,  # (M_pad,) bool, host
    assignment: np.ndarray,  # (M_pad,) int32, host
    node_visible: jnp.ndarray,  # (M_pad, F) bool, device
    frame_ids: Sequence,  # original frame identifiers, len >= F real frames
    *,
    k_max: int = 127,
    point_filter_threshold: float = 0.5,
    dbscan_eps: float = 0.1,
    dbscan_min_points: int = 4,
    overlap_merge_ratio: float = 0.8,
    min_masks_per_object: int = 2,
    timings: Optional[Dict[str, float]] = None,
    pull_chunk: int = 0,
    donate: bool = False,
    count_dtype: str = "bf16",
) -> SceneObjects:
    """Same contract and outputs as postprocess_scene, minus the (F, N) pulls.

    first/last/node_visible stay on device; only bit-packed (R, N/8) planes
    and O(M_pad) scalars cross the host boundary. The DBSCAN split and the
    final merge/emit run on host exactly as in the host path, so artifacts
    are byte-identical (asserted by tests/test_postprocess_device.py).

    ``pull_chunk`` > 0 drains the claimed bit-planes in row chunks of that
    size: every chunk's ``copy_to_host_async`` is issued up front, then
    chunks materialize and unpack in order — the unpack of chunk i rides
    under chunk i+1's DMA, splitting ``post.claims`` into overlapping
    kernel/transfer/unpack slices (the structural answer to the
    kernel-vs-tunnel attribution question; identical bytes either way).

    ``donate=True`` donates the (F, N) first/last tensors into the final
    group-counts kernel — their HBM frees mid-postprocess instead of at
    scene teardown. The caller must not touch them afterwards.
    """
    t = _PhaseTimer(timings)
    f, n = first.shape
    m_pad = mask_frame.shape[0]
    k2 = k_max + 2

    prep = _live_rep_prep(mask_frame, mask_id, mask_active, assignment,
                          f, k2, min_masks_per_object)
    if prep is None:
        t.mark("claims")
        return SceneObjects(point_ids_list=[], mask_list=[], num_points=n)
    reps, r_pad, rep_lut, rep_tab, live_slots, live_valid, r_pull = prep
    from maskclustering_tpu.utils.compile_cache import record_shape_bucket

    record_shape_bucket("post.nodestats", r_pad, m_pad, f, n, k2)

    # The round-5 open question — is post.claims kernel time or transfer
    # time? — is answered by fencing the two halves separately: with obs
    # armed, the kernel span syncs on the kernel outputs (pure device
    # compute) and the pull span owns only the device->host DMA + unpack.
    # Disarmed, both spans are timing-only no-ops with NO extra sync, so
    # the async-dispatch overlap this phase depends on is preserved.
    with obs.span("post.claims.kernel", r_pad=r_pad, m_pad=m_pad,
                  f=f, n=n) as sp:
        claimed_p, ratio_p, nv_rep_d = sp.sync(_node_stats_kernel(
            first, last, jnp.asarray(rep_tab), node_visible,
            jnp.asarray(live_slots), jnp.asarray(live_valid),
            r_pad=r_pad, point_filter_threshold=float(point_filter_threshold),
            count_dtype=count_dtype))
    # device->host transfers dominate this phase on a narrow link (the
    # driver rig's tunnel moves ~2-3 MB/s; a TPU-VM's PCIe makes them
    # ~free). Three cuts: pull only the len(reps) live rows of the
    # (r_pad, N/8) planes; drain them in double-buffered row chunks (all
    # asyncs issued up front, so the unpack of chunk i overlaps chunk
    # i+1's DMA); and start the ratio plane's DMA after them — it isn't
    # consumed until the emit phase, so the copy rides the link while
    # dbscan/mask_assign run on the host. copy_to_host_async (not a helper
    # thread calling np.asarray: the blocking device_get holds the GIL on
    # this backend, so a threaded "overlap" serialized the dbscan stage's
    # Python loops — post.dbscan 0.11 -> 2.0 s measured on the driver rig).
    r_live = len(reps)
    with obs.span("post.claims.pull", r_pull=r_pull) as sp:
        chunks = _row_chunks(claimed_p, r_pull, pull_chunk)
        for c in chunks:
            _start_host_copy(c)
        ratio_sliced = ratio_p[:r_pull]
        _start_host_copy(ratio_sliced)
        pulled = 0
        parts = []
        for c in chunks:
            h = np.asarray(c)  # already landed (or blocks on the DMA)
            pulled += h.nbytes
            parts.append(_unpack_bits(h, n))
        claimed = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
        nv_host = np.asarray(nv_rep_d[:r_pull])
        nv_any = nv_host[:r_live].any(axis=1)
        sp.set(chunks=len(chunks))
        obs.count_transfer("d2h", pulled + nv_host.nbytes, "post.claims")
    t.mark("claims")

    # ---- DBSCAN split per live rep (host, native C++/sklearn) ----
    # group numbering matches the host path: offsets accumulate over reps in
    # ascending slot order, label 0 (noise) is kept as its own candidate.
    # The native call releases the GIL, so reps split in a thread pool;
    # ordered ex.map keeps the offset assembly deterministic.
    candidates: List[Tuple[int, np.ndarray]] = []
    for ridx in range(len(reps)):
        if not nv_any[ridx]:
            continue
        node_pts = np.nonzero(claimed[ridx])[0].astype(np.int32)
        if len(node_pts):
            candidates.append((ridx, node_pts))
    labels_list = dbscan_labels_parallel(
        [scene_points[pts] for _, pts in candidates], dbscan_eps, dbscan_min_points)

    rep_slices: List[Tuple[int, int, np.ndarray, np.ndarray]] = []
    goff_by_ridx = np.zeros(len(reps), dtype=np.int64)
    ngrp_by_ridx = np.zeros(len(reps), dtype=np.int64)
    pt_chunks: List[np.ndarray] = []
    grp_chunks: List[np.ndarray] = []
    group_offset = 0
    for (ridx, node_pts), labels in zip(candidates, labels_list):
        groups = (labels + 1).astype(np.int64)
        ngrp = int(groups.max()) + 1
        rep_slices.append((ridx, group_offset, node_pts, groups))
        goff_by_ridx[ridx] = group_offset
        ngrp_by_ridx[ridx] = ngrp
        pt_chunks.append(node_pts)
        grp_chunks.append(group_offset + groups)
        group_offset += ngrp
    t.mark("dbscan")

    if group_offset == 0:
        # materialize the in-flight ratio copy so a transfer error surfaces
        # here instead of being dropped with the unconsumed buffer
        np.asarray(ratio_sliced)
        return SceneObjects(point_ids_list=[], mask_list=[], num_points=n)
    # floor 128: the group-counts matmul's output width rides MXU lanes, so
    # widths below 128 waste lanes — and small-scene s_pad compile variants
    # ({32, 64, ...}) collapse into one
    s_pad = _bucket_pow2(group_offset, minimum=128)
    all_pts = np.concatenate(pt_chunks)
    all_grps = np.concatenate(grp_chunks)
    group_size = np.bincount(all_grps, minlength=s_pad)
    c_pad = _bucket_pow2(len(all_pts), minimum=1024)
    record_shape_bucket("post.groupcounts", s_pad, c_pad, m_pad, f, n, k2)
    pt_ids = np.full(c_pad, n, dtype=np.int32)  # sentinel n -> dropped scatter
    pt_grp = np.full(c_pad, s_pad, dtype=np.int32)
    pt_ids[: len(all_pts)] = all_pts
    pt_grp[: len(all_pts)] = all_grps

    # per-mask global group range of its rep (0-width for dead masks)
    ridx_of_mask = rep_lut[assignment]
    alive = mask_active & (ridx_of_mask >= 0)
    glo = np.zeros(m_pad, dtype=np.int32)
    ghi = np.zeros(m_pad, dtype=np.int32)
    glo[alive] = goff_by_ridx[ridx_of_mask[alive]]
    ghi[alive] = glo[alive] + ngrp_by_ridx[ridx_of_mask[alive]]
    mask_flat = (mask_frame.astype(np.int64) * k2
                 + np.clip(mask_id, 0, k2 - 1)).astype(np.int32)
    mask_flat[~alive] = 0

    with obs.span("post.mask_assign.kernel", s_pad=s_pad, c_pad=c_pad) as sp:
        # last consumer of first/last: the donating variant hands their HBM
        # back to the allocator for the next scene's same-bucket dispatch
        kernel = (_mask_group_counts_kernel_donating if donate
                  else _mask_group_counts_kernel)
        best_group_d, best_count_d = sp.sync(kernel(
            first, last, jnp.asarray(pt_ids), jnp.asarray(pt_grp),
            jnp.asarray(mask_flat), jnp.asarray(glo), jnp.asarray(ghi),
            k2=k2, s_pad=s_pad, count_dtype=count_dtype))
    best_group = np.asarray(best_group_d)
    best_count = np.asarray(best_count_d)
    obs.count_transfer("d2h", best_group.nbytes + best_count.nbytes,
                       "post.mask_assign")
    t.mark("mask_assign")

    # ---- assemble mask lists per global group (ascending mask order) ----
    obj_masks: Dict[int, List[Tuple]] = {}
    for m in np.nonzero(alive & (ghi > glo))[0]:
        cnt = best_count[m]
        if cnt <= 0:  # no surviving claims (all mid-id overlaps)
            continue
        gl = int(best_group[m])
        obj_masks.setdefault(gl, []).append(
            (frame_ids[mask_frame[m]], int(mask_id[m]),
             float(cnt / group_size[gl])))

    # ---- emit candidate objects (same order/filters as the host path) ----
    # the async host copy started after the claims pull is resident (or
    # nearly so) by now; this materializes it without re-transfer
    ratio_host = np.asarray(ratio_sliced)
    obs.count_transfer("d2h", ratio_host.nbytes, "post.emit")
    ratio_ok = _unpack_bits(ratio_host, n)
    total_point_ids: List[np.ndarray] = []
    total_bboxes: List[Tuple[np.ndarray, np.ndarray]] = []
    total_masks: List[List[Tuple]] = []
    for ridx, goff, node_pts, groups in rep_slices:
        r_ok = ratio_ok[ridx][node_pts]
        for g in range(int(groups.max()) + 1):
            sel = groups == g
            if not sel.any():
                continue
            masks_g = obj_masks.get(goff + g, [])
            obj_pts_all = node_pts[sel]
            obj_pts = obj_pts_all[r_ok[sel]]
            if len(obj_pts) == 0 or len(masks_g) < min_masks_per_object:
                continue
            pts3d = scene_points[obj_pts_all]
            total_point_ids.append(obj_pts)
            total_bboxes.append((pts3d.min(axis=0), pts3d.max(axis=0)))
            total_masks.append(masks_g)
    t.mark("emit")

    point_ids_list, mask_list = _merge_overlapping(
        total_point_ids, total_bboxes, total_masks, overlap_merge_ratio)
    t.mark("merge")
    return SceneObjects(point_ids_list=point_ids_list, mask_list=mask_list,
                        num_points=n)
