"""Per-frame mask backprojection as dense projective association.

The reference lifts each 2D mask to scene points with a serial per-frame,
per-mask pipeline: depth -> Open3D view cloud, per-mask voxel downsample +
DBSCAN denoise, 3D bbox crop of the scene cloud, then a CUDA ball_query
(K=20, r=0.01) and a coverage >= 0.3 test (reference
utils/mask_backprojection.py:70-151). That shape — ragged per-mask point
sets, data-dependent crops — is hostile to XLA.

This module inverts the direction of the search: instead of asking "which
scene points are near each mask point?", it asks, for every scene point at
once, "which mask pixel backprojections are near me?" Each scene point is
projected into the frame, a small pixel window around its footprint is
gathered, and window pixels whose 3D backprojection lies within
``distance_threshold`` of the point claim it for their mask. This is a dense
gather with static shapes — one lax.map over frames, no ragged crops, no
ball query — and the per-point winner/boundary logic reproduces the
reference's point-in-mask matrix semantics (construction.py:22-64):

- a point claimed by exactly one valid mask gets that mask id;
- a point claimed by >= 2 valid masks in a frame is a *boundary* point:
  zeroed in the id matrix, recorded globally (construction.py:55-62). We
  additionally keep the (min, max) claiming ids per point ("first"/"last")
  so node point sets can include boundary points the way the reference's
  per-mask sets do (a point claimed by > 2 masks keeps only its extreme
  ids — a deliberate compression; overlaps are overwhelmingly pairwise).

Mask-level filters mirror the reference:
- masks with < few_points_threshold valid-depth pixels are dropped
  (FEW_POINTS_THRESHOLD, mask_backprojection.py:101-110);
- masks whose backprojection is absent from the reconstructed cloud are
  dropped by a coverage test. Coverage here = (#scene points claimed) /
  (#occupied voxels of the mask's backprojection), a density-calibrated
  analog of the reference's "fraction of downsampled mask points with a
  scene neighbor" (mask_backprojection.py:105,143-145). The voxel size is
  ``max(distance_threshold, scene point spacing)``: with voxels at the
  cloud's own spacing, a fully reconstructed mask has ~1 claimed point per
  occupied voxel regardless of how dense the scan is, mirroring the
  reference's ratio (which self-calibrates because both its numerator and
  denominator count downsampled MASK points). A fixed distance_threshold
  voxel would undercount coverage ~4x on a 2 cm cloud at the reference's
  radius 0.01 and reject every mask. The exact ball-query semantics are
  available via models/exact_backprojection.py in parity mode.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from maskclustering_tpu.ops import counting
from maskclustering_tpu.ops.geometry import invert_se3, unproject_depth
from maskclustering_tpu.utils.donation import suppress_unusable_donation_warning

# this module donates the fed frame stacks (associate_scene_tensors); see
# the helper's docstring for why the filter is global and why it is safe
suppress_unusable_donation_warning()


@functools.partial(jax.jit, static_argnames=("sample", "chunk"))
def estimate_spacing(points: jnp.ndarray, *, sample: int = 2048,
                     chunk: int = 32768) -> jnp.ndarray:
    """Median nearest-neighbor distance of a point sample vs the full cloud.

    Calibrates the coverage voxel size to the reconstruction's density (the
    reference's analog is voxel-downsampling mask points before its coverage
    ratio, mask_backprojection.py:105). Two padding artifacts are excluded
    from the median: zero distances (exact duplicates from tile-padding, or
    sentinel pad points stacked at one coordinate) and absurdly large ones
    (a sentinel's distance to the nearest REAL point — finite and huge; in a
    majority-padded cloud of the fused batch path those would otherwise
    dominate the median). No indoor reconstruction has metre-scale spacing,
    so entries >= 10 m count as padding.
    """
    n = points.shape[0]
    stride = max(n // sample, 1)
    sub = points[::stride][:sample]  # (S, 3); may be < sample for tiny clouds
    s = sub.shape[0]
    best = jnp.full((s,), jnp.inf, jnp.float32)
    n_chunks = -(-n // chunk)
    padded = jnp.pad(points, ((0, n_chunks * chunk - n), (0, 0)),
                     constant_values=jnp.inf)

    def body(best, c):
        blk = jax.lax.dynamic_slice(padded, (c * chunk, 0), (chunk, 3))
        d2 = jnp.sum((sub[:, None, :] - blk[None, :, :]) ** 2, axis=-1)
        # self / exact duplicates (d=0) and inf-pad rows (inf or nan) -> inf
        d2 = jnp.where(jnp.isfinite(d2) & (d2 > 1e-12), d2, jnp.inf)
        return jnp.minimum(best, jnp.min(d2, axis=1)), None

    best, _ = jax.lax.scan(body, best, jnp.arange(n_chunks))
    from maskclustering_tpu.datasets.base import PAD_DISTANCE_CUTOFF

    d = jnp.sqrt(best)
    valid = jnp.isfinite(d) & (d < PAD_DISTANCE_CUTOFF)
    # median over valid entries: sort with inf padding, index count/2
    ds = jnp.sort(jnp.where(valid, d, jnp.inf))
    cnt = jnp.sum(valid)
    med = ds[jnp.clip(cnt // 2, 0, s - 1)]
    # all-padding degenerate sample: fall back to 0 (callers take
    # max(distance_threshold, estimate))
    return jnp.where(cnt > 0, med, 0.0)


class FrameAssociation(NamedTuple):
    """Per-frame association results, stacked over frames by the caller.

    first/last are int16: mask ids are bounded by k_max (ceiling 1023,
    pipeline.K_MAX_CEILING) so the claim extremes fit with headroom, and
    the stacked (F, N) planes — the scene's largest long-lived HBM
    residents, alive from association emit through the end of postprocess
    — halve vs int32, as do their host pulls on the non-device postprocess
    path.
    """

    mask_of_point: jnp.ndarray  # (N,) int32: unique claiming mask id, 0 = none/boundary
    first_id: jnp.ndarray  # (N,) int16: smallest valid claiming mask id (0 = none)
    last_id: jnp.ndarray  # (N,) int16: largest valid claiming mask id
    mask_valid: jnp.ndarray  # (K_max+1,) bool: per-mask-id validity (index 0 unused)
    n_pixels: jnp.ndarray  # (K_max+1,) int32: valid-depth pixel count per mask
    n_voxels: jnp.ndarray  # (K_max+1,) int32: occupied voxel count per mask
    n_claimed: jnp.ndarray  # (K_max+1,) int32: scene points claimed per mask


class SceneAssociation(NamedTuple):
    """Stacked (F, ...) association tensors for a scene."""

    mask_of_point: jnp.ndarray  # (F, N) int32 — the reference's point_in_mask_matrix
    first_id: jnp.ndarray  # (F, N) int16
    last_id: jnp.ndarray  # (F, N) int16
    point_visible: jnp.ndarray  # (F, N) bool — the reference's point_frame_matrix
    boundary: jnp.ndarray  # (N,) bool — global boundary points
    mask_valid: jnp.ndarray  # (F, K_max+1) bool


def _hash_bits(num_ids: int) -> int:
    """Voxel-hash width so the packed (id, hash) key stays within int32."""
    return 30 - max(int(num_ids - 1).bit_length(), 1)


def _hash_voxel(keys: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Mix integer voxel coords into a positive int32 hash (bits < 31)."""
    h = keys[..., 0] * 73856093 ^ keys[..., 1] * 19349663 ^ keys[..., 2] * 83492791
    return jnp.abs(h) & ((1 << bits) - 1)


def _counts_by_id(weights: jnp.ndarray, ids: jnp.ndarray, num_ids: int,
                  count_dtype: str = "bf16") -> jnp.ndarray:
    """Per-id weighted counts as a one-hot matvec (MXU), not a scatter.

    TPU scatters cost ~6.6 ns/element (scripts/micro_tpu.py) — at N x window
    candidates per frame that is ~10 ms/frame; the (E, num_ids) one-hot
    contraction is bandwidth-bound and ~100x cheaper. Exact under either
    counting encoding: every ``weights`` this module passes is 0/1 (ones,
    window-dedupe flags, distinct-key flags — audited, see ARCHITECTURE.md
    "Integer counting dtype policy"), so int8 operands lose nothing.
    """
    oh = counting.count_onehot(ids, num_ids, count_dtype=count_dtype)
    return counting.count_dot(weights, oh, count_dtype=count_dtype)


def _count_distinct_per_mask(ids: jnp.ndarray, vox_hash: jnp.ndarray, valid: jnp.ndarray,
                             num_ids: int, bits: int,
                             count_dtype: str = "bf16") -> jnp.ndarray:
    """Count distinct (id, voxel-hash) pairs per id via one sort (no scatter).

    Invalid entries collapse into slot 0 (background), which callers ignore.
    Hash collisions (2^bits buckets; 23 bits at the default k_max=127)
    undercount by ~0.1% — immaterial for a 0.3 coverage threshold. ``bits``
    shrinks as k_max grows to keep the packed key within int32 (the TPU-native
    integer width); at k_max=1023 the 20-bit buckets still undercount < 1%.
    """
    ids = jnp.where(valid, ids, 0)
    key = ids * (1 << bits) + jnp.where(valid, vox_hash, 0)
    skey = jnp.sort(key)
    new = jnp.concatenate([jnp.array([True]), skey[1:] != skey[:-1]])
    sid = skey >> bits
    return _counts_by_id(new, sid, num_ids, count_dtype)


@functools.partial(
    jax.jit,
    static_argnames=("k_max", "window", "distance_threshold", "depth_trunc",
                     "few_points_threshold", "coverage_threshold",
                     "full_tile_table", "count_dtype"),
)
def associate_frame(
    scene_points: jnp.ndarray,  # (N, 3) float32
    depth: jnp.ndarray,  # (H, W) float32
    seg: jnp.ndarray,  # (H, W) int32
    intrinsics: jnp.ndarray,  # (3, 3)
    cam_to_world: jnp.ndarray,  # (4, 4)
    frame_valid: jnp.ndarray,  # () bool
    vox_size: Optional[jnp.ndarray] = None,  # () f32 coverage voxel size (traced)
    *,
    k_max: int = 127,
    window: int = 1,
    distance_threshold: float = 0.01,
    depth_trunc: float = 20.0,
    few_points_threshold: int = 25,
    coverage_threshold: float = 0.3,
    full_tile_table: Optional[bool] = None,
    count_dtype: str = "bf16",
) -> FrameAssociation:
    """Associate every scene point with the masks of one frame.

    ``full_tile_table``: the single-take window table is quadratic in the
    window (2*(2w+1)^2 channels) and materializes F-fold under the fused
    path's frame vmap, so it is the default only at window <= 1; larger
    windows use one take per window ROW (linear in window). Exposed for
    the equivalence test; semantics are identical either way.
    """
    n = scene_points.shape[0]
    h, w = depth.shape
    fx, fy = intrinsics[0, 0], intrinsics[1, 1]
    cx, cy = intrinsics[0, 2], intrinsics[1, 2]

    # Ids outside [1, k_max] are dropped to background, never merged: clipping
    # would alias every id > k_max into one mask and cross-contaminate it
    # (the reference handles arbitrary uint16 ids, mask_backprojection.py:89-94;
    # callers derive k_max from the scene's true max id, pipeline.run_scene).
    seg = jnp.where((seg < 0) | (seg > k_max), 0, seg)
    depth_ok = (depth > 0) & (depth <= depth_trunc)

    # ---- project scene points into the frame ----
    w2c = invert_se3(cam_to_world)
    # full f32 precision: TPU default matmul precision would cost ~mm-cm here
    cam = jnp.matmul(scene_points, w2c[:3, :3].T, precision="highest") + w2c[:3, 3]
    px, py, pz = cam[:, 0], cam[:, 1], cam[:, 2]
    in_front = pz > 1e-6
    safe_z = jnp.where(in_front, pz, 1.0)
    ui = jnp.round(px / safe_z * fx + cx).astype(jnp.int32)
    vi = jnp.round(py / safe_z * fy + cy).astype(jnp.int32)

    # ---- gather the pixel window; record claiming mask id per candidate ----
    # depth and seg interleave into a padded tile table whose row at (v, u)
    # holds a window of both channels; one `take` per table fetches every
    # candidate (layout per branch below). Out-of-bounds pixels on either
    # axis read the zero padding (depth 0 -> never claims), replacing the
    # per-offset bounds masks.
    ww = 2 * window + 1
    dz = jnp.where(depth_ok, depth, 0.0)
    padded = jnp.pad(
        jnp.stack([dz, seg.astype(jnp.float32)], axis=-1),
        ((window, window), (window, window), (0, 0)))  # (H+2w, W+2w, 2)

    r2 = distance_threshold * distance_threshold
    # clip the center pixel; tiles at a clipped center still contain every
    # in-bounds pixel of the ORIGINAL [vi-w..vi+w] x [ui-w..ui+w] window
    # (the clip shifts by <= window on each axis), and the |.| <= window
    # tests keep exactly those — border behavior is identical to the
    # per-offset formulation
    uc = jnp.clip(ui, 0, w - 1)
    vc = jnp.clip(vi, 0, h - 1)
    flat_idx = vc * w + uc

    def claim_col(d, s, dv, du):
        win_ok = (jnp.abs(uc + du - ui) <= window) & (jnp.abs(vc + dv - vi) <= window)
        # 3D position of this pixel's backprojection, in camera frame
        qx = (uc + du - cx) * d / fx
        qy = (vc + dv - cy) * d / fy
        dist2 = (qx - px) ** 2 + (qy - py) ** 2 + (d - pz) ** 2
        claim = in_front & win_ok & (d > 0) & (s > 0) & (dist2 <= r2)
        return jnp.where(claim, s, 0)

    cand_cols = []
    use_full = (window <= 1) if full_tile_table is None else full_tile_table
    if use_full:
        # ONE take per frame: a (H*W, 2*ww^2) table whose row at (v, u)
        # holds the FULL window of both channels. Gather cost on TPU is
        # per-index, not per-byte (~1.5 ms per 192k-index take,
        # scripts/micro_tpu.py), so one wide take beats ww narrow ones.
        tiles = jnp.concatenate(
            [padded[kv : kv + h, ku : ku + w]
             for kv in range(ww) for ku in range(ww)], axis=-1)
        tile_tab = tiles.reshape(h * w, 2 * ww * ww)
        g = jnp.take(tile_tab, flat_idx, axis=0)  # (N, 2*ww^2)
        for j, (dv, du) in enumerate(
                (dv, du) for dv in range(-window, window + 1)
                for du in range(-window, window + 1)):
            cand_cols.append(claim_col(
                g[:, 2 * j], g[:, 2 * j + 1].astype(jnp.int32), dv, du))
    else:
        # window > 1: one take per window ROW over a (H*W, 2*ww) strip
        # table — linear in window instead of quadratic, bounding the
        # F-fold HBM footprint under the fused path's frame vmap
        # (ADVICE r4) at the cost of ww takes.
        for iv, dv in enumerate(range(-window, window + 1)):
            strip = jnp.concatenate(
                [padded[iv : iv + h, ku : ku + w] for ku in range(ww)],
                axis=-1).reshape(h * w, 2 * ww)
            gs = jnp.take(strip, flat_idx, axis=0)  # (N, 2*ww)
            for ju, du in enumerate(range(-window, window + 1)):
                cand_cols.append(claim_col(
                    gs[:, 2 * ju], gs[:, 2 * ju + 1].astype(jnp.int32), dv, du))
    cand = jnp.stack(cand_cols, axis=1)  # (N, (2w+1)^2) claiming mask ids, 0 = none

    # ---- per-mask statistics ----
    seg_flat = seg.reshape(-1)
    dok_flat = depth_ok.reshape(-1)
    pix_ids = jnp.where(dok_flat, seg_flat, 0)
    n_pixels = _counts_by_id(jnp.ones_like(pix_ids), pix_ids, k_max + 1,
                             count_dtype)

    # occupied voxels of the mask's backprojected pixels (coverage denominator)
    if vox_size is None:
        vox_size = jnp.float32(distance_threshold)
    world_pix, _ = unproject_depth(depth, intrinsics, cam_to_world, depth_trunc)
    vox = jnp.floor(world_pix.reshape(-1, 3) / vox_size).astype(jnp.int32)
    bits = _hash_bits(k_max + 1)
    n_voxels = _count_distinct_per_mask(pix_ids, _hash_voxel(vox, bits),
                                        dok_flat & (seg_flat > 0), k_max + 1,
                                        bits, count_dtype)

    # scene points claimed per mask (numerator): each (point, mask) pair
    # counts once — dedupe candidate ids within each point's window row.
    cand_sorted = jnp.sort(cand, axis=1)
    row_new = jnp.concatenate(
        [cand_sorted[:, :1] > 0, (cand_sorted[:, 1:] != cand_sorted[:, :-1]) & (cand_sorted[:, 1:] > 0)],
        axis=1,
    )
    # scan over the window columns: 9 (N, K) one-hot matvecs instead of one
    # (9N, K) — same FLOPs, 9x smaller peak temporary (matters under the
    # fused path's vmap over frames, where per-frame temporaries stack)
    def claimed_col(acc, col):
        w, ids = col
        return acc + _counts_by_id(w, ids, k_max + 1, count_dtype), None

    n_claimed, _ = jax.lax.scan(
        claimed_col, jnp.zeros(k_max + 1, jnp.float32),
        (row_new.T, cand_sorted.T))

    coverage = n_claimed / jnp.maximum(n_voxels, 1)
    mask_valid = (
        (n_pixels >= few_points_threshold)
        & (n_voxels >= 1)
        & (coverage >= coverage_threshold)
        & (jnp.arange(k_max + 1) > 0)
        & frame_valid
    )

    # ---- final per-point assignment against valid masks only ----
    cand_ok = jnp.take(mask_valid, cand) & (cand > 0)
    first = jnp.min(jnp.where(cand_ok, cand, k_max + 1), axis=1)
    last = jnp.max(jnp.where(cand_ok, cand, 0), axis=1)
    claimed_any = last > 0
    first = jnp.where(claimed_any, first, 0)
    unique_claim = claimed_any & (first == last)
    mask_of_point = jnp.where(unique_claim, first, 0)

    # the claim extremes narrow to int16 at emit: values are mask ids
    # <= k_max + 1 <= 1024, and the stacked (F, N) planes outlive every
    # other association output (they feed postprocess at scene end).
    # mask_of_point stays int32: it dies inside the graph stage (the
    # co-occurrence gather consumes it immediately), so narrowing it buys
    # no steady-state HBM — residency, not representability, decides.
    return FrameAssociation(
        mask_of_point=mask_of_point,
        first_id=first.astype(jnp.int16),
        last_id=last.astype(jnp.int16),
        mask_valid=mask_valid,
        n_pixels=n_pixels.astype(jnp.int32),
        n_voxels=n_voxels.astype(jnp.int32),
        n_claimed=n_claimed.astype(jnp.int32),
    )


def _associate_scene_impl(
    scene_points: jnp.ndarray,  # (N, 3) float32
    depths: jnp.ndarray,  # (F, H, W)
    segs: jnp.ndarray,  # (F, H, W) int32
    intrinsics: jnp.ndarray,  # (F, 3, 3)
    cam_to_world: jnp.ndarray,  # (F, 4, 4)
    frame_valid: jnp.ndarray,  # (F,) bool
    vox_size: Optional[jnp.ndarray] = None,  # () f32, traced
    *,
    k_max: int = 127,
    window: int = 1,
    distance_threshold: float = 0.01,
    depth_trunc: float = 20.0,
    few_points_threshold: int = 25,
    coverage_threshold: float = 0.3,
    frame_batch: int = 1,
    count_dtype: str = "bf16",
) -> SceneAssociation:
    """Projective association over all frames with lax.map (trace-time body).

    lax.map (not vmap) keeps per-frame intermediates (N x window gathers) at
    one frame's footprint; frames are still processed back-to-back inside a
    single jit. ``frame_batch > 1`` vmaps that many frames per map step
    (lax.map batch_size) — a bounded B-fold intermediate footprint for
    B-wide utilization. Sharding over a `frames` mesh axis happens at the
    caller via shard_map (parallel/).
    """

    def one(args):
        depth, seg, intr, c2w, fv = args
        fa = associate_frame(
            scene_points, depth, seg, intr, c2w, fv, vox_size,
            k_max=k_max, window=window, distance_threshold=distance_threshold,
            depth_trunc=depth_trunc, few_points_threshold=few_points_threshold,
            coverage_threshold=coverage_threshold,
            # sequential map holds ONE frame's intermediates, so the
            # quadratic full-window table is safe at every window; with
            # frame_batch > 1 the step is a B-frame vmap, so fall back to
            # the window-gated default (strip table when window > 1),
            # matching the fused path's frame-vmap policy
            full_tile_table=True if frame_batch == 1 else None,
            count_dtype=count_dtype,
        )
        return fa.mask_of_point, fa.first_id, fa.last_id, fa.mask_valid

    mop, first, last, mask_valid = jax.lax.map(
        one, (depths, segs, intrinsics, cam_to_world, frame_valid),
        batch_size=frame_batch if frame_batch > 1 else None,
    )
    boundary = jnp.any(first != last, axis=0)
    point_visible = first > 0
    return SceneAssociation(
        mask_of_point=mop,
        first_id=first,
        last_id=last,
        point_visible=point_visible,
        boundary=boundary,
        mask_valid=mask_valid,
    )


# jitted so the threshold constant bakes into the program (the eager form
# was an implicit per-scene scalar host->device upload — flagged by the
# Family-3 transfer guard) and the spacing-median chain dispatches as one
# program instead of op-by-op. Static threshold: a handful of distinct
# configs, same cache story as _associate_scene_jit.
@functools.partial(jax.jit, static_argnames="distance_threshold")
def _vox_size_jit(scene_points, *, distance_threshold: float):
    return jnp.maximum(jnp.float32(distance_threshold),
                       estimate_spacing(scene_points))


@functools.lru_cache(maxsize=None)
def _associate_scene_jit(k_max, window, distance_threshold, depth_trunc,
                         few_points_threshold, coverage_threshold,
                         frame_batch=1, donate=False, count_dtype="bf16"):
    """One cached top-level jit per static config.

    Calling lax.map eagerly re-traces AND re-compiles the whole frame scan
    on every invocation (~48 s at ScanNet scale, measured) because the
    eager dispatch cache misses on the fresh closure; routing through one
    persistent jit makes the first scene pay compilation and every later
    scene (and repeat run) reuse it. (Steady-state execution cost is
    gather/bandwidth-bound, not dispatch-bound — see PROFILE.md.)

    ``donate=True`` donates the depth/seg frame stacks (args 1 and 2) —
    the scene's dominant HBM tenants, dead after this program — so their
    buffers recycle into the next same-bucket dispatch instead of
    coexisting with it. Only safe when the caller owns the uploaded
    buffers exclusively (associate_scene_tensors checks this).
    """
    impl = functools.partial(
        _associate_scene_impl, k_max=k_max, window=window,
        distance_threshold=distance_threshold, depth_trunc=depth_trunc,
        few_points_threshold=few_points_threshold,
        coverage_threshold=coverage_threshold, frame_batch=frame_batch,
        count_dtype=count_dtype)
    # name the partial: jax's compile log (and therefore the retrace
    # sanitizer's per-program attribution) keys executables by __name__ —
    # an anonymous partial logs as "<unnamed wrapped function>" and every
    # partial-wrapped program would collide on that one key
    impl.__name__ = _associate_scene_impl.__name__
    return jax.jit(impl, donate_argnums=(1, 2) if donate else ())


def associate_scene(
    scene_points, depths, segs, intrinsics, cam_to_world, frame_valid,
    vox_size=None, *,
    k_max: int = 127, window: int = 1, distance_threshold: float = 0.01,
    depth_trunc: float = 20.0, few_points_threshold: int = 25,
    coverage_threshold: float = 0.3, frame_batch: int = 1,
    donate: bool = False, count_dtype: str = "bf16",
) -> SceneAssociation:
    """Run projective association over all frames (jit-cached).

    ``vox_size`` (a traced scalar) calibrates the coverage voxel grid; when
    None it is estimated as max(distance_threshold, median scene spacing).
    ``donate=True`` invalidates the passed depths/segs device arrays.
    """
    if vox_size is None:
        vox_size = _vox_size_jit(scene_points,
                                 distance_threshold=float(distance_threshold))
    fn = _associate_scene_jit(k_max, window, float(distance_threshold),
                              float(depth_trunc), few_points_threshold,
                              float(coverage_threshold), int(frame_batch),
                              bool(donate), str(count_dtype))
    args = (scene_points, depths, segs, intrinsics, cam_to_world, frame_valid,
            jnp.asarray(vox_size, jnp.float32))
    # persistent AOT executable cache (utils/aot_cache.py): when armed, a
    # warm-started process dispatches the RESTORED executable for this
    # bucket — zero tracing, zero compilation — and a cold bucket's first
    # dispatch captures the export so the NEXT process (a respawned
    # worker, a restarted daemon) starts warm. The key is the retrace
    # census coordinate: fn + arg avals (the shape bucket) + the
    # compile-stable statics + count_dtype + donation.
    from maskclustering_tpu.utils import aot_cache

    if aot_cache.active() is not None:
        key = aot_cache.key_for(
            "_associate_scene_impl", args,
            statics={"k_max": k_max, "window": window,
                     "distance_threshold": float(distance_threshold),
                     "depth_trunc": float(depth_trunc),
                     "few_points_threshold": few_points_threshold,
                     "coverage_threshold": float(coverage_threshold),
                     "frame_batch": int(frame_batch)},
            count_dtype=str(count_dtype), donate=bool(donate))
        fn = aot_cache.serving_callable(
            key, fn, args, donate_argnums=(1, 2) if donate else ())
    return fn(*args)


def associate_scene_tensors(tensors, cfg, k_max: int = 127) -> SceneAssociation:
    """Convenience wrapper: run association from a SceneTensors bundle.

    Depth/seg frames cross the host->device link through the compact-feed
    codec (io/feed.py): uint16 quanta when bit-exact (native ScanNet-family
    depth is uint16 mm), f32 passthrough otherwise — halves-to-quarters the
    dominant per-scene transfer at identical results.
    """
    from maskclustering_tpu import obs
    from maskclustering_tpu.io.feed import device_resident, to_device_frames

    # ownership: frames arriving as HOST arrays are uploaded by the codec
    # into fresh device buffers no one else holds — those may be donated
    # into the association program (their last and only consumer). Frames
    # already device-resident (the bench renders directly in HBM) belong
    # to the caller and must survive the call.
    owned = not (device_resident(tensors.depths)
                 or device_resident(tensors.segmentations))
    depths_dev, segs_dev = to_device_frames(tensors.depths, tensors.segmentations)
    # the codec accounts depth/seg bytes itself (it sees the encoded size);
    # the remaining per-scene uploads are the cloud + the small pose tables
    for arr in (tensors.scene_points, tensors.intrinsics,
                tensors.cam_to_world, tensors.frame_valid):
        if isinstance(arr, np.ndarray):
            obs.count_transfer("h2d", arr.nbytes, "associate")
    return associate_scene(
        jnp.asarray(tensors.scene_points),
        depths_dev,
        segs_dev,
        jnp.asarray(tensors.intrinsics),
        jnp.asarray(tensors.cam_to_world),
        jnp.asarray(tensors.frame_valid),
        k_max=k_max,
        window=cfg.association_window,
        distance_threshold=cfg.distance_threshold,
        depth_trunc=cfg.depth_trunc,
        few_points_threshold=cfg.few_points_threshold,
        coverage_threshold=cfg.coverage_threshold,
        frame_batch=cfg.association_frame_batch,
        donate=bool(cfg.donate_buffers) and owned,
        count_dtype=cfg.count_dtype,
    )
