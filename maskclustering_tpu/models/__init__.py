from maskclustering_tpu.models.backprojection import (
    FrameAssociation,
    SceneAssociation,
    associate_frame,
    associate_scene,
)
from maskclustering_tpu.models.clustering import ClusterResult, iterative_clustering
from maskclustering_tpu.models.graph import (
    GraphStats,
    MaskTable,
    build_mask_table,
    compute_graph_stats,
    observer_schedule,
)
from maskclustering_tpu.models.pipeline import SceneResult, run_scene
from maskclustering_tpu.models.postprocess import (
    SceneObjects,
    export_artifacts,
    postprocess_scene,
)
from maskclustering_tpu.models.streaming import StreamAccumulator, stream_scene

__all__ = [
    "FrameAssociation",
    "SceneAssociation",
    "associate_frame",
    "associate_scene",
    "ClusterResult",
    "iterative_clustering",
    "GraphStats",
    "MaskTable",
    "build_mask_table",
    "compute_graph_stats",
    "observer_schedule",
    "SceneResult",
    "run_scene",
    "SceneObjects",
    "export_artifacts",
    "postprocess_scene",
    "StreamAccumulator",
    "stream_scene",
]
