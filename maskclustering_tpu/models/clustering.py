"""Iterative view-consensus clustering, fully on-device.

The reference alternates GPU affinity matmuls with a host roundtrip to
networkx connected_components every iteration (reference
graph/iterative_clustering.py:17-32), materializing Python Node objects as
it goes (graph/node.py:24-37). Here the whole schedule runs as one
lax.scan with no host sync:

- cluster state is a single assignment vector ``a[m] -> representative mask
  index`` over a fixed M_pad slot space (no object churn, no recompiles);
- per-iteration node features are re-aggregated from the original mask
  features by a one-hot matmul (segment-OR on the MXU), replacing
  Node.create_node_from_list;
- the observer/supporter affinities are V V^T and C C^T exactly as in the
  reference (iterative_clustering.py:20-23) — counting contractions
  (ops/counting.py: bf16+f32 or, under ``count_dtype="int8"``, s8+s32
  on the MXU's double-rate integer path), exact for 0/1 data either way;
- connected components is min-label propagation run to fixpoint inside a
  lax.while_loop, replacing networkx (iterative_clustering.py:32);
- the dynamic-length threshold schedule is padded with +inf: an inf
  threshold disconnects every pair, so padded iterations are no-ops.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from maskclustering_tpu.ops import counting


class ClusterResult(NamedTuple):
    assignment: jnp.ndarray  # (M_pad,) int32: final representative per mask
    node_visible: jnp.ndarray  # (M_pad, F) bool: per-rep aggregated visible_frame
    node_active: jnp.ndarray  # (M_pad,) bool: slot is a live representative


def _connected_components(adj: jnp.ndarray) -> jnp.ndarray:
    """Min-label propagation to fixpoint. adj must be symmetric (M, M) bool."""
    m = adj.shape[0]
    sentinel = jnp.int32(m)
    init = jnp.arange(m, dtype=jnp.int32)

    def cond(state):
        labels, changed = state
        return changed

    def body(state):
        labels, _ = state
        neigh = jnp.where(adj, labels[None, :], sentinel)
        best = jnp.minimum(labels, jnp.min(neigh, axis=1))
        # two hops per sweep (pointer jumping) to cut iteration count
        best = jnp.minimum(best, best[best])
        return (best, jnp.any(best != labels))

    labels, _ = jax.lax.while_loop(cond, body, (init, jnp.bool_(True)))
    return labels


def iterative_clustering(
    visible: jnp.ndarray,
    contained: jnp.ndarray,
    active: jnp.ndarray,
    schedule: jnp.ndarray,
    init: jnp.ndarray = None,
    *,
    view_consensus_threshold: float = 0.9,
    count_dtype: str = "bf16",
) -> ClusterResult:
    """Dispatch wrapper: one obs span (and, when armed with annotations,
    one ``jax.profiler.TraceAnnotation``) around the jitted solve so the
    clustering step is identifiable inside XLA profile traces. Static
    shapes only — no device sync, zero cost when obs is disarmed.

    ``init`` (optional, (M_pad,) int32) warm-starts the merge from a prior
    assignment instead of singletons — the streaming accumulator
    (models/streaming.py) restarts each periodic re-cluster from the
    previous chunk's labels. ``init=None`` keeps the batch path's exact
    historical program (same jit signature, no extra traced arg), and an
    ``init`` equal to ``arange(M_pad)`` produces bit-identical results to
    the cold start: connected-components under min-label propagation is
    invariant to any initial partition that refines the final components
    (pinned by tests/test_streaming.py).
    """
    if isinstance(visible, jax.core.Tracer) or (
            init is not None and isinstance(init, jax.core.Tracer)):
        # called from inside another jit (the fused mesh path / the
        # streaming re-cluster program): a span here would time Python
        # TRACING once per compile and nothing per cached execution — a
        # bogus row; the enclosing stage span owns the timing
        return _iterative_clustering_body(
            visible, contained, active, schedule, init,
            view_consensus_threshold=view_consensus_threshold,
            count_dtype=count_dtype)
    from maskclustering_tpu import obs

    with obs.span("cluster.solve", m_pad=int(visible.shape[0]),
                  schedule_len=int(schedule.shape[0])):
        if init is None:
            return _iterative_clustering_jit(
                visible, contained, active, schedule,
                view_consensus_threshold=view_consensus_threshold,
                count_dtype=count_dtype)
        return _iterative_clustering_warm_jit(
            visible, contained, active, schedule, init,
            view_consensus_threshold=view_consensus_threshold,
            count_dtype=count_dtype)


@functools.partial(jax.jit, static_argnames=("view_consensus_threshold",
                                             "count_dtype"))
def _iterative_clustering_jit(
    visible: jnp.ndarray,
    contained: jnp.ndarray,
    active: jnp.ndarray,
    schedule: jnp.ndarray,
    *,
    view_consensus_threshold: float = 0.9,
    count_dtype: str = "bf16",
) -> ClusterResult:
    """The batch program: cold start from singletons (no init arg, so the
    historical jit signature — and the AOT/compile-cache coordinates the
    serve-many contract pins — are byte-unchanged)."""
    return _iterative_clustering_body(
        visible, contained, active, schedule, None,
        view_consensus_threshold=view_consensus_threshold,
        count_dtype=count_dtype)


@functools.partial(jax.jit, static_argnames=("view_consensus_threshold",
                                             "count_dtype"))
def _iterative_clustering_warm_jit(
    visible: jnp.ndarray,
    contained: jnp.ndarray,
    active: jnp.ndarray,
    schedule: jnp.ndarray,
    init: jnp.ndarray,
    *,
    view_consensus_threshold: float = 0.9,
    count_dtype: str = "bf16",
) -> ClusterResult:
    """The streaming re-cluster program: warm start from ``init`` labels.

    A separate executable (one extra traced (M_pad,) arg) so the batch
    path's compile surface is untouched; classified in the retrace
    census alongside ``_iterative_clustering_jit``.
    """
    return _iterative_clustering_body(
        visible, contained, active, schedule, init,
        view_consensus_threshold=view_consensus_threshold,
        count_dtype=count_dtype)


def _iterative_clustering_body(
    visible: jnp.ndarray,  # (M_pad, F) bool mask-level visible_frame
    contained: jnp.ndarray,  # (M_pad, M_pad) bool mask-level contained_mask
    active: jnp.ndarray,  # (M_pad,) bool: valid & not undersegmented
    schedule: jnp.ndarray,  # (T,) f32 observer thresholds, +inf padded
    init,  # Optional (M_pad,) int32 prior assignment (None = singletons)
    *,
    view_consensus_threshold: float = 0.9,
    count_dtype: str = "bf16",
) -> ClusterResult:
    m_pad = visible.shape[0]
    arange = jnp.arange(m_pad, dtype=jnp.int32)
    eye = jnp.eye(m_pad, dtype=bool)
    vis_m = (visible & active[:, None])
    con_m = (contained & active[:, None])

    def aggregate(assign):
        """Segment-OR of mask features into representative slots (MXU)."""
        onehot = (assign[None, :] == arange[:, None]) & active[None, :]  # (rep, member)
        v = counting.count_dot(onehot, vis_m, count_dtype=count_dtype,
                               out_dtype=None) > 0
        c = counting.count_dot(onehot, con_m, count_dtype=count_dtype,
                               out_dtype=None) > 0
        rep_active = jnp.any(onehot, axis=1)
        return v, c, rep_active

    def step(assign, threshold):
        v, c, rep_active = aggregate(assign)
        observers = counting.count_dot(v, v.T, count_dtype=count_dtype)
        supporters = counting.count_dot(c, c.T, count_dtype=count_dtype)
        rate = supporters / (observers + 1e-7)
        adj = (rate >= view_consensus_threshold) & (observers >= threshold)
        adj = adj & ~eye & rep_active[:, None] & rep_active[None, :]
        labels = _connected_components(adj)
        # non-representative slots keep their label; members follow their rep
        new_assign = labels[assign]
        return new_assign, None

    # while_loop, not scan: the +inf suffix of the schedule disconnects
    # every pair (observers >= inf is false), so those iterations are
    # no-ops — stopping at the first inf skips their full-size affinity
    # matmuls. The schedule is inf-padded only as a suffix (both schedule
    # builders terminate once dead), so this exits exactly at the pad.
    num_t = schedule.shape[0]

    def live(state):
        t, _ = state
        return (t < num_t) & ~jnp.isinf(schedule[jnp.minimum(t, num_t - 1)])

    def advance(state):
        t, assign = state
        new_assign, _ = step(assign, schedule[t])
        return t + 1, new_assign

    init_assign = arange if init is None else init.astype(jnp.int32)
    _, assignment = jax.lax.while_loop(live, advance,
                                       (jnp.int32(0), init_assign))
    v, _, rep_active = aggregate(assignment)
    return ClusterResult(assignment=assignment, node_visible=v, node_active=rep_active)
